"""Multi-host launcher seam: ssh exec wrapper + routable-host plumbing.

The reference launched workers as containers on remote cluster nodes
(AMRMCallbackHandler.java:159-182).  The ssh launcher is the TPU-native
equivalent; these tests run localhost-as-remote through a fake ``ssh``
that executes the remote command locally, with workers bound to this
machine's real (non-loopback) interface — exercising exactly the address
plumbing a 2-machine run needs: stdin config transport, routable
WorkerConfig.host, a 0.0.0.0-bound coordinator with an advertised address,
and the loopback-mismatch guard.
"""

import os
import socket
import stat

import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.coordinator.coordinator import (
    Coordinator,
    JobSpec,
    JobState,
)
from shifu_tensorflow_tpu.coordinator.submitter import JobSubmitter
from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.data.splitter import split_training_data
from jaxcaps import needs_nonloopback_spmd

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO_ROOT,
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}

FAKE_SSH = """#!/bin/sh
# fake ssh: skip -o options, drop the host argument, run the command
# locally through the shell — exactly what sshd would do remotely.
while [ "$1" = "-o" ]; do shift 2; done
shift  # the host
exec /bin/sh -c "$*"
"""


def _primary_ip() -> str | None:
    """This machine's non-loopback IP (no packets are sent)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("198.51.100.1", 53))
        ip = s.getsockname()[0]
    except OSError:
        return None
    finally:
        s.close()
    return None if ip.startswith("127.") else ip


@pytest.fixture
def fake_ssh(tmp_path):
    path = tmp_path / "ssh"
    path.write_text(FAKE_SSH)
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return str(path)


@needs_nonloopback_spmd
def test_ssh_launcher_spmd_on_nonloopback_interface(
    psv_dataset, tmp_path, fake_ssh
):
    ip = _primary_ip()
    if ip is None:
        pytest.skip("no non-loopback interface available")
    mc = ModelConfig.from_json(
        {"train": {"numTrainEpochs": 2, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05, "Optimizer": "adam"}}}
    )
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    shards = split_training_data(psv_dataset["root"], 2)
    ckpt_dir = str(tmp_path / "ckpt")

    def make_cfg(worker_id: str, addr) -> WorkerConfig:
        return WorkerConfig(
            worker_id=worker_id,
            coordinator_host=addr[0],
            coordinator_port=addr[1],
            model_config=mc,
            schema=schema,
            batch_size=32,
            checkpoint_dir=ckpt_dir,
            heartbeat_interval_s=0.2,
            spmd=True,
        )

    spec = JobSpec(
        n_workers=2, shards=shards, spmd=True, epochs=2,
        registration_timeout_s=120.0,
    )
    submitter = JobSubmitter(
        spec, make_cfg,
        launcher="ssh",
        hosts=[ip, ip],  # localhost-as-remote: both "machines" are this one
        ssh_command=[fake_ssh],
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        bind_host="0.0.0.0",
        advertise_host=ip,
    )
    result = submitter.run(timeout_s=300.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    # every worker registered its routable (non-loopback) identity, and
    # the chief's jax coordination service was reachable there
    recs = list(submitter.coordinator.workers.values())
    assert len(recs) == 2
    assert all(r.host == ip for r in recs)
    assert len(result.epoch_summaries) == 2


@needs_nonloopback_spmd
def test_ssh_launcher_remote_kill_uses_run_tag(
    psv_dataset, tmp_path, fake_ssh
):
    """kill_worker for the ssh launcher must issue the remote pkill (the
    local ssh client alone cannot kill the remote tree)."""
    ip = _primary_ip() or "127.0.0.1"
    calls = tmp_path / "ssh-calls.log"
    logging_ssh = tmp_path / "ssh-logging"
    logging_ssh.write_text(
        "#!/bin/sh\n"
        f'echo "$@" >> {calls}\n'
        + FAKE_SSH.split("\n", 1)[1]
    )
    logging_ssh.chmod(logging_ssh.stat().st_mode | stat.S_IEXEC)

    mc = ModelConfig.from_json(
        {"train": {"numTrainEpochs": 3, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05, "Optimizer": "adam"}}}
    )
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    shards = split_training_data(psv_dataset["root"], 2)
    spec = JobSpec(
        n_workers=2, shards=shards, spmd=True, epochs=3,
        spare_restarts=1, registration_timeout_s=120.0,
        heartbeat_interval_ms=200, max_missed_heartbeats=5,
    )

    def make_cfg(worker_id: str, addr) -> WorkerConfig:
        return WorkerConfig(
            worker_id=worker_id, coordinator_host=addr[0],
            coordinator_port=addr[1], model_config=mc, schema=schema,
            batch_size=32, checkpoint_dir=str(tmp_path / "ckpt"),
            heartbeat_interval_s=0.2, spmd=True,
        )

    submitter = JobSubmitter(
        spec, make_cfg, launcher="ssh", hosts=[ip, ip],
        ssh_command=[str(logging_ssh)], worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        bind_host="0.0.0.0" if not ip.startswith("127.") else "127.0.0.1",
        advertise_host=ip,
        kill_injections={"worker-1": 0},
    )
    result = submitter.run(timeout_s=300.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    assert result.restarts_used == 1
    logged = calls.read_text()
    assert "pkill -KILL -f stpu-worker-1" in logged


def test_loopback_chief_with_remote_peers_fails_clearly():
    """Round-2 Weak #6: a chief registering 127.0.0.1 while peers register
    routable hosts must be a clear error, not a silent peer hang."""
    from shifu_tensorflow_tpu.data.splitter import Shard

    spec = JobSpec(
        n_workers=2,
        shards=[
            Shard(worker_index=0, paths=("a",), total_bytes=0),
            Shard(worker_index=1, paths=("b",), total_bytes=0),
        ],
        spmd=True,
        registration_timeout_s=10.0,
    )
    coord = Coordinator(spec)
    r0 = coord.register("w0", 0, host="127.0.0.1", jax_port=12345)
    r1 = coord.register("w1", 1, host="10.9.8.7", jax_port=12346)
    assert r0["ok"] and r1["ok"]
    started = coord.await_start(timeout_s=5.0)
    assert not started.get("ok")
    assert "loopback" in (started.get("error") or "")
    assert coord.state == JobState.FAILED
    coord.shutdown()
