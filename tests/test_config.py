"""Config-system tests: layered merge, XML/JSON parity, typed getters,
ModelConfig/ColumnConfig ingestion (SURVEY.md §5.6 parity surface)."""

import json

import pytest

from shifu_tensorflow_tpu.config import keys as K
from shifu_tensorflow_tpu.config.conf import Conf, parse_memory_string
from shifu_tensorflow_tpu.config.model_config import (
    ColumnConfig,
    ModelConfig,
    TrainParams,
)

HADOOP_XML = """<?xml version="1.0"?>
<configuration>
  <property><name>shifu.application.name</name><value>myapp</value></property>
  <property><name>shifu.worker.instances</name><value>3</value></property>
  <property><name>shifu.worker.instances.backup</name><value>2</value></property>
  <property><name>shifu.worker.memory</name><value>10g</value></property>
</configuration>
"""

# the reference's global-default-bk.xml is two concatenated XML documents
DOUBLE_XML = HADOOP_XML + """<configuration>
  <property><name>shifu.worker.instances</name><value>5</value></property>
</configuration>
"""


def test_layered_merge_order(tmp_path):
    user = tmp_path / "global.xml"
    user.write_text(HADOOP_XML)
    conf = Conf.load_layered(str(user), {"shifu.worker.instances": 7})
    # builtin default overridden by file, file overridden by dict
    assert conf.get(K.APPLICATION_NAME) == "myapp"
    assert conf.num_instances() == 7
    assert conf.num_backup_instances() == 2


def test_double_document_xml(tmp_path):
    p = tmp_path / "global-default.xml"
    p.write_text(DOUBLE_XML)
    conf = Conf().add_resource(str(p))
    assert conf.num_instances() == 5  # later document wins


def test_json_resource_and_final_roundtrip(tmp_path):
    p = tmp_path / "conf.json"
    p.write_text(json.dumps({"shifu.tpu.batch-size": 512, "flag": True}))
    conf = Conf.load_layered(str(p))
    assert conf.get_int(K.BATCH_SIZE) == 512
    assert conf.get_bool("flag")

    final_xml = tmp_path / "global-final.xml"
    conf.write_final(str(final_xml))
    reread = Conf().add_resource(str(final_xml))
    assert reread.as_dict() == conf.as_dict()

    final_json = tmp_path / "global-final.json"
    conf.write_final(str(final_json))
    assert json.loads(final_json.read_text())["shifu.tpu.batch-size"] == "512"


def test_typed_getters():
    conf = Conf({"a": "1 2 3", "b": "4,5,6", "mem": "2g", "f": "0.25"})
    assert conf.get_ints("a") == [1, 2, 3]
    assert conf.get_ints("b") == [4, 5, 6]
    assert conf.get_ints("missing", [9]) == [9]
    assert conf.get_memory("mem") == 2 << 30
    assert conf.get_float("f") == 0.25
    assert conf.get_int("missing") is None


def test_parse_memory_string():
    assert parse_memory_string("1536m") == 1536 << 20
    assert parse_memory_string("2G") == 2 << 30
    assert parse_memory_string(4096) == 4096
    with pytest.raises(ValueError):
        parse_memory_string("abc")


def test_defaults_match_reference_envelope():
    conf = Conf.load_layered()
    assert conf.get_int(K.TASK_HEARTBEAT_INTERVAL_MS) == 1000
    assert conf.get_int(K.TASK_MAX_MISSED_HEARTBEATS) == 25
    assert conf.get_int(K.BATCH_SIZE) == 100
    assert conf.get_int(K.TARGET_COLUMN_NUM) == 0
    assert conf.get_int(K.WEIGHT_COLUMN_NUM) == -1


def test_model_config_ingestion(model_config_json):
    mc = ModelConfig.from_json(model_config_json)
    assert mc.num_train_epochs == 3
    assert mc.valid_set_rate == 0.2
    assert mc.params.num_hidden_layers == 2
    assert mc.params.num_hidden_nodes == (16, 8)
    assert mc.params.activation_funcs == ("relu", "tanh")
    assert mc.params.learning_rate == 0.05
    assert mc.params.optimizer == "adadelta"  # reference default
    assert mc.params.model_type == "dnn"
    assert mc.delimiter == "|"


def test_model_config_validates_layer_mismatch():
    with pytest.raises(ValueError):
        TrainParams.from_json(
            {"NumHiddenLayers": 3, "NumHiddenNodes": [4], "ActivationFunc": ["tanh"]}
        )


def test_model_config_extensions_default_off(model_config_json):
    mc = ModelConfig.from_json(model_config_json)
    assert mc.params.num_tasks == 1
    assert mc.params.embedding_hash_size == 0
    assert mc.params.update_window == 1


COLUMN_CONF = [
    {"columnNum": 0, "columnName": "diagnosis", "columnFlag": "Target",
     "finalSelect": False, "columnType": "N"},
    {"columnNum": 1, "columnName": "radius", "finalSelect": True, "columnType": "N",
     "columnStats": {"mean": 14.1, "stdDev": 3.5}},
    {"columnNum": 2, "columnName": "texture", "finalSelect": True, "columnType": "N",
     "columnStats": {"mean": 19.3, "stdDev": 4.3}},
    {"columnNum": 3, "columnName": "wgt", "columnFlag": "Weight", "finalSelect": False},
    {"columnNum": 4, "columnName": "unused", "finalSelect": False},
]


def test_column_config_selection():
    cc = ColumnConfig.from_json(COLUMN_CONF)
    assert cc.target_column_num == 0
    assert cc.weight_column_num == 3
    assert cc.selected_column_nums == [1, 2]
    means, stds = cc.zscale_stats([1, 2])
    assert means == [14.1, 19.3]
    assert stds == [3.5, 4.3]


def test_column_config_fallback_all_columns():
    # parity: with no finalSelect, every non-target/non-weight column is a
    # feature (ssgd_monitor.py:390-394)
    cc = ColumnConfig.from_json(
        [dict(c, finalSelect=False) for c in COLUMN_CONF]
    )
    assert cc.selected_column_nums == [1, 2, 4]


def test_zscale_stats_zero_std_guard():
    cc = ColumnConfig.from_json(
        [{"columnNum": 0, "columnName": "c", "finalSelect": True,
          "columnStats": {"mean": 1.0, "stdDev": 0.0}}]
    )
    _, stds = cc.zscale_stats([0])
    assert stds == [1.0]


def test_every_tpu_conf_key_is_documented():
    """No-drift guard: every shifu.tpu.* key constant must appear in
    docs/operations.md's config table (new keys landing undocumented is
    exactly how the reference accumulated dead keys)."""
    import os

    from shifu_tensorflow_tpu.config import keys as K

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    doc = open(os.path.join(repo, "docs", "operations.md")).read()
    tpu_keys = sorted(
        v for n, v in vars(K).items()
        if isinstance(v, str) and v.startswith("shifu.tpu.")
        and not n.startswith("DEFAULT") and not n.endswith("_PREFIX")
    )
    assert tpu_keys, "expected shifu.tpu.* key constants"
    # match the backtick-delimited form the doc table renders: bare
    # substring matching would let a key that prefixes a documented key
    # (e.g. a future shifu.tpu.cache vs shifu.tpu.cache-dir) pass silently
    missing = [k for k in tpu_keys if f"`{k}`" not in doc]
    assert missing == [], f"keys missing from docs/operations.md: {missing}"
