"""Device & compiler observability (PR 10): the compile flight recorder
(obs/compile.py), the device-memory accountant (obs/memory.py), the
on-demand profiler window (obs/profile.py), and their CLI renders.

Every test that installs a process-global recorder/accountant/journal
uninstalls it — the hooks are shared state by design.
"""

from __future__ import annotations

import io
import json
import os
import time
from contextlib import redirect_stderr, redirect_stdout

import numpy as np
import pytest

from shifu_tensorflow_tpu.obs import compile as compile_mod
from shifu_tensorflow_tpu.obs import journal as journal_mod
from shifu_tensorflow_tpu.obs import memory as memory_mod
from shifu_tensorflow_tpu.obs import profile as profile_mod
from shifu_tensorflow_tpu.obs import slo as slo_mod
from shifu_tensorflow_tpu.obs.journal import Journal, read_events


@pytest.fixture(autouse=True)
def _clean_hooks():
    yield
    compile_mod.uninstall()
    memory_mod.uninstall()
    journal_mod.uninstall()
    slo_mod.uninstall()
    profile_mod.unconfigure()


def _journal(tmp_path, plane="train"):
    path = str(tmp_path / "journal.jsonl")
    journal_mod.install(Journal(path, plane=plane))
    return path


def _recorder(plane="train", **kw) -> compile_mod.CompileRecorder:
    return compile_mod.install(
        compile_mod.CompileRecorder(plane=plane, **kw))


# ---- compile flight recorder ----

def test_observed_jit_journals_one_compile_event_per_signature(tmp_path):
    """Each NEW abstract signature journals exactly one `compile` event
    carrying the signature, timing, and the backend's cost/memory
    analysis; cache hits journal nothing."""
    import jax
    import jax.numpy as jnp

    path = _journal(tmp_path)
    _recorder()
    f = compile_mod.observe(jax.jit(lambda x: (x * 2).sum()),
                            "unit.fn")
    f(jnp.ones((8, 4)))
    f(jnp.ones((8, 4)))   # dispatch-cache hit: no event
    f(jnp.ones((16, 4)))  # new shape: one more event
    journal_mod.uninstall()
    evs = [e for e in read_events(path) if e["event"] == "compile"]
    assert len(evs) == 2
    sigs = {e["signature"] for e in evs}
    assert sigs == {"float32[8,4]", "float32[16,4]"}
    for e in evs:
        assert e["name"] == "unit.fn"
        assert e["compile_s"] > 0
        assert e["wall_s"] >= e["compile_s"] * 0.1  # same order, sane
        assert e["backend"] == "cpu"
        # CPU provides both analyses (memory_analysis code bytes may be
        # 0 on CPU, but the argument/output fields are real)
        assert e["flops"] > 0
        assert e["arg_bytes"] > 0
        assert "temp_bytes" in e


def test_observed_jit_with_recorder_off_is_transparent():
    import jax
    import jax.numpy as jnp

    calls = []

    def raw(x):
        calls.append(1)
        return x + 1

    f = compile_mod.observe(jax.jit(raw), "unit.fn")
    out = f(jnp.ones(3))
    assert np.allclose(np.asarray(out), 2.0)
    # attribute proxying: jit introspection still works through the wrap
    assert f._cache_size() == 1
    assert f.__wrapped__ is not None


def test_analysis_off_still_journals_timing(tmp_path):
    import jax
    import jax.numpy as jnp

    path = _journal(tmp_path)
    _recorder(analysis="off")
    f = compile_mod.observe(jax.jit(lambda x: x * 3), "unit.fn")
    f(jnp.ones((4,)))
    journal_mod.uninstall()
    (ev,) = [e for e in read_events(path) if e["event"] == "compile"]
    assert ev["compile_s"] > 0
    assert "flops" not in ev and "arg_bytes" not in ev


def test_executable_registry_and_gauges(tmp_path):
    _journal(tmp_path)
    rec = _recorder()
    rec.record(name="a", signature="s1", compile_s=0.5)
    rec.record(name="a", signature="s1", compile_s=0.25)  # re-compile
    rec.record(name="a", signature="s2", compile_s=0.5, code_bytes=1024)
    rec.record(name="b", signature="s1", compile_s=1.0, code_bytes=2048)
    s = rec.state()
    assert s["live_executables"] == 3  # (a,s1), (a,s2), (b,s1)
    assert s["compile_seconds_total"] == pytest.approx(2.25)
    assert s["executable_bytes"] == 1024 + 2048
    text = rec.render_prometheus()
    assert "stpu_compile_live_executables 3" in text
    assert "stpu_compile_executable_bytes 3072" in text
    assert "stpu_compile_storm_active 0" in text


def test_compile_events_feed_slo_compile_s_signal(tmp_path):
    from shifu_tensorflow_tpu.obs.config import ObsConfig

    _journal(tmp_path)
    wd = slo_mod.install(slo_mod.from_config(
        ObsConfig(enabled=True, slo_compile_s=1.0, slo_hysteresis=1),
        plane="train"))
    rec = _recorder()
    rec.record(name="a", signature="s", compile_s=2.0)
    events = wd.evaluate()
    assert any(e["event"] == "slo_breach" and e["signal"] == "compile_s"
               for e in events)


def test_recompile_storm_opens_names_culprit_and_clears(tmp_path):
    path = _journal(tmp_path)
    rec = _recorder(storm_window_s=60.0, storm_threshold=4)
    t0 = 1000.0
    # a churning callable + one innocent bystander
    rec.record(name="innocent", signature="x", compile_s=0.01, now=t0)
    for i in range(4):
        rec.record(name="eval.native_score",
                   signature=f"float32[{i + 3},6]",
                   compile_s=0.01, now=t0 + 1 + i)
    assert rec.state()["storm_active"] is True
    assert rec.state()["storms_total"] == 1
    # compiles stop; the tick (epoch / slo-loop seam) clears the storm
    rec.tick(now=t0 + 300)
    assert rec.state()["storm_active"] is False
    journal_mod.uninstall()
    evs = read_events(path)
    storm = next(e for e in evs if e["event"] == "recompile_storm")
    clear = next(e for e in evs if e["event"] == "recompile_storm_clear")
    # the storm names the CHURNING signature, not the bystander
    assert storm["culprit"] == "eval.native_score"
    assert storm["signature"].startswith("float32[")
    assert storm["compiles_in_window"] >= 4
    # the clear still names the storm's culprit (the window is empty by
    # then — "who churned" must not degrade to '?')
    assert clear["culprit"] == "eval.native_score"
    assert clear["storm_s"] > 0


def test_warm_compiles_never_count_toward_a_storm(tmp_path):
    _journal(tmp_path)
    rec = _recorder(storm_window_s=60.0, storm_threshold=3)
    t0 = 2000.0
    with compile_mod.warm_section():
        for i in range(10):
            rec.record(name="eval.native_score", signature=f"w{i}",
                       compile_s=0.01, kind="warm", now=t0 + i)
    assert rec.state()["storm_active"] is False
    # explicit kind="warm" (no section) is excluded too
    for i in range(10):
        rec.record(name="eval.native_score", signature=f"v{i}",
                   compile_s=0.01, kind="warm", now=t0 + 20 + i)
    assert rec.state()["storm_active"] is False


def test_eval_model_warm_journals_warm_compiles(tmp_path):
    """The serve warm ladder journals kind="warm" compile events with
    bucket + model attribution, and the pinned trace-count contract
    survives the observe() wrap."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.export.eval_model import EvalModel
    from shifu_tensorflow_tpu.export.saved_model import export_native_bundle
    from shifu_tensorflow_tpu.train.trainer import Trainer

    mc = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.05}}})
    t = Trainer(mc, 5)
    bundle = str(tmp_path / "m")
    export_native_bundle(bundle, t.state.params, mc, 5)

    path = _journal(tmp_path, plane="serve")
    _recorder(plane="serve")
    m = EvalModel(bundle, backend="native")
    assert m.warm((8, 16)) == 2
    assert m.warm((8, 16)) == 0  # already compiled: no new traces
    m.compute_batch(np.zeros((3, 5), np.float32))  # pads into bucket 8
    journal_mod.uninstall()
    evs = [e for e in read_events(path) if e["event"] == "compile"]
    assert len(evs) == 2  # the two warm buckets; the padded call hit
    assert {e["bucket"] for e in evs} == {8, 16}
    assert all(e["kind"] == "warm" for e in evs)
    assert all(e["model"] == "m" for e in evs)
    m.release()


def test_ladder_disabled_knob_reproduces_raw_shape_churn(tmp_path):
    """STPU_NO_BUCKET (the storm drill's lever) makes bucket_size the
    identity: distinct batch lengths each compile their own program and
    the storm detector names the scorer."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.export import bucketing
    from shifu_tensorflow_tpu.export.eval_model import EvalModel
    from shifu_tensorflow_tpu.export.saved_model import export_native_bundle
    from shifu_tensorflow_tpu.train.trainer import Trainer

    mc = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.05}}})
    t = Trainer(mc, 5)
    bundle = str(tmp_path / "m")
    export_native_bundle(bundle, t.state.params, mc, 5)

    path = _journal(tmp_path, plane="serve")
    rec = _recorder(plane="serve", storm_window_s=60.0, storm_threshold=4)
    m = EvalModel(bundle, backend="native")
    bucketing.set_ladder_disabled(True)
    try:
        for n in (1, 2, 3, 4, 5):
            m.compute_batch(np.zeros((n, 5), np.float32))
    finally:
        bucketing.set_ladder_disabled(False)
    assert m.native_trace_count == 5  # the unpadded-shape bug, on purpose
    assert rec.state()["storm_active"] is True
    # ladder back on: the same request mix collapses to one bucket
    before = m.native_trace_count
    for n in (1, 2, 3):
        m.compute_batch(np.zeros((n, 5), np.float32))
    assert m.native_trace_count == before + 1  # bucket 8, once
    journal_mod.uninstall()
    storm = next(e for e in read_events(path)
                 if e["event"] == "recompile_storm")
    assert storm["culprit"] == "eval.native_score"
    m.release()


def test_attribute_region_records_eager_pallas_compiles(tmp_path):
    """The attribute() seam catches compiles with no jitted callable to
    lower: an eager Pallas embedding gather journals under the pallas
    name (timing only — no signature/analysis, by contract)."""
    import jax.numpy as jnp

    from shifu_tensorflow_tpu.ops.pallas.embedding import embedding_gather

    path = _journal(tmp_path)
    _recorder()
    ids = jnp.arange(8, dtype=jnp.int32)
    table = jnp.ones((32, 4), jnp.float32)
    np.asarray(embedding_gather(ids, table))
    journal_mod.uninstall()
    evs = [e for e in read_events(path) if e["event"] == "compile"]
    pallas = [e for e in evs if e["name"] == "pallas.embedding_gather"]
    assert pallas, [e["name"] for e in evs]
    assert pallas[0]["compile_s"] > 0


def test_trainer_epoch_paths_journal_compile_events(tmp_path):
    """The per-step and scanned epoch paths both journal their step
    compiles under the train.* names (the seam the ROADMAP SPMD work
    will lean on)."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.train import make_trainer

    path = _journal(tmp_path)
    _recorder()
    mc = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.05}}})
    rng = np.random.default_rng(0)

    def batches(n_batches, rows):
        for _ in range(n_batches):
            yield {"x": rng.normal(size=(rows, 6)).astype(np.float32),
                   "y": rng.integers(0, 2, (rows, 1)).astype(np.float32),
                   "w": np.ones((rows, 1), np.float32)}

    t = make_trainer(mc, 6)
    t.train_epoch(batches(2, 16))
    t2 = make_trainer(mc, 6, scan_steps=2)
    t2.train_epoch(batches(2, 16))
    journal_mod.uninstall()
    names = {e["name"] for e in read_events(path)
             if e["event"] == "compile"}
    assert "train.step" in names
    assert "train.scan_epoch" in names


# ---- device-memory accountant ----

def test_memory_snapshot_buckets_and_high_water(tmp_path):
    import jax.numpy as jnp

    path = _journal(tmp_path)
    rec = _recorder()
    rec.record(name="a", signature="s", compile_s=0.1, code_bytes=4096)
    mem = memory_mod.install(memory_mod.MemoryAccountant(plane="train"))
    params = {"w": jnp.ones((32, 32)), "b": jnp.ones((32,))}
    opt = {"m": jnp.ones((32, 32))}
    snap = mem.snapshot(params=params, opt_state=opt, epoch=3)
    assert snap["params_bytes"] == 4 * (32 * 32 + 32)
    assert snap["opt_bytes"] == 4 * 32 * 32
    assert snap["exec_bytes"] == 4096  # from the compile registry
    assert snap["total_bytes"] >= snap["params_bytes"] + snap["opt_bytes"]
    assert snap["other_bytes"] == (snap["total_bytes"]
                                   - snap["params_bytes"]
                                   - snap["opt_bytes"])
    assert snap["hwm_bytes"] == snap["total_bytes"]
    # high water sticks when arrays are freed
    del params, opt
    snap2 = mem.snapshot(epoch=4)
    assert snap2["hwm_bytes"] >= snap2["total_bytes"]
    journal_mod.uninstall()
    evs = [e for e in read_events(path) if e["event"] == "device_mem"]
    assert len(evs) == 2
    assert evs[0]["epoch"] == 3 and evs[0]["params_bytes"] > 0
    text = mem.render_prometheus()
    assert "stpu_devmem_total_bytes" in text
    assert "stpu_devmem_hwm_bytes" in text


def test_memory_snapshot_per_model_merge_and_drop(tmp_path):
    _journal(tmp_path)
    mem = memory_mod.install(memory_mod.MemoryAccountant(plane="serve"))
    mem.snapshot(models={"alpha": 1000, "beta": 2000})
    # a single-model reload snapshot must not wipe the sibling
    mem.snapshot(models={"alpha": 1500})
    assert mem.model_bytes() == {"alpha": 1500, "beta": 2000}
    text = mem.render_prometheus()
    assert 'stpu_devmem_model_bytes_alpha{model="alpha"} 1500' in text
    assert 'stpu_devmem_model_bytes_beta{model="beta"} 2000' in text
    mem.drop_model("beta")
    assert "beta" not in mem.model_bytes()
    assert "beta" not in mem.render_prometheus()


def test_tenancy_admission_journals_device_mem(tmp_path):
    """Admission/eviction are the serve plane's snapshot cadence: the
    journaled device_mem names each admitted model's device bytes and
    the model_admit event carries them."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.export.saved_model import export_native_bundle
    from shifu_tensorflow_tpu.serve.config import ServeConfig
    from shifu_tensorflow_tpu.serve.tenancy.store import MultiModelStore
    from shifu_tensorflow_tpu.train.trainer import Trainer

    models_dir = tmp_path / "models"
    models_dir.mkdir()
    mc = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.05}}})
    t = Trainer(mc, 5)
    export_native_bundle(str(models_dir / "alpha"), t.state.params, mc, 5)

    path = _journal(tmp_path, plane="serve")
    memory_mod.install(memory_mod.MemoryAccountant(plane="serve"))
    cfg = ServeConfig(models_dir=str(models_dir), max_batch=8,
                      max_queue_rows=16)
    store = MultiModelStore(cfg, warm=False)
    try:
        tenant = store.acquire("alpha")
        assert tenant.store.current().model.device_bytes() > 0
    finally:
        store.close()
    journal_mod.uninstall()
    evs = read_events(path)
    admit = next(e for e in evs if e["event"] == "model_admit")
    assert admit["device_bytes"] > 0
    mems = [e for e in evs if e["event"] == "device_mem"]
    assert any((e.get("models") or {}).get("alpha", 0) > 0 for e in mems)


# ---- profiler capture window ----

def test_profile_request_trigger_roundtrip(tmp_path):
    base = str(tmp_path / "j.jsonl")
    trig = profile_mod.request(base, str(tmp_path / "dump"), seconds=1.5,
                               worker=1)
    assert os.path.exists(trig)
    body = json.load(open(trig))
    assert body["seconds"] == 1.5 and body["worker"] == 1
    # a poller with the WRONG worker index leaves the trigger in place
    profile_mod.configure(base, plane="train", worker=0)
    assert profile_mod.poll() is False
    assert os.path.exists(trig)
    # the addressed worker consumes it and journals the capture
    journal_mod.install(Journal(base, plane="train", worker=1))
    profile_mod.configure(base, plane="train", worker=1)
    assert profile_mod.poll() is True
    assert not os.path.exists(trig)
    deadline = time.monotonic() + 20.0
    done = None
    while time.monotonic() < deadline:
        evs = [e for e in read_events(base)
               if e.get("event") == "profile_capture"]
        done = next((e for e in evs if e.get("status") in
                     ("done", "failed")), None)
        if done is not None:
            break
        time.sleep(0.1)
    journal_mod.uninstall()
    assert done is not None, "capture thread never finished"
    # on this backend the capture should succeed and leave a dump dir
    assert done["status"] == "done", done
    assert os.path.isdir(done["dir"])


def test_profile_poll_without_configure_is_noop():
    assert profile_mod.poll() is False


# ---- CLI ----

def _run_cli(argv) -> tuple[int, str]:
    from shifu_tensorflow_tpu.obs.__main__ import main

    out = io.StringIO()
    with redirect_stdout(out), redirect_stderr(out):
        rc = main(argv)
    return rc, out.getvalue()


def _drill_journal(tmp_path) -> str:
    """A dead fleet's journal with compiles, a storm, and memory events
    — everything the jax-free CLI renders from files alone."""
    path = str(tmp_path / "dead.jsonl")
    journal_mod.install(Journal(path, plane="serve", worker=0))
    rec = _recorder(plane="serve", storm_window_s=60.0, storm_threshold=4)
    mem = memory_mod.install(memory_mod.MemoryAccountant(plane="serve",
                                                         worker=0))
    t0 = 100.0
    rec.record(name="eval.native_score", signature="float32[8,6]",
               compile_s=0.02, bucket=8, kind="warm", now=t0)
    for i in range(5):
        rec.record(name="eval.native_score",
                   signature=f"float32[{i + 1},6]",
                   compile_s=0.02, bucket=i + 1, now=t0 + i)
    rec.tick(now=t0 + 300)  # clears the storm
    mem._model_bytes = {"alpha": 4096}
    journal_mod.emit("device_mem", plane="serve", worker=0,
                     total_bytes=8192, params_bytes=0, opt_bytes=0,
                     infeed_bytes=0, exec_bytes=0, other_bytes=8192,
                     arrays=3, hwm_bytes=8192,
                     models={"alpha": 4096})
    journal_mod.uninstall()
    compile_mod.uninstall()
    memory_mod.uninstall()
    return path


def test_cli_compile_renders_history_and_storm(tmp_path):
    path = _drill_journal(tmp_path)
    rc, out = _run_cli(["compile", "--journal", path])
    assert rc == 0
    assert "compile flight recorder" in out
    assert "eval.native_score" in out
    assert "recompile storms" in out
    assert "churning: eval.native_score" in out
    # the storm cleared — the excursion shows a bounded span, and the
    # journal alone reconstructs which signature churned
    assert "STILL ACTIVE" not in out
    rc, out = _run_cli(["compile", "--journal", path, "--json"])
    assert rc == 0
    doc = json.loads(out)
    assert doc["callables"]["eval.native_score"]["compiles"] == 6
    assert doc["callables"]["eval.native_score"]["warm"] == 1
    (storm,) = doc["storms"]
    assert storm["culprit"] == "eval.native_score"
    assert storm["cleared_ts"] is not None


def test_cli_mem_renders_buckets_and_models(tmp_path):
    path = _drill_journal(tmp_path)
    rc, out = _run_cli(["mem", "--journal", path])
    assert rc == 0
    assert "device memory accountant" in out
    assert "serve/w0" in out
    assert "alpha" in out
    rc, out = _run_cli(["mem", "--journal", path, "--json"])
    doc = json.loads(out)
    assert doc["models"]["alpha"] == 4096
    assert doc["workers"]["serve/w0"]["hwm_bytes"] == 8192


def test_cli_profile_lists_and_requests(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with Journal(path, plane="train") as j:
        journal_mod.install(j)
        journal_mod.emit("profile_capture", plane="train", status="done",
                         dir="/tmp/dump", wall_s=5.0)
        journal_mod.uninstall()
    rc, out = _run_cli(["profile", "--journal", path])
    assert rc == 0 and "profile_capture" in out
    rc, out = _run_cli(["profile", "--journal", path, "--request",
                        "--dir", str(tmp_path / "dump")])
    assert rc == 0
    assert os.path.exists(profile_mod.trigger_path(path))
    # --request without --dir fails loudly
    rc, _ = _run_cli(["profile", "--journal", path, "--request"])
    assert rc == 2


def test_exec_bytes_absent_when_analysis_is_not_full(tmp_path):
    """Under analysis=cost/off no memory_analysis ever runs: executable
    bytes must be ABSENT from the scrape and the device_mem event, not a
    measured zero (the accountant's absent-never-zero discipline)."""
    _journal(tmp_path)
    rec = _recorder(analysis="cost")
    rec.record(name="a", signature="s", compile_s=0.1)
    assert "stpu_compile_executable_bytes" not in rec.render_prometheus()
    mem = memory_mod.install(memory_mod.MemoryAccountant(plane="serve"))
    snap = mem.snapshot()
    assert "exec_bytes" not in snap
    assert "stpu_devmem_exec_bytes" not in mem.render_prometheus()


def test_cli_mem_prunes_evicted_models(tmp_path):
    """An evicted tenant's device bytes leave the `obs mem` table (the
    live /metrics drops the gauge via drop_model; the dead-fleet CLI
    must agree, or it inverts the leak diagnosis)."""
    path = str(tmp_path / "j.jsonl")
    with Journal(path, plane="serve") as j:
        journal_mod.install(j)
        journal_mod.emit("device_mem", plane="serve", total_bytes=100,
                         models={"alpha": 60, "beta": 40}, hwm_bytes=100)
        journal_mod.emit("model_evict", plane="serve", model="alpha",
                         reason="budget", freed_bytes=60)
        journal_mod.emit("device_mem", plane="serve", total_bytes=40,
                         models={"beta": 40}, hwm_bytes=100)
        journal_mod.uninstall()
    rc, out = _run_cli(["mem", "--journal", path, "--json"])
    assert rc == 0
    doc = json.loads(out)
    assert doc["models"] == {"beta": 40}, doc["models"]


def test_cli_compile_clean_miss(tmp_path):
    rc, _ = _run_cli(["compile", "--journal",
                      str(tmp_path / "nothing.jsonl")])
    assert rc == 1


# ---- scrape surfaces ----

def test_serve_metrics_carry_device_leg_and_build_info(tmp_path):
    """/metrics (single-model path) appends stpu_compile_*,
    stpu_devmem_*, and the stpu_build_info identity gauge."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.export.saved_model import export_native_bundle
    from shifu_tensorflow_tpu.serve.config import ServeConfig
    from shifu_tensorflow_tpu.serve.server import ScoringServer
    from shifu_tensorflow_tpu.train.trainer import Trainer

    mc = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.05}}})
    t = Trainer(mc, 5)
    bundle = str(tmp_path / "m")
    export_native_bundle(bundle, t.state.params, mc, 5)

    _journal(tmp_path, plane="serve")
    _recorder(plane="serve")
    memory_mod.install(memory_mod.MemoryAccountant(plane="serve"))
    with ScoringServer(ServeConfig(model_dir=bundle, port=0),
                       warm=False) as srv:
        srv.start()
        text = srv.metrics_text()
    assert "stpu_compile_live_executables" in text
    assert "stpu_devmem_total_bytes" in text
    assert "stpu_build_info{" in text
    assert 'backend="cpu"' in text  # jax initialized in this process
    import jax

    assert f'jax="{jax.__version__}"' in text


def test_build_info_without_device_leg_still_renders(tmp_path):
    """stpu_build_info rides every scrape even with no recorder (the
    satellite's contract: every /metrics surface identifies the build)."""
    from shifu_tensorflow_tpu.obs.registry import build_info_text

    text = build_info_text()
    assert "stpu_build_info{" in text
    assert 'version="' in text


def test_coordinator_metrics_carry_build_info():
    from shifu_tensorflow_tpu.coordinator.coordinator import (
        Coordinator,
        JobSpec,
    )

    coord = Coordinator(JobSpec(n_workers=1, shards=[None]))
    text = coord.metrics_text()
    assert "stpu_coord_" in text
    assert "stpu_build_info{" in text
