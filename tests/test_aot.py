"""AOT executable shipping (export/aot.py): compile once at export,
serve everywhere.

The drills the acceptance criteria pin: an AOT bundle admits by
DESERIALIZE (zero new traces, ``kind=aot_load`` compile events with
``compile_s`` ~ 0) and scores bit-identically to the live-compile path;
a bit-flipped serialized executable refuses cleanly PER BUCKET (falls
back, journals ``kind=aot_fallback``) without refusing the bundle; a
bundle exported under a faked compile environment falls back everywhere
and still serves bit-identical scores; legacy no-AOT bundles admit
byte-identically to before; and the manifest chain covers the shipped
executables like any artifact.
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.export import aot as aot_mod
from shifu_tensorflow_tpu.export.eval_model import EvalModel
from shifu_tensorflow_tpu.export.saved_model import (
    NATIVE_MANIFEST,
    export_model,
    export_native_bundle,
)
from shifu_tensorflow_tpu.obs import compile as compile_mod
from shifu_tensorflow_tpu.obs import journal as journal_mod
from shifu_tensorflow_tpu.obs import slo as slo_mod
from shifu_tensorflow_tpu.obs.journal import Journal, read_events
from shifu_tensorflow_tpu.serve.model_store import (
    ArtifactCorrupt,
    ModelStore,
    _verify_manifest,
)
from shifu_tensorflow_tpu.train.trainer import Trainer

N_FEATURES = 6
BUCKETS = (8, 16)


@pytest.fixture(autouse=True)
def _clean_hooks():
    yield
    compile_mod.uninstall()
    journal_mod.uninstall()
    slo_mod.uninstall()


def _model_config():
    return ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05}}}
    )


def _export(tmp_dir: str, seed: int = 0, aot=BUCKETS) -> str:
    export_model(tmp_dir, Trainer(_model_config(), N_FEATURES, seed=seed),
                 aot_buckets=aot)
    return tmp_dir


def _rows(n: int, seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).random((n, N_FEATURES)).astype(
        np.float32)


def _journal(tmp_path, plane="serve"):
    path = str(tmp_path / "journal.jsonl")
    journal_mod.install(Journal(path, plane=plane))
    return path


def _recorder(**kw):
    return compile_mod.install(
        compile_mod.CompileRecorder(plane="serve", **kw))


def _compile_events(path):
    journal_mod.uninstall()
    return [e for e in read_events(path) if e["event"] == "compile"]


# --------------------------------------------------------- bundle layout


def test_export_aot_bundle_layout_and_manifest(tmp_path):
    """The aot/ files land committed AND digested into the export
    manifest — the PR-3 verify chain covers the executables exactly
    like the weights."""
    d = _export(str(tmp_path / "m"))
    meta_path = os.path.join(d, aot_mod.AOT_META)
    assert os.path.exists(meta_path)
    for b in BUCKETS:
        assert os.path.exists(os.path.join(d, aot_mod.bucket_file(b)))
    meta = json.loads(open(meta_path).read())
    assert set(meta["buckets"]) == {str(b) for b in BUCKETS}
    fp = meta["fingerprint"]
    assert fp == aot_mod.compile_env_fingerprint()
    # the weights-generation stamp matches the manifest's bundle digest
    manifest = _verify_manifest(d)  # raises on any digest mismatch
    assert meta["weights_sha256"] == manifest["sha256"]
    covered = set(manifest["files"])
    assert aot_mod.AOT_META in covered
    assert {aot_mod.bucket_file(b) for b in BUCKETS} <= covered


def test_export_without_aot_prunes_stale_executables(tmp_path):
    """A re-export WITHOUT AOT removes the previous generation's aot/
    dir: executables compiled for other weights must not linger beside
    a manifest that no longer vouches for them."""
    d = str(tmp_path / "m")
    _export(d, seed=0)
    assert os.path.exists(os.path.join(d, aot_mod.AOT_DIR))
    _export(d, seed=1, aot=None)
    assert not os.path.exists(os.path.join(d, aot_mod.AOT_DIR))


def test_stale_aot_generation_refuses_and_falls_back(tmp_path):
    """An aot/ dir restored beside RE-EXPORTED weights (a copy/rsync
    accident) refuses wholesale via the stamped weights digest — and
    the model still serves through the live-compile fallback."""
    d = str(tmp_path / "m")
    _export(d, seed=0)
    saved = str(tmp_path / "stale_aot")
    shutil.copytree(os.path.join(d, aot_mod.AOT_DIR), saved)
    _export(d, seed=1, aot=None)  # new weights, no aot
    shutil.copytree(saved, os.path.join(d, aot_mod.AOT_DIR))
    m = EvalModel(d)
    st = m.aot_stats
    assert st["shipped"] is True
    assert "weights generation" in (st["unusable"] or "")
    # serves anyway, bit-identical to a clean live-compile model
    clean = EvalModel(_export(str(tmp_path / "clean"), seed=1, aot=None))
    rows = _rows(5)
    np.testing.assert_array_equal(m.compute_batch(rows),
                                  clean.compute_batch(rows))
    m.release()
    clean.release()


# ------------------------------------------------- admission deserialize


def test_aot_admission_deserializes_bit_identical(tmp_path):
    """The headline: warming an AOT bundle causes ZERO new traces (the
    executables deserialize), journals one ``kind=aot_load`` compile
    event per bucket with ``compile_s`` == 0, and scores bit-identical
    to the live-compiled path."""
    aot_dir = _export(str(tmp_path / "aot"))
    plain_dir = _export(str(tmp_path / "plain"), aot=None)
    path = _journal(tmp_path)
    _recorder()
    m = EvalModel(aot_dir)
    assert m.warm(BUCKETS) == 0  # no traces: admission is a deserialize
    assert m.native_trace_count == 0
    assert m.aot_stats == {"shipped": True, "loads": 2, "fallbacks": 0,
                           "unusable": None}
    plain = EvalModel(plain_dir)
    plain.warm(BUCKETS)
    rows = _rows(5)
    np.testing.assert_array_equal(m.compute_batch(rows),
                                  plain.compute_batch(rows))
    rows = _rows(12, seed=1)  # bucket 16
    np.testing.assert_array_equal(m.compute_batch(rows),
                                  plain.compute_batch(rows))
    assert m.native_trace_count == 0  # requests ride the AOT executables
    evs = _compile_events(path)
    aot_evs = [e for e in evs if e.get("kind") == "aot_load"]
    assert {e["bucket"] for e in aot_evs} == set(BUCKETS)
    for e in aot_evs:
        assert e["compile_s"] == 0.0
        assert e["wall_s"] > 0  # the deserialize cost, visible
        assert e["model"] == "aot"
    # the plain bundle's warms journaled kind=warm, untouched by AOT
    assert {e.get("kind") for e in evs if e.get("model") == "plain"} \
        == {"warm"}
    m.release()
    plain.release()


def test_unshipped_bucket_rides_the_plain_live_path(tmp_path):
    """A bucket the bundle never promised (beyond --export-aot-rows)
    live-compiles WITHOUT an aot_fallback marker — fallback means
    'promised and failed', not 'never promised'."""
    d = _export(str(tmp_path / "m"))  # ships 8, 16 only
    path = _journal(tmp_path)
    _recorder()
    m = EvalModel(d)
    m.compute_batch(_rows(20))  # bucket 32: not shipped
    evs = _compile_events(path)
    (ev,) = [e for e in evs if e.get("bucket") == 32]
    assert ev.get("kind") is None
    assert m.native_trace_count == 1
    m.release()


def test_bitflip_refuses_per_bucket_and_falls_back(tmp_path):
    """A corrupted serialized executable refuses ONLY its bucket: the
    meta's CRC catches it before the pickle layer, the bucket journals
    ``kind=aot_fallback`` (with the reason), the OTHER bucket still
    deserializes, and scores stay bit-identical."""
    d = _export(str(tmp_path / "m"))
    victim = os.path.join(d, aot_mod.bucket_file(8))
    blob = bytearray(open(victim, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(blob))
    path = _journal(tmp_path)
    _recorder()
    m = EvalModel(d)
    assert m.warm(BUCKETS) == 1  # bucket 8 live-compiled, 16 deserialized
    st = m.aot_stats
    assert st["loads"] == 1 and st["fallbacks"] == 1
    plain = EvalModel(_export(str(tmp_path / "plain"), aot=None))
    rows = _rows(5)
    np.testing.assert_array_equal(m.compute_batch(rows),
                                  plain.compute_batch(rows))
    evs = _compile_events(path)
    fb = [e for e in evs if e.get("kind") == "aot_fallback"]
    loads = [e for e in evs if e.get("kind") == "aot_load"]
    assert [e["bucket"] for e in fb] == [8]
    assert "CRC32" in fb[0]["aot_error"]
    assert fb[0]["compile_s"] > 0  # a real compile, honestly priced
    assert [e["bucket"] for e in loads] == [16]
    m.release()
    plain.release()


def test_fingerprint_mismatch_falls_back_everywhere(tmp_path):
    """A bundle exported under a different compile environment (faked
    fingerprint) falls back on EVERY bucket — journaled aot_fallback
    naming the mismatch — and still serves bit-identical scores."""
    d = str(tmp_path / "m")
    fake = dict(aot_mod.compile_env_fingerprint(), jax="9.9.9")
    real_fp = aot_mod.compile_env_fingerprint
    aot_mod.compile_env_fingerprint = lambda **kw: fake
    try:
        _export(d)
    finally:
        aot_mod.compile_env_fingerprint = real_fp
    path = _journal(tmp_path)
    _recorder()
    m = EvalModel(d)
    assert m.warm(BUCKETS) == 2  # everything live-compiled
    st = m.aot_stats
    assert st["loads"] == 0 and st["fallbacks"] == 2
    assert "jax" in st["unusable"]
    plain = EvalModel(_export(str(tmp_path / "plain"), aot=None))
    rows = _rows(9, seed=2)
    np.testing.assert_array_equal(m.compute_batch(rows),
                                  plain.compute_batch(rows))
    evs = _compile_events(path)
    fb = [e for e in evs if e.get("kind") == "aot_fallback"]
    assert {e["bucket"] for e in fb} == set(BUCKETS)
    assert all("jax" in e["aot_error"] for e in fb)
    assert not [e for e in evs if e.get("kind") == "aot_load"]
    m.release()
    plain.release()


# -------------------------------------------------- serve admission path


def test_model_store_admission_deserializes(tmp_path):
    """ModelStore's verify→warm admission rides AOT end to end: the
    manifest chain verifies the shipped executables, the warm ladder
    deserializes them (zero traces), and the hot-reload swap journals
    the aot split."""
    d = _export(str(tmp_path / "m"))
    path = _journal(tmp_path)
    _recorder()
    store = ModelStore(d, poll_interval_s=0, warm_buckets=BUCKETS)
    loaded = store.current()
    assert loaded.verified is True
    assert loaded.model.native_trace_count == 0
    assert loaded.model.aot_stats["loads"] == len(BUCKETS)
    # hot reload re-admits through the same ladder
    os.utime(os.path.join(d, NATIVE_MANIFEST))
    reloaded = store.reload_now()
    assert reloaded.model.native_trace_count == 0
    journal_mod.uninstall()
    evs = read_events(path)
    reload_ev = next(e for e in evs if e["event"] == "reload")
    assert reload_ev["aot_loads"] == len(BUCKETS)
    assert reload_ev["aot_fallbacks"] == 0
    store.close()


def test_manifest_chain_refuses_corrupt_aot_artifact(tmp_path):
    """At the serve admission boundary a flipped executable is caught
    by the MANIFEST (before EvalModel ever constructs): the bundle
    refuses exactly like corrupt weights — AOT artifacts are bundle
    artifacts, not a side channel."""
    d = _export(str(tmp_path / "m"))
    victim = os.path.join(d, aot_mod.bucket_file(16))
    blob = bytearray(open(victim, "rb").read())
    blob[10] ^= 0x01
    open(victim, "wb").write(bytes(blob))
    with pytest.raises(ArtifactCorrupt, match="bucket_16"):
        ModelStore(d, poll_interval_s=0, warm_buckets=BUCKETS)


def test_legacy_bundle_admits_byte_identically(tmp_path):
    """No aot/ dir → the pre-AOT behavior exactly: warms live-compile
    with kind=warm, no aot fields on the reload event, no aot gauges
    movement."""
    d = _export(str(tmp_path / "m"), aot=None)
    path = _journal(tmp_path)
    rec = _recorder()
    store = ModelStore(d, poll_interval_s=0, warm_buckets=BUCKETS)
    assert store.current().model.aot_stats["shipped"] is False
    os.utime(os.path.join(d, NATIVE_MANIFEST))
    store.reload_now()
    journal_mod.uninstall()
    evs = read_events(path)
    reload_ev = next(e for e in evs if e["event"] == "reload")
    assert "aot_loads" not in reload_ev and "aot_fallbacks" not in reload_ev
    warm_evs = [e for e in evs if e["event"] == "compile"]
    assert warm_evs and all(e["kind"] == "warm" for e in warm_evs)
    assert rec.state()["aot_loads_total"] == 0
    store.close()


# --------------------------------------------- recorder/storm/CLI/rollup


def test_aot_kinds_never_count_toward_a_storm():
    """A 10-tenant fleet restart deserializing (or even fallback-
    compiling) its ladders must keep the storm detector quiet — while
    the same volume of UNMARKED compiles still storms (control arm)."""
    rec = _recorder(storm_window_s=60.0, storm_threshold=4)
    t0 = 1000.0
    for i in range(10):
        rec.record(name="eval.native_score", signature=f"a{i}",
                   compile_s=0.0, kind="aot_load", now=t0 + i)
    for i in range(10):
        rec.record(name="eval.native_score", signature=f"f{i}",
                   compile_s=0.01, kind="aot_fallback", now=t0 + i)
    assert rec.state()["storm_active"] is False
    assert rec.state()["aot_loads_total"] == 10
    # aot loads are not compilations
    assert rec.state()["compiles_total"] == 10  # the fallbacks only
    text = rec.render_prometheus()
    assert "stpu_compile_aot_loads_total 10" in text
    # control: the same volume unmarked storms immediately
    for i in range(5):
        rec.record(name="eval.native_score", signature=f"u{i}",
                   compile_s=0.01, now=t0 + 20 + i)
    assert rec.state()["storm_active"] is True


def test_kind_section_overrides_and_carries_fields(tmp_path):
    """kind_section (the generalized warm_section) stamps kind + extra
    fields onto compiles inside its extent; innermost wins."""
    path = _journal(tmp_path)
    _recorder()
    import jax
    import jax.numpy as jnp

    f = compile_mod.observe(jax.jit(lambda x: x * 2), "unit.fn")
    with compile_mod.warm_section():
        with compile_mod.kind_section("aot_fallback", aot_error="why"):
            f(jnp.ones((3,)))
        f(jnp.ones((5,)))
    evs = _compile_events(path)
    by_sig = {e["signature"]: e for e in evs}
    assert by_sig["float32[3]"]["kind"] == "aot_fallback"
    assert by_sig["float32[3]"]["aot_error"] == "why"
    assert by_sig["float32[5]"]["kind"] == "warm"


def test_obs_compile_cli_distinguishes_aot_kinds(tmp_path, capsys):
    """`obs compile` renders what admission actually did: loads vs
    fallbacks vs live compiles, from the dead fleet's journal alone."""
    from shifu_tensorflow_tpu.obs.__main__ import _compile_data, main

    path = _journal(tmp_path)
    rec = _recorder()
    rec.record(name="eval.native_score", signature="s8", compile_s=0.0,
               wall_s=0.002, bucket=8, kind="aot_load")
    rec.record(name="eval.native_score", signature="s16", compile_s=0.03,
               bucket=16, kind="aot_fallback", aot_error="CRC32 mismatch")
    rec.record(name="eval.native_score", signature="s32", compile_s=0.02,
               bucket=32, kind="warm")
    journal_mod.uninstall()
    data = _compile_data(read_events(path))
    a = data["callables"]["eval.native_score"]
    assert a["aot_loads"] == 1
    assert a["aot_fallbacks"] == 1
    assert a["warm"] == 1
    assert a["compiles"] == 2  # the aot_load is a LOAD, not a compile
    rc = main(["compile", "--journal", path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "1 AOT executable load(s)" in out
    assert "aot" in out and "fb" in out


def test_rollup_folds_aot_kinds(tmp_path):
    """The PR-13 rollup sidecar distinguishes aot loads from compiles:
    a window full of aot_load events folds zero into the compile-cost
    bucket and counts the loads on their own key."""
    from shifu_tensorflow_tpu.obs import rollup as rollup_mod

    comp = rollup_mod.RollupCompactor(
        str(tmp_path / "r.rollup.jsonl"), window_s=60.0, thread=False)
    for i in range(3):
        comp.note_event({"event": "compile", "ts": 100.0 + i,
                         "kind": "aot_load", "compile_s": 0.0})
    comp.note_event({"event": "compile", "ts": 103.0,
                     "kind": "aot_fallback", "compile_s": 0.5})
    comp.note_event({"event": "compile", "ts": 104.0, "compile_s": 0.25})
    comp.close()
    recs = [json.loads(l) for l in
            open(str(tmp_path / "r.rollup.jsonl"))]
    c = recs[0]["compile"]
    assert c["aot_loads"] == 3
    assert c["aot_fallbacks"] == 1
    assert c["compiles"] == 2
    assert c["compile_s"] == pytest.approx(0.75)


# -------------------------------------------- persistent cache satellite


def test_persistent_compile_cache_populates_and_applies(tmp_path):
    """apply_persistent_cache points jax's on-disk cache at the dir (the
    AOT fallback ladder's middle tier): compiles land entries there."""
    import jax
    import jax.numpy as jnp

    cache = tmp_path / "xla-cache"
    before = {
        k: getattr(jax.config, k) for k in
        ("jax_compilation_cache_dir",
         "jax_persistent_cache_min_compile_time_secs")
    }
    try:
        assert compile_mod.apply_persistent_cache(str(cache)) is True
        f = jax.jit(lambda x: jnp.tanh(x) * 3 + 1)
        np.asarray(f(jnp.ones((7,))))
        assert any(cache.iterdir())
    finally:
        for k, v in before.items():
            jax.config.update(k, v)
        # drop the live cache object too: it initialized against the
        # tmp dir and would otherwise serve cache HITS to later tests
        # whose compile-event assertions expect real backend compiles
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )

        _cc.reset_cache()


def test_compile_cache_dir_rides_obs_config(tmp_path):
    """shifu.tpu.compile-cache-dir resolves ObsConfig-style (conf key,
    CLI flag wins) and survives the JSON bridge to subprocess
    workers."""
    from shifu_tensorflow_tpu.config.conf import Conf
    from shifu_tensorflow_tpu.obs.config import ObsConfig, resolve_obs_config

    class _A:
        pass

    conf = Conf()
    conf.update({"shifu.tpu.compile-cache-dir": "/cache/from-conf"},
                source="<test>")
    cfg = resolve_obs_config(_A(), conf)
    assert cfg.compile_cache_dir == "/cache/from-conf"
    a = _A()
    a.compile_cache_dir = "/cache/from-cli"
    assert resolve_obs_config(a, conf).compile_cache_dir \
        == "/cache/from-cli"
    assert ObsConfig.from_json(cfg.to_json()) == cfg
    # default: off
    assert resolve_obs_config(_A(), Conf()).compile_cache_dir == ""


def test_resolve_aot_buckets_cli_and_conf(tmp_path):
    """--export-aot / shifu.tpu.export-aot decide; --export-aot-rows
    sizes the ladder (default = the serve warm set)."""
    from shifu_tensorflow_tpu.config import keys as K
    from shifu_tensorflow_tpu.config.conf import Conf
    from shifu_tensorflow_tpu.export.bucketing import ladder

    class _A:
        export_aot = None
        export_aot_rows = None

    assert aot_mod.resolve_aot_buckets(_A(), Conf()) is None
    a = _A()
    a.export_aot = True
    assert aot_mod.resolve_aot_buckets(a, Conf()) \
        == ladder(K.DEFAULT_SERVE_QUEUE_ROWS)
    a.export_aot_rows = 64
    assert aot_mod.resolve_aot_buckets(a, Conf()) == ladder(64)
    conf = Conf()
    conf.update({K.EXPORT_AOT: "true", K.EXPORT_AOT_ROWS: "32"},
                source="<test>")
    assert aot_mod.resolve_aot_buckets(_A(), conf) == ladder(32)
    # CLI false... (store_true can only enable; conf-off + no flag = off)
    conf2 = Conf()
    conf2.update({K.EXPORT_AOT: "false"}, source="<test>")
    assert aot_mod.resolve_aot_buckets(_A(), conf2) is None
