"""Trainer tests: loss parity semantics, end-to-end convergence on the
synthetic PSV dataset, checkpoint/resume epoch accounting, mesh-sharded DP
(SURVEY.md §7.1 step 4-5; §4 test-strategy items 3 and 5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.data.dataset import InMemoryDataset
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.ops.losses import weighted_bce, weighted_mse
from shifu_tensorflow_tpu.parallel.mesh import make_mesh
from shifu_tensorflow_tpu.train.checkpoint import Checkpointer
from shifu_tensorflow_tpu.train.trainer import Trainer


def _mc(epochs=3, opt="adam", lr=0.05, **params_extra):
    params = {"NumHiddenLayers": 2, "NumHiddenNodes": [16, 8],
              "ActivationFunc": ["relu", "tanh"], "LearningRate": lr,
              "Optimizer": opt}
    params.update(params_extra)
    return ModelConfig.from_json(
        {"train": {"numTrainEpochs": epochs, "validSetRate": 0.2,
                   "params": params}}
    )


def _dataset(psv_dataset, valid_rate=0.2):
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    return InMemoryDataset.load(psv_dataset["paths"], schema, valid_rate)


# ---- loss semantics ----

def test_weighted_mse_nonzero_weight_normalization():
    # TF1 SUM_BY_NONZERO_WEIGHTS parity: denominator = count of w != 0
    pred = jnp.asarray([[0.0], [1.0], [0.5]])
    target = jnp.asarray([[1.0], [1.0], [0.0]])
    w = jnp.asarray([[2.0], [0.0], [1.0]])
    # sum = 2*1 + 0 + 0.25 = 2.25; nonzero count = 2
    assert np.isclose(float(weighted_mse(pred, target, w)), 2.25 / 2)


def test_weighted_mse_padding_free():
    pred = jnp.asarray([[0.2], [0.9]])
    target = jnp.asarray([[0.0], [1.0]])
    w1 = jnp.asarray([[1.0], [1.0]])
    base = float(weighted_mse(pred, target, w1))
    # appending zero-weight padding rows must not change the loss
    pred2 = jnp.concatenate([pred, jnp.zeros((3, 1))])
    target2 = jnp.concatenate([target, jnp.zeros((3, 1))])
    w2 = jnp.concatenate([w1, jnp.zeros((3, 1))])
    assert np.isclose(float(weighted_mse(pred2, target2, w2)), base)


def test_weighted_bce_range():
    pred = jnp.asarray([[0.999], [0.001]])
    target = jnp.asarray([[1.0], [0.0]])
    w = jnp.ones((2, 1))
    assert float(weighted_bce(pred, target, w)) < 0.01


# ---- end-to-end convergence (the minimum end-to-end slice, §7.1) ----

def test_fit_learns_and_reports(psv_dataset):
    ds = _dataset(psv_dataset)
    trainer = Trainer(_mc(epochs=5), ds.schema.num_features, worker_index=0)
    seen = []
    history = trainer.fit(ds, batch_size=50, on_epoch=seen.append)
    assert len(history) == 5
    assert seen == history
    # learns: training loss drops, KS/AUC clearly better than chance
    assert history[-1].training_loss < history[0].training_loss
    assert np.isfinite(history[-1].valid_loss)
    assert history[-1].auc > 0.75
    assert history[-1].ks > 0.3
    # global step advances by steps-per-epoch each epoch
    assert history[0].global_step > 0
    assert history[-1].global_step == 5 * history[0].global_step


def test_adadelta_default_runs(psv_dataset):
    ds = _dataset(psv_dataset)
    trainer = Trainer(_mc(epochs=1, opt="adadelta", lr=1.0),
                      ds.schema.num_features)
    history = trainer.fit(ds, batch_size=100)
    assert np.isfinite(history[0].training_loss)


def test_predict_shape(psv_dataset):
    ds = _dataset(psv_dataset)
    trainer = Trainer(_mc(epochs=1), ds.schema.num_features)
    scores = trainer.predict(ds.valid.features)
    assert scores.shape == (len(ds.valid), 1)
    assert ((scores >= 0) & (scores <= 1)).all()


# ---- checkpoint / resume (fixes reference backup.py:30 TODO) ----

def test_checkpoint_resume_epoch_accounting(psv_dataset, tmp_path):
    ds = _dataset(psv_dataset)
    mc = _mc(epochs=4)

    with Checkpointer(str(tmp_path / "ckpt"), every_epochs=1) as ckpt:
        t1 = Trainer(mc, ds.schema.num_features, seed=3)
        t1.fit(ds, batch_size=50, epochs=2, checkpointer=ckpt)
        ckpt.wait()
        assert ckpt.latest_epoch() == 1

    # new process simulation: fresh trainer restores and resumes at epoch 2
    with Checkpointer(str(tmp_path / "ckpt")) as ckpt2:
        t2 = Trainer(mc, ds.schema.num_features, seed=99)  # different init
        next_epoch = t2.restore(ckpt2)
        assert next_epoch == 2
        # restored params equal the saved ones, not the fresh init
        np.testing.assert_allclose(
            jax.device_get(t2.state.params["shifu_output_0"]["kernel"]),
            jax.device_get(t1.state.params["shifu_output_0"]["kernel"]),
        )
        assert int(t2.state.step) == int(t1.state.step)
        history = t2.fit(ds, batch_size=50, start_epoch=next_epoch,
                         checkpointer=ckpt2)
        # trains exactly the remaining budget: epochs 2 and 3
        assert [h.current_epoch for h in history] == [2, 3]


def test_checkpoint_every_n(tmp_path, psv_dataset):
    ds = _dataset(psv_dataset)
    with Checkpointer(str(tmp_path / "c2"), every_epochs=2) as ckpt:
        t = Trainer(_mc(epochs=4), ds.schema.num_features)
        t.fit(ds, batch_size=100, checkpointer=ckpt)
        ckpt.wait()
        assert ckpt.latest_epoch() == 3  # epochs 1 and 3 saved (0-indexed)


# ---- mesh-sharded data parallelism (§4 item 3) ----

def test_mesh_dp_training_eight_devices(psv_dataset):
    assert jax.device_count() == 8, "conftest must force 8 cpu devices"
    mesh = make_mesh("data:8")
    ds = _dataset(psv_dataset)
    trainer = Trainer(_mc(epochs=2), ds.schema.num_features, mesh=mesh)
    history = trainer.fit(ds, batch_size=64)  # 64 rows / 8 devices
    assert np.isfinite(history[-1].training_loss)
    assert history[-1].valid_loss <= history[0].valid_loss * 1.5


def test_mesh_dp_matches_single_device(psv_dataset):
    """Sharded and unsharded training produce the same result — sync-DP
    semantic parity (SURVEY.md §7.2 item 3): the all-reduced sharded grad
    equals the full-batch grad."""
    ds = _dataset(psv_dataset)
    mc = _mc(epochs=1, opt="sgd", lr=0.1)

    t_single = Trainer(mc, ds.schema.num_features, seed=7)
    t_single.fit(ds, batch_size=64)

    mesh = make_mesh("data:8")
    t_mesh = Trainer(mc, ds.schema.num_features, seed=7, mesh=mesh)
    t_mesh.fit(ds, batch_size=64)

    a = jax.device_get(t_single.state.params["shifu_output_0"]["kernel"])
    b = jax.device_get(t_mesh.state.params["shifu_output_0"]["kernel"])
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_mesh_indivisible_batch_padded(psv_dataset):
    # regression: batch 100 on an 8-device mesh must not crash (review finding)
    ds = _dataset(psv_dataset)
    mesh = make_mesh("data:8")
    trainer = Trainer(_mc(epochs=1), ds.schema.num_features, mesh=mesh)
    assert trainer.align_batch_size(100) == 104
    history = trainer.fit(ds, batch_size=100)
    assert np.isfinite(history[0].training_loss)


def test_checkpoint_cross_mesh_restore(psv_dataset, tmp_path):
    """A checkpoint written by a model-parallel trainer (nn.Partitioned
    boxed embedding table) must restore into a mesh-less trainer and vice
    versa — the chief-export path builds exactly such a mesh-less Trainer.
    The on-disk tree is canonical (unboxed); the restoring template decides
    boxing."""
    mc = _mc(epochs=1, EmbeddingColumnNums=[2, 3], EmbeddingHashSize=64,
             EmbeddingDim=4)
    ds = _dataset(psv_dataset)
    feats = tuple(psv_dataset["feature_cols"])

    sharded = Trainer(mc, len(feats), feature_columns=feats,
                      mesh=make_mesh("data:4,model:2"))
    sharded.fit(ds, epochs=1, batch_size=100)
    with Checkpointer(str(tmp_path / "xmesh")) as ckpt:
        ckpt.save(0, sharded.state)
        ckpt.wait()

        plain = Trainer(mc, len(feats), feature_columns=feats)
        next_epoch = plain.restore(ckpt)
    assert next_epoch == 1
    # predictions agree between the two trainers after restore
    x = ds.valid.features[:32]
    np.testing.assert_allclose(
        plain.predict(x), sharded.predict(x), rtol=1e-5, atol=1e-6
    )

    # and the reverse direction: plain checkpoint into a sharded template
    with Checkpointer(str(tmp_path / "xmesh2")) as ckpt2:
        ckpt2.save(0, plain.state)
        ckpt2.wait()
        sharded2 = Trainer(mc, len(feats), feature_columns=feats,
                           mesh=make_mesh("data:4,model:2"))
        assert sharded2.restore(ckpt2) == 1
    np.testing.assert_allclose(
        sharded2.predict(x), plain.predict(x), rtol=1e-5, atol=1e-6
    )


# ---- chunked-scan epochs (shifu.tpu.scan-steps) ----

def test_scan_epoch_matches_per_step(psv_dataset):
    """scan_steps=K runs the same body in the same order as the per-step
    path — final params and reported epoch losses must match."""
    ds = _dataset(psv_dataset)
    mc = _mc(epochs=2, opt="adam", lr=0.05)

    t_step = Trainer(mc, ds.schema.num_features, seed=3)
    h_step = t_step.fit(ds, batch_size=64)

    t_scan = Trainer(mc, ds.schema.num_features, seed=3, scan_steps=4)
    h_scan = t_scan.fit(ds, batch_size=64)

    a = jax.device_get(t_step.state.params["shifu_output_0"]["kernel"])
    b = jax.device_get(t_scan.state.params["shifu_output_0"]["kernel"])
    np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    for hs, hc in zip(h_step, h_scan):
        assert np.isclose(hs.training_loss, hc.training_loss,
                          rtol=1e-5, atol=1e-6)
        assert hs.global_step == hc.global_step


def test_scan_epoch_tail_padding_counts():
    """A batch count not divisible by K pads the last chunk with no-op
    batches: the reported batch count and global step must count only the
    real batches, and the loss mean must ignore the padding."""
    mc = _mc(epochs=1)
    rng_ = np.random.default_rng(5)
    trainer = Trainer(mc, 6, seed=1, scan_steps=4)
    batches = [
        {
            "x": rng_.normal(size=(32, 6)).astype(np.float32),
            "y": (rng_.random((32, 1)) < 0.4).astype(np.float32),
            "w": np.ones((32, 1), np.float32),
        }
        for _ in range(7)  # 1 full chunk + tail of 3
    ]
    loss, n = trainer.train_epoch(iter(batches))
    assert n == 7
    assert int(jax.device_get(trainer.state.step)) == 7
    assert np.isfinite(loss)

    # parity with the per-step path on the identical batch sequence
    t_ref = Trainer(mc, 6, seed=1)
    loss_ref, n_ref = t_ref.train_epoch(iter(batches))
    assert n_ref == 7
    np.testing.assert_allclose(loss, loss_ref, rtol=1e-5, atol=1e-6)
    a = jax.device_get(trainer.state.params["shifu_output_0"]["kernel"])
    b = jax.device_get(t_ref.state.params["shifu_output_0"]["kernel"])
    np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_scan_epoch_fixed_shape_and_timer_rows():
    """A stream with varying batch sizes compiles ONE scan shape (fixed
    from the first chunk) as long as no later batch exceeds it, and the
    step timer is fed each chunk's REAL row count — not a later chunk's
    (the prefetch lookahead runs the producer ahead of the consumer)."""
    mc = _mc(epochs=1)
    rng_ = np.random.default_rng(9)

    def mk(n):
        return {
            "x": rng_.normal(size=(n, 6)).astype(np.float32),
            "y": (rng_.random((n, 1)) < 0.4).astype(np.float32),
            "w": np.ones((n, 1), np.float32),
        }

    trainer = Trainer(mc, 6, seed=1, scan_steps=2)
    rows_seen = []

    class _Timer:
        def step(self, loss, rows):
            rows_seen.append(rows)

    trainer.step_timer = _Timer()
    # first chunk fixes rows=32; later smaller batches pad into it
    batches = [mk(32), mk(32), mk(20), mk(8), mk(16)]
    loss, n = trainer.train_epoch(iter(batches))
    assert n == 5
    assert rows_seen == [64, 28, 16]  # real rows per chunk, in order
    assert np.isfinite(loss)
    sizes = trainer._scan_epoch._cache_size()
    assert sizes == 1, f"expected one compiled scan shape, got {sizes}"
    # a LARGER later batch regrows once — exactly one extra compile
    loss2, n2 = trainer.train_epoch(iter([mk(48), mk(32)]))
    assert n2 == 2
    assert trainer._scan_epoch._cache_size() == 2


# ---- gradient accumulation (shifu.tpu.accum-steps) ----

def test_accum_step_equals_big_batch_step():
    """accum_steps=A over A microbatches must produce the SAME update as
    one step on the concatenated batch — including the SUM_BY_NONZERO
    normalization, the tail group (zero-weight pad micros), and the
    l2 term applied once per update."""
    mc = _mc(epochs=1, L2Reg=0.01)
    rng_ = np.random.default_rng(11)

    def mk(n):
        return {
            "x": rng_.normal(size=(n, 6)).astype(np.float32),
            "y": (rng_.random((n, 1)) < 0.4).astype(np.float32),
            "w": (rng_.random((n, 1)) < 0.9).astype(np.float32),  # some 0s
        }

    micros = [mk(32) for _ in range(6)]  # A=4: one full group + tail of 2

    t_acc = Trainer(mc, 6, seed=2, accum_steps=4)
    loss_acc, n = t_acc.train_epoch(iter(micros))
    assert n == 6
    # one update per group: 2 updates
    assert int(jax.device_get(t_acc.state.step)) == 2

    # reference: per-step trainer fed the CONCATENATED groups
    def cat(bs):
        return {k: np.concatenate([b[k] for b in bs]) for k in bs[0]}

    t_big = Trainer(mc, 6, seed=2)
    loss_big, n_big = t_big.train_epoch(
        iter([cat(micros[:4]), cat(micros[4:])])
    )
    assert n_big == 2
    a = jax.device_get(t_acc.state.params["shifu_output_0"]["kernel"])
    b = jax.device_get(t_big.state.params["shifu_output_0"]["kernel"])
    np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(loss_acc, loss_big, rtol=1e-5, atol=1e-6)


def test_accum_on_mesh_matches_single_device():
    """The stacked chunk shards the batch dim over the data axis; mesh
    accumulation equals single-device accumulation."""
    from shifu_tensorflow_tpu.parallel.mesh import make_mesh

    mc = _mc(epochs=1, opt="sgd", lr=0.1)
    rng_ = np.random.default_rng(13)

    def mk(n):
        return {
            "x": rng_.normal(size=(n, 6)).astype(np.float32),
            "y": (rng_.random((n, 1)) < 0.4).astype(np.float32),
            "w": np.ones((n, 1), np.float32),
        }

    micros = [mk(64) for _ in range(4)]
    t_mesh = Trainer(mc, 6, seed=5, accum_steps=2, mesh=make_mesh("data:-1"))
    t_mesh.train_epoch(iter(micros))
    t_one = Trainer(mc, 6, seed=5, accum_steps=2)
    t_one.train_epoch(iter(micros))
    a = jax.device_get(t_mesh.state.params["shifu_output_0"]["kernel"])
    b = jax.device_get(t_one.state.params["shifu_output_0"]["kernel"])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-6)


def test_accum_and_scan_are_mutually_exclusive():
    with pytest.raises(ValueError, match="mutually exclusive"):
        Trainer(_mc(epochs=1), 6, scan_steps=4, accum_steps=4)


def test_accum_rejects_update_window():
    """Both knobs define gradient accumulation; composing them would wrap
    each accumulated group's apply in a SECOND MultiSteps window — nested
    semantics nobody configured."""
    with pytest.raises(ValueError, match="UpdateWindow"):
        Trainer(_mc(epochs=1, UpdateWindow=4), 6, accum_steps=8)


def test_keep_best_ignores_unreadable_snapshot(tmp_path):
    """A truncated/corrupt keep-best.npz degrades to 'no best yet' with a
    warning — it must never brick resume or the fleet export."""
    d = str(tmp_path)
    (tmp_path / "keep-best.npz").write_bytes(b"not a zip at all")
    t = Trainer(_mc(epochs=1), 6, keep_best="ks")
    with pytest.warns(UserWarning, match="unreadable keep-best"):
        t._restore_best(d)
    assert t.best_params is None
    # absent file: silently none, no warning
    t2 = Trainer(_mc(epochs=1), 6, keep_best="ks")
    t2._restore_best(str(tmp_path / "nowhere"))
    assert t2.best_params is None


def test_keep_best_skips_empty_validation_epochs():
    """ks=0 with NaN valid loss means NO scored rows — absence of a
    measurement must not crown the first epoch as 'best', and the fit
    loop warns once."""
    from shifu_tensorflow_tpu.train.trainer import EpochStats

    t = Trainer(_mc(epochs=1), 6, keep_best="ks")
    empty = EpochStats(0, 0, 0.2, float("nan"), 1.0, 0.1, 1, ks=0.0)
    t._maybe_snapshot_best(empty)
    assert t.best_params is None  # not crowned
    with pytest.warns(UserWarning, match="no scored rows"):
        t._warn_if_validation_empty(empty, None)
    # real 0-KS epochs (with a real loss) still participate
    real = EpochStats(0, 1, 0.2, 0.4, 1.0, 0.1, 2, ks=0.0)
    t._maybe_snapshot_best(real)
    assert t.best_params is not None


def test_sagn_rejects_accum_steps():
    from shifu_tensorflow_tpu.train import make_trainer

    sagn_mc = _mc(epochs=1, Algorithm="sagn")
    with pytest.raises(ValueError, match="accum-steps"):
        make_trainer(sagn_mc, 6, accum_steps=4)


def test_sagn_rejects_lr_schedule():
    """A schedule would apply only to SAGN's global apply while the local
    window steps kept the flat LR — reject the half-applied semantics."""
    from shifu_tensorflow_tpu.train import make_trainer

    with pytest.raises(ValueError, match="LearningRateSchedule"):
        make_trainer(
            _mc(epochs=1, Algorithm="sagn",
                LearningRateSchedule="cosine", DecaySteps=10), 6
        )
    with pytest.raises(ValueError, match="LearningRateSchedule"):
        make_trainer(_mc(epochs=1, Algorithm="sagn", WarmupSteps=5), 6)


# ---- learning-rate schedules (LearningRateSchedule/WarmupSteps/...) ----

def test_make_schedule_shapes_and_errors():
    import pytest

    from shifu_tensorflow_tpu.train.optimizers import make_schedule

    # constant stays a bare float
    assert make_schedule(_mc().params) == 0.05

    cos = make_schedule(_mc(LearningRateSchedule="cosine", DecaySteps=100,
                            DecayRate=0.1, lr=0.2).params)
    np.testing.assert_allclose(float(cos(0)), 0.2, rtol=1e-6)
    np.testing.assert_allclose(float(cos(100)), 0.02, rtol=1e-5)  # alpha*lr

    exp = make_schedule(_mc(LearningRateSchedule="exponential",
                            DecaySteps=10, DecayRate=0.5, lr=0.2).params)
    np.testing.assert_allclose(float(exp(0)), 0.2, rtol=1e-6)
    np.testing.assert_allclose(float(exp(10)), 0.1, rtol=1e-5)
    np.testing.assert_allclose(float(exp(20)), 0.05, rtol=1e-5)

    warm = make_schedule(_mc(LearningRateSchedule="cosine", DecaySteps=100,
                             WarmupSteps=10, lr=0.2).params)
    np.testing.assert_allclose(float(warm(0)), 0.0, atol=1e-9)
    np.testing.assert_allclose(float(warm(10)), 0.2, rtol=1e-5)  # peak
    assert float(warm(110)) < 0.021  # decayed past warmup

    with pytest.raises(ValueError, match="DecaySteps"):
        make_schedule(_mc(LearningRateSchedule="cosine").params)
    with pytest.raises(ValueError, match="unknown LearningRateSchedule"):
        make_schedule(_mc(LearningRateSchedule="triangular",
                          DecaySteps=5).params)


def test_lr_schedule_trains_and_decays():
    """A scheduled trainer runs, and the schedule actually bites: with an
    aggressive exponential decay the post-warmup updates shrink (compare
    param movement per epoch against a constant-LR twin)."""
    mc_sched = _mc(epochs=1, opt="sgd", lr=0.5,
                   LearningRateSchedule="exponential", DecaySteps=1,
                   DecayRate=0.01)
    mc_const = _mc(epochs=1, opt="sgd", lr=0.5)
    rng_ = np.random.default_rng(3)
    batches = [
        {
            "x": rng_.normal(size=(64, 6)).astype(np.float32),
            "y": (rng_.random((64, 1)) < 0.4).astype(np.float32),
            "w": np.ones((64, 1), np.float32),
        }
        for _ in range(8)
    ]
    t_s = Trainer(mc_sched, 6, seed=1)
    t_c = Trainer(mc_const, 6, seed=1)
    k0 = jax.device_get(t_s.state.params["shifu_output_0"]["kernel"]).copy()
    t_s.train_epoch(iter(batches))
    t_c.train_epoch(iter(batches))
    moved_s = np.abs(
        jax.device_get(t_s.state.params["shifu_output_0"]["kernel"]) - k0
    ).sum()
    moved_c = np.abs(
        jax.device_get(t_c.state.params["shifu_output_0"]["kernel"]) - k0
    ).sum()
    # decay 0.01/step collapses the LR after step 1; constant keeps moving
    assert moved_s < moved_c * 0.6, (moved_s, moved_c)


# ---- keep-best (shifu.tpu.keep-best) ----

def test_keep_best_snapshots_and_export_serves_it(tmp_path):
    """The best-validation epoch's params are snapshotted and the export
    serves THEM — scores must match the snapshot, not the (worse) final
    params."""
    import pytest

    from shifu_tensorflow_tpu.export.eval_model import EvalModel
    from shifu_tensorflow_tpu.export.saved_model import export_model
    from shifu_tensorflow_tpu.train.trainer import EpochStats

    with pytest.raises(ValueError, match="keep_best"):
        Trainer(_mc(), 6, keep_best="auc")

    t = Trainer(_mc(epochs=1), 6, seed=2, keep_best="valid_loss")

    def stats(epoch, valid_loss):
        return EpochStats(0, epoch, 0.2, valid_loss, 1.0, 0.1, epoch)

    t._maybe_snapshot_best(stats(0, 0.5))
    assert t.best_epoch == 0 and t.best_metric == 0.5
    best_kernel = t.best_params["shifu_output_0"]["kernel"].copy()
    # make the live params drift (simulates further, worse epochs)
    t.state = t.state.replace(
        params=jax.tree_util.tree_map(lambda p: p + 1.0, t.state.params)
    )
    t._maybe_snapshot_best(stats(1, 0.7))  # worse: no new snapshot
    assert t.best_epoch == 0
    np.testing.assert_array_equal(
        t.best_params["shifu_output_0"]["kernel"], best_kernel
    )
    t._maybe_snapshot_best(stats(2, float("nan")))  # NaN never wins
    assert t.best_epoch == 0

    export_dir = str(tmp_path / "best-model")
    export_model(export_dir, t)
    x = np.random.default_rng(0).random((16, 6)).astype(np.float32)
    want = t.model.apply({"params": t.best_params}, x)
    with EvalModel(export_dir, backend="native") as em:
        np.testing.assert_allclose(em.compute_batch(x), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
    # and NOT the drifted live params
    live = np.asarray(t.model.apply({"params": t.state.params}, x))
    assert not np.allclose(np.asarray(want), live)


def test_keep_best_survives_resume(psv_dataset, tmp_path):
    """The best snapshot persists beside the checkpoints: a resumed run
    competes against the TRUE best, not best-since-resume — otherwise the
    export after a crash+resume silently serves a worse model."""
    from shifu_tensorflow_tpu.train.trainer import EpochStats

    ds = _dataset(psv_dataset)
    ckpt_dir = str(tmp_path / "ckpt")
    t1 = Trainer(_mc(epochs=2), ds.schema.num_features, seed=1,
                 keep_best="valid_loss")
    ck = Checkpointer(ckpt_dir)
    t1.fit(ds, batch_size=100, checkpointer=ck)
    assert t1.best_params is not None
    # simulate a much better epoch than a resumed run will ever see
    t1.best_metric = 1e-9
    t1.best_epoch = 1
    t1._persist_best(ck.directory)
    ck.close()

    t2 = Trainer(_mc(epochs=4), ds.schema.num_features, seed=1,
                 keep_best="valid_loss")
    ck2 = Checkpointer(ckpt_dir)
    start = t2.restore(ck2)
    assert start == 2
    assert t2.best_metric == 1e-9 and t2.best_epoch == 1  # true best kept
    np.testing.assert_array_equal(
        t2.best_params["shifu_output_0"]["kernel"],
        t1.best_params["shifu_output_0"]["kernel"],
    )
    # further epochs cannot beat 1e-9: the persisted best stays exported
    t2.fit(ds, batch_size=100, checkpointer=ck2, start_epoch=start)
    assert t2.best_epoch == 1
    ck2.close()
    # a DIFFERENT metric ignores the stale snapshot instead of comparing
    # apples to oranges
    t3 = Trainer(_mc(epochs=4), ds.schema.num_features, seed=1,
                 keep_best="ks")
    t3._restore_best(ckpt_dir)
    assert t3.best_params is None


def test_keep_best_ks_tracks_improvements(psv_dataset):
    """End-to-end fit with keep_best='ks': the snapshot tracks the best-KS
    epoch seen in history."""
    ds = _dataset(psv_dataset)
    t = Trainer(_mc(epochs=4), ds.schema.num_features, seed=1,
                keep_best="ks")
    hist = t.fit(ds, batch_size=100)
    assert t.best_params is not None
    best = max(hist, key=lambda h: h.ks)
    assert t.best_epoch == best.current_epoch
    assert t.best_metric == pytest.approx(best.ks)


# ---- early stopping (shifu.tpu.early-stop-ks / early-stop-patience) ----

def test_early_stop_on_target_ks(psv_dataset):
    """Once validation KS reaches the target the fit loop stops, records
    the reason, and history is shorter than the epoch budget."""
    from shifu_tensorflow_tpu.train.trainer import EarlyStopper

    ds = _dataset(psv_dataset)
    t = Trainer(_mc(epochs=50), ds.schema.num_features, seed=1)
    hist = t.fit(ds, batch_size=100,
                 early_stop=EarlyStopper(target_ks=0.2))
    assert len(hist) < 50
    assert t.stop_reason and "reached target" in t.stop_reason
    assert hist[-1].ks >= 0.2


def test_early_stop_patience_counts_only_real_valid_epochs():
    """NaN validation loss (no validation data) must not feed patience —
    and with real validation, patience stops after N bad epochs."""
    from shifu_tensorflow_tpu.train.trainer import EarlyStopper
    from shifu_tensorflow_tpu.train.trainer import EpochStats

    def stats(epoch, valid_loss, ks=0.0):
        return EpochStats(0, epoch, 0.1, valid_loss, 0.0, 0.0, epoch, ks)

    es = EarlyStopper(patience=2)
    assert es.should_stop(stats(0, float("nan"))) is None
    assert es.should_stop(stats(1, float("nan"))) is None  # NaN never counts
    assert es.should_stop(stats(2, 0.5)) is None   # first real: improves inf
    assert es.should_stop(stats(3, 0.6)) is None   # bad 1
    reason = es.should_stop(stats(4, 0.55))        # bad 2 -> stop
    assert reason and "improvement" in reason
    # improvement resets the counter
    es2 = EarlyStopper(patience=2)
    assert es2.should_stop(stats(0, 0.5)) is None
    assert es2.should_stop(stats(1, 0.6)) is None  # bad 1
    assert es2.should_stop(stats(2, 0.4)) is None  # improves -> reset
    assert es2.should_stop(stats(3, 0.5)) is None  # bad 1
    assert es2.should_stop(stats(4, 0.5)) is not None  # bad 2 -> stop


def test_scan_epoch_on_mesh_matches_per_step(psv_dataset):
    """Stacked chunks shard the batch dim over the data axis; mesh-sharded
    scan training equals mesh-sharded per-step training."""
    ds = _dataset(psv_dataset)
    mc = _mc(epochs=1, opt="sgd", lr=0.1)

    t_step = Trainer(mc, ds.schema.num_features, seed=7,
                     mesh=make_mesh("data:8"))
    t_step.fit(ds, batch_size=64)

    t_scan = Trainer(mc, ds.schema.num_features, seed=7,
                     mesh=make_mesh("data:8"), scan_steps=3)
    t_scan.fit(ds, batch_size=64)

    a = jax.device_get(t_step.state.params["shifu_output_0"]["kernel"])
    b = jax.device_get(t_scan.state.params["shifu_output_0"]["kernel"])
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_scan_epoch_indivisible_and_ragged_batches(psv_dataset):
    """Review regression: the scan path must accept exactly what the
    per-step path accepts — batch sizes that don't divide the data axis
    (padded via align_batch_size, like _pad_for_mesh) and a short final
    batch (padded to the chunk's row count)."""
    ds = _dataset(psv_dataset)
    mc = _mc(epochs=1)

    # 100-row batches on an 8-device mesh, scan chunks of 3
    mesh = make_mesh("data:8")
    t = Trainer(mc, ds.schema.num_features, mesh=mesh, scan_steps=3)
    history = t.fit(ds, batch_size=100)
    assert np.isfinite(history[0].training_loss)

    # ragged iterator: mixed 32/20-row batches, no mesh
    rng_ = np.random.default_rng(9)

    def mk(n):
        return {
            "x": rng_.normal(size=(n, ds.schema.num_features)).astype(np.float32),
            "y": (rng_.random((n, 1)) < 0.4).astype(np.float32),
            "w": np.ones((n, 1), np.float32),
        }

    t2 = Trainer(mc, ds.schema.num_features, scan_steps=4)
    loss, n = t2.train_epoch(iter([mk(32), mk(32), mk(20), mk(32), mk(8)]))
    assert n == 5 and np.isfinite(loss)
    assert int(jax.device_get(t2.state.step)) == 5


# ---- device-resident fit (--device-resident / shifu.tpu.device-resident) ----

def test_device_resident_fit_learns(psv_dataset):
    """Whole-dataset-in-HBM epochs: converges on the synthetic set, counts
    steps correctly (ceil(n/B) per epoch), reports KS/AUC."""
    ds = _dataset(psv_dataset)
    mc = _mc(epochs=4)
    trainer = Trainer(mc, ds.schema.num_features, seed=2)
    history = trainer.fit_device_resident(ds, batch_size=64)
    assert len(history) == 4
    assert history[-1].valid_loss < history[0].valid_loss
    assert history[-1].ks > 0.3
    steps_per_epoch = -(-len(ds.train) // 64)
    assert history[-1].global_step == 4 * steps_per_epoch


def test_device_resident_fit_deterministic(psv_dataset):
    ds = _dataset(psv_dataset)
    mc = _mc(epochs=2)
    a = Trainer(mc, ds.schema.num_features, seed=11)
    a.fit_device_resident(ds, batch_size=64)
    b = Trainer(mc, ds.schema.num_features, seed=11)
    b.fit_device_resident(ds, batch_size=64)
    ka = jax.device_get(a.state.params["shifu_output_0"]["kernel"])
    kb = jax.device_get(b.state.params["shifu_output_0"]["kernel"])
    np.testing.assert_array_equal(ka, kb)


def test_device_resident_fit_on_mesh(psv_dataset):
    ds = _dataset(psv_dataset)
    mc = _mc(epochs=2)
    trainer = Trainer(mc, ds.schema.num_features, seed=2,
                      mesh=make_mesh("data:8"))
    history = trainer.fit_device_resident(ds, batch_size=64)
    assert np.isfinite(history[-1].training_loss)
    assert history[-1].ks > 0.2


def test_device_resident_checkpoint_interop(psv_dataset, tmp_path):
    """Checkpoints written by the device-resident path restore into the
    per-step path and vice versa — one on-disk contract."""
    ds = _dataset(psv_dataset)
    mc = _mc(epochs=2)
    t1 = Trainer(mc, ds.schema.num_features, seed=4)
    with Checkpointer(str(tmp_path / "dr")) as ckpt:
        t1.fit_device_resident(ds, batch_size=64, checkpointer=ckpt)
        ckpt.wait()
        t2 = Trainer(mc, ds.schema.num_features, seed=99)
        restored, nxt = ckpt.restore_latest(t2.state)
    assert nxt == 2
    ka = jax.device_get(t1.state.params["shifu_output_0"]["kernel"])
    kb = jax.device_get(restored.params["shifu_output_0"]["kernel"])
    np.testing.assert_allclose(ka, kb, rtol=1e-6)


def test_device_resident_rejects_cross_process(psv_dataset):
    from shifu_tensorflow_tpu.parallel.distributed import ProcessTopology

    ds = _dataset(psv_dataset)
    trainer = Trainer(_mc(epochs=1), ds.schema.num_features,
                      mesh=make_mesh("data:8"),
                      topology=ProcessTopology(num_processes=1, process_id=0))
    with pytest.raises(ValueError, match="single-controller"):
        trainer.fit_device_resident(ds, batch_size=64)


def test_device_resident_rejects_sagn(psv_dataset):
    from shifu_tensorflow_tpu.train import make_trainer

    ds = _dataset(psv_dataset)
    mc = _mc(epochs=1, Algorithm="sagn")
    trainer = make_trainer(mc, ds.schema.num_features)
    with pytest.raises(NotImplementedError, match="SAGN"):
        trainer.fit_device_resident(ds, batch_size=64)


def test_device_resident_multi_task_eval(psv_dataset):
    """Regression: multi-output heads (C>1) must score head 0 for KS/AUC,
    not a flattened (rows*C) vector."""
    ds = _dataset(psv_dataset)
    trainer = Trainer(_mc(epochs=2, ModelType="multi_task", NumTasks=3),
                      ds.schema.num_features, seed=2)
    history = trainer.fit_device_resident(ds, batch_size=64)
    assert np.isfinite(history[-1].valid_loss)
    assert 0.0 <= history[-1].auc <= 1.0


def test_scan_epoch_composes_with_shard_stream(psv_dataset):
    """--stream + --scan-steps: chunked-scan over a deterministic 1-reader
    ShardStream must equal the per-step stream run exactly."""
    from shifu_tensorflow_tpu.data.dataset import ShardStream
    from shifu_tensorflow_tpu.data.reader import RecordSchema

    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    mc = _mc(epochs=2)

    def run(scan_steps):
        tr = Trainer(mc, schema.num_features, seed=6, scan_steps=scan_steps)
        tr.fit_stream(
            lambda epoch: ShardStream(
                psv_dataset["paths"], schema, 64,
                valid_rate=0.2, emit="train", n_readers=1,
            ),
            epochs=2,
        )
        return jax.device_get(tr.state.params["shifu_output_0"]["kernel"])

    np.testing.assert_allclose(run(1), run(3), rtol=2e-5, atol=2e-6)


def test_device_resident_bf16(psv_dataset):
    """--device-resident composes with --dtype bfloat16 (fp32 host data
    cast on device; loss finite, metrics sane)."""
    ds = _dataset(psv_dataset)
    trainer = Trainer(_mc(epochs=2), ds.schema.num_features, seed=2,
                      dtype=jnp.bfloat16)
    history = trainer.fit_device_resident(ds, batch_size=64)
    assert np.isfinite(history[-1].training_loss)
    assert 0.0 <= history[-1].auc <= 1.0


# ---- compact bf16 transport, fp32 compute ----

def test_bf16_transport_widens_on_device_fp32_compute():
    """The streaming default ships bf16 features to an fp32 model; the
    jitted step widens on device (_widen_features), so params stay fp32
    and the loss trajectory tracks the fp32-transport run to bf16 input
    quantization error (r04 verdict item 3: transport is KS-neutral)."""
    import ml_dtypes

    rng = np.random.default_rng(7)
    n, f = 512, 6
    x32 = rng.normal(size=(n, f)).astype(np.float32)
    y = (rng.random((n, 1)) < 0.4).astype(np.float32)
    w = np.ones((n, 1), np.float32)
    x16 = x32.astype(ml_dtypes.bfloat16)

    def run(x):
        tr = Trainer(_mc(epochs=1), f, seed=3)
        losses = []
        for i in range(0, n, 128):
            sl = slice(i, i + 128)
            batch = tr._put({"x": x[sl], "y": y[sl], "w": w[sl]})
            tr.state, loss = tr._train_step(tr.state, batch)
            losses.append(float(loss))
        return tr, losses

    tr32, l32 = run(x32)
    tr16, l16 = run(x16)
    # params computed fp32 in both runs
    leaves = jax.tree_util.tree_leaves(tr16.state.params)
    assert all(l.dtype == jnp.float32 for l in leaves)
    # bf16 transport tracks fp32 transport closely (input quantization
    # is ~0.4% relative; trajectories stay within a small tolerance)
    np.testing.assert_allclose(l16, l32, rtol=0.05, atol=5e-3)
    # eval path widens too
    ev16 = tr16._eval_step(
        tr16.state.params,
        tr16._put({"x": x16[:128], "y": y[:128], "w": w[:128]}))
    assert np.isfinite(float(ev16[0]))


def test_bf16_transport_ks_parity_streaming(psv_dataset):
    """KS-parity gate for the compact-transport default: streaming the
    demo set with bf16 features yields the same validation KS/AUC as fp32
    transport to within noise (r04 verdict item 3 done-criterion)."""
    from shifu_tensorflow_tpu.data.dataset import ShardStream
    from shifu_tensorflow_tpu.data.reader import RecordSchema

    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )

    def run(feature_dtype):
        tr = Trainer(_mc(epochs=3), schema.num_features, seed=4)
        history = tr.fit_stream(
            lambda epoch: ShardStream(
                psv_dataset["paths"], schema, 64, valid_rate=0.2,
                emit="train", n_readers=1, feature_dtype=feature_dtype,
            ),
            (lambda: ShardStream(
                psv_dataset["paths"], schema, 64, valid_rate=0.2,
                emit="valid", n_readers=1, feature_dtype=feature_dtype,
            )),
            epochs=3,
        )
        return history[-1]

    f32 = run("float32")
    b16 = run("bfloat16")
    assert np.isfinite(b16.ks) and np.isfinite(b16.auc)
    assert abs(b16.ks - f32.ks) < 0.05
    assert abs(b16.auc - f32.auc) < 0.03


def test_npz_checkpoint_arrays_do_not_alias_device_buffers(tmp_path):
    """CPU-backend device_get is zero-copy: without an explicit copy the
    async checkpoint writer would stream a VIEW of the live XLA buffer
    that the next donated train step may reuse mid-write.  The saved
    bytes must be a stable snapshot: mutate the state with donated steps
    after an async save; the restored checkpoint equals the pre-step
    snapshot."""
    from shifu_tensorflow_tpu.train.checkpoint import NpzCheckpointer
    from shifu_tensorflow_tpu.train.trainer import make_train_step

    tr = Trainer(_mc(epochs=1), 6, seed=11)
    rng = np.random.default_rng(0)
    batch = tr._put({
        "x": rng.normal(size=(64, 6)).astype(np.float32),
        "y": (rng.random((64, 1)) < 0.4).astype(np.float32),
        "w": np.ones((64, 1), np.float32),
    })
    snapshot = jax.tree_util.tree_map(
        lambda l: np.array(l, copy=True), jax.device_get(tr.state.params))
    step = make_train_step(tr.model.apply, donate=True)
    with NpzCheckpointer(str(tmp_path), async_save=True) as ck:
        ck.save(0, tr.state)
        # donated steps churn the buffers while the write may be in flight
        for _ in range(10):
            tr.state, _ = step(tr.state, batch)
        ck.wait()
        restored, _next = ck.restore_latest(tr.state)
    got = jax.device_get(restored.params)
    for path in (("trunk", "hidden_layer0", "kernel"),
                 ("shifu_output_0", "kernel")):
        want = snapshot
        have = got
        for k in path:
            want, have = want[k], have[k]
        np.testing.assert_array_equal(np.asarray(have), want)
