"""Long-horizon observability: rollup archive, cost attribution, and
cross-run regression detection (obs/rollup.py + obs/cost.py).

The load-bearing drills:

- **Rotation conservation**: with a journal small enough to rotate
  several times, the rollup-reconstructed totals must equal the live
  registry counters exactly — the sidecar is the survivor, the journal
  is not.
- **Restart idempotence**: a compactor that crashed mid-window loses at
  most that window; a restarted one can never double-count.
- **Shed exactness**: rate-limited `shed` events undercount by design;
  the report's totals must come from the monotonic counters.
- **Cost conservation**: per-tenant device-seconds must sum to within
  5% of the dispatch lane's measured busy wall.
"""

import json
import time

import numpy as np
import pytest

from shifu_tensorflow_tpu.obs import cost as cost_mod
from shifu_tensorflow_tpu.obs import journal as journal_mod
from shifu_tensorflow_tpu.obs import rollup as rollup_mod
from shifu_tensorflow_tpu.obs import slo as slo_mod
from shifu_tensorflow_tpu.obs import trace as trace_mod
from shifu_tensorflow_tpu.obs.__main__ import main as obs_main
from shifu_tensorflow_tpu.obs.journal import Journal, read_events
from shifu_tensorflow_tpu.obs.registry import MetricsRegistry
from shifu_tensorflow_tpu.obs.rollup import (
    RegressionWatchdog,
    RollupCompactor,
    merge_digest_snapshots,
    read_rollups,
    reconstruct,
    rollup_files,
    rollup_path,
)


@pytest.fixture(autouse=True)
def _clean_obs_hooks():
    yield
    from shifu_tensorflow_tpu.obs import compile as compile_mod
    from shifu_tensorflow_tpu.obs import datastats as datastats_mod
    from shifu_tensorflow_tpu.obs import fleet as fleet_mod
    from shifu_tensorflow_tpu.obs import memory as memory_mod

    trace_mod.uninstall()
    journal_mod.uninstall()
    slo_mod.uninstall()
    fleet_mod.uninstall()
    compile_mod.uninstall()
    memory_mod.uninstall()
    datastats_mod.uninstall()
    datastats_mod.uninstall_train()
    cost_mod.uninstall()
    rollup_mod.uninstall()
    rollup_mod.uninstall_regression()
    for name in ("test", "serve", "cost"):
        rollup_mod.unregister_source(name)


class _Clock:
    """Manually-advanced wall clock for the frozen-clock drills."""

    def __init__(self, t: float = 1_000_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _serve_batch(ts: float, rows: int = 8, model: str | None = None,
                 bucket: int | None = None) -> dict:
    rec = {"ts": ts, "event": "serve_batch", "plane": "serve",
           "rows": rows, "requests": 2,
           "bucket": bucket if bucket is not None else rows,
           "dispatch_s": 0.004, "queue_delay_s": 0.001}
    if model:
        rec["model"] = model
    return rec


# ---- compactor folding ----

def test_compactor_folds_events_and_reconstructs(tmp_path):
    path = str(tmp_path / "j.jsonl.rollup.jsonl")
    comp = RollupCompactor(path, window_s=10.0, plane="serve",
                           worker=0, job="jobx", thread=False)
    t = 1000.0
    for i in range(30):
        comp.note_event(_serve_batch(t + i * 0.5, rows=8, model="alpha"))
    comp.note_event({"ts": t + 1, "event": "step_breakdown",
                     "plane": "train", "worker": 1, "steps": 64,
                     "dispatch_s": 0.5, "infeed_s": 0.1, "host_s": 0.2,
                     "block_s": 0.05})
    comp.note_event({"ts": t + 2, "event": "epoch", "plane": "train",
                     "worker": 1, "train_time_s": 1.25})
    comp.note_event({"ts": t + 3, "event": "device_mem",
                     "total_bytes": 1 << 20, "devmem_frac": 0.25})
    comp.note_event({"ts": t + 4, "event": "compile", "name": "x",
                     "compile_s": 0.8})
    comp.close()
    records = read_rollups(path)
    assert records, "no rollup records written"
    assert all(r["schema"] == rollup_mod.ROLLUP_SCHEMA for r in records)
    # 30 events at 0.5s spacing cross the 10s window boundary: >1 record
    assert len(records) >= 2
    doc = reconstruct(records)
    assert doc["events"]["serve_batch"] == 30
    assert doc["serve"]["alpha"]["rows"] == 240
    assert doc["serve"]["alpha"]["batches"] == 30
    assert doc["train"]["1"]["steps"] == 64
    assert doc["train"]["1"]["train_time_s"] == pytest.approx(1.25)
    assert doc["gauges"]["total_bytes"] == 1 << 20
    assert doc["compile"]["compiles"] == 1
    assert doc["jobs"] == ["jobx"]


def test_rotation_conservation_frozen_clock(tmp_path, monkeypatch):
    """The acceptance drill in miniature: a journal that rotated ≥2
    times has LOST events, but the rollup-reconstructed totals equal
    the live registry counters exactly, and the event folds equal what
    was emitted."""
    clk = _Clock()
    monkeypatch.setattr(rollup_mod, "_time", clk)
    monkeypatch.setattr(journal_mod.time, "time", clk)
    base = str(tmp_path / "journal.jsonl")
    jrn = Journal(base, max_bytes=4096, max_files=3, plane="serve")
    comp = RollupCompactor(rollup_path(base), window_s=10.0,
                           plane="serve", thread=False)
    jrn.set_tap(comp.note_event)
    jrn.on_close(comp.close)
    registry = MetricsRegistry()
    rollup_mod.register_source("test", registry.counters)

    emitted_rows = 0
    n_events = 400
    for i in range(n_events):
        rows = 4 + (i % 5)
        # padding (the x field) makes lines fat enough that 400 events
        # blow through the 4 KiB cap several times over
        jrn.emit("serve_batch", plane="serve", rows=rows, requests=1,
                 bucket=rows, dispatch_s=0.001, queue_delay_s=0.0,
                 x="p" * 64)
        registry.inc("requests_total")
        registry.inc("rows_total", rows)
        emitted_rows += rows
        clk.advance(0.25)
    jrn.close()

    # the journal really rotated and really lost history
    rotated = [p for p in journal_mod.journal_files(base)
               if p != base]
    assert len(rotated) >= 2, journal_mod.journal_files(base)
    surviving = [e for e in read_events(base)
                 if e["event"] == "serve_batch"]
    assert len(surviving) < n_events, \
        "journal never rotated anything away — the drill proves nothing"

    # ... but the rollup reconstruction is exact
    doc = reconstruct(read_rollups(base))
    assert doc["events"]["serve_batch"] == n_events
    assert doc["serve"]["default"]["rows"] == emitted_rows
    live = registry.counters()
    assert doc["counters"]["test"]["requests_total"] == live["requests_total"]
    assert doc["counters"]["test"]["rows_total"] == live["rows_total"]
    # windows actually downsampled: far fewer records than events
    assert doc["windows"] < n_events / 4


def test_compactor_restart_never_double_counts(tmp_path, monkeypatch):
    """Crash mid-window: the unflushed window is lost (undercount at
    most one window), never replayed (a restarted compactor appends,
    it does not re-read)."""
    clk = _Clock()
    monkeypatch.setattr(rollup_mod, "_time", clk)
    path = str(tmp_path / "j.jsonl.rollup.jsonl")

    reg_a = MetricsRegistry()
    rollup_mod.register_source("test", reg_a.counters)
    a = RollupCompactor(path, window_s=10.0, thread=False)
    for i in range(10):
        a.note_event(_serve_batch(clk.t, rows=8))
        reg_a.inc("rows_total", 8)
        clk.advance(0.5)
    a.flush(clk.t)
    flushed_counter = reg_a.counters()["rows_total"]
    # crash mid-window: more events + counter movement, NO flush/close
    for i in range(5):
        a.note_event(_serve_batch(clk.t, rows=8))
        reg_a.inc("rows_total", 8)
        clk.advance(0.5)
    del a  # the process died — nothing flushes

    # restart: a NEW process means fresh counters starting at zero
    reg_b = MetricsRegistry()
    rollup_mod.register_source("test", reg_b.counters)
    b = RollupCompactor(path, window_s=10.0, thread=False)
    for i in range(7):
        b.note_event(_serve_batch(clk.t, rows=8))
        reg_b.inc("rows_total", 8)
        clk.advance(0.5)
    b.close()

    doc = reconstruct(read_rollups(path))
    # 10 flushed + 7 after restart; the 5 crashed-window events are
    # lost, not doubled
    assert doc["events"]["serve_batch"] == 17
    assert doc["serve"]["default"]["rows"] == 17 * 8
    assert doc["counters"]["test"]["rows_total"] == (
        flushed_counter + reg_b.counters()["rows_total"])


def test_counter_reset_clamps_to_rate_semantics(tmp_path):
    """A source whose counter moves BACKWARD (replaced registry) is a
    reset: the delta is the new absolute value, never negative."""
    path = str(tmp_path / "j.rollup.jsonl")
    comp = RollupCompactor(path, window_s=10.0, thread=False)
    val = {"n": 100}
    rollup_mod.register_source("test", lambda: {"c": val["n"]})
    comp.note_event(_serve_batch(1000.0))
    comp.flush(1000.0)
    val["n"] = 30  # reset below the last poll
    comp.note_event(_serve_batch(1001.0))
    comp.flush(1001.0)
    comp.close()
    doc = reconstruct(read_rollups(path))
    assert doc["counters"]["test"]["c"] == 130  # 100 + 30, not 100 - 70


def test_shed_totals_come_from_counters_not_events(tmp_path):
    """Satellite drill: flood sheds past the journal's rate limit — the
    journal sees ONE shed event, the report total matches the monotonic
    counter exactly."""
    from shifu_tensorflow_tpu.serve.batcher import MicroBatcher, ShedLoad
    from shifu_tensorflow_tpu.serve.metrics import ServeMetrics

    base = str(tmp_path / "j.jsonl")
    jrn = journal_mod.install(Journal(base, plane="serve"))
    comp = RollupCompactor(rollup_path(base), window_s=10.0,
                           plane="serve", thread=False)
    jrn.set_tap(comp.note_event)
    jrn.on_close(comp.close)
    metrics = ServeMetrics()
    rollup_mod.register_source("serve", metrics.counters)

    import threading

    release = threading.Event()
    b = MicroBatcher(lambda x: (release.wait(10.0), x[:, :1])[1],
                     max_batch=8, max_delay_s=0.0, max_queue_rows=8,
                     metrics=metrics)
    rows = np.ones((8, 3), np.float32)
    # fillers: the pipeline absorbs ~3 batches (dispatch blocked in the
    # scorer), the 4th parks in the admission queue and pins it full —
    # every flood submit below then sheds.  Fillers retry their own
    # sheds: only a successfully parked submit pins the queue.
    def filler():
        while not release.is_set():
            try:
                b.submit(rows, timeout_s=30.0)
                return
            except ShedLoad:
                time.sleep(0.005)

    fillers = [threading.Thread(target=filler) for _ in range(4)]
    for t in fillers:
        t.start()
    # wait until the pipeline absorbed 3 batches AND one filler parked
    # in the admission queue (queued+inflight = 4 x 8 rows) — only then
    # does every flood submit shed deterministically
    deadline = time.monotonic() + 5.0
    while b.queued_rows() < 32 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert b.queued_rows() == 32, b.queued_rows()
    deadline = time.monotonic() + 10.0
    sheds = 0
    while sheds < 40 and time.monotonic() < deadline:
        try:
            b.submit(rows, timeout_s=0.01)
        except ShedLoad:
            sheds += 1
        except TimeoutError:
            pass  # absorbed before the fillers pinned the queue
    assert sheds >= 40, "flood never shed"
    # the journal's rate limiter would write one event per 5s window:
    # emit exactly one, the way ScoringServer.note_shed does
    jrn.emit("shed", plane="serve", rid="r1",
             shed_total=metrics.counters()["shed_total"])
    release.set()
    for t in fillers:
        t.join()
    b.close()
    jrn.close()
    journal_mod.uninstall()

    doc = reconstruct(read_rollups(base))
    live = metrics.counters()["shed_total"]
    assert live >= 40
    assert doc["events"].get("shed", 0) == 1  # rate-limited: undercounts
    assert doc["counters"]["serve"]["shed_total"] == live  # exact


# ---- excursion intervals ----

def test_excursion_intervals_fold_and_survive(tmp_path):
    path = str(tmp_path / "j.rollup.jsonl")
    comp = RollupCompactor(path, window_s=10.0, thread=False)
    comp.note_event({"ts": 1000.0, "event": "slo_breach",
                     "signal": "serve_p99_s", "value": 0.5})
    comp.note_event({"ts": 1025.0, "event": "slo_recover",
                     "signal": "serve_p99_s", "value": 0.01})
    comp.note_event({"ts": 1030.0, "event": "data_drift",
                     "model": "beta", "feature": 2})
    comp.close()
    doc = reconstruct(read_rollups(path))
    closed = [e for e in doc["excursions"] if e["end_ts"] is not None]
    assert len(closed) == 1
    assert closed[0]["kind"] == "slo" and closed[0]["name"] == "serve_p99_s"
    assert closed[0]["end_ts"] - closed[0]["start_ts"] == pytest.approx(25.0)
    assert [e["kind"] for e in doc["open_excursions"]] == ["drift"]
    assert doc["open_excursions"][0]["name"] == "beta/f2"


def test_open_excursions_matched_per_writer(tmp_path):
    """Worker A's recovery must not hide worker B's still-open
    excursion of the same signal: open/closed intervals match per
    writer, not fleet-wide."""
    base = str(tmp_path / "fleet.jsonl")
    a = RollupCompactor(rollup_path(base + ".s0"), window_s=10.0,
                        plane="serve", worker=0, thread=False)
    b = RollupCompactor(rollup_path(base + ".s1"), window_s=10.0,
                        plane="serve", worker=1, thread=False)
    a.note_event({"ts": 1000.0, "event": "slo_breach",
                  "signal": "serve_p99_s"})
    b.note_event({"ts": 1001.0, "event": "slo_breach",
                  "signal": "serve_p99_s"})
    a.note_event({"ts": 1030.0, "event": "slo_recover",
                  "signal": "serve_p99_s"})  # A recovers; B does not
    a.close()
    b.close()
    doc = reconstruct(read_rollups(base))
    closed = [e for e in doc["excursions"] if e["end_ts"] is not None]
    assert len(closed) == 1 and closed[0]["writer"] == "serve/w0"
    assert len(doc["open_excursions"]) == 1
    assert doc["open_excursions"][0]["writer"] == "serve/w1"


# ---- cost accountant ----

def test_cost_accountant_counters_and_render():
    acct = cost_mod.CostAccountant(plane="serve")
    acct.note_dispatch("alpha", dispatch_s=0.01, rows=10, bucket_rows=16,
                       nbytes=1200)
    acct.note_dispatch("alpha", dispatch_s=0.01, rows=6, bucket_rows=8,
                       nbytes=720)
    acct.note_dispatch("beta", dispatch_s=0.02, rows=4, bucket_rows=4,
                       nbytes=480)
    acct.note_busy(0.045)
    acct.note_train_epoch(1, dispatch_s=0.5, steps=64)
    c = acct.counters()
    assert c["device_seconds:alpha"] == pytest.approx(0.02)
    assert c["padded_row_seconds:alpha"] == pytest.approx(
        0.01 * 16 + 0.01 * 8)
    assert c["rows:alpha"] == 16
    assert c["bytes:beta"] == 480
    assert c["train_device_seconds:w1"] == pytest.approx(0.5)
    assert c["device_busy_seconds"] == pytest.approx(0.045)
    text = acct.render_prometheus()
    assert 'stpu_cost_device_seconds_total{model="alpha"} 0.02' in text
    assert 'stpu_cost_train_device_seconds_total{worker="1"} 0.5' in text
    assert "stpu_cost_device_busy_frac" in text
    util = acct.utilization()
    assert util is not None and 0.0 < util["busy_frac"] <= 1.0


def test_batcher_dispatch_feeds_cost_ledger():
    from shifu_tensorflow_tpu.serve.batcher import MicroBatcher

    acct = cost_mod.install(cost_mod.CostAccountant(plane="serve"))

    def score(x):
        # measurable dispatch time: sub-µs dispatches round to noise in
        # the 6-decimal counter export
        time.sleep(0.002)
        return x[:, :1]

    b = MicroBatcher(score, max_batch=16, max_delay_s=0.0,
                     model="alpha")
    rows = np.ones((6, 4), np.float32)
    for _ in range(5):
        b.submit(rows, timeout_s=5.0)
    b.close()
    c = acct.counters()
    assert c["rows:alpha"] == 30
    assert c["batches:alpha"] == 5
    assert c["device_seconds:alpha"] > 0
    # bucket ladder pads 6 -> 8: the DRR currency charges padded rows
    assert (c["padded_row_seconds:alpha"]
            >= c["device_seconds:alpha"] * 8 * 0.99)
    assert c["bytes:alpha"] == 30 * 4 * 4


def test_tenant_device_seconds_conserve_against_busy_wall():
    """Acceptance bound: per-tenant device-seconds sum to within 5% of
    the dispatch lane's measured busy wall when scoring dominates."""
    from shifu_tensorflow_tpu.serve.batcher import MicroBatcher
    from shifu_tensorflow_tpu.serve.tenancy.scheduler import DeviceScheduler

    acct = cost_mod.install(cost_mod.CostAccountant(plane="serve"))
    sched = DeviceScheduler()

    def slow_score(x):
        time.sleep(0.005)
        return x[:, :1]

    ba = MicroBatcher(slow_score, max_batch=8, max_delay_s=0.0,
                      scheduler=sched, model="alpha")
    bb = MicroBatcher(slow_score, max_batch=8, max_delay_s=0.0,
                      scheduler=sched, model="beta", weight=2.0)
    rows = np.ones((8, 3), np.float32)
    import threading

    def hammer(b, n):
        for _ in range(n):
            b.submit(rows, timeout_s=30.0)

    threads = [threading.Thread(target=hammer, args=(b, 20))
               for b in (ba, bb)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # the scheduler's own ledger (read before close unregisters the
    # tenant queues)
    totals = sched.dispatch_totals()
    assert totals["alpha"]["device_s"] > 0
    assert totals["beta"]["device_s"] > 0
    ba.close()
    bb.close()
    state = acct.state()
    tenant_sum = sum(t["device_s"] for t in state["tenants"].values())
    busy = state["utilization"]["busy_s"]
    assert busy > 0
    assert tenant_sum <= busy * 1.0001
    assert tenant_sum >= busy * 0.95, (tenant_sum, busy)
    sched.close()


def test_trainer_epoch_attributes_device_seconds(tmp_path):
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.data.dataset import (
        InMemoryDataset,
        ParsedBlock,
    )
    from shifu_tensorflow_tpu.data.reader import RecordSchema
    from shifu_tensorflow_tpu.train import make_trainer

    acct = cost_mod.install(cost_mod.CostAccountant(plane="train"))
    tracer = trace_mod.install(trace_mod.Tracer(worker_index=0))
    # _obs_epoch runs only with a journal or watchdog installed
    journal_mod.install(Journal(str(tmp_path / "t.jsonl"),
                                plane="train"))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = (x[:, :1] > 0).astype(np.float32)
    block = ParsedBlock(features=x, targets=y,
                        weights=np.ones((64, 1), np.float32))
    dataset = InMemoryDataset(
        train=block, valid=ParsedBlock.empty(4),
        schema=RecordSchema(feature_columns=(1, 2, 3, 4),
                            target_column=0))
    mc = ModelConfig.from_json({"train": {"numTrainEpochs": 2, "params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.05}}})
    trainer = make_trainer(mc, 4, feature_columns=(1, 2, 3, 4))
    trainer.tracer = tracer
    trainer.fit(dataset, batch_size=16)
    c = acct.counters()
    assert c.get("train_device_seconds:w0", 0) > 0
    assert c.get("train_steps:w0", 0) >= 4


# ---- digests ----

def test_digest_snapshots_and_merge():
    wd = slo_mod.SloWatchdog(window_s=60.0, plane="serve")
    wd.track("serve_p99_s", stat="p99", target=0.0, unit="s")
    for i in range(200):
        wd.observe("serve_p99_s", 0.01 + (i % 10) * 0.001)
    snaps = wd.digest_snapshots()
    assert "serve_p99_s" in snaps
    s = snaps["serve_p99_s"]
    assert s["count"] == 200 and s["stat"] == "p99"
    merged = merge_digest_snapshots([s, s])
    assert merged["count"] == 400
    assert merged["mean"] == pytest.approx(s["mean"], rel=1e-6)
    assert merged["p99"] == pytest.approx(s["p99"], rel=1e-6)
    assert merged["stat"] == "p99"


def test_flush_records_digest_snapshots(tmp_path):
    wd = slo_mod.install(slo_mod.SloWatchdog(window_s=60.0,
                                             plane="serve"))
    wd.track("serve_p99_s", stat="p99")
    for _ in range(50):
        wd.observe("serve_p99_s", 0.02)
    path = str(tmp_path / "j.rollup.jsonl")
    comp = RollupCompactor(path, window_s=10.0, thread=False)
    comp.note_event(_serve_batch(1000.0))
    comp.close()
    doc = reconstruct(read_rollups(path))
    assert doc["digests"]["serve_p99_s"]["count"] == 50
    assert doc["digests"]["serve_p99_s"]["p99"] == pytest.approx(
        0.02, rel=0.05)


def test_digest_conservation_survives_expired_window(tmp_path):
    """Observations whose sliding SLO window expired BEFORE the flush
    still land in the sidecar (values unknown, count/sum exact) — the
    conservation property must not depend on flush timing."""
    wd = slo_mod.install(slo_mod.SloWatchdog(window_s=0.3, buckets=2,
                                             plane="serve"))
    wd.track("serve_p99_s", stat="p99")
    for _ in range(50):
        wd.observe("serve_p99_s", 0.02)
    time.sleep(0.4)  # the window drains; the lifetime totals do not
    assert wd.digest_snapshots() == {}
    path = str(tmp_path / "j.rollup.jsonl")
    comp = RollupCompactor(path, window_s=10.0, thread=False)
    comp.note_event(_serve_batch(1000.0))
    comp.close()
    doc = reconstruct(read_rollups(path))
    d = doc["digests"]["serve_p99_s"]
    assert d["count"] == 50
    assert d["mean"] == pytest.approx(0.02, rel=1e-6)


# ---- regression watchdog ----

def _baseline_doc(p99=0.01, count=1000):
    return {"digests": {"serve_p99_s": {
        "count": count, "sum": p99 * count * 0.9, "max": p99 * 2,
        "mean": p99 * 0.9, "p99": p99, "stat": "p99"}}}


def test_regression_watchdog_fires_names_metric_and_clears(tmp_path):
    base = str(tmp_path / "j.jsonl")
    journal_mod.install(Journal(base, plane="serve"))
    wd = slo_mod.install(slo_mod.SloWatchdog(window_s=0.5, buckets=2,
                                             plane="serve"))
    wd.track("serve_p99_s", stat="p99")
    rw = RegressionWatchdog(_baseline_doc(p99=0.01), threshold=1.5,
                            hysteresis=2, plane="serve")
    # slowdown: 5x the baseline p99, enough samples to clear the noise
    # discount
    for _ in range(100):
        wd.observe("serve_p99_s", 0.05)
    assert rw.evaluate() == []          # hysteresis tick 1
    events = rw.evaluate()              # tick 2: fires
    assert [e["event"] for e in events] == ["perf_regression"]
    ev = events[0]
    assert ev["metric"] == "serve_p99_s" and ev["stat"] == "p99"
    assert ev["ratio"] > 3.0 and ev["baseline"] == pytest.approx(0.01)
    # recovery: the slow window ages out, fast samples replace it
    time.sleep(0.6)
    for _ in range(100):
        wd.observe("serve_p99_s", 0.01)
    assert rw.evaluate() == []          # clean tick 1
    events = rw.evaluate()              # tick 2: clears
    assert [e["event"] for e in events] == ["perf_regression_clear"]
    assert events[0]["regression_s"] > 0
    journal_mod.active().close()
    evs = read_events(base)
    kinds = [e["event"] for e in evs]
    assert kinds.count("perf_regression") == 1
    assert kinds.count("perf_regression_clear") == 1


def test_regression_watchdog_control_arm_quiet():
    wd = slo_mod.install(slo_mod.SloWatchdog(window_s=60.0,
                                             plane="serve"))
    wd.track("serve_p99_s", stat="p99")
    rw = RegressionWatchdog(_baseline_doc(p99=0.01), threshold=1.5,
                            hysteresis=1, plane="serve")
    for _ in range(200):
        wd.observe("serve_p99_s", 0.0101)  # ~the baseline
    for _ in range(5):
        assert rw.evaluate() == []
    assert rw.state().get("serve_p99_s", {}).get("breached") is not True


def test_regression_watchdog_small_sample_discounted():
    """A handful of slow samples is not a regression: the k/√n discount
    (and the min-count floor) keeps tiny windows quiet."""
    wd = slo_mod.install(slo_mod.SloWatchdog(window_s=60.0,
                                             plane="serve"))
    wd.track("serve_p99_s", stat="p99")
    rw = RegressionWatchdog(_baseline_doc(p99=0.01), threshold=1.5,
                            hysteresis=1, plane="serve")
    for _ in range(5):
        wd.observe("serve_p99_s", 0.05)
    assert rw.evaluate() == []


def test_install_obs_wires_rollup_cost_and_regression(tmp_path):
    from shifu_tensorflow_tpu.obs import install_obs
    from shifu_tensorflow_tpu.obs.config import ObsConfig

    base = str(tmp_path / "wired.jsonl")
    # a pinned baseline sidecar with digests
    bl_path = str(tmp_path / "baseline.rollup.jsonl")
    with open(bl_path, "w") as f:
        f.write(json.dumps({
            "schema": rollup_mod.ROLLUP_SCHEMA, "t0": 0.0, "t1": 60.0,
            "digests": _baseline_doc()["digests"],
        }) + "\n")
    cfg = ObsConfig(enabled=True, journal_path=base,
                    rollup_window_s=5.0, baseline_path=bl_path,
                    slo_regression=2.0)
    tracer, jrn = install_obs(cfg, plane="serve")
    try:
        assert rollup_mod.active() is not None
        assert cost_mod.active() is not None
        assert rollup_mod.regression_active() is not None
        assert rollup_mod.regression_active().threshold == 2.0
        jrn.emit("serve_batch", plane="serve", rows=4, requests=1,
                 bucket=4, dispatch_s=0.001, queue_delay_s=0.0)
        jrn.close()  # close hook flushes the compactor
        doc = reconstruct(read_rollups(base))
        assert doc["events"]["serve_batch"] == 1
    finally:
        install_obs(ObsConfig(), plane="serve")
    assert rollup_mod.active() is None
    assert rollup_mod.regression_active() is None


# ---- CLI: report / diff ----

def _make_run(tmp_path, name: str, p99: float, rows_per_evt: int = 8,
              n: int = 40) -> str:
    """One synthetic run: a journal + compactor + slo digests + cost
    counters, flushed to its sidecar set."""
    base = str(tmp_path / f"{name}.jsonl")
    wd = slo_mod.install(slo_mod.SloWatchdog(window_s=600.0,
                                             plane="serve"))
    wd.track("serve_p99_s", stat="p99")
    acct = cost_mod.CostAccountant(plane="serve")
    rollup_mod.register_source("cost", acct.counters)
    reg = MetricsRegistry()
    rollup_mod.register_source("serve", reg.counters)
    comp = RollupCompactor(rollup_path(base), window_s=10.0,
                           plane="serve", worker=None, job=name,
                           thread=False)
    t = 1000.0
    for i in range(n):
        comp.note_event(_serve_batch(t + i * 0.5, rows=rows_per_evt,
                                     model="alpha"))
        wd.observe("serve_p99_s", p99)
        acct.note_dispatch("alpha", dispatch_s=p99, rows=rows_per_evt,
                           bucket_rows=rows_per_evt, nbytes=rows_per_evt * 12)
        acct.note_busy(p99 * 1.01)
        reg.inc("requests_total")
        reg.inc("rows_total", rows_per_evt)
    comp.close()
    slo_mod.uninstall()
    rollup_mod.unregister_source("cost")
    rollup_mod.unregister_source("serve")
    return base


def test_obs_report_renders_from_rollups_alone(tmp_path, capsys):
    base = _make_run(tmp_path, "runA", p99=0.01)
    # no journal file exists at all — the report reads sidecars only
    assert not (tmp_path / "runA.jsonl").exists()
    rc = obs_main(["report", "--journal", base])
    out = capsys.readouterr().out
    assert rc == 0
    assert "rollup report" in out
    assert "per-tenant cost" in out
    assert "alpha" in out
    assert "device lane" in out
    assert "totals (monotonic counters)" in out
    assert "requests 40" in out


def test_obs_report_json_schema_and_totals(tmp_path, capsys):
    base = _make_run(tmp_path, "runJ", p99=0.01)
    rc = obs_main(["report", "--journal", base, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["schema"] == "stpu.obs.report/1"
    assert doc["counters"]["serve"]["requests_total"] == 40
    assert doc["counters"]["serve"]["rows_total"] == 320
    assert doc["counters"]["cost"]["rows:alpha"] == 320
    assert doc["digests"]["serve_p99_s"]["count"] == 40


def test_obs_report_missing_rollups_rc1(tmp_path, capsys):
    rc = obs_main(["report", "--journal", str(tmp_path / "nope.jsonl")])
    assert rc == 1
    assert "no rollup records" in capsys.readouterr().err


def test_obs_diff_flags_regression(tmp_path, capsys):
    a = _make_run(tmp_path, "fast", p99=0.01, n=200)
    b = _make_run(tmp_path, "slow", p99=0.05, n=200)
    rc = obs_main(["diff", a, b, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["schema"] == "stpu.obs.diff/1"
    by_metric = {r["metric"]: r for r in doc["metrics"]}
    assert by_metric["serve_p99_s.p99"]["verdict"] == "REGRESSED"
    assert "serve_p99_s.p99" in doc["regressions"]
    assert by_metric["device_s_per_krow"]["verdict"] == "REGRESSED"
    # human renderer names the regression too
    rc = obs_main(["diff", a, b])
    out = capsys.readouterr().out
    assert rc == 0 and "REGRESSED" in out


def test_obs_diff_same_run_is_quiet(tmp_path, capsys):
    a = _make_run(tmp_path, "same1", p99=0.01, n=200)
    b = _make_run(tmp_path, "same2", p99=0.01, n=200)
    rc = obs_main(["diff", a, b, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["regressions"] == []


def test_obs_summary_json_schema_pinned(tmp_path, capsys):
    base = str(tmp_path / "s.jsonl")
    jrn = Journal(base, plane="train")
    jrn.emit("worker_start", plane="train", worker=0)
    jrn.close()
    rc = obs_main(["summary", "--journal", base, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["schema"] == "stpu.obs.summary/1"


# ---- bench history ----

def _bench_entry(name, ts, value):
    return {"ts": ts, "name": name, "rc": 0,
            "artifact": f"BENCH_{name.upper()}.json",
            "host": {"hostname": "h", "cpus": 2},
            "metrics": {"value": value, "threshold_pct": 2.0}}


def test_obs_diff_bench_renders_last_two_entries(tmp_path, capsys):
    hist = str(tmp_path / "BENCH_HISTORY.jsonl")
    with open(hist, "w") as f:
        for e in (_bench_entry("obs", 1.0, 1.2),
                  _bench_entry("serve", 2.0, 9.0),
                  _bench_entry("obs", 3.0, 1.5)):
            f.write(json.dumps(e) + "\n")
    rc = obs_main(["diff", "--bench", "--history", hist, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["schema"] == "stpu.obs.diff/1"
    assert doc["mode"] == "bench" and doc["name"] == "obs"
    row = {r["metric"]: r for r in doc["metrics"]}["value"]
    assert row["a"] == 1.2 and row["b"] == 1.5
    assert row["delta_pct"] == pytest.approx(25.0)
    # human render
    rc = obs_main(["diff", "--bench", "--history", hist])
    out = capsys.readouterr().out
    assert rc == 0 and "bench diff — obs" in out


def test_obs_diff_bench_needs_two_entries(tmp_path, capsys):
    hist = str(tmp_path / "h.jsonl")
    with open(hist, "w") as f:
        f.write(json.dumps(_bench_entry("obs", 1.0, 1.2)) + "\n")
    rc = obs_main(["diff", "--bench", "--history", hist])
    assert rc == 1
    assert "two" in capsys.readouterr().err


def test_bench_history_append_helper(tmp_path, monkeypatch):
    """bench.py's history hook: one JSONL line with host fingerprint,
    scalar metrics from the artifact, and the caller-supplied ts."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    monkeypatch.setattr(
        bench.os.path, "abspath",
        lambda p: str(tmp_path / "bench.py") if p.endswith("bench.py")
        else os.path.abspath(p))
    with open(tmp_path / "BENCH_X.json", "w") as f:
        json.dump({"value": 3.5, "acceptance_ok": True,
                   "unit": "x", "nested": {"a": 1}}, f)
    monkeypatch.setenv("BENCH_TS", "2026-08-04T00:00:00")
    bench._append_bench_history("x", "BENCH_X.json", rc=0)
    lines = (tmp_path / "BENCH_HISTORY.jsonl").read_text().splitlines()
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["name"] == "x" and rec["ts"] == "2026-08-04T00:00:00"
    assert rec["metrics"] == {"value": 3.5}  # scalars only, bools out
    assert rec["host"]["cpus"] == os.cpu_count()


# ---- sidecar discovery ----

def test_rollup_files_discovers_fleet_siblings(tmp_path):
    base = str(tmp_path / "fleet.jsonl")
    for suffix in ("", ".w0", ".w1", ".s0"):
        comp = RollupCompactor(rollup_path(base + suffix),
                               window_s=10.0, thread=False)
        comp.note_event(_serve_batch(1000.0))
        comp.close()
    files = rollup_files(base)
    assert len(files) == 4
    doc = reconstruct(read_rollups(base))
    assert doc["events"]["serve_batch"] == 4
    # journal readers must NOT pick sidecars up as journal files
    assert not journal_mod.journal_files(base)
