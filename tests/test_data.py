"""Data-layer tests: splitter parity + size-aware upgrade, PSV parsing,
deterministic split, fixed-shape batching, streaming (SURVEY.md §7.1 step 2)."""

import gzip

import numpy as np
import pytest

from shifu_tensorflow_tpu.data import splitter
from shifu_tensorflow_tpu.data.dataset import (
    InMemoryDataset,
    ShardStream,
    iter_batches,
    pad_to_batch,
    prefetch_to_device,
)
from shifu_tensorflow_tpu.data.reader import (
    ParsedBlock,
    RecordSchema,
    parse_block,
    split_train_valid,
)


def _schema(ds):
    return RecordSchema(
        feature_columns=tuple(ds["feature_cols"]),
        target_column=ds["target_col"],
        weight_column=ds["weight_col"],
    )


# ---- splitter ----

def test_list_data_files_skips_hidden(tmp_path):
    (tmp_path / "part-0").write_text("a\n")
    (tmp_path / "_SUCCESS").write_text("")
    (tmp_path / ".hidden").write_text("")
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "part-1").write_text("b\n")
    files = splitter.list_data_files(str(tmp_path))
    assert [f.rsplit("/", 1)[-1] for f in files] == ["part-0", "part-1"]


def test_round_robin_parity(tmp_path):
    paths = []
    for i in range(5):
        p = tmp_path / f"f{i}"
        p.write_text("x\n" * (i + 1))
        paths.append(str(p))
    shards = splitter.split_round_robin(paths, 2)
    # reference round-robins by listing order (TrainingDataSet.java:66-82)
    assert list(shards[0].paths) == [paths[0], paths[2], paths[4]]
    assert list(shards[1].paths) == [paths[1], paths[3]]
    assert shards[0].joined() == ",".join([paths[0], paths[2], paths[4]])


def test_not_enough_files_raises(tmp_path):
    p = tmp_path / "only"
    p.write_text("x\n")
    with pytest.raises(splitter.NotEnoughFilesError):
        splitter.split_round_robin([str(p)], 2)


def test_size_aware_balances(tmp_path):
    sizes = [100, 1, 1, 1, 99, 2]
    paths = []
    for i, s in enumerate(sizes):
        p = tmp_path / f"f{i}"
        p.write_bytes(b"x" * s)
        paths.append(str(p))
    shards = splitter.split_size_aware(paths, 2)
    loads = sorted(s.total_bytes for s in shards)
    assert loads == [102, 102]  # LPT balances perfectly here
    # every file assigned exactly once
    assigned = sorted(p for s in shards for p in s.paths)
    assert assigned == sorted(paths)


def test_total_line_count_gz_and_plain(tmp_path):
    plain = tmp_path / "a.txt"
    plain.write_text("1\n2\n3\n")
    gz = tmp_path / "b.gz"
    with gzip.open(gz, "wt") as f:
        f.write("1\n2\n")
    assert splitter.total_line_count([str(plain), str(gz)]) == 5


# ---- reader ----

def test_parse_block_basic():
    schema = RecordSchema(feature_columns=(1, 2), target_column=0, weight_column=3)
    lines = [b"1|0.5|-0.25|2.0\n", b"0|1.5|0.75|-3.0\n"]
    blk = parse_block(lines, schema)
    assert blk.features.shape == (2, 2)
    np.testing.assert_allclose(blk.features, [[0.5, -0.25], [1.5, 0.75]])
    np.testing.assert_allclose(blk.targets[:, 0], [1.0, 0.0])
    # negative weight clamped to 1.0 (ssgd_monitor.py:412-415)
    np.testing.assert_allclose(blk.weights[:, 0], [2.0, 1.0])


def test_parse_block_drops_bad_rows():
    schema = RecordSchema(feature_columns=(1,), target_column=0)
    lines = [b"1|2.0\n", b"1|abc\n", b"1\n", b"0|3.0\n"]
    blk = parse_block(lines, schema)
    assert len(blk) == 2
    np.testing.assert_allclose(blk.features[:, 0], [2.0, 3.0])
    np.testing.assert_allclose(blk.weights[:, 0], [1.0, 1.0])  # no weight col


def test_parse_block_zscale():
    schema = RecordSchema(feature_columns=(1, 2), target_column=0).with_zscale(
        [1.0, 2.0], [2.0, 0.0]  # zero std guarded to 1.0
    )
    blk = parse_block([b"1|3.0|5.0\n"], schema)
    np.testing.assert_allclose(blk.features, [[1.0, 3.0]])


def test_split_train_valid_deterministic():
    lines = [f"{i}|{i*0.1}\n".encode() for i in range(1000)]
    tr1, va1 = split_train_valid(lines, 0.2)
    tr2, va2 = split_train_valid(lines, 0.2)
    assert tr1 == tr2 and va1 == va2
    assert len(va1) + len(tr1) == 1000
    assert 120 < len(va1) < 280  # ~20%
    # different salt → different membership
    _, va3 = split_train_valid(lines, 0.2, salt=7)
    assert va3 != va1
    # zero rate → everything trains
    tr4, va4 = split_train_valid(lines, 0.0)
    assert len(tr4) == 1000 and va4 == []


# ---- batching ----

def test_pad_to_batch_weights_zero():
    blk = ParsedBlock(
        np.ones((5, 3), np.float32), np.ones((5, 1), np.float32),
        np.ones((5, 1), np.float32),
    )
    padded = pad_to_batch(blk, 4)
    assert len(padded) == 8
    assert padded.weights[5:].sum() == 0.0  # padded rows can't affect loss


def test_iter_batches_fixed_shape():
    blk = ParsedBlock(
        np.arange(30, dtype=np.float32).reshape(10, 3),
        np.zeros((10, 1), np.float32), np.ones((10, 1), np.float32),
    )
    batches = list(iter_batches(blk, 4))
    assert len(batches) == 3
    assert all(b["x"].shape == (4, 3) for b in batches)
    # shuffle is deterministic per epoch seed
    a = [b["x"] for b in iter_batches(blk, 4, shuffle=True, seed=1)]
    b = [b["x"] for b in iter_batches(blk, 4, shuffle=True, seed=1)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


# ---- datasets ----

def test_in_memory_dataset(psv_dataset):
    schema = _schema(psv_dataset)
    ds = InMemoryDataset.load(psv_dataset["paths"], schema, valid_rate=0.2)
    total = len(ds.train) + len(ds.valid)
    assert total == psv_dataset["n_rows"]
    assert 0.1 < len(ds.valid) / total < 0.3
    assert ds.train.features.shape[1] == psv_dataset["n_features"]
    # all batches fixed-shape
    shapes = {b["x"].shape for b in ds.train_batches(32)}
    assert shapes == {(32, psv_dataset["n_features"])}


def test_shard_stream_matches_in_memory(psv_dataset):
    schema = _schema(psv_dataset)
    ds = InMemoryDataset.load(psv_dataset["paths"], schema, valid_rate=0.2)
    stream = ShardStream(
        psv_dataset["paths"], schema, batch_size=32, valid_rate=0.2,
        block_bytes=1024,
    )
    rows = sum(int(b["w"].sum() > 0) * int((b["w"] > 0).sum()) for b in stream)
    assert rows == len(ds.train)  # same rows stream as load (weights>0 = real)


def test_shard_stream_propagates_errors(tmp_path):
    schema = RecordSchema(feature_columns=(1,), target_column=0)
    with pytest.raises(FileNotFoundError):
        list(ShardStream([str(tmp_path / "missing")], schema, batch_size=4))


def test_prefetch_to_device_order():
    batches = [{"x": np.full((2, 2), i)} for i in range(5)]
    out = list(prefetch_to_device(iter(batches), put=lambda b: b, depth=2))
    assert [int(b["x"][0, 0]) for b in out] == [0, 1, 2, 3, 4]


def test_size_aware_zero_size_files(tmp_path):
    # zero-byte part files must still spread across workers (review finding)
    paths = []
    for i in range(4):
        p = tmp_path / f"z{i}"
        p.write_bytes(b"")
        paths.append(str(p))
    shards = splitter.split_size_aware(paths, 2)
    assert [len(s.paths) for s in shards] == [2, 2]


def test_shard_stream_abandoned_consumer_unblocks(psv_dataset):
    import time

    schema = _schema(psv_dataset)
    stream = ShardStream(psv_dataset["paths"], schema, batch_size=8,
                         queue_depth=2, block_bytes=256)
    it = iter(stream)
    next(it)  # start the producer, then abandon the iterator
    it.close()
    deadline = time.time() + 5.0
    import threading

    while time.time() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.daemon and t.is_alive() and "Thread-" in t.name]
        if not alive:
            break
        time.sleep(0.1)
    # producer must not be stuck on a full queue
    assert time.time() < deadline


def _stream_row_multiset(stream, n_features):
    """Sorted real rows (weight>0) across all batches — order-insensitive."""
    rows = []
    for b in stream:
        mask = b["w"][:, 0] > 0
        rows.append(np.concatenate(
            [b["x"][mask], b["y"][mask], b["w"][mask]], axis=1))
    allr = np.concatenate(rows, axis=0) if rows else np.empty((0, n_features + 2))
    return allr[np.lexsort(allr.T[::-1])]


@pytest.mark.parametrize("n_readers", [1, 3, 4])
def test_shard_stream_parallel_readers_same_rows(psv_dataset, n_readers):
    """Reader-count must not change WHICH rows stream (membership is per-row
    content hashing), only arrival order."""
    schema = _schema(psv_dataset)
    nf = psv_dataset["n_features"]
    base = _stream_row_multiset(
        ShardStream(psv_dataset["paths"], schema, batch_size=32,
                    valid_rate=0.2, n_readers=1), nf)
    got = _stream_row_multiset(
        ShardStream(psv_dataset["paths"], schema, batch_size=32,
                    valid_rate=0.2, n_readers=n_readers, block_bytes=512), nf)
    np.testing.assert_array_equal(got, base)


def test_shard_stream_parallel_fixed_batch_shapes(psv_dataset):
    schema = _schema(psv_dataset)
    shapes = {
        b["x"].shape
        for b in ShardStream(psv_dataset["paths"], schema, batch_size=32,
                             n_readers=4)
    }
    assert shapes == {(32, psv_dataset["n_features"])}


def test_shard_stream_parallel_error_propagates(psv_dataset, tmp_path):
    schema = _schema(psv_dataset)
    paths = list(psv_dataset["paths"]) + [str(tmp_path / "nope")]
    with pytest.raises(FileNotFoundError):
        list(ShardStream(paths, schema, batch_size=16, n_readers=4))


def test_shard_stream_drop_remainder(psv_dataset):
    schema = _schema(psv_dataset)
    n = psv_dataset["n_rows"]
    batches = list(ShardStream(psv_dataset["paths"], schema, batch_size=32,
                               drop_remainder=True, n_readers=2))
    # every batch full and entirely real rows may not hold at file tails
    # (tails are dropped), so just check: full shape, count <= n//32
    assert all(b["x"].shape == (32, psv_dataset["n_features"]) for b in batches)
    assert len(batches) <= n // 32
