"""Closed-loop lifecycle units: the policy state machine under a frozen
clock, the declarative ctl plane, the journal-fold signals, bundle
publication ordering, and the generation-lineage manifest stamp."""

from __future__ import annotations

import json
import os
import threading

import numpy as np
import pytest

from shifu_tensorflow_tpu.lifecycle import ctl as ctl_mod
from shifu_tensorflow_tpu.lifecycle.config import (
    LifecycleConfig,
    parse_ramp_steps,
    resolve_lifecycle_config,
)
from shifu_tensorflow_tpu.lifecycle.policy import (
    IDLE,
    RAMP,
    RETRAINING,
    SHADOW,
    LifecycleObservation,
    LifecyclePolicy,
)


class FrozenClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _cfg(**kw) -> LifecycleConfig:
    base = dict(
        model="beta", models_dir="/tmp/models", journal_base="/tmp/j",
        poll_s=1.0, trigger_hysteresis=3, cooldown_s=300.0,
        shadow_min_rows=100, divergence_threshold=1.0,
        ramp_steps=(0.05, 0.25, 0.5), ramp_interval_s=30.0,
        rollback_hysteresis=2, retrain_timeout_s=600.0,
    )
    base.update(kw)
    return LifecycleConfig(**base)


def _drift(n=1) -> LifecycleObservation:
    return LifecycleObservation(
        new_events=n, drift_open=True,
        drift_signals=["data_drift:beta:f3"])


def _clean(rows=0, div=None, n=1) -> LifecycleObservation:
    return LifecycleObservation(new_events=n, shadow_rows=rows,
                                divergence=div)


def _bad(**kw) -> LifecycleObservation:
    base = dict(new_events=1, slo_breached=True,
                slo_signals=["serve_p99_s:beta"])
    base.update(kw)
    return LifecycleObservation(**base)


# ------------------------------------------------------- policy: trigger


def test_policy_trigger_debounce_requires_consecutive_drifted_polls():
    clk = FrozenClock()
    p = LifecyclePolicy(_cfg(), clock=clk)
    assert p.observe(_drift()) is None
    assert p.observe(_drift()) is None
    # a clean poll in between resets the debounce entirely
    assert p.observe(_clean()) is None
    assert p.observe(_drift()) is None
    assert p.observe(_drift()) is None
    act = p.observe(_drift())
    assert act is not None and act.action == "retrain"
    assert "data_drift:beta:f3" in act.evidence["signals"]
    assert p.state == RETRAINING


def test_policy_latched_drift_on_quiet_fleet_is_not_evidence():
    """Drift latched but ZERO new events = a dead fleet's stale latch,
    not live drift — the debounce must not accrue."""
    clk = FrozenClock()
    p = LifecyclePolicy(_cfg(), clock=clk)
    for _ in range(10):
        assert p.observe(_drift(n=0)) is None
    assert p.state == IDLE


def test_policy_read_error_is_fully_neutral():
    clk = FrozenClock()
    p = LifecyclePolicy(_cfg(), clock=clk)
    p.observe(_drift())
    p.observe(_drift())
    # unreadable journal: no reset, no accrual
    assert p.observe(LifecycleObservation(read_error=True)) is None
    act = p.observe(_drift())
    assert act is not None and act.action == "retrain"


def test_policy_cooldown_blocks_retrigger_and_rollback_restarts_it():
    clk = FrozenClock()
    p = LifecyclePolicy(_cfg(), clock=clk)
    for _ in range(3):
        p.observe(_drift())
    assert p.state == RETRAINING
    # poisoned retrain: verdict is a rollback, cooldown restarts in full
    act = p.on_retrain_result(False, reason="rc 3")
    assert act is not None and act.action == "rollback"
    assert p.state == IDLE
    # the same drift is still latched and live: inside cooldown, no
    # retrain storm at poll cadence
    clk.advance(200.0)
    for _ in range(10):
        assert p.observe(_drift()) is None
    clk.advance(150.0)  # past the 300 s cooldown (restarted at verdict)
    # the debounce has long been satisfied by the latched live drift:
    # the first out-of-cooldown tick retriggers
    act = p.observe(_drift())
    assert act is not None and act.action == "retrain"


# ------------------------------------------- policy: shadow, ramp, promote


def _to_shadow(clk, cfg=None) -> LifecyclePolicy:
    p = LifecyclePolicy(cfg or _cfg(), clock=clk)
    for _ in range(3):
        p.observe(_drift())
    act = p.on_retrain_result(True)
    assert act.action == "shadow_admit"
    assert p.state == SHADOW
    p.on_action_applied(act, True)
    return p


def test_policy_shadow_gates_rows_and_divergence_then_ramps():
    clk = FrozenClock()
    p = _to_shadow(clk)
    # not enough mirrored rows yet
    assert p.observe(_clean(rows=50, div=0.1)) is None
    # rows ok but divergence not yet computable: hold
    assert p.observe(_clean(rows=200, div=None)) is None
    act = p.observe(_clean(rows=200, div=0.1))
    assert act is not None and act.action == "ramp_step"
    assert act.fraction == 0.05
    p.on_action_applied(act, True)
    assert p.state == RAMP and p.fraction == 0.05


def test_policy_ramp_schedule_walks_steps_then_promotes():
    clk = FrozenClock()
    p = _to_shadow(clk)
    act = p.observe(_clean(rows=200, div=0.1))
    p.on_action_applied(act, True)
    fractions = [0.05]
    for _ in range(8):
        # clean ticks inside the interval: hold
        assert p.observe(_clean(rows=400, div=0.1)) is None
        clk.advance(30.0)
        act = p.observe(_clean(rows=400, div=0.1))
        assert act is not None
        if act.action == "promote":
            break
        assert act.action == "ramp_step"
        fractions.append(act.fraction)
        p.on_action_applied(act, True)
    assert fractions == [0.05, 0.25, 0.5]
    assert act.action == "promote"
    p.on_action_applied(act, True)
    assert p.state == IDLE


def test_policy_quiet_tick_does_not_advance_ramp():
    """A dead fleet's silence must never walk a candidate to 100%."""
    clk = FrozenClock()
    p = _to_shadow(clk)
    act = p.observe(_clean(rows=200, div=0.1))
    p.on_action_applied(act, True)
    clk.advance(3600.0)  # interval long since elapsed...
    # ...but the fleet is quiet: no events, no advancement
    for _ in range(5):
        assert p.observe(_clean(rows=400, div=0.1, n=0)) is None
    act = p.observe(_clean(rows=400, div=0.1, n=1))
    assert act is not None and act.action == "ramp_step"


def test_policy_rollback_hysteresis_on_slo_breach():
    clk = FrozenClock()
    p = _to_shadow(clk)
    act = p.observe(_clean(rows=200, div=0.1))
    p.on_action_applied(act, True)
    # one bad tick: held (hysteresis 2)
    assert p.observe(_bad()) is None
    # a clean LIVE tick resets the accrual
    assert p.observe(_clean(rows=300, div=0.1)) is None
    assert p.observe(_bad()) is None
    act = p.observe(_bad())
    assert act is not None and act.action == "rollback"
    assert "slo" in act.reason
    assert p.state == IDLE


def test_policy_rollback_on_score_divergence():
    clk = FrozenClock()
    p = _to_shadow(clk)
    for obs in (_clean(rows=200, div=2.5), _clean(rows=220, div=2.5)):
        act = p.observe(obs)
    assert act is not None and act.action == "rollback"
    assert "divergence" in act.reason


def test_policy_quiet_tick_does_not_accrue_bad_ticks():
    clk = FrozenClock()
    p = _to_shadow(clk)
    assert p.observe(_bad()) is None
    # stale breach latch + quiet fleet: neutral, not rollback evidence
    for _ in range(5):
        assert p.observe(_bad(new_events=0)) is None
    assert p.state == SHADOW


def test_policy_failed_candidate_actuation_is_a_rollback():
    clk = FrozenClock()
    p = LifecyclePolicy(_cfg(), clock=clk)
    for _ in range(3):
        p.observe(_drift())
    act = p.on_retrain_result(True)
    follow = p.on_action_applied(act, False, reason="publish failed")
    assert follow is not None and follow.action == "rollback"
    assert p.state == IDLE
    # and the rollback's own actuation failing keeps the policy IDLE
    assert p.on_action_applied(follow, False, reason="ctl write") is None
    assert p.state == IDLE


def test_policy_retrain_result_outside_retraining_is_ignored():
    p = LifecyclePolicy(_cfg(), clock=FrozenClock())
    assert p.on_retrain_result(True) is None
    assert p.state == IDLE


# ------------------------------------------------------------ ctl plane


def test_ctl_round_trip_and_seq_monotonic(tmp_path):
    d = str(tmp_path)
    assert ctl_mod.read_ctl(d) is None
    ctl_mod.write_ctl(d, model="beta", shadow="beta.next", mirror=True,
                      route_fraction=0.0, weights={"beta.next": 0.05})
    doc = ctl_mod.read_ctl(d)
    assert doc["seq"] == 1 and doc["shadow"] == "beta.next"
    assert doc["mirror"] is True and doc["weights"] == {"beta.next": 0.05}
    ctl_mod.write_ctl(d, model="beta", shadow=None, mirror=False,
                      route_fraction=0.0, retire=["beta.next"])
    doc = ctl_mod.read_ctl(d)
    assert doc["seq"] == 2 and doc["shadow"] is None
    assert doc["retire"] == ["beta.next"]


def test_ctl_torn_file_reads_as_none(tmp_path):
    d = str(tmp_path)
    ctl_mod.write_ctl(d, model="beta", shadow=None, mirror=False,
                      route_fraction=0.0)
    path = ctl_mod.ctl_path(d)
    with open(path, "w") as f:
        f.write('{"seq": 3, "model": "be')  # torn mid-write
    assert ctl_mod.read_ctl(d) is None


def test_route_to_shadow_deterministic_and_proportional():
    rids = [f"req-{i}" for i in range(4000)]
    hits = [ctl_mod.route_to_shadow(r, 0.25) for r in rids]
    # deterministic: same rid, same verdict, every time
    assert hits == [ctl_mod.route_to_shadow(r, 0.25) for r in rids]
    frac = sum(hits) / len(hits)
    assert 0.20 < frac < 0.30, frac
    # monotone in the fraction: a rid routed at f stays routed at f' > f
    for r in rids[:200]:
        if ctl_mod.route_to_shadow(r, 0.05):
            assert ctl_mod.route_to_shadow(r, 0.5)
    assert not any(ctl_mod.route_to_shadow(r, 0.0) for r in rids[:100])


def test_ctl_dir_is_invisible_to_tenant_discovery(tmp_path):
    from shifu_tensorflow_tpu.serve.tenancy.store import _NAME_OK

    assert _NAME_OK.match(ctl_mod.CTL_DIR) is None
    assert _NAME_OK.match("beta.next") is not None


# ------------------------------------------------------------- signals


def _serve_journal(base: str, worker: int = 0):
    from shifu_tensorflow_tpu.obs.journal import Journal

    return Journal(f"{base}.s{worker}", plane="serve", worker=worker)


def _snap(values, rng_seed=0):
    from shifu_tensorflow_tpu.obs.datastats import DataSketch

    sk = DataSketch(1)
    sk.add_batch(np.asarray(values, np.float64).reshape(-1, 1))
    return sk.snapshot()


def test_signals_fold_drift_slo_and_clears(tmp_path):
    from shifu_tensorflow_tpu.lifecycle.signals import LifecycleSignals

    base = str(tmp_path / "j")
    jrn = _serve_journal(base)
    jrn.emit("serve_start", workers=1)
    jrn.emit("data_drift", model="beta", feature="f3", stat="mean",
             score=2.0)
    jrn.emit("slo_breach", signal="serve_p99_s:beta")
    jrn.close()
    sig = LifecycleSignals(base, "beta", "beta.next")
    obs = sig.poll()
    assert obs.drift_open and "data_drift:beta:f3" in obs.drift_signals
    assert obs.slo_breached and obs.slo_signals == ["serve_p99_s:beta"]
    assert obs.new_events > 0
    # second poll with nothing new: quiet tick, latches persist
    obs = sig.poll()
    assert obs.new_events == 0 and obs.drift_open and obs.slo_breached
    # clears drain the latches
    jrn2 = _serve_journal(base)
    jrn2.emit("data_drift_clear", model="beta", feature="f3")
    jrn2.emit("slo_recover", signal="serve_p99_s:beta")
    jrn2.close()
    obs = sig.poll()
    assert not obs.drift_open and not obs.slo_breached


def test_signals_ignore_other_models_and_other_planes(tmp_path):
    from shifu_tensorflow_tpu.lifecycle.signals import LifecycleSignals
    from shifu_tensorflow_tpu.obs.journal import Journal

    base = str(tmp_path / "j")
    jrn = _serve_journal(base)
    jrn.emit("data_drift", model="gamma", feature="f0", stat="mean",
             score=9.0)
    jrn.emit("slo_breach", signal="serve_p99_s:gamma")
    jrn.close()
    train = Journal(f"{base}.w1", plane="train", worker=1)
    train.emit("data_drift", model="beta", feature="f3", stat="mean",
               score=9.0)
    train.close()
    sig = LifecycleSignals(base, "beta", "beta.next")
    obs = sig.poll()
    # a different tenant's drift and the train plane's drift are not
    # THIS loop's trigger; gamma's per-tenant SLO is not its rollback
    assert not obs.drift_open
    assert not obs.slo_breached


def test_signals_lifecycle_plane_is_not_fleet_liveness(tmp_path):
    from shifu_tensorflow_tpu.lifecycle.signals import LifecycleSignals
    from shifu_tensorflow_tpu.obs.journal import Journal

    base = str(tmp_path / "j")
    ctl = Journal(f"{base}.l0", plane="lifecycle", worker=0)
    ctl.emit("lifecycle_trigger", model="beta")
    ctl.close()
    sig = LifecycleSignals(base, "beta", "beta.next")
    assert sig.poll().new_events == 0


def test_signals_writer_restart_clears_its_latches(tmp_path):
    from shifu_tensorflow_tpu.lifecycle.signals import LifecycleSignals

    base = str(tmp_path / "j")
    jrn = _serve_journal(base)
    jrn.emit("slo_breach", signal="serve_p99_s")
    jrn.close()
    sig = LifecycleSignals(base, "beta", "beta.next")
    assert sig.poll().slo_breached
    jrn2 = _serve_journal(base)
    jrn2.emit("serve_start", workers=1)  # the process restarted
    jrn2.close()
    assert not sig.poll().slo_breached


def test_signals_divergence_from_score_stats(tmp_path):
    from shifu_tensorflow_tpu.lifecycle.signals import LifecycleSignals

    base = str(tmp_path / "j")
    rng = np.random.default_rng(7)
    same = rng.normal(0.5, 0.1, 4096)
    jrn = _serve_journal(base)
    jrn.emit("score_stats", model="beta", snapshot=_snap(same[:2048]))
    jrn.emit("score_stats", model="beta.next",
             snapshot=_snap(same[2048:]))
    jrn.close()
    sig = LifecycleSignals(base, "beta", "beta.next")
    obs = sig.poll()
    assert obs.shadow_rows == 2048
    assert obs.divergence is not None and obs.divergence < 1.0
    # a shifted shadow distribution diverges; cumulative snapshots
    # REPLACE (not accumulate) per writer
    jrn2 = _serve_journal(base)
    jrn2.emit("score_stats", model="beta.next",
              snapshot=_snap(rng.normal(5.0, 0.1, 2048)))
    jrn2.close()
    obs = sig.poll()
    assert obs.divergence is not None and obs.divergence >= 1.0


def test_signals_read_error_observation(tmp_path):
    from shifu_tensorflow_tpu.lifecycle import signals as sig_mod

    sig = sig_mod.LifecycleSignals(str(tmp_path / "j"), "beta",
                                   "beta.next")

    def boom(*a, **kw):
        raise OSError("disk gone")

    sig._read_keyed = boom
    assert sig.poll().read_error


# -------------------------------------------- publication + lineage pins


def test_publish_bundle_commits_manifest_last(tmp_path, monkeypatch):
    from shifu_tensorflow_tpu.export.saved_model import NATIVE_MANIFEST
    from shifu_tensorflow_tpu.lifecycle import controller as ctrl_mod

    src = tmp_path / "src"
    (src / "aot").mkdir(parents=True)
    (src / "weights.npz").write_bytes(b"w" * 64)
    (src / "aot" / "b8.bin").write_bytes(b"x" * 32)
    (src / NATIVE_MANIFEST).write_text("{}")
    order = []
    real_replace = os.replace

    def spying_replace(a, b):
        order.append(os.path.basename(b))
        return real_replace(a, b)

    monkeypatch.setattr(ctrl_mod.os, "replace", spying_replace)
    dst = tmp_path / "dst"
    ctrl_mod.publish_bundle(str(src), str(dst))
    assert order[-1] == NATIVE_MANIFEST
    assert order.count(NATIVE_MANIFEST) == 1
    assert (dst / "aot" / "b8.bin").read_bytes() == b"x" * 32


def test_publish_bundle_without_manifest_refuses(tmp_path):
    from shifu_tensorflow_tpu.lifecycle.controller import publish_bundle

    src = tmp_path / "src"
    src.mkdir()
    (src / "weights.npz").write_bytes(b"w")
    with pytest.raises(FileNotFoundError):
        publish_bundle(str(src), str(tmp_path / "dst"))


def test_bundle_lineage_legacy_manifest_pins_generation_zero(tmp_path):
    """A pre-lifecycle bundle (manifest without a ``lineage`` key) loads
    with lineage absent: generation 0, no parent — pinned so the stamp
    stays optional forever."""
    from shifu_tensorflow_tpu.export.saved_model import (
        NATIVE_MANIFEST,
        bundle_lineage,
    )

    d = str(tmp_path)
    with open(os.path.join(d, NATIVE_MANIFEST), "w") as f:
        json.dump({"format_version": 1, "sha256": "abc123"}, f)
    lin = bundle_lineage(d)
    assert lin == {"sha256": "abc123", "parent_sha256": None,
                   "generation": 0}
    # no manifest at all: same contract, sha unknown
    assert bundle_lineage(str(tmp_path / "nope")) == {
        "sha256": None, "parent_sha256": None, "generation": 0}


def test_export_stamps_lineage_and_legacy_load_still_admits(tmp_path):
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.export.eval_model import EvalModel
    from shifu_tensorflow_tpu.export.saved_model import (
        export_native_bundle,
        bundle_lineage,
    )
    from shifu_tensorflow_tpu.train.trainer import Trainer

    mc = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.05}}})
    t = Trainer(mc, 5)
    legacy = str(tmp_path / "legacy")
    export_native_bundle(legacy, t.state.params, mc, 5)
    lin = bundle_lineage(legacy)
    assert lin["generation"] == 0 and lin["parent_sha256"] is None
    assert lin["sha256"]  # identity is always stamped
    child = str(tmp_path / "child")
    export_native_bundle(
        child, t.state.params, mc, 5,
        lineage={"parent_sha256": lin["sha256"], "generation": 1})
    got = bundle_lineage(child)
    assert got["generation"] == 1
    assert got["parent_sha256"] == lin["sha256"]
    # both bundles admit through the verifying loader
    for d in (legacy, child):
        m = EvalModel(d, backend="native")
        out = m.compute_batch(np.zeros((2, 5), np.float32))
        assert out.shape[0] == 2


# --------------------------------------------- scheduler runtime weights


def test_scheduler_set_weight_runtime_and_journaled(tmp_path):
    from shifu_tensorflow_tpu.obs import journal as journal_mod
    from shifu_tensorflow_tpu.obs.journal import read_events
    from shifu_tensorflow_tpu.serve.batcher import MicroBatcher
    from shifu_tensorflow_tpu.serve.tenancy.scheduler import (
        DeviceScheduler,
    )

    base = str(tmp_path / "j")
    jrn = journal_mod.Journal(f"{base}.s0", plane="serve", worker=0)
    journal_mod.install(jrn)
    try:
        sched = DeviceScheduler()
        b = MicroBatcher(
            lambda rows: np.zeros((rows.shape[0], 1), np.float32),
            max_batch=8, max_delay_s=0.001, scheduler=sched,
            model="beta", weight=1.0)
        try:
            before = sched.set_weight("beta", 4.0)
            assert before == 1.0
            with pytest.raises(ValueError):
                sched.set_weight("beta", 0.0)
            with pytest.raises(KeyError):
                sched.set_weight("ghost", 2.0)
        finally:
            b.close(drain=True)
            sched.close()
    finally:
        journal_mod.uninstall()
    evs = [e for e in read_events(base)
           if e["event"] == "weight_change"]
    assert len(evs) == 1
    assert evs[0]["model"] == "beta"
    assert evs[0]["weight"] == 4.0 and evs[0]["weight_before"] == 1.0


def test_store_retire_evicts_and_is_reversible(tmp_path):
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.export.saved_model import export_native_bundle
    from shifu_tensorflow_tpu.serve.config import ServeConfig
    from shifu_tensorflow_tpu.serve.tenancy.store import MultiModelStore
    from shifu_tensorflow_tpu.train.trainer import Trainer

    mc = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.05}}})
    t = Trainer(mc, 5)
    models_dir = tmp_path / "models"
    export_native_bundle(str(models_dir / "beta"), t.state.params, mc, 5)
    cfg = ServeConfig(models_dir=str(models_dir), port=0,
                      reload_poll_ms=0)
    store = MultiModelStore(cfg)
    try:
        assert store.admitted() == ["beta"]
        assert store.retire("beta") is True
        assert store.admitted() == []
        # unknown / already-cold: no-op
        assert store.retire("beta") is False
        assert store.retire("ghost") is False
        # a request re-admits from the directory (post-promote contract)
        tenant = store.acquire("beta")
        assert tenant.state == "admitted"
    finally:
        store.close()


# ------------------------------------------------------- config surface


def test_parse_ramp_steps_validation():
    assert parse_ramp_steps("0.05,0.25,0.5") == (0.05, 0.25, 0.5)
    for bad in ("", "0.5,0.25", "0.3,0.3", "0,0.5", "0.5,1.0"):
        with pytest.raises(ValueError):
            parse_ramp_steps(bad)


def test_lifecycle_config_validation_and_json_round_trip():
    cfg = _cfg(train_args=("--epochs", "3"))
    back = LifecycleConfig.from_json(
        json.loads(json.dumps(cfg.to_json())))
    assert back == cfg
    assert back.shadow_name == "beta.next"
    with pytest.raises(ValueError):
        _cfg(model="")
    with pytest.raises(ValueError):
        _cfg(trigger_hysteresis=0)
    with pytest.raises(ValueError):
        _cfg(divergence_threshold=0.0)
    with pytest.raises(ValueError):
        _cfg(ramp_steps=(0.5, 0.25))


def test_lifecycle_cli_resolution_precedence(tmp_path):
    from shifu_tensorflow_tpu.config.conf import Conf
    from shifu_tensorflow_tpu.lifecycle.__main__ import build_parser

    conf_path = tmp_path / "g.json"
    conf_path.write_text(json.dumps({
        "shifu.tpu.lifecycle-model": "beta",
        "shifu.tpu.serve-models-dir": str(tmp_path / "models"),
        "shifu.tpu.obs-journal": str(tmp_path / "j"),
        "shifu.tpu.lifecycle-ramp-steps": "0.1,0.9",
        "shifu.tpu.lifecycle-cooldown": 42.5,
    }))
    args = build_parser().parse_args(
        ["run", "--globalconfig", str(conf_path),
         "--trigger-hysteresis", "7"])
    conf = Conf()
    conf.add_resource(str(conf_path))
    cfg = resolve_lifecycle_config(args, conf)
    assert cfg.model == "beta"               # conf
    assert cfg.ramp_steps == (0.1, 0.9)      # conf, parsed
    assert cfg.cooldown_s == 42.5            # conf float
    assert cfg.trigger_hysteresis == 7       # CLI wins
    assert cfg.poll_s == 1.0                 # built-in default


# --------------------------------------------------- obs reconstruction


def test_obs_lifecycle_reconstructs_cycle_from_journal(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main
    from shifu_tensorflow_tpu.obs.journal import Journal

    base = str(tmp_path / "j")
    ctl = Journal(f"{base}.l0", plane="lifecycle", worker=0)
    ctl.emit("lifecycle_trigger", model="beta",
             evidence={"signals": ["data_drift:beta:f3"]})
    ctl.emit("retrain_start", model="beta", generation=2,
             parent_sha256="aaa")
    ctl.emit("retrain_done", model="beta", ok=True, rc=0,
             generation=2, duration_s=3.2)
    ctl.emit("shadow_admit", model="beta", shadow="beta.next",
             sha256="bbb", generation=2)
    ctl.emit("ramp_step", model="beta", fraction=0.05)
    ctl.emit("ramp_step", model="beta", fraction=0.25)
    ctl.emit("promote", model="beta", sha256="bbb", generation=2)
    ctl.close()
    srv = Journal(f"{base}.s0", plane="serve", worker=0)
    srv.emit("lifecycle_ctl_applied", seq=1, shadow="beta.next",
             mirror=True, route_fraction=0.0)
    srv.close()
    rc = obs_main(["lifecycle", "--journal", base, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    cyc = out["cycles"][0]
    assert cyc["verdict"] == "promote" and cyc["generation"] == 2
    assert cyc["ramp_steps"] == [0.05, 0.25]
    assert cyc["latency_s"] is not None and cyc["latency_s"] >= 0
    assert cyc["retrain"]["ok"] is True
    # human rendering exercises too
    rc = obs_main(["lifecycle", "--journal", base])
    text = capsys.readouterr().out
    assert rc == 0 and "PROMOTE" in text


def test_obs_lifecycle_poisoned_retrain_cycle(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main
    from shifu_tensorflow_tpu.obs.journal import Journal

    base = str(tmp_path / "j")
    ctl = Journal(f"{base}.l0", plane="lifecycle", worker=0)
    ctl.emit("lifecycle_trigger", model="beta",
             evidence={"signals": ["data_drift:beta:f1"]})
    ctl.emit("retrain_start", model="beta", generation=3)
    ctl.emit("retrain_done", model="beta", ok=False, rc=3,
             why="rc 3: TrainingUnhealthy", generation=3)
    ctl.emit("rollback", model="beta",
             reason="retrain_failed: rc 3", parent_sha256="aaa")
    ctl.close()
    rc = obs_main(["lifecycle", "--journal", base, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    cyc = out["cycles"][0]
    assert cyc["verdict"] == "rollback"
    assert cyc["retrain"]["ok"] is False and cyc["retrain"]["rc"] == 3


# ----------------------------------------------- serve-side ctl reconcile


def test_server_route_split_is_rid_deterministic():
    from shifu_tensorflow_tpu.lifecycle.ctl import route_to_shadow

    # the serving split and any offline replay agree on every rid
    routed = [r for r in (f"r{i}" for i in range(1000))
              if route_to_shadow(r, 0.25)]
    again = [r for r in (f"r{i}" for i in range(1000))
             if route_to_shadow(r, 0.25)]
    assert routed == again and 150 < len(routed) < 350
