"""Observability plane: registry thread-safety, journal rotation and
corrupt-tail recovery, span tracing, trainer/coordinator integration,
and the obs CLI.

Every test that installs a process-global tracer/journal uninstalls it
(the obs hooks are module state the rest of the suite must not see).
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from shifu_tensorflow_tpu.obs import journal as journal_mod
from shifu_tensorflow_tpu.obs import trace as trace_mod
from shifu_tensorflow_tpu.obs.config import ObsConfig
from shifu_tensorflow_tpu.obs.journal import (
    Journal,
    journal_files,
    read_events,
)
from shifu_tensorflow_tpu.obs.registry import LatencyHistogram, MetricsRegistry
from shifu_tensorflow_tpu.obs.trace import Tracer, budget_fields


@pytest.fixture(autouse=True)
def _clean_obs_hooks():
    yield
    trace_mod.uninstall()
    journal_mod.uninstall()
    from shifu_tensorflow_tpu.obs import fleet as fleet_mod
    from shifu_tensorflow_tpu.obs import slo as slo_mod

    slo_mod.uninstall()
    fleet_mod.uninstall()


# ---- registry ----

def test_registry_prereg_counters_render_at_zero():
    r = MetricsRegistry()
    r.counter("requests_total")
    text = r.render_prometheus("t_")
    assert "# TYPE t_requests_total counter" in text
    assert "t_requests_total 0" in text


def test_registry_thread_safety_under_concurrent_writers():
    """8 writer threads hammering one registry: counter totals must be
    exact (no lost increments), histogram count must equal records."""
    r = MetricsRegistry()
    hist = r.histogram("lat")
    N, T = 2000, 8

    def writer(i):
        for k in range(N):
            r.inc("ops_total")
            r.set_gauge("last_writer", i)
            hist.record(0.001 * (k % 7))

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert r.counters()["ops_total"] == N * T
    assert hist.snapshot()["count"] == N * T
    # render must not crash mid-write either (smoke: it parses as text)
    assert "ops_total" in r.render_prometheus("x_")


def test_serve_metrics_format_unchanged_over_registry():
    """The /metrics body through the shared registry must keep the exact
    serve exposition format (the CI smoke greps these lines verbatim)."""
    from shifu_tensorflow_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics()
    m.inc("requests_total")
    m.inc("rows_total", 2)
    m.request_latency.record(0.004)
    text = m.render_prometheus(
        queue_rows=3, model_epoch=7, model_digest="abc123", model_verified=True
    )
    lines = text.splitlines()
    assert "stpu_serve_requests_total 1" in lines
    assert "stpu_serve_rows_total 2" in lines
    assert "# TYPE stpu_serve_queue_rows gauge" in lines
    assert "stpu_serve_queue_rows 3" in lines
    assert 'stpu_serve_model_info{digest="abc123"} 1' in lines
    assert any(
        l.startswith('stpu_serve_request_latency_seconds{quantile="0.99"}')
        for l in lines
    )
    assert any(l.startswith("stpu_serve_request_latency_seconds_count 1")
               for l in lines)
    # the full counter set renders even before any event (dashboards)
    assert "stpu_serve_shed_total 0" in lines


def test_registry_renders_cumulative_bucket_lines():
    """Satellite: real `_bucket{le=...}` cumulative lines beside the
    quantile gauges, so external Prometheus can histogram_quantile()
    instead of trusting our ladder-bound estimates."""
    r = MetricsRegistry(bounds=(0.01, 0.1, 1.0))
    h = r.histogram("lat")
    for v in (0.005, 0.005, 0.05, 0.5, 5.0):
        h.record(v)
    lines = r.render_prometheus("t_").splitlines()
    assert 't_lat_bucket{le="0.01"} 2' in lines
    assert 't_lat_bucket{le="0.1"} 3' in lines
    assert 't_lat_bucket{le="1.0"} 4' in lines
    assert 't_lat_bucket{le="+Inf"} 5' in lines  # +Inf == _count
    assert "t_lat_count 5" in lines
    # the existing quantile gauges stay (dashboards grep them)
    assert any(l.startswith('t_lat{quantile="0.99"}') for l in lines)


def test_serve_scrape_carries_bucket_lines():
    """Serve /metrics parity after the bucket satellite: cumulative
    buckets for both latency histograms, +Inf equal to the count."""
    from shifu_tensorflow_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics()
    m.request_latency.record(0.004)
    m.request_latency.record(0.2)
    text = m.render_prometheus(queue_rows=0, model_epoch=0,
                               model_digest="d", model_verified=True)
    lines = text.splitlines()
    assert ('stpu_serve_request_latency_seconds_bucket{le="+Inf"} 2'
            in lines)
    assert ('stpu_serve_batch_latency_seconds_bucket{le="+Inf"} 0'
            in lines)
    # cumulative: every bucket count is <= the next one
    counts = [int(l.rsplit(" ", 1)[1]) for l in lines
              if l.startswith("stpu_serve_request_latency_seconds_bucket")]
    assert counts == sorted(counts) and counts[-1] == 2


def test_latency_histogram_lives_only_in_the_registry():
    """serve/metrics re-exports the obs registry type (no third copy),
    and the coordinator/metrics_board deprecation shim is GONE — the
    PR-4 migration window closed, obs.registry is the one address."""
    from shifu_tensorflow_tpu.coordinator import metrics_board
    from shifu_tensorflow_tpu.serve import metrics as serve_metrics

    assert serve_metrics.LatencyHistogram is LatencyHistogram
    assert not hasattr(metrics_board, "LatencyHistogram")


def test_coordinator_metrics_render_through_registry():
    from types import SimpleNamespace

    from shifu_tensorflow_tpu.coordinator.coordinator import (
        Coordinator,
        JobSpec,
    )

    spec = JobSpec(n_workers=1, shards=[SimpleNamespace(paths=("s0",))])
    coord = Coordinator(spec)
    try:
        assert coord.register("w0", 0)["ok"]
        text = coord.metrics_text()
    finally:
        coord.shutdown()
    assert "stpu_coord_registrations_total 1" in text
    assert "stpu_coord_workers_registered 1" in text
    assert 'stpu_coord_state_info{state="training"} 1' in text
    # the dispatch surface exposes it too (the serve-/metrics analogue)
    resp = coord.dispatch({"op": "metrics"})
    assert resp["ok"] and "stpu_coord_registrations_total" in resp["text"]


# ---- journal ----

def test_journal_emit_read_roundtrip(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with Journal(path, plane="train", worker=3) as j:
        j.emit("epoch", epoch=0, loss=0.5)
        j.emit("epoch", epoch=1, loss=0.25, worker=9)  # explicit wins
    events = read_events(path)
    assert [e["event"] for e in events] == ["epoch", "epoch"]
    assert events[0]["plane"] == "train" and events[0]["worker"] == 3
    assert events[1]["worker"] == 9
    assert events[0]["ts"] <= events[1]["ts"]


def test_journal_rotation_bounds_footprint(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with Journal(path, max_bytes=4096, max_files=3) as j:
        for i in range(2000):
            j.emit("tick", i=i, pad="x" * 40)
    files = journal_files(path)
    assert 1 < len(files) <= 3
    for f in files:
        # one event of slack past the cap, never unbounded growth
        assert os.path.getsize(f) <= 4096 + 200
    events = read_events(path)
    assert events, "rotation must not lose the active file"
    # the newest event always survives rotation
    assert events[-1]["i"] == 1999


def test_journal_corrupt_tail_and_middle_recovery(tmp_path):
    """A writer killed mid-write tears the final line; at-rest corruption
    can garble a middle line.  Readers skip both, keep every intact
    event, and never raise."""
    path = str(tmp_path / "j.jsonl")
    with Journal(path) as j:
        for i in range(5):
            j.emit("tick", i=i)
    raw = open(path, "rb").read().splitlines(keepends=True)
    raw[2] = b"\x00\xff garbage not json \xfe\n"  # corrupted middle
    raw.append(b'{"ts": 1.0, "event": "torn", "i"')  # torn tail, no \n
    open(path, "wb").write(b"".join(raw))
    events = read_events(path)
    assert [e["i"] for e in events] == [0, 1, 3, 4]


def test_journal_merges_worker_siblings_and_rotations(tmp_path):
    base = str(tmp_path / "job.jsonl")
    with Journal(base, plane="coordinator") as j:
        j.emit("register", worker=0)
    for w in (0, 1):
        with Journal(f"{base}.w{w}", max_bytes=4096, max_files=2,
                     plane="train", worker=w) as jw:
            for i in range(200):
                jw.emit("epoch", epoch=i, pad="y" * 30)
    files = journal_files(base)
    assert any(f.endswith(".w0") for f in files)
    assert any(".w0.1" in f for f in files), "rotations must be discovered"
    # an unrelated sibling must NOT be swept in
    open(str(tmp_path / "job.jsonl.bak"), "w").write('{"event": "no"}\n')
    assert not any(f.endswith(".bak") for f in journal_files(base))
    events = read_events(base)
    assert {e["event"] for e in events} == {"register", "epoch"}
    assert events == sorted(events, key=lambda e: e["ts"])


def test_journal_discovers_serve_worker_siblings(tmp_path):
    """--serve-workers scoring processes write <base>.s<i> siblings; the
    reader merges them beside train (.w<i>) siblings and rotations, and
    install_obs routes a serve-plane worker to the .s path."""
    base = str(tmp_path / "job.jsonl")
    with Journal(base, plane="serve") as j:
        j.emit("serve_fleet_start", workers=2)
    for s in (0, 1):
        with Journal(f"{base}.s{s}", plane="serve", worker=s) as js:
            js.emit("serve_start", port=1234)
    files = journal_files(base)
    assert any(f.endswith(".s0") for f in files)
    assert any(f.endswith(".s1") for f in files)
    events = read_events(base)
    assert [e["event"] for e in events] == [
        "serve_fleet_start", "serve_start", "serve_start"]
    assert {e.get("worker") for e in events
            if e["event"] == "serve_start"} == {0, 1}

    from shifu_tensorflow_tpu.obs import install_obs

    cfg = ObsConfig(enabled=True, journal_path=base)
    _, j = install_obs(cfg, worker_index=3, plane="serve")
    assert j.path.endswith(".s3")
    journal_mod.uninstall()


def test_journal_seq_is_per_writer_monotonic(tmp_path):
    """Every record carries a monotonic per-writer seq, surviving
    rotation — `obs trace` renders merge order as causality, so
    same-microsecond events must keep emission order."""
    path = str(tmp_path / "j.jsonl")
    with Journal(path, max_bytes=4096, max_files=8) as j:
        for i in range(300):
            j.emit("tick", i=i, pad="x" * 40)
    events = read_events(path)
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    assert [e["i"] for e in events] == sorted(e["i"] for e in events)


def test_read_events_merges_same_timestamp_by_seq(tmp_path,
                                                  monkeypatch):
    """The satellite's pinned contract: with every event stamped the
    SAME ts (a frozen clock — the worst case a fast writer can produce),
    the merged read still returns one writer's events in seq order
    across a rotation boundary."""
    import shifu_tensorflow_tpu.obs.journal as jm

    monkeypatch.setattr(jm.time, "time", lambda: 1234.5)
    path = str(tmp_path / "j.jsonl")
    with Journal(path, max_bytes=4096, max_files=4) as j:
        for i in range(200):
            j.emit("tick", i=i, pad="y" * 40)
    files = journal_files(path)
    assert len(files) > 1, "the drill needs a rotation to mean anything"
    events = read_events(path)
    assert all(e["ts"] == 1234.5 for e in events)
    ids = [e["i"] for e in events]
    assert ids == sorted(ids), "same-ts events must merge in seq order"


def test_read_events_same_ts_across_writers_stable(tmp_path, monkeypatch):
    """Equal timestamps across writers keep the deterministic base →
    .w<k> → .s<k> writer order, each writer internally seq-ordered."""
    import shifu_tensorflow_tpu.obs.journal as jm

    monkeypatch.setattr(jm.time, "time", lambda: 99.0)
    base = str(tmp_path / "job.jsonl")
    with Journal(base + ".s0", plane="serve", worker=0) as js:
        js.emit("s-first")
        js.emit("s-second")
    with Journal(base + ".w1", plane="train", worker=1) as jw:
        jw.emit("w-first")
    with Journal(base, plane="coordinator") as j:
        j.emit("base-first")
    names = [e["event"] for e in read_events(base)]
    assert names == ["base-first", "w-first", "s-first", "s-second"]


def test_journal_job_stamp_and_install_wiring(tmp_path):
    """The fleet-wide job correlation id stamps every event the writer
    emits; install_obs threads it through."""
    from shifu_tensorflow_tpu.obs import install_obs

    base = str(tmp_path / "j.jsonl")
    with Journal(base, plane="train", job="abc123") as j:
        j.emit("epoch", epoch=0)
    assert read_events(base)[0]["job"] == "abc123"
    cfg = ObsConfig(enabled=True, journal_path=base)
    _, jrn = install_obs(cfg, worker_index=1, plane="train", job="abc123")
    assert jrn.job == "abc123"
    journal_mod.emit("worker_start")
    journal_mod.uninstall()
    ev = [e for e in read_events(base) if e["event"] == "worker_start"][0]
    assert ev["job"] == "abc123" and ev["worker"] == 1


def test_read_events_cache_reuses_unchanged_files(tmp_path, monkeypatch):
    """The `obs top` refresh contract: with a caller-held cache, files
    whose (size, mtime) are unchanged are NOT re-parsed — only growth
    is paid for."""
    import shifu_tensorflow_tpu.obs.journal as jm

    path = str(tmp_path / "j.jsonl")
    with Journal(path) as j:
        for i in range(5):
            j.emit("tick", i=i)
    cache: dict = {}
    first = read_events(path, cache=cache)
    assert [e["i"] for e in first] == [0, 1, 2, 3, 4]
    # unchanged file: the parse layer must not even be consulted
    real_iter = jm.iter_events
    monkeypatch.setattr(jm, "iter_events",
                        lambda p: (_ for _ in ()).throw(AssertionError(
                            f"re-parsed unchanged {p}")))
    assert [e["i"] for e in read_events(path, cache=cache)] == [0, 1, 2, 3, 4]
    monkeypatch.setattr(jm, "iter_events", real_iter)
    # growth invalidates the cached entry and the new event appears
    with Journal(path) as j:
        j.emit("tick", i=5)
    assert [e["i"] for e in read_events(path, cache=cache)][-1] == 5


def test_read_events_cache_invalidates_across_rotation(tmp_path):
    """Satellite (PR 10): a journal rolling path→.1 while a poller holds
    a parse cache must never serve stale lines — even on a coarse-mtime
    filesystem where the NEW active file can land with the same (size,
    mtime) the cached one had.  The cache signature includes st_ino,
    which travels WITH the content across the rotation rename."""
    path = str(tmp_path / "j.jsonl")

    def write_lines(p, ts0, tags):
        # hand-rolled fixed-width lines (a Journal's float ts wobbles
        # by a byte run to run): equal line lengths -> EQUAL file sizes
        with open(p, "w") as f:
            for k, tag in enumerate(tags):
                f.write('{"ts":%.6f,"seq":%d,"event":"tick",'
                        '"tag":"%s"}\n' % (ts0 + k, k, tag))

    write_lines(path, 100.0, ["old0", "old1", "old2"])
    cache: dict = {}
    assert [e["tag"] for e in read_events(path, cache=cache)] \
        == ["old0", "old1", "old2"]
    st_old = os.stat(path)
    # the rotation: path -> path.1 (content + inode + mtime travel),
    # a fresh active file appears with same-length lines
    os.replace(path, path + ".1")
    write_lines(path, 200.0, ["new0", "new1", "new2"])
    # force the coarse-mtime collision: same size, same mtime_ns
    assert os.stat(path).st_size == st_old.st_size
    os.utime(path, ns=(st_old.st_atime_ns, st_old.st_mtime_ns))
    got = [e["tag"] for e in read_events(path, cache=cache)]
    # every event exactly once, rotation first: stale cache would have
    # yielded old0..old2 TWICE (and lost new0..new2 entirely)
    assert got == ["old0", "old1", "old2", "new0", "new1", "new2"], got


def test_obs_cli_trace_json(tmp_path, capsys):
    """Satellite: `obs trace --json` — one raw event object per line,
    CLI parity with summary/tail."""
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    base = _seed_trace_journal(tmp_path)
    assert obs_main(["trace", "rid-scored-1", "--journal", base,
                     "--json"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert lines, "trace --json printed nothing"
    evs = [json.loads(l) for l in lines]
    assert all(
        e.get("rid") == "rid-scored-1"
        or "rid-scored-1" in (e.get("rids") or [])
        for e in evs
    )


def test_obs_cli_tail_follow_streams_new_events(tmp_path):
    """Satellite: `obs tail --follow` — a live poller prints events as
    they land, re-reading only the growing file (parse cache)."""
    path = str(tmp_path / "j.jsonl")
    with Journal(path) as j:
        j.emit("worker_start", i=0)
    p = subprocess.Popen(
        [sys.executable, "-m", "shifu_tensorflow_tpu.obs", "tail",
         "--journal", path, "--follow", "--interval", "0.2", "--json"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    def readline_deadline(timeout_s=30.0):
        # bare readline() would hang the whole suite on a follow-mode
        # regression; a reader thread turns "no output" into a red test
        import queue

        q: queue.Queue = queue.Queue()
        threading.Thread(target=lambda: q.put(p.stdout.readline()),
                         daemon=True).start()
        try:
            return q.get(timeout=timeout_s)
        except queue.Empty:
            raise AssertionError(
                "follower printed nothing within the deadline")

    try:
        first = json.loads(readline_deadline())
        assert first["event"] == "worker_start"
        # an event appended AFTER the follower started must stream out
        with Journal(path) as j:
            j.emit("late_event", i=1)
        late = json.loads(readline_deadline())
        assert late["event"] == "late_event"
    finally:
        p.kill()
        p.wait(timeout=10)


def test_journal_install_emit_is_noop_without_install():
    journal_mod.uninstall()
    journal_mod.emit("nobody-listening", x=1)  # must not raise


def test_journal_write_failure_degrades_not_raises(tmp_path):
    j = Journal(str(tmp_path / "j.jsonl"))
    j.emit("ok")
    # simulate the disk going away mid-job: further emits drop, not raise
    os.close(j._file)
    j._file = -1
    j.emit("dropped")
    assert j.dropped == 1
    j._file = None  # avoid double-close on cleanup
    j.close()


# ---- tracer ----

def test_tracer_spans_and_budget_fields():
    t = Tracer(worker_index=2)
    with t.span("step.dispatch"):
        pass
    with t.span("step.dispatch"):
        pass
    t.add("step.infeed", 0.25)
    t.add("checkpoint.save", 1.5)
    fields = budget_fields(t.take_summary())
    assert fields["steps"] == 2
    assert fields["infeed_s"] == 0.25
    assert fields["host_s"] == 0.0
    assert fields["spans"]["checkpoint.save"]["count"] == 1
    # take_summary drained the tracer
    assert t.summary() == {}


def test_tracer_sampling_measures_every_nth():
    # sampling applies to the hot-path step.* phases only
    t = Tracer(sample_every=4)
    f = t.timed("step.host", lambda: None)
    for _ in range(8):
        f()
    s = t.summary()["step.host"]
    assert s["count"] == 2 and s["sampled_every"] == 4


def test_maybe_span_is_noop_without_tracer():
    with trace_mod.maybe_span(None, "x"):
        pass
    trace_mod.record("x", 1.0)  # no tracer installed: no-op


def test_retry_sleep_records_span():
    from shifu_tensorflow_tpu.utils import retry as retry_util

    t = trace_mod.install(Tracer())
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("nope")
        return "ok"

    pol = retry_util.RetryPolicy(max_attempts=5, base_delay_s=0.001,
                                 max_delay_s=0.002, seed=7)
    assert retry_util.call(flaky, policy=pol, site="test.seam") == "ok"
    spans = t.summary()
    assert spans["retry.sleep"]["count"] == 2


def test_checkpoint_save_restore_spans_and_events(tmp_path):
    import jax.numpy as jnp

    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.train import make_trainer
    from shifu_tensorflow_tpu.train.checkpoint import NpzCheckpointer

    t = trace_mod.install(Tracer())
    j = journal_mod.install(Journal(str(tmp_path / "j.jsonl")))
    mc = ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.1}}}
    )
    trainer = make_trainer(mc, 2, feature_columns=(0, 1))
    with NpzCheckpointer(str(tmp_path / "ckpt")) as ckpt:
        ckpt.save(0, trainer.state)
        restored, nxt = ckpt.restore_latest(trainer.state)
    assert nxt == 1
    spans = t.summary()
    assert spans["checkpoint.save"]["count"] == 1
    assert spans["checkpoint.restore"]["count"] == 1
    events = [e["event"] for e in read_events(str(tmp_path / "j.jsonl"))]
    assert "checkpoint_saved" in events and "checkpoint_restored" in events


# ---- trainer integration ----

def _tiny_dataset(tmp_path):
    from shifu_tensorflow_tpu.data.dataset import InMemoryDataset
    from shifu_tensorflow_tpu.data.reader import RecordSchema

    rng = np.random.default_rng(0)
    path = tmp_path / "data.psv"
    with open(path, "w") as f:
        for _ in range(120):
            x = rng.normal(size=2)
            y = int(x[0] + 0.5 * x[1] > 0)
            f.write(f"{y}|{x[0]:.4f}|{x[1]:.4f}\n")
    schema = RecordSchema(feature_columns=(1, 2), target_column=0)
    return InMemoryDataset.load([str(path)], schema, valid_rate=0.2), schema


@pytest.mark.parametrize("scan_steps", [1, 4])
def test_trainer_journals_epoch_and_step_breakdown(tmp_path, scan_steps):
    """The acceptance loop in miniature: a traced fit emits one epoch +
    one step_breakdown event per epoch, and the breakdown's phases are
    populated (dispatch counted per device call)."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.train import make_trainer

    trace_mod.install(Tracer())
    journal_mod.install(Journal(str(tmp_path / "j.jsonl"), plane="train"))
    dataset, schema = _tiny_dataset(tmp_path)
    mc = ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.1}}}
    )
    trainer = make_trainer(mc, 2, feature_columns=(1, 2),
                           scan_steps=scan_steps)
    assert trainer.tracer is trace_mod.active()
    trainer.fit(dataset, epochs=2, batch_size=32)
    events = read_events(str(tmp_path / "j.jsonl"))
    epochs = [e for e in events if e["event"] == "epoch"]
    breakdowns = [e for e in events if e["event"] == "step_breakdown"]
    assert len(epochs) == 2 and len(breakdowns) == 2
    for b in breakdowns:
        assert b["steps"] > 0
        assert b["dispatch_s"] > 0.0
        assert b["infeed_s"] > 0.0
        # pipelined infeed (default): host production ran on the put
        # thread — reported as overlapped host_produce_s, with the
        # disjoint host_s phase ~0 by construction
        assert b.get("host_produce_s", 0.0) > 0.0
        assert b["host_s"] == 0.0
    assert epochs[0]["global_step"] > 0


def test_trainer_untraced_emits_nothing_and_has_no_tracer(tmp_path):
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.train import make_trainer

    dataset, _ = _tiny_dataset(tmp_path)
    mc = ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.1}}}
    )
    trainer = make_trainer(mc, 2, feature_columns=(1, 2))
    assert trainer.tracer is None
    trainer.fit(dataset, epochs=1, batch_size=32)  # must not journal/crash


# ---- CLI ----

def _seed_cli_journal(tmp_path) -> str:
    base = str(tmp_path / "job.jsonl")
    with Journal(base, plane="coordinator") as j:
        j.emit("register", worker=0, worker_id="w-0", generation=0)
        j.emit("rollback", worker=0, epoch=1, rollbacks=1, lr_scale=0.5)
    with Journal(f"{base}.w0", plane="train", worker=0) as jw:
        jw.emit("epoch", epoch=0, train_loss=0.4, train_time_s=2.0)
        jw.emit("step_breakdown", epoch=0, steps=10, infeed_s=0.2,
                host_s=0.3, dispatch_s=1.2, block_s=0.1,
                spans={"rpc.epoch": {"count": 1, "total_s": 0.05}})
    return base


def test_obs_cli_summary_renders_budget_and_timeline(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    base = _seed_cli_journal(tmp_path)
    assert obs_main(["summary", "--journal", base]) == 0
    out = capsys.readouterr().out
    assert "per-step time budget" in out
    assert "fleet timeline" in out
    assert "register" in out and "rollback" in out
    # the budget row: 1.2s dispatch of a 2.0s epoch wall = 60%
    assert "60.0" in out
    assert "rpc.epoch 1x 0.050s" in out


def test_obs_cli_summary_renders_serve_plane(tmp_path, capsys):
    """The serve plane renders per-worker from journal events alongside
    the train/fleet views: request volume + rate, shed pressure, reload
    outcomes, and the --serve-workers split."""
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    base = _seed_cli_journal(tmp_path)  # train events: plane must coexist
    with Journal(base + ".sup", plane="serve") as sup:
        pass  # (unmatched name: must NOT be swept in)
    with Journal(base, plane="serve") as j:
        j.emit("serve_fleet_start", port=9100, workers=2)
        j.emit("serve_worker_restart", index=1, restarts=1)
    for s, reqs in ((0, 120), (1, 80)):
        with Journal(f"{base}.s{s}", plane="serve", worker=s) as js:
            js.emit("serve_start", port=9100)
            js.emit("reload", epoch=1, digest="abc", verified=True)
            if s == 1:
                js.emit("reload_refused", why="weights.npz: sha256 differs")
                js.emit("shed", queue_rows=64, shed_total=17)
            js.emit("serve_stop", requests_total=reqs, shed_total=17 * s)
    assert obs_main(["summary", "--journal", base]) == 0
    out = capsys.readouterr().out
    assert "serve plane" in out
    assert "fleet: 2 workers, 1 restart(s)" in out
    lines = [ln for ln in out.splitlines() if ln.strip().startswith(("0 ", "1 "))]
    serve_rows = {ln.split()[0]: ln.split() for ln in lines}
    assert serve_rows["0"][1] == "120"
    assert serve_rows["1"][1] == "80"
    assert serve_rows["1"][3] == "17"   # shed column
    assert serve_rows["1"][5] == "1"    # refused column
    # the train budget and timeline still render beside it
    assert "per-step time budget" in out and "fleet timeline" in out


def test_obs_cli_tail_shows_last_events(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    base = _seed_cli_journal(tmp_path)
    assert obs_main(["tail", "--journal", base, "-n", "2"]) == 0
    out = capsys.readouterr().out
    assert len(out.strip().splitlines()) == 2


def test_obs_cli_missing_journal_fails_cleanly(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    assert obs_main(["summary", "--journal",
                     str(tmp_path / "nope.jsonl")]) == 1
    assert "no journal events" in capsys.readouterr().err


def _seed_trace_journal(tmp_path) -> str:
    """A journal with one scored request (rid riding a serve_batch), one
    shed rid, and slo transitions — the trace/top fixtures."""
    base = str(tmp_path / "job.jsonl")
    with Journal(base, plane="coordinator", job="j1") as j:
        j.emit("register", worker=0, worker_id="w-0")
        j.emit("epoch_summary", epoch=1, n_workers=1, ks=0.31)
    with Journal(f"{base}.w0", plane="train", worker=0, job="j1") as jw:
        jw.emit("epoch", epoch=1, train_loss=0.4, train_time_s=1.0,
                global_step=20)
        jw.emit("step_breakdown", epoch=1, steps=10, infeed_s=0.1,
                host_s=0.1, dispatch_s=0.7, block_s=0.1, global_step=20)
    with Journal(f"{base}.s0", plane="serve", worker=0, job="j1") as js:
        js.emit("serve_start", port=9100)
        js.emit("serve_batch", rids=["rid-scored-1", "rid-peer"],
                requests=2, rows=3, bucket=4, queue_delay_s=0.004,
                dispatch_s=0.002)
        js.emit("shed", rid="rid-shed-1", queue_rows=64, shed_total=9)
        js.emit("slo_breach", signal="serve_shed_rate", value=0.4,
                target=0.2, window_s=5.0,
                window={"count": 50, "p99": 0.4})
        js.emit("slo_recover", signal="serve_shed_rate", value=0.0,
                target=0.2, breach_s=3.5)
        js.emit("serve_stop", requests_total=40, shed_total=9)
    return base


def test_obs_cli_summary_and_tail_json(tmp_path, capsys):
    """Satellite: machine-readable output — the autoscaling supervisor
    must not screen-scrape the human renderer."""
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    base = _seed_trace_journal(tmp_path)
    assert obs_main(["summary", "--journal", base, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["jobs"] == ["j1"]
    assert doc["counts"]["serve_batch"] == 1
    assert doc["budget"]["0"]["steps"] == 10
    assert doc["budget"]["0"]["pct"]["dispatch"] == 70.0
    assert doc["serve"]["workers"]["0"]["requests"] == 40
    slo = doc["slo"]["serve_shed_rate"]
    assert slo["breaches"] == 1 and slo["breached"] is False
    assert obs_main(["tail", "--journal", base, "-n", "3", "--json"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 3
    assert all(json.loads(l)["event"] for l in lines)


def test_obs_cli_summary_renders_slo_section(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    base = _seed_trace_journal(tmp_path)
    assert obs_main(["summary", "--journal", base]) == 0
    out = capsys.readouterr().out
    assert "slo" in out and "serve_shed_rate" in out
    # recovered by the journal's last transition: renders ok, not BREACHED
    assert "BREACHED" not in out


def test_obs_cli_trace_resolves_rid(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    base = _seed_trace_journal(tmp_path)
    assert obs_main(["trace", "rid-scored-1", "--journal", base]) == 0
    out = capsys.readouterr().out
    assert "serve_batch" in out and "rid-scored-1" in out
    assert "coalesced into a 3-row dispatch" in out
    # a shed request's id resolves to its shed event
    assert obs_main(["trace", "rid-shed-1", "--journal", base]) == 0
    assert "shed" in capsys.readouterr().out
    # an unknown rid is a clean failure, not a stack trace
    assert obs_main(["trace", "rid-nope", "--journal", base]) == 1
    assert "no events for rid" in capsys.readouterr().err


def test_obs_cli_trace_colon_rid_falls_back(tmp_path, capsys):
    """The serve sanitizer strips ':' from new rids, but a hand-written
    or legacy journal may carry one — a worker:epoch-shaped query that
    matches nothing falls back to a rid match."""
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    base = str(tmp_path / "j.jsonl")
    with Journal(base, plane="serve", worker=0) as j:
        j.emit("serve_batch", rids=["12:3"], requests=1, rows=1, bucket=8)
    assert obs_main(["trace", "12:3", "--journal", base]) == 0
    out = capsys.readouterr().out
    assert "rid 12:3" in out and "serve_batch" in out


def test_obs_cli_trace_worker_epoch(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    base = _seed_trace_journal(tmp_path)
    assert obs_main(["trace", "0:1", "--journal", base]) == 0
    out = capsys.readouterr().out
    # the worker's epoch + breakdown AND the coordinator's quorum record
    # merge into one causal story
    assert "step_breakdown" in out and "epoch_summary" in out
    assert "global_step=20" in out


def test_obs_cli_top_once_renders_all_sections(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    base = _seed_trace_journal(tmp_path)
    assert obs_main(["top", "--journal", base, "--once"]) == 0
    out = capsys.readouterr().out
    assert "obs top" in out and "job j1" in out
    assert "slo" in out and "serve_shed_rate" in out
    assert "train" in out and "serve" in out
    assert "recent events" in out
    # dead-fleet contract: an unreachable metrics URL must not break it
    assert obs_main(["top", "--journal", base, "--once",
                     "--metrics-url", "http://127.0.0.1:9/metrics"]) == 0
    assert "scraped 0/1" in capsys.readouterr().out


# ---- ObsConfig ----

def test_obs_config_json_bridge_roundtrip():
    cfg = ObsConfig(enabled=True, journal_path="/tmp/j.jsonl",
                    journal_max_bytes=1 << 20, journal_max_files=2,
                    trace_sample=3, hist_buckets=(0.001, 0.01, 0.1))
    assert ObsConfig.from_json(json.loads(json.dumps(cfg.to_json()))) == cfg


def test_obs_config_rejects_misconfiguration():
    with pytest.raises(ValueError, match="obs-trace-sample"):
        ObsConfig(trace_sample=0)
    with pytest.raises(ValueError, match="obs-journal-max-files"):
        ObsConfig(journal_max_files=0)
    with pytest.raises(ValueError, match="obs-hist-buckets"):
        ObsConfig(hist_buckets=(0.1, 0.01))
    with pytest.raises(ValueError, match="obs-journal-max-bytes"):
        ObsConfig(journal_max_bytes=100)


def test_install_obs_wires_worker_sibling_paths(tmp_path):
    from shifu_tensorflow_tpu.obs import install_obs

    cfg = ObsConfig(enabled=True, journal_path=str(tmp_path / "j.jsonl"))
    tracer, j = install_obs(cfg, worker_index=2, plane="train")
    assert tracer is trace_mod.active() and tracer.worker_index == 2
    assert j.path.endswith(".w2") and j.worker == 2
    journal_mod.emit("hello")
    journal_mod.uninstall()
    assert read_events(str(tmp_path / "j.jsonl"))[0]["worker"] == 2
    # disabled config installs nothing
    assert install_obs(ObsConfig()) == (None, None)


# ---- review-fix regressions ----

def test_budget_fields_scales_sampled_step_phases():
    """trace-sample=N measures 1/N of step events; the journal must carry
    unbiased ABSOLUTE estimates or the CLI budget overstates step_ms by N."""
    t = Tracer(sample_every=4)
    f = t.timed("step.infeed", lambda: None)
    for _ in range(8):
        f()
        with t.span("step.dispatch"):
            pass
    t.add("retry.sleep", 0.5)  # aux spans are never sampled
    fields = budget_fields(t.take_summary())
    assert fields["steps"] == 8  # 2 measured x 4
    assert fields["trace_sample"] == 4
    assert fields["spans"]["retry.sleep"]["count"] == 1


def test_aux_spans_are_never_sampled():
    t = Tracer(sample_every=10)
    for _ in range(3):
        with t.span("checkpoint.save"):
            pass
    assert t.summary()["checkpoint.save"]["count"] == 3


def test_journal_survives_persistent_rotation_failure(tmp_path):
    """Rotation failing forever (dir lost write permission) must degrade
    to append-past-the-cap, not recurse to a crash."""
    path = str(tmp_path / "j.jsonl")
    j = Journal(path, max_bytes=4096, max_files=3)
    j._rotate = lambda: None  # every rotation attempt silently fails
    for i in range(500):
        j.emit("tick", i=i, pad="x" * 40)
    j.close()
    events = read_events(path)
    assert events[-1]["i"] == 499  # nothing lost, nothing raised
    assert os.path.getsize(path) > 4096  # bound degraded, job alive


def test_hist_buckets_reach_scrape_registries(tmp_path):
    """shifu.tpu.obs-hist-buckets must actually drive the histograms the
    scrape surfaces build (it was once resolved-but-dead)."""
    from shifu_tensorflow_tpu.obs import install_obs
    from shifu_tensorflow_tpu.obs import registry as registry_mod
    from shifu_tensorflow_tpu.serve.metrics import ServeMetrics

    try:
        install_obs(ObsConfig(enabled=True, hist_buckets=(0.5, 1.0)))
        m = ServeMetrics()
        m.request_latency.record(0.7)
        assert m.request_latency.percentile(99) == 1.0  # custom ladder
        snap = m.request_latency.snapshot()
        assert set(snap["buckets"]) == {"0.5", "1.0", "+Inf"}
    finally:
        registry_mod.set_default_bounds(None)


def test_run_worker_does_not_clobber_shared_process_obs(tmp_path):
    """Thread-launcher seam: a worker sharing the submitter's process must
    NOT replace the installed journal/tracer (coordinator events would be
    misattributed and the journal fd leaked) — it gets a private tracer
    and emits into the shared journal with explicit plane/worker."""
    from shifu_tensorflow_tpu.obs import journal as jm
    from shifu_tensorflow_tpu.obs import trace as tm

    base = str(tmp_path / "job.jsonl")
    shared_j = jm.install(Journal(base, plane="coordinator"))
    shared_t = tm.install(Tracer(worker_index=0))
    # simulate the run_worker install-guard branch
    from shifu_tensorflow_tpu.obs.config import ObsConfig as OC

    cfg = OC(enabled=True, journal_path=base)
    assert jm.active() is shared_j and tm.active() is shared_t
    # the guard condition run_worker checks:
    assert not (jm.active() is None and tm.active() is None)
    jm.emit("epoch", plane="train", worker=1)
    jm.uninstall()
    tm.uninstall()
    ev = read_events(base)[0]
    assert ev["plane"] == "train" and ev["worker"] == 1


# ---- fleet leg: clock sync, journal offsets, comm spans, CLI ----

def test_clock_sync_symmetric_exchange_recovers_offset():
    from shifu_tensorflow_tpu.obs.fleet import ClockSync

    cs = ClockSync()
    # frozen clocks: server 5s AHEAD, 10ms symmetric network legs, 2s of
    # server processing (a barrier hold) — processing must cancel exactly
    assert cs.offset() is None
    cs.update(t0=100.0, t1=105.010, t2=107.010, t3=102.020)
    assert cs.offset() == pytest.approx(5.0, abs=1e-9)
    assert cs.delay() == pytest.approx(0.020, abs=1e-9)


def test_clock_sync_asymmetric_latency_error_bounded_by_half_delay():
    from shifu_tensorflow_tpu.obs.fleet import ClockSync

    cs = ClockSync()
    # request leg 10ms, reply leg 50ms: the symmetric assumption is off
    # by (50-10)/2 = 20ms — exactly the NTP bound delay/2 = 30ms
    cs.update(t0=100.0, t1=105.010, t2=105.010, t3=100.060)
    err = abs(cs.offset() - 5.0)
    assert err <= cs.delay() / 2 + 1e-12
    assert err == pytest.approx(0.020, abs=1e-9)
    # a later LOW-delay exchange wins over the congested one
    cs.update(t0=200.0, t1=205.001, t2=205.001, t3=200.002)
    assert cs.offset() == pytest.approx(5.0, abs=1e-3)
    assert cs.delay() == pytest.approx(0.002, abs=1e-9)


def test_clock_sync_rejects_garbage_and_resets():
    from shifu_tensorflow_tpu.obs.fleet import ClockSync

    cs = ClockSync()
    assert cs.update(1.0, None, 2.0, 3.0) is None
    assert cs.update(10.0, 5.0, 4.0, 11.0) is None  # t2 < t1
    assert cs.offset() is None
    cs.update(100.0, 105.0, 105.0, 100.1)
    assert cs.offset() is not None
    # worker restart semantics: a fresh estimator has no carry-over
    cs.reset()
    assert cs.offset() is None and cs.delay() is None


def test_client_clock_resets_with_the_client():
    """A relaunched worker builds a fresh CoordinatorClient; its clock
    estimate must not survive the process whose clock it described."""
    from shifu_tensorflow_tpu.coordinator.coordinator import (
        CoordinatorClient,
    )

    c1 = CoordinatorClient("127.0.0.1", 1)
    c1.clock.update(100.0, 105.0, 105.0, 100.1)
    assert c1.clock_offset() is not None
    c2 = CoordinatorClient("127.0.0.1", 1)
    assert c2.clock_offset() is None


def test_journal_stamps_offset_once_known(tmp_path):
    base = str(tmp_path / "off.jsonl")
    j = Journal(base, plane="train", worker=1)
    j.emit("before")
    j.set_offset(0.125)
    j.emit("after")
    j.set_offset(None)
    j.emit("cleared")
    j.close()
    evs = read_events(base)
    assert "offset" not in evs[0]
    assert evs[1]["offset"] == pytest.approx(0.125)
    assert "offset" not in evs[2]


def test_note_offset_reaches_active_journal(tmp_path):
    from shifu_tensorflow_tpu.obs import fleet as fleet_mod

    base = str(tmp_path / "noted.jsonl")
    journal_mod.install(Journal(base, plane="train", worker=0))
    fleet_mod.note_offset(0.25)
    assert fleet_mod.clock_offset() == pytest.approx(0.25)
    journal_mod.emit("ev", plane="train")
    journal_mod.uninstall()
    assert read_events(base)[0]["offset"] == pytest.approx(0.25)


def test_comm_region_records_span_bytes_and_epoch_drain():
    from shifu_tensorflow_tpu.obs import fleet as fleet_mod

    t = trace_mod.install(Tracer(worker_index=0))
    fleet_mod.take_comm()  # drain residue other tests' collectives left
    with fleet_mod.comm_region("ring_attention", nbytes=1024):
        pass
    with fleet_mod.comm_region("ring_attention", nbytes=1024):
        pass
    summ = t.summary()
    assert summ["comm.ring_attention"]["count"] == 2
    drained = fleet_mod.take_comm()
    assert drained["ring_attention"] == {"calls": 2, "bytes": 2048}
    # the per-epoch drain resets; the scrape-surface totals do not
    # (process-lifetime counters — assert presence, not a value other
    # tests' collectives would shift)
    assert fleet_mod.take_comm() == {}
    assert 'fleet_comm_bytes_total{kind="ring_attention"}' in \
        fleet_mod.comm_text()


def test_shard_map_calls_run_under_comm_region():
    import jax.numpy as jnp

    from shifu_tensorflow_tpu.parallel.mesh import make_mesh
    from shifu_tensorflow_tpu.parallel.shmap import shard_map
    from jax.sharding import PartitionSpec as P

    t = trace_mod.install(Tracer(worker_index=0))
    mesh = make_mesh("data:-1")

    def double(x):
        return x * 2

    fn = shard_map(double, mesh, in_specs=(P("data"),), out_specs=P("data"))
    out = fn(jnp.ones((8, 2)))
    assert out.shape == (8, 2)
    assert "comm.shmap.double" in t.summary()
    # call sites that run their own comm region can opt out
    bare = shard_map(double, mesh, in_specs=(P("data"),),
                     out_specs=P("data"), comm_label=None)
    t.take_summary()
    bare(jnp.ones((8, 2)))
    assert "comm.shmap.double" not in t.summary()


def _write_fleet_journal(tmp_path):
    base = str(tmp_path / "fleet.jsonl")
    j = Journal(base, plane="coordinator")
    j.emit("register", worker=0)
    j.emit("straggler_detect", worker=1, epoch=2, skew=2.5,
           phase="infeed", step_s=0.9, fleet_step_s=0.36, threshold=1.5)
    j.emit("fleet_skew", epoch=2, n_workers=2, max_skew=2.5, straggler=1,
           ranks={"0": {"step_s": 0.36, "skew": 0.4, "phase": "dispatch",
                        "straggler": False, "epoch": 2,
                        "offset_s": 0.0001},
                  "1": {"step_s": 0.9, "skew": 2.5, "phase": "infeed",
                        "straggler": True, "epoch": 2, "barrier_s": 0.01,
                        "offset_s": -0.002}})
    j.emit("comm", plane="train", worker=1, epoch=2,
           kinds={"ring_attention": {"calls": 4, "bytes": 4096}})
    j.emit("straggler_clear", worker=1, epoch=7, skew=1.1,
           straggler_s=12.5, since_epoch=2)
    j.close()
    return base


def test_obs_cli_fleet_renders_table_and_excursions(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main

    base = _write_fleet_journal(tmp_path)
    assert main(["fleet", "--journal", base]) == 0
    out = capsys.readouterr().out
    assert "fleet skew" in out
    assert "STRAGGLER" in out or "straggler: worker 1" in out
    assert "infeed" in out
    assert "ring_attention" in out
    # machine-readable: excursion carries detect AND clear coordinates
    assert main(["fleet", "--journal", base, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    exc = doc["excursions"][0]
    assert exc["worker"] == 1 and exc["phase"] == "infeed"
    assert exc["clear_epoch"] == 7 and exc["straggler_s"] == 12.5
    assert doc["ranks"]["1"]["skew"] == 2.5
    assert doc["comm"]["ring_attention"]["bytes"] == 4096


def test_obs_cli_fleet_clean_miss(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main

    base = str(tmp_path / "empty.jsonl")
    j = Journal(base, plane="train")
    j.emit("worker_start", worker=0)
    j.close()
    assert main(["fleet", "--journal", base]) == 1
    assert "no fleet events" in capsys.readouterr().out


def test_obs_cli_top_renders_fleet_panel(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main

    base = _write_fleet_journal(tmp_path)
    assert main(["top", "--once", "--journal", base]) == 0
    out = capsys.readouterr().out
    assert "fleet" in out
    assert "STRAGGLER" in out


def test_obs_cli_summary_renders_fleet_section(tmp_path, capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main

    base = _write_fleet_journal(tmp_path)
    assert main(["summary", "--journal", base]) == 0
    assert "fleet skew" in capsys.readouterr().out
    assert main(["summary", "--journal", base, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["fleet"]["ranks"]["1"]["straggler"] is True


def test_obs_cli_trace_renders_offset_aligned(tmp_path, capsys):
    """Two writers whose wall clocks disagree by 10s: the raw merge
    interleaves wrong, the offset-aligned trace restores causality —
    and --json preserves the raw clocks untouched."""
    import time as _time

    from shifu_tensorflow_tpu.obs.__main__ import main

    base = str(tmp_path / "aligned.jsonl")
    now = _time.time()
    coord = Journal(base, plane="coordinator")
    # worker 1's clock runs 10s BEHIND the coordinator: offset=+10
    w1 = Journal(base + ".w1", plane="train", worker=1)
    w1.set_offset(10.0)
    # hand-build timestamps: the coordinator publishes the epoch at
    # now+1; the worker's step_breakdown happened at now+0.5 REAL time
    # but its skewed clock wrote now-9.5
    coord._file = None  # force open at emit
    import json as _json
    import os as _os

    def raw(journal_path, rec):
        with open(journal_path, "a") as f:
            f.write(_json.dumps(rec) + "\n")

    raw(base, {"ts": now + 1.0, "seq": 0, "event": "epoch_summary",
               "plane": "coordinator", "epoch": 3})
    raw(base + ".w1", {"ts": now - 9.5, "seq": 0, "event":
                       "step_breakdown", "plane": "train", "worker": 1,
                       "epoch": 3, "offset": 10.0, "steps": 4})
    coord.close()
    w1.close()
    assert main(["trace", "1:3", "--journal", base]) == 0
    out = capsys.readouterr().out
    assert "offset-aligned" in out
    # aligned: the worker event (+0.5) renders BEFORE the coordinator's
    # (+1.0) despite its raw ts sorting 10.5s earlier
    lines = [ln for ln in out.splitlines() if "+" in ln]
    bd = next(i for i, ln in enumerate(lines) if "step_breakdown" in ln)
    es = next(i for i, ln in enumerate(lines) if "epoch_summary" in ln)
    assert bd < es
    assert main(["trace", "1:3", "--journal", base, "--json"]) == 0
    docs = [json.loads(ln) for ln in
            capsys.readouterr().out.splitlines()]
    w1_ev = next(d for d in docs if d["event"] == "step_breakdown")
    assert w1_ev["ts"] == pytest.approx(now - 9.5)  # raw clock preserved
    assert w1_ev["offset"] == 10.0
