"""Multi-tenant serve fleet drills: weighted-fair device sharing,
budget-bounded LRU admission/eviction, `/score/<model>` routing, and the
acceptance gates ISSUE 9 pins — two-model e2e bit-identity, eviction +
re-admission without a failed request on the surviving tenant, and the
fairness isolation drill (one tenant at sustained overload, the other's
p99 and shed rate inside bounds)."""

from __future__ import annotations

import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

from shifu_tensorflow_tpu.export.saved_model import (
    NATIVE_WEIGHTS,
    export_model,
)
from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.serve.batcher import MicroBatcher, ShedLoad
from shifu_tensorflow_tpu.serve.config import ServeConfig
from shifu_tensorflow_tpu.serve.model_store import ModelStore
from shifu_tensorflow_tpu.serve.server import ScoringServer
from shifu_tensorflow_tpu.serve.tenancy import store as tenancy_store
from shifu_tensorflow_tpu.serve.tenancy.scheduler import DeviceScheduler
from shifu_tensorflow_tpu.serve.tenancy.store import (
    AdmissionRefused,
    MultiModelStore,
    UnknownModel,
)
from shifu_tensorflow_tpu.train.trainer import Trainer

N_FEATURES = 6


def _model_config():
    return ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05}}}
    )


def _export(tmp_dir: str, seed: int = 0) -> str:
    export_model(tmp_dir, Trainer(_model_config(), N_FEATURES, seed=seed))
    return tmp_dir


def _rows(n: int, seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).random((n, N_FEATURES)).astype(
        np.float32
    )


@pytest.fixture()
def models_dir(tmp_path):
    """Two distinguishable tenants (different seeds → different
    weights → different scores) under one models root."""
    root = tmp_path / "models"
    root.mkdir()
    _export(str(root / "alpha"), seed=1)
    _export(str(root / "beta"), seed=2)
    return str(root)


def _bundle_bytes(path: str) -> int:
    # recursive, matching MultiModelStore._bundle_cost (SavedModel
    # exports keep weights under variables/)
    return sum(os.path.getsize(os.path.join(root, f))
               for root, _dirs, files in os.walk(path) for f in files)


def _post(port: int, payload: dict, path="/score"):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=60.0)
    try:
        c.request("POST", path, json.dumps(payload),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        return r.status, dict(r.getheaders()), json.loads(r.read())
    finally:
        c.close()


def _get(port: int, path: str):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=60.0)
    try:
        c.request("GET", path)
        r = c.getresponse()
        return r.status, r.read().decode()
    finally:
        c.close()


# ---------------------------------------------------- scheduler (DRR)


def _mk_batcher(sched, name, weight, score_s=0.002, max_queue_rows=512):
    """Synthetic-tenant batcher: a sleep-based scorer with deterministic
    per-dispatch cost (no jax — the scheduler's properties are about
    arbitration, not XLA)."""

    def score(rows):
        time.sleep(score_s)
        return np.zeros((rows.shape[0], 1), np.float32)

    return MicroBatcher(
        score, max_batch=8, max_delay_s=0.001,
        max_queue_rows=max_queue_rows, scheduler=sched, model=name,
        weight=weight,
    )


def test_scheduler_single_tenant_is_work_conserving():
    """With one tenant, the shared scheduler serves at full speed — no
    reserved shares, no idle quanta."""
    sched = DeviceScheduler()
    b = _mk_batcher(sched, "solo", 1.0, score_s=0.0)
    try:
        out = b.submit(_rows(5))
        assert out.shape[0] == 5
        for _ in range(20):
            b.submit(_rows(3))
        totals = sched.dispatch_totals()
        assert totals["solo"]["rows"] >= 65
    finally:
        b.close(drain=True)
        sched.close()


def test_scheduler_shares_rows_by_weight_under_contention():
    """Two backlogged tenants at weights 3:1 split dispatched device
    rows ≈ 3:1 — the deficit round-robin property.  BARRIER-gated, not
    timed: a semaphore inside the scorer holds the device thread, both
    backlogs build to a known depth while nothing drains, then exactly
    32 dispatches are released and counted — the measured window is
    guaranteed fully contended however a 2-core host schedules
    threads."""
    sched = DeviceScheduler()
    # deepen the staged handoff for THIS arbitration drill: with the
    # default MAX_STAGED=2 the ring can catch a tenant mid-refill (the
    # re-pick races the pack thread for the lock after every dispatch),
    # and an empty-at-visit queue forfeits its deficit — on a fast idle
    # host that couples the measured ratio to lock-scheduling luck, not
    # to DRR.  The staging bound's own property (shed-before-queue) has
    # its own tests; this one is about weight arbitration over queues
    # that are genuinely never dry.
    sched.MAX_STAGED = 8
    gate = threading.Semaphore(0)
    dispatched = [0]
    count_lock = threading.Lock()

    def mk(name, weight):
        def score(rows):
            gate.acquire()
            with count_lock:
                dispatched[0] += 1
            return np.zeros((rows.shape[0], 1), np.float32)

        return MicroBatcher(
            score, max_batch=8, max_delay_s=0.001, max_queue_rows=4096,
            scheduler=sched, model=name, weight=weight)

    heavy = mk("heavy", 3.0)
    light = mk("light", 1.0)
    submitters = []
    try:
        # 40 blocked 8-row submits per tenant: backlog far deeper than
        # the measured window, so neither queue can run dry mid-window
        for b in (heavy, light):
            for i in range(40):
                t = threading.Thread(
                    target=lambda b=b, i=i: b.submit(
                        _rows(8, seed=i), timeout_s=120.0),
                    daemon=True)
                t.start()
                submitters.append(t)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and (
                heavy.queued_rows() < 200 or light.queued_rows() < 200):
            time.sleep(0.005)
        assert heavy.queued_rows() >= 200 and light.queued_rows() >= 200
        # release exactly 32 gated dispatches against the standing
        # backlogs and wait until the device thread has consumed them
        for _ in range(32):
            gate.release()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and dispatched[0] < 32:
            time.sleep(0.005)
        assert dispatched[0] >= 32
        totals = sched.dispatch_totals()
        heavy_rows = totals["heavy"]["rows"]
        light_rows = totals["light"]["rows"]
        assert light_rows > 0, totals
        ratio = heavy_rows / light_rows
        # 3:1 nominal over a fully-backlogged DRR window; slack covers
        # only the pre-gate packing order, not thread-scheduling luck
        assert 1.8 <= ratio <= 5.0, (heavy_rows, light_rows, ratio)
    finally:
        # open the gate wide so the remaining backlog drains and every
        # blocked submitter returns before teardown
        for _ in range(200):
            gate.release()
        for t in submitters:
            t.join(timeout=30.0)
        heavy.close(drain=True)
        light.close(drain=True)
        sched.close()


def test_fairness_isolation_overload_cannot_starve_peer():
    """The ROADMAP item-3 gate as a tier-1 drill with synthetic scoring:
    tenant A driven to sustained overload (deep backlog, shedding under
    its own 429 plane), tenant B paced — B sheds nothing and every B
    request completes in bounded time.

    BARRIER-gated like test_serving's overload drill: A's scorer holds
    the (shared) device thread on an Event while A's flood
    arithmetically overruns its 64-row admission bound, so the shed
    proof cannot race thread scheduling on a 2-core host.  B's latency
    is measured only AFTER the gate opens — with one shared device
    thread, a closed gate stalls B by construction, which would measure
    the barrier, not the scheduler.  The old 2×-solo-baseline p99 bound
    flaked there for exactly that reason (microsecond baseline, shared-
    core jitter); the property under test is starvation-freedom, so the
    bound is an absolute one a starved tenant (stuck behind A's
    standing multi-second backlog) still cannot meet."""
    sched = DeviceScheduler()
    release = threading.Event()

    def a_score(rows):
        release.wait(30.0)
        return np.zeros((rows.shape[0], 1), np.float32)

    a = MicroBatcher(a_score, max_batch=8, max_delay_s=0.001,
                     max_queue_rows=64, scheduler=sched, model="a",
                     weight=1.0)
    b = _mk_batcher(sched, "b", 1.0, score_s=0.0)
    stop = threading.Event()
    a_sheds = [0]

    def flood():
        while not stop.is_set():
            try:
                a.submit(_rows(16), timeout_s=120.0)
            except ShedLoad:
                a_sheds[0] += 1
                time.sleep(0.0005)

    # 8 × 16 = 128 in-flight rows against the closed gate: the 64-row
    # queue plus pipeline depth overruns whatever the thread order
    floods = [threading.Thread(target=flood, daemon=True)
              for _ in range(8)]
    for t in floods:
        t.start()
    deadline = time.monotonic() + 30.0
    while a_sheds[0] < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    release.set()
    b_sheds = 0
    lat = []
    try:
        for i in range(40):
            t0 = time.monotonic()
            b.submit(_rows(1, seed=i), timeout_s=30.0)
            lat.append(time.monotonic() - t0)
            time.sleep(0.005)
    except ShedLoad:
        b_sheds += 1
        raise
    finally:
        stop.set()
        for t in floods:
            t.join(timeout=30.0)
    totals = sched.dispatch_totals()
    a.close(drain=False)
    b.close(drain=True)
    sched.close()
    assert b_sheds == 0
    assert a_sheds[0] > 0, "A never overloaded — the drill didn't drill"
    assert totals["a"]["rows"] > totals["b"]["rows"], totals
    lat.sort()
    contended_p99 = lat[int(0.99 * (len(lat) - 1))]
    assert contended_p99 <= 5.0, (
        f"B p99 {contended_p99 * 1000:.1f} ms under A's overload — "
        f"starved behind A's backlog"
    )


# ------------------------------------------------ store units (no HTTP)


def _mt_config(models_dir: str, **kw) -> ServeConfig:
    defaults = dict(models_dir=models_dir, port=0, max_batch=64,
                    max_delay_ms=2.0, max_queue_rows=256,
                    reload_poll_ms=0)
    defaults.update(kw)
    return ServeConfig(**defaults)


def test_store_discovers_and_admits_within_budget(models_dir):
    store = MultiModelStore(_mt_config(models_dir), warm=False)
    try:
        assert store.admitted() == ["alpha", "beta"]
        t = store.acquire("alpha")
        out = t.batcher.submit(_rows(4))
        assert out.shape[0] == 4
        listing = store.models()
        assert listing["alpha"]["state"] == "admitted"
        assert listing["alpha"]["model_verified"] is True
        with pytest.raises(UnknownModel):
            store.acquire("nope")
        # path traversal can never resolve
        with pytest.raises(UnknownModel):
            store.acquire("..")
    finally:
        store.close()


def test_store_budget_admits_lru_evicts_and_readmits(models_dir):
    a_cost = _bundle_bytes(os.path.join(models_dir, "alpha"))
    b_cost = _bundle_bytes(os.path.join(models_dir, "beta"))
    # fits either alone, never both
    budget_mb = (max(a_cost, b_cost) * 1.5) / (1 << 20)
    store = MultiModelStore(_mt_config(models_dir,
                                       model_budget_mb=budget_mb),
                            warm=False)
    try:
        assert store.admitted() == ["alpha"]  # eager in name order
        # admit-on-demand evicts the LRU tenant (alpha)
        t_b = store.acquire("beta")
        assert t_b.batcher.submit(_rows(3)).shape[0] == 3
        assert store.admitted() == ["beta"]
        listing = store.models()
        assert listing["alpha"]["state"] == "cold"
        # and back again
        t_a = store.acquire("alpha")
        assert t_a.batcher.submit(_rows(2)).shape[0] == 2
        assert store.admitted() == ["alpha"]
    finally:
        store.close()


def test_store_refuses_bundle_larger_than_whole_budget(models_dir):
    store = MultiModelStore(
        _mt_config(models_dir, model_budget_mb=1e-6), warm=False)
    try:
        assert store.admitted() == []
        with pytest.raises(AdmissionRefused, match="budget"):
            store.acquire("alpha", wait_s=30.0)
    finally:
        store.close()


def test_corrupt_tenant_refused_while_others_serve(models_dir,
                                                   monkeypatch):
    """A corrupt bundle refuses ONLY its tenant (verify-before-admit per
    tenant); after a clean re-export it re-admits on demand."""
    monkeypatch.setattr(tenancy_store, "_REFUSAL_HOLDDOWN_S", 0.0)
    beta_weights = os.path.join(models_dir, "beta", NATIVE_WEIGHTS)
    good = open(beta_weights, "rb").read()
    with open(beta_weights, "r+b") as f:  # flip a byte under the manifest
        f.seek(100)
        byte = f.read(1)
        f.seek(100)
        f.write(bytes([byte[0] ^ 0xFF]))
    store = MultiModelStore(_mt_config(models_dir), warm=False)
    try:
        assert store.admitted() == ["alpha"]
        assert store.models()["beta"]["state"] == "refused"
        with pytest.raises(AdmissionRefused):
            store.acquire("beta", wait_s=30.0)
        # alpha unaffected throughout
        assert store.acquire("alpha").batcher.submit(
            _rows(2)).shape[0] == 2
        # clean artifact lands → re-admits on demand
        with open(beta_weights, "wb") as f:
            f.write(good)
        t = store.acquire("beta", wait_s=30.0)
        assert t.batcher.submit(_rows(2)).shape[0] == 2
    finally:
        store.close()


def test_deleted_tenant_prunes_back_to_404(models_dir):
    """A bundle directory deleted out from under an UNADMITTED tenant
    goes back to UnknownModel (404), not a doomed admission loop; an
    admitted tenant keeps serving from memory."""
    import shutil

    a_cost = _bundle_bytes(os.path.join(models_dir, "alpha"))
    b_cost = _bundle_bytes(os.path.join(models_dir, "beta"))
    budget_mb = (max(a_cost, b_cost) * 1.5) / (1 << 20)
    store = MultiModelStore(_mt_config(models_dir,
                                       model_budget_mb=budget_mb),
                            warm=False)
    try:
        assert store.admitted() == ["alpha"]  # beta stays cold
        shutil.rmtree(os.path.join(models_dir, "beta"))
        with pytest.raises(UnknownModel):
            store.acquire("beta")
        assert "beta" not in store.models()  # pruned from the listing
        # alpha (admitted) unaffected
        assert store.acquire("alpha").batcher.submit(
            _rows(2)).shape[0] == 2
    finally:
        store.close()


def test_cold_tenant_width_raises_body_bound(models_dir):
    """The fleet-wide body bound sees a DISCOVERED tenant's feature
    width (read off the arch file) even before admission — a wide cold
    model's first request must not be 413'd below what its own
    single-model server would accept."""
    store = MultiModelStore(
        _mt_config(models_dir, model_budget_mb=1e-6), warm=False)
    try:
        assert store.admitted() == []  # nothing fits the budget
        assert store.max_num_features() == N_FEATURES
    finally:
        store.close()


def test_fingerprint_cache_skips_manifest_reread(models_dir,
                                                 monkeypatch):
    """Satellite: an unchanged manifest mtime costs one stat per poll,
    not a read+parse — the idle-poll cost that scales with hundreds of
    tenants."""
    from shifu_tensorflow_tpu.serve import model_store as ms_mod
    from shifu_tensorflow_tpu.utils import fs

    # collapse the stability window the cache waits out before trusting
    # a candidate (it guards same-granule republishes on coarse-mtime
    # filesystems; this test's mtimes are controlled)
    monkeypatch.setattr(ms_mod, "_FP_CONFIRM_S", 0.0)
    store = ModelStore(os.path.join(models_dir, "alpha"),
                       poll_interval_s=0)
    try:
        reads = [0]
        real_read_text = fs.read_text

        def counting_read_text(path):
            reads[0] += 1
            return real_read_text(path)

        monkeypatch.setattr(fs, "read_text", counting_read_text)
        fp1 = store._fingerprint()
        assert reads[0] <= 1  # the candidate read
        assert store._fingerprint() == fp1  # the confirming read
        confirmed = reads[0]
        assert confirmed <= 2
        for _ in range(5):
            assert store._fingerprint() == fp1
        assert reads[0] == confirmed, \
            "confirmed unchanged mtime re-read the manifest"
        # a re-publish (fresh mtime) must bust the cache
        mpath = os.path.join(
            models_dir, "alpha",
            "shifu_tpu_export.manifest.json")
        st = os.stat(mpath)
        os.utime(mpath, ns=(st.st_atime_ns, st.st_mtime_ns + 10_000_000))
        fp2 = store._fingerprint()
        assert fp2 != fp1
        assert reads[0] == confirmed + 1
    finally:
        store.close()


# --------------------------------------------------------- HTTP e2e


@pytest.fixture()
def mt_server(models_dir):
    cfg = _mt_config(models_dir, reload_poll_ms=50)
    with ScoringServer(cfg) as srv:
        srv.start()
        yield srv


def test_two_model_routing_bit_identical_to_single_model(
        mt_server, models_dir, tmp_path):
    """Acceptance: /score/<model> routes to the right verified bundle,
    and the scores are bit-identical to a single-model server on the
    same bundle (same rounding, same bytes on the wire)."""
    x = _rows(7, seed=42)
    multi = {}
    for name in ("alpha", "beta"):
        status, _, body = _post(mt_server.port, {"rows": x.tolist()},
                                path=f"/score/{name}")
        assert status == 200, body
        assert body["model"] == name
        multi[name] = body["scores"]
    # the two tenants are different models
    assert multi["alpha"] != multi["beta"]
    for name in ("alpha", "beta"):
        cfg = ServeConfig(model_dir=os.path.join(models_dir, name),
                          port=0, max_batch=64, max_delay_ms=2.0,
                          max_queue_rows=256, reload_poll_ms=0)
        with ScoringServer(cfg) as single:
            single.start()
            status, _, body = _post(single.port, {"rows": x.tolist()})
        assert status == 200
        assert body["scores"] == multi[name], name


def test_unknown_model_404_and_listing_and_health_detail(mt_server):
    status, _, body = _post(mt_server.port,
                            {"rows": _rows(1).tolist()},
                            path="/score/nope")
    assert status == 404 and "unknown model" in body["error"]
    status, text = _get(mt_server.port, "/models")
    assert status == 200
    models = json.loads(text)["models"]
    assert set(models) == {"alpha", "beta"}
    assert all(m["state"] == "admitted" for m in models.values())
    # fleet healthz carries the per-model split
    status, text = _get(mt_server.port, "/healthz")
    health = json.loads(text)
    assert status == 200 and health["ok"]
    assert health["models_admitted"] == 2
    # per-model detail endpoint
    status, text = _get(mt_server.port, "/healthz/alpha")
    detail = json.loads(text)
    assert status == 200 and detail["ok"] and detail["model"] == "alpha"
    assert detail["model_verified"] is True
    status, _ = _get(mt_server.port, "/healthz/nope")
    assert status == 404


def test_legacy_score_routes_single_admitted_model(tmp_path):
    """Acceptance: legacy /score (no model segment) keeps working
    against a store with one admitted model; with two it asks the
    client to name one."""
    root = tmp_path / "one"
    root.mkdir()
    _export(str(root / "only"), seed=3)
    cfg = _mt_config(str(root))
    with ScoringServer(cfg) as srv:
        srv.start()
        x = _rows(4)
        status, _, body = _post(srv.port, {"rows": x.tolist()})
        assert status == 200 and body["model"] == "only"


def test_legacy_score_ambiguous_with_two_models(mt_server):
    status, _, body = _post(mt_server.port,
                            {"rows": _rows(1).tolist()})
    assert status == 400
    assert "/score/<model>" in body["error"]


def test_per_model_metrics_labels_and_fleet_gauges(mt_server):
    _post(mt_server.port, {"rows": _rows(3).tolist()},
          path="/score/alpha")
    _post(mt_server.port, {"rows": _rows(2).tolist()},
          path="/score/beta")
    status, text = _get(mt_server.port, "/metrics")
    assert status == 200
    assert 'stpu_serve_requests_total{model="alpha"} 1' in text
    assert 'stpu_serve_rows_total{model="alpha"} 3' in text
    assert 'stpu_serve_requests_total{model="beta"} 1' in text
    assert 'stpu_serve_rows_total{model="beta"} 2' in text
    assert "stpu_serve_fleet_models_admitted 2" in text
    assert "stpu_serve_fleet_admissions_total 2" in text
    # histogram series carry the label merged with their own labels
    assert ('stpu_serve_request_latency_seconds'
            '{quantile="0.99",model="alpha"}') in text
    # valid exposition format: ONE "# TYPE" line per metric family even
    # with several per-tenant registries merged (strict parsers reject
    # a scrape with duplicate TYPE lines)
    type_lines = [l for l in text.splitlines() if l.startswith("# TYPE ")]
    families = [l.split()[2] for l in type_lines]
    assert len(families) == len(set(families)), sorted(
        f for f in families if families.count(f) > 1)
    # 404s land on the unrouted surface
    _post(mt_server.port, {"rows": _rows(1).tolist()},
          path="/score/nope")
    _, text = _get(mt_server.port, "/metrics")
    assert 'stpu_serve_errors_total{model="_unrouted"} 1' in text
    assert "stpu_serve_fleet_unknown_model_total 1" in text


def test_budget_eviction_e2e_no_failed_request_on_survivor(
        models_dir, tmp_path):
    """Acceptance: under a memory budget that fits only one model, LRU
    eviction + re-admission works end-to-end while concurrent requests
    on the tenant being ADMITTED (the survivor of the swap) all
    succeed."""
    a_cost = _bundle_bytes(os.path.join(models_dir, "alpha"))
    b_cost = _bundle_bytes(os.path.join(models_dir, "beta"))
    budget_mb = (max(a_cost, b_cost) * 1.5) / (1 << 20)
    cfg = _mt_config(models_dir, model_budget_mb=budget_mb)
    with ScoringServer(cfg) as srv:
        srv.start()
        x = _rows(5, seed=7)
        # alpha admitted eagerly; first beta request admits-on-demand,
        # evicting alpha
        status, _, a1 = _post(srv.port, {"rows": x.tolist()},
                              path="/score/alpha")
        assert status == 200
        failures = []
        done = threading.Event()

        def hammer_beta():
            # concurrent requests on beta from the moment its admission
            # starts: every one must succeed (cold-start guard waits)
            for i in range(10):
                s, _, body = _post(srv.port, {"rows": x.tolist()},
                                   path="/score/beta")
                if s != 200:
                    failures.append((s, body))
            done.set()

        t = threading.Thread(target=hammer_beta, daemon=True)
        t.start()
        assert done.wait(120.0)
        t.join()
        assert not failures, failures
        status, text = _get(srv.port, "/healthz/alpha")
        assert status == 503  # evicted
        # re-admission of alpha scores identically to before eviction
        status, _, a2 = _post(srv.port, {"rows": x.tolist()},
                              path="/score/alpha")
        assert status == 200
        assert a2["scores"] == a1["scores"]
        # tenancy churn is visible on the fleet surface
        _, text = _get(srv.port, "/metrics")
        fleet = {l.split(" ")[0]: float(l.rsplit(" ", 1)[1])
                 for l in text.splitlines()
                 if l.startswith("stpu_serve_fleet_")}
        assert fleet["stpu_serve_fleet_evictions_total"] >= 2
        assert fleet["stpu_serve_fleet_admissions_total"] >= 3


@pytest.fixture()
def obs_env(tmp_path):
    """Serve-plane obs journal + watchdog; uninstalls on teardown so
    the module-global hooks never leak into the rest of the suite."""
    from shifu_tensorflow_tpu.obs import install_obs
    from shifu_tensorflow_tpu.obs import journal as journal_mod
    from shifu_tensorflow_tpu.obs import slo as slo_mod
    from shifu_tensorflow_tpu.obs import trace as trace_mod
    from shifu_tensorflow_tpu.obs.config import ObsConfig

    base = str(tmp_path / "tenancy-journal.jsonl")
    install_obs(ObsConfig(enabled=True, journal_path=base),
                plane="serve")
    yield base
    trace_mod.uninstall()
    journal_mod.uninstall()
    slo_mod.uninstall()


def test_tenancy_events_and_slo_signals(models_dir, obs_env):
    """The journal carries the model dimension end-to-end (model_admit /
    model_evict / serve_batch), and admissions register per-tenant SLO
    signals on the active watchdog."""
    from shifu_tensorflow_tpu.obs import slo as obs_slo
    from shifu_tensorflow_tpu.obs.journal import read_events

    a_cost = _bundle_bytes(os.path.join(models_dir, "alpha"))
    b_cost = _bundle_bytes(os.path.join(models_dir, "beta"))
    budget_mb = (max(a_cost, b_cost) * 1.5) / (1 << 20)
    cfg = _mt_config(models_dir, model_budget_mb=budget_mb)
    with ScoringServer(cfg) as srv:
        srv.start()
        _post(srv.port, {"rows": _rows(2).tolist()},
              path="/score/alpha")
        _post(srv.port, {"rows": _rows(2).tolist()},
              path="/score/beta")  # evicts alpha
        wd = obs_slo.active()
        assert wd is not None
        state = wd.state()
        assert "serve_p99_s:beta" in state
        assert "serve_shed_rate:beta" in state
        # an evicted tenant's signals (and gauges) leave with it — no
        # frozen last-known p99 for a model that isn't serving
        assert "serve_p99_s:alpha" not in state
    events = read_events(obs_env)
    kinds = {(e["event"], e.get("model")) for e in events}
    assert ("model_admit", "alpha") in kinds
    assert ("model_admit", "beta") in kinds
    assert ("model_evict", "alpha") in kinds
    batches = [e for e in events if e["event"] == "serve_batch"]
    assert {e.get("model") for e in batches} >= {"alpha"}


def test_obs_cli_renders_per_model_serve_table(models_dir, obs_env,
                                               capsys):
    """`obs summary` aggregates the model dimension into a per-model
    serve table — the fleet view /metrics (per-process) cannot give."""
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    cfg = _mt_config(models_dir)
    with ScoringServer(cfg) as srv:
        srv.start()
        _post(srv.port, {"rows": _rows(3).tolist()},
              path="/score/alpha")
        _post(srv.port, {"rows": _rows(4).tolist()},
              path="/score/beta")
    rc = obs_main(["summary", "--journal", obs_env])
    out = capsys.readouterr().out
    assert rc == 0
    assert "model" in out
    assert "alpha" in out and "beta" in out
    rc = obs_main(["summary", "--journal", obs_env, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["serve"]["models"]["alpha"]["admits"] == 1
    assert doc["serve"]["models"]["beta"]["rows"] >= 4
