"""Sharded-parameter SPMD (parallel/sharding.py): regex partition rules
over a 2D data×model mesh, per-shard checkpoints, and mesh-aware export.

The drills the acceptance criteria pin:

- rule matching: ordered ``(regex, PartitionSpec)`` first-match-wins over
  '/'-joined pytree paths, scalars never partition, unmatched leaves fall
  back to their ``nn.with_partitioning`` annotation, non-divisible dims
  degrade to replication instead of erroring;
- checkpoint mesh migration: a generation saved under ``data:2,model:2``
  restores bit-identically under ``data:4`` (and vice versa), and a
  SAME-mesh restore performs ZERO full-parameter gathers (pinned via the
  checkpointer's restore stats — no host-side model-dim concat);
- per-shard integrity: one corrupt shard condemns the whole generation
  (quarantine every file of it) and restore falls back to the previous
  verified generation;
- AOT mesh fingerprint: executables compiled under one mesh fall back
  (``kind=aot_fallback``, ``aot_error`` naming ``mesh_shape``) beside a
  differently-sharded bundle, scoring bit-identically via live compile.
"""

from __future__ import annotations

import json
import os
import shutil

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.export import aot as aot_mod
from shifu_tensorflow_tpu.export.eval_model import EvalModel
from shifu_tensorflow_tpu.export.saved_model import (
    NATIVE_MANIFEST,
    NATIVE_WEIGHTS,
    export_model,
    export_native_bundle,
    load_native_weights,
    native_weights_shard_name,
)
from shifu_tensorflow_tpu.obs import compile as compile_mod
from shifu_tensorflow_tpu.obs import journal as journal_mod
from shifu_tensorflow_tpu.obs.journal import Journal, read_events
from shifu_tensorflow_tpu.parallel import sharding as sh
from shifu_tensorflow_tpu.parallel.mesh import (
    MESH_SHAPE_KEY,
    make_mesh,
    mesh_coord,
    mesh_shape_fingerprint,
    parse_mesh_shape,
)
from shifu_tensorflow_tpu.train.checkpoint import NpzCheckpointer
from shifu_tensorflow_tpu.train.trainer import Trainer

N_FEATURES = 8


@pytest.fixture(autouse=True)
def _clean_hooks():
    yield
    compile_mod.uninstall()
    journal_mod.uninstall()


def _mesh(spec: str, n: int):
    return make_mesh(spec, devices=jax.devices()[:n])


def _model_config():
    return ModelConfig.from_json(
        {"train": {"numTrainEpochs": 1, "params": {
            "NumHiddenLayers": 1, "NumHiddenNodes": [8],
            "ActivationFunc": ["relu"], "LearningRate": 0.05,
            "Optimizer": "adam",
            "EmbeddingColumnNums": [0, 1], "EmbeddingHashSize": 64,
            "EmbeddingDim": 4,
        }}})


def _trainer(mesh=None, seed: int = 7) -> Trainer:
    return Trainer(_model_config(), N_FEATURES, mesh=mesh, seed=seed)


def _gathered(state) -> list[np.ndarray]:
    return [np.asarray(v) for v in jax.tree_util.tree_leaves(
        sh.gather_params(state.params))]


def _table_leaf(params):
    flat, _ = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=sh._is_partitioned)
    for path, leaf in flat:
        if sh._path_str(path).endswith("/table"):
            return sh._leaf_value(leaf)
    raise AssertionError("no embedding table in the param tree")


# ------------------------------------------------------- mesh parsing


def test_parse_mesh_shape_rejects_indivisible_model_axis():
    """model>1 that does not divide the device count refuses with an
    actionable error naming the config key, not a reshape traceback."""
    with pytest.raises(ValueError) as e:
        parse_mesh_shape("data:-1,model:3", 8)
    msg = str(e.value)
    assert MESH_SHAPE_KEY in msg
    assert "model axis of 3" in msg and "8" in msg


def test_parse_mesh_shape_errors_name_the_key():
    for spec, n in (("data:3", 8), ("data:-1,model:-1", 8)):
        with pytest.raises(ValueError) as e:
            parse_mesh_shape(spec, n)
        assert MESH_SHAPE_KEY in str(e.value) or "-1" in str(e.value)


def test_mesh_coord_row_major():
    assert mesh_coord("data:2,model:2", 4, 0) == {"data": 0, "model": 0}
    assert mesh_coord("data:2,model:2", 4, 1) == {"data": 0, "model": 1}
    assert mesh_coord("data:2,model:2", 4, 2) == {"data": 1, "model": 0}
    assert mesh_coord("data:-1,model:2", 8, 5) == {"data": 2, "model": 1}


def test_mesh_shape_fingerprint_collapses_data_parallel():
    """Pure data-parallel degree never changes the weights layout, so
    every model:1 mesh fingerprints as unsharded — serve artifacts stay
    portable across data-parallel widths."""
    assert mesh_shape_fingerprint(None) == "unsharded"
    assert mesh_shape_fingerprint(_mesh("data:4", 4)) == "unsharded"
    assert mesh_shape_fingerprint(_mesh("data:2,model:1", 2)) == "unsharded"
    assert (mesh_shape_fingerprint(_mesh("data:2,model:2", 4))
            == "data:2,model:2")


# ------------------------------------------------------ partition rules


def test_match_partition_rules_first_match_wins_and_scalars_replicate():
    mesh = _mesh("data:2,model:2", 4)
    params = {
        "emb": {"table": np.ones((8, 4), np.float32)},
        "dense": {"kernel": np.ones((4, 4), np.float32)},
        "step": np.float32(3.0),
    }
    rules = (
        (r"(^|/)table$", P("model", None)),
        (r".*", P()),  # catch-all AFTER the table rule: must not shadow
    )
    specs = sh.match_partition_rules(rules, params, mesh)
    assert specs["emb"]["table"].spec == P("model", None)
    assert specs["dense"]["kernel"].spec == P()
    assert specs["step"].spec == P()


def test_match_partition_rules_degrades_indivisible_dims():
    """A table whose rows the model axis cannot divide replicates that
    dim instead of erroring — small tables stay replicated, big ones
    shard."""
    mesh = _mesh("data:2,model:2", 4)
    params = {"emb": {"table": np.ones((5, 4), np.float32)}}
    specs = sh.match_partition_rules(
        sh.DEFAULT_PARTITION_RULES, params, mesh)
    assert specs["emb"]["table"].spec == P(None, None)


def test_match_partition_rules_absent_axis_replicates():
    mesh = _mesh("data:4", 4)  # no model axis at all
    params = {"emb": {"table": np.ones((8, 4), np.float32)}}
    specs = sh.match_partition_rules(
        sh.DEFAULT_PARTITION_RULES, params, mesh)
    assert specs["emb"]["table"].spec == P(None, None)


def test_unmatched_leaf_falls_back_to_partitioned_annotation():
    nn = pytest.importorskip("flax.linen")
    mesh = _mesh("data:2,model:2", 4)
    boxed = nn.Partitioned(np.ones((8, 4), np.float32),
                           names=("model", None))
    specs = sh.match_partition_rules(
        ((r"(^|/)nothing_matches$", P()),), {"w": boxed}, mesh)
    assert specs["w"].spec == P("model", None)


def test_trainer_shards_embedding_table_on_model_axis():
    tr = _trainer(mesh=_mesh("data:2,model:2", 4))
    table = _table_leaf(tr.state.params)
    assert sh.model_shard_info(table) == (0, 2)
    # per-device params footprint drops vs replication: each model rank
    # holds half the table (the capacity the accountant's params bucket
    # reports per device)
    from shifu_tensorflow_tpu.obs.memory import (
        tree_device_bytes,
        tree_per_device_bytes,
    )

    per_dev = tree_per_device_bytes(tr.state.params)
    assert per_dev and max(per_dev.values()) < tree_device_bytes(
        tr.state.params)


# -------------------------------------------- per-shard checkpointing


def test_per_shard_checkpoint_layout_and_zero_gather_restore(tmp_path):
    """A model-sharded state saves one npz PER model coordinate (meta
    committed last), and a same-mesh restore reassembles device shards
    directly — ZERO full-parameter gathers, pinned by the restore
    stats' model-concat counters."""
    mesh = _mesh("data:2,model:2", 4)
    tr = _trainer(mesh=mesh)
    d = str(tmp_path / "ck")
    with NpzCheckpointer(d) as ck:
        ck.save(0, tr.state)
    names = sorted(os.listdir(d))
    assert "ckpt-0.shard0of2.npz" in names
    assert "ckpt-0.shard1of2.npz" in names
    assert "ckpt-0.shards.json" in names
    assert "ckpt-0.npz" not in names  # sharded layout replaces flat

    tr2 = _trainer(mesh=_mesh("data:2,model:2", 4), seed=99)
    with NpzCheckpointer(d) as ck:
        state, nxt = ck.restore_latest(tr2.state)
        stats = ck.last_restore_stats
    assert nxt == 1
    assert stats["sharded"] is True and stats["shards"] == 2
    assert stats["full_model_concats"] == 0, \
        "same-mesh restore must never reassemble a full parameter"
    # restored table is still model-sharded on the new mesh
    table = _table_leaf(state.params)
    assert sh.model_shard_info(table) == (0, 2)
    tr2.state = state
    for a, b in zip(_gathered(tr.state), _gathered(tr2.state)):
        np.testing.assert_array_equal(a, b)


def test_checkpoint_migrates_sharded_to_replicated(tmp_path):
    """Save under data:2,model:2 → restore under data:4: bit-identical
    parameters (the one full-span concat there is the migration work
    itself, counted but allowed)."""
    tr = _trainer(mesh=_mesh("data:2,model:2", 4))
    d = str(tmp_path / "ck")
    with NpzCheckpointer(d) as ck:
        ck.save(3, tr.state)
    tr2 = _trainer(mesh=_mesh("data:4", 4), seed=99)
    with NpzCheckpointer(d) as ck:
        state, nxt = ck.restore_latest(tr2.state)
        stats = ck.last_restore_stats
    assert nxt == 4
    assert stats["sharded"] is True
    tr2.state = state
    for a, b in zip(_gathered(tr.state), _gathered(tr2.state)):
        np.testing.assert_array_equal(a, b)


def test_checkpoint_migrates_replicated_to_sharded(tmp_path):
    """Save under data:4 (flat npz — no model axis) → restore under
    data:2,model:2: the flat generation re-shards onto the new mesh and
    parameters stay bit-identical."""
    tr = _trainer(mesh=_mesh("data:4", 4))
    d = str(tmp_path / "ck")
    with NpzCheckpointer(d) as ck:
        ck.save(2, tr.state)
    assert os.path.exists(os.path.join(d, "ckpt-2.npz"))  # flat layout
    tr2 = _trainer(mesh=_mesh("data:2,model:2", 4), seed=99)
    with NpzCheckpointer(d) as ck:
        state, nxt = ck.restore_latest(tr2.state)
    assert nxt == 3
    table = _table_leaf(state.params)
    assert sh.model_shard_info(table) == (0, 2), \
        "flat restore must re-shard onto the current mesh"
    tr2.state = state
    for a, b in zip(_gathered(tr.state), _gathered(tr2.state)):
        np.testing.assert_array_equal(a, b)


def test_corrupt_shard_quarantines_generation_and_falls_back(tmp_path):
    """One flipped byte in ONE shard condemns the whole generation —
    every file of it renamed ``.corrupt`` — and restore falls back to
    the previous verified generation instead of serving a torn tree."""
    mesh = _mesh("data:2,model:2", 4)
    tr = _trainer(mesh=mesh)
    d = str(tmp_path / "ck")
    with NpzCheckpointer(d, max_to_keep=4) as ck:
        ck.save(0, tr.state)
        ck.save(1, tr.state)
    bad = os.path.join(d, native := "ckpt-1.shard1of2.npz")
    blob = bytearray(open(bad, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(bad, "wb").write(bytes(blob))

    tr2 = _trainer(mesh=_mesh("data:2,model:2", 4), seed=99)
    with NpzCheckpointer(d, max_to_keep=4) as ck:
        state, nxt = ck.restore_latest(tr2.state)
    assert nxt == 1, "must fall back to epoch 0"
    left = sorted(os.listdir(d))
    assert not any(n.startswith("ckpt-1.") and not n.endswith(".corrupt")
                   for n in left), left
    # the whole epoch-1 generation went together: npz shards, their
    # manifests, and the shard meta
    corrupted = [n for n in left if n.endswith(".corrupt")]
    assert any(native in n for n in corrupted)
    assert any("ckpt-1.shards.json" in n for n in corrupted)
    tr2.state = state
    for a, b in zip(_gathered(tr.state), _gathered(tr2.state)):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------- mesh-aware export


def test_sharded_export_identity_and_scores_match_flat(tmp_path):
    """A mesh-aware export ships per-shard weight files + the manifest's
    ``weights_sharding`` record, keeps the LOGICAL identity digest of
    the flat layout (sharding-invariant), and scores bit-identically."""
    tr = _trainer(mesh=_mesh("data:2,model:2", 4))
    d_sh = str(tmp_path / "sharded")
    d_fl = str(tmp_path / "flat")
    export_native_bundle(d_sh, tr.state.params, tr.model_config, N_FEATURES)
    export_native_bundle(d_fl, sh.gather_params(tr.state.params),
                         tr.model_config, N_FEATURES)
    assert not os.path.exists(os.path.join(d_sh, NATIVE_WEIGHTS))
    for k in range(2):
        assert os.path.exists(
            os.path.join(d_sh, native_weights_shard_name(k, 2)))
    m_sh = json.load(open(os.path.join(d_sh, NATIVE_MANIFEST)))
    m_fl = json.load(open(os.path.join(d_fl, NATIVE_MANIFEST)))
    assert m_sh["mesh_shape"] == "data:2,model:2"
    assert m_fl["mesh_shape"] == "unsharded"
    assert m_sh["sha256"] == m_fl["sha256"]
    assert m_sh["weights_sharding"]["num_shards"] == 2
    w_sh, w_fl = load_native_weights(d_sh), load_native_weights(d_fl)
    assert set(w_sh) == set(w_fl)
    for k in w_fl:
        np.testing.assert_array_equal(w_sh[k], w_fl[k])
    rows = np.random.default_rng(3).random((12, N_FEATURES)).astype(
        np.float32)
    a, b = EvalModel(d_sh), EvalModel(d_fl)
    np.testing.assert_array_equal(a.compute_batch(rows),
                                  b.compute_batch(rows))
    a.release(), b.release()


def test_aot_mesh_fingerprint_mismatch_falls_back_bit_identical(tmp_path):
    """Executables compiled beside a ``data:2,model:2`` export refuse to
    load beside an unsharded bundle of the SAME weights (the generation
    digest matches by design — mesh_shape is exactly the differing
    field): every bucket falls back, journals ``kind=aot_fallback`` with
    ``aot_error`` naming the mesh, and scores stay bit-identical via
    live compile."""
    buckets = (8, 16)
    tr = _trainer(mesh=_mesh("data:2,model:2", 4))
    d_sh = str(tmp_path / "sharded")
    export_model(d_sh, tr, aot_buckets=buckets)
    meta = json.loads(open(os.path.join(d_sh, aot_mod.AOT_META)).read())
    assert meta["fingerprint"]["mesh_shape"] == "data:2,model:2"
    # same weights, unsharded layout — then graft the sharded export's
    # aot/ dir beside it (the stale-executables hazard a reshard leaves)
    d_fl = str(tmp_path / "flat")
    export_native_bundle(d_fl, sh.gather_params(tr.state.params),
                         tr.model_config, N_FEATURES,
                         feature_columns=tr.feature_columns)
    shutil.copytree(os.path.join(d_sh, aot_mod.AOT_DIR),
                    os.path.join(d_fl, aot_mod.AOT_DIR))
    idx = aot_mod.AotIndex.load(d_fl)
    assert idx is not None and idx.unusable
    assert "mesh_shape" in idx.unusable

    path = str(tmp_path / "journal.jsonl")
    journal_mod.install(Journal(path, plane="serve"))
    compile_mod.install(compile_mod.CompileRecorder(plane="serve"))
    m = EvalModel(d_fl)
    assert m.warm(buckets) == len(buckets)  # everything live-compiled
    st = m.aot_stats
    assert st["loads"] == 0 and st["fallbacks"] == len(buckets)
    assert "mesh_shape" in st["unusable"]
    d_plain = str(tmp_path / "plain")
    export_native_bundle(d_plain, sh.gather_params(tr.state.params),
                         tr.model_config, N_FEATURES,
                         feature_columns=tr.feature_columns)
    plain = EvalModel(d_plain)
    rows = np.random.default_rng(5).random((9, N_FEATURES)).astype(
        np.float32)
    np.testing.assert_array_equal(m.compute_batch(rows),
                                  plain.compute_batch(rows))
    journal_mod.uninstall()
    evs = [e for e in read_events(path) if e["event"] == "compile"]
    fb = [e for e in evs if e.get("kind") == "aot_fallback"]
    assert {e["bucket"] for e in fb} == set(buckets)
    assert all("mesh_shape" in e["aot_error"] for e in fb)
    assert not [e for e in evs if e.get("kind") == "aot_load"]
    m.release()
    plain.release()


def test_matching_mesh_aot_still_loads(tmp_path):
    """The mesh stamp must not break the happy path: a sharded export's
    own executables deserialize beside it (fingerprint mesh ==
    manifest mesh)."""
    tr = _trainer(mesh=_mesh("data:2,model:2", 4))
    d = str(tmp_path / "m")
    export_model(d, tr, aot_buckets=(8,))
    idx = aot_mod.AotIndex.load(d)
    assert idx is not None and not idx.unusable
    m = EvalModel(d)
    assert m.warm((8,)) == 0, "an AOT hit must cost zero new traces"
    assert m.aot_stats["loads"] == 1 and m.aot_stats["fallbacks"] == 0
    m.release()
