"""Transient-fault resilience: retry/backoff at every network seam, proven
by injected faults (utils/retry.py, utils/faults.py).

The chaos drills here are the coverage the reference never had — it leaned
on YARN/ZooKeeper retry machinery it didn't test.  Our stdlib planes carry
their own discipline, so the drills make it load-bearing: a WebHDFS-backed
train → checkpoint → kill → resume cycle must complete BIT-IDENTICALLY
under a >=20% injected fault rate (503s, dropped connections, mid-body
truncations), and must FAIL with retries disabled; the coordinator RPC
fleet must converge while connections drop mid-barrier, with dedup tokens
keeping retried deliveries of non-idempotent ops (register / epoch report /
complete) from double-applying.
"""

import dataclasses
import http.client
import json
import os
import random
import socket
import threading
import urllib.error
import urllib.parse
from collections import Counter
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from shifu_tensorflow_tpu.config import keys as K
from shifu_tensorflow_tpu.config.conf import Conf
from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.coordinator.coordinator import (
    Coordinator,
    CoordinatorClient,
    JobSpec,
    JobState,
)
from shifu_tensorflow_tpu.data.splitter import Shard
from shifu_tensorflow_tpu.train.checkpoint import NpzCheckpointer
from shifu_tensorflow_tpu.train.trainer import EpochStats, Trainer
from shifu_tensorflow_tpu.utils import faults, fs, retry
from shifu_tensorflow_tpu.utils.fs_gcs import GcsError
from shifu_tensorflow_tpu.utils.fs_webhdfs import WebHdfsError
from shifu_tensorflow_tpu.utils.retry import RetryPolicy

#: fast deterministic policy for drills — real backoff shape, toy delays
FAST = RetryPolicy(max_attempts=8, base_delay_s=0.001, max_delay_s=0.004,
                   deadline_s=30.0, seed=1234)


@pytest.fixture(autouse=True)
def _reset_resilience_state():
    retry.reset_counters()
    retry.set_default_policy(FAST)
    faults.set_plan(None)
    yield
    faults.set_plan(None)
    retry.set_default_policy(RetryPolicy())


# --------------------------------------------------------------------------
# retryable-error classification (satellite: table-driven, both fs backends
# and the RPC client's transport errors)
# --------------------------------------------------------------------------


def _wrapped_transport_error():
    """WebHdfsError as _open_raw raises it for a failed connect: no code,
    __cause__ = URLError — classified by the cause."""
    try:
        try:
            raise urllib.error.URLError(ConnectionRefusedError("no route"))
        except urllib.error.URLError as e:
            raise WebHdfsError("webhdfs GET http://x: no route") from e
    except WebHdfsError as e:
        return e


CLASSIFICATION_TABLE = [
    # HTTP-coded: 5xx / 429 retry, 4xx never (auth + not-found included)
    (WebHdfsError("x", code=500), True),
    (WebHdfsError("x", code=502), True),
    (WebHdfsError("x", code=503), True),
    (WebHdfsError("x", code=504), True),
    (WebHdfsError("x", code=429), True),
    (WebHdfsError("x", code=400), False),
    (WebHdfsError("x", code=401), False),
    (WebHdfsError("x", code=403), False),
    (WebHdfsError("x", code=404), False),
    (WebHdfsError("x", code=409), False),
    (GcsError("x", code=503), True),
    (GcsError("x", code=429), True),
    (GcsError("x", code=404), False),
    (GcsError("x", code=403), False),
    (urllib.error.HTTPError("u", 503, "m", {}, None), True),
    (urllib.error.HTTPError("u", 404, "m", {}, None), False),
    (faults.InjectedHttpError(503, "s"), True),
    (faults.InjectedHttpError(404, "s"), False),
    # transport-level: always retry
    (ConnectionResetError("peer reset"), True),
    (ConnectionRefusedError("refused"), True),
    (ConnectionAbortedError("aborted"), True),
    (BrokenPipeError("pipe"), True),
    (TimeoutError("timed out"), True),
    (socket.timeout("timed out"), True),
    (socket.gaierror("dns"), True),
    (http.client.RemoteDisconnected("gone"), True),
    (http.client.IncompleteRead(b"", 10), True),
    (urllib.error.URLError(ConnectionRefusedError("refused")), True),
    # wrapped transport error classifies by cause; a LOGICAL fs error with
    # neither code nor cause (rename returned boolean:false) never retries
    (_wrapped_transport_error(), True),
    (WebHdfsError("rename a -> b failed"), False),
    # plain bugs never retry
    (ValueError("bad"), False),
    (KeyError("missing"), False),
    (FileNotFoundError("gone"), False),
]


def test_retryable_classification_table():
    for exc, want in CLASSIFICATION_TABLE:
        assert retry.retryable(exc) is want, (
            f"{type(exc).__name__}({exc}, code={getattr(exc, 'code', None)})"
            f" should be retryable={want}"
        )


# --------------------------------------------------------------------------
# retry loop mechanics
# --------------------------------------------------------------------------


def test_retry_call_recovers_with_jittered_backoff():
    calls = Counter()
    sleeps = []

    def fn():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionResetError("boom")
        return "ok"

    pol = RetryPolicy(max_attempts=5, base_delay_s=0.01, max_delay_s=0.04,
                      deadline_s=5.0, seed=9)
    assert retry.call(fn, policy=pol, site="t.rec", sleep=sleeps.append) == "ok"
    assert calls["n"] == 3
    # full jitter: uniform in [0, base * 2^(attempt-1)], capped
    assert len(sleeps) == 2
    assert 0.0 <= sleeps[0] <= 0.01
    assert 0.0 <= sleeps[1] <= 0.02
    c = retry.counters()
    assert c["t.rec.retries"] == 2
    assert c["t.rec.recovered"] == 1


def test_retry_call_non_retryable_raises_immediately():
    calls = Counter()

    def fn():
        calls["n"] += 1
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        retry.call(fn, policy=FAST, site="t.bug", sleep=lambda d: None)
    assert calls["n"] == 1
    assert "t.bug.retries" not in retry.counters()


def test_retry_call_exhausts_attempts():
    calls = Counter()

    def fn():
        calls["n"] += 1
        raise ConnectionResetError("always")

    pol = RetryPolicy(max_attempts=3, base_delay_s=0.0001, seed=1)
    with pytest.raises(ConnectionResetError):
        retry.call(fn, policy=pol, site="t.exh", sleep=lambda d: None)
    assert calls["n"] == 3
    assert retry.counters()["t.exh.exhausted"] == 1


def test_retry_deadline_caps_cumulative_backoff():
    sleeps = []

    def fn():
        raise ConnectionResetError("always")

    pol = RetryPolicy(max_attempts=100, base_delay_s=0.01, deadline_s=0.0,
                      seed=2)
    with pytest.raises(ConnectionResetError):
        retry.call(fn, policy=pol, site="t.dead", sleep=sleeps.append)
    assert sleeps == []  # the first backoff already exceeded the deadline


def test_retry_deadline_ignores_attempt_runtime():
    """The deadline caps the retry layer's OWN stall (sleep), not the
    attempts' runtime — a barrier RPC that blocks far past the deadline
    before a transient drop must still get its reconnects."""
    import time as _time

    calls = Counter()

    def fn():
        calls["n"] += 1
        _time.sleep(0.05)  # attempt runtime alone exceeds the deadline
        if calls["n"] < 3:
            raise ConnectionResetError("shed mid-barrier")
        return "ok"

    pol = RetryPolicy(max_attempts=5, base_delay_s=1e-6, max_delay_s=1e-6,
                      deadline_s=0.01, seed=4)
    assert retry.call(fn, policy=pol, site="t.block",
                      sleep=lambda d: None) == "ok"
    assert calls["n"] == 3


def test_policy_conf_and_json_bridge():
    conf = Conf({K.RETRY_MAX_ATTEMPTS: 3, K.RETRY_BASE_DELAY_MS: 10,
                 K.RETRY_MAX_DELAY_MS: 100, K.RETRY_DEADLINE_MS: 5000})
    pol = retry.policy_from_conf(conf)
    assert pol.max_attempts == 3
    assert pol.base_delay_s == pytest.approx(0.01)
    assert pol.max_delay_s == pytest.approx(0.1)
    assert pol.deadline_s == pytest.approx(5.0)
    assert RetryPolicy.from_dict(pol.to_dict()) == pol
    # the multi-worker CLI path carries the policy into WorkerConfig JSON
    from shifu_tensorflow_tpu.train.__main__ import (
        build_parser,
        worker_runtime_kwargs,
    )

    args = build_parser().parse_args(
        ["--training-data-path", "/tmp/x", "--feature-columns", "1,2"])
    kw = worker_runtime_kwargs(args, conf)
    assert kw["retry"]["max_attempts"] == 3


# --------------------------------------------------------------------------
# fault plan
# --------------------------------------------------------------------------


def _fires(plan, site):
    try:
        plan.check(site)
        return None
    except Exception as e:
        return type(e).__name__


def test_fault_plan_parse_grammar_and_errors():
    plan = faults.FaultPlan.parse("fs.read:503@0.5, rpc:reset@1.0", seed=1)
    assert _fires(plan, "rpc.connect") == "ConnectionResetError"
    with pytest.raises(ValueError, match="site:kind@rate"):
        faults.FaultPlan.parse("nonsense")
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.FaultPlan.parse("fs.read:explode@0.5")
    with pytest.raises(ValueError, match="rate out of"):
        faults.FaultPlan.parse("fs.read:503@1.5")


def test_fault_plan_is_deterministic_and_scoped():
    spec = "fs.read:503@0.4,rpc:timeout@0.3"
    sites = ["fs.read", "rpc.connect", "fs.read", "rpc.recv"] * 12
    p1 = faults.FaultPlan.parse(spec, seed=11)
    p2 = faults.FaultPlan.parse(spec, seed=11)
    seq1 = [_fires(p1, s) for s in sites]
    seq2 = [_fires(p2, s) for s in sites]
    # same seed + same check sequence -> identical fire pattern, and the
    # storm actually contains faults
    assert seq1 == seq2
    assert any(seq1)
    # a different seed reshuffles
    p3 = faults.FaultPlan.parse(spec, seed=12)
    assert [_fires(p3, s) for s in sites] != seq1
    # scoping: the fs.read term never fires at fs.write; the bare "rpc"
    # prefix term fires at rpc.* sites only
    p4 = faults.FaultPlan.parse("fs.read:503@1.0", seed=3)
    assert _fires(p4, "fs.write") is None
    assert _fires(p4, "fs.read") == "InjectedHttpError"
    p5 = faults.FaultPlan.parse("rpc:reset@1.0", seed=3)
    assert _fires(p5, "fs.read") is None
    assert _fires(p5, "rpc.recv") == "ConnectionResetError"
    assert p5.fired() == {"rpc:reset": 1}


def test_fault_plan_env_activation(monkeypatch):
    monkeypatch.setenv("STPU_FAULT_PLAN", "ckpt.write:503@1.0")
    monkeypatch.setenv("STPU_FAULT_SEED", "5")
    faults.set_plan(None)
    faults._loaded_env = False  # force env re-read
    try:
        with pytest.raises(faults.InjectedHttpError):
            faults.check("ckpt.write")
        faults.check("fs.read")  # unlisted site: no-op
    finally:
        faults.set_plan(None)


# --------------------------------------------------------------------------
# flaky WebHDFS server: the in-process fake from test_fs_remote plus
# seeded chaos — 503s, dropped connections, mid-body truncations
# --------------------------------------------------------------------------


class _FlakyWebHdfsHandler(BaseHTTPRequestHandler):
    root: str
    chaos: dict  # rng, rate, midbody, fired (Counter), ops (Counter)

    def log_message(self, *a):
        pass

    def _local(self, urlpath: str) -> str:
        assert urlpath.startswith("/webhdfs/v1")
        rel = urllib.parse.unquote(urlpath[len("/webhdfs/v1"):]).lstrip("/")
        return os.path.join(self.root, rel)

    def _json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _status_obj(self, p: str) -> dict:
        st = os.stat(p)
        return {
            "length": st.st_size,
            "modificationTime": int(st.st_mtime * 1000),
            "type": "DIRECTORY" if os.path.isdir(p) else "FILE",
            "pathSuffix": "",
        }

    def _inject(self, op: str) -> bool:
        """Pre-dispatch chaos: the op is NOT applied when a fault fires, so
        even non-idempotent ops (RENAME) stay consistent — the
        applied-but-response-lost case gets its own dedicated handlers."""
        c = self.chaos
        c["ops"][op] += 1
        if c.get("rate", 0.0) <= 0.0:
            return False
        if c["rng"].random() < c["rate"]:
            c["fired"][op] += 1
            if c["rng"].random() < 0.5:
                self._json(503, {"RemoteException": {
                    "message": "injected 503"}})
            # else: close without any response -> RemoteDisconnected
            return True
        return False

    def do_GET(self):
        u = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(u.query))
        p = self._local(u.path)
        op = q.get("op")
        if self._inject(op):
            return
        if op == "GETFILESTATUS":
            if not os.path.exists(p):
                return self._json(404, {"RemoteException": {
                    "message": "File does not exist"}})
            return self._json(200, {"FileStatus": self._status_obj(p)})
        if op == "LISTSTATUS":
            if not os.path.isdir(p):
                return self._json(404, {"RemoteException": {
                    "message": "not a directory"}})
            entries = []
            for name in sorted(os.listdir(p)):
                e = self._status_obj(os.path.join(p, name))
                e["pathSuffix"] = name
                entries.append(e)
            return self._json(200, {"FileStatuses": {"FileStatus": entries}})
        if op == "OPEN":
            if not os.path.exists(p):
                return self._json(404, {"RemoteException": {
                    "message": "File does not exist"}})
            with open(p, "rb") as f:
                data = f.read()
            offset = int(q.get("offset", "0"))
            data = data[offset:]
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            c = self.chaos
            if (c.get("midbody", 0.0) > 0.0 and len(data) > 1
                    and c["rng"].random() < c["midbody"]):
                # declared full length, deliver half, die — the resumable
                # reader must re-OPEN from its high-water mark
                c["fired"]["OPEN-midbody"] += 1
                self.wfile.write(data[: len(data) // 2])
                return
            self.wfile.write(data)
            return
        self._json(400, {"RemoteException": {"message": f"bad op {op}"}})

    def do_PUT(self):
        u = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(u.query))
        p = self._local(u.path)
        op = q.get("op")
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        if self._inject(op):
            return
        if op == "CREATE":
            if "step2" not in q:
                # model the real namenode's 307 hop so chaos hits BOTH hops
                self.send_response(307)
                self.send_header(
                    "Location",
                    f"http://{self.headers['Host']}{u.path}?"
                    + urllib.parse.urlencode({**q, "step2": "1"}),
                )
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            os.makedirs(os.path.dirname(p), exist_ok=True)
            with open(p, "wb") as f:
                f.write(body)
            return self._json(201, {})
        if op == "MKDIRS":
            os.makedirs(p, exist_ok=True)
            return self._json(200, {"boolean": True})
        if op == "RENAME":
            return self._do_rename(p, q)
        self._json(400, {"RemoteException": {"message": f"bad op {op}"}})

    def _do_rename(self, p, q):
        dst = os.path.join(self.root, q["destination"].lstrip("/"))
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(p, dst)
        return self._json(200, {"boolean": True})

    def do_DELETE(self):
        u = urllib.parse.urlsplit(self.path)
        p = self._local(u.path)
        if self._inject("DELETE"):
            return
        ok = os.path.exists(p)
        if ok:
            os.remove(p)
        self._json(200, {"boolean": ok})


@pytest.fixture
def flaky_hdfs(tmp_path):
    """Factory: spin up a chaos-configured fake WebHDFS server; returns
    (base_url, chaos_dict, local_root)."""
    servers = []

    def make(name, rate=0.0, midbody=0.0, seed=7, handler=None):
        root = tmp_path / name
        root.mkdir()
        chaos = {
            "rng": random.Random(seed), "rate": rate, "midbody": midbody,
            "fired": Counter(), "ops": Counter(),
        }
        cls = type("H", (handler or _FlakyWebHdfsHandler,),
                   {"root": str(root), "chaos": chaos})
        server = ThreadingHTTPServer(("127.0.0.1", 0), cls)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        servers.append(server)
        host, port = server.server_address[:2]
        return f"hdfs://{host}:{port}", chaos, root

    yield make
    for s in servers:
        s.shutdown()
        s.server_close()


# --------------------------------------------------------------------------
# resumable reads
# --------------------------------------------------------------------------


def test_resumable_read_survives_midbody_truncation(flaky_hdfs):
    base, chaos, root = flaky_hdfs("resume", rate=0.0, midbody=0.7, seed=3)
    payload = bytes(random.Random(0).getrandbits(8) for _ in range(96_000))
    (root / "blob.bin").write_bytes(payload)
    with fs.open_read(f"{base}/blob.bin") as f:
        got = f.read()
    assert got == payload
    assert chaos["fired"]["OPEN-midbody"] > 0, "no truncation injected"
    # the resume path re-issued OPEN with an offset (not full restarts)
    assert chaos["ops"]["OPEN"] > 1


# --------------------------------------------------------------------------
# the fs chaos drill: train -> checkpoint -> kill -> resume, bit-identical
# --------------------------------------------------------------------------


def _model_config():
    return ModelConfig.from_json(
        {"train": {"numTrainEpochs": 4, "params": {
            "NumHiddenLayers": 1, "NumHiddenNodes": [4],
            "ActivationFunc": ["relu"], "LearningRate": 0.1}}}
    )


def _batches():
    rng = np.random.default_rng(42)
    out = []
    for _ in range(3):
        out.append({
            "x": rng.normal(size=(16, 3)).astype(np.float32),
            "y": (rng.random((16, 1)) < 0.5).astype(np.float32),
            "w": np.ones((16, 1), np.float32),
        })
    return out


def _state_leaves(state):
    import jax

    return [np.asarray(jax.device_get(leaf)) for leaf in
            jax.tree_util.tree_leaves(
                {"params": state.params, "opt": state.opt_state,
                 "step": state.step})]


def _train_ckpt_kill_resume(ckpt_dir: str, epochs=4, kill_after=2):
    """The drill choreography, identical for the clean and chaos arms:
    train, checkpoint each epoch, 'kill' (fresh trainer = fresh process),
    restore from the (possibly remote) checkpoint, finish the budget."""
    batches = _batches()
    mc = _model_config()
    tr = Trainer(mc, 3)
    with NpzCheckpointer(ckpt_dir, every_epochs=1, max_to_keep=2) as ck:
        for e in range(kill_after):
            tr.train_epoch(list(batches))
            ck.save(e, tr.state)
    tr2 = Trainer(mc, 3)
    with NpzCheckpointer(ckpt_dir, every_epochs=1, max_to_keep=2) as ck:
        state, nxt = ck.restore_latest(tr2.state)
        assert nxt == kill_after, "resume must pick up the exact epoch"
        tr2.state = state
        for e in range(nxt, epochs):
            tr2.train_epoch(list(batches))
            ck.save(e, tr2.state)
    return _state_leaves(tr2.state)


def test_chaos_drill_webhdfs_train_ckpt_resume_bit_identical(
        flaky_hdfs, tmp_path):
    """Acceptance drill: >=20% injected transient faults on every fs
    request (503s + dropped connections) plus mid-body truncations on
    reads, and the full cycle still produces BIT-identical parameters to a
    fault-free local run."""
    clean = _train_ckpt_kill_resume(str(tmp_path / "clean-ckpt"))

    base, chaos, _ = flaky_hdfs("chaos", rate=0.25, midbody=0.3, seed=1007)
    stormy = _train_ckpt_kill_resume(f"{base}/ckpt")

    assert len(clean) == len(stormy)
    for a, b in zip(clean, stormy):
        np.testing.assert_array_equal(a, b)
    fired = sum(chaos["fired"].values())
    assert fired >= 5, f"drill proved nothing: only {fired} faults fired"
    # and the retry layer actually absorbed them
    absorbed = sum(v for k, v in retry.counters().items()
                   if k.startswith("webhdfs.") and k.endswith(".retries"))
    assert absorbed > 0


def test_chaos_drill_fails_without_retries(flaky_hdfs):
    """Control arm: same storm, retries disabled — the drill must die,
    proving the retry layer (not luck) carries the chaos drill."""
    retry.set_default_policy(RetryPolicy(max_attempts=1))
    base, chaos, _ = flaky_hdfs("noretry", rate=0.25, midbody=0.3, seed=1007)
    with pytest.raises((OSError, http.client.HTTPException)):
        _train_ckpt_kill_resume(f"{base}/ckpt")
    assert sum(chaos["fired"].values()) > 0


# --------------------------------------------------------------------------
# rename-commit: at-most-once EFFECT (never blindly re-issued)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _MiniState:
    """Just enough state surface for NpzCheckpointer (params/opt_state/step
    + .replace) without paying a Trainer build."""

    params: dict
    opt_state: tuple
    step: int

    def replace(self, **kw):
        return dataclasses.replace(self, **kw)


def _mini_state():
    return _MiniState(
        params={"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
        opt_state=(np.zeros(3, np.float32),),
        step=7,
    )


class _RenameAppliedButLostHandler(_FlakyWebHdfsHandler):
    """RENAME applies server-side, then the response is a 500 — the
    lost-response case for the non-idempotent commit."""

    def _do_rename(self, p, q):
        dst = os.path.join(self.root, q["destination"].lstrip("/"))
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(p, dst)
        return self._json(500, {"RemoteException": {
            "message": "injected post-apply failure"}})


class _RenameFailsOnceHandler(_FlakyWebHdfsHandler):
    """First RENAME 503s WITHOUT applying; later ones apply normally —
    the verifiably-not-applied case where one re-issue is safe."""

    def _do_rename(self, p, q):
        if self.chaos["ops"]["RENAME"] == 1:  # _inject counted this call
            return self._json(503, {"RemoteException": {
                "message": "injected pre-apply failure"}})
        return super()._do_rename(p, q)


def test_rename_commit_lost_response_verifies_instead_of_reissuing(
        flaky_hdfs):
    base, chaos, _ = flaky_hdfs("lost", handler=_RenameAppliedButLostHandler)
    with NpzCheckpointer(f"{base}/ckpt", every_epochs=1) as ck:
        ck.save(0, _mini_state())
        assert ck.latest_epoch() == 0
    # exactly one RENAME per committed file (npz + manifest sidecar):
    # both lost responses were VERIFIED, not blindly retried
    assert chaos["ops"]["RENAME"] == 2
    # and the published checkpoint restores
    with NpzCheckpointer(f"{base}/ckpt", every_epochs=1) as ck:
        state, nxt = ck.restore_latest(_mini_state())
        assert nxt == 1
        np.testing.assert_array_equal(state.params["w"],
                                      _mini_state().params["w"])


def test_rename_commit_reissues_only_when_verifiably_not_applied(flaky_hdfs):
    base, chaos, _ = flaky_hdfs("failonce", handler=_RenameFailsOnceHandler)
    with NpzCheckpointer(f"{base}/ckpt", every_epochs=1) as ck:
        ck.save(0, _mini_state())
        assert ck.latest_epoch() == 0
    # first delivery provably did not apply (tmp present, dst absent), so
    # ONE re-issue happened — two RENAMEs for the npz, one effect; plus
    # the manifest sidecar's own single commit
    assert chaos["ops"]["RENAME"] == 3


def test_webhdfs_rename_is_never_transport_retried(flaky_hdfs, monkeypatch):
    """The fs layer must issue RENAME exactly once per rename() call even
    with an aggressive default policy — retry lives at the verify layer."""
    base, chaos, root = flaky_hdfs("raw", handler=_RenameAppliedButLostHandler)
    (root / "src.txt").write_bytes(b"x")
    impl = fs.filesystem_for(base)
    with pytest.raises(WebHdfsError):
        impl.rename(f"{base}/src.txt", f"{base}/dst.txt")
    assert chaos["ops"]["RENAME"] == 1


# --------------------------------------------------------------------------
# RPC: dedup tokens for non-idempotent ops
# --------------------------------------------------------------------------


def _spec(n=2, epochs=3, **kw):
    shards = [Shard(i, (f"/data/part-{i}",), 1) for i in range(n)]
    kw.setdefault("registration_timeout_s", 10.0)
    return JobSpec(n_workers=n, shards=shards, epochs=epochs, **kw)


def _stats(worker, epoch, loss=0.5):
    return EpochStats(
        worker_index=worker, current_epoch=epoch, training_loss=loss,
        valid_loss=loss, training_time_s=1.0 + worker, valid_time_s=0.1,
        global_step=epoch + 1,
    )


def test_register_duplicate_delivery_replays_cached_response():
    coord = Coordinator(_spec(2))
    msg = {"op": "register", "worker_id": "a", "worker_index": None,
           "host": "h1", "jax_port": None, "token": "tok-reg-1"}
    r1 = coord.dispatch(dict(msg))
    r2 = coord.dispatch(dict(msg))

    # the replay is byte-identical MINUS the clock stamps, which
    # describe each delivery's own exchange (obs/fleet.ClockSync must
    # never estimate an offset from the ORIGINAL delivery's times)
    def unstamped(r):
        return {k: v for k, v in r.items()
                if k not in ("srv_ts", "srv_recv_ts")}

    assert unstamped(r1) == unstamped(r2)
    assert r1["srv_ts"] <= r2["srv_ts"]
    assert r1["worker_index"] == 0
    assert coord.status()["registered"] == 1
    assert coord.op_replays == 1
    # a genuinely NEW registration (new token, new worker) still lands
    r3 = coord.dispatch({**msg, "worker_id": "b", "token": "tok-reg-2"})
    assert r3["worker_index"] == 1
    assert coord.status()["registered"] == 2


def test_epoch_report_duplicate_delivery_cannot_double_count():
    coord = Coordinator(_spec(2))
    coord.register("a", 0, host="h")
    coord.register("b", 1, host="h")
    msg = {"op": "epoch", "stats": _stats(0, 0).__dict__, "token": "tok-e0"}
    coord.dispatch(dict(msg))
    coord.dispatch(dict(msg))  # retried delivery
    assert coord.op_replays == 1
    coord.dispatch({"op": "epoch", "stats": _stats(1, 0).__dict__,
                    "token": "tok-e1"})
    # quorum completed exactly once, with exactly 2 worker records
    assert [s.epoch for s in coord.aggregator.summaries] == [0]
    assert coord.aggregator.summaries[0].n_workers == 2
    coord.liveness.stop()


def test_complete_duplicate_delivery_burns_budget_once():
    # 3 workers, restart budget = floor(0.4 * 3) = 1
    coord = Coordinator(_spec(3, max_worker_failure_ratio=0.4))
    for i, wid in enumerate(["a", "b", "c"]):
        coord.register(wid, i, host="h")
    assert coord.max_restarts == 1
    msg = {"op": "complete", "worker_id": "b", "exit_code": 1,
           "token": "tok-c1"}
    coord.dispatch(dict(msg))
    coord.dispatch(dict(msg))  # retried delivery of the same failure
    st = coord.status()
    assert st["restarts_used"] == 1, "duplicate complete double-burned budget"
    assert coord.state == JobState.TRAINING
    # a DISTINCT second failure exhausts the budget — proving the budget
    # accounting is live and the duplicate above was truly deduped
    coord.dispatch({"op": "complete", "worker_id": "c", "exit_code": 1,
                    "token": "tok-c2"})
    assert coord.state == JobState.FAILED
    coord.liveness.stop()


# --------------------------------------------------------------------------
# RPC chaos drill: connections drop mid-barrier, fleet converges
# --------------------------------------------------------------------------


def test_rpc_drill_fleet_converges_under_connection_faults():
    plan = faults.FaultPlan.parse(
        "rpc.connect:reset@0.3,rpc.recv:reset@0.3", seed=5)
    faults.set_plan(plan)
    coord = Coordinator(_spec(2, epochs=3, sync_epochs=True))
    host, port = coord.serve()
    errors = []

    def run(wid, idx):
        try:
            c = CoordinatorClient(host, port, retry_policy=FAST)
            assert c.register(wid, idx, host="127.0.0.1")["ok"]
            assert c.await_start()["ok"]
            for e in range(3):
                assert c.report_epoch(_stats(idx, e))["ok"]
                assert c.epoch_barrier(wid, e)["ok"]
            c.complete(wid, 0)
        except Exception as exc:  # surface in the main thread
            errors.append((wid, exc))

    threads = [threading.Thread(target=run, args=(f"w{i}", i))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    try:
        assert not errors, f"workers failed under chaos: {errors}"
        assert coord.state == JobState.FINISHED
        # every epoch published exactly once, with full quorum — retried
        # deliveries never double-counted a worker or an epoch stat
        assert sorted(s.epoch for s in coord.aggregator.summaries) == [0, 1, 2]
        assert all(s.n_workers == 2 for s in coord.aggregator.summaries)
        assert sum(plan.fired().values()) > 0, "no faults injected"
    finally:
        coord.shutdown()


def test_rpc_faults_fatal_without_retry():
    faults.set_plan(faults.FaultPlan.parse("rpc.connect:refused@1.0", seed=1))
    c = CoordinatorClient("127.0.0.1", 1, retry_policy=retry.NO_RETRY)
    with pytest.raises(ConnectionRefusedError):
        c.status()
    # with retries the attempts are bounded, then the error surfaces
    c2 = CoordinatorClient(
        "127.0.0.1", 1,
        retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.0001, seed=1))
    with pytest.raises(ConnectionRefusedError):
        c2.status()
    # both arms count as exhausted (NO_RETRY = a 1-attempt policy)
    assert retry.counters()["rpc.status.exhausted"] == 2


# --------------------------------------------------------------------------
# fault plan drives the checkpoint seam end to end
# --------------------------------------------------------------------------


def test_ckpt_write_fault_site_respects_retry_and_counts(tmp_path):
    faults.set_plan(faults.FaultPlan.parse("ckpt.write:503@1.0", seed=2))
    with NpzCheckpointer(str(tmp_path / "ck")) as ck:
        # ckpt.write faults are raised at the seam and are NOT retried by
        # the checkpointer itself (they model the fetch/serialize layer);
        # the async path surfaces them on the next wait()
        with pytest.raises(faults.InjectedHttpError):
            ck.save(0, _mini_state())
    faults.set_plan(None)
    with NpzCheckpointer(str(tmp_path / "ck")) as ck:
        ck.save(0, _mini_state())
        assert ck.latest_epoch() == 0


# --------------------------------------------------------------------------
# verified checkpoints: manifest sidecars, quarantine, fallback chain
# --------------------------------------------------------------------------


def test_fault_grammar_at_rest_and_at_step():
    # new kinds parse; at-step (bare integer >= 2) fires exactly once, at
    # the Nth check; rates still validate
    p = faults.FaultPlan.parse("ckpt.at-rest:bitflip@2", seed=1)
    assert p.mutate("ckpt.at-rest", b"abcdef") == b"abcdef"  # check 1
    assert p.mutate("ckpt.at-rest", b"abcdef") != b"abcdef"  # check 2 fires
    assert p.mutate("ckpt.at-rest", b"abcdef") == b"abcdef"  # latched
    t = faults.FaultPlan.parse("ckpt.at-rest:truncate@1.0", seed=1)
    out = t.mutate("ckpt.at-rest", b"0123456789")
    assert len(out) < 10 and b"0123456789".startswith(out)
    # flag kind: index-keyed at-step firing, once
    f = faults.FaultPlan.parse("health.nan-loss.e1:nan-loss@3", seed=1)
    assert not f.poll("health.nan-loss.e1", index=2)
    assert not f.poll("health.nan-loss.e0", index=3)  # site mismatch
    assert f.poll("health.nan-loss.e1", index=3)
    assert not f.poll("health.nan-loss.e1", index=3)  # fired once
    # prefix term matches the epoch-qualified site
    g = faults.FaultPlan.parse("health.nan-loss:nan-loss@1.0", seed=1)
    assert g.poll("health.nan-loss.e7", index=0)
    # mutation/flag kinds never leak into the exception seam
    faults.set_plan(faults.FaultPlan.parse(
        "ckpt:bitflip@1.0,health.nan-loss:nan-loss@1.0", seed=1))
    faults.check("ckpt.write")  # must not raise
    faults.set_plan(None)
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.FaultPlan.parse("x:explode@0.5")


def _save_epochs(ck, upto, base=None):
    states = {}
    for e in range(upto):
        s = base or _mini_state()
        s = s.replace(params={"w": s.params["w"] + e})
        ck.save(e, s)
        states[e] = s
    return states


def test_manifest_sidecar_written_and_verified(tmp_path):
    d = str(tmp_path / "ck")
    with NpzCheckpointer(d, max_to_keep=5) as ck:
        _save_epochs(ck, 2)
        assert os.path.exists(os.path.join(d, "ckpt-1.npz.manifest.json"))
        assert ck.verified_epochs() == [0, 1]
        assert ck.latest_verified_epoch() == 1
        state, nxt = ck.restore_latest(_mini_state())
        assert nxt == 2


def test_bitflip_at_rest_quarantines_and_falls_back_bit_identical(tmp_path):
    """Acceptance: corrupt-latest -> resume lands on the previous verified
    epoch, bit-identically, and the corrupt generation is quarantined
    (renamed *.corrupt), never deleted."""
    d = str(tmp_path / "ck")
    with NpzCheckpointer(d, max_to_keep=5) as ck:
        states = _save_epochs(ck, 2)
        faults.set_plan(faults.FaultPlan.parse(
            "ckpt.at-rest:bitflip@1.0", seed=9))
        ck.save(2, _mini_state())
        faults.set_plan(None)
        # bit-level corruption preserves size: the cheap check still
        # offers epoch 2, the restore's digest check rejects it
        state, nxt = ck.restore_latest(_mini_state())
        assert nxt == 2, "must fall back to the newest VERIFIED epoch"
        np.testing.assert_array_equal(
            state.params["w"], states[1].params["w"])
        assert os.path.exists(os.path.join(d, "ckpt-2.npz.corrupt"))
        assert not os.path.exists(os.path.join(d, "ckpt-2.npz"))
        # quarantined, not deleted — and skipped by every later listing
        assert ck.latest_epoch() == 1
        assert ck.verified_epochs() == [0, 1]


def test_truncate_at_rest_detected_by_cheap_check(tmp_path):
    d = str(tmp_path / "ck")
    with NpzCheckpointer(d, max_to_keep=5) as ck:
        _save_epochs(ck, 1)
        faults.set_plan(faults.FaultPlan.parse(
            "ckpt.at-rest:truncate@1.0", seed=4))
        ck.save(1, _mini_state())
        faults.set_plan(None)
        # size mismatch: even the no-payload-read check rejects it, so
        # sync_plan never counts it into the fleet agreement
        assert ck.latest_verified_epoch() == 0
        state, nxt = ck.restore_latest(_mini_state())
        assert nxt == 1
        assert os.path.exists(os.path.join(d, "ckpt-1.npz.corrupt"))


def test_no_verified_generation_fails_with_manifest_diagnostic(tmp_path):
    from shifu_tensorflow_tpu.train.checkpoint import CheckpointCorruptError

    d = str(tmp_path / "ck")
    faults.set_plan(faults.FaultPlan.parse(
        "ckpt.at-rest:bitflip@1.0", seed=2))
    with NpzCheckpointer(d) as ck:
        ck.save(0, _mini_state())
        faults.set_plan(None)
        with pytest.raises(CheckpointCorruptError, match="manifest"):
            ck.restore_latest(_mini_state())
        # quarantined for the post-mortem, never silently deleted
        assert os.path.exists(os.path.join(d, "ckpt-0.npz.corrupt"))


def test_legacy_generation_without_manifest_still_restores(tmp_path):
    d = str(tmp_path / "ck")
    with NpzCheckpointer(d) as ck:
        ck.save(0, _mini_state())
        os.remove(os.path.join(d, "ckpt-0.npz.manifest.json"))
        # not "verified" (sync_plan won't count it) but restorable: the
        # npz parse is the remaining integrity guard
        assert ck.latest_verified_epoch() is None
        assert ck.latest_epoch() == 0
        state, nxt = ck.restore_latest(_mini_state())
        assert nxt == 1


def test_retention_sweep_removes_manifests_and_keeps_one_verified(tmp_path):
    d = str(tmp_path / "ck")
    with NpzCheckpointer(d, max_to_keep=2) as ck:
        _save_epochs(ck, 2)  # epochs 0, 1: verified
        faults.set_plan(faults.FaultPlan.parse(
            "ckpt.at-rest:truncate@1.0", seed=5))
        ck.save(2, _mini_state())  # sweep: survivors {1, 2}, 1 verified
        ck.save(3, _mini_state())  # sweep: survivors {2, 3} BOTH corrupt
        faults.set_plan(None)
        names = set(os.listdir(d))
        # epoch 0 swept together with its manifest
        assert "ckpt-0.npz" not in names
        assert "ckpt-0.npz.manifest.json" not in names
        # epoch 1 retained PAST the keep budget: it is the only verified
        # generation left
        assert "ckpt-1.npz" in names and "ckpt-1.npz.manifest.json" in names
        assert ck.latest_verified_epoch() == 1
        state, nxt = ck.restore_latest(_mini_state())
        assert nxt == 2


# --------------------------------------------------------------------------
# training-health guard: NaN detection, spike detector, hang watchdog
# --------------------------------------------------------------------------


def _health_trainer(health, epochs=3):
    from shifu_tensorflow_tpu.train.trainer import Trainer

    mc = ModelConfig.from_json(
        {"train": {"numTrainEpochs": epochs, "params": {
            "NumHiddenLayers": 1, "NumHiddenNodes": [4],
            "ActivationFunc": ["relu"], "LearningRate": 0.1}}}
    )
    return Trainer(mc, 3, health=health)


def test_health_guard_trips_on_injected_nan_with_step_index():
    from shifu_tensorflow_tpu.train.trainer import (
        HealthConfig,
        TrainingUnhealthy,
    )

    batches = _batches()
    faults.set_plan(faults.FaultPlan.parse(
        "health.nan-loss.e1:nan-loss@2", seed=1))
    tr = _health_trainer(HealthConfig())
    with pytest.raises(TrainingUnhealthy) as ei:
        tr.fit_stream(lambda e: iter(batches), epochs=3)
    faults.set_plan(None)
    assert ei.value.epoch == 1
    assert 2 in ei.value.bad_steps
    assert "non-finite" in ei.value.reason
    # diagnostics carry the evidence the coordinator bundles
    assert ei.value.diag["injected_nans"] == 1
    assert ei.value.diag["last_losses"]


def test_health_guard_padding_nan_never_trips():
    """The NaN-as-padding loss marker must stay invisible to the guard:
    an all-padding (zero-weight) batch reports NaN by contract."""
    from shifu_tensorflow_tpu.train.trainer import HealthConfig

    batches = _batches()
    pad = {k: np.zeros_like(v) for k, v in batches[0].items()}
    tr = _health_trainer(HealthConfig())
    hist = tr.fit_stream(lambda e: iter(batches + [pad]), epochs=2)
    assert len(hist) == 2  # no TrainingUnhealthy


def test_health_guard_disabled_lets_divergence_through():
    """Control arm: same injection, check_finite off -> the run completes
    with NaN parameters (the failure mode the guard exists to stop)."""
    import jax

    from shifu_tensorflow_tpu.train.trainer import HealthConfig

    batches = _batches()
    faults.set_plan(faults.FaultPlan.parse(
        "health.nan-loss.e1:nan-loss@2", seed=1))
    tr = _health_trainer(HealthConfig(check_finite=False))
    hist = tr.fit_stream(lambda e: iter(batches), epochs=3)
    faults.set_plan(None)
    assert len(hist) == 3
    assert any(
        np.isnan(np.asarray(leaf)).any()
        for leaf in jax.tree_util.tree_leaves(tr.state.params)
    )


def test_health_skip_window_avoids_replaying_the_bad_step():
    import jax

    from shifu_tensorflow_tpu.train.trainer import HealthConfig

    batches = _batches()
    faults.set_plan(faults.FaultPlan.parse(
        "health.nan-loss.e1:nan-loss@2", seed=1))
    tr = _health_trainer(HealthConfig(skip_epoch=1, skip_steps=(2,)))
    hist = tr.fit_stream(lambda e: iter(batches), epochs=3)
    faults.set_plan(None)
    assert len(hist) == 3
    assert tr.health_guard.skipped_steps == 1
    assert not any(
        np.isnan(np.asarray(leaf)).any()
        for leaf in jax.tree_util.tree_leaves(tr.state.params)
    )


def test_health_spike_detector_ema():
    from shifu_tensorflow_tpu.train.trainer import HealthConfig, HealthGuard

    g = HealthGuard(HealthConfig(
        check_finite=False, spike_factor=3.0, spike_min_epochs=2))

    def stats(e, loss):
        return EpochStats(
            worker_index=0, current_epoch=e, training_loss=loss,
            valid_loss=loss, training_time_s=0.0, valid_time_s=0.0,
            global_step=e,
        )

    g.begin_epoch(0)
    assert g.check_epoch(stats(0, 1.0)) is None
    g.begin_epoch(1)
    assert g.check_epoch(stats(1, 1.1)) is None
    g.begin_epoch(2)
    # within min_epochs x factor: 2.0 < 3 x EMA
    assert g.check_epoch(stats(2, 2.0)) is None
    g.begin_epoch(3)
    reason = g.check_epoch(stats(3, 50.0))
    assert reason is not None and "spike" in reason


def test_hang_watchdog_fires_and_reports():
    import time as _time

    from shifu_tensorflow_tpu.train.trainer import HealthConfig

    fired = []
    tr = _health_trainer(HealthConfig(hang_timeout_s=0.2))
    tr.health_guard.on_hang = lambda reason, diag: fired.append(
        (reason, diag))
    batches = _batches()

    def slow(e):
        yield batches[0]
        _time.sleep(0.7)  # stall well past the watchdog deadline
        yield batches[1]

    tr.fit_stream(slow, epochs=1)
    tr.health_guard.close()
    assert len(fired) == 1, "watchdog must fire exactly once"
    reason, diag = fired[0]
    assert "hung step" in reason and diag["epoch"] == 0


# --------------------------------------------------------------------------
# coordinated rollback: the 2-worker fleet chaos drill
# --------------------------------------------------------------------------


def _fleet_model_config(epochs):
    return ModelConfig.from_json(
        {"train": {"numTrainEpochs": epochs, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05, "Optimizer": "adam"}}}
    )


def _fleet_cfg_factory(psv_dataset, mc, ckpt_dir, *, check_finite=True):
    from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
    from shifu_tensorflow_tpu.data.reader import RecordSchema

    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )

    def make(worker_id, addr):
        return WorkerConfig(
            worker_id=worker_id,
            coordinator_host=addr[0],
            coordinator_port=addr[1],
            model_config=mc,
            schema=schema,
            batch_size=100,
            checkpoint_dir=ckpt_dir,
            heartbeat_interval_s=0.1,
            flat_checkpoint=True,  # the manifest-verified chain
            health_check_finite=check_finite,
        )

    return make


def test_fleet_chaos_drill_corrupt_ckpt_plus_nan_rolls_back_once(
        psv_dataset, tmp_path):
    """Acceptance drill: STPU_FAULT_PLAN corrupts a checkpoint at rest AND
    injects a NaN loss mid-run; the 2-worker fleet restores from the
    newest VERIFIED epoch, performs exactly ONE coordinated rollback, and
    finishes with finite parameters — the rollback visible in the job
    metrics."""
    from shifu_tensorflow_tpu.coordinator.submitter import (
        JobSubmitter,
        make_job_spec,
    )

    mc = _fleet_model_config(4)
    ckpt_dir = str(tmp_path / "fleet-ckpt")
    # the chief's 2nd checkpoint write (epoch 1) rots at rest; one worker
    # hits a NaN at epoch 2, step 1
    faults.set_plan(faults.FaultPlan.parse(
        "ckpt.at-rest:bitflip@2,health.nan-loss.e2:nan-loss@2", seed=77))
    spec = make_job_spec(
        psv_dataset["root"], 2, epochs=4,
        registration_timeout_s=30.0, spare_restarts=3,
        sync_epochs=True, epoch_barrier_timeout_s=60.0,
        health_max_rollbacks=2,
    )
    sub = JobSubmitter(
        spec, _fleet_cfg_factory(psv_dataset, mc, ckpt_dir),
    )
    result = sub.run(timeout_s=180.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    assert result.rollbacks_used == 1, (
        "exactly one coordinated rollback expected")
    # the corrupt epoch-1 generation was quarantined by the roll-back
    # restore, and the final state restores verified and finite
    assert any(n.startswith("ckpt-1.npz") and n.endswith(".corrupt")
               for n in os.listdir(ckpt_dir))
    with NpzCheckpointer(ckpt_dir) as ck:
        assert ck.latest_verified_epoch() == 3
        state, nxt = ck.restore_latest(_mini_state_like(ckpt_dir))
    assert nxt == 4


def _mini_state_like(ckpt_dir):
    """Template state matching the fleet drill's DNN tree: build the same
    trainer shape the workers used."""
    import glob as _glob
    import io as _io
    import json as _json

    # read leaf count from the newest manifest and rebuild a template via
    # a fresh trainer of the same architecture
    from shifu_tensorflow_tpu.train import make_trainer

    mc = _fleet_model_config(4)
    tr = make_trainer(mc, 10)
    return tr.state


def test_fleet_chaos_drill_without_health_layer_diverges(
        psv_dataset, tmp_path):
    """Control arm: the same fault plan with the health layer disabled —
    the job 'finishes' but the published model is garbage (NaN params),
    or fails outright.  Either way it cannot produce the verified finite
    artifact the guarded run does."""
    import jax

    from shifu_tensorflow_tpu.coordinator.submitter import (
        JobSubmitter,
        make_job_spec,
    )

    mc = _fleet_model_config(4)
    ckpt_dir = str(tmp_path / "ctrl-ckpt")
    faults.set_plan(faults.FaultPlan.parse(
        "health.nan-loss.e2:nan-loss@2", seed=77))
    spec = make_job_spec(
        psv_dataset["root"], 1, epochs=4,
        registration_timeout_s=30.0,
    )
    sub = JobSubmitter(
        spec,
        _fleet_cfg_factory(psv_dataset, mc, ckpt_dir, check_finite=False),
    )
    result = sub.run(timeout_s=120.0)
    assert result.rollbacks_used == 0
    if result.state == JobState.FINISHED:
        with NpzCheckpointer(ckpt_dir) as ck:
            state, _ = ck.restore_latest(_mini_state_like(ckpt_dir))
        assert any(
            np.isnan(np.asarray(leaf)).any()
            for leaf in jax.tree_util.tree_leaves(state.params)
        ), "without the health layer the drill must diverge"


def test_rollback_budget_exhaustion_fails_fast_with_diagnostics():
    """Budget exhausted -> clean FAILED with the diagnostic bundle (last
    losses, per-worker heartbeat ages), never a hang."""
    coord = Coordinator(_spec(2, spmd=True, spare_restarts=9,
                              health_max_rollbacks=1))
    coord.register("a", 0, host="h", jax_port=1)
    coord.register("b", 1, host="h")
    r1 = coord.report_unhealthy(
        "a", 1, "nan loss", bad_steps=[2],
        diag={"last_losses": [0.4, float("nan")]})
    assert r1["ok"] and r1["fleet"]
    coord.register("a", 0, host="h", jax_port=1)
    coord.register("b", 1, host="h")
    r2 = coord.report_unhealthy("a", 1, "nan loss again", bad_steps=[2])
    assert r2.get("abort")
    assert coord.state == JobState.FAILED
    assert "rollback budget exhausted" in coord.failure_reason
    assert "last_heartbeat_age_s" in coord.failure_reason  # diagnostics
    d = coord.diagnostics()
    assert d["last_unhealthy"]["reason"] == "nan loss again"
    assert d["rollbacks"] == 2
    coord.liveness.stop()


def test_unhealthy_duplicate_delivery_charges_budget_once():
    coord = Coordinator(_spec(2, spmd=True, spare_restarts=9,
                              health_max_rollbacks=5))
    coord.register("a", 0, host="h", jax_port=1)
    coord.register("b", 1, host="h")
    msg = {"op": "unhealthy", "worker_id": "a", "epoch": 1,
           "reason": "nan", "bad_steps": [3], "token": "tok-u1"}
    coord.dispatch(dict(msg))
    coord.dispatch(dict(msg))  # retried delivery
    assert coord.op_replays == 1
    assert coord._rollbacks == 1, "duplicate delivery double-charged"
    # peer reporting the same root cause dedups by generation
    r = coord.report_unhealthy("b", 1, "nan", bad_steps=[3])
    assert r.get("deduped")
    assert coord._rollbacks == 1
    coord.liveness.stop()
