"""Artifact-contract tests — the TPU-side mirror of the reference's only
real test, TensorflowModelTest.testCompute (SURVEY.md §4 item 4): exported
model must carry shifu_input_0/shifu_output_0, the serve tag, and a
GenericModelConfig.json with normtype ZSCALE; scores must be in [0,1] and
the scoring path must agree with in-process inference."""

import json
import os

import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.data.dataset import InMemoryDataset
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.export.eval_model import EvalModel
from shifu_tensorflow_tpu.export.saved_model import (
    GENERIC_CONFIG,
    export_model,
    generic_model_config_json,
)
from shifu_tensorflow_tpu.train.trainer import Trainer


def _trained(psv_dataset, tmp_path, epochs=1):
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    ds = InMemoryDataset.load(psv_dataset["paths"], schema, 0.2)
    mc = ModelConfig.from_json(
        {"train": {"numTrainEpochs": epochs, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05, "Optimizer": "adam"}}}
    )
    t = Trainer(mc, ds.schema.num_features)
    t.fit(ds, batch_size=100)
    export_dir = str(tmp_path / "model")
    status = export_model(export_dir, t,
                          feature_columns=psv_dataset["feature_cols"])
    return t, ds, export_dir, status


def test_generic_model_config_exact_reference_content():
    cfg = json.loads(generic_model_config_json())
    assert cfg["inputnames"] == ["shifu_input_0"]
    assert cfg["properties"]["outputnames"] == "shifu_output_0"
    assert cfg["properties"]["tags"] == ["serve"]
    assert cfg["properties"]["normtype"] == "ZSCALE"
    assert cfg["properties"]["algorithm"] == "tensorflow"


def test_native_bundle_roundtrip(psv_dataset, tmp_path):
    t, ds, export_dir, status = _trained(psv_dataset, tmp_path)
    assert status["native"]
    assert os.path.exists(os.path.join(export_dir, GENERIC_CONFIG))
    with EvalModel(export_dir, backend="native") as em:
        x = ds.valid.features[:50]
        got = em.compute_batch(x)
        want = t.predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        # single-row Computable parity + output range contract
        score = em.compute(x[0])
        assert 0.0 <= score <= 1.0
        # 1522 random rows like TensorflowModelTest (shrunk to 200 for speed)
        rand = np.random.default_rng(0).random((200, ds.schema.num_features))
        out = em.compute_batch(rand.astype(np.float32))
        assert ((out >= 0) & (out <= 1)).all()


def test_eval_model_feature_width_check(psv_dataset, tmp_path):
    _, _, export_dir, _ = _trained(psv_dataset, tmp_path)
    with EvalModel(export_dir, backend="native") as em:
        with pytest.raises(ValueError, match="features"):
            em.compute_batch(np.zeros((2, 3), np.float32))


def test_saved_model_contract(psv_dataset, tmp_path):
    tf = pytest.importorskip("tensorflow")
    t, ds, export_dir, status = _trained(psv_dataset, tmp_path)
    assert status["saved_model"], "TF available but SavedModel export failed"
    # the artifact itself carries the serve tag + signature names
    from tensorflow.python.tools import saved_model_utils

    meta = saved_model_utils.get_meta_graph_def(export_dir, "serve")
    sig = meta.signature_def["serving_default"]
    assert list(sig.inputs.keys()) == ["shifu_input_0"]
    assert list(sig.outputs.keys()) == ["shifu_output_0"]
    # scoring through the TF signature matches in-process inference
    with EvalModel(export_dir, backend="saved_model") as em:
        x = ds.valid.features[:32]
        got = em.compute_batch(x)
        want = t.predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_export_with_zscale_stats(psv_dataset, tmp_path):
    t, ds, export_dir, _ = _trained(psv_dataset, tmp_path)
    means = [0.1] * ds.schema.num_features
    stds = [2.0] * ds.schema.num_features
    export_dir2 = str(tmp_path / "model-z")
    export_model(export_dir2, t, feature_columns=psv_dataset["feature_cols"],
                 zscale_means=means, zscale_stds=stds)
    with EvalModel(export_dir2, backend="native") as em:
        raw = ds.valid.features[:10]
        got = em.compute_batch(raw)
        want = t.predict((raw - 0.1) / 2.0)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_saved_model_backend_applies_zscale(psv_dataset, tmp_path):
    pytest.importorskip("tensorflow")
    t, ds, _, _ = _trained(psv_dataset, tmp_path)
    means = [0.5] * ds.schema.num_features
    stds = [3.0] * ds.schema.num_features
    export_dir = str(tmp_path / "model-z2")
    export_model(export_dir, t, feature_columns=psv_dataset["feature_cols"],
                 zscale_means=means, zscale_stds=stds)
    raw = ds.valid.features[:8]
    with EvalModel(export_dir, backend="native") as a, \
         EvalModel(export_dir, backend="saved_model") as b:
        np.testing.assert_allclose(a.compute_batch(raw), b.compute_batch(raw),
                                   rtol=1e-4, atol=1e-5)


def test_export_does_not_mutate_trainer_config(tmp_path):
    """Forcing SeqAttention='full' for the serving rebuild must act on a
    deep copy: the trainer's raw config is reused for WorkerConfig
    transport and re-exports, so a shallow-copy mutation would silently
    swap ring/auto attention for full on the live job."""
    mc = ModelConfig.from_json(
        {"train": {"numTrainEpochs": 1, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05, "Optimizer": "adam",
                              "ModelType": "sequence", "SeqLen": 8,
                              "SeqDModel": 8, "SeqHeads": 2, "SeqBlocks": 1,
                              "SeqAttention": "auto"}}}
    )
    t = Trainer(mc, 8)
    export_model(str(tmp_path / "seq-model"), t)
    assert t.model_config.raw["train"]["params"]["SeqAttention"] == "auto"
    assert t.model_config.params.seq_attention == "auto"


def test_export_defaults_feature_columns_from_trainer(tmp_path):
    """A caller that omits feature_columns must get the TRAINING graph's
    column positions, not a 0..n-1 default — otherwise wide_deep/embedding
    scores silently disagree between training and serving."""
    cols = (2, 4, 5, 7, 9, 11, 12, 14, 15, 17)
    mc = ModelConfig.from_json(
        {"train": {"numTrainEpochs": 1, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05, "Optimizer": "adam",
                              "EmbeddingColumnNums": [4],
                              "EmbeddingHashSize": 32, "EmbeddingDim": 4}}}
    )
    t = Trainer(mc, len(cols), feature_columns=cols)
    export_dir = str(tmp_path / "cols-model")
    export_model(export_dir, t)  # no feature_columns kwarg
    arch = json.loads(
        open(os.path.join(export_dir, "shifu_tpu_model.json")).read()
    )
    assert tuple(arch["feature_columns"]) == cols
    # and the serving scores use those positions
    x = np.random.default_rng(1).random((16, len(cols))).astype(np.float32)
    with EvalModel(export_dir, backend="native") as em:
        np.testing.assert_allclose(
            em.compute_batch(x), t.predict(x), rtol=1e-5, atol=1e-6
        )


# ---- C++ scorer (cpp/stpu_scorer.cc — JNI-evaluator parity path) ----

def _cpp_available():
    from shifu_tensorflow_tpu.export import native_scorer

    return native_scorer.available()


needs_cpp = pytest.mark.skipif(
    not _cpp_available(), reason="native scorer library unavailable"
)


@needs_cpp
def test_cpp_scorer_matches_python(psv_dataset, tmp_path):
    t, ds, export_dir, _ = _trained(psv_dataset, tmp_path)
    x = ds.valid.features[:200]
    with EvalModel(export_dir, backend="native") as py_em, \
            EvalModel(export_dir, backend="cpp") as cpp_em:
        want = py_em.compute_batch(x)
        got = cpp_em.compute_batch(x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)
    assert got.min() >= 0.0 and got.max() <= 1.0
    # single-row compute parity (Computable.compute contract)
    with EvalModel(export_dir, backend="cpp") as em:
        assert abs(em.compute(x[0]) - float(want[0, 0])) < 1e-5


@needs_cpp
def test_cpp_scorer_applies_zscale(psv_dataset, tmp_path):
    """ZSCALE happens inside the native code; both backends must agree on
    raw (un-normalized) inputs."""
    from shifu_tensorflow_tpu.data.reader import RecordSchema

    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    ds = InMemoryDataset.load(psv_dataset["paths"], schema, 0.2)
    mc = ModelConfig.from_json(
        {"train": {"numTrainEpochs": 1, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 2, "NumHiddenNodes": [8, 4],
                              "ActivationFunc": ["tanh", "weird_name"],
                              "LearningRate": 0.05, "Optimizer": "adam"}}}
    )
    t = Trainer(mc, schema.num_features)
    t.fit(ds, batch_size=100)
    export_dir = str(tmp_path / "zs-model")
    means = [0.1] * schema.num_features
    stds = [2.0] * schema.num_features
    export_model(export_dir, t, feature_columns=psv_dataset["feature_cols"],
                 zscale_means=means, zscale_stds=stds)
    x = ds.valid.features[:64]
    with EvalModel(export_dir, backend="native") as py_em, \
            EvalModel(export_dir, backend="cpp") as cpp_em:
        np.testing.assert_allclose(
            cpp_em.compute_batch(x), py_em.compute_batch(x),
            rtol=2e-5, atol=2e-6,
        )


@needs_cpp
def test_three_way_scorer_parity_single_artifact(psv_dataset, tmp_path):
    """The round-3 verdict's Java-eval closure (as far as this environment
    allows): ONE exported artifact scored through (a) the TF SavedModel
    signature — the exact graph contract the reference's Java consumer
    loads (TensorflowModel.java:112-172, SavedModelBundle.load + feed/
    fetch by tensor name), (b) the C++ scorer (the JNI-call-pattern
    stand-in), and (c) the jitted flax scorer — all three must agree to
    float tolerance on the same raw batch, with ZSCALE applied inside
    each backend.  Agreement pins both downstream consumers to one
    numeric contract."""
    pytest.importorskip("tensorflow")
    t, ds, _, _ = _trained(psv_dataset, tmp_path)
    means = [0.2] * ds.schema.num_features
    stds = [1.5] * ds.schema.num_features
    export_dir = str(tmp_path / "three-way")
    export_model(export_dir, t, feature_columns=psv_dataset["feature_cols"],
                 zscale_means=means, zscale_stds=stds)
    x = ds.valid.features[:128]
    with EvalModel(export_dir, backend="native") as a, \
            EvalModel(export_dir, backend="saved_model") as b, \
            EvalModel(export_dir, backend="cpp") as c:
        sa, sb, sc = (m.compute_batch(x) for m in (a, b, c))
    np.testing.assert_allclose(sb, sa, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(sc, sa, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(sc, sb, rtol=1e-4, atol=1e-5)


@needs_cpp
@pytest.mark.parametrize("family_params", [
    pytest.param({"ModelType": "wide_deep", "WideColumnNums": [2, 3],
                  "CrossHashSize": 32}, id="wide_deep"),
    pytest.param({"ModelType": "multi_task", "NumTasks": 3},
                 id="multi_task"),
    pytest.param({"EmbeddingColumnNums": [2, 5], "EmbeddingHashSize": 64,
                  "EmbeddingDim": 4}, id="embedding"),
    pytest.param({"ModelType": "wide_deep", "WideColumnNums": [2, 3],
                  "CrossHashSize": 32, "EmbeddingColumnNums": [2, 5],
                  "EmbeddingHashSize": 64, "EmbeddingDim": 4},
                 id="wide_deep_embedding"),
])
def test_cpp_scorer_all_families_three_way(psv_dataset, tmp_path,
                                           family_params):
    """r04 verdict item 4: every exported family scores through all three
    backends — jitted flax, C++ (hashing bit-identical to ops/hashing.py),
    and the TF SavedModel signature when TF is importable — against ONE
    artifact with ZSCALE applied inside each backend."""
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    ds = InMemoryDataset.load(psv_dataset["paths"], schema, 0.2)
    mc = ModelConfig.from_json(
        {"train": {"numTrainEpochs": 1, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 2, "NumHiddenNodes": [8, 4],
                              "ActivationFunc": ["relu", "tanh"],
                              "LearningRate": 0.05, "Optimizer": "adam",
                              **family_params}}}
    )
    t = Trainer(mc, schema.num_features,
                feature_columns=schema.feature_columns)
    t.fit(ds, batch_size=100)
    export_dir = str(tmp_path / "fam-model")
    means = [0.2] * schema.num_features
    stds = [1.5] * schema.num_features
    export_model(export_dir, t, feature_columns=psv_dataset["feature_cols"],
                 zscale_means=means, zscale_stds=stds)
    x = ds.valid.features[:128]
    with EvalModel(export_dir, backend="native") as py_em, \
            EvalModel(export_dir, backend="cpp") as cpp_em:
        want = py_em.compute_batch(x)
        got = cpp_em.compute_batch(x)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)
    assert got.min() >= 0.0 and got.max() <= 1.0
    try:
        import tensorflow  # noqa: F401
    except Exception:
        return
    with EvalModel(export_dir, backend="saved_model") as tf_em:
        tf_scores = tf_em.compute_batch(x)
    np.testing.assert_allclose(tf_scores, want, rtol=1e-4, atol=1e-5)


@needs_cpp
def test_cpp_scorer_rejects_sequence_family(psv_dataset, tmp_path):
    """The one family the native scorer does not cover: attention serving
    goes through the Python/jitted scorer, and the load must say so."""
    t, ds, export_dir, _ = _trained(psv_dataset, tmp_path)
    arch_path = os.path.join(export_dir, "shifu_tpu_model.json")
    arch = json.loads(open(arch_path).read())
    arch["model_config"]["train"]["params"]["ModelType"] = "sequence"
    open(arch_path, "w").write(json.dumps(arch))
    with pytest.raises(RuntimeError, match="sequence"):
        EvalModel(export_dir, backend="cpp")
