"""SLO watchdog (obs/slo.py): P² digest accuracy, sliding-window
expiry, EWMA-z anomaly detection, the hysteretic breach/recover state
machine with journaled transitions, and the install_obs wiring."""

from __future__ import annotations

import random

import pytest

from shifu_tensorflow_tpu.obs import journal as journal_mod
from shifu_tensorflow_tpu.obs import slo as slo_mod
from shifu_tensorflow_tpu.obs import trace as trace_mod
from shifu_tensorflow_tpu.obs.config import ObsConfig
from shifu_tensorflow_tpu.obs.journal import Journal, read_events
from shifu_tensorflow_tpu.obs.slo import (
    EwmaZ,
    P2Quantile,
    SloWatchdog,
    WindowedCounter,
    WindowedDigest,
)


@pytest.fixture(autouse=True)
def _clean_obs_hooks():
    yield
    trace_mod.uninstall()
    journal_mod.uninstall()
    slo_mod.uninstall()


# ---- P² quantile estimator ----

@pytest.mark.parametrize("p", [0.5, 0.9, 0.99])
def test_p2_quantile_tracks_true_quantile(p):
    rng = random.Random(7)
    xs = [rng.lognormvariate(0.0, 0.5) for _ in range(20_000)]
    est = P2Quantile(p)
    for x in xs:
        est.add(x)
    true = sorted(xs)[int(p * len(xs)) - 1]
    assert est.value() == pytest.approx(true, rel=0.05)


def test_p2_quantile_point_estimate_beats_bucket_bound():
    """The motivating defect: LatencyHistogram.percentile returns the
    bucket UPPER BOUND — a p99 at 3ms reads as 5ms on the default
    ladder.  P² interpolates; movement within one bucket is visible."""
    from shifu_tensorflow_tpu.obs.registry import LatencyHistogram

    rng = random.Random(3)
    hist = LatencyHistogram()
    est = P2Quantile(0.99)
    xs = [0.003 + 0.0002 * rng.random() for _ in range(5000)]
    for x in xs:
        hist.record(x)
        est.add(x)
    true = sorted(xs)[int(0.99 * len(xs)) - 1]
    assert hist.percentile(99) == 0.005  # the ladder bound above 3ms
    assert est.value() == pytest.approx(true, rel=0.02)


def test_p2_quantile_small_counts_nearest_rank():
    est = P2Quantile(0.5)
    assert est.value() is None
    for x in (5.0, 1.0, 3.0):
        est.add(x)
    assert est.value() == 3.0  # median of {1, 3, 5}


# ---- P² edge cases the data-plane taps now hit (obs/datastats.py
# feeds one estimator per feature per quantile, including constant
# columns, tiny live windows, and unbounded client payloads) ----

@pytest.mark.parametrize("n", [1, 2, 3, 4])
def test_p2_quantile_under_five_samples_matches_nearest_rank(n):
    import math

    rng = random.Random(11)
    for p in (0.05, 0.5, 0.95):
        xs = [rng.uniform(-10, 10) for _ in range(n)]
        est = P2Quantile(p)
        for x in xs:
            est.add(x)
        ordered = sorted(xs)
        rank = max(0, min(n - 1, int(math.ceil(p * n)) - 1))
        assert est.value() == ordered[rank]


def test_p2_quantile_constant_stream_is_exact():
    for p in (0.05, 0.5, 0.99):
        est = P2Quantile(p)
        for _ in range(1000):
            est.add(7.25)
        assert est.value() == 7.25


def test_p2_quantile_adversarial_extremes_stay_finite_and_bounded():
    """Alternating ±1e30 spikes around a tiny signal: the estimate must
    stay FINITE and inside [observed min, observed max] — no inf/NaN
    out of the parabolic update's divisions.  (The marker heights DO
    get dragged by such spikes — a documented P² property; the data
    leg's drift score treats that consistently, because a baseline
    carrying the same spikes has an equally dragged scale.)"""
    import math

    rng = random.Random(5)
    est = P2Quantile(0.5)
    lo, hi = float("inf"), float("-inf")
    for i in range(5000):
        if i % 97 == 0:
            x = 1e30 if (i // 97) % 2 == 0 else -1e30
        else:
            x = rng.gauss(0.0, 1e-6)
        lo, hi = min(lo, x), max(hi, x)
        est.add(x)
    v = est.value()
    assert math.isfinite(v)
    assert lo <= v <= hi
    # a clean stream after the spikes pulls the markers back toward the
    # bulk (monotone marker ordering survives the abuse)
    for _ in range(50_000):
        est.add(rng.gauss(0.0, 1e-6))
    v2 = est.value()
    assert math.isfinite(v2) and abs(v2) < abs(v)


@pytest.mark.parametrize("dist,p,rel,abs_", [
    ("normal", 0.05, None, 0.08),
    ("normal", 0.5, None, 0.05),
    ("normal", 0.95, None, 0.08),
    ("uniform", 0.5, 0.05, None),
    ("uniform", 0.95, 0.05, None),
    ("exponential", 0.5, 0.08, None),
    ("exponential", 0.95, 0.08, None),
])
def test_p2_quantile_pinned_against_numpy(dist, p, rel, abs_):
    import numpy as np

    rng = np.random.default_rng(42)
    xs = {
        "normal": lambda: rng.normal(0.0, 1.0, 8000),
        "uniform": lambda: rng.uniform(1.0, 3.0, 8000),
        "exponential": lambda: rng.exponential(2.0, 8000),
    }[dist]()
    est = P2Quantile(p)
    for x in xs:
        est.add(float(x))
    want = float(np.quantile(xs, p))
    assert est.value() == pytest.approx(want, rel=rel, abs=abs_)


# ---- sliding window ----

def test_windowed_digest_expires_old_cells():
    d = WindowedDigest(window_s=10.0, buckets=5)
    t = 1000.0
    for i in range(100):
        d.add(float(i), now=t + i * 0.01)
    snap = d.snapshot(now=t + 1.0)
    assert snap["count"] == 100
    assert snap["max"] == 99.0
    assert 0 < snap["p50"] < 99.0
    # past the window: the signal is ABSENT, not zero
    assert d.snapshot(now=t + 20.0) is None


def test_windowed_digest_window_moves_with_load():
    """Observations only in the latest window bucket dominate once the
    older cells expire — a latency spike ages out instead of pinning the
    p99 forever (the failure mode of a cumulative histogram)."""
    d = WindowedDigest(window_s=10.0, buckets=5)
    t = 1000.0
    for _ in range(500):
        d.add(5.0, now=t)
    for i in range(500):
        d.add(0.001, now=t + 9.0 + i * 0.001)
    # both cells live: the old spike still in the window stat
    assert d.snapshot(now=t + 9.5)["p99"] > 1.0
    # spike cell expired, only the fast cell remains
    snap = d.snapshot(now=t + 13.0)
    assert snap["count"] == 500 and snap["p99"] < 0.01


def test_windowed_counter_rate_window():
    c = WindowedCounter(window_s=10.0, buckets=5)
    t = 1000.0
    c.add(5, now=t)
    c.add(3, now=t + 4.0)
    assert c.total(now=t + 5.0) == 8
    assert c.total(now=t + 11.0) == 3  # first cell expired
    assert c.total(now=t + 30.0) == 0


# ---- anomaly detection ----

def test_ewma_z_warmup_then_detects_jump():
    rng = random.Random(0)
    e = EwmaZ(warmup=8)
    zs = [e.update(1.0 + 0.02 * rng.random()) for _ in range(20)]
    assert all(z is None for z in zs[:8])
    assert all(abs(z) < 3 for z in zs[10:] if z is not None)
    assert e.update(3.0) > 6.0  # a 3x jump clears any sane sigma


# ---- watchdog state machine ----

def _watchdog(**kw):
    kw.setdefault("window_s", 10.0)
    kw.setdefault("hysteresis", 2)
    kw.setdefault("plane", "serve")
    return SloWatchdog(**kw)


def test_breach_requires_hysteresis_consecutive_ticks():
    wd = _watchdog()
    wd.track("lat", stat="p99", target=0.1)
    wd.observe("lat", 0.5)
    assert wd.evaluate() == []  # first breaching tick: no event yet
    events = wd.evaluate()
    assert [e["event"] for e in events] == ["slo_breach"]
    ev = events[0]
    assert ev["signal"] == "lat" and ev["value"] > ev["target"]
    # the offending window's digest snapshot rides the event
    assert ev["window"]["count"] == 1 and ev["window"]["p99"] == 0.5
    assert wd.evaluate() == []  # still breached: state, not a tick flood


def test_recover_requires_hysteresis_and_carries_duration():
    wd = _watchdog(hysteresis=1)
    wd.track("lat", stat="p99", target=0.1)
    wd.observe("lat", 0.5)
    assert [e["event"] for e in wd.evaluate()] == ["slo_breach"]
    # clean window: one OK tick recovers at hysteresis=1.  The window
    # still holds the old 0.5 — pass an explicit `now` past the window
    # so the stat is re-evaluated on fresh (absent) data.
    t = slo_mod._mono() + 60.0
    events = wd.evaluate(now=t)
    assert [e["event"] for e in events] == ["slo_recover"]
    assert events[0]["breach_s"] == pytest.approx(60.0, abs=1.0)


def test_empty_window_counts_as_clean_not_breaching():
    """A shed storm that drove every client away leaves an empty latency
    window — that must recover the signal, never pin the breach."""
    wd = _watchdog(hysteresis=1)
    wd.track("lat", stat="p99", target=0.1)
    assert wd.evaluate() == []  # no data, no breach
    wd.observe("lat", 9.0)
    assert [e["event"] for e in wd.evaluate()] == ["slo_breach"]
    assert [e["event"] for e in wd.evaluate(now=slo_mod._mono() + 99.0)] \
        == ["slo_recover"]


def test_rate_signal_breach_and_recover():
    wd = _watchdog(hysteresis=1, window_s=5.0)
    wd.track_rate("shed_rate", num="shed", den="requests", target=0.25)
    for _ in range(10):
        wd.count("requests")
    for _ in range(5):
        wd.count("shed")
    events = wd.evaluate()
    assert [e["event"] for e in events] == ["slo_breach"]
    assert events[0]["value"] == pytest.approx(0.5)
    # window drains -> denominator 0 -> absent -> clean tick
    assert [e["event"] for e in wd.evaluate(now=slo_mod._mono() + 30.0)] \
        == ["slo_recover"]


def test_untargeted_signal_never_breaches_but_alarms_on_anomaly():
    wd = _watchdog(hysteresis=1, anomaly_sigma=6.0)
    wd.track("lat", stat="p99", target=0.0)
    rng = random.Random(1)
    # steady state through warmup: one evaluation per observation so the
    # EWMA sees a stable signal
    for i in range(12):
        wd.observe("lat", 0.010 + 0.0002 * rng.random(),
                   )
        assert wd.evaluate(now=slo_mod._mono() + i * 0.1) == []
    # sustained 20x excursion (a real p99 jump is many slow requests —
    # P² needs a handful of them to converge onto the new level):
    # anomaly fires once, not on every following tick
    for _ in range(20):
        wd.observe("lat", 0.2)
    events = wd.evaluate()
    assert [e["event"] for e in events] == ["slo_anomaly"]
    assert events[0]["z"] >= 6.0
    assert wd.evaluate() == []  # same excursion: no repeat


def test_watchdog_journals_transitions_with_plane_and_ids(tmp_path):
    journal_mod.install(Journal(str(tmp_path / "j.jsonl"), plane="serve",
                                worker=1, job="jobx"))
    wd = _watchdog(hysteresis=1, plane="serve", worker=1)
    wd.track("lat", stat="p99", target=0.1)
    wd.observe("lat", 0.9)
    wd.evaluate(epoch=3)
    journal_mod.uninstall()
    events = read_events(str(tmp_path / "j.jsonl"))
    assert [e["event"] for e in events] == ["slo_breach"]
    ev = events[0]
    assert ev["plane"] == "serve" and ev["worker"] == 1
    assert ev["job"] == "jobx" and ev["epoch"] == 3
    assert ev["window"]["count"] == 1


def test_watchdog_renders_stpu_slo_gauges():
    wd = _watchdog(hysteresis=1)
    wd.track("serve_p99_s", stat="p99", target=0.25)
    wd.observe("serve_p99_s", 0.5)
    wd.evaluate()
    text = wd.render_prometheus()
    assert "stpu_slo_serve_p99_s 0.5" in text
    assert "stpu_slo_serve_p99_s_target 0.25" in text
    assert "stpu_slo_serve_p99_s_breached 1" in text


# ---- config + install wiring ----

def test_from_config_registers_plane_signals():
    cfg = ObsConfig(enabled=True, slo_serve_p99_ms=250.0,
                    slo_serve_shed_rate=0.2, slo_step_time_ms=50.0,
                    slo_infeed_frac=0.3, slo_window_s=30.0,
                    slo_hysteresis=3)
    # the device/compiler signals (PR 10) and the data-drift signal
    # (PR 12) ride every plane
    device = {"compile_s", "devmem_frac", "data_drift_score"}
    serve = slo_mod.from_config(cfg, plane="serve", worker=2)
    assert set(serve.state()) == {"serve_p99_s", "serve_shed_rate"} | device
    assert serve.state()["serve_p99_s"]["target"] == pytest.approx(0.25)
    assert serve.hysteresis == 3 and serve.window_s == 30.0
    # the fleet leg (PR 11) adds the straggler-skew signal on the
    # train/coordinator planes (fed by the coordinator's FleetMonitor)
    train_set = {"train_step_ms", "train_infeed_frac",
                 "fleet_skew"} | device
    train = slo_mod.from_config(cfg, plane="train")
    assert set(train.state()) == train_set
    assert train.state()["train_step_ms"]["target"] == 50.0
    # epoch-level samples: the step-time stat is a windowed mean, not a
    # per-step p99 the aggregate tracer cannot provide
    assert train.state()["train_step_ms"]["stat"] == "mean"
    # one slow rank is the breach, not the fleet's average skew
    assert train.state()["fleet_skew"]["stat"] == "max"
    # the coordinator plane registers the train signals too — on the
    # thread launcher its process HOSTS the trainers, which pick this
    # watchdog up via slo.active(); without them the configured train
    # targets would be silently dead
    coord = slo_mod.from_config(cfg, plane="coordinator")
    assert set(coord.state()) == train_set
    assert coord.state()["train_step_ms"]["target"] == 50.0


def test_obs_config_validates_slo_fields():
    with pytest.raises(ValueError, match="slo-window"):
        ObsConfig(slo_window_s=0)
    with pytest.raises(ValueError, match="slo-hysteresis"):
        ObsConfig(slo_hysteresis=0)
    with pytest.raises(ValueError, match="slo-serve-p99"):
        ObsConfig(slo_serve_p99_ms=-1)
    with pytest.raises(ValueError, match="fraction"):
        ObsConfig(slo_serve_shed_rate=1.5)


def test_install_obs_installs_and_clears_watchdog(tmp_path):
    from shifu_tensorflow_tpu.obs import install_obs

    install_obs(ObsConfig(enabled=True,
                          journal_path=str(tmp_path / "j.jsonl")),
                plane="serve", worker_index=0)
    wd = slo_mod.active()
    assert wd is not None and wd.plane == "serve" and wd.worker == 0
    # a disabled config clears a stale watchdog (process reuse in tests)
    install_obs(ObsConfig())
    assert slo_mod.active() is None
