"""Chunked + flash attention — numerics vs full attention.

The chunked path (parallel/ring.py chunked_attention) is the XLA
online-softmax scan; the flash path (ops/pallas/flash_attention.py) is
the Pallas TPU kernel, exercised here in interpret mode on CPU (the
same kernel runs compiled on TPU; on-chip parity is covered by the
bench's parity preamble and was validated on the real chip — see
docs/benchmarks.md sequence section).  Tolerances are tight here
because CPU math is uniform; on the TPU MXU, blocked-vs-monolithic f32
matmul orderings differ at ~1e-3 and checks must be scale-aware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shifu_tensorflow_tpu.models.sequence import make_attention
from shifu_tensorflow_tpu.ops.pallas.flash_attention import flash_attention
from shifu_tensorflow_tpu.parallel.mesh import make_mesh
from shifu_tensorflow_tpu.parallel.ring import (
    chunked_attention,
    full_attention,
)


def _qkv(b=2, s=96, h=4, d=24, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [16, 32, 96, 7])  # 7: non-divisor
def test_chunked_matches_full(causal, block):
    q, k, v = _qkv()
    want = full_attention(q, k, v, causal=causal)
    got = chunked_attention(q, k, v, causal=causal, block_size=block)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_chunked_prime_seq_pads_instead_of_collapsing(causal):
    # S=127 (prime): a largest-divisor block search would collapse to
    # blk=1 — an S-step scan with an S×carry backward; the padding path
    # must keep the requested block and mask the padded keys
    q, k, v = _qkv(s=127)
    want = full_attention(q, k, v, causal=causal)
    got = chunked_attention(q, k, v, causal=causal, block_size=32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [96, 127])  # 127: the padded bwd branch
def test_chunked_grads_match_full(causal, s):
    q, k, v = _qkv(s=s)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    want = jax.grad(
        loss(lambda q, k, v: full_attention(q, k, v, causal=causal)),
        (0, 1, 2))(q, k, v)
    got = jax.grad(
        loss(lambda q, k, v: chunked_attention(
            q, k, v, causal=causal, block_size=32)),
        (0, 1, 2))(q, k, v)
    for w, g in zip(want, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [64, 96, 320])  # 96/320: pad the blocks
def test_flash_matches_full(causal, s):
    q, k, v = _qkv(s=s)
    want = full_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_mismatched_blocks_cover_whole_sequence():
    # regression: S must pad to a common multiple of BOTH blocks — with
    # only max(bq, bk) the smaller block's grid dimension floors and
    # trailing rows/keys are silently dropped
    q, k, v = _qkv(s=100)
    want = full_attention(q, k, v)
    for bq, bk in ((64, 96), (96, 64)):
        got = flash_attention(q, k, v, False, bq, bk)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_flash_grads_match_full():
    q, k, v = _qkv(s=128)
    want = jax.grad(
        lambda q, k, v: jnp.sum(full_attention(q, k, v, causal=True) ** 2),
        (0, 1, 2))(q, k, v)
    got = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, True) ** 2),
        (0, 1, 2))(q, k, v)
    for w, g in zip(want, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


def test_flash_under_jit_and_vmapped_model_shapes():
    # the shape the sequence family actually feeds: bf16, D=32
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.normal(size=(4, 256, 4, 32)),
                           jnp.bfloat16) for _ in range(3))
    out = jax.jit(lambda q, k, v: flash_attention(q, k, v))(q, k, v)
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=0.05, atol=0.05)


def test_make_attention_resolution(monkeypatch):
    # default: auto on a single device is ALWAYS full (the measured
    # verdict — chunked loses where it compiled, BENCH_SEQUENCE_TPU.json)
    assert make_attention("auto", None, seq_len=256,
                          num_heads=4) is full_attention
    assert make_attention("auto", None, seq_len=8192,
                          num_heads=4) is full_attention
    # a measured deployment opts in via the env cutover
    monkeypatch.setenv("STPU_CHUNKED_MIN_SEQ", "2048")
    assert make_attention("auto", None, seq_len=256,
                          num_heads=4) is full_attention
    big = make_attention("auto", None, seq_len=4096, num_heads=4)
    assert big is not full_attention
    q, k, v = _qkv(s=96)
    np.testing.assert_allclose(
        np.asarray(big(q, k, v)),
        np.asarray(full_attention(q, k, v)), rtol=2e-5, atol=2e-5)
    # explicit chunked + flash resolve and agree with full
    for impl in ("chunked", "flash"):
        fn = make_attention(impl, None, seq_len=96, num_heads=4)
        np.testing.assert_allclose(
            np.asarray(fn(q, k, v)),
            np.asarray(full_attention(q, k, v)), rtol=2e-5, atol=2e-5)
    # auto with a seq mesh still picks ring (unchanged behavior)
    mesh = make_mesh("seq:8")
    ring_fn = make_attention("auto", mesh, seq_len=64, num_heads=8)
    q8, k8, v8 = _qkv(s=64, h=8)
    np.testing.assert_allclose(
        np.asarray(ring_fn(q8, k8, v8)),
        np.asarray(full_attention(q8, k8, v8)), rtol=2e-5, atol=2e-5)


def test_sequence_model_trains_with_chunked_attention():
    """SequenceClassifier end-to-end with the chunked path: loss falls."""
    import optax

    from shifu_tensorflow_tpu.models.sequence import SequenceClassifier

    model = SequenceClassifier(
        seq_len=32, d_model=32, num_heads=4, num_blocks=1,
        attention=make_attention("chunked", None, seq_len=32, num_heads=4),
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 32 * 4)), jnp.float32)
    y = jnp.asarray((rng.random((64, 1)) < 0.5), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    @jax.jit
    def step(p, s):
        def loss_fn(p):
            return jnp.mean((model.apply(p, x) - y) ** 2)

        l, g = jax.value_and_grad(loss_fn)(p)
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    params, opt, l0 = step(params, opt)
    for _ in range(20):
        params, opt, l = step(params, opt)
    assert float(l) < float(l0)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [128, 96, 200])  # 96/200: padded S
def test_pallas_flash_backward_matches_full(causal, s):
    """The r05 Pallas FlashAttention-2 backward (dQ over key blocks,
    dK/dV over query blocks, P from saved logsumexp): gradients must
    match full attention including zero-padded tails."""
    q, k, v = _qkv(s=s, seed=3)
    want = jax.grad(
        lambda q, k, v: jnp.sum(
            full_attention(q, k, v, causal=causal) ** 2), (0, 1, 2)
    )(q, k, v)
    got = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal) ** 2), (0, 1, 2)
    )(q, k, v)
    for w, g in zip(want, got):
        assert not np.isnan(np.asarray(g)).any()
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


def test_pallas_flash_backward_ab_matches_chunked_fallback(monkeypatch):
    """STPU_FLASH_BWD=chunked is the A/B seam the sweep uses: both
    gradient paths must agree on the same inputs."""
    q, k, v = _qkv(s=128, seed=5)

    def grads():
        return jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, True) ** 2), (0, 1, 2)
        )(q, k, v)

    pallas_g = grads()
    monkeypatch.setenv("STPU_FLASH_BWD", "chunked")
    chunked_g = grads()
    for a, b in zip(pallas_g, chunked_g):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_pallas_flash_backward_bf16():
    """bf16 inputs: the backward computes f32 internally and casts the
    grads back; values track the f32 reference at bf16 tolerance."""
    rng = np.random.default_rng(9)
    qf, kf, vf = (jnp.asarray(rng.normal(size=(2, 128, 2, 32)),
                              jnp.float32) for _ in range(3))
    q, k, v = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))
    got = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, True).astype(jnp.float32) ** 2),
        (0, 1, 2))(q, k, v)
    want = jax.grad(
        lambda q, k, v: jnp.sum(
            full_attention(q, k, v, causal=True) ** 2), (0, 1, 2)
    )(qf, kf, vf)
    for w, g in zip(want, got):
        assert g.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(w), rtol=0.1, atol=0.1)
