"""Data-plane observability (obs/datastats.py): sketch math pinned
against numpy, windowed expiry, the drift score + hysteretic per-feature
state machine, the bundle-shipped baseline chain (export → manifest →
ModelStore → monitor), the serve batcher/ingress taps, and the
ColumnConfig missing-stats satellite."""

from __future__ import annotations

import json
import os
import shutil

import numpy as np
import pytest

from shifu_tensorflow_tpu.obs import datastats as ds_mod
from shifu_tensorflow_tpu.obs import journal as journal_mod
from shifu_tensorflow_tpu.obs import slo as slo_mod
from shifu_tensorflow_tpu.obs import trace as trace_mod
from shifu_tensorflow_tpu.obs.datastats import (
    DataDriftMonitor,
    DataSketch,
    SkewDetector,
    TrainDataSketch,
    WindowedDataSketch,
    drift_components,
    merge_snapshots,
)
from shifu_tensorflow_tpu.obs.journal import Journal, read_events


@pytest.fixture(autouse=True)
def _clean_obs_hooks():
    yield
    trace_mod.uninstall()
    journal_mod.uninstall()
    slo_mod.uninstall()
    ds_mod.uninstall()
    ds_mod.uninstall_train()


# ---- DataSketch math ----

def test_sketch_moments_match_numpy():
    rng = np.random.default_rng(0)
    x = rng.normal(3.0, 2.0, size=(4000, 5)).astype(np.float32)
    sk = DataSketch()
    for i in range(0, len(x), 333):  # uneven batches exercise the merge
        sk.add_batch(x[i:i + 333])
    s = sk.snapshot()
    assert s["rows"] == 4000
    for j in range(5):
        col = x[:, j].astype(np.float64)
        assert s["count"][j] == 4000
        assert s["mean"][j] == pytest.approx(col.mean(), abs=1e-3)
        assert s["std"][j] == pytest.approx(col.std(), rel=1e-3)
        assert s["min"][j] == pytest.approx(col.min(), abs=1e-4)
        assert s["max"][j] == pytest.approx(col.max(), abs=1e-4)
        assert s["missing_rate"][j] == 0.0


def test_sketch_counts_nan_and_inf_separately():
    x = np.array([[1.0, np.nan, np.inf],
                  [2.0, np.nan, -np.inf],
                  [3.0, 5.0, 1.0]], np.float32)
    sk = DataSketch()
    sk.add_batch(x)
    s = sk.snapshot()
    assert s["count"] == [3, 1, 1]
    assert s["missing"] == [0, 2, 0]
    assert s["inf"] == [0, 0, 2]
    assert s["missing_rate"][1] == pytest.approx(2 / 3)
    assert s["inf_rate"][2] == pytest.approx(2 / 3)
    # the non-finite column's moments come from its finite values only
    assert s["mean"][1] == pytest.approx(5.0)
    assert s["mean"][2] == pytest.approx(1.0)


def test_sketch_quantiles_track_numpy():
    rng = np.random.default_rng(1)
    x = rng.exponential(2.0, size=(6000, 2)).astype(np.float32)
    # a high budget feeds every row → the P² estimate itself is on trial
    sk = DataSketch(quantile_budget=1_000_000)
    for i in range(0, len(x), 500):
        sk.add_batch(x[i:i + 500])
    s = sk.snapshot()
    for q in (0.05, 0.5, 0.95):
        want = np.quantile(x.astype(np.float64), q, axis=0)
        got = s["quantiles"][str(q)]
        for j in range(2):
            assert got[j] == pytest.approx(want[j], rel=0.08, abs=0.05)


def test_sketch_width_change_resets():
    sk = DataSketch()
    sk.add_batch(np.ones((10, 3), np.float32))
    sk.add_batch(np.ones((10, 5), np.float32))
    s = sk.snapshot()
    assert s["num_features"] == 5 and s["rows"] == 10


def test_merge_snapshots_equals_single_pass():
    # stay under MOMENT_ROW_CAP so both sides fold identical row sets
    rng = np.random.default_rng(2)
    x = rng.normal(-1.0, 4.0, size=(2000, 3))
    whole, a, b = DataSketch(), DataSketch(), DataSketch()
    whole.add_batch(x)
    a.add_batch(x[:1000])
    b.add_batch(x[1000:])
    m = merge_snapshots([a.snapshot(), b.snapshot()])
    w = whole.snapshot()
    assert m["rows"] == 2000
    for j in range(3):
        assert m["mean"][j] == pytest.approx(w["mean"][j], abs=1e-3)
        assert m["std"][j] == pytest.approx(w["std"][j], rel=1e-3)
        assert m["min"][j] == pytest.approx(w["min"][j])
        assert m["max"][j] == pytest.approx(w["max"][j])


def test_windowed_sketch_mixed_width_keeps_newest_schema():
    """A reload that changed the model's feature width leaves old-width
    cells in the preserved live window: the merged snapshot must carry
    the NEWEST width (cells merge oldest-first), not whichever cell the
    ring's index order happened to put last."""
    w = WindowedDataSketch(window_s=8.0, buckets=4)  # bucket_s = 2
    w.add(np.ones((40, 2), np.float32), now=1000.0)
    w.add(np.ones((40, 3), np.float32), now=1002.5)  # newer cell, wider
    snap = w.snapshot(now=1003.0)
    assert snap["num_features"] == 3 and snap["rows"] == 40


def test_windowed_sketch_expires_old_cells():
    w = WindowedDataSketch(window_s=8.0, buckets=4)
    w.add(np.ones((50, 2), np.float32), now=1000.0)
    assert w.snapshot(now=1001.0)["rows"] == 50
    # inside the window it still contributes; past it the cell is gone
    assert w.snapshot(now=1007.0)["rows"] == 50
    assert w.snapshot(now=1020.0) is None


# ---- drift score ----

def _baseline(rows=5000, f=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0.0, 1.0, size=(rows, f))
    sk = DataSketch()
    sk.add_batch(x)
    return x, sk.snapshot()


def test_drift_components_mean_shift():
    x, base = _baseline()
    live_sk = DataSketch()
    live_sk.add_batch(x[:500] + np.array([3.0, 0.0, 0.0]))
    live = live_sk.snapshot()
    c0 = drift_components(base, live, 0)
    # a 3σ mean shift scores ~3 on the mean axis (quantiles move too)
    assert c0["mean"] == pytest.approx(3.0, rel=0.15)
    for j in (1, 2):
        assert max(drift_components(base, live, j).values()) < 0.6


def test_drift_components_scale_and_missing():
    x, base = _baseline()
    scaled = x[:500].copy()
    scaled[:, 1] *= 4.0
    holes = x[500:1000].copy()
    holes[:250, 2] = np.nan
    live_sk = DataSketch()
    live_sk.add_batch(scaled)
    live_sk.add_batch(holes)
    live = live_sk.snapshot()
    c1 = drift_components(base, live, 1)
    assert max(c1, key=c1.get) in ("std", "quantile")
    assert c1["std"] > 1.0
    c2 = drift_components(base, live, 2)
    # 25% of live rows are NaN vs ~0 at train: 0.25 * RATE_WEIGHT = 1.0
    assert c2["missing_rate"] == pytest.approx(
        ds_mod.RATE_WEIGHT * 0.25, rel=0.1)


def test_drift_constant_feature_any_move_scores_large():
    sk = DataSketch()
    sk.add_batch(np.full((1000, 1), 7.0))
    base = sk.snapshot()
    live_sk = DataSketch()
    live_sk.add_batch(np.full((100, 1), 7.7))
    c = drift_components(base, live_sk.snapshot(), 0)
    assert c["mean"] > 5.0  # 10% off a constant is a schema change


# ---- detector state machine ----

def test_skew_detector_hysteresis_and_clear():
    x, base = _baseline()
    det = SkewDetector("m", base, columns=[11, 12, 13], threshold=1.0,
                       hysteresis=2, window_s=10.0, min_rows=32)
    det.observe(x[:200] + np.array([5.0, 0.0, 0.0]), now=100.0)
    assert det.evaluate(now=100.5) == []  # tick 1 of 2: hysteresis holds
    evs = det.evaluate(now=101.0)
    drifts = [e for e in evs if e["event"] == "data_drift"]
    assert len(drifts) == 1 and drifts[0]["feature"] == 0
    assert drifts[0]["column"] == 11
    assert drifts[0]["stat"] in ("mean", "quantile")
    assert drifts[0]["score"] >= 1.0
    assert det.drifting() == 1
    # no re-fire while it stays drifted
    assert not det.evaluate(now=101.5)
    # traffic returns to baseline; the shifted cells age out
    det.observe(x[200:400], now=115.0)
    det.evaluate(now=115.5)
    evs = det.evaluate(now=116.0)
    clears = [e for e in evs if e["event"] == "data_drift_clear"]
    assert len(clears) == 1 and clears[0]["feature"] == 0
    assert clears[0]["drift_s"] > 0
    assert det.drifting() == 0


def test_skew_detector_small_window_never_evaluates():
    x, base = _baseline()
    det = SkewDetector("m", base, threshold=1.0, hysteresis=1,
                       window_s=10.0, min_rows=64)
    det.observe(x[:16] + 100.0, now=10.0)  # wildly shifted but 16 rows
    assert det.evaluate(now=10.5) == []
    assert det.last_score == 0.0


def test_skew_detector_empty_window_counts_clean():
    """The slo.py empty-window rule: a tenant whose traffic stopped
    entirely (window drained) must still clear an open drift."""
    x, base = _baseline()
    det = SkewDetector("m", base, threshold=1.0, hysteresis=1,
                       window_s=5.0, min_rows=32)
    det.observe(x[:100] + np.array([5.0, 0.0, 0.0]), now=10.0)
    assert any(e["event"] == "data_drift" for e in det.evaluate(now=10.5))
    # nothing observed since; the window is empty at now=30
    evs = det.evaluate(now=30.0)
    assert any(e["event"] == "data_drift_clear" for e in evs)


def test_detector_without_baseline_collects_but_never_breaches():
    det = SkewDetector("m", None, threshold=0.001, hysteresis=1)
    det.observe(np.ones((100, 2), np.float32), now=5.0)
    assert det.evaluate(now=5.5) == []
    assert det.live.rows(now=5.5) == 100


# ---- monitor (journal + gauges + watchdog feed) ----

def test_monitor_journals_drift_and_renders_gauges(tmp_path):
    jrn = journal_mod.install(Journal(str(tmp_path / "j.jsonl"),
                                      plane="serve"))
    wd = slo_mod.install(SloWatchdog_with_target())
    x, base = _baseline()
    mon = ds_mod.install(DataDriftMonitor(
        threshold=1.0, hysteresis=1, window_s=10.0, plane="serve"))
    mon.register("alpha", base, columns=[1, 2, 3])
    mon.register("beta", base, columns=[1, 2, 3])
    mon.observe("alpha", x[:200] + np.array([4.0, 0.0, 0.0]))
    mon.observe("beta", x[200:400])
    evs = mon.evaluate()
    drifts = [e for e in evs if e["event"] == "data_drift"]
    assert drifts and all(e["model"] == "alpha" for e in drifts)
    events = read_events(str(tmp_path / "j.jsonl"))
    kinds = {e["event"] for e in events}
    assert "data_drift" in kinds and "data_stats" in kinds
    stats_models = {e.get("model") for e in events
                    if e["event"] == "data_stats"}
    assert stats_models == {"alpha", "beta"}
    text = mon.render_prometheus()
    assert "stpu_data_drift_score_alpha" in text
    assert "stpu_data_drifting_features_alpha" in text
    assert "stpu_data_live_rows_beta" in text
    # the fleet-wide max fed the watchdog's data_drift_score signal
    assert wd.state()["data_drift_score"]["value"] >= 1.0
    # unregister removes the gauges (eviction contract)
    mon.unregister("alpha")
    text = mon.render_prometheus()
    assert "alpha" not in text and "beta" in text
    jrn.close()


def SloWatchdog_with_target():
    from shifu_tensorflow_tpu.obs.slo import SloWatchdog

    wd = SloWatchdog(window_s=30.0, plane="serve")
    wd.track("data_drift_score", stat="max", target=2.0)
    return wd


def test_open_drift_clears_on_reload_and_evict(tmp_path):
    """A detector discarded with an OPEN breach (hot reload replaces
    the baseline; eviction drops the tenant) journals the clear — an
    excursion left open forever would render STILL DRIFTING long after
    the condition ended."""
    jrn = journal_mod.install(Journal(str(tmp_path / "j.jsonl"),
                                      plane="serve"))
    x, base = _baseline()
    mon = ds_mod.install(DataDriftMonitor(
        threshold=1.0, hysteresis=1, window_s=30.0, plane="serve"))
    for name in ("reloaded", "evicted"):
        mon.register(name, base, columns=[1, 2, 3])
        mon.observe(name, x[:100] + np.array([5.0, 0.0, 0.0]))
    evs = mon.evaluate()
    assert sum(1 for e in evs if e["event"] == "data_drift") == 2
    mon.register("reloaded", base)   # hot reload: new contract
    mon.unregister("evicted")        # eviction
    jrn.close()
    events = read_events(str(tmp_path / "j.jsonl"))
    clears = {e["model"]: e for e in events
              if e["event"] == "data_drift_clear"}
    assert clears["reloaded"]["reason"] == "reload"
    assert clears["evicted"]["reason"] == "evict"
    assert all(e["feature"] == 0 for e in clears.values())


def test_monitor_observe_never_raises():
    mon = DataDriftMonitor()
    mon.observe("m", "not an array")  # swallowed + warned once
    mon.observe("m", None)
    assert mon.evaluate() == []


# ---- train sketch + taps ----

def test_train_sketch_generation_reset_between_trainings():
    """A fit starting after every previous fit ended is a NEW training
    and resets the sketch (a second same-width training must not export
    a baseline blended with the first one's data); CONCURRENT fits (a
    thread-launcher fleet) share it."""
    sk = TrainDataSketch()
    sk.begin_fit(1)
    sk.add_dataset(np.full((100, 2), 1.0, np.float32))
    sk.end_fit(1)
    # concurrent fleet: two overlapping fits accumulate together
    sk2 = TrainDataSketch()
    sk2.begin_fit(1)
    sk2.begin_fit(2)
    sk2.add_dataset(np.full((50, 2), 1.0, np.float32))
    sk2.end_fit(1)
    sk2.add_dataset(np.full((50, 2), 2.0, np.float32))
    assert sk2.snapshot()["rows"] == 100
    sk2.end_fit(2)
    # sequential: the next generation starts clean
    sk.begin_fit(7)
    assert sk.snapshot() is None
    sk.add_dataset(np.full((10, 2), 3.0, np.float32))
    snap = sk.snapshot()
    assert snap["rows"] == 10 and snap["mean"][0] == pytest.approx(3.0)


def test_train_sketch_dataset_dedup_is_identity_safe():
    """Dedup keys on the ARRAY OBJECT (weakref-guarded), not a bare
    id() — CPython reuses ids after GC, and a later different array at
    a recycled id must still fold."""
    sk = TrainDataSketch()
    a = np.full((10, 2), 1.0, np.float32)
    sk.add_dataset(a)
    sk.add_dataset(a)  # same object: folded once
    assert sk.snapshot()["rows"] == 10
    b = np.full((10, 2), 2.0, np.float32)
    sk.add_dataset(b)
    assert sk.snapshot()["rows"] == 20
    # simulate id reuse: a dead entry pointing at a's id must not mask
    # a NEW array (the weakref no longer resolves to the same object)
    key = id(a)
    del a
    c = np.full((10, 2), 3.0, np.float32)
    sk._datasets[id(c)] = sk._datasets.pop(key, None) or (lambda: None)
    sk.add_dataset(c)
    assert sk.snapshot()["rows"] == 30


def test_trainer_fits_bracket_the_sketch(tmp_path):
    """Two sequential in-memory fits in one process export DISTINCT
    baselines — the second fit's sketch holds only its own data."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.data.dataset import (
        InMemoryDataset,
        ParsedBlock,
    )
    from shifu_tensorflow_tpu.data.reader import RecordSchema
    from shifu_tensorflow_tpu.train import make_trainer

    ds_mod.install_train(TrainDataSketch())

    def one_fit(mean):
        x = np.full((64, 2), mean, np.float32)
        y = np.zeros((64, 1), np.float32)
        w = np.ones((64, 1), np.float32)
        data = InMemoryDataset(
            train=ParsedBlock(x, y, w), valid=ParsedBlock.empty(2),
            schema=RecordSchema(feature_columns=(1, 2), target_column=0))
        mc = ModelConfig.from_json({"train": {"params": {
            "NumHiddenLayers": 1, "NumHiddenNodes": [4],
            "ActivationFunc": ["relu"], "LearningRate": 0.05}}})
        t = make_trainer(mc, 2, feature_columns=(1, 2))
        t.fit(data, epochs=1, batch_size=32)

    one_fit(1.0)
    first = ds_mod.train_active().snapshot()
    assert first["rows"] == 64 and first["mean"][0] == pytest.approx(1.0)
    one_fit(5.0)
    second = ds_mod.train_active().snapshot()
    assert second["rows"] == 64
    assert second["mean"][0] == pytest.approx(5.0)  # not blended with 1.0


def test_train_sketch_samples_blocks_and_folds_datasets():
    sk = TrainDataSketch(sample_every=2)
    x = np.ones((10, 2), np.float32)
    for _ in range(4):
        sk.add_block(x)  # every 2nd block folds
    snap = sk.snapshot()
    assert snap["rows"] == 20
    y = np.zeros((30, 2), np.float32)
    sk.add_dataset(y)
    sk.add_dataset(y)  # same array: folded once
    assert sk.snapshot()["rows"] == 50


def test_blocks_to_batches_feeds_tap_prepadding():
    from shifu_tensorflow_tpu.data.pipeline import blocks_to_batches
    from shifu_tensorflow_tpu.data.reader import ParsedBlock

    seen = []

    class Tap:
        def add_block(self, feats):
            seen.append(np.asarray(feats).shape)

    blocks = [ParsedBlock(np.ones((5, 2), np.float32),
                          np.ones((5, 1), np.float32),
                          np.ones((5, 1), np.float32))]
    out = list(blocks_to_batches(iter(blocks), 4, 2, stats_tap=Tap()))
    # tap saw the raw 5-row block; the emitted batches are padded to 4s
    assert seen == [(5, 2)]
    assert sum(b["x"].shape[0] for b in out) == 8  # 4 + padded tail


def test_batcher_pack_tap_feeds_monitor():
    from shifu_tensorflow_tpu.serve.batcher import MicroBatcher

    mon = ds_mod.install(DataDriftMonitor(window_s=30.0))
    mb = MicroBatcher(lambda rows: rows[:, :1], max_batch=16,
                      max_delay_s=0.0, model="tenant-a")
    try:
        mb.submit(np.ones((4, 3), np.float32))
    finally:
        mb.close()
    det = mon.detector("tenant-a")
    assert det is not None and det.live.rows() == 4


# ---- export → manifest → ModelStore chain ----

def _tiny_bundle(tmp_path, feature_stats=None, name="bundle"):
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.export.saved_model import export_native_bundle
    from shifu_tensorflow_tpu.train.trainer import Trainer

    mc = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.05}}})
    t = Trainer(mc, 3)
    d = str(tmp_path / name)
    export_native_bundle(d, t.state.params, mc, 3,
                         feature_columns=[1, 2, 3],
                         feature_stats=feature_stats)
    return d


def test_feature_stats_rides_manifest_and_loads(tmp_path):
    from shifu_tensorflow_tpu.export.saved_model import (
        FEATURE_STATS,
        NATIVE_MANIFEST,
    )
    from shifu_tensorflow_tpu.serve.model_store import ModelStore

    _, base = _baseline(f=3)
    d = _tiny_bundle(tmp_path, feature_stats=base)
    man = json.loads((tmp_path / "bundle" / NATIVE_MANIFEST).read_text())
    assert FEATURE_STATS in man["files"]
    mon = ds_mod.install(DataDriftMonitor(window_s=30.0))
    store = ModelStore(d, poll_interval_s=0, model_name="alpha")
    try:
        loaded = store.current()
        assert loaded.feature_stats["stats"]["rows"] == base["rows"]
        assert loaded.feature_stats["feature_columns"] == [1, 2, 3]
        det = mon.detector("alpha")
        assert det is not None and det.baseline is not None
    finally:
        store.close()
    # close unregisters (the eviction path runs through here)
    assert mon.detector("alpha") is None


def test_bitflipped_feature_stats_refuses_admission(tmp_path):
    from shifu_tensorflow_tpu.export.saved_model import FEATURE_STATS
    from shifu_tensorflow_tpu.serve.model_store import (
        ArtifactCorrupt,
        ModelStore,
    )

    _, base = _baseline(f=3)
    d = _tiny_bundle(tmp_path, feature_stats=base)
    p = os.path.join(d, FEATURE_STATS)
    raw = bytearray(open(p, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    with pytest.raises(ArtifactCorrupt, match="feature_stats"):
        ModelStore(d, poll_interval_s=0)


def test_bundle_without_stats_loads_and_registers_baselineless(tmp_path):
    from shifu_tensorflow_tpu.serve.model_store import ModelStore

    d = _tiny_bundle(tmp_path, feature_stats=None)
    assert not os.path.exists(os.path.join(d, "feature_stats.json"))
    mon = ds_mod.install(DataDriftMonitor(window_s=30.0))
    store = ModelStore(d, poll_interval_s=0)
    try:
        assert store.current().feature_stats is None
        det = mon.detector("default")
        assert det is not None and det.baseline is None
    finally:
        store.close()


def test_stale_orphan_stats_ignored_without_manifest_entry(tmp_path):
    """A feature_stats.json the manifest does not cover belongs to some
    other generation — nothing vouches for it, so it must not load."""
    from shifu_tensorflow_tpu.export.saved_model import FEATURE_STATS
    from shifu_tensorflow_tpu.serve.model_store import ModelStore

    d = _tiny_bundle(tmp_path, feature_stats=None)
    with open(os.path.join(d, FEATURE_STATS), "w") as f:
        json.dump({"stats": {"rows": 9}}, f)
    store = ModelStore(d, poll_interval_s=0)
    try:
        assert store.current().feature_stats is None
    finally:
        store.close()


# ---- two-tenant drill (the acceptance shape, in-process) ----

def test_two_tenant_drift_isolation(tmp_path):
    """One tenant fed a shifted stream journals data_drift naming the
    tenant/feature/statistic; the unshifted tenant stays quiet; the
    restored stream journals data_drift_clear."""
    jrn = journal_mod.install(Journal(str(tmp_path / "j.jsonl"),
                                      plane="serve"))
    x, base = _baseline(f=3)
    mon = ds_mod.install(DataDriftMonitor(
        threshold=1.0, hysteresis=1, window_s=6.0, plane="serve"))
    mon.register("alpha", base, columns=[1, 2, 3])
    mon.register("beta", base, columns=[1, 2, 3])
    shifted = x[:300].copy()
    shifted[:, 1] += 4.0
    mon.detector("beta").observe(shifted, now=50.0)
    mon.detector("alpha").observe(x[300:600], now=50.0)
    evs = mon.evaluate(now=51.0)
    drifts = [e for e in evs if e["event"] == "data_drift"]
    assert drifts, evs
    assert {e["model"] for e in drifts} == {"beta"}
    assert drifts[0]["feature"] == 1 and drifts[0]["column"] == 2
    # restore beta's stream; shifted cells age out of the 6s window
    mon.detector("beta").observe(x[600:900], now=60.0)
    evs = mon.evaluate(now=61.0)
    clears = [e for e in evs if e["event"] == "data_drift_clear"]
    assert clears and clears[0]["model"] == "beta"
    jrn.close()
    events = read_events(str(tmp_path / "j.jsonl"))
    assert not any(e.get("model") == "alpha"
                   for e in events if e["event"] == "data_drift")


# ---- serve ingress NaN counting (satellite) ----

def test_ingress_nan_rows_counted_and_rejected(tmp_path):
    from shifu_tensorflow_tpu.serve.metrics import ServeMetrics
    from shifu_tensorflow_tpu.serve.server import ScoringServer, _BadRequest

    mon = ds_mod.install(DataDriftMonitor(window_s=30.0))
    metrics = ServeMetrics()
    rows = np.ones((4, 3), np.float32)
    rows[1, 0] = np.nan
    rows[2, 2] = np.inf
    with pytest.raises(_BadRequest, match="NaN"):
        ScoringServer._reject_nonfinite(rows, metrics, "alpha")
    assert metrics.counters()["nan_rows_total"] == 2
    assert "stpu_serve_nan_rows_total" in metrics.render_prometheus(
        queue_rows=0, model_epoch=0, model_digest="", model_verified=True)
    # the refused rows still fed the tenant's live sketch: their
    # missing-rate is the drift signal the rejection would otherwise hide
    det = mon.detector("alpha")
    assert det is not None and det.live.rows() == 4
    clean = np.ones((4, 3), np.float32)
    ScoringServer._reject_nonfinite(clean, metrics, "alpha")  # no raise
    assert metrics.counters()["nan_rows_total"] == 2


# ---- journal reconstruction (fleet export path) ----

def test_baseline_from_journal_merges_workers(tmp_path):
    base = str(tmp_path / "fleet.jsonl")
    for w in (0, 1):
        j = Journal(f"{base}.w{w}", plane="train", worker=w)
        sk = DataSketch()
        sk.add_batch(np.full((100, 2), float(w)))
        j.emit("data_stats", stats=sk.snapshot(), epoch=0)
        # an older, smaller snapshot first would also be superseded
        j.close()
    merged = ds_mod.baseline_from_journal(base)
    assert merged["rows"] == 200
    assert merged["mean"][0] == pytest.approx(0.5)


# ---- ColumnConfig missing-stats satellite ----

def test_zscale_stats_warns_and_journals_missing_columns(tmp_path):
    import logging

    from shifu_tensorflow_tpu.config import model_config as mc_mod
    from shifu_tensorflow_tpu.config.model_config import ColumnConfig
    from shifu_tensorflow_tpu.utils import logs

    mc_mod._warned_stats_missing.clear()
    jrn = journal_mod.install(Journal(str(tmp_path / "cfg.jsonl"),
                                      plane="train"))
    cc = ColumnConfig.from_json([
        {"columnNum": 0, "columnFlag": "Target"},
        {"columnNum": 1, "columnStats": {"mean": 2.0, "stdDev": 3.0},
         "finalSelect": True},
        {"columnNum": 2, "finalSelect": True},            # no stats block
        {"columnNum": 3, "columnStats": {"mean": 1.0},    # partial stats
         "finalSelect": True},
        {"columnNum": 4, "columnStats": {"mean": 7.0, "stdDev": 0.0},
         "finalSelect": True},  # zero std: std=1 silently substituted
    ])
    # the config logger does not propagate to root (caplog can't see
    # it); listen on the real logger directly
    records: list[logging.LogRecord] = []
    handler = logging.Handler()
    handler.emit = records.append
    logger = logs.get("config")
    logger.addHandler(handler)
    try:
        means, stds = cc.zscale_stats([1, 2, 3, 4, 9])
        # columns 3/4 keep their means; 3's MISSING stdDev, 4's ZERO
        # stdDev, and 2/9's full absence are what the warning names
        assert means == [2.0, 0.0, 1.0, 7.0, 0.0]
        assert stds == [3.0, 1.0, 1.0, 1.0, 1.0]
        assert any("columnStats" in r.getMessage() for r in records)
        records.clear()
        cc.zscale_stats([1, 2, 3, 4, 9])  # same set: deduped
        assert not records
    finally:
        logger.removeHandler(handler)
    jrn.close()
    events = read_events(str(tmp_path / "cfg.jsonl"))
    ev = next(e for e in events if e["event"] == "config_stats_missing")
    assert ev["columns"] == [2, 3, 4, 9] and ev["selected"] == 5


def test_config_stats_missing_journals_even_when_detected_pre_install(
        tmp_path):
    """The real CLI order: config resolution (zscale_stats) runs BEFORE
    install_obs — the journal record is deferred to journal install
    instead of being eaten by the warn dedup (the event would otherwise
    never reach a dead fleet's files)."""
    from shifu_tensorflow_tpu.config import model_config as mc_mod
    from shifu_tensorflow_tpu.config.model_config import ColumnConfig

    mc_mod._warned_stats_missing.clear()
    assert journal_mod.active() is None
    cc = ColumnConfig.from_json([
        {"columnNum": 0, "columnFlag": "Target"},
        {"columnNum": 5, "finalSelect": True},
    ])
    cc.zscale_stats([5])  # detected with NO journal installed
    jrn = journal_mod.install(Journal(str(tmp_path / "late.jsonl"),
                                      plane="train"))
    jrn.close()
    events = read_events(str(tmp_path / "late.jsonl"))
    ev = next(e for e in events if e["event"] == "config_stats_missing")
    assert ev["columns"] == [5]
