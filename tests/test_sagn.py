"""SAGN local-SGD trainer (reference parity: SAGN.py / sagn_monitor.py).

Covers SURVEY.md §2.2 component #21: communication windows of local steps,
averaged-gradient global apply, single all-reduce per window.
"""

import jax
import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.parallel.mesh import make_mesh
from shifu_tensorflow_tpu.train import make_trainer
from shifu_tensorflow_tpu.train.sagn import SAGNTrainer
from shifu_tensorflow_tpu.train.trainer import Trainer

N_FEATS = 10


def _mc(window: int, optimizer: str = "sgd", epochs: int = 3) -> ModelConfig:
    return ModelConfig.from_json(
        {
            "train": {
                "numTrainEpochs": epochs,
                "validSetRate": 0.2,
                "params": {
                    "NumHiddenLayers": 2,
                    "NumHiddenNodes": [16, 8],
                    "ActivationFunc": ["relu", "tanh"],
                    "LearningRate": 0.05,
                    "Optimizer": optimizer,
                    "UpdateWindow": window,
                    "Algorithm": "sagn",
                },
            }
        }
    )


def _synth(n_rows: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=N_FEATS)
    x = rng.normal(size=(n_rows, N_FEATS)).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(x @ w_true)))
    y = (rng.random(n_rows) < p).astype(np.float32)[:, None]
    return {"x": x, "y": y, "w": np.ones((n_rows, 1), np.float32)}


def _batches(data, batch_size):
    n = data["x"].shape[0]
    for i in range(0, n - n % batch_size, batch_size):
        yield {k: v[i : i + batch_size] for k, v in data.items()}


def _flat(params):
    return np.concatenate(
        [np.asarray(l).ravel() for l in jax.tree_util.tree_leaves(params)]
    )


def test_factory_dispatch():
    t = make_trainer(_mc(window=4), N_FEATS)
    assert isinstance(t, SAGNTrainer)
    t2 = make_trainer(
        ModelConfig.from_json({"train": {"params": {"Algorithm": "ssgd"}}}),
        N_FEATS,
    )
    assert isinstance(t2, Trainer) and not isinstance(t2, SAGNTrainer)
    with pytest.raises(ValueError):
        make_trainer(
            ModelConfig.from_json({"train": {"params": {"Algorithm": "nope"}}}),
            N_FEATS,
        )


def test_window1_matches_plain_step():
    """A window of 1 is exactly one synchronous step: same grads, same
    global apply — SAGN must coincide with the plain trainer."""
    data = _synth(64)
    sagn = SAGNTrainer(_mc(window=1), N_FEATS, seed=7)
    plain = Trainer(_mc(window=1), N_FEATS, seed=7)
    batch = {k: v[:32] for k, v in data.items()}
    sagn.train_epoch(iter([batch]))
    plain.train_epoch(iter([batch]))
    np.testing.assert_allclose(
        _flat(sagn.state.params), _flat(plain.state.params), rtol=1e-5, atol=1e-6
    )


def test_sagn_converges():
    data = _synth(512)
    trainer = SAGNTrainer(_mc(window=4, optimizer="adam", epochs=1), N_FEATS, seed=3)
    first = trainer.train_epoch(_batches(data, 32))[0]
    for _ in range(4):
        last = trainer.train_epoch(_batches(data, 32))[0]
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first, f"SAGN did not reduce loss: {first} -> {last}"


def test_partial_window_fallback():
    """7 batches with window 4 => one SAGN window + 3 plain steps; nothing
    dropped."""
    data = _synth(7 * 16)
    trainer = SAGNTrainer(_mc(window=4), N_FEATS)
    loss, n_micro = trainer.train_epoch(_batches(data, 16))
    assert n_micro == 7
    assert np.isfinite(loss)


def test_mesh_sagn_runs_and_drifts_locally():
    """On an 8-device mesh each shard runs its own local window; the result
    must differ from the single-worker window (true per-shard drift) while
    both remain finite and both converge."""
    mesh = make_mesh("data:8")
    data = _synth(8 * 32)
    single = SAGNTrainer(_mc(window=3), N_FEATS, seed=11)
    sharded = SAGNTrainer(_mc(window=3), N_FEATS, seed=11, mesh=mesh)

    batches = list(_batches(data, 64))[:3]
    single.train_epoch(iter(batches))
    sharded.train_epoch(iter(batches))

    a, b = _flat(single.state.params), _flat(sharded.state.params)
    assert np.all(np.isfinite(a)) and np.all(np.isfinite(b))
    # same data, same seed: local drift must make the sharded window differ
    assert not np.allclose(a, b, rtol=1e-6, atol=1e-7)
    # but they solve the same problem: both should be close in loss
    ev_a = single.evaluate(iter(batches))
    ev_b = sharded.evaluate(iter(batches))
    assert abs(ev_a["loss"] - ev_b["loss"]) < 0.1


@pytest.mark.parametrize("rows", [64, 60])
def test_mesh_window1_matches_unsharded(rows):
    """With window=1 the count-weighted psum of per-shard grads is exactly
    the full-batch weighted gradient — including when the batch does not
    divide the mesh (60 rows -> 4 zero-weight pad rows land on one shard)."""
    mesh = make_mesh("data:8")
    data = _synth(128)
    single = SAGNTrainer(_mc(window=1), N_FEATS, seed=5)
    sharded = SAGNTrainer(_mc(window=1), N_FEATS, seed=5, mesh=mesh)
    batch = {k: v[:rows] for k, v in data.items()}
    single.train_epoch(iter([batch]))
    sharded.train_epoch(iter([batch]))
    np.testing.assert_allclose(
        _flat(single.state.params),
        _flat(sharded.state.params),
        rtol=1e-4,
        atol=1e-5,
    )


def test_sagn_rejects_partitioned_params_on_mesh():
    mc = ModelConfig.from_json(
        {
            "train": {
                "params": {
                    "Algorithm": "sagn",
                    "UpdateWindow": 2,
                    "EmbeddingColumnNums": [8, 9],
                    "EmbeddingHashSize": 64,
                    "EmbeddingDim": 4,
                }
            }
        }
    )
    mesh = make_mesh("data:4,model:2")
    with pytest.raises(ValueError, match="Partitioned"):
        SAGNTrainer(
            mc, N_FEATS, mesh=mesh, feature_columns=tuple(range(N_FEATS))
        )
