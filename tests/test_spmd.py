"""Cross-process SPMD training: N worker processes, ONE model.

This is the reference's defining capability — SyncReplicasOptimizer
aggregating gradients across workers through the PS
(ssgd_monitor.py:136-142,234-257) — rebuilt as jax.distributed + XLA
all-reduce.  The tests here run real subprocesses over CPU loopback:

- params parity: 2 processes training one model must match (to float
  tolerance) a single process training on the union of their shards with
  the concatenated global batches;
- kill-based recovery: SIGKILL one process mid-job and watch the fleet
  restart from the shared checkpoint and finish — the test the reference
  only ever ran by hand (CommonUtils.java:265-273).
"""

from __future__ import annotations

import os
import sys
import threading
import time

import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.coordinator.coordinator import (
    Coordinator,
    JobSpec,
    JobState,
)
from shifu_tensorflow_tpu.coordinator.submitter import JobSubmitter
from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
from shifu_tensorflow_tpu.data.dataset import (
    InMemoryDataset,
    fixed_step_batches,
)
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.data.splitter import split_training_data
from shifu_tensorflow_tpu.train import make_trainer
from shifu_tensorflow_tpu.train.checkpoint import NpzCheckpointer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# subprocess fleets need cross-process CPU collectives — an environment
# capability, not framework logic; see tests/jaxcaps.py for the rationale
from jaxcaps import needs_multiprocess_collectives  # noqa: E402

#: subprocess workers run on plain CPU (1 device each); 2 procs -> 2-device
#: global mesh over loopback
WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO_ROOT,
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


def _spec(shards, n_workers, **kw) -> JobSpec:
    kw.setdefault("registration_timeout_s", 120.0)
    kw.setdefault("epoch_barrier_timeout_s", 120.0)
    return JobSpec(n_workers=n_workers, shards=shards, spmd=True, **kw)


def _model_config(epochs: int, **params_extra) -> ModelConfig:
    params = {
        "NumHiddenLayers": 1,
        "NumHiddenNodes": [8],
        "ActivationFunc": ["relu"],
        "LearningRate": 0.05,
        "Optimizer": "adam",
    }
    params.update(params_extra)
    return ModelConfig.from_json(
        {
            "train": {
                "numTrainEpochs": epochs,
                "validSetRate": 0.2,
                "params": params,
            }
        }
    )


def _schema(psv_dataset) -> RecordSchema:
    return RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )


# ---------------------------------------------------------------- unit level


def test_fixed_step_batches_pads_and_drops():
    def batches(sizes):
        for n in sizes:
            yield {
                "x": np.ones((n, 3), np.float32),
                "y": np.ones((n, 1), np.float32),
                "w": np.ones((n, 1), np.float32),
            }

    # short source: pads the partial batch and fabricates zero batches
    out = list(fixed_step_batches(batches([4, 2]), 4, 4, 3))
    assert len(out) == 4
    assert all(b["x"].shape == (4, 3) for b in out)
    assert float(out[1]["w"].sum()) == 2.0  # 2 real rows, 2 padded
    assert float(out[2]["w"].sum()) == 0.0  # fabricated
    assert float(out[3]["w"].sum()) == 0.0

    # long source: surplus dropped, reported
    dropped = []
    out = list(
        fixed_step_batches(
            batches([4, 4, 4]), 4, 2, 3, on_dropped=dropped.append
        )
    )
    assert len(out) == 2
    assert dropped == [4]


def test_npz_checkpointer_roundtrip(tmp_path):
    mc = _model_config(1)
    trainer = make_trainer(mc, 10, feature_columns=tuple(range(10)))
    ckpt = NpzCheckpointer(str(tmp_path), max_to_keep=2)
    assert ckpt.latest_epoch() is None
    ckpt.save(0, trainer.state)
    ckpt.save(1, trainer.state)
    ckpt.save(2, trainer.state)
    assert ckpt.latest_epoch() == 2
    # max_to_keep pruned the oldest
    assert not os.path.exists(os.path.join(str(tmp_path), "ckpt-0.npz"))

    other = make_trainer(mc, 10, feature_columns=tuple(range(10)), seed=7)
    restored, next_epoch = ckpt.restore_latest(other.state)
    assert next_epoch == 3
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(restored.params),
        jax.tree_util.tree_leaves(trainer.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restore_epoch picks a specific (agreed) epoch
    state1, nxt = ckpt.restore_epoch(1, other.state)
    assert nxt == 2


def test_npz_checkpointer_async_roundtrip(tmp_path):
    """async_save moves writes off the epoch loop; restore paths must see
    in-flight saves (wait-before-read), eviction still applies, and a
    failed background write surfaces instead of vanishing."""
    mc = _model_config(1)
    trainer = make_trainer(mc, 10, feature_columns=tuple(range(10)))
    with NpzCheckpointer(str(tmp_path / "a"), max_to_keep=2,
                         async_save=True) as ckpt:
        ckpt.save(0, trainer.state)
        ckpt.save(1, trainer.state)
        ckpt.save(2, trainer.state)
        # restore_latest waits for the queue, then reads epoch 2
        other = make_trainer(mc, 10, feature_columns=tuple(range(10)), seed=7)
        restored, next_epoch = ckpt.restore_latest(other.state)
        assert next_epoch == 3
        assert ckpt._epochs() == [1, 2]  # eviction ran after publish
        import jax

        for a, b in zip(
            jax.tree_util.tree_leaves(restored.params),
            jax.tree_util.tree_leaves(trainer.state.params),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # write failure: checkpoint dir replaced by a plain file (covers "dir
    # vanished mid-run") -> surfaced on wait(), not lost (chmod tricks
    # don't work here: tests run as root, which ignores permission bits)
    import shutil

    bad = NpzCheckpointer(str(tmp_path / "b"), async_save=True)
    shutil.rmtree(str(tmp_path / "b"))
    (tmp_path / "b").write_text("not a directory")
    try:
        bad.save(0, trainer.state)
        with pytest.raises(OSError):
            bad.wait()
    finally:
        bad._pending = []
        bad.close()


def test_npz_checkpointer_sweeps_dead_writer_tmp(tmp_path):
    """SIGKILL'd writers leave ckpt-N.npz.tmp.<host>.<pid> debris;
    construction sweeps it once the pid is dead AND the file is past the
    in-flight grace — young files and live/own pids are kept.  Temps
    stamped with a FOREIGN hostname (shared NFS checkpoint dir: the
    writer's pid means nothing here) and legacy pid-only suffixes are
    never pid-checked: only the max-age ceiling collects them."""
    import time

    from shifu_tensorflow_tpu.train.checkpoint import _host_tag

    d = str(tmp_path)
    host = _host_tag()
    dead = os.path.join(d, f"ckpt-3.npz.tmp.{host}.999999")
    young = os.path.join(d, f"ckpt-4.npz.tmp.{host}.999998")
    mine = os.path.join(d, f"ckpt-5.npz.tmp.{host}.{os.getpid()}")
    foreign = os.path.join(d, "ckpt-6.npz.tmp.other-host.999999")
    foreign_old = os.path.join(d, "ckpt-7.npz.tmp.other-host.999998")
    legacy = os.path.join(d, "ckpt-8.npz.tmp.999997")
    for p in (dead, young, mine, foreign, foreign_old, legacy):
        open(p, "w").write("partial")
    old_t = time.time() - 600  # past the 120s grace, under the 1h max
    for p in (dead, mine, foreign, legacy):
        os.utime(p, (old_t, old_t))
    ancient = time.time() - 4000  # past the 1h debris ceiling
    os.utime(foreign_old, (ancient, ancient))
    NpzCheckpointer(d)
    assert not os.path.exists(dead)      # own host, dead pid, past grace
    assert os.path.exists(young)         # young: could be in flight
    assert os.path.exists(mine)          # own pid: kept
    assert os.path.exists(foreign)       # foreign host, inside ceiling
    assert not os.path.exists(foreign_old)  # foreign but ancient: debris
    assert os.path.exists(legacy)        # origin unknowable: ceiling only


def test_sync_plan_agrees_max_steps_min_epoch(tiny_shards):
    spec = _spec(tiny_shards, 2)
    coord = Coordinator(spec)
    coord.register("a", 0, host="127.0.0.1", jax_port=1234)
    coord.register("b", 1, host="127.0.0.1")

    results = {}

    def call(wid, plan):
        results[wid] = coord.sync_plan(wid, plan, timeout_s=10.0)

    t = threading.Thread(
        target=call,
        args=("a", {"train_steps": 5, "valid_steps": 1, "ckpt_epoch": 3}),
    )
    t.start()
    time.sleep(0.1)
    call("b", {"train_steps": 8, "valid_steps": 2, "ckpt_epoch": 2})
    t.join(timeout=5)
    for wid in ("a", "b"):
        assert results[wid]["ok"]
        assert results[wid]["train_steps"] == 8
        assert results[wid]["valid_steps"] == 2
        assert results[wid]["ckpt_epoch"] == 2
    coord.shutdown()


def test_await_start_carries_cluster_info(tiny_shards):
    spec = _spec(tiny_shards, 2)
    coord = Coordinator(spec)
    coord.register("a", 0, host="10.0.0.5", jax_port=4321)
    coord.register("b", 1, host="10.0.0.6", jax_port=9999)
    reply = coord.await_start(timeout_s=5.0)
    assert reply["ok"]
    cluster = reply["cluster"]
    assert cluster["chief_host"] == "10.0.0.5"
    assert cluster["jax_port"] == 4321  # the chief's port, not a peer's
    assert cluster["n_workers"] == 2
    coord.shutdown()


def test_fleet_restart_state_machine(tiny_shards):
    spec = _spec(tiny_shards, 2, spare_restarts=1)
    coord = Coordinator(spec)
    r0 = coord.register("a", 0)
    coord.register("b", 1)
    assert coord.state == JobState.TRAINING
    assert r0["generation"] == 0

    # any worker failing (chief included) bumps the generation
    coord.complete("a", 1)
    assert coord.generation == 1
    assert coord.state == JobState.REGISTERING
    assert coord._failed_restarts == 1

    # the peer's cascade exit must not consume budget
    coord.complete("b", 1)
    assert coord._failed_restarts == 1
    assert coord.state == JobState.REGISTERING

    # sticky re-registration into the new generation restarts training
    ra = coord.register("a", 0)
    assert ra["ok"] and ra["generation"] == 1
    coord.register("b", 1)
    assert coord.state == JobState.TRAINING

    # budget exhausted -> job fails
    coord.complete("b", 1)
    assert coord.state == JobState.FAILED
    coord.shutdown()


def test_submitter_rejects_spmd_threads(tiny_shards):
    spec = _spec(tiny_shards, 2)
    with pytest.raises(ValueError, match="process"):
        JobSubmitter(spec, lambda wid, addr: None, launcher="thread")


@pytest.fixture()
def tiny_shards(psv_dataset):
    return split_training_data(psv_dataset["root"], 2)


# --------------------------------------------------------- subprocess level


def _worker_cfg_factory(psv_dataset, mc, ckpt_dir, **extra):
    schema = _schema(psv_dataset)

    def make_cfg(worker_id: str, addr) -> WorkerConfig:
        return WorkerConfig(
            worker_id=worker_id,
            coordinator_host=addr[0],
            coordinator_port=addr[1],
            model_config=mc,
            schema=schema,
            batch_size=32,
            checkpoint_dir=ckpt_dir,
            heartbeat_interval_s=0.2,
            seed=0,
            spmd=True,
            **extra,
        )

    return make_cfg


def _emulate_single_process(psv_dataset, mc, shards, batch_size=32):
    """Single-device training on the union of shards with the exact global
    batches the SPMD fleet sees: per-shard fixed-step batches concatenated
    in worker order."""
    schema = _schema(psv_dataset)
    datasets = [
        InMemoryDataset.load(list(s.paths), schema, mc.valid_set_rate, salt=0)
        for s in shards
    ]
    steps = max(d.steps_per_epoch(batch_size) for d in datasets)
    valid_steps = max(d.valid_steps(batch_size) for d in datasets)
    nf = schema.num_features

    def make_train(epoch):
        its = [
            fixed_step_batches(
                d.train_batches(batch_size, epoch=epoch), batch_size, steps, nf
            )
            for d in datasets
        ]
        for parts in zip(*its):
            yield {
                k: np.concatenate([p[k] for p in parts]) for k in parts[0]
            }

    def make_valid():
        its = [
            fixed_step_batches(
                d.valid_batches(batch_size), batch_size, valid_steps, nf
            )
            for d in datasets
        ]
        for parts in zip(*its):
            yield {
                k: np.concatenate([p[k] for p in parts]) for k in parts[0]
            }

    trainer = make_trainer(
        mc, nf, feature_columns=schema.feature_columns, seed=0
    )
    trainer.fit_stream(
        make_train, make_valid, epochs=mc.num_train_epochs
    )
    return trainer


@needs_multiprocess_collectives
def test_spmd_two_processes_train_one_model(psv_dataset, tmp_path):
    """2 worker processes over jax.distributed == 1 process on the union of
    shards (same global batches), to float tolerance."""
    mc = _model_config(epochs=2)
    shards = split_training_data(psv_dataset["root"], 2)
    ckpt_dir = str(tmp_path / "ckpt")
    spec = _spec(shards, 2, epochs=2)
    submitter = JobSubmitter(
        spec,
        _worker_cfg_factory(psv_dataset, mc, ckpt_dir),
        launcher="process",
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
    )
    result = submitter.run(timeout_s=300.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    assert result.restarts_used == 0

    # reference: single-device run over the same global batch sequence
    ref = _emulate_single_process(psv_dataset, mc, shards)

    ckpt = NpzCheckpointer(ckpt_dir)
    assert ckpt.latest_epoch() == 1  # chief saved every epoch
    restored, _ = ckpt.restore_latest(ref.state)
    import jax

    ref_leaves = jax.tree_util.tree_leaves(ref.state.params)
    got_leaves = jax.tree_util.tree_leaves(restored.params)
    assert len(ref_leaves) == len(got_leaves)
    for r, g in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), rtol=2e-4, atol=2e-5
        )


@needs_multiprocess_collectives
def test_spmd_sigkill_recovers_via_fleet_restart(psv_dataset, tmp_path):
    """SIGKILL one worker after its first epoch report: the coordinator
    expires it, bumps the generation, the submitter kills + relaunches the
    fleet, workers resume from the agreed checkpoint, and the job finishes
    within the restart budget."""
    mc = _model_config(epochs=3)
    shards = split_training_data(psv_dataset["root"], 2)
    ckpt_dir = str(tmp_path / "ckpt")
    spec = _spec(
        shards, 2, epochs=3,
        spare_restarts=1,
        heartbeat_interval_ms=200,
        max_missed_heartbeats=5,
    )
    submitter = JobSubmitter(
        spec,
        _worker_cfg_factory(psv_dataset, mc, ckpt_dir),
        launcher="process",
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        kill_injections={"worker-1": 0},
    )
    result = submitter.run(timeout_s=300.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    assert result.restarts_used == 1
    # the final model exists and covers the full epoch budget
    ckpt = NpzCheckpointer(ckpt_dir)
    assert ckpt.latest_epoch() == 2


@needs_multiprocess_collectives
def test_spmd_sigkill_keep_best_survives_fleet_restart(psv_dataset, tmp_path):
    """SIGKILL recovery with keep-best on: the chief's persisted best
    snapshot (keep-best.npz) must survive the fleet restart — the
    relaunched generation competes against the TRUE best, and the final
    snapshot's metric can never be worse than any pre-crash epoch's."""
    mc = _model_config(epochs=3)
    shards = split_training_data(psv_dataset["root"], 2)
    ckpt_dir = str(tmp_path / "ckpt")
    schema = _schema(psv_dataset)
    # DISCRIMINATOR: pre-seed the snapshot with an unbeatable metric.  If
    # the chief restores it at every (re)launch — including the relaunch
    # whose sync_plan agrees ckpt_epoch=-1 — no real epoch can improve on
    # it and the file survives both generations untouched.  If the
    # restore is broken, the race restarts and the first real epoch
    # OVERWRITES it with its own (lower) KS: the assertions below fail.
    os.makedirs(ckpt_dir, exist_ok=True)
    seed_trainer = make_trainer(mc, schema.num_features,
                                feature_columns=schema.feature_columns,
                                keep_best="ks")
    import jax

    seed_trainer.best_metric = 0.999
    seed_trainer.best_epoch = 0
    seed_trainer.best_params = jax.device_get(seed_trainer.state.params)
    seed_trainer._persist_best(ckpt_dir)
    seed_kernel = np.asarray(
        seed_trainer.best_params["shifu_output_0"]["kernel"]
    )

    spec = _spec(
        shards, 2, epochs=3,
        spare_restarts=1,
        heartbeat_interval_ms=200,
        max_missed_heartbeats=5,
    )
    submitter = JobSubmitter(
        spec,
        _worker_cfg_factory(psv_dataset, mc, ckpt_dir, keep_best="ks"),
        launcher="process",
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        kill_injections={"worker-1": 0},
    )
    result = submitter.run(timeout_s=300.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    assert result.restarts_used == 1
    best_file = os.path.join(ckpt_dir, "keep-best.npz")
    import json as _json

    data = np.load(best_file)
    meta = _json.loads(bytes(data["__meta__"]).decode())
    assert meta["keep_best"] == "ks"
    assert meta["metric"] == 0.999, (
        "a real epoch overwrote the seeded best: the (re)launch restore "
        f"lost the race state ({meta})"
    )
    # and the snapshot the fleet export would restore is byte-identical
    # to the seeded one
    t = make_trainer(mc, schema.num_features,
                     feature_columns=schema.feature_columns,
                     keep_best="ks")
    t._restore_best(ckpt_dir)
    np.testing.assert_array_equal(
        np.asarray(t.best_params["shifu_output_0"]["kernel"]), seed_kernel
    )


@needs_multiprocess_collectives
def test_spmd_streaming_sigkill_during_cold_cache_build(psv_dataset, tmp_path):
    """SIGKILL a worker while the fleet is streaming its FIRST epoch — the
    cold pass that parses text shards and writes binary cache entries.
    Recovery must (a) not trip over half-written cache temp files (atomic
    commit: aborted entries are invisible), and (b) finish with the full
    epoch budget from the shared checkpoint."""
    mc = _model_config(epochs=3)
    shards = split_training_data(psv_dataset["root"], 2)
    ckpt_dir = str(tmp_path / "ckpt")
    cache_dir = str(tmp_path / "cache")
    spec = _spec(
        shards, 2, epochs=3,
        spare_restarts=1,
        heartbeat_interval_ms=200,
        max_missed_heartbeats=5,
    )
    submitter = JobSubmitter(
        spec,
        _worker_cfg_factory(
            psv_dataset, mc, ckpt_dir,
            stream=True, cache_dir=cache_dir,
        ),
        launcher="process",
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        kill_injections={"worker-1": 0},
    )
    result = submitter.run(timeout_s=300.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    assert result.restarts_used == 1
    ckpt = NpzCheckpointer(ckpt_dir)
    assert ckpt.latest_epoch() == 2
    # the relaunched fleet streams warm where entries committed; whatever
    # was mid-write at kill time must not have produced a visible entry
    # without its meta (lookup-able implies complete)
    import os

    names = os.listdir(cache_dir)
    keys_with_meta = {n[: -len(".meta.json")] for n in names
                      if n.endswith(".meta.json")}
    assert keys_with_meta, "warm epochs should have committed cache entries"
    for k in keys_with_meta:
        # a published meta implies its slabs exist (commit renames slabs
        # FIRST, meta last) — a kill can orphan slabs, never a meta
        assert any(n.startswith(f"{k}.x.") for n in names), k
        assert f"{k}.y.f32" in names and f"{k}.w.f32" in names, k


@needs_multiprocess_collectives
def test_spmd_trains_sequence_family(psv_dataset, tmp_path):
    """The sequence model family composes with cross-process SPMD: a
    2-process fleet trains ONE transformer over jax.distributed and
    checkpoints it (attention=auto resolves to full on the data-only
    mesh; seq-axis sharding is a single-controller mesh concern)."""
    mc = _model_config(
        1, LearningRate=0.01, ModelType="sequence",
        SeqLen=5, SeqDModel=16, SeqHeads=4, SeqBlocks=1,
    )
    shards = split_training_data(psv_dataset["root"], 2)
    ckpt_dir = str(tmp_path / "seq-ckpt")
    spec = _spec(shards, 2, epochs=1)
    submitter = JobSubmitter(
        spec,
        _worker_cfg_factory(psv_dataset, mc, ckpt_dir),
        launcher="process",
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
    )
    result = submitter.run(timeout_s=300.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    ckpt = NpzCheckpointer(ckpt_dir)
    assert ckpt.latest_epoch() == 0


@needs_multiprocess_collectives
def test_spmd_sigkill_recovery_with_async_checkpointing(psv_dataset, tmp_path):
    """Same SIGKILL drill with shifu.tpu.async-checkpoint on: background
    writes must leave either a complete published checkpoint or nothing —
    a crash mid-write must not corrupt what the restarted fleet restores."""
    mc = _model_config(epochs=3)
    shards = split_training_data(psv_dataset["root"], 2)
    ckpt_dir = str(tmp_path / "ckpt")
    spec = _spec(
        shards, 2, epochs=3,
        spare_restarts=1,
        heartbeat_interval_ms=200,
        max_missed_heartbeats=5,
    )
    submitter = JobSubmitter(
        spec,
        _worker_cfg_factory(psv_dataset, mc, ckpt_dir,
                            async_checkpoint=True),
        launcher="process",
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        kill_injections={"worker-1": 0},
    )
    result = submitter.run(timeout_s=300.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    assert result.restarts_used == 1
    # atomic publish: only complete published checkpoints are ever visible
    # to restore (kill-mid-write debris, if any, is .tmp.* the reader
    # never parses; the age-gated sweep collects it later — see
    # test_npz_checkpointer_sweeps_dead_writer_tmp)
    ckpt = NpzCheckpointer(ckpt_dir)
    assert ckpt.latest_epoch() == 2


@needs_multiprocess_collectives
def test_spmd_scan_steps_matches_per_step_fleet(psv_dataset, tmp_path):
    """Cross-process chunked scan: a 2-process fleet with scan_steps=2
    (stacked (S, B_local, F) chunks through put_process_local) must match
    the single-process per-step emulation — the scan path's only
    semantic difference is dispatch granularity, even across processes."""
    mc = _model_config(epochs=2)
    shards = split_training_data(psv_dataset["root"], 2)
    ckpt_dir = str(tmp_path / "scan-ckpt")
    spec = _spec(shards, 2, epochs=2)
    submitter = JobSubmitter(
        spec,
        _worker_cfg_factory(psv_dataset, mc, ckpt_dir, scan_steps=2),
        launcher="process",
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
    )
    result = submitter.run(timeout_s=300.0)
    assert result.state == JobState.FINISHED, result.failure_reason

    ref = _emulate_single_process(psv_dataset, mc, shards)
    ckpt = NpzCheckpointer(ckpt_dir)
    restored, _ = ckpt.restore_latest(ref.state)
    import jax

    for r, g in zip(
        jax.tree_util.tree_leaves(ref.state.params),
        jax.tree_util.tree_leaves(restored.params),
    ):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), rtol=2e-4, atol=2e-5
        )
