"""Ring attention / Ulysses sequence parallelism — numerics vs full
attention on the 8-device CPU mesh (SURVEY.md §4 item 3 simulation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shifu_tensorflow_tpu.parallel.mesh import make_mesh
from shifu_tensorflow_tpu.parallel.ring import (
    full_attention,
    ring_attention_sharded,
    ulysses_attention_sharded,
)


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh("seq:8")


def _qkv(b=2, s=64, h=8, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(seq_mesh, causal):
    q, k, v = _qkv()
    want = full_attention(q, k, v, causal=causal)
    got = ring_attention_sharded(seq_mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(seq_mesh, causal):
    q, k, v = _qkv()
    want = full_attention(q, k, v, causal=causal)
    got = ulysses_attention_sharded(seq_mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_full(seq_mesh):
    q, k, v = _qkv(b=1, s=32, h=4, d=8, seed=3)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(seq_mesh, q, k, v) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=5e-5, atol=5e-5)


def test_ring_under_jit_with_dp_axis():
    """Ring attention composes with a data axis in the same mesh (the
    realistic topology: dp × sp)."""
    mesh = make_mesh("data:2,seq:4")
    q, k, v = _qkv(b=4, s=32, h=4, d=8, seed=9)

    got = jax.jit(
        lambda q, k, v: ring_attention_sharded(mesh, q, k, v, causal=True)
    )(q, k, v)
    want = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_long_sequence_odd_heads():
    """Ulysses needs P | H; ring has no such constraint — check a head
    count indivisible by the axis size."""
    mesh = make_mesh("seq:8")
    q, k, v = _qkv(b=1, s=128, h=3, d=8, seed=5)
    got = ring_attention_sharded(mesh, q, k, v)
    want = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
