"""Every conf key must change behavior — no dead keys.

Round-2 verdict found keys with accessors nothing called
(shifu.worker.instances.backup, heartbeat tunables, shifu.tpu.dtype,
shifu.tpu.prefetch-depth).  These tests pin each key to the object it now
configures, through the same CLI resolution paths run_single/run_multi use.
"""

import jax.numpy as jnp

from shifu_tensorflow_tpu.config import keys as K
from shifu_tensorflow_tpu.config.conf import Conf
from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.train import make_trainer
from shifu_tensorflow_tpu.train.__main__ import (
    build_parser,
    job_spec_kwargs,
    trainer_extras,
)


def _args(extra=()):
    return build_parser().parse_args(
        ["--training-data-path", "/tmp/x", "--feature-columns", "1,2",
         *extra]
    )


def _conf(values: dict) -> Conf:
    conf = Conf()
    conf.update(values, source="<test>")
    return conf


def test_backup_instances_key_drives_spare_restarts():
    kw = job_spec_kwargs(_conf({K.backup_instances_key("worker"): 3}))
    assert kw["spare_restarts"] == 3
    assert job_spec_kwargs(_conf({}))["spare_restarts"] == 0


def test_heartbeat_keys_drive_job_spec():
    kw = job_spec_kwargs(_conf({
        K.TASK_HEARTBEAT_INTERVAL_MS: 250,
        K.TASK_MAX_MISSED_HEARTBEATS: 7,
    }))
    assert kw["heartbeat_interval_ms"] == 250
    assert kw["max_missed_heartbeats"] == 7
    base = job_spec_kwargs(_conf({}))
    assert base["heartbeat_interval_ms"] == K.DEFAULT_TASK_HEARTBEAT_INTERVAL_MS
    assert base["max_missed_heartbeats"] == K.DEFAULT_TASK_MAX_MISSED_HEARTBEATS


def test_sync_epochs_key_drives_job_spec():
    assert job_spec_kwargs(_conf({K.SYNC_EPOCHS: "true"}))["sync_epochs"] is True
    assert job_spec_kwargs(_conf({}))["sync_epochs"] is False


def test_dtype_conf_key_reaches_trainer():
    extras = trainer_extras(_args(), _conf({K.DTYPE: "bfloat16"}))
    assert extras["dtype"] is jnp.bfloat16
    # CLI flag wins over conf
    extras = trainer_extras(_args(["--dtype", "float32"]),
                            _conf({K.DTYPE: "bfloat16"}))
    assert extras["dtype"] is jnp.float32
    # and the dtype actually lands in the model parameters
    mc = ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.1}}}
    )
    trainer = make_trainer(mc, 2, feature_columns=(0, 1),
                           dtype=jnp.bfloat16)
    pred = trainer.model.apply(
        {"params": trainer.state.params}, jnp.zeros((1, 2), jnp.bfloat16)
    )
    assert pred.dtype == jnp.bfloat16


def test_prefetch_depth_key_reaches_trainer():
    extras = trainer_extras(_args(), _conf({K.PREFETCH_DEPTH: 5}))
    assert extras["prefetch_depth"] == 5
    mc = ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.1}}}
    )
    trainer = make_trainer(mc, 2, feature_columns=(0, 1), prefetch_depth=5)
    assert trainer.prefetch_depth == 5


def test_prefetch_depth_changes_infeed_lookahead():
    """The depth value must actually govern the prefetch window: with
    depth=d, d batches are transferred before the first is consumed."""
    from shifu_tensorflow_tpu.data.dataset import prefetch_to_device

    for depth in (1, 3):
        put_order = []

        def put(b, _log=put_order):
            _log.append(b)
            return b

        it = prefetch_to_device(iter(range(10)), put=put, depth=depth)
        first = next(it)
        assert first == 0
        assert len(put_order) == depth  # exactly the window, no more


def test_ps_keys_are_gone():
    assert not hasattr(K, "PS_JOB_NAME")
    assert not hasattr(K, "PS_FAULT_TOLERANCE_THRESHOLD")
    # legacy configs carrying shifu.ps.* still parse
    conf = _conf({"shifu.ps.instances": 2})
    assert conf.get_int("shifu.ps.instances", 0) == 2


def test_cache_max_bytes_prunes_oldest(tmp_path):
    import gzip
    import os
    import time as _time

    import numpy as np

    from shifu_tensorflow_tpu.data import cache as shard_cache
    from shifu_tensorflow_tpu.data.dataset import ShardStream
    from shifu_tensorflow_tpu.data.reader import RecordSchema

    schema = RecordSchema(feature_columns=(1, 2), target_column=0)
    cache_dir = str(tmp_path / "cache")
    rng = np.random.default_rng(0)
    paths = []
    for i in range(3):
        p = str(tmp_path / f"s{i}.gz")
        with gzip.open(p, "wt") as f:
            for _ in range(500):
                x = rng.normal(size=2)
                f.write(f"1|{x[0]:.5f}|{x[1]:.5f}\n")
        paths.append(p)
    for p in paths:  # build one entry per shard, oldest first
        for _ in ShardStream([p], schema, 128, cache_dir=cache_dir):
            pass
        _time.sleep(0.02)
    assert len([f for f in os.listdir(cache_dir)
                if f.endswith(".meta.json")]) == 3
    total = shard_cache.cache_size_bytes(cache_dir)
    removed = shard_cache.prune_cache(cache_dir, total // 2)
    assert removed >= 1
    assert shard_cache.cache_size_bytes(cache_dir) <= total // 2
    # the NEWEST entry survives and still serves warm reads
    survivors = [f for f in os.listdir(cache_dir)
                 if f.endswith(".meta.json")]
    assert survivors
    newest = shard_cache.lookup(cache_dir, paths[-1], schema, 0)
    assert newest is not None and newest.n_rows == 500
    # unbounded budget is a no-op
    assert shard_cache.prune_cache(cache_dir, 10**12) == 0


def test_cache_max_bytes_key_reaches_prune(tmp_path, capsys):
    from shifu_tensorflow_tpu.train.__main__ import prune_cache_if_configured

    from shifu_tensorflow_tpu.data.cache import CACHE_VERSION

    conf = _conf({K.CACHE_DIR: str(tmp_path), K.CACHE_MAX_BYTES: 1})
    (tmp_path / "aaaa.meta.json").write_text(
        '{"version": %d, "n_rows": 0}' % CACHE_VERSION
    )
    (tmp_path / "aaaa.x.f32").write_bytes(b"\0" * 4096)
    (tmp_path / "aaaa.y.f32").write_bytes(b"")
    (tmp_path / "aaaa.w.f32").write_bytes(b"")
    prune_cache_if_configured(conf)
    assert not (tmp_path / "aaaa.meta.json").exists()
    assert "evicted" in capsys.readouterr().out


def test_prune_sweeps_stale_tmp_and_orphan_slabs(tmp_path):
    import os
    import time as _time

    from shifu_tensorflow_tpu.data import cache as shard_cache

    old = _time.time() - 7200
    # stale tmp from a SIGKILLed writer + slab orphaned before meta publish
    for name in ("k1.x.f32.tmp.123.456.0", "k2.x.f32", "k2.y.f32"):
        p = tmp_path / name
        p.write_bytes(b"\0" * 128)
        os.utime(p, (old, old))
    # fresh tmp (in-flight writer) must survive
    fresh = tmp_path / "k3.x.f32.tmp.789.1.2"
    fresh.write_bytes(b"\0" * 128)
    shard_cache.prune_cache(str(tmp_path), max_bytes=10**9)
    left = sorted(os.listdir(tmp_path))
    assert left == ["k3.x.f32.tmp.789.1.2"], left


def test_cache_max_bytes_accepts_memory_strings(tmp_path, capsys):
    from shifu_tensorflow_tpu.train.__main__ import prune_cache_if_configured

    # "2g" must parse, not crash a finished run
    conf = _conf({K.CACHE_DIR: str(tmp_path), K.CACHE_MAX_BYTES: "2g"})
    prune_cache_if_configured(conf)  # no entries: no-op, no raise
    # garbage values are reported, never raised
    conf = _conf({K.CACHE_DIR: str(tmp_path), K.CACHE_MAX_BYTES: "lots"})
    prune_cache_if_configured(conf)
    assert "ignoring" in capsys.readouterr().err


def test_prune_drops_superseded_version_entries(tmp_path):
    import json
    import os

    from shifu_tensorflow_tpu.data import cache as shard_cache

    # a v1-era entry: unreadable by lookup, must not sit on disk forever
    (tmp_path / "old.meta.json").write_text(
        json.dumps({"version": 1, "n_rows": 5, "n_features": 2})
    )
    (tmp_path / "old.x.f32").write_bytes(b"\0" * 40)
    shard_cache.prune_cache(str(tmp_path), max_bytes=10**9)
    assert not (tmp_path / "old.meta.json").exists()
    assert not (tmp_path / "old.x.f32").exists()


def test_scan_steps_key_reaches_trainer():
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.train import make_trainer

    extras = trainer_extras(_args(), _conf({K.SCAN_STEPS: 8}))
    assert extras["scan_steps"] == 8
    # CLI flag wins over conf
    extras = trainer_extras(_args(["--scan-steps", "2"]),
                            _conf({K.SCAN_STEPS: 8}))
    assert extras["scan_steps"] == 2
    mc = ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.1}}}
    )
    trainer = make_trainer(mc, 2, feature_columns=(0, 1), scan_steps=8)
    assert trainer.scan_steps == 8
    assert trainer._scan_epoch is not None
    # default stays on the per-step path
    trainer = make_trainer(mc, 2, feature_columns=(0, 1))
    assert trainer.scan_steps == 1 and trainer._scan_epoch is None


def test_accum_steps_key_reaches_trainer():
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.train import make_trainer
    from shifu_tensorflow_tpu.train.__main__ import worker_runtime_kwargs

    extras = trainer_extras(_args(), _conf({K.ACCUM_STEPS: 4}))
    assert extras["accum_steps"] == 4
    # CLI flag wins over conf
    extras = trainer_extras(_args(["--accum-steps", "2"]),
                            _conf({K.ACCUM_STEPS: 4}))
    assert extras["accum_steps"] == 2
    # multi-worker path resolves the same key
    kw = worker_runtime_kwargs(_args(), _conf({K.ACCUM_STEPS: 4}))
    assert kw["accum_steps"] == 4
    mc = ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.1}}}
    )
    trainer = make_trainer(mc, 2, feature_columns=(0, 1), accum_steps=4)
    assert trainer.accum_steps == 4
    assert trainer._accum_step is not None
    # default stays on the per-step path
    trainer = make_trainer(mc, 2, feature_columns=(0, 1))
    assert trainer.accum_steps == 1 and trainer._accum_step is None


def test_keep_best_key_reaches_trainer():
    import pytest

    from shifu_tensorflow_tpu.train.__main__ import resolve_keep_best

    # the conf-key path has no argparse choices guard: a typo must be one
    # clean pre-launch error, not an N-worker Trainer crash cascade
    with pytest.raises(SystemExit, match="keep-best"):
        resolve_keep_best(_args(), _conf({K.KEEP_BEST: "auc"}))
    assert resolve_keep_best(_args(), _conf({})) == ""
    assert resolve_keep_best(_args(), _conf({K.KEEP_BEST: "ks"})) == "ks"
    # CLI flag wins over conf
    assert resolve_keep_best(
        _args(["--keep-best", "valid_loss"]), _conf({K.KEEP_BEST: "ks"})
    ) == "valid_loss"
    extras = trainer_extras(_args(), _conf({K.KEEP_BEST: "ks"}))
    assert extras["keep_best"] == "ks"


def test_early_stop_keys_reach_fit_loop():
    from shifu_tensorflow_tpu.train.__main__ import resolve_early_stop

    assert resolve_early_stop(_args(), _conf({})) is None
    es = resolve_early_stop(_args(), _conf({K.EARLY_STOP_KS: 0.45}))
    assert es is not None and es.target_ks == 0.45
    es = resolve_early_stop(_args(), _conf({K.EARLY_STOP_PATIENCE: 3}))
    assert es is not None and es.patience == 3
    # CLI flags win over conf
    es = resolve_early_stop(_args(["--early-stop-ks", "0.3"]),
                            _conf({K.EARLY_STOP_KS: 0.45}))
    assert es.target_ks == 0.3


def test_async_checkpoint_key_reaches_worker_config():
    """shifu.tpu.async-checkpoint drives WorkerConfig.async_checkpoint via
    the run_multi field resolution (worker_runtime_kwargs) and lands in
    NpzCheckpointer's async machinery."""
    from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
    from shifu_tensorflow_tpu.train.__main__ import worker_runtime_kwargs
    from shifu_tensorflow_tpu.train.checkpoint import NpzCheckpointer

    kw = worker_runtime_kwargs(_args(), _conf({K.ASYNC_CHECKPOINT: "true"}))
    assert kw["async_checkpoint"] is True
    kw = worker_runtime_kwargs(_args(), _conf({}))
    assert kw["async_checkpoint"] is False

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        with NpzCheckpointer(d, async_save=True) as ck:
            assert ck._executor is not None
        with NpzCheckpointer(d) as ck:
            assert ck._executor is None


def test_stream_feature_dtype_key_reaches_worker_config():
    """shifu.tpu.stream-feature-dtype drives WorkerConfig through
    worker_runtime_kwargs and resolves through the hashing-aware gate."""
    from shifu_tensorflow_tpu.train.__main__ import worker_runtime_kwargs

    kw = worker_runtime_kwargs(_args(), _conf({}))
    assert kw["stream_feature_dtype"] == "auto"
    kw = worker_runtime_kwargs(
        _args(), _conf({K.STREAM_FEATURE_DTYPE: "float32"}))
    assert kw["stream_feature_dtype"] == "float32"


def test_stream_feature_dtype_survives_worker_json_bridge():
    """The field must survive to_json/from_json — subprocess workers get
    their config over this bridge, so an omitted field silently reverts
    an operator's explicit opt-out to the bf16 default."""
    from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
    from shifu_tensorflow_tpu.data.reader import RecordSchema

    mc = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.1}}})
    schema = RecordSchema(feature_columns=(1, 2), target_column=0)
    cfg = WorkerConfig(
        worker_id="w", coordinator_host="h", coordinator_port=1,
        model_config=mc, schema=schema, stream_feature_dtype="float32",
    )
    rt = WorkerConfig.from_json(cfg.to_json())
    assert rt.stream_feature_dtype == "float32"


def test_serve_keys_round_trip_xml_to_dataclass(tmp_path):
    """Every shifu.tpu.serve-* key must survive the full resolution
    chain: Hadoop-XML resource → layered Conf merge → CLI override →
    ServeConfig dataclass (the serving WorkerConfig analogue) → JSON
    bridge — same contract the PR-2 health keys are held to."""
    from shifu_tensorflow_tpu.serve.config import ServeConfig
    from shifu_tensorflow_tpu.serve.__main__ import build_parser as serve_parser
    from shifu_tensorflow_tpu.serve import resolve_serve_config

    xml = tmp_path / "serve.xml"
    values = {
        K.SERVE_HOST: "0.0.0.0",
        K.SERVE_PORT: "9100",
        K.SERVE_BACKEND: "cpp",
        K.SERVE_MAX_BATCH: "128",
        K.SERVE_MAX_DELAY_MS: "7.5",
        K.SERVE_QUEUE_ROWS: "2048",
        K.SERVE_RETRY_AFTER_S: "3",
        K.SERVE_RELOAD_POLL_MS: "500",
        K.SERVE_WORKERS: "4",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    args = serve_parser().parse_args(["--model-dir", "/m"])
    cfg = resolve_serve_config(args, conf)
    assert cfg.host == "0.0.0.0" and cfg.port == 9100
    assert cfg.backend == "cpp"
    assert cfg.max_batch == 128 and cfg.max_delay_ms == 7.5
    assert cfg.max_queue_rows == 2048
    assert cfg.retry_after_s == 3 and cfg.reload_poll_ms == 500
    assert cfg.workers == 4
    # CLI flags win over the XML layer
    args = serve_parser().parse_args(
        ["--model-dir", "/m", "--port", "9200", "--backend", "native",
         "--max-batch", "64", "--max-delay-ms", "2", "--queue-rows",
         "512", "--retry-after", "9", "--reload-poll-ms", "0",
         "--serve-workers", "2"]
    )
    cfg = resolve_serve_config(args, conf)
    assert (cfg.port, cfg.backend, cfg.max_batch, cfg.max_delay_ms,
            cfg.max_queue_rows, cfg.retry_after_s, cfg.reload_poll_ms,
            cfg.workers) \
        == (9200, "native", 64, 2.0, 512, 9, 0, 2)
    # and the WorkerConfig-style JSON bridge round-trips every field
    assert ServeConfig.from_json(cfg.to_json()) == cfg
    # defaults with neither layer set
    d = resolve_serve_config(
        serve_parser().parse_args(["--model-dir", "/m"]), Conf()
    )
    assert d.port == K.DEFAULT_SERVE_PORT
    assert d.max_batch == K.DEFAULT_SERVE_MAX_BATCH
    assert d.backend == K.DEFAULT_SERVE_BACKEND
    assert d.workers == K.DEFAULT_SERVE_WORKERS


def test_serve_config_rejects_misconfiguration():
    """Typos/incoherent values are one clean pre-launch error (the conf
    path has no argparse choices guard), not a crash inside the server."""
    import pytest

    from shifu_tensorflow_tpu.serve.config import ServeConfig

    with pytest.raises(ValueError, match="serve-backend"):
        ServeConfig(model_dir="/m", backend="tensorrt")
    with pytest.raises(ValueError, match="serve-queue-rows"):
        ServeConfig(model_dir="/m", max_batch=256, max_queue_rows=100)
    with pytest.raises(ValueError, match="serve-max-batch"):
        ServeConfig(model_dir="/m", max_batch=0)
    with pytest.raises(ValueError, match="serve-workers"):
        ServeConfig(model_dir="/m", workers=0)
    # tenancy: exactly one of model_dir/models_dir, positive weights
    with pytest.raises(ValueError, match="exactly one"):
        ServeConfig(model_dir="/m", models_dir="/ms")
    with pytest.raises(ValueError, match="exactly one"):
        ServeConfig()
    with pytest.raises(ValueError, match="tenant-weight"):
        ServeConfig(models_dir="/ms", tenant_weights=(("a", 0.0),))
    with pytest.raises(ValueError, match="model-budget"):
        ServeConfig(models_dir="/ms", model_budget_mb=-1)
    # wire protocol: -1 (ephemeral) is the only negative frame port, and
    # the frame bound must fit the admission bound (a frame the queue
    # can never admit would always be refused AFTER its bytes shipped)
    with pytest.raises(ValueError, match="serve-frame-port"):
        ServeConfig(model_dir="/m", frame_port=-2)
    with pytest.raises(ValueError, match="serve-frame-max-rows"):
        ServeConfig(model_dir="/m", frame_max_rows=-4)
    with pytest.raises(ValueError, match="serve-frame-max-rows"):
        ServeConfig(model_dir="/m", max_queue_rows=512,
                    frame_max_rows=1024)


def test_serve_wire_keys_round_trip(tmp_path):
    """The wire-protocol / shared-lane keys (shifu.tpu.serve-frame-port
    / serve-frame-max-rows / serve-shared-lane) resolve XML → CLI-wins →
    ServeConfig → JSON bridge like every other serve key."""
    from shifu_tensorflow_tpu.serve import resolve_serve_config
    from shifu_tensorflow_tpu.serve.__main__ import (
        build_parser as serve_parser,
    )
    from shifu_tensorflow_tpu.serve.config import ServeConfig

    xml = tmp_path / "wire.xml"
    xml.write_text(
        "<configuration>"
        f"<property><name>{K.SERVE_FRAME_PORT}</name>"
        "<value>9300</value></property>"
        f"<property><name>{K.SERVE_FRAME_MAX_ROWS}</name>"
        "<value>2048</value></property>"
        f"<property><name>{K.SERVE_SHARED_LANE}</name>"
        "<value>true</value></property>"
        "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    cfg = resolve_serve_config(
        serve_parser().parse_args(["--model-dir", "/m"]), conf)
    assert cfg.frame_port == 9300
    assert cfg.frame_max_rows == 2048
    assert cfg.shared_lane is True
    # CLI wins over the XML layer
    cfg = resolve_serve_config(
        serve_parser().parse_args(
            ["--model-dir", "/m", "--frame-port", "-1",
             "--frame-max-rows", "512"]), conf)
    assert cfg.frame_port == -1 and cfg.frame_max_rows == 512
    assert cfg.shared_lane is True  # XML still supplies the lane flag
    cfg = resolve_serve_config(
        serve_parser().parse_args(
            ["--model-dir", "/m", "--shared-lane"]), Conf())
    assert cfg.shared_lane is True
    # JSON bridge round-trips the new fields
    assert ServeConfig.from_json(cfg.to_json()) == cfg
    # defaults: frame listener off, lane off, frame bound tracking the
    # admission bound (the 0 sentinel resolves in __post_init__)
    d = resolve_serve_config(
        serve_parser().parse_args(["--model-dir", "/m"]), Conf())
    assert d.frame_port == K.DEFAULT_SERVE_FRAME_PORT == 0
    assert d.frame_max_rows == d.max_queue_rows
    assert d.shared_lane is K.DEFAULT_SERVE_SHARED_LANE is False
    small = ServeConfig(model_dir="/m", max_queue_rows=512, max_batch=8)
    assert small.frame_max_rows == 512


def test_serve_tenancy_keys_round_trip(tmp_path):
    """The multi-tenant keys (shifu.tpu.serve-models-dir /
    serve-model-budget-mb / serve-model-admit-wait /
    serve-tenant-weight-<model>) resolve XML → CLI-wins → ServeConfig →
    JSON bridge, with per-model weight merge (CLI overrides the conf
    key for the SAME model only)."""
    from shifu_tensorflow_tpu.serve import resolve_serve_config
    from shifu_tensorflow_tpu.serve.__main__ import (
        build_parser as serve_parser,
    )
    from shifu_tensorflow_tpu.serve.config import ServeConfig

    xml = tmp_path / "tenancy.xml"
    values = {
        K.SERVE_MODELS_DIR: "/models",
        K.SERVE_MODEL_BUDGET_MB: "512.5",
        K.SERVE_MODEL_ADMIT_WAIT_S: "12",
        K.SERVE_TENANT_WEIGHT_PREFIX + "alpha": "2.0",
        K.SERVE_TENANT_WEIGHT_PREFIX + "beta": "0.5",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    cfg = resolve_serve_config(serve_parser().parse_args([]), conf)
    assert cfg.models_dir == "/models" and cfg.model_dir is None
    assert cfg.model_budget_mb == 512.5
    assert cfg.model_admit_wait_s == 12.0
    assert cfg.weight_for("alpha") == 2.0
    assert cfg.weight_for("beta") == 0.5
    assert cfg.weight_for("other") == K.DEFAULT_SERVE_TENANT_WEIGHT
    # CLI wins: models-dir, budget, and the alpha weight (beta's conf
    # weight survives the merge)
    args = serve_parser().parse_args(
        ["--models-dir", "/other", "--model-budget-mb", "64",
         "--model-admit-wait", "5", "--tenant-weight", "alpha=4",
         "--tenant-weight", "gamma=3"]
    )
    cfg = resolve_serve_config(args, conf)
    assert cfg.models_dir == "/other"
    assert cfg.model_budget_mb == 64.0
    assert cfg.model_admit_wait_s == 5.0
    assert (cfg.weight_for("alpha"), cfg.weight_for("beta"),
            cfg.weight_for("gamma")) == (4.0, 0.5, 3.0)
    # JSON bridge round-trips the weight pairs back to hashable form
    assert ServeConfig.from_json(cfg.to_json()) == cfg
    # defaults: no tenancy keys → single-model mode requirements hold
    d = resolve_serve_config(
        serve_parser().parse_args(["--model-dir", "/m"]), Conf()
    )
    assert d.models_dir is None and d.tenant_weights == ()
    assert d.model_budget_mb == K.DEFAULT_SERVE_MODEL_BUDGET_MB
    # CLI --model-dir beats a fleet-wide conf serve-models-dir key: an
    # explicit single-model flag must not be vetoed into a hard error
    # by shared XML (CLI wins over the conf layer)
    s = resolve_serve_config(
        serve_parser().parse_args(["--model-dir", "/m"]), conf
    )
    assert s.model_dir == "/m" and s.models_dir is None


def test_health_keys_drive_worker_and_spec_fields():
    import pytest

    from shifu_tensorflow_tpu.train.__main__ import (
        resolve_health,
        worker_runtime_kwargs,
    )

    conf = _conf({
        K.HEALTH_CHECK_FINITE: "false",
        K.HEALTH_SPIKE_FACTOR: "3.5",
        K.HEALTH_SPIKE_MIN_EPOCHS: "4",
        K.HEALTH_HANG_TIMEOUT_MS: "1500",
        K.HEALTH_LR_BACKOFF: "0.25",
        K.HEALTH_MAX_ROLLBACKS: "7",
        K.HEALTH_SKIP_WINDOW: "3",
    })
    kw = worker_runtime_kwargs(_args(), conf)
    assert kw["health_check_finite"] is False
    assert kw["health_spike_factor"] == pytest.approx(3.5)
    assert kw["health_spike_min_epochs"] == 4
    assert kw["health_hang_timeout_s"] == pytest.approx(1.5)
    spec_kw = job_spec_kwargs(conf)
    assert spec_kw["health_lr_backoff"] == pytest.approx(0.25)
    assert spec_kw["health_max_rollbacks"] == 7
    assert spec_kw["health_skip_window"] == 3
    # single-process path: same keys feed the Trainer's HealthConfig
    hc = resolve_health(conf)
    assert hc.check_finite is False
    assert hc.spike_factor == pytest.approx(3.5)
    assert hc.hang_timeout_s == pytest.approx(1.5)
    # defaults: guard on, spike/hang off
    d = resolve_health(_conf({}))
    assert d.check_finite is True and d.spike_factor == 0.0
    assert d.hang_timeout_s == 0.0


def test_obs_keys_round_trip_xml_to_dataclass(tmp_path):
    """Every shifu.tpu.obs-* key must survive the full resolution chain:
    Hadoop-XML resource → layered Conf merge → CLI override → ObsConfig →
    JSON bridge (the WorkerConfig transport) — the same contract the
    serve and health keys are held to."""
    from shifu_tensorflow_tpu.obs.config import ObsConfig
    from shifu_tensorflow_tpu.train.__main__ import resolve_obs

    xml = tmp_path / "obs.xml"
    values = {
        K.OBS_ENABLED: "true",
        K.OBS_JOURNAL: "/tmp/job.jsonl",
        K.OBS_JOURNAL_MAX_BYTES: "2m",
        K.OBS_JOURNAL_MAX_FILES: "6",
        K.OBS_TRACE_SAMPLE: "5",
        K.OBS_HIST_BUCKETS: "0.001,0.01,0.1,1.0",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    cfg = resolve_obs(_args(), conf)
    assert cfg.enabled is True
    assert cfg.journal_path == "/tmp/job.jsonl"
    assert cfg.journal_max_bytes == 2 << 20
    assert cfg.journal_max_files == 6
    assert cfg.trace_sample == 5
    assert cfg.hist_buckets == (0.001, 0.01, 0.1, 1.0)
    # JSON bridge round-trips (subprocess workers receive this dict)
    assert ObsConfig.from_json(cfg.to_json()) == cfg
    # CLI flags win over the XML layer
    cfg = resolve_obs(
        _args(["--obs-journal", "/tmp/other.jsonl"]), conf
    )
    assert cfg.journal_path == "/tmp/other.jsonl"


def test_obs_defaults_are_off_and_cli_flags_imply_enabled():
    from shifu_tensorflow_tpu.train.__main__ import resolve_obs

    cfg = resolve_obs(_args(), _conf({}))
    assert cfg.enabled is False and cfg.journal_path == ""
    # --obs enables tracing without a journal
    assert resolve_obs(_args(["--obs"]), _conf({})).enabled is True
    # --obs-journal implies enabled (a requested journal that silently
    # recorded nothing would be the worst observability bug)
    cfg = resolve_obs(_args(["--obs-journal", "/tmp/x.jsonl"]), _conf({}))
    assert cfg.enabled is True and cfg.journal_path == "/tmp/x.jsonl"
    # a conf journal path alone also enables
    assert resolve_obs(_args(),
                       _conf({K.OBS_JOURNAL: "/tmp/y.jsonl"})).enabled


def test_slo_keys_round_trip_xml_to_dataclass(tmp_path):
    """shifu.tpu.slo-* keys ride the SAME ObsConfig (and therefore the
    same WorkerConfig JSON bridge) as the obs keys: Hadoop-XML resource →
    layered Conf → ObsConfig → JSON round trip."""
    from shifu_tensorflow_tpu.obs.config import ObsConfig
    from shifu_tensorflow_tpu.train.__main__ import resolve_obs

    xml = tmp_path / "slo.xml"
    values = {
        K.OBS_ENABLED: "true",
        K.SLO_WINDOW_S: "30",
        K.SLO_SERVE_P99_MS: "250",
        K.SLO_SERVE_SHED_RATE: "0.2",
        K.SLO_STEP_TIME_MS: "50",
        K.SLO_INFEED_FRAC: "0.3",
        K.SLO_HYSTERESIS: "3",
        K.SLO_ANOMALY_SIGMA: "4.5",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    cfg = resolve_obs(_args(), conf)
    assert cfg.slo_window_s == 30.0
    assert cfg.slo_serve_p99_ms == 250.0
    assert cfg.slo_serve_shed_rate == 0.2
    assert cfg.slo_step_time_ms == 50.0
    assert cfg.slo_infeed_frac == 0.3
    assert cfg.slo_hysteresis == 3
    assert cfg.slo_anomaly_sigma == 4.5
    assert ObsConfig.from_json(cfg.to_json()) == cfg
    # defaults: window 60s, hysteresis 2, sigma 6, every target off
    d = resolve_obs(_args(), _conf({}))
    assert d.slo_window_s == 60.0 and d.slo_hysteresis == 2
    assert d.slo_anomaly_sigma == 6.0
    assert d.slo_serve_p99_ms == d.slo_serve_shed_rate == 0.0
    assert d.slo_step_time_ms == d.slo_infeed_frac == 0.0
    assert d.slo_compile_s == d.slo_devmem_frac == 0.0


def test_device_obs_keys_round_trip_xml_to_dataclass(tmp_path):
    """The PR-10 device/compiler keys ride the same ObsConfig chain:
    compile-analysis depth, storm threshold, and the two new watchdog
    targets — XML → Conf → ObsConfig → JSON bridge."""
    import pytest

    from shifu_tensorflow_tpu.obs.config import ObsConfig
    from shifu_tensorflow_tpu.train.__main__ import resolve_obs

    xml = tmp_path / "devobs.xml"
    values = {
        K.OBS_ENABLED: "true",
        K.OBS_COMPILE_ANALYSIS: "cost",
        K.OBS_COMPILE_STORM: "12",
        K.SLO_COMPILE_S: "2.5",
        K.SLO_DEVMEM_FRAC: "0.9",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    cfg = resolve_obs(_args(), conf)
    assert cfg.compile_analysis == "cost"
    assert cfg.compile_storm == 12
    assert cfg.slo_compile_s == 2.5
    assert cfg.slo_devmem_frac == 0.9
    assert ObsConfig.from_json(cfg.to_json()) == cfg
    # defaults: auto analysis (full on train, cost on serve — resolved
    # per plane by install_obs), storm threshold 8, targets off
    d = resolve_obs(_args(), _conf({}))
    assert d.compile_analysis == "auto" and d.compile_storm == 8
    # misconfiguration fails loudly
    with pytest.raises(ValueError, match="obs-compile-analysis"):
        ObsConfig(compile_analysis="verbose")
    with pytest.raises(ValueError, match="obs-compile-storm"):
        ObsConfig(compile_storm=1)
    with pytest.raises(ValueError, match="slo-devmem-frac"):
        ObsConfig(slo_devmem_frac=1.5)


def test_fleet_obs_keys_round_trip_xml_to_dataclass(tmp_path):
    """The PR-11 fleet keys ride the same ObsConfig chain: the
    straggler-skew watchdog target and the detect/clear threshold —
    XML → Conf → ObsConfig → JSON bridge."""
    import pytest

    from shifu_tensorflow_tpu.obs.config import ObsConfig
    from shifu_tensorflow_tpu.train.__main__ import resolve_obs

    xml = tmp_path / "fleetobs.xml"
    values = {
        K.OBS_ENABLED: "true",
        K.SLO_STRAGGLER_SKEW: "2.5",
        K.FLEET_SKEW_THRESHOLD: "1.8",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    cfg = resolve_obs(_args(), conf)
    assert cfg.slo_straggler_skew == 2.5
    assert cfg.fleet_skew_threshold == 1.8
    assert ObsConfig.from_json(cfg.to_json()) == cfg
    # the target reaches the watchdog signal on train/coordinator planes
    from shifu_tensorflow_tpu.obs import slo as slo_mod

    wd = slo_mod.from_config(cfg, plane="coordinator")
    assert wd.state()["fleet_skew"]["target"] == 2.5
    # defaults: no watchdog target, detection threshold 1.5
    d = resolve_obs(_args(), _conf({}))
    assert d.slo_straggler_skew == 0.0
    assert d.fleet_skew_threshold == 1.5
    # misconfiguration fails loudly: skew is a RATIO, 1 means balanced
    with pytest.raises(ValueError, match="slo-straggler-skew"):
        ObsConfig(slo_straggler_skew=0.8)
    with pytest.raises(ValueError, match="fleet-skew-threshold"):
        ObsConfig(fleet_skew_threshold=1.0)


def test_data_obs_keys_round_trip_xml_to_dataclass(tmp_path):
    """The PR-12 data keys ride the same ObsConfig chain: the
    drift-score watchdog target and the per-feature detect/clear
    threshold — XML → Conf → ObsConfig → JSON bridge."""
    import pytest

    from shifu_tensorflow_tpu.obs.config import ObsConfig
    from shifu_tensorflow_tpu.train.__main__ import resolve_obs

    xml = tmp_path / "dataobs.xml"
    values = {
        K.OBS_ENABLED: "true",
        K.SLO_DATA_DRIFT: "2.0",
        K.DATA_DRIFT_THRESHOLD: "0.5",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    cfg = resolve_obs(_args(), conf)
    assert cfg.slo_data_drift == 2.0
    assert cfg.data_drift_threshold == 0.5
    assert ObsConfig.from_json(cfg.to_json()) == cfg
    # the target reaches the watchdog signal (every plane)
    from shifu_tensorflow_tpu.obs import slo as slo_mod

    wd = slo_mod.from_config(cfg, plane="serve")
    assert wd.state()["data_drift_score"]["target"] == 2.0
    assert wd.state()["data_drift_score"]["stat"] == "max"
    # install_obs builds the monitor from these knobs
    from shifu_tensorflow_tpu.obs import datastats as ds_mod
    from shifu_tensorflow_tpu.obs import install_obs

    try:
        install_obs(cfg, plane="serve")
        mon = ds_mod.active()
        assert mon is not None and mon.threshold == 0.5
        assert ds_mod.train_active() is not None
    finally:
        install_obs(ObsConfig(enabled=False), plane="serve")
    # defaults: no watchdog target, detection threshold 1.0
    d = resolve_obs(_args(), _conf({}))
    assert d.slo_data_drift == 0.0
    assert d.data_drift_threshold == 1.0
    # misconfiguration fails loudly
    with pytest.raises(ValueError, match="slo-data-drift"):
        ObsConfig(slo_data_drift=-1.0)
    with pytest.raises(ValueError, match="data-drift-threshold"):
        ObsConfig(data_drift_threshold=0.0)


def test_rollup_keys_round_trip_xml_to_dataclass(tmp_path):
    """The PR-13 long-horizon keys ride the same ObsConfig chain: the
    rollup compactor knobs, the pinned baseline, and the regression
    watchdog target — XML → Conf → ObsConfig → JSON bridge."""
    import pytest

    from shifu_tensorflow_tpu.obs.config import ObsConfig
    from shifu_tensorflow_tpu.train.__main__ import resolve_obs

    xml = tmp_path / "rollup.xml"
    values = {
        K.OBS_ENABLED: "true",
        K.OBS_ROLLUP: "false",
        K.OBS_ROLLUP_WINDOW_S: "30",
        K.OBS_BASELINE: "/tmp/base.rollup.jsonl",
        K.SLO_REGRESSION: "1.5",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    cfg = resolve_obs(_args(), conf)
    assert cfg.rollup is False
    assert cfg.rollup_window_s == 30.0
    assert cfg.baseline_path == "/tmp/base.rollup.jsonl"
    assert cfg.slo_regression == 1.5
    assert ObsConfig.from_json(cfg.to_json()) == cfg
    # rollup=false: install_obs must NOT start a compactor even with a
    # journal configured
    from shifu_tensorflow_tpu.obs import install_obs
    from shifu_tensorflow_tpu.obs import rollup as rollup_mod

    off = ObsConfig(enabled=True,
                    journal_path=str(tmp_path / "j.jsonl"),
                    rollup=False)
    try:
        install_obs(off, plane="train")
        assert rollup_mod.active() is None
        on = ObsConfig(enabled=True,
                       journal_path=str(tmp_path / "j2.jsonl"))
        install_obs(on, plane="train")
        assert rollup_mod.active() is not None
    finally:
        install_obs(ObsConfig(enabled=False), plane="train")
        from shifu_tensorflow_tpu.obs import journal as journal_mod
        from shifu_tensorflow_tpu.obs import trace as trace_mod

        journal_mod.uninstall()
        trace_mod.uninstall()
    # defaults: rollup on (with a journal), no baseline, watchdog off
    d = resolve_obs(_args(), _conf({}))
    assert d.rollup is True
    assert d.rollup_window_s == 60.0
    assert d.baseline_path == ""
    assert d.slo_regression == 0.0
    # misconfiguration fails loudly
    with pytest.raises(ValueError, match="obs-rollup-window"):
        ObsConfig(rollup_window_s=0.0)
    with pytest.raises(ValueError, match="slo-regression"):
        ObsConfig(slo_regression=-1.0)
    with pytest.raises(ValueError, match="slo-regression"):
        ObsConfig(slo_regression=0.8)


def test_obs_keys_reach_worker_config_bridge():
    """run_multi ships the resolved ObsConfig to subprocess workers via
    WorkerConfig.obs (JSON bridge) — and omits it entirely when obs is
    off, so the off path stays a None check."""
    from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
    from shifu_tensorflow_tpu.obs.config import ObsConfig
    from shifu_tensorflow_tpu.train.__main__ import worker_runtime_kwargs

    kw = worker_runtime_kwargs(
        _args(), _conf({K.OBS_JOURNAL: "/tmp/fleet.jsonl"})
    )
    assert kw["obs"]["journal_path"] == "/tmp/fleet.jsonl"
    assert ObsConfig.from_json(kw["obs"]).enabled is True
    assert worker_runtime_kwargs(_args(), _conf({}))["obs"] is None
    # and the field survives the WorkerConfig JSON transport
    import dataclasses
    fields = {f.name for f in dataclasses.fields(WorkerConfig)}
    assert "obs" in fields


def test_data_keys_round_trip_xml_to_worker_config(tmp_path):
    """shifu.tpu.data-* keys: Hadoop-XML resource → layered Conf → CLI
    override → resolve_ingest → WorkerConfig JSON round-trip — the same
    contract the obs/serve/health keys are held to."""
    from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
    from shifu_tensorflow_tpu.train.__main__ import (
        resolve_ingest,
        worker_runtime_kwargs,
    )

    xml = tmp_path / "data.xml"
    values = {
        K.DATA_READERS: "3",
        K.DATA_DECODE_WORKERS: "2",
        K.DATA_PREFETCH: "5",
        K.DATA_AUTOTUNE: "false",
        K.DATA_SHUFFLE_ROWS: "4096",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    ing = resolve_ingest(_args(), conf)
    assert ing == {"readers": 3, "decode_workers": 2, "prefetch": 5,
                   "autotune": False, "shuffle_rows": 4096}
    # CLI flags win over the XML layer
    ing = resolve_ingest(
        _args(["--readers", "7", "--data-autotune"]), conf)
    assert ing["readers"] == 7 and ing["autotune"] is True
    # worker bridge carries every field, and the WorkerConfig JSON
    # transport round-trips them to subprocess workers
    kw = worker_runtime_kwargs(_args(), conf)
    assert kw["n_readers"] == 3  # one resolver feeds run_multi's bridge
    assert kw["decode_workers"] == 2
    assert kw["data_prefetch"] == 5
    assert kw["data_autotune"] is False
    assert kw["data_shuffle_rows"] == 4096
    mc = ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.1}}}
    )
    from shifu_tensorflow_tpu.data.reader import RecordSchema
    cfg = WorkerConfig(
        worker_id="w0", coordinator_host="127.0.0.1", coordinator_port=1,
        model_config=mc,
        schema=RecordSchema(feature_columns=(1,), target_column=0),
        n_readers=3, decode_workers=2, data_prefetch=5,
        data_autotune=False, data_shuffle_rows=4096,
    )
    back = WorkerConfig.from_json(cfg.to_json())
    assert (back.n_readers, back.decode_workers, back.data_prefetch,
            back.data_autotune, back.data_shuffle_rows) == (3, 2, 5,
                                                            False, 4096)


def test_data_keys_defaults_autotune_on_and_auto_widths():
    """Defaults: every width 0 (= auto), autotune ON, shuffle off —
    and resolve_ingest_knobs turns explicit values into PINNED
    dimensions the tuner must not touch."""
    from shifu_tensorflow_tpu.data.autotune import resolve_ingest_knobs
    from shifu_tensorflow_tpu.train.__main__ import resolve_ingest

    ing = resolve_ingest(_args(), _conf({}))
    assert ing == {"readers": 0, "decode_workers": 0, "prefetch": 0,
                   "autotune": True, "shuffle_rows": 0}
    knobs, tuner = resolve_ingest_knobs(
        ing["readers"], ing["decode_workers"], ing["prefetch"],
        autotune=ing["autotune"], fallback_prefetch=2, cpu_count=4)
    assert tuner is not None and tuner.pinned == frozenset()
    assert knobs.readers >= 1 and knobs.prefetch == 2
    # an explicit knob wins AND disables autotuning for that dimension
    ing = resolve_ingest(_args(["--decode-workers", "3"]), _conf({}))
    knobs, tuner = resolve_ingest_knobs(
        ing["readers"], ing["decode_workers"], ing["prefetch"],
        autotune=ing["autotune"], fallback_prefetch=2, cpu_count=4)
    assert knobs.decode_workers == 3
    assert "decode_workers" in tuner.pinned
    # --no-data-autotune freezes everything (no tuner object at all)
    ing = resolve_ingest(_args(["--no-data-autotune"]), _conf({}))
    assert ing["autotune"] is False


def test_aot_keys_round_trip_xml_cli_and_json_bridge(tmp_path):
    """The AOT shipping keys (PR 14): shifu.tpu.export-aot /
    export-aot-rows resolve the export ladder (CLI wins), and
    shifu.tpu.compile-cache-dir rides ObsConfig through the same
    XML → Conf → CLI → JSON-bridge chain as every obs key."""
    from shifu_tensorflow_tpu.export.aot import resolve_aot_buckets
    from shifu_tensorflow_tpu.export.bucketing import ladder
    from shifu_tensorflow_tpu.obs.config import ObsConfig
    from shifu_tensorflow_tpu.train.__main__ import resolve_obs

    xml = tmp_path / "aot.xml"
    values = {
        K.EXPORT_AOT: "true",
        K.EXPORT_AOT_ROWS: "128",
        K.COMPILE_CACHE_DIR: "/cache/xla",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    assert resolve_aot_buckets(_args(), conf) == ladder(128)
    # CLI wins over the conf ladder size; the flag alone enables
    assert resolve_aot_buckets(
        _args(["--export-aot-rows", "64"]), conf) == ladder(64)
    assert resolve_aot_buckets(_args(["--export-aot"]), _conf({})) \
        == ladder(K.DEFAULT_SERVE_QUEUE_ROWS)
    # defaults: AOT export off, cache off
    assert resolve_aot_buckets(_args(), _conf({})) is None
    cfg = resolve_obs(_args(), conf)
    assert cfg.compile_cache_dir == "/cache/xla"
    assert ObsConfig.from_json(cfg.to_json()) == cfg
    cfg = resolve_obs(_args(["--compile-cache-dir", "/cache/cli"]), conf)
    assert cfg.compile_cache_dir == "/cache/cli"
    assert resolve_obs(_args(), _conf({})).compile_cache_dir == ""


def test_elastic_keys_round_trip_xml_cli_and_spec(tmp_path):
    """shifu.tpu.standby-workers / shifu.tpu.elastic: XML → Conf → CLI
    override → JobSpec kwargs (the elastic-fleet switchboard)."""
    from shifu_tensorflow_tpu.train.__main__ import elastic_spec_kwargs

    xml = tmp_path / "elastic.xml"
    xml.write_text(
        "<configuration>"
        f"<property><name>{K.STANDBY_WORKERS}</name><value>2</value>"
        "</property>"
        f"<property><name>{K.ELASTIC}</name><value>true</value>"
        "</property>"
        "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    kw = elastic_spec_kwargs(_args(), conf)
    # elastic forces sync_epochs: the shrink/release/re-split directives
    # are delivered through the per-epoch barrier
    assert kw == {"standby_workers": 2, "elastic": True,
                  "sync_epochs": True}
    # CLI wins over the XML layer
    kw = elastic_spec_kwargs(
        _args(["--standby-workers", "1", "--no-elastic"]), conf)
    assert kw == {"standby_workers": 1, "elastic": False}
    # defaults: no standbys, elastic off (budget exhaustion still fails)
    kw = elastic_spec_kwargs(_args(), _conf({}))
    assert kw == {"standby_workers": K.DEFAULT_STANDBY_WORKERS,
                  "elastic": K.DEFAULT_ELASTIC}
    # the JobSpec accepts them and the worker JSON bridge carries role
    from shifu_tensorflow_tpu.coordinator.coordinator import JobSpec
    from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
    from shifu_tensorflow_tpu.data.reader import RecordSchema
    from shifu_tensorflow_tpu.data.splitter import Shard

    spec = JobSpec(n_workers=1, shards=[Shard(0, ("/d/p0",), 1)],
                   standby_workers=2, elastic=True)
    assert spec.standby_workers == 2 and spec.elastic is True
    wc = WorkerConfig(
        worker_id="sb-0", coordinator_host="127.0.0.1",
        coordinator_port=1,
        model_config=ModelConfig.from_json({}),
        schema=RecordSchema(feature_columns=(1,), target_column=0),
        role="standby",
    )
    assert WorkerConfig.from_json(wc.to_json()).role == "standby"


def test_autoscale_keys_round_trip_xml_to_serve_config(tmp_path):
    """shifu.tpu.serve-workers-max / serve-autoscale-* /
    serve-supervisor-port: XML → Conf → CLI override → ServeConfig →
    JSON bridge."""
    from shifu_tensorflow_tpu.serve import resolve_serve_config
    from shifu_tensorflow_tpu.serve.__main__ import (
        build_parser as serve_parser,
    )
    from shifu_tensorflow_tpu.serve.config import ServeConfig

    xml = tmp_path / "autoscale.xml"
    values = {
        K.SERVE_WORKERS: "2",
        K.SERVE_WORKERS_MAX: "6",
        K.SERVE_AUTOSCALE_COOLDOWN_S: "45",
        K.SERVE_AUTOSCALE_TICKS: "3",
        K.SERVE_AUTOSCALE_RECOVERY_TICKS: "9",
        K.SERVE_AUTOSCALE_POLL_S: "2.5",
        K.SERVE_SUPERVISOR_PORT: "9301",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    cfg = resolve_serve_config(
        serve_parser().parse_args(["--model-dir", "/m"]), conf)
    assert (cfg.workers, cfg.workers_max) == (2, 6)
    assert cfg.autoscale_cooldown_s == 45.0
    assert cfg.autoscale_ticks == 3
    assert cfg.autoscale_recovery_ticks == 9
    assert cfg.autoscale_poll_s == 2.5
    assert cfg.supervisor_port == 9301
    # CLI wins
    cfg = resolve_serve_config(serve_parser().parse_args(
        ["--model-dir", "/m", "--serve-workers-max", "4",
         "--autoscale-cooldown", "5", "--autoscale-poll", "1",
         "--supervisor-port", "0"]), conf)
    assert cfg.workers_max == 4 and cfg.autoscale_cooldown_s == 5.0
    assert cfg.autoscale_poll_s == 1.0 and cfg.supervisor_port == 0
    # JSON bridge round-trips the new fields
    assert ServeConfig.from_json(cfg.to_json()) == cfg
    # defaults: autoscale off
    d = resolve_serve_config(
        serve_parser().parse_args(["--model-dir", "/m"]), Conf())
    assert d.workers_max == K.DEFAULT_SERVE_WORKERS_MAX == 0
    # validation: a ceiling below the floor is a config error
    import pytest

    with pytest.raises(ValueError, match="serve-workers-max"):
        ServeConfig(model_dir="/m", workers=4, workers_max=2)


def test_lifecycle_keys_round_trip_xml_to_dataclass(tmp_path):
    """Every shifu.tpu.lifecycle-* key must survive the full resolution
    chain: Hadoop-XML resource → layered Conf merge → CLI override →
    LifecycleConfig dataclass → JSON bridge — the serve-key contract,
    applied to the controller surface."""
    import pytest

    from shifu_tensorflow_tpu.lifecycle.__main__ import (
        build_parser as lifecycle_parser,
    )
    from shifu_tensorflow_tpu.lifecycle.config import (
        LifecycleConfig,
        resolve_lifecycle_config,
    )

    xml = tmp_path / "lifecycle.xml"
    values = {
        K.LIFECYCLE_MODEL: "beta",
        K.SERVE_MODELS_DIR: "/srv/models",
        K.OBS_JOURNAL: "/var/log/stpu/j",
        K.TRAINING_DATA_PATH: "/data/train",
        K.LIFECYCLE_POLL_S: "0.5",
        K.LIFECYCLE_TRIGGER_HYSTERESIS: "5",
        K.LIFECYCLE_COOLDOWN_S: "120.5",
        K.LIFECYCLE_SHADOW_MIN_ROWS: "64",
        K.LIFECYCLE_DIVERGENCE_THRESHOLD: "0.8",
        K.LIFECYCLE_RAMP_STEPS: "0.1,0.4,0.8",
        K.LIFECYCLE_RAMP_INTERVAL_S: "12.5",
        K.LIFECYCLE_ROLLBACK_HYSTERESIS: "4",
        K.LIFECYCLE_RETRAIN_TIMEOUT_S: "900",
    }
    xml.write_text(
        "<configuration>" + "".join(
            f"<property><name>{k}</name><value>{v}</value></property>"
            for k, v in values.items()
        ) + "</configuration>"
    )
    conf = Conf()
    conf.add_resource(str(xml))
    cfg = resolve_lifecycle_config(
        lifecycle_parser().parse_args(["run"]), conf)
    assert cfg.model == "beta"
    assert cfg.models_dir == "/srv/models"
    assert cfg.journal_base == "/var/log/stpu/j"
    assert cfg.train_data_path == "/data/train"
    assert cfg.poll_s == 0.5
    assert cfg.trigger_hysteresis == 5
    assert cfg.cooldown_s == 120.5
    assert cfg.shadow_min_rows == 64
    assert cfg.divergence_threshold == 0.8
    assert cfg.ramp_steps == (0.1, 0.4, 0.8)
    assert cfg.ramp_interval_s == 12.5
    assert cfg.rollback_hysteresis == 4
    assert cfg.retrain_timeout_s == 900.0
    # CLI flags win over the XML layer
    cfg = resolve_lifecycle_config(lifecycle_parser().parse_args(
        ["run", "--model", "gamma", "--models-dir", "/m2",
         "--journal", "/j2", "--train-data", "/d2",
         "--train-arg=--epochs", "--train-arg=3",
         "--poll", "2", "--trigger-hysteresis", "2",
         "--cooldown", "60", "--shadow-min-rows", "32",
         "--divergence-threshold", "1.5", "--ramp-steps", "0.5",
         "--ramp-interval", "5", "--rollback-hysteresis", "1",
         "--retrain-timeout", "30"]), conf)
    assert (cfg.model, cfg.models_dir, cfg.journal_base,
            cfg.train_data_path) == ("gamma", "/m2", "/j2", "/d2")
    assert cfg.train_args == ("--epochs", "3")
    assert (cfg.poll_s, cfg.trigger_hysteresis, cfg.cooldown_s,
            cfg.shadow_min_rows, cfg.divergence_threshold,
            cfg.ramp_steps, cfg.ramp_interval_s,
            cfg.rollback_hysteresis, cfg.retrain_timeout_s) \
        == (2.0, 2, 60.0, 32, 1.5, (0.5,), 5.0, 1, 30.0)
    # the JSON bridge round-trips every field (drill harnesses ship the
    # config to the controller subprocess whole)
    assert LifecycleConfig.from_json(cfg.to_json()) == cfg
    # defaults with only the required identity keys set
    d = resolve_lifecycle_config(lifecycle_parser().parse_args(
        ["run", "--model", "beta", "--models-dir", "/m",
         "--journal", "/j"]), Conf())
    assert d.poll_s == K.DEFAULT_LIFECYCLE_POLL_S
    assert d.trigger_hysteresis == K.DEFAULT_LIFECYCLE_TRIGGER_HYSTERESIS
    assert d.cooldown_s == K.DEFAULT_LIFECYCLE_COOLDOWN_S
    assert d.shadow_min_rows == K.DEFAULT_LIFECYCLE_SHADOW_MIN_ROWS
    assert (d.divergence_threshold
            == K.DEFAULT_LIFECYCLE_DIVERGENCE_THRESHOLD)
    assert d.ramp_steps == tuple(
        float(s) for s in K.DEFAULT_LIFECYCLE_RAMP_STEPS.split(","))
    assert d.ramp_interval_s == K.DEFAULT_LIFECYCLE_RAMP_INTERVAL_S
    assert (d.rollback_hysteresis
            == K.DEFAULT_LIFECYCLE_ROLLBACK_HYSTERESIS)
    assert d.retrain_timeout_s == K.DEFAULT_LIFECYCLE_RETRAIN_TIMEOUT_S
    # misconfiguration is one clean pre-launch ValueError naming the key
    with pytest.raises(ValueError, match="lifecycle-ramp-steps"):
        resolve_lifecycle_config(lifecycle_parser().parse_args(
            ["run", "--model", "beta", "--models-dir", "/m",
             "--journal", "/j", "--ramp-steps", "0.5,0.25"]), Conf())
    with pytest.raises(ValueError, match="lifecycle-trigger-hysteresis"):
        resolve_lifecycle_config(lifecycle_parser().parse_args(
            ["run", "--model", "beta", "--models-dir", "/m",
             "--journal", "/j", "--trigger-hysteresis", "0"]), Conf())
