"""Every conf key must change behavior — no dead keys.

Round-2 verdict found keys with accessors nothing called
(shifu.worker.instances.backup, heartbeat tunables, shifu.tpu.dtype,
shifu.tpu.prefetch-depth).  These tests pin each key to the object it now
configures, through the same CLI resolution paths run_single/run_multi use.
"""

import jax.numpy as jnp

from shifu_tensorflow_tpu.config import keys as K
from shifu_tensorflow_tpu.config.conf import Conf
from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.train import make_trainer
from shifu_tensorflow_tpu.train.__main__ import (
    build_parser,
    job_spec_kwargs,
    trainer_extras,
)


def _args(extra=()):
    return build_parser().parse_args(
        ["--training-data-path", "/tmp/x", "--feature-columns", "1,2",
         *extra]
    )


def _conf(values: dict) -> Conf:
    conf = Conf()
    conf.update(values, source="<test>")
    return conf


def test_backup_instances_key_drives_spare_restarts():
    kw = job_spec_kwargs(_conf({K.backup_instances_key("worker"): 3}))
    assert kw["spare_restarts"] == 3
    assert job_spec_kwargs(_conf({}))["spare_restarts"] == 0


def test_heartbeat_keys_drive_job_spec():
    kw = job_spec_kwargs(_conf({
        K.TASK_HEARTBEAT_INTERVAL_MS: 250,
        K.TASK_MAX_MISSED_HEARTBEATS: 7,
    }))
    assert kw["heartbeat_interval_ms"] == 250
    assert kw["max_missed_heartbeats"] == 7
    base = job_spec_kwargs(_conf({}))
    assert base["heartbeat_interval_ms"] == K.DEFAULT_TASK_HEARTBEAT_INTERVAL_MS
    assert base["max_missed_heartbeats"] == K.DEFAULT_TASK_MAX_MISSED_HEARTBEATS


def test_sync_epochs_key_drives_job_spec():
    assert job_spec_kwargs(_conf({K.SYNC_EPOCHS: "true"}))["sync_epochs"] is True
    assert job_spec_kwargs(_conf({}))["sync_epochs"] is False


def test_dtype_conf_key_reaches_trainer():
    extras = trainer_extras(_args(), _conf({K.DTYPE: "bfloat16"}))
    assert extras["dtype"] is jnp.bfloat16
    # CLI flag wins over conf
    extras = trainer_extras(_args(["--dtype", "float32"]),
                            _conf({K.DTYPE: "bfloat16"}))
    assert extras["dtype"] is jnp.float32
    # and the dtype actually lands in the model parameters
    mc = ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.1}}}
    )
    trainer = make_trainer(mc, 2, feature_columns=(0, 1),
                           dtype=jnp.bfloat16)
    pred = trainer.model.apply(
        {"params": trainer.state.params}, jnp.zeros((1, 2), jnp.bfloat16)
    )
    assert pred.dtype == jnp.bfloat16


def test_prefetch_depth_key_reaches_trainer():
    extras = trainer_extras(_args(), _conf({K.PREFETCH_DEPTH: 5}))
    assert extras["prefetch_depth"] == 5
    mc = ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.1}}}
    )
    trainer = make_trainer(mc, 2, feature_columns=(0, 1), prefetch_depth=5)
    assert trainer.prefetch_depth == 5


def test_prefetch_depth_changes_infeed_lookahead():
    """The depth value must actually govern the prefetch window: with
    depth=d, d batches are transferred before the first is consumed."""
    from shifu_tensorflow_tpu.data.dataset import prefetch_to_device

    for depth in (1, 3):
        put_order = []

        def put(b, _log=put_order):
            _log.append(b)
            return b

        it = prefetch_to_device(iter(range(10)), put=put, depth=depth)
        first = next(it)
        assert first == 0
        assert len(put_order) == depth  # exactly the window, no more


def test_ps_keys_are_gone():
    assert not hasattr(K, "PS_JOB_NAME")
    assert not hasattr(K, "PS_FAULT_TOLERANCE_THRESHOLD")
    # legacy configs carrying shifu.ps.* still parse
    conf = _conf({"shifu.ps.instances": 2})
    assert conf.get_int("shifu.ps.instances", 0) == 2
