"""Host-resident embedding spill (EmbeddingPlacement=host) — the capacity
tier past HBM (SURVEY §7.2-6): host-side hashed gather, sparse Adagrad,
bit-identical bucket assignment to the device path, standard-bundle
export."""

import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.data.dataset import InMemoryDataset
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.models.host_embedding import (
    HostEmbeddingTable,
    bucket_ids,
)
from shifu_tensorflow_tpu.train.trainer import Trainer


def _mc(placement="host", epochs=2, **extra):
    return ModelConfig.from_json(
        {"train": {"numTrainEpochs": epochs, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05, "Optimizer": "adam",
                              "EmbeddingColumnNums": [2, 5],
                              "EmbeddingHashSize": 128,
                              "EmbeddingDim": 4,
                              "EmbeddingPlacement": placement,
                              **extra}}}
    )


def _dataset(psv_dataset):
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    return InMemoryDataset.load(psv_dataset["paths"], schema, 0.2), schema


def test_host_hash_parity_with_device():
    """bucket_ids (numpy) must be BIT-IDENTICAL to ops/hashing
    salted_bucket_ids (jax) — the whole export story rests on it."""
    import jax.numpy as jnp

    from shifu_tensorflow_tpu.ops import hashing

    rng = np.random.default_rng(0)
    x = np.concatenate([
        rng.normal(size=(500, 4)).astype(np.float32) * 1000,
        rng.integers(0, 10_000_000, size=(500, 4)).astype(np.float32),
        np.zeros((1, 4), np.float32),
        -np.ones((1, 4), np.float32),
    ])
    for hash_size in (128, 65536, 1_000_003):
        want = np.asarray(hashing.salted_bucket_ids(
            jnp.asarray(x), hash_size))
        got = bucket_ids(x, hash_size)
        np.testing.assert_array_equal(got, want)


def test_adagrad_duplicate_ids_accumulate():
    """Two occurrences of the same bucket in one batch must behave like
    their summed gradient (np.add.at semantics), not last-wins."""
    t = HostEmbeddingTable(8, 2, lr=0.1, seed=0)
    before = t.table.copy()
    ids = np.array([[3], [3]], np.int32)
    g = np.array([[[1.0, 0.0]], [[1.0, 0.0]]], np.float32)
    t.apply_grads(ids, g)
    # dense-equivalent: grads SUM first, the accumulator sees the summed
    # row's squared norm (||g1+g2||^2 = 4), update -lr*2/sqrt(4)
    assert t.accum[3] == pytest.approx(4.0)
    expected = before[3, 0] - 0.1 * 2.0 / (np.sqrt(4.0) + t.eps)
    assert t.table[3, 0] == pytest.approx(expected, rel=1e-6)
    # untouched rows stay untouched
    np.testing.assert_array_equal(t.table[:3], before[:3])


def test_host_placement_trains_and_moves_table(psv_dataset):
    ds, schema = _dataset(psv_dataset)
    tr = Trainer(_mc(), schema.num_features,
                 feature_columns=schema.feature_columns, seed=1)
    assert tr._host_emb is not None
    t0 = tr._host_emb.table.copy()
    history = tr.fit(ds, batch_size=64)
    assert len(history) == 2
    assert np.isfinite(history[-1].training_loss)
    assert np.isfinite(history[-1].valid_loss)
    assert 0.0 <= history[-1].auc <= 1.0
    # the table actually learned (rows moved) and ONLY via sparse updates
    assert not np.array_equal(tr._host_emb.table, t0)
    # loss went down across epochs
    assert history[-1].training_loss <= history[0].training_loss + 1e-3


def test_host_placement_export_scores_match_all_backends(
        psv_dataset, tmp_path):
    """A host-trained model exports as a standard device-embedding bundle;
    the jitted scorer and (when built) the C++ scorer reproduce the
    host-side lookups exactly — end-to-end proof of hash parity."""
    from shifu_tensorflow_tpu.export.eval_model import EvalModel
    from shifu_tensorflow_tpu.export.saved_model import export_model

    ds, schema = _dataset(psv_dataset)
    tr = Trainer(_mc(), schema.num_features,
                 feature_columns=schema.feature_columns, seed=1)
    tr.fit(ds, batch_size=64)
    export_dir = str(tmp_path / "host-model")
    export_model(export_dir, tr, feature_columns=schema.feature_columns)

    x = ds.valid.features[:96]
    # reference scores computed through the TRAINING path: host gather +
    # device base net
    batch = tr._put({"x": x,
                     "y": np.zeros((len(x), 1), np.float32),
                     "w": np.ones((len(x), 1), np.float32)})
    _, want = tr._eval_step(tr.state.params, batch)
    want = np.asarray(want)

    with EvalModel(export_dir, backend="native") as em:
        got = em.compute_batch(x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)

    from shifu_tensorflow_tpu.export import native_scorer

    if native_scorer.available():
        with EvalModel(export_dir, backend="cpp") as em:
            got_cpp = em.compute_batch(x)
        np.testing.assert_allclose(got_cpp, want, rtol=2e-5, atol=2e-6)


def test_host_placement_guards():
    mc = _mc()
    with pytest.raises(ValueError, match="per-step path"):
        Trainer(mc, 10, feature_columns=tuple(range(1, 11)), scan_steps=4)
    with pytest.raises(ValueError, match="per-step path"):
        Trainer(mc, 10, feature_columns=tuple(range(1, 11)), accum_steps=4)
    with pytest.raises(ValueError, match="sagn"):
        Trainer(_mc(Algorithm="sagn"), 10,
                feature_columns=tuple(range(1, 11)))
    with pytest.raises(ValueError, match="unknown EmbeddingPlacement"):
        Trainer(_mc(placement="hbm"), 10,
                feature_columns=tuple(range(1, 11)))

    from shifu_tensorflow_tpu.parallel.distributed import ProcessTopology

    with pytest.raises(ValueError, match="single-process"):
        Trainer(mc, 10, feature_columns=tuple(range(1, 11)),
                topology=ProcessTopology(
                    coordinator_address="h:1", num_processes=2,
                    process_id=0))


def test_host_placement_device_resident_refused(psv_dataset):
    ds, schema = _dataset(psv_dataset)
    tr = Trainer(_mc(), schema.num_features,
                 feature_columns=schema.feature_columns)
    with pytest.raises(ValueError, match="device-resident"):
        tr.fit_device_resident(ds, batch_size=64)


def test_host_table_checkpoint_sidecar_roundtrip(psv_dataset, tmp_path):
    """The table is model state: maybe_save publishes a sidecar beside
    the checkpoint, restore() loads it, and the restored trainer's table
    equals the original's."""
    from shifu_tensorflow_tpu.train.checkpoint import NpzCheckpointer

    ds, schema = _dataset(psv_dataset)
    ckpt_dir = str(tmp_path / "ckpt")
    tr = Trainer(_mc(epochs=2), schema.num_features,
                 feature_columns=schema.feature_columns, seed=3)
    with NpzCheckpointer(ckpt_dir) as ck:
        tr.fit(ds, batch_size=64, checkpointer=ck)
    import os

    assert any(f.startswith("host-emb-") for f in os.listdir(ckpt_dir))

    tr2 = Trainer(_mc(epochs=2), schema.num_features,
                  feature_columns=schema.feature_columns, seed=99)
    with NpzCheckpointer(ckpt_dir) as ck:
        next_epoch = tr2.restore(ck)
    assert next_epoch == 2
    np.testing.assert_array_equal(tr2._host_emb.table, tr._host_emb.table)
    np.testing.assert_array_equal(tr2._host_emb.accum, tr._host_emb.accum)


def test_host_table_keep_best_snapshot(psv_dataset, tmp_path):
    """keep-best must snapshot the TABLE with the dense params — exporting
    the best dense net against the last epoch's embeddings would serve a
    model that never existed."""
    ds, schema = _dataset(psv_dataset)
    tr = Trainer(_mc(epochs=3), schema.num_features,
                 feature_columns=schema.feature_columns, seed=2,
                 keep_best="ks")
    tr.fit(ds, batch_size=64)
    assert tr.best_params is not None
    assert tr.best_host_table is not None
    # the snapshot is a COPY, not a live alias of the training table
    assert tr.best_host_table is not tr._host_emb.table


def test_stream_fit_with_host_placement(psv_dataset):
    """fit_stream composes: augmentation happens in _put, so the
    streaming path needs no special handling (and the hashing gate keeps
    the stream transport at f32)."""
    from shifu_tensorflow_tpu.data.dataset import ShardStream

    _, schema = _dataset(psv_dataset)
    tr = Trainer(_mc(epochs=2), schema.num_features,
                 feature_columns=schema.feature_columns, seed=5)
    history = tr.fit_stream(
        lambda epoch: ShardStream(
            psv_dataset["paths"], schema, 64, valid_rate=0.2,
            emit="train", n_readers=1,
        ),
        (lambda: ShardStream(
            psv_dataset["paths"], schema, 64, valid_rate=0.2,
            emit="valid", n_readers=1,
        )),
        epochs=2,
    )
    assert len(history) == 2
    assert np.isfinite(history[-1].valid_loss)
