"""Sequence model family: transformer encoder over event sequences, with
ring/Ulysses sequence-parallel attention as first-class consumers of
parallel/ring.py (SURVEY.md §5.7 beyond-parity capability)."""

import jax
import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.data.dataset import InMemoryDataset
from shifu_tensorflow_tpu.data.reader import ParsedBlock, RecordSchema
from shifu_tensorflow_tpu.models.factory import build_model
from shifu_tensorflow_tpu.parallel.mesh import make_mesh
from shifu_tensorflow_tpu.train.trainer import Trainer

SEQ_LEN, STEP_F = 8, 4
NUM_FEATURES = SEQ_LEN * STEP_F


def _mc(epochs=3, attention="auto", **extra):
    params = {
        "NumHiddenLayers": 1, "NumHiddenNodes": [8],
        "ActivationFunc": ["relu"],
        "LearningRate": 0.003, "Optimizer": "adam",
        "ModelType": "sequence", "SeqLen": SEQ_LEN,
        "SeqDModel": 32, "SeqHeads": 4, "SeqBlocks": 2,
        "SeqAttention": attention,
    }
    params.update(extra)
    return ModelConfig.from_json(
        {"train": {"numTrainEpochs": epochs, "validSetRate": 0.2,
                   "params": params}}
    )


def _seq_dataset(rows=600, seed=0):
    """Label depends on a cross-step aggregate (mean of step feature 0
    gated by feature 1's trajectory) — only a model that sees the sequence
    can separate it."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(rows, SEQ_LEN, STEP_F)).astype(np.float32)
    agg = x[:, :, 0].mean(axis=1) + 0.8 * np.sign(
        x[:, -1, 1] - x[:, 0, 1]
    )
    y = (agg > 0).astype(np.float32)  # deterministic: separability is the
    # point; label noise would cap the AUC the test asserts on
    flat = x.reshape(rows, NUM_FEATURES)
    n_valid = rows // 5
    schema = RecordSchema(
        feature_columns=tuple(range(1, NUM_FEATURES + 1)), target_column=0
    )
    mk = lambda lo, hi: ParsedBlock(
        flat[lo:hi], y[lo:hi, None], np.ones((hi - lo, 1), np.float32)
    )
    return InMemoryDataset(mk(n_valid, rows), mk(0, n_valid), schema)


def test_factory_builds_sequence_model_and_forward_shape():
    model = build_model(_mc(), tuple(range(1, NUM_FEATURES + 1)))
    x = np.random.default_rng(0).normal(size=(6, NUM_FEATURES)).astype(
        np.float32
    )
    params = model.init(jax.random.key(0), x)["params"]
    out = model.apply({"params": params}, x)
    assert out.shape == (6, 1)
    assert np.all(np.isfinite(np.asarray(out)))
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) <= 1))


def test_sequence_composes_with_keep_best_and_early_stop():
    """The round-4 training features are family-agnostic: the sequence
    family under keep-best=ks + early-stop must track its best epoch and
    stop at the target like the DNN gate test does."""
    from shifu_tensorflow_tpu.train.trainer import EarlyStopper

    ds = _seq_dataset(rows=5000)
    trainer = Trainer(_mc(epochs=10, LearningRate=0.003), NUM_FEATURES,
                      seed=3, keep_best="ks")
    history = trainer.fit(ds, batch_size=128,
                          early_stop=EarlyStopper(target_ks=0.45))
    assert trainer.stop_reason, "sequence family never hit KS 0.45"
    assert history[-1].ks >= 0.45
    assert trainer.best_metric >= 0.45


def test_seq_remat_is_numerically_invisible():
    """SeqRemat changes WHERE activations come from in the backward
    (recompute vs store), never the numbers: loss and grads must match
    the non-remat model exactly on the same params."""
    from shifu_tensorflow_tpu.models.factory import build_model as bm

    cols = tuple(range(1, NUM_FEATURES + 1))
    base = bm(_mc(), cols)
    remat = bm(_mc(SeqRemat="true"), cols)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, NUM_FEATURES)).astype(np.float32)
    y = (rng.random((32, 1)) < 0.5).astype(np.float32)
    params = base.init(jax.random.key(0), x)

    def loss(model):
        def f(p):
            out = model.apply(p, x)
            return ((out - y) ** 2).mean()

        return f

    l0, g0 = jax.value_and_grad(loss(base))(params)
    l1, g1 = jax.value_and_grad(loss(remat))(params)
    assert float(l0) == float(l1)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_seq_remat_config_parsing():
    assert _mc(SeqRemat="true").params.seq_remat is True
    assert _mc(SeqRemat=True).params.seq_remat is True
    # same token set as Conf.get_bool: "on"/"1" are true everywhere
    assert _mc(SeqRemat="on").params.seq_remat is True
    assert _mc(SeqRemat="1").params.seq_remat is True
    assert _mc(SeqRemat="false").params.seq_remat is False
    assert _mc().params.seq_remat is False


@pytest.mark.parametrize("attention", [
    "chunked",
    # the flash variant runs the Pallas kernel in interpret mode on the
    # CPU backend: ~400 s wall for a wiring check the chunked variant
    # covers identically (kernel parity itself is pinned fast in
    # tests/test_flash.py) — nearly half the tier-1 wall-clock budget,
    # so it runs under -m slow only
    pytest.param("flash", marks=pytest.mark.slow),
])
def test_config_level_memory_safe_attention_trains(attention):
    """SeqAttention=chunked|flash resolve from ModelConfig params and
    train end-to-end through the Trainer (the long-S single-device
    paths; parity is pinned in tests/test_flash.py — here the wiring)."""
    ds = _seq_dataset(rows=400)
    trainer = Trainer(_mc(epochs=2, attention=attention), NUM_FEATURES,
                      seed=1)
    history = trainer.fit(ds, batch_size=64)
    assert len(history) == 2
    assert np.isfinite(history[-1].valid_loss)


def test_sequence_model_learns_sequence_signal():
    # 5K rows: transformers are data-hungry; at 600 rows this plateaus at
    # AUC ~0.55, at 5K it reaches ~0.98 by epoch 8 (measured)
    ds = _seq_dataset(rows=5000)
    trainer = Trainer(_mc(epochs=8, LearningRate=0.003), NUM_FEATURES,
                      seed=3)
    history = trainer.fit(ds, batch_size=128)
    assert history[-1].valid_loss < history[0].valid_loss
    assert history[-1].auc > 0.9


def test_ring_attention_forward_parity_with_full():
    """Same params, same input: ring-sharded attention over a data x seq
    mesh must reproduce single-device full attention."""
    mesh = make_mesh("data:2,seq:4")
    model_full = build_model(_mc(attention="full"),
                             tuple(range(1, NUM_FEATURES + 1)))
    model_ring = build_model(_mc(attention="ring"),
                             tuple(range(1, NUM_FEATURES + 1)), mesh=mesh)
    x = np.random.default_rng(1).normal(size=(8, NUM_FEATURES)).astype(
        np.float32
    )
    params = model_full.init(jax.random.key(7), x)["params"]
    a = np.asarray(model_full.apply({"params": params}, x))
    b = np.asarray(model_ring.apply({"params": params}, x))
    np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_sequence_trains_on_seq_parallel_mesh():
    mesh = make_mesh("data:2,seq:4")
    ds = _seq_dataset(rows=256)
    trainer = Trainer(_mc(epochs=2, attention="ring"), NUM_FEATURES,
                      mesh=mesh, seed=3)
    history = trainer.fit(ds, batch_size=64)
    assert np.isfinite(history[-1].training_loss)
    # auto resolves to ring on a seq mesh: same path, one epoch sanity
    t_auto = Trainer(_mc(epochs=1, attention="auto"), NUM_FEATURES,
                     mesh=mesh, seed=3)
    h = t_auto.fit(ds, batch_size=64)
    assert np.isfinite(h[-1].training_loss)
    # SeqRemat composes with ring: jax.checkpoint over the shard_map'd
    # attention — the one remat composition not covered elsewhere
    t_remat = Trainer(_mc(epochs=1, attention="ring", SeqRemat="true"),
                      NUM_FEATURES, mesh=mesh, seed=3)
    hr = t_remat.fit(ds, batch_size=64)
    assert np.isfinite(hr[-1].training_loss)


def test_sequence_config_errors():
    with pytest.raises(ValueError, match="SeqLen"):
        build_model(_mc(SeqLen=0), tuple(range(1, NUM_FEATURES + 1)))
    with pytest.raises(ValueError, match="seq"):
        # ring without a seq mesh axis
        build_model(_mc(attention="ring"),
                    tuple(range(1, NUM_FEATURES + 1)), mesh=None)
    with pytest.raises(ValueError, match="divisible"):
        model = build_model(_mc(), tuple(range(1, NUM_FEATURES + 1)))
        bad = np.zeros((2, NUM_FEATURES + 3), np.float32)
        model.init(jax.random.key(0), bad)


def test_sequence_export_native_roundtrip(tmp_path):
    """Exported sequence bundles carry the Seq* arch params (serving pins
    full attention) and rescore exactly through the native backend."""
    from shifu_tensorflow_tpu.export.eval_model import EvalModel
    from shifu_tensorflow_tpu.export.saved_model import export_native_bundle

    ds = _seq_dataset(rows=256)
    trainer = Trainer(_mc(epochs=1), NUM_FEATURES, seed=5)
    trainer.fit(ds, batch_size=64)
    export_dir = str(tmp_path / "seq-model")
    export_native_bundle(
        export_dir, trainer.state.params, trainer.model_config,
        NUM_FEATURES, feature_columns=tuple(range(1, NUM_FEATURES + 1)),
    )
    with EvalModel(export_dir, backend="native") as em:
        x = ds.valid.features[:32]
        got = em.compute_batch(x)
        want = trainer.predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_sequence_config_validation_names_keys():
    # conflicting ModelType + SeqLen
    with pytest.raises(ValueError, match="conflicts"):
        build_model(_mc(ModelType="multi_task"),
                    tuple(range(1, NUM_FEATURES + 1)))
    # uneven heads
    with pytest.raises(ValueError, match="SeqDModel"):
        build_model(_mc(SeqDModel=32, SeqHeads=6),
                    tuple(range(1, NUM_FEATURES + 1)))
    # seq axis must divide SeqLen
    with pytest.raises(ValueError, match="SeqLen"):
        build_model(_mc(attention="ring", SeqLen=6),
                    tuple(range(1, 6 * STEP_F + 1)),
                    mesh=make_mesh("data:2,seq:4"))
    # ulysses head divisibility
    with pytest.raises(ValueError, match="SeqHeads"):
        build_model(_mc(attention="ulysses", SeqHeads=3),
                    tuple(range(1, NUM_FEATURES + 1)),
                    mesh=make_mesh("data:2,seq:4"))


def test_ring_trained_model_exports_saved_model(tmp_path):
    """Review regression: export_model must rebuild the serving function
    mesh-less — a ring-trained sequence model's shard_map attention must
    not be traced into the jax2tf SavedModel."""
    pytest.importorskip("tensorflow")
    from shifu_tensorflow_tpu.export.saved_model import export_model

    mesh = make_mesh("data:2,seq:4")
    ds = _seq_dataset(rows=128)
    trainer = Trainer(_mc(epochs=1, attention="ring"), NUM_FEATURES,
                      mesh=mesh, seed=5)
    trainer.fit(ds, batch_size=64)
    status = export_model(str(tmp_path / "ring-export"), trainer,
                          feature_columns=tuple(range(1, NUM_FEATURES + 1)))
    assert status["native"] and status["saved_model"]


def test_ulysses_attention_forward_parity_with_full():
    """Ulysses all-to-all attention (heads re-sharded over the seq axis)
    must also reproduce full attention at the model level (seq:4 | heads=4)."""
    mesh = make_mesh("data:2,seq:4")
    model_full = build_model(_mc(attention="full"),
                             tuple(range(1, NUM_FEATURES + 1)))
    model_uly = build_model(_mc(attention="ulysses"),
                            tuple(range(1, NUM_FEATURES + 1)), mesh=mesh)
    x = np.random.default_rng(2).normal(size=(8, NUM_FEATURES)).astype(
        np.float32
    )
    params = model_full.init(jax.random.key(9), x)["params"]
    a = np.asarray(model_full.apply({"params": params}, x))
    b = np.asarray(model_uly.apply({"params": params}, x))
    np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)
