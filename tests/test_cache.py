"""Binary shard cache (data/cache.py) + fused native stream + routing rule.

The contract under test: a ShardStream emits IDENTICAL batches whether a
file is served by the byte-chunk fallback, the fused native stream, a cold
cache build, or a warm cache hit — and the cache invalidates itself when
the source or the parse config changes.
"""

import gzip
import os
import threading
import time

import numpy as np
import pytest

from shifu_tensorflow_tpu.data import cache as shard_cache
from shifu_tensorflow_tpu.data import native
from shifu_tensorflow_tpu.data.dataset import ShardStream
from shifu_tensorflow_tpu.data.reader import (
    RecordSchema,
    parse_lines_full,
    route_is_valid,
    wanted_columns,
)
from shifu_tensorflow_tpu.utils import fs

SCHEMA = RecordSchema(feature_columns=(1, 2, 3), target_column=0, weight_column=4)


def _write_shards(root, n_shards=3, rows=2000, compress=True, seed=0):
    rng = np.random.default_rng(seed)
    paths = []
    for s in range(n_shards):
        p = os.path.join(root, f"part-{s}{'.gz' if compress else '.psv'}")
        lines = []
        for _ in range(rows):
            x = rng.normal(size=3)
            y = int(x.sum() > 0)
            lines.append("|".join([str(y)] + [f"{v:.5f}" for v in x] + ["1.0"]))
        data = ("\n".join(lines) + "\n").encode()
        with open(p, "wb") as f:
            f.write(gzip.compress(data) if compress else data)
        paths.append(p)
    return paths


def _drain(paths, cache_dir, valid_rate=0.0, emit="train", batch=256):
    stream = ShardStream(
        paths, SCHEMA, batch, valid_rate=valid_rate, emit=emit,
        cache_dir=cache_dir,
    )
    return [
        (b["x"].copy(), b["y"].copy(), b["w"].copy()) for b in stream
    ]


def _assert_same(a, b):
    assert len(a) == len(b)
    for (x1, y1, w1), (x2, y2, w2) in zip(a, b):
        np.testing.assert_array_equal(x1, x2)
        np.testing.assert_array_equal(y1, y2)
        np.testing.assert_array_equal(w1, w2)


def test_cold_warm_nocache_batch_parity(tmp_path):
    paths = _write_shards(str(tmp_path))
    cache_dir = str(tmp_path / "cache")
    no_cache = _drain(paths, None)
    cold = _drain(paths, cache_dir)  # parse + write entries
    warm = _drain(paths, cache_dir)  # memmap hit
    _assert_same(no_cache, cold)
    _assert_same(no_cache, warm)
    metas = [f for f in os.listdir(cache_dir) if f.endswith(".meta.json")]
    assert len(metas) == len(paths)
    # no leftover temp slabs
    assert not [f for f in os.listdir(cache_dir) if ".tmp." in f]


def test_valid_split_parity_cached(tmp_path):
    paths = _write_shards(str(tmp_path))
    cache_dir = str(tmp_path / "cache")
    for emit in ("train", "valid"):
        ref = _drain(paths, None, valid_rate=0.3, emit=emit)
        _drain(paths, cache_dir, valid_rate=0.3, emit=emit)  # cold
        warm = _drain(paths, cache_dir, valid_rate=0.3, emit=emit)
        _assert_same(ref, warm)


def test_cache_invalidated_on_source_change(tmp_path):
    paths = _write_shards(str(tmp_path), n_shards=1)
    cache_dir = str(tmp_path / "cache")
    before = _drain(paths, cache_dir)
    _drain(paths, cache_dir)  # warm once
    # rewrite the shard with different content (different size + mtime)
    _write_shards(str(tmp_path), n_shards=1, seed=9)
    os.utime(paths[0], ns=(time.time_ns(), time.time_ns() + 10**9))
    after = _drain(paths, cache_dir)
    with pytest.raises(AssertionError):
        _assert_same(before, after)


def test_cache_key_covers_parse_config(tmp_path):
    paths = _write_shards(str(tmp_path), n_shards=1)
    k1 = shard_cache.cache_key(paths[0], SCHEMA, 0)
    k2 = shard_cache.cache_key(paths[0], SCHEMA, salt=7)
    zs = SCHEMA.with_zscale([0.1, 0.2, 0.3], [1.0, 1.0, 1.0])
    k3 = shard_cache.cache_key(paths[0], zs, 0)
    assert k1 and len({k1, k2, k3}) == 3


def test_concurrent_writers_same_key(tmp_path):
    """Two streams building the same entries at once (train+valid zipped)
    must not corrupt each other — the round-2 review found PID-only temp
    suffixes let same-process writers truncate each other's slabs."""
    paths = _write_shards(str(tmp_path))
    cache_dir = str(tmp_path / "cache")
    ref_t = _drain(paths, None, valid_rate=0.3, emit="train")
    ref_v = _drain(paths, None, valid_rate=0.3, emit="valid")

    results = {}

    def run(emit):
        results[emit] = _drain(paths, cache_dir, valid_rate=0.3, emit=emit)

    threads = [threading.Thread(target=run, args=(e,)) for e in ("train", "valid")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    _assert_same(results["train"], ref_t)
    _assert_same(results["valid"], ref_v)
    # whatever got committed must serve correct warm reads
    _assert_same(_drain(paths, cache_dir, valid_rate=0.3, emit="train"), ref_t)
    _assert_same(_drain(paths, cache_dir, valid_rate=0.3, emit="valid"), ref_v)


def test_plain_text_shards_and_gzip_sniffing(tmp_path):
    # gzip content named .psv and plain content named .gz must both parse
    # identically on every path (magic sniff, not extension)
    rng = np.random.default_rng(3)
    lines = []
    for _ in range(500):
        x = rng.normal(size=3)
        lines.append("|".join(["1"] + [f"{v:.5f}" for v in x] + ["1.0"]))
    data = ("\n".join(lines) + "\n").encode()
    p_gz_as_psv = str(tmp_path / "a.psv")
    p_plain_as_gz = str(tmp_path / "b.gz")
    with open(p_gz_as_psv, "wb") as f:
        f.write(gzip.compress(data))
    with open(p_plain_as_gz, "wb") as f:
        f.write(data)
    a = _drain([p_gz_as_psv], None)
    b = _drain([p_plain_as_gz], None)
    _assert_same(a, b)
    # 500 rows pad up to 2 full batches; padding rows carry weight 0
    assert sum(x.shape[0] for x, _, _ in a) == 512
    assert float(a[-1][2][-12:].sum()) == 0.0


@pytest.mark.skipif(not native.available(), reason="native lib not built")
def test_native_stream_matches_python_fallback(tmp_path):
    paths = _write_shards(str(tmp_path), n_shards=1, rows=777)
    wanted = wanted_columns(SCHEMA)
    blocks = list(native.stream_blocks(paths[0], wanted, "|", salt=5,
                                       want_hashes=True, block_rows=100))
    arr = np.concatenate([a for a, _ in blocks])
    hashes = np.concatenate([h for _, h in blocks])
    with fs.open_maybe_gzip(paths[0]) as f:
        buf = f.read()
    ref_arr, ref_h = parse_lines_full(buf, SCHEMA, 5, True)
    np.testing.assert_array_equal(arr, ref_arr)
    np.testing.assert_array_equal(hashes, ref_h)


@pytest.mark.skipif(not native.available(), reason="native lib not built")
def test_native_stream_truncated_gzip_raises(tmp_path):
    p = str(tmp_path / "t.gz")
    with open(p, "wb") as f:
        f.write(gzip.compress(b"1|2|3|4|5\n" * 500)[:-16])
    with pytest.raises(OSError):
        list(native.stream_blocks(p, wanted_columns(SCHEMA), "|"))


def test_routing_rule_shared_and_uint64_safe():
    hashes = np.array([0, 1, 0x7FFFFFFF, 0xFFFFFFFF], np.uint32)
    # valid_rate=1.0: threshold is 2**32 — EVERY row is valid, including
    # hash 0xFFFFFFFF (a uint32-clamped compare would misroute it)
    assert route_is_valid(hashes, 1.0).all()
    assert not route_is_valid(hashes, 0.0).any()
    half = route_is_valid(hashes, 0.5)  # threshold 0x80000000
    np.testing.assert_array_equal(half, [True, True, True, False])


def test_remote_scheme_without_mtime_is_never_cached(tmp_path):
    class NoMtimeFS(fs.FileSystem):
        def size(self, path):
            return 10

    fs.register_filesystem("fakefs", NoMtimeFS())
    try:
        assert shard_cache.cache_key("fakefs://x/y.gz", SCHEMA, 0) is None
    finally:
        fs._SCHEME_HANDLERS.pop("fakefs", None)


def test_bf16_feature_dtype_cold_warm_parity(tmp_path):
    """bf16 streams must serve identical values cold (parse + cast +
    cache-write) and warm (bf16 memmap), in separate cache entries from
    the f32 variant."""
    import ml_dtypes

    paths = _write_shards(str(tmp_path), n_shards=2, rows=700)
    cache_dir = str(tmp_path / "cache")

    def drain(dtype, cd=cache_dir):
        stream = ShardStream(paths, SCHEMA, 128, valid_rate=0.2,
                             emit="train", cache_dir=cd,
                             feature_dtype=dtype)
        return [b["x"].copy() for b in stream]

    bf16 = np.dtype(ml_dtypes.bfloat16)
    cold = drain("bfloat16")
    warm = drain("bfloat16")
    assert cold and all(b.dtype == bf16 for b in cold)
    for c, w in zip(cold, warm):
        np.testing.assert_array_equal(c.view(np.uint16), w.view(np.uint16))
    # both dtype variants coexist without collision
    f32 = drain("float32")
    assert all(b.dtype == np.float32 for b in f32)
    metas = [f for f in os.listdir(cache_dir) if f.endswith(".meta.json")]
    assert len(metas) == 4  # 2 shards x 2 dtypes
    # bf16 values are the f32 values rounded to bf16
    np.testing.assert_array_equal(
        cold[0].view(np.uint16),
        f32[0].astype(bf16).view(np.uint16),
    )
    # bf16 slabs are half the f32 feature bytes
    x_f32 = sum(os.path.getsize(os.path.join(cache_dir, f))
                for f in os.listdir(cache_dir) if f.endswith(".x.f32"))
    x_bf16 = sum(os.path.getsize(os.path.join(cache_dir, f))
                 for f in os.listdir(cache_dir) if f.endswith(".x.bf16"))
    assert x_bf16 * 2 == x_f32


def test_bf16_fixed_step_zero_batches_match_dtype():
    import ml_dtypes

    from shifu_tensorflow_tpu.data.dataset import fixed_step_batches

    bf16 = np.dtype(ml_dtypes.bfloat16)
    out = list(fixed_step_batches(iter([]), 8, 2, 3, x_dtype=bf16))
    assert len(out) == 2 and all(b["x"].dtype == bf16 for b in out)


def test_bf16_gated_off_for_hashed_feature_models():
    """bf16 ingest must not engage when raw float bits feed a hash —
    bf16-rounded category codes would re-bucket embeddings, skewing
    training against the f32-hashing exported scorer."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.coordinator.worker import (
        WorkerConfig,
        _feature_dtype_for,
    )

    # z-scaled schema isolates the HASHING gate (the no-normalization
    # gate is covered by test_fp32_worker_defaults_to_bf16_transport)
    zs = SCHEMA.with_zscale([0.0, 0.0, 0.0], [1.0, 1.0, 1.0])

    def cfg(params):
        mc = ModelConfig.from_json({"train": {"params": {
            "NumHiddenLayers": 1, "NumHiddenNodes": [4],
            "ActivationFunc": ["relu"], "LearningRate": 0.1, **params}}})
        return WorkerConfig(
            worker_id="w", coordinator_host="h", coordinator_port=1,
            model_config=mc, schema=zs, dtype="bfloat16",
        )

    assert _feature_dtype_for(cfg({})) == "bfloat16"
    assert _feature_dtype_for(cfg({
        "EmbeddingColumnNums": [1], "EmbeddingHashSize": 128,
    })) == "float32"
    assert _feature_dtype_for(cfg({
        "ModelType": "wide_deep", "WideColumnNums": [1],
        "CrossHashSize": 64,
    })) == "float32"


def test_prune_keeps_newer_version_entries(tmp_path):
    """Rolling upgrades share cache dirs: a NEWER binary's entries must
    survive this binary's prune (only superseded versions are swept)."""
    import json as _json

    newer = shard_cache.CACHE_VERSION + 1
    (tmp_path / "new.meta.json").write_text(
        _json.dumps({"version": newer, "n_rows": 1, "n_features": 2})
    )
    (tmp_path / "new.x.f32").write_bytes(b"\0" * 8)
    shard_cache.prune_cache(str(tmp_path), max_bytes=10**9)
    assert (tmp_path / "new.meta.json").exists()
    assert (tmp_path / "new.x.f32").exists()


def test_stream_feature_dtype_resolver():
    """auto = compact bf16 transport by default, f32 when hashing needs
    raw float bits; explicit bf16 + hashing refuses loudly (r04 verdict
    item 3: compact transfer is the streaming DEFAULT)."""
    import pytest

    from shifu_tensorflow_tpu.data.dataset import resolve_stream_feature_dtype

    assert resolve_stream_feature_dtype(
        "auto", uses_feature_hashing=False) == "bfloat16"
    assert resolve_stream_feature_dtype(
        None, uses_feature_hashing=False) == "bfloat16"
    assert resolve_stream_feature_dtype(
        "auto", uses_feature_hashing=True) == "float32"
    # no ZSCALE stats = raw-magnitude features: auto stays conservative
    # (bf16's 8-bit mantissa silently truncates un-normalized codes), but
    # an explicit bfloat16 is the operator's call and still forces it
    assert resolve_stream_feature_dtype(
        "auto", uses_feature_hashing=False,
        has_normalization_stats=False) == "float32"
    assert resolve_stream_feature_dtype(
        "bfloat16", uses_feature_hashing=False,
        has_normalization_stats=False) == "bfloat16"
    assert resolve_stream_feature_dtype(
        "float32", uses_feature_hashing=False) == "float32"
    assert resolve_stream_feature_dtype(
        "bfloat16", uses_feature_hashing=False) == "bfloat16"
    with pytest.raises(ValueError, match="unsafe with"):
        resolve_stream_feature_dtype("bfloat16", uses_feature_hashing=True)
    with pytest.raises(ValueError, match="unknown"):
        resolve_stream_feature_dtype("float16", uses_feature_hashing=False)


def test_fp32_worker_defaults_to_bf16_transport():
    """The compact-transport default engages for PLAIN fp32 models too —
    transport dtype is decoupled from compute dtype (the jitted step
    widens on device, train/trainer.py _widen_features) — but only when
    the schema carries ZSCALE stats: normalized features are O(1) where
    bf16 is plenty; raw magnitudes stay float32 (docs/migration.md)."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.coordinator.worker import (
        WorkerConfig,
        _feature_dtype_for,
    )

    mc = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.1}}})
    zs = SCHEMA.with_zscale([0.0, 0.0, 0.0], [1.0, 1.0, 1.0])
    cfg = WorkerConfig(
        worker_id="w", coordinator_host="h", coordinator_port=1,
        model_config=mc, schema=zs,  # dtype defaults to fp32 compute
    )
    assert _feature_dtype_for(cfg) == "bfloat16"
    # no normalization stats: auto falls back to f32 transport
    cfg_raw = WorkerConfig(
        worker_id="w", coordinator_host="h", coordinator_port=1,
        model_config=mc, schema=SCHEMA,
    )
    assert _feature_dtype_for(cfg_raw) == "float32"
    # explicit opt-out survives the config bridge
    cfg2 = WorkerConfig(
        worker_id="w", coordinator_host="h", coordinator_port=1,
        model_config=mc, schema=zs, stream_feature_dtype="float32",
    )
    assert _feature_dtype_for(cfg2) == "float32"


def test_bare_cross_hash_size_does_not_block_bf16():
    """CrossHashSize without WideColumnNums builds a model with NO cross
    (models/factory.py gates it), so it must not count as feature hashing:
    auto keeps the compact bf16 transport, and an explicit bfloat16 must
    not be rejected."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.data.dataset import resolve_stream_feature_dtype

    mc = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.1,
        "CrossHashSize": 32}}})
    assert not mc.params.uses_feature_hashing
    assert resolve_stream_feature_dtype(
        "auto", uses_feature_hashing=mc.params.uses_feature_hashing
    ) == "bfloat16"
    # WITH wide columns the cross is real and the gate engages
    mc2 = ModelConfig.from_json({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.1,
        "ModelType": "wide_deep", "WideColumnNums": [1],
        "CrossHashSize": 32}}})
    assert mc2.params.uses_feature_hashing
