"""Wire-protocol + shared-dispatch-lane drills (serve/wire/): frame
codec round-trips and garbage rejection, the streaming frame server's
rid-multiplexed concurrency and typed ERROR frames (shed → 429 +
Retry-After, oversize → 413 before buffering), bit-identical parity
with the JSON /score path (single and multi-tenant), the zero-copy
single-source pack fast path, and the fleet lane's ownership /
degradation / restoration lifecycle — a killed owner loses ZERO
in-flight requests."""

from __future__ import annotations

import http.client
import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.export.saved_model import export_model
from shifu_tensorflow_tpu.serve.batcher import MicroBatcher
from shifu_tensorflow_tpu.serve.config import ServeConfig
from shifu_tensorflow_tpu.serve.server import ScoringServer
from shifu_tensorflow_tpu.serve.wire import frame as wire
from shifu_tensorflow_tpu.serve.wire.lane import LaneClient
from shifu_tensorflow_tpu.serve.wire.stream import FrameClient, FrameServer
from shifu_tensorflow_tpu.train.trainer import Trainer

N_FEATURES = 6


def _model_config():
    return ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05}}}
    )


def _export(tmp_dir: str, seed: int = 0) -> str:
    export_model(tmp_dir, Trainer(_model_config(), N_FEATURES, seed=seed))
    return tmp_dir


@pytest.fixture()
def export_dir(tmp_path):
    return _export(str(tmp_path / "model"))


@pytest.fixture()
def models_dir(tmp_path):
    root = tmp_path / "models"
    root.mkdir()
    _export(str(root / "alpha"), seed=1)
    _export(str(root / "beta"), seed=2)
    return str(root)


def _rows(n: int, seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).random((n, N_FEATURES)).astype(
        np.float32
    )


def _post(port: int, payload: dict, path="/score"):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=60.0)
    try:
        c.request("POST", path, json.dumps(payload),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        return r.status, dict(r.getheaders()), json.loads(r.read())
    finally:
        c.close()


# ------------------------------------------------------------ codec


def test_frame_codec_round_trips_all_kinds():
    a, b = socket.socketpair()
    try:
        rows = _rows(5)
        head, payload = wire.encode_score_request(rows, tenant="alpha",
                                                  rid="r-1")
        a.sendall(head)
        a.sendall(payload)
        f = wire.read_frame(b)
        assert (f.kind, f.tenant, f.rid) == (wire.KIND_SCORE, "alpha",
                                             "r-1")
        assert f.rows == 5 and f.features == N_FEATURES
        m = f.matrix()
        np.testing.assert_array_equal(m, rows)
        # the decode is a VIEW over the received payload, not a parse:
        # no per-row copies anywhere between the socket and the batcher
        assert np.shares_memory(
            m, np.frombuffer(f.payload, dtype=np.uint8))

        scores = np.arange(5, dtype=np.float64) / 7
        head, payload = wire.encode_scores_reply(scores, tenant="alpha",
                                                 rid="r-1")
        b.sendall(head)
        b.sendall(payload)
        g = wire.read_frame(a)
        assert g.kind == wire.KIND_SCORES and g.rid == "r-1"
        np.testing.assert_array_equal(g.vector(), scores)

        head, payload = wire.encode_error_reply(
            429, "busy", tenant="", rid="r-2", retry_after=3)
        b.sendall(head)
        b.sendall(payload)
        e = wire.read_frame(a)
        assert (e.kind, e.status, e.retry_after) == (wire.KIND_ERROR,
                                                     429, 3)
        assert e.message() == "busy" and e.rid == "r-2"
    finally:
        a.close()
        b.close()


def test_frame_codec_clean_eof_and_garbage():
    a, b = socket.socketpair()
    a.close()
    assert wire.read_frame(b) is None  # clean EOF between frames
    b.close()

    a, b = socket.socketpair()
    try:
        bad = wire.HEADER.pack(b"NOPE", 1, wire.KIND_SCORE, wire.DTYPE_F32,
                               0, 0, 0, 0, 1, 1)
        a.sendall(struct.pack("<I", len(bad)) + bad)
        with pytest.raises(wire.FrameProtocolError, match="magic"):
            wire.read_frame(b)
    finally:
        a.close()
        b.close()

    a, b = socket.socketpair()
    try:
        # geometry lie: header claims 4 rows, payload carries 2
        head, payload = wire.encode_score_request(_rows(2), rid="x")
        hdr = bytearray(head[4:])
        rows_off = wire.HEADER.size - 8
        hdr[rows_off:rows_off + 4] = struct.pack("<I", 4)
        body = bytes(hdr) + bytes(payload)
        a.sendall(struct.pack("<I", len(body)) + body)
        with pytest.raises(wire.FrameProtocolError):
            wire.read_frame(b)
    finally:
        a.close()
        b.close()


def test_frame_codec_oversize_is_discarded_not_buffered():
    """A frame past max_rows raises FrameTooLarge carrying the caller's
    identity (for the typed 413 reply) and DISCARDS the payload — the
    stream stays framed, the next frame reads fine."""
    a, b = socket.socketpair()
    try:
        for chunk in wire.encode_score_request(_rows(64), tenant="t",
                                               rid="big"):
            a.sendall(chunk)
        for chunk in wire.encode_score_request(_rows(2), rid="ok"):
            a.sendall(chunk)
        with pytest.raises(wire.FrameTooLarge) as ei:
            wire.read_frame(b, max_rows=16)
        assert ei.value.rid == "big" and ei.value.tenant == "t"
        f = wire.read_frame(b, max_rows=16)
        assert f.rid == "ok" and f.rows == 2
    finally:
        a.close()
        b.close()


# ------------------------------------------- frame server (single model)


def _cfg(export_dir, **kw):
    kw.setdefault("port", 0)
    kw.setdefault("frame_port", -1)
    return ServeConfig(model_dir=export_dir, **kw)


def test_frame_scores_bit_identical_to_json(export_dir):
    """The acceptance gate: the wire path reuses handle_rows, so frame
    scores are BIT-identical to the JSON path's round(6) floats."""
    with ScoringServer(_cfg(export_dir)) as srv:
        srv.start()
        rows = _rows(9, seed=3)
        _, _, body = _post(srv.port, {"rows": rows.tolist()})
        c = FrameClient(("127.0.0.1", srv.frame_port))
        try:
            got = c.score(rows)
        finally:
            c.close()
        assert np.array_equal(np.asarray(body["scores"], np.float64), got)
        counters = srv.metrics.counters()
        assert counters["frame_requests_total"] == 1
        assert counters["frame_rows_total"] == 9
        # occupancy gauge rides /metrics
        assert "stpu_serve_occupancy" in srv.metrics_text()


def test_frame_connection_multiplexes_concurrent_requests(export_dir):
    """One persistent connection, many in-flight requests, replies
    matched by rid regardless of completion order."""
    with ScoringServer(_cfg(export_dir)) as srv:
        srv.start()
        c = FrameClient(("127.0.0.1", srv.frame_port))
        try:
            want, pend = {}, {}
            for i in range(12):
                rows = _rows(3 + (i % 5), seed=10 + i)
                rid, p = c.submit(rows, rid=f"req{i}")
                pend[rid] = p
                _, _, body = _post(srv.port, {"rows": rows.tolist()})
                want[rid] = np.asarray(body["scores"], np.float64)
            for rid, p in pend.items():
                np.testing.assert_array_equal(c.wait(rid, p), want[rid])
        finally:
            c.close()


def test_frame_shed_returns_typed_429_with_retry_after(export_dir):
    """Shed-before-queue on the wire path: a frame the admission bound
    cannot take gets a typed ERROR frame carrying Retry-After — never a
    silent drop, never an unbounded queue."""
    cfg = _cfg(export_dir, max_batch=8, max_queue_rows=8,
               max_delay_ms=50.0, frame_max_rows=8)
    with ScoringServer(cfg) as srv:
        srv.start()
        c = FrameClient(("127.0.0.1", srv.frame_port))
        try:
            pend = [c.submit(_rows(8, seed=i)) for i in range(16)]
            sheds = 0
            for rid, p in pend:
                try:
                    c.wait(rid, p, timeout_s=60.0)
                except wire.FrameError as e:
                    assert e.status == 429
                    assert e.retry_after >= 1
                    sheds += 1
            assert sheds >= 1
            assert srv.metrics.counters()["shed_total"] >= 1
        finally:
            c.close()


def test_frame_oversize_replies_413_and_connection_survives(export_dir):
    cfg = _cfg(export_dir, frame_max_rows=16)
    with ScoringServer(cfg) as srv:
        srv.start()
        c = FrameClient(("127.0.0.1", srv.frame_port))
        try:
            with pytest.raises(wire.FrameError) as ei:
                c.score(_rows(64))
            assert ei.value.status == 413
            # same connection still scores
            assert c.score(_rows(4)).shape == (4,)
        finally:
            c.close()
        assert srv.metrics.counters()["frame_errors_total"] >= 1


def test_frame_garbage_closes_connection_but_not_server(export_dir):
    with ScoringServer(_cfg(export_dir)) as srv:
        srv.start()
        s = socket.create_connection(("127.0.0.1", srv.frame_port))
        # well-framed length, garbage header (bad magic): framing is
        # unrecoverable, so the server closes the connection
        s.sendall(struct.pack("<I", wire.HEADER.size)
                  + b"X" * wire.HEADER.size)
        s.settimeout(10.0)
        assert s.recv(1) == b""
        s.close()
        c = FrameClient(("127.0.0.1", srv.frame_port))
        try:
            assert c.score(_rows(3)).shape == (3,)
        finally:
            c.close()


def test_frame_multi_tenant_routes_by_tenant_field(models_dir):
    """Frames carry the tenant name where JSON uses /score/<model>; the
    scores must match that tenant's JSON path bit-for-bit, and the two
    tenants must differ (distinct seeds)."""
    cfg = ServeConfig(models_dir=models_dir, port=0, frame_port=-1)
    with ScoringServer(cfg) as srv:
        srv.start()
        rows = _rows(7, seed=4)
        c = FrameClient(("127.0.0.1", srv.frame_port))
        try:
            got = {}
            for tenant in ("alpha", "beta"):
                _, _, body = _post(srv.port, {"rows": rows.tolist()},
                                   path=f"/score/{tenant}")
                got[tenant] = c.score(rows, tenant=tenant)
                assert np.array_equal(
                    np.asarray(body["scores"], np.float64), got[tenant])
            assert not np.array_equal(got["alpha"], got["beta"])
            with pytest.raises(wire.FrameError) as ei:
                c.score(rows, tenant="gamma")
            assert ei.value.status == 404
        finally:
            c.close()


# ------------------------------------------------- zero-copy fast path


def test_pack_single_source_is_zero_copy():
    """The ride-along pin: when ONE pending request exactly fills its
    bucket, the matrix handed to score_fn IS the submitted array — no
    concat, no pad copy, end to end."""
    seen = []

    def score_fn(x):
        seen.append(x)
        return np.zeros((x.shape[0], 1), np.float32)

    b = MicroBatcher(score_fn, max_batch=64, max_delay_s=0.001)
    try:
        rows = _rows(8)  # bucket_size(8) == 8: pad is a no-op
        b.submit(rows)
    finally:
        b.close()
    assert len(seen) == 1
    assert seen[0].shape == (8, N_FEATURES)
    assert np.shares_memory(seen[0], rows)


def test_frame_payload_reaches_scorer_without_copy(export_dir):
    """The whole receive chain — socket buffer → frame view → batcher →
    scorer — moves ONE allocation: score_fn sees memory shared with the
    frame payload when the frame exactly fills a bucket."""
    shared = []
    sent = {}

    from shifu_tensorflow_tpu.serve.metrics import ServeMetrics

    class Probe:
        """Stands in for ScoringServer: handle_rows records whether the
        matrix it got aliases the frame payload read_frame produced."""

        metrics = ServeMetrics()

        def handle_rows(self, rows, rid, model_name=None):
            shared.append(np.shares_memory(rows, sent["payload_probe"]))
            return {"scores": [0.0] * rows.shape[0]}

        def note_shed(self, *a, **k):
            pass

    fs = FrameServer(Probe(), host="127.0.0.1", port=0, max_rows=4096)
    fs.start()
    try:
        # capture the server-side payload buffer via a frame tap: easier
        # to verify aliasing INSIDE the server by monkeypatching
        # read_frame than to reach across the thread boundary
        orig = wire.read_frame

        def tap(sock, max_rows=None):
            f = orig(sock, max_rows=max_rows)
            if f is not None and f.kind == wire.KIND_SCORE:
                sent["payload_probe"] = np.frombuffer(f.payload,
                                                      dtype=np.uint8)
            return f

        wire.read_frame = tap
        try:
            c = FrameClient(("127.0.0.1", fs.port))
            try:
                c.score(_rows(8))
            finally:
                c.close()
        finally:
            wire.read_frame = orig
    finally:
        fs.close()
    assert shared == [True]


# --------------------------------------------------- shared dispatch lane


@pytest.fixture()
def obs_env(tmp_path):
    from shifu_tensorflow_tpu.obs import install_obs
    from shifu_tensorflow_tpu.obs import journal as journal_mod
    from shifu_tensorflow_tpu.obs import slo as slo_mod
    from shifu_tensorflow_tpu.obs import trace as trace_mod
    from shifu_tensorflow_tpu.obs.config import ObsConfig

    base = str(tmp_path / "wire-journal.jsonl")
    install_obs(ObsConfig(enabled=True, journal_path=base), plane="serve")
    yield base
    trace_mod.uninstall()
    journal_mod.uninstall()
    slo_mod.uninstall()


def _wait(pred, timeout_s=15.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.02)


def test_lane_owner_and_sibling_share_one_dispatch(export_dir, tmp_path,
                                                   obs_env):
    """Owner (index 0) binds the lane socket; the sibling forwards its
    packed batches there and scatters the owner's replies — scores stay
    bit-identical to a direct submission, the owner's counters carry the
    device truth, and the journal records ownership + the join."""
    from shifu_tensorflow_tpu.obs.journal import read_events

    lane_path = str(tmp_path / "lane.sock")
    owner = ScoringServer(_cfg(export_dir, frame_port=0), worker_index=0,
                          lane_socket=lane_path)
    owner.start()
    sib = ScoringServer(_cfg(export_dir, frame_port=0), worker_index=1,
                        lane_socket=lane_path)
    sib.start()
    try:
        _wait(sib.lane.connected, what="lane join")
        rows = _rows(9, seed=5)
        via_lane = np.asarray(
            sib.handle_rows(rows, rid="lane-1")["scores"], np.float64)
        direct = np.asarray(
            owner.handle_rows(rows, rid="own-1")["scores"], np.float64)
        np.testing.assert_array_equal(via_lane, direct)
        # device truth lives at the owner: the sibling forwarded, so its
        # own batch counters must NOT double-count the dispatch
        _wait(lambda: owner.metrics.counters()["batches_total"] >= 2,
              what="owner dispatch counters")
        assert sib.metrics.counters()["batches_total"] == 0
        assert sib.metrics.counters()["requests_total"] == 1
        assert sib.lane.stats()["forwarded"] >= 1
    finally:
        sib.close()
        owner.close()
    events = read_events(obs_env)
    kinds = [e["event"] for e in events]
    assert "lane_owner" in kinds
    assert "lane_restored" in kinds
    # exactly the one owner ever bound the lane
    assert kinds.count("lane_owner") == 1
    # the forwarded dispatch journals ONE serve_batch (the owner's) —
    # its rids list carries the sibling's lane correlation id
    batches = [e for e in events if e["event"] == "serve_batch"]
    rids = [r for e in batches for r in e.get("rids", ())]
    assert any(r.startswith("l") for r in rids)


def test_lane_owner_death_loses_nothing_and_rejoins(export_dir, tmp_path,
                                                    obs_env):
    """The kill drill: requests racing an owner death fall back to the
    sibling's private dispatch (no error, no loss), the outage journals
    lane_degraded, and a re-elected owner on the same socket journals a
    fresh lane_restored join."""
    from shifu_tensorflow_tpu.obs.journal import read_events

    lane_path = str(tmp_path / "lane.sock")
    owner = ScoringServer(_cfg(export_dir, frame_port=0), worker_index=0,
                          lane_socket=lane_path)
    owner.start()
    sib = ScoringServer(_cfg(export_dir, frame_port=0), worker_index=1,
                        lane_socket=lane_path)
    sib.start()
    owner2 = None
    try:
        _wait(sib.lane.connected, what="lane join")
        assert sib.handle_rows(_rows(4), rid="warm")["scores"]
        # keep traffic flowing while the owner dies mid-stream
        errors, done = [], []

        def pound():
            for i in range(40):
                try:
                    out = sib.handle_rows(_rows(3, seed=i),
                                          rid=f"k{i}")["scores"]
                    assert len(out) == 3
                    done.append(i)
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                time.sleep(0.005)

        t = threading.Thread(target=pound)
        t.start()
        time.sleep(0.05)
        owner.close()  # the kill (socket dies with it)
        t.join(timeout=120.0)
        assert not t.is_alive()
        assert errors == []          # ZERO lost / errored requests
        assert len(done) == 40
        _wait(lambda: not sib.lane.connected(), what="lane loss notice")
        assert sib.metrics.counters()["batches_total"] >= 1  # fallback
        # re-elected owner (same index, same socket) → sibling rejoins
        owner2 = ScoringServer(_cfg(export_dir, frame_port=0),
                               worker_index=0, lane_socket=lane_path)
        owner2.start()
        _wait(sib.lane.connected, what="lane rejoin")
        out = sib.handle_rows(_rows(5), rid="after")["scores"]
        assert len(out) == 5
    finally:
        sib.close()
        if owner2 is not None:
            owner2.close()
    events = read_events(obs_env)
    kinds = [e["event"] for e in events]
    assert kinds.count("lane_owner") == 2     # original + re-elected
    assert "lane_degraded" in kinds
    # degraded then restored, in that order
    assert (kinds.index("lane_degraded")
            < len(kinds) - 1 - kinds[::-1].index("lane_restored"))


def test_lane_client_falls_back_when_owner_never_existed(tmp_path):
    """No owner at all: forward() says no, the batcher dispatches
    privately, and nothing journals a degradation (there was no lane to
    degrade — startup races must not trip the kill-drill check)."""
    lane = LaneClient(str(tmp_path / "nobody.sock"),
                      reconnect_interval_s=0.05)
    try:
        seen = []

        def score_fn(x):
            seen.append(x.shape[0])
            return np.zeros((x.shape[0], 1), np.float32)

        b = MicroBatcher(score_fn, max_batch=32, max_delay_s=0.001,
                         lane=lane)
        try:
            out = b.submit(_rows(4))
            assert out.shape[0] == 4
        finally:
            b.close()
        assert seen  # dispatched locally
        assert lane.stats()["fallback"] >= 1
        assert lane.stats()["connected"] is False
    finally:
        lane.close()
