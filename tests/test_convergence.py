"""Convergence gates at the BASELINE.md north star: KS >= 0.45.

SURVEY.md §7.2 item 3 requires convergence-parity validation, not
bit-parity: the clean psum equivalent of SyncReplicasOptimizer changes
effective batch/step math, so the proof is that every training path
reaches the quality bar on a learnable dataset.  Four gated paths:

    ssgd  x {single-process, 2-process SPMD}
    sagn  x {single-process, 2-process SPMD}

The dataset is synthetic logistic with a strong signal (scaled logits) so
the Bayes-optimal KS is comfortably above the gate; a regression that
breaks optimization math (loss weighting, gradient aggregation, SAGN
window averaging, SPMD batch assembly) lands well under it.
"""

from __future__ import annotations

import gzip
import os

import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.coordinator.coordinator import JobSpec, JobState
from shifu_tensorflow_tpu.coordinator.submitter import JobSubmitter
from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
from shifu_tensorflow_tpu.data.dataset import InMemoryDataset
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.data.splitter import split_training_data
from shifu_tensorflow_tpu.train import make_trainer
from shifu_tensorflow_tpu.train.checkpoint import NpzCheckpointer

# subprocess fleets need cross-process CPU collectives — an environment
# capability, not framework logic; see tests/jaxcaps.py for the rationale
from jaxcaps import needs_multiprocess_collectives

KS_GATE = 0.45  # BASELINE.md north star
N_FEATURES = 10
EPOCHS = 6

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO_ROOT,
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


@pytest.fixture(scope="module")
def strong_dataset(tmp_path_factory):
    """Gzip PSV shards with a strongly learnable signal: logits scaled 3x
    so the Bayes-optimal KS is ~0.7 — far enough above the 0.45 gate that
    passing requires real optimization, not luck."""
    rng = np.random.default_rng(7)
    root = tmp_path_factory.mktemp("strongdata")
    w_true = rng.normal(size=N_FEATURES)
    w_true *= 3.0 / np.linalg.norm(w_true)
    paths = []
    for i in range(4):
        path = root / f"part-{i:05d}.gz"
        with gzip.open(path, "wt") as f:
            for _ in range(600):
                x = rng.normal(size=N_FEATURES)
                p = 1.0 / (1.0 + np.exp(-float(x @ w_true)))
                y = 1 if rng.random() < p else 0
                cols = [str(y)] + [f"{v:.5f}" for v in x] + ["1.0"]
                f.write("|".join(cols) + "\n")
        paths.append(str(path))
    return {"root": str(root), "paths": paths}


def _schema() -> RecordSchema:
    return RecordSchema(
        feature_columns=tuple(range(1, N_FEATURES + 1)),
        target_column=0,
        weight_column=N_FEATURES + 1,
    )


def _model_config(algorithm: str) -> ModelConfig:
    params = {
        "NumHiddenLayers": 2,
        "NumHiddenNodes": [16, 8],
        "ActivationFunc": ["relu", "tanh"],
        "LearningRate": 0.05,
        "Optimizer": "adam",
        "Algorithm": algorithm,
    }
    if algorithm == "sagn":
        # the reference's communication window (SAGN.py update_window=5);
        # window=1 degenerates to the plain step and would gate nothing
        # SAGN-specific
        params["UpdateWindow"] = 5
    return ModelConfig.from_json(
        {
            "train": {
                "numTrainEpochs": EPOCHS,
                "validSetRate": 0.2,
                "params": params,
            }
        }
    )


def _final_ks_from_checkpoint(ckpt_dir: str, mc: ModelConfig,
                              dataset: InMemoryDataset) -> float:
    """Restore the chief's final checkpoint into a fresh local trainer and
    score the union validation set — the quality the exported model would
    actually serve."""
    trainer = make_trainer(
        mc, N_FEATURES, feature_columns=_schema().feature_columns
    )
    ckpt = NpzCheckpointer(ckpt_dir)
    assert ckpt.latest_epoch() == EPOCHS - 1
    restored, _ = ckpt.restore_latest(trainer.state)
    trainer.state = restored
    ev = trainer.evaluate(dataset.valid_batches(64))
    return ev["ks"]


@pytest.mark.parametrize("algorithm", ["ssgd", "sagn"])
def test_single_process_reaches_ks_gate(strong_dataset, algorithm):
    mc = _model_config(algorithm)
    dataset = InMemoryDataset.load(
        strong_dataset["paths"], _schema(), mc.valid_set_rate, salt=0
    )
    trainer = make_trainer(
        mc, N_FEATURES, feature_columns=_schema().feature_columns
    )
    history = trainer.fit(dataset, batch_size=64)
    ks = history[-1].ks
    assert ks >= KS_GATE, (
        f"{algorithm} single-process KS {ks:.3f} < gate {KS_GATE}"
    )


def test_round4_training_features_reach_ks_gate(strong_dataset):
    """The round-4 training features composed — gradient accumulation,
    warmup+cosine LR schedule, keep-best, early-stop-at-target — must
    still clear the north-star gate (and the early stop must fire AT or
    above it, by definition of the criterion)."""
    params = {
        "NumHiddenLayers": 2,
        "NumHiddenNodes": [16, 8],
        "ActivationFunc": ["relu", "tanh"],
        "LearningRate": 0.1,
        "Optimizer": "adam",
        "LearningRateSchedule": "cosine",
        "WarmupSteps": 10,
        "DecaySteps": 200,
        "DecayRate": 0.1,
    }
    mc = ModelConfig.from_json(
        {"train": {"numTrainEpochs": 12, "validSetRate": 0.2,
                   "params": params}}
    )
    dataset = InMemoryDataset.load(
        strong_dataset["paths"], _schema(), mc.valid_set_rate, salt=0
    )
    from shifu_tensorflow_tpu.train.trainer import EarlyStopper

    trainer = make_trainer(
        mc, N_FEATURES, feature_columns=_schema().feature_columns,
        accum_steps=2, keep_best="ks",
    )
    history = trainer.fit(
        dataset, batch_size=64,
        early_stop=EarlyStopper(target_ks=KS_GATE),
    )
    assert trainer.stop_reason, "never reached the gate within the budget"
    assert history[-1].ks >= KS_GATE
    assert trainer.best_metric >= KS_GATE  # keep-best tracked the gate run


@pytest.mark.parametrize("algorithm", ["ssgd", "sagn"])
@needs_multiprocess_collectives
def test_two_process_spmd_reaches_ks_gate(strong_dataset, tmp_path,
                                          algorithm):
    mc = _model_config(algorithm)
    shards = split_training_data(strong_dataset["root"], 2)
    ckpt_dir = str(tmp_path / "ckpt")
    schema = _schema()

    def make_cfg(worker_id: str, addr) -> WorkerConfig:
        return WorkerConfig(
            worker_id=worker_id,
            coordinator_host=addr[0],
            coordinator_port=addr[1],
            model_config=mc,
            schema=schema,
            batch_size=64,
            checkpoint_dir=ckpt_dir,
            heartbeat_interval_s=0.2,
            seed=0,
            spmd=True,
        )

    spec = JobSpec(
        n_workers=2, shards=shards, spmd=True, epochs=EPOCHS,
        registration_timeout_s=120.0, epoch_barrier_timeout_s=120.0,
    )
    submitter = JobSubmitter(
        spec, make_cfg, launcher="process", worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
    )
    result = submitter.run(timeout_s=600.0)
    assert result.state == JobState.FINISHED, result.failure_reason

    dataset = InMemoryDataset.load(
        strong_dataset["paths"], schema, mc.valid_set_rate, salt=0
    )
    ks = _final_ks_from_checkpoint(ckpt_dir, mc, dataset)
    assert ks >= KS_GATE, (
        f"{algorithm} 2-process SPMD KS {ks:.3f} < gate {KS_GATE}"
    )
