"""HDFS (WebHDFS) + GCS backends against in-process fake servers.

The fakes implement the REST subset the backends speak, over a temp dir /
dict — the test strategy the reference never had for its HDFS paths
(SURVEY.md §4: no fake backends existed at all).  End-to-end coverage:
ShardStream ingest, NpzCheckpointer save/restore, and the metrics board
all on non-local schemes.
"""

import gzip
import json
import os
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from shifu_tensorflow_tpu.data.dataset import ShardStream
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.utils import fs
from shifu_tensorflow_tpu.utils.fs_gcs import GcsFileSystem
from shifu_tensorflow_tpu.utils.fs_webhdfs import WebHdfsFileSystem

SCHEMA = RecordSchema(feature_columns=(1, 2, 3), target_column=0, weight_column=4)


# --------------------------------------------------------------------------
# fake WebHDFS namenode+datanode in one server, backed by a local dir
# --------------------------------------------------------------------------


class _WebHdfsHandler(BaseHTTPRequestHandler):
    root: str
    redirect_creates = True

    def log_message(self, *a):  # quiet
        pass

    def _local(self, urlpath: str) -> str:
        assert urlpath.startswith("/webhdfs/v1")
        rel = urllib.parse.unquote(urlpath[len("/webhdfs/v1"):]).lstrip("/")
        return os.path.join(self.root, rel)

    def _json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _status_obj(self, p: str) -> dict:
        st = os.stat(p)
        return {
            "length": st.st_size,
            "modificationTime": int(st.st_mtime * 1000),
            "type": "DIRECTORY" if os.path.isdir(p) else "FILE",
            "pathSuffix": "",
        }

    def do_GET(self):
        u = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(u.query))
        p = self._local(u.path)
        op = q.get("op")
        if op == "GETFILESTATUS":
            if not os.path.exists(p):
                return self._json(404, {"RemoteException": {
                    "message": "File does not exist"}})
            return self._json(200, {"FileStatus": self._status_obj(p)})
        if op == "LISTSTATUS":
            if not os.path.isdir(p):
                return self._json(404, {"RemoteException": {
                    "message": "not a directory"}})
            entries = []
            for name in sorted(os.listdir(p)):
                e = self._status_obj(os.path.join(p, name))
                e["pathSuffix"] = name
                entries.append(e)
            return self._json(200, {"FileStatuses": {"FileStatus": entries}})
        if op == "OPEN":
            if not os.path.exists(p):
                return self._json(404, {"RemoteException": {
                    "message": "File does not exist"}})
            with open(p, "rb") as f:
                data = f.read()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        self._json(400, {"RemoteException": {"message": f"bad op {op}"}})

    def do_PUT(self):
        u = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(u.query))
        p = self._local(u.path)
        op = q.get("op")
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        if op == "CREATE":
            # the real namenode 307-redirects the first (bodyless) PUT to a
            # datanode; model that to exercise the client's two-step hop
            if self.redirect_creates and "step2" not in q:
                self.send_response(307)
                self.send_header(
                    "Location",
                    f"http://{self.headers['Host']}{u.path}?"
                    + urllib.parse.urlencode({**q, "step2": "1"}),
                )
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            os.makedirs(os.path.dirname(p), exist_ok=True)
            with open(p, "wb") as f:
                f.write(body)
            return self._json(201, {})
        if op == "MKDIRS":
            os.makedirs(p, exist_ok=True)
            return self._json(200, {"boolean": True})
        if op == "RENAME":
            dst = os.path.join(self.root, q["destination"].lstrip("/"))
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            os.replace(p, dst)
            return self._json(200, {"boolean": True})
        self._json(400, {"RemoteException": {"message": f"bad op {op}"}})

    def do_DELETE(self):
        u = urllib.parse.urlsplit(self.path)
        p = self._local(u.path)
        ok = os.path.exists(p)
        if ok:
            os.remove(p)
        self._json(200, {"boolean": ok})


@pytest.fixture
def webhdfs(tmp_path):
    root = str(tmp_path / "hdfs-root")
    os.makedirs(root)
    handler = type("H", (_WebHdfsHandler,), {"root": root})
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    host, port = server.server_address[:2]
    yield {"base": f"hdfs://{host}:{port}", "root": root}
    server.shutdown()
    server.server_close()


# --------------------------------------------------------------------------
# fake GCS JSON API, backed by a dict
# --------------------------------------------------------------------------


class _GcsHandler(BaseHTTPRequestHandler):
    objects: dict  # name -> (bytes, generation)
    gen_counter: list

    def log_message(self, *a):
        pass

    def _json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _meta(self, name: str) -> dict:
        data, gen = self.objects[name]
        return {"name": name, "size": str(len(data)), "generation": str(gen)}

    def do_GET(self):
        u = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(u.query))
        m = re.match(r"^/storage/v1/b/[^/]+/o/([^/]+)$", u.path)
        if m:
            name = urllib.parse.unquote(m.group(1))
            if name not in self.objects:
                return self._json(404, {"error": "not found"})
            if q.get("alt") == "media":
                data = self.objects[name][0]
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            return self._json(200, self._meta(name))
        if re.match(r"^/storage/v1/b/[^/]+/o$", u.path):
            prefix = q.get("prefix", "")
            items = [
                self._meta(n) for n in sorted(self.objects)
                if n.startswith(prefix)
            ]
            return self._json(200, {"items": items})
        self._json(400, {"error": f"bad path {u.path}"})

    def do_POST(self):
        u = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(u.query))
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        if u.path.startswith("/upload/storage/v1/b/"):
            name = q["name"]
            self.gen_counter[0] += 1
            self.objects[name] = (body, self.gen_counter[0])
            return self._json(200, self._meta(name))
        m = re.match(
            r"^/storage/v1/b/[^/]+/o/([^/]+)/rewriteTo/b/[^/]+/o/([^/]+)$",
            u.path,
        )
        if m:
            src = urllib.parse.unquote(m.group(1))
            dst = urllib.parse.unquote(m.group(2))
            self.gen_counter[0] += 1
            self.objects[dst] = (self.objects[src][0], self.gen_counter[0])
            return self._json(200, {"done": True})
        self._json(400, {"error": f"bad path {u.path}"})

    def do_DELETE(self):
        u = urllib.parse.urlsplit(self.path)
        m = re.match(r"^/storage/v1/b/[^/]+/o/([^/]+)$", u.path)
        if m:
            self.objects.pop(urllib.parse.unquote(m.group(1)), None)
            return self._json(204, {})
        self._json(400, {"error": "bad path"})


@pytest.fixture
def gcs(monkeypatch):
    handler = type("G", (_GcsHandler,), {"objects": {}, "gen_counter": [0]})
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    host, port = server.server_address[:2]
    fs.register_filesystem("gs", GcsFileSystem(endpoint=f"http://{host}:{port}"))
    yield {"base": "gs://bucket", "objects": handler.objects}
    fs._SCHEME_HANDLERS.pop("gs", None)
    server.shutdown()
    server.server_close()


# --------------------------------------------------------------------------


def _shard_bytes(rows=1000, seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(rows):
        x = rng.normal(size=3)
        lines.append("|".join(["1"] + [f"{v:.5f}" for v in x] + ["1.0"]))
    return ("\n".join(lines) + "\n").encode()


class TestWebHdfs:
    def test_roundtrip(self, webhdfs):
        base = webhdfs["base"]
        fs.mkdirs(f"{base}/data")
        fs.write_text(f"{base}/data/a.txt", "hello")
        assert fs.exists(f"{base}/data/a.txt")
        assert not fs.exists(f"{base}/data/missing")
        assert fs.read_text(f"{base}/data/a.txt") == "hello"
        assert fs.size(f"{base}/data/a.txt") == 5
        assert fs.mtime_ns(f"{base}/data/a.txt") > 0
        fs.rename(f"{base}/data/a.txt", f"{base}/data/b.txt")
        assert fs.read_text(f"{base}/data/b.txt") == "hello"
        assert fs.listdir_recursive(f"{base}/data") == [f"{base}/data/b.txt"]
        fs.delete(f"{base}/data/b.txt")
        assert not fs.exists(f"{base}/data/b.txt")

    def test_append_text_board(self, webhdfs):
        board = f"{webhdfs['base']}/board/progress.log"
        fs.append_text(board, "epoch 0\n")
        fs.append_text(board, "epoch 1\n")
        assert fs.read_text(board) == "epoch 0\nepoch 1\n"

    def test_shardstream_over_hdfs(self, webhdfs, tmp_path):
        base = webhdfs["base"]
        data = _shard_bytes()
        # one gzip shard, one plain shard (magic-sniffed, not extension)
        fs.mkdirs(f"{base}/shards")
        with fs.filesystem_for(base).open_write(f"{base}/shards/s0.gz") as f:
            f.write(gzip.compress(data))
        with fs.filesystem_for(base).open_write(f"{base}/shards/s1.psv") as f:
            f.write(data)
        local = tmp_path / "local.psv"
        local.write_bytes(data)

        remote = [f"{base}/shards/s0.gz", f"{base}/shards/s1.psv"]
        got = [
            b["x"].copy()
            for b in ShardStream(remote, SCHEMA, 128, valid_rate=0.2)
        ]
        want = [
            b["x"].copy()
            for b in ShardStream(
                [str(local), str(local)], SCHEMA, 128, valid_rate=0.2
            )
        ]
        assert len(got) == len(want)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    def test_shard_cache_from_remote_source(self, webhdfs, tmp_path):
        base = webhdfs["base"]
        data = _shard_bytes()
        with fs.filesystem_for(base).open_write(f"{base}/s.gz") as f:
            f.write(gzip.compress(data))
        cache_dir = str(tmp_path / "cache")
        path = f"{base}/s.gz"
        cold = [b["x"].copy() for b in ShardStream([path], SCHEMA, 128,
                                                   cache_dir=cache_dir)]
        assert any(
            f.endswith(".meta.json") for f in os.listdir(cache_dir)
        ), "remote shard should cache (webhdfs supplies mtime)"
        warm = [b["x"].copy() for b in ShardStream([path], SCHEMA, 128,
                                                   cache_dir=cache_dir)]
        for c, w in zip(cold, warm):
            np.testing.assert_array_equal(c, w)

    def test_npz_checkpointer_on_hdfs(self, webhdfs):
        jax = pytest.importorskip("jax")
        from shifu_tensorflow_tpu.config.model_config import ModelConfig
        from shifu_tensorflow_tpu.train.checkpoint import NpzCheckpointer
        from shifu_tensorflow_tpu.train.trainer import Trainer

        mc = ModelConfig.from_json(
            {"train": {"numTrainEpochs": 1, "params": {
                "NumHiddenLayers": 1, "NumHiddenNodes": [4],
                "ActivationFunc": ["relu"], "LearningRate": 0.1}}}
        )
        tr = Trainer(mc, 3)
        ckpt_dir = f"{webhdfs['base']}/ckpt"
        ck = NpzCheckpointer(ckpt_dir, every_epochs=1, max_to_keep=2)
        ck.save(0, tr.state)
        ck.save(1, tr.state)
        ck.save(2, tr.state)  # max_to_keep prunes epoch 0
        assert ck.latest_epoch() == 2
        assert ck._epochs() == [1, 2]
        restored, nxt = ck.restore_latest(tr.state)
        assert nxt == 3
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(restored.step)),
            np.asarray(jax.device_get(tr.state.step)),
        )


class TestGcs:
    def test_roundtrip(self, gcs):
        base = gcs["base"]
        fs.write_text(f"{base}/data/a.txt", "hello")
        assert fs.exists(f"{base}/data/a.txt")
        assert not fs.exists(f"{base}/data/missing")
        assert fs.read_text(f"{base}/data/a.txt") == "hello"
        assert fs.size(f"{base}/data/a.txt") == 5
        m1 = fs.mtime_ns(f"{base}/data/a.txt")
        fs.write_text(f"{base}/data/a.txt", "hello2")
        assert fs.mtime_ns(f"{base}/data/a.txt") > m1, \
            "generation must advance on rewrite (cache staleness signal)"
        fs.rename(f"{base}/data/a.txt", f"{base}/data/b.txt")
        assert fs.read_text(f"{base}/data/b.txt") == "hello2"
        assert not fs.exists(f"{base}/data/a.txt")
        assert fs.listdir_recursive(f"{base}/data") == [f"{base}/data/b.txt"]

    def test_shardstream_over_gcs(self, gcs, tmp_path):
        base = gcs["base"]
        data = _shard_bytes()
        with fs.filesystem_for(base).open_write(f"{base}/shards/s0.gz") as f:
            f.write(gzip.compress(data))
        local = tmp_path / "local.psv"
        local.write_bytes(data)
        got = [
            b["x"].copy()
            for b in ShardStream([f"{base}/shards/s0.gz"], SCHEMA, 128)
        ]
        want = [
            b["x"].copy() for b in ShardStream([str(local)], SCHEMA, 128)
        ]
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)


def test_unknown_scheme_still_errors():
    with pytest.raises(ValueError, match="no filesystem registered"):
        fs.filesystem_for("s3://bucket/x")


class TestErrorSemantics:
    def test_exists_propagates_non_404(self, gcs, monkeypatch):
        """A transient 5xx/403 must NOT read as 'absent' — append_text
        would silently rebuild the metrics board from scratch."""
        from shifu_tensorflow_tpu.utils.fs_gcs import GcsError

        base = gcs["base"]
        fs.write_text(f"{base}/board.log", "history\n")
        impl = fs.filesystem_for(base)

        def broken_meta(path):
            raise GcsError("gcs GET ...: 503 Service Unavailable", code=503)

        monkeypatch.setattr(impl, "_meta", broken_meta)
        with pytest.raises(GcsError):
            impl.exists(f"{base}/board.log")

    def test_upload_on_close_discards_on_exception(self, gcs):
        """An exception inside the with-block must not publish the partial
        buffer (checkpoint writers raise mid-serialization)."""
        base = gcs["base"]
        with pytest.raises(RuntimeError):
            with fs.filesystem_for(base).open_write(f"{base}/partial.npz") as f:
                f.write(b"half-written")
                raise RuntimeError("serialization failed")
        assert not fs.exists(f"{base}/partial.npz")
