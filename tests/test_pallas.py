"""Pallas fused hashed-embedding kernel — exact parity with the XLA path.

Runs in interpreter mode on CPU (the kernel auto-selects interpret off-TPU);
the contract is bit-identical outputs and gradients between the pallas and
XLA implementations for any shape, including non-tile-aligned ones.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shifu_tensorflow_tpu.models.embeddings import HashedEmbedding
from shifu_tensorflow_tpu.ops import hashing
from shifu_tensorflow_tpu.ops.pallas.embedding import hashed_embedding_lookup


def _xla_reference(x, table):
    ids = hashing.salted_bucket_ids(x, table.shape[0])
    return jnp.take(table, ids, axis=0).reshape(x.shape[0], -1)


@pytest.mark.parametrize(
    "n,c,h,d",
    [
        (16, 5, 256, 8),
        (33, 3, 100, 4),  # nothing tile-aligned
        (7, 1, 513, 16),
        (260, 2, 1030, 8),  # batch and hash both cross block boundaries
    ],
)
def test_forward_parity(n, c, h, d):
    rng = np.random.default_rng(n * 31 + h)
    x = jnp.asarray(rng.normal(size=(n, c)) * 5, jnp.float32)
    table = jnp.asarray(rng.normal(size=(h, d)), jnp.float32)
    got = hashed_embedding_lookup(x, table, 64, 128)
    want = _xla_reference(x, table)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_gradient_parity():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(40, 3)) * 3, jnp.float32)
    table = jnp.asarray(rng.normal(size=(128, 8)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(40, 24)), jnp.float32)

    def loss_pallas(t):
        return jnp.sum(hashed_embedding_lookup(x, t, 16, 64) * w)

    def loss_xla(t):
        return jnp.sum(_xla_reference(x, t) * w)

    g_pallas = jax.grad(loss_pallas)(table)
    g_xla = jax.grad(loss_xla)(table)
    np.testing.assert_allclose(
        np.asarray(g_pallas), np.asarray(g_xla), rtol=1e-6, atol=1e-6
    )
    # collisions: several rows hashing to the same bucket must accumulate,
    # which the XLA grad does by construction — equality above proves the
    # scatter-add; also check the grad is not trivially zero
    assert float(jnp.abs(g_pallas).sum()) > 0


def test_x_gradient_is_zero():
    x = jnp.ones((8, 2), jnp.float32)
    table = jnp.ones((64, 4), jnp.float32)
    gx = jax.grad(lambda xx: jnp.sum(hashed_embedding_lookup(xx, table)))(x)
    np.testing.assert_array_equal(np.asarray(gx), np.zeros_like(gx))


def test_module_pallas_impl_matches_xla():
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(20, 4)) * 2, jnp.float32)
    key = jax.random.key(0)
    m_xla = HashedEmbedding(hash_size=128, features=8, shard_table=False,
                            impl="xla")
    m_pl = HashedEmbedding(hash_size=128, features=8, shard_table=False,
                           impl="pallas")
    v = m_xla.init(key, x)
    out_xla = m_xla.apply(v, x)
    out_pl = m_pl.apply(v, x)  # same params: impl is not part of the pytree
    np.testing.assert_array_equal(np.asarray(out_pl), np.asarray(out_xla))


def test_auto_impl_off_tpu_is_xla(monkeypatch):
    from shifu_tensorflow_tpu.models import embeddings
    from shifu_tensorflow_tpu.models.embeddings import _resolve_impl

    assert _resolve_impl("auto", sharded=True) == "xla"
    # on the CPU test backend auto must not pick pallas
    assert _resolve_impl("auto", sharded=False) == "xla"
    assert _resolve_impl("pallas", sharded=False) == "pallas"
    # UNMEASURED default (PALLAS_MAX_HASH_SIZE=0): auto never picks
    # pallas, even for tiny tables on any backend — the cutover exists
    # only once BENCH_PALLAS_EMBEDDING.json backs it.  (Pinned via
    # monkeypatch: a measured host may legitimately export
    # STPU_PALLAS_MAX_HASH_SIZE, which must not fail this suite.)
    monkeypatch.setattr(embeddings, "PALLAS_MAX_HASH_SIZE", 0)
    assert _resolve_impl("auto", sharded=False, hash_size=128) == "xla"
    # malformed env values keep the safe default instead of crashing import
    monkeypatch.setenv("STPU_PALLAS_MAX_HASH_SIZE", "16K")
    with pytest.warns(UserWarning, match="not an integer"):
        assert embeddings._env_cutover() == 0
    # a measured deployment re-enables the win region: cutover honored,
    # huge tables still stay on XLA's gather (cost ∝ hash_size)
    monkeypatch.setattr(embeddings, "PALLAS_MAX_HASH_SIZE", 16384)
    assert _resolve_impl("auto", sharded=False, hash_size=1 << 20) == "xla"


def test_trainer_forces_xla_impl_on_multi_device_mesh(model_config_json):
    """The pallas kernel has no GSPMD partitioning rule: any multi-device
    mesh — including pure data-parallel — must pin the XLA lookup."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.parallel.mesh import make_mesh
    from shifu_tensorflow_tpu.train.trainer import Trainer

    mc = dict(model_config_json)
    mc["train"] = dict(mc["train"])
    mc["train"]["params"] = dict(
        mc["train"]["params"], EmbeddingColumnNums=[2], EmbeddingHashSize=64,
        EmbeddingDim=4,
    )
    config = ModelConfig.from_json(mc)
    t_mesh = Trainer(config, 4, feature_columns=(0, 1, 2, 3),
                     mesh=make_mesh("data:-1"))
    assert t_mesh.model.embedding_impl == "xla"
    t_single = Trainer(config, 4, feature_columns=(0, 1, 2, 3))
    assert t_single.model.embedding_impl == "auto"


def test_trainer_with_embeddings_still_trains(model_config_json):
    """The factory threads shard_embeddings through; a trainer without a
    'model' axis must build and train the embedding-augmented model."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.train.trainer import Trainer

    mc = dict(model_config_json)
    mc["train"] = dict(mc["train"])
    mc["train"]["params"] = dict(
        mc["train"]["params"],
        EmbeddingColumnNums=[2, 3],
        EmbeddingHashSize=64,
        EmbeddingDim=4,
    )
    trainer = Trainer(ModelConfig.from_json(mc), 4,
                      feature_columns=(0, 1, 2, 3))
    rng = np.random.default_rng(1)
    batch = {
        "x": rng.normal(size=(32, 4)).astype(np.float32),
        "y": (rng.random((32, 1)) < 0.5).astype(np.float32),
        "w": np.ones((32, 1), np.float32),
    }
    loss, n = trainer.train_epoch(iter([batch]))
    assert n == 1 and np.isfinite(loss)
