"""Training CLI (`python -m shifu_tensorflow_tpu.train`) — the client
surface that replaces the reference's TensorflowClient arg/conf handling
(TensorflowClient.java:211-290)."""

import json

import pytest

from shifu_tensorflow_tpu.config import keys as K
from shifu_tensorflow_tpu.train.__main__ import (
    build_parser,
    load_conf,
    main,
    resolve_schema,
)

# subprocess fleets need cross-process CPU collectives — an environment
# capability, not framework logic; see tests/jaxcaps.py for the rationale
from jaxcaps import needs_multiprocess_collectives


def _write_model_config(tmp_path, model_config_json, epochs=2):
    mc = dict(model_config_json)
    mc["train"] = dict(mc["train"], numTrainEpochs=epochs)
    p = tmp_path / "ModelConfig.json"
    p.write_text(json.dumps(mc))
    return str(p)


def _write_column_config(tmp_path, n_feats, weight_col):
    cols = [{"columnNum": 0, "columnName": "tgt", "columnFlag": "Target"}]
    for i in range(1, n_feats + 1):
        cols.append(
            {
                "columnNum": i,
                "columnName": f"f{i}",
                "finalSelect": True,
                "columnStats": {"mean": 0.0, "stdDev": 1.0},
            }
        )
    cols.append(
        {"columnNum": weight_col, "columnName": "wgt", "columnFlag": "Weight"}
    )
    p = tmp_path / "ColumnConfig.json"
    p.write_text(json.dumps(cols))
    return str(p)


def test_conf_precedence_cli_over_globalconfig(tmp_path):
    gc = tmp_path / "global.json"
    gc.write_text(json.dumps({K.EPOCHS: 7, K.BATCH_SIZE: 64}))
    args = build_parser().parse_args(
        ["--training-data-path", "/data", "--globalconfig", str(gc),
         "--epochs", "3"]
    )
    conf = load_conf(args)
    assert conf.get_int(K.EPOCHS) == 3  # CLI wins
    assert conf.get_int(K.BATCH_SIZE) == 64  # file layer survives


def test_resolve_schema_from_column_config(tmp_path, model_config_json):
    from shifu_tensorflow_tpu.config.model_config import ModelConfig

    cc = _write_column_config(tmp_path, 4, weight_col=5)
    args = build_parser().parse_args(
        ["--training-data-path", "/d", "--column-config", cc, "--zscale"]
    )
    schema, _ = resolve_schema(args, ModelConfig.from_json(model_config_json))
    assert schema.feature_columns == (1, 2, 3, 4)
    assert schema.target_column == 0
    assert schema.weight_column == 5
    assert len(schema.means) == 4


def test_resolve_schema_flags_override(model_config_json):
    from shifu_tensorflow_tpu.config.model_config import ModelConfig

    args = build_parser().parse_args(
        ["--training-data-path", "/d", "--feature-columns", "2,3",
         "--target-column", "1", "--weight-column", "4"]
    )
    schema, _ = resolve_schema(args, ModelConfig.from_json(model_config_json))
    assert schema.feature_columns == (2, 3)
    assert schema.target_column == 1
    assert schema.weight_column == 4


def test_main_requires_data_path(capsys):
    assert main(["--feature-columns", "1"]) == 2


def test_globalconfig_can_provide_artifact_paths(
    tmp_path, capsys, psv_dataset, model_config_json
):
    """Artifact paths from a --globalconfig file must be honored, same as
    epochs/batch-size (the documented three-layer precedence)."""
    export_dir = tmp_path / "gc-export"
    gc = tmp_path / "global.json"
    gc.write_text(json.dumps({
        K.FINAL_MODEL_PATH: str(export_dir),
        K.TMP_MODEL_PATH: str(tmp_path / "gc-ckpt"),
        K.EPOCHS: 1,
    }))
    argv = [
        "--training-data-path", psv_dataset["root"],
        "--model-config", _write_model_config(tmp_path, model_config_json, 2),
        "--feature-columns", ",".join(map(str, psv_dataset["feature_cols"])),
        "--target-column", str(psv_dataset["target_col"]),
        "--weight-column", str(psv_dataset["weight_col"]),
        "--globalconfig", str(gc),
    ]
    assert main(argv) == 0
    assert (export_dir / "shifu_tpu_model.json").exists()
    assert (tmp_path / "gc-ckpt").exists()


@pytest.mark.parametrize("stream", [False, True])
def test_cli_single_worker_end_to_end(
    tmp_path, capsys, psv_dataset, model_config_json, stream
):
    mc = _write_model_config(tmp_path, model_config_json, epochs=2)
    export_dir = tmp_path / "export"
    argv = [
        "--training-data-path", psv_dataset["root"],
        "--model-config", mc,
        "--feature-columns", ",".join(map(str, psv_dataset["feature_cols"])),
        "--target-column", str(psv_dataset["target_col"]),
        "--weight-column", str(psv_dataset["weight_col"]),
        "--batch-size", "100",
        "--export-dir", str(export_dir),
        "--seed", "3",
    ]
    if stream:
        argv.append("--stream")
    assert main(argv) == 0
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["state"] == "finished"
    assert tail["epochs_run"] == 2
    assert (export_dir / "shifu_tpu_model.json").exists()
    assert (export_dir / "GenericModelConfig.json").exists()


@needs_multiprocess_collectives
def test_cli_multi_worker_end_to_end(
    tmp_path, capsys, psv_dataset, model_config_json
):
    mc = _write_model_config(tmp_path, model_config_json, epochs=2)
    export_dir = tmp_path / "export-multi"
    ckpt_dir = tmp_path / "ckpt-multi"
    argv = [
        "--training-data-path", psv_dataset["root"],
        "--model-config", mc,
        "--feature-columns", ",".join(map(str, psv_dataset["feature_cols"])),
        "--target-column", str(psv_dataset["target_col"]),
        "--weight-column", str(psv_dataset["weight_col"]),
        "--workers", "2",
        "--checkpoint-dir", str(ckpt_dir),
        "--export-dir", str(export_dir),
    ]
    assert main(argv) == 0
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["state"] == "finished"
    assert tail["epochs_run"] == 2
    assert (export_dir / "shifu_tpu_weights.npz").exists()


@needs_multiprocess_collectives
def test_cli_multi_worker_keep_best_exports_chief_snapshot(
    tmp_path, capsys, psv_dataset, model_config_json
):
    """Fleet keep-best: the chief persists its best snapshot beside the
    shared checkpoints and the export serves exactly those parameters."""
    import numpy as np

    mcj = dict(model_config_json)
    mcj["train"] = dict(mcj["train"])
    mcj["train"]["params"] = dict(mcj["train"]["params"], Optimizer="adam")
    mc = _write_model_config(tmp_path, mcj, epochs=3)
    export_dir = tmp_path / "export-best"
    ckpt_dir = tmp_path / "ckpt-best"
    argv = [
        "--training-data-path", psv_dataset["root"],
        "--model-config", mc,
        "--feature-columns", ",".join(map(str, psv_dataset["feature_cols"])),
        "--target-column", str(psv_dataset["target_col"]),
        "--weight-column", str(psv_dataset["weight_col"]),
        "--workers", "2",
        "--keep-best", "ks",
        "--checkpoint-dir", str(ckpt_dir),
        "--export-dir", str(export_dir),
    ]
    assert main(argv) == 0
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["state"] == "finished"
    best_file = ckpt_dir / "keep-best.npz"
    assert best_file.exists(), "chief never persisted its best snapshot"
    best = np.load(best_file)
    exported = np.load(export_dir / "shifu_tpu_weights.npz")
    # identical param trees: the export IS the best snapshot
    keys = [k for k in best.files if k != "__meta__"]
    assert sorted(keys) == sorted(exported.files)
    for k in keys:
        np.testing.assert_array_equal(best[k], exported[k])


def test_cli_resume_from_checkpoint(
    tmp_path, capsys, psv_dataset, model_config_json
):
    """Interrupted job resumes with the correct remaining epoch budget (the
    reference's acknowledged gap, backup.py:30)."""
    ckpt = tmp_path / "ckpt"
    base = [
        "--training-data-path", psv_dataset["root"],
        "--model-config", _write_model_config(tmp_path, model_config_json, 1),
        "--feature-columns", ",".join(map(str, psv_dataset["feature_cols"])),
        "--target-column", str(psv_dataset["target_col"]),
        "--weight-column", str(psv_dataset["weight_col"]),
        "--checkpoint-dir", str(ckpt),
    ]
    assert main(base) == 0  # trains epoch 0, checkpoints
    capsys.readouterr()
    # second run with a 3-epoch budget resumes at epoch 1
    base[3] = _write_model_config(tmp_path, model_config_json, 3)
    assert main(base) == 0
    out = capsys.readouterr().out
    assert "resuming at epoch 1" in out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["epochs_run"] == 2  # only the remaining budget


class TestDataCli:
    def test_build_status_prune_roundtrip(self, psv_dataset, tmp_path, capsys):
        import json

        from shifu_tensorflow_tpu.data.__main__ import main as data_main
        from shifu_tensorflow_tpu.data.dataset import ShardStream
        from shifu_tensorflow_tpu.data.reader import RecordSchema

        cache_dir = str(tmp_path / "cache")
        cols = ",".join(str(c) for c in psv_dataset["feature_cols"])
        rc = data_main([
            "build", "--training-data-path", psv_dataset["root"],
            "--cache-dir", cache_dir, "--feature-columns", cols,
            "--target-column", str(psv_dataset["target_col"]),
            "--weight-column", str(psv_dataset["weight_col"]),
        ])
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()
        summary = json.loads(out[-1])
        assert summary["rows"] == psv_dataset["n_rows"]

        # a training stream over the SAME schema/salt hits the prebuilt
        # entries — including a valid split (hashes were stored)
        schema = RecordSchema(
            feature_columns=tuple(psv_dataset["feature_cols"]),
            target_column=psv_dataset["target_col"],
            weight_column=psv_dataset["weight_col"],
        )
        ref = [b["x"].copy() for b in ShardStream(
            psv_dataset["paths"], schema, 128, valid_rate=0.2)]
        warm = [b["x"].copy() for b in ShardStream(
            psv_dataset["paths"], schema, 128, valid_rate=0.2,
            cache_dir=cache_dir)]
        assert len(ref) == len(warm)
        import numpy as np

        for r, w in zip(ref, warm):
            np.testing.assert_array_equal(r, w)

        rc = data_main(["status", "--cache-dir", cache_dir])
        assert rc == 0
        status = json.loads(capsys.readouterr().out.strip())
        assert status["entries"] == len(psv_dataset["paths"])
        assert status["bytes"] > 0

        rc = data_main(["prune", "--cache-dir", cache_dir,
                        "--max-bytes", "1"])
        assert rc == 0
        removed = json.loads(capsys.readouterr().out.strip())
        assert removed["removed"] == len(psv_dataset["paths"])

    def test_build_fails_nonzero_when_nothing_caches(self, psv_dataset,
                                                     tmp_path, capsys,
                                                     monkeypatch):
        import json

        from shifu_tensorflow_tpu.data import cache as shard_cache
        from shifu_tensorflow_tpu.data.__main__ import main as data_main

        monkeypatch.setattr(shard_cache, "cache_key",
                            lambda *a, **k: None)
        cols = ",".join(str(c) for c in psv_dataset["feature_cols"])
        rc = data_main([
            "build", "--training-data-path", psv_dataset["root"],
            "--cache-dir", str(tmp_path / "c"), "--feature-columns", cols,
        ])
        assert rc == 1
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["cached_files"] == 0

    def test_build_with_column_config_zscale_matches_training_keys(
            self, tmp_path, capsys):
        import gzip
        import json

        import numpy as np

        from shifu_tensorflow_tpu.config.model_config import ColumnConfig
        from shifu_tensorflow_tpu.data.__main__ import main as data_main
        from shifu_tensorflow_tpu.data.dataset import ShardStream

        rng = np.random.default_rng(0)
        p = tmp_path / "s.gz"
        with gzip.open(p, "wt") as f:
            for _ in range(300):
                x = rng.normal(size=2)
                f.write(f"1|{x[0]:.5f}|{x[1]:.5f}|1.0\n")
        cc_path = tmp_path / "ColumnConfig.json"
        cc_path.write_text(json.dumps([
            {"columnNum": 0, "columnName": "t", "finalSelect": False},
            {"columnNum": 1, "columnName": "a", "finalSelect": True,
             "columnStats": {"mean": 0.1, "stdDev": 1.2}},
            {"columnNum": 2, "columnName": "b", "finalSelect": True,
             "columnStats": {"mean": -0.3, "stdDev": 0.8}},
            {"columnNum": 3, "columnName": "w", "finalSelect": False},
        ]))
        cache_dir = str(tmp_path / "cache")
        rc = data_main([
            "build", "--training-data-path", str(p),
            "--cache-dir", cache_dir, "--column-config", str(cc_path),
            "--zscale", "--target-column", "0", "--weight-column", "3",
            "--salt", "7",
        ])
        assert rc == 0
        capsys.readouterr()
        # the training-side schema (same stats, same salt) must HIT
        cc = ColumnConfig.load(str(cc_path))
        from shifu_tensorflow_tpu.data.reader import RecordSchema

        features = tuple(cc.selected_column_nums)
        means, stds = cc.zscale_stats(features)
        schema = RecordSchema(feature_columns=features, target_column=0,
                              weight_column=3).with_zscale(means, stds)
        from shifu_tensorflow_tpu.data import cache as shard_cache

        assert shard_cache.lookup(cache_dir, str(p), schema, 7) is not None
        warm = [b["x"].copy() for b in ShardStream(
            [str(p)], schema, 64, valid_rate=0.2, salt=7,
            cache_dir=cache_dir)]
        assert warm


def test_stream_and_device_resident_conflict(tmp_path):
    """Explicitly requested but silently dropped modes are bugs: the pair
    is rejected up front."""
    import pytest

    from shifu_tensorflow_tpu.train.__main__ import main

    with pytest.raises(SystemExit, match="conflict"):
        main([
            "--training-data-path", str(tmp_path),
            "--feature-columns", "1,2", "--stream", "--device-resident",
        ])


def test_device_resident_rejected_for_multi_worker_and_sagn(tmp_path):
    import gzip

    import pytest

    from shifu_tensorflow_tpu.train.__main__ import main

    with gzip.open(tmp_path / "part-0.gz", "wt") as f:
        for i in range(50):
            f.write(f"{i % 2}|0.5|1.5|1.0\n")
    base = [
        "--training-data-path", str(tmp_path),
        "--feature-columns", "1,2", "--device-resident",
    ]
    with pytest.raises(SystemExit, match="single-process"):
        main(base + ["--workers", "2"])

    import json
    mc = tmp_path / "mc.json"
    mc.write_text(json.dumps({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.1,
        "Algorithm": "sagn"}}}))
    with pytest.raises(SystemExit, match="sagn"):
        main(base + ["--model-config", str(mc)])


def test_multi_worker_preflight_rejects_bad_accum_configs(tmp_path):
    """Invalid scan/accum combinations must be ONE clean error before
    launch — not an N-worker crash cascade after cluster bring-up."""
    import gzip
    import json

    import pytest

    from shifu_tensorflow_tpu.train.__main__ import main

    with gzip.open(tmp_path / "part-0.gz", "wt") as f:
        for i in range(50):
            f.write(f"{i % 2}|0.5|1.5|1.0\n")
    base = [
        "--training-data-path", str(tmp_path),
        "--feature-columns", "1,2", "--workers", "2",
    ]
    with pytest.raises(SystemExit, match="mutually exclusive"):
        main(base + ["--scan-steps", "4", "--accum-steps", "4"])

    mc = tmp_path / "mc.json"
    mc.write_text(json.dumps({"train": {"params": {
        "NumHiddenLayers": 1, "NumHiddenNodes": [4],
        "ActivationFunc": ["relu"], "LearningRate": 0.1,
        "Algorithm": "sagn"}}}))
    with pytest.raises(SystemExit, match="sagn"):
        main(base + ["--model-config", str(mc), "--accum-steps", "4"])

@needs_multiprocess_collectives
def test_cli_multi_worker_fleet_early_stop(
    tmp_path, capsys, psv_dataset, model_config_json
):
    """Fleet-coordinated early stopping: the coordinator evaluates quorum
    epoch aggregates and every worker stops after the SAME epoch, well
    short of the budget."""
    # adam, not the fixture's default adadelta: per-shard KS must actually
    # clear the target within the budget for the stop to have a trigger
    mcj = dict(model_config_json)
    mcj["train"] = dict(mcj["train"])
    mcj["train"]["params"] = dict(mcj["train"]["params"], Optimizer="adam")
    mc = _write_model_config(tmp_path, mcj, epochs=30)
    argv = [
        "--training-data-path", psv_dataset["root"],
        "--model-config", mc,
        "--feature-columns", ",".join(map(str, psv_dataset["feature_cols"])),
        "--target-column", str(psv_dataset["target_col"]),
        "--weight-column", str(psv_dataset["weight_col"]),
        "--workers", "2",
        "--early-stop-ks", "0.2",
    ]
    assert main(argv) == 0
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["state"] == "finished"
    assert tail["epochs_run"] < 30, tail


def test_single_process_preflight_rejects_unfireable_configs(tmp_path):
    """Configs that could only fail late (after dataset load) or silently
    (early stop that can never fire) must be one clean error up front."""
    import gzip

    import pytest

    from shifu_tensorflow_tpu.train.__main__ import main

    with gzip.open(tmp_path / "part-0.gz", "wt") as f:
        for i in range(50):
            f.write(f"{i % 2}|0.5|1.5|1.0\n")
    base = ["--training-data-path", str(tmp_path), "--feature-columns", "1,2"]
    with pytest.raises(SystemExit, match="accum"):
        main(base + ["--device-resident", "--accum-steps", "2"])
    with pytest.raises(SystemExit, match="validation"):
        main(base + ["--early-stop-ks", "0.45", "--valid-rate", "0"])
    # fleet keep-best needs the shared checkpoint dir the chief persists
    # the snapshot into — without it the key would be a silent no-op
    with pytest.raises(SystemExit, match="checkpoint-dir"):
        main(base + ["--workers", "2", "--keep-best", "ks"])
    # and, like early stop, it needs validation data to rank epochs
    with pytest.raises(SystemExit, match="validation"):
        main(base + ["--workers", "2", "--keep-best", "ks",
                     "--valid-rate", "0"])
