"""Genuinely-multi-address SPMD: two network namespaces, distinct IPs,
the real ssh-launcher path (r04 verdict item 7).

test_ssh_launcher.py runs localhost-as-remote — every worker still shares
the submitter's network identity, so the loopback-topology guard
(coordinator.py _cluster_info) and the WorkerConfig host plumbing had only
ever been exercised against registration *data*.  Here each worker runs in
its own network namespace with its own veth/IP on a bridge: worker-to-
coordinator traffic and the chief's jax.distributed coordination service
both cross real non-loopback links between distinct network identities —
the closest this single machine gets to two hosts.

Topology (root-only; skipped without ip-netns capability):

    root ns:  br-stpu 10.223.1.1/24
    stpu-nsb: eth0 10.223.1.2/24  (worker 0 — SPMD chief)
    stpu-nsc: eth0 10.223.1.3/24  (worker 1)

The fake ssh maps the host argument to ``ip netns exec`` — exactly the
launcher's pluggable exec-wrapper seam (submitter.py ssh_command).
"""

import json
import os
import stat
import subprocess

import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.coordinator.coordinator import JobSpec, JobState
from shifu_tensorflow_tpu.coordinator.submitter import JobSubmitter
from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.data.splitter import split_training_data

# subprocess fleets need cross-process CPU collectives — an environment
# capability, not framework logic; see tests/jaxcaps.py for the rationale
from jaxcaps import needs_multiprocess_collectives

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO_ROOT,
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}

BRIDGE = "br-stpu"
COORD_IP = "10.223.1.1"
NS = {"10.223.1.2": "stpu-nsb", "10.223.1.3": "stpu-nsc"}


def _ip(*args) -> subprocess.CompletedProcess:
    return subprocess.run(["ip", *args], capture_output=True, text=True)


def _netns_capable() -> bool:
    if os.geteuid() != 0:
        return False
    probe = _ip("netns", "add", "stpu-capability-probe")
    if probe.returncode != 0:
        return False
    _ip("netns", "del", "stpu-capability-probe")
    return True


pytestmark = pytest.mark.skipif(
    not _netns_capable(), reason="needs root + ip-netns capability"
)


@pytest.fixture
def netns_pair():
    """Two namespaces bridged to the root namespace; yields nothing, the
    module constants carry the addresses.  Teardown removes everything
    even when the test fails mid-run."""

    def teardown():
        for ns in NS.values():
            _ip("netns", "del", ns)
        _ip("link", "del", BRIDGE)

    teardown()  # sweep a previous crashed run's debris
    try:
        assert _ip("link", "add", BRIDGE, "type", "bridge").returncode == 0
        _ip("addr", "add", f"{COORD_IP}/24", "dev", BRIDGE)
        _ip("link", "set", BRIDGE, "up")
        for addr, ns in NS.items():
            veth = f"v-{ns[-3:]}-{os.getpid() % 1000}"[:15]
            assert _ip("netns", "add", ns).returncode == 0
            assert _ip("link", "add", veth, "type", "veth", "peer", "name",
                       "eth0", "netns", ns).returncode == 0
            _ip("link", "set", veth, "master", BRIDGE)
            _ip("link", "set", veth, "up")
            subprocess.run(["ip", "netns", "exec", ns, "ip", "addr", "add",
                            f"{addr}/24", "dev", "eth0"], check=True)
            subprocess.run(["ip", "netns", "exec", ns, "ip", "link", "set",
                            "eth0", "up"], check=True)
            subprocess.run(["ip", "netns", "exec", ns, "ip", "link", "set",
                            "lo", "up"], check=True)
        yield
    finally:
        teardown()


# fake ssh with REAL network isolation: the host argument selects the
# namespace the "remote" command runs in (loopback = the root namespace,
# for the guard test's deliberately-misconfigured chief)
NETNS_SSH = """#!/bin/sh
while [ "$1" = "-o" ]; do shift 2; done
host="$1"; shift
case "$host" in
%s
  127.0.0.1) exec /bin/sh -c "$*";;
  *) echo "netns-ssh: unknown host $host" >&2; exit 255;;
esac
exec ip netns exec "$ns" /bin/sh -c "$*"
""" % "\n".join(f'  {addr}) ns={ns};;' for addr, ns in NS.items())


@pytest.fixture
def netns_ssh(tmp_path):
    path = tmp_path / "netns-ssh"
    path.write_text(NETNS_SSH)
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return str(path)


def _mc(epochs=2):
    return ModelConfig.from_json(
        {"train": {"numTrainEpochs": epochs, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05, "Optimizer": "adam"}}}
    )


def _spec_and_cfg(psv_dataset, tmp_path, epochs=2):
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    shards = split_training_data(psv_dataset["root"], 2)
    mc = _mc(epochs)

    def make_cfg(worker_id: str, addr) -> WorkerConfig:
        return WorkerConfig(
            worker_id=worker_id, coordinator_host=addr[0],
            coordinator_port=addr[1], model_config=mc, schema=schema,
            batch_size=32, checkpoint_dir=str(tmp_path / "ckpt"),
            heartbeat_interval_s=0.2, spmd=True,
        )

    spec = JobSpec(n_workers=2, shards=shards, spmd=True, epochs=epochs,
                   registration_timeout_s=120.0)
    return spec, make_cfg


@needs_multiprocess_collectives
def test_spmd_across_network_namespaces(psv_dataset, tmp_path, netns_ssh,
                                        netns_pair):
    """Two workers with DISTINCT network identities train one model: the
    chief's jax.distributed service binds in one namespace and the peer
    dials it across the bridge; the coordinator is reached at a third
    address.  No loopback shortcut exists on any leg."""
    spec, make_cfg = _spec_and_cfg(psv_dataset, tmp_path)
    submitter = JobSubmitter(
        spec, make_cfg, launcher="ssh",
        hosts=list(NS),  # 10.223.1.2 (chief), 10.223.1.3
        ssh_command=[netns_ssh],
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        bind_host="0.0.0.0",
        advertise_host=COORD_IP,
    )
    result = submitter.run(timeout_s=300.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    recs = {r.worker_index: r for r in submitter.coordinator.workers.values()}
    # every worker registered ITS OWN namespace address — the plumbing the
    # localhost-as-remote test could not distinguish from defaults
    assert recs[0].host == "10.223.1.2"
    assert recs[1].host == "10.223.1.3"
    assert len(result.epoch_summaries) == 2


def test_loopback_chief_guard_fires_against_real_network(
    psv_dataset, tmp_path, netns_ssh, netns_pair
):
    """The _cluster_info loopback guard, against reality: the hosts list
    itself assigns the chief to 127.0.0.1 (so the launcher's own
    loopback-healing cannot fix it) while the peer runs in a namespace and
    registers its routable address.  Without the guard the peer would dial
    ITS OWN loopback for the jax coordination service and hang to the
    barrier timeout; with it the job fails fast with an actionable
    reason."""
    spec, make_cfg = _spec_and_cfg(psv_dataset, tmp_path)

    submitter = JobSubmitter(
        spec, make_cfg, launcher="ssh",
        hosts=["127.0.0.1", "10.223.1.3"],  # chief deliberately loopback
        ssh_command=[netns_ssh],
        worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"),
        bind_host="0.0.0.0",
        advertise_host=COORD_IP,
    )
    result = submitter.run(timeout_s=180.0)
    assert result.state == JobState.FAILED
    assert "loopback" in (result.failure_reason or "")


@needs_multiprocess_collectives
def test_netns_worker_logs_carry_distinct_identities(
    psv_dataset, tmp_path, netns_ssh, netns_pair
):
    """The per-worker log files (container-log parity) must show each
    worker launched through its own namespace — a regression here would
    mean the exec wrapper silently collapsed back to one host."""
    spec, make_cfg = _spec_and_cfg(psv_dataset, tmp_path)
    marker = tmp_path / "host-markers"
    marker.mkdir()
    # wrap the wrapper: record which namespace each launch entered
    logging_ssh = tmp_path / "logging-netns-ssh"
    logging_ssh.write_text(
        "#!/bin/sh\n"
        f'echo "$1" >> {marker}/hosts.log\n'
        + NETNS_SSH.split("\n", 1)[1]
    )
    logging_ssh.chmod(logging_ssh.stat().st_mode | stat.S_IEXEC)
    submitter = JobSubmitter(
        spec, make_cfg, launcher="ssh", hosts=list(NS),
        ssh_command=[str(logging_ssh)], worker_env=WORKER_ENV,
        log_dir=str(tmp_path / "logs"), bind_host="0.0.0.0",
        advertise_host=COORD_IP,
    )
    result = submitter.run(timeout_s=300.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    launched = set((marker / "hosts.log").read_text().split())
    assert launched == set(NS)
