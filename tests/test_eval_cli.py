"""Scoring CLI (`python -m shifu_tensorflow_tpu.export`) and model-family
coverage through the training CLI — the end-to-end surface a reference user
would exercise."""

import json

import numpy as np
import pytest

from shifu_tensorflow_tpu.export.__main__ import main as eval_main
from shifu_tensorflow_tpu.train.__main__ import main as train_main

# subprocess fleets need cross-process CPU collectives — an environment
# capability, not framework logic; see tests/jaxcaps.py for the rationale
from jaxcaps import needs_multiprocess_collectives


def _write_model_config(tmp_path, model_config_json, **params):
    mc = dict(model_config_json)
    mc["train"] = dict(mc["train"], numTrainEpochs=2)
    mc["train"]["params"] = dict(mc["train"]["params"], **params)
    p = tmp_path / "ModelConfig.json"
    p.write_text(json.dumps(mc))
    return str(p)


def _train(tmp_path, psv_dataset, mc_path, export_name="export", extra=()):
    export_dir = tmp_path / export_name
    argv = [
        "--training-data-path", psv_dataset["root"],
        "--model-config", mc_path,
        "--feature-columns", ",".join(map(str, psv_dataset["feature_cols"])),
        "--target-column", str(psv_dataset["target_col"]),
        "--weight-column", str(psv_dataset["weight_col"]),
        "--export-dir", str(export_dir),
        *extra,
    ]
    assert train_main(argv) == 0
    return export_dir


def test_score_cli_with_metrics(tmp_path, capsys, psv_dataset,
                                model_config_json):
    export_dir = _train(
        tmp_path, psv_dataset,
        _write_model_config(tmp_path, model_config_json),
    )
    capsys.readouterr()
    scores_file = tmp_path / "scores.txt"
    rc = eval_main([
        "--model-dir", str(export_dir),
        "--data-path", psv_dataset["root"],
        "--feature-columns", ",".join(map(str, psv_dataset["feature_cols"])),
        "--target-column", str(psv_dataset["target_col"]),
        "--weight-column", str(psv_dataset["weight_col"]),
        "--output", str(scores_file),
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["rows"] == psv_dataset["n_rows"]
    assert 0.0 <= summary["ks"] <= 1.0 and 0.0 <= summary["auc"] <= 1.0
    vals = np.loadtxt(scores_file)
    assert vals.shape[0] == psv_dataset["n_rows"]
    assert (vals >= 0).all() and (vals <= 1).all()


def test_score_cli_cpp_backend_matches_native(tmp_path, capsys, psv_dataset,
                                              model_config_json):
    from shifu_tensorflow_tpu.export import native_scorer

    if not native_scorer.available():
        pytest.skip("native scorer library unavailable")
    export_dir = _train(
        tmp_path, psv_dataset,
        _write_model_config(tmp_path, model_config_json), "exp-cpp",
    )
    capsys.readouterr()
    outs = {}
    for backend in ("native", "cpp"):
        f = tmp_path / f"scores-{backend}.txt"
        assert eval_main([
            "--model-dir", str(export_dir),
            "--data-path", psv_dataset["root"],
            "--feature-columns",
            ",".join(map(str, psv_dataset["feature_cols"])),
            "--backend", backend,
            "--output", str(f),
        ]) == 0
        outs[backend] = np.loadtxt(f)
    np.testing.assert_allclose(outs["cpp"], outs["native"],
                               rtol=2e-5, atol=2e-6)


def test_score_cli_feature_count_mismatch(tmp_path, capsys, psv_dataset,
                                          model_config_json):
    export_dir = _train(
        tmp_path, psv_dataset,
        _write_model_config(tmp_path, model_config_json), "exp-mm",
    )
    rc = eval_main([
        "--model-dir", str(export_dir),
        "--data-path", psv_dataset["root"],
        "--feature-columns", "1,2",
    ])
    assert rc == 2


@needs_multiprocess_collectives
def test_multi_worker_embedding_checkpoint_matches_export(
    tmp_path, capsys, psv_dataset, model_config_json
):
    """Workers and the chief-export trainer must build the same param tree:
    feature_columns resolve wide/embedding positions, so a worker trained
    without them would checkpoint a structurally different model than the
    export path restores."""
    mc = _write_model_config(
        tmp_path, model_config_json,
        EmbeddingColumnNums=[psv_dataset["feature_cols"][1]],
        EmbeddingHashSize=32, EmbeddingDim=4,
    )
    export_dir = _train(
        tmp_path, psv_dataset, mc, "exp-mw-emb",
        extra=["--workers", "2",
               "--checkpoint-dir", str(tmp_path / "mw-emb-ckpt")],
    )
    tail = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert tail["state"] == "finished"
    assert (export_dir / "shifu_tpu_weights.npz").exists()
    # the exported weights include the embedding table
    weights = np.load(export_dir / "shifu_tpu_weights.npz")
    assert any("hashed_columns" in k for k in weights.files)


@pytest.mark.parametrize(
    "params",
    [
        {"ModelType": "wide_deep", "WideColumnNums": [1, 2],
         "CrossHashSize": 64},
        {"ModelType": "multi_task", "NumTasks": 3},
        {"Algorithm": "sagn", "UpdateWindow": 3},
    ],
    ids=["wide_deep", "multi_task", "sagn"],
)
def test_train_cli_model_families(tmp_path, capsys, psv_dataset,
                                  model_config_json, params):
    """Every model family / algorithm trains and exports through the same
    CLI the plain DNN uses."""
    mc = _write_model_config(tmp_path, model_config_json, **params)
    export_dir = _train(tmp_path, psv_dataset, mc,
                        f"exp-{params.get('ModelType', 'sagn')}")
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["state"] == "finished" and tail["epochs_run"] == 2
    assert (export_dir / "shifu_tpu_weights.npz").exists()
