"""Native block parser (cpp/stpu_data.cc) — parity with the Python path.

The contract under test: for any input buffer, the native parse + hash
routing must produce byte-identical train/valid membership and float-equal
parsed values to the pure-Python fallback (reader.parse_block +
reader.split_train_valid), because a worker may run either path depending
on toolchain availability and both must resume into the same split.
"""

import zlib

import numpy as np
import pytest

from shifu_tensorflow_tpu.data import native
from shifu_tensorflow_tpu.data.reader import (
    ParsedBlock,
    RecordSchema,
    parse_block,
    parse_buffer_split,
    split_train_valid,
    wanted_columns,
)

needs_native = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)

SCHEMA = RecordSchema(
    feature_columns=(1, 2, 3), target_column=0, weight_column=4
)


def _python_reference(buf: bytes, schema, valid_rate, salt=0):
    lines = [c + b"\n" for c in buf.split(b"\n")]
    lines[-1] = lines[-1][:-1]
    if not lines[-1]:
        lines.pop()
    tr, va = split_train_valid(lines, valid_rate, salt)
    return parse_block(tr, schema), parse_block(va, schema)


def _assert_blocks_equal(a: ParsedBlock, b: ParsedBlock):
    np.testing.assert_array_equal(a.features, b.features)
    np.testing.assert_array_equal(a.targets, b.targets)
    np.testing.assert_array_equal(a.weights, b.weights)


@needs_native
def test_native_available():
    assert native.available()


@needs_native
@pytest.mark.parametrize("valid_rate", [0.0, 0.3, 1.0])
def test_parity_clean_input(valid_rate):
    rng = np.random.default_rng(7)
    rows = []
    for _ in range(500):
        vals = rng.normal(size=5)
        rows.append("|".join(f"{v:.6f}" for v in vals))
    buf = ("\n".join(rows) + "\n").encode()
    tr_n, va_n = parse_buffer_split(buf, SCHEMA, valid_rate, salt=3)
    tr_p, va_p = _python_reference(buf, SCHEMA, valid_rate, salt=3)
    _assert_blocks_equal(tr_n, tr_p)
    _assert_blocks_equal(va_n, va_p)
    assert len(tr_n) + len(va_n) == 500


@needs_native
def test_parity_adversarial_rows():
    buf = b"".join(
        [
            b"1|2|3|4|5\n",  # ok
            b"\n",  # blank -> dropped
            b"1|2|3\n",  # too few columns -> dropped
            b"1|x|3|4|5\n",  # non-numeric wanted cell -> dropped
            b"0|-1.5|2e3|.5|-2\n",  # negative weight -> clamped to 1.0
            b"1| 2 |3|4|5\r\n",  # spaces + CRLF -> ok
            b"1|2|3|4|5|6|7\n",  # extra columns -> ok
            b"nan|inf|-inf|1|1\n",  # nan/inf spellings float() accepts
            b"1|+2|3.|4|5",  # plus sign, trailing dot, no trailing newline
        ]
    )
    for rate in (0.0, 0.5):
        tr_n, va_n = parse_buffer_split(buf, SCHEMA, rate, salt=1)
        tr_p, va_p = _python_reference(buf, SCHEMA, rate, salt=1)
        _assert_blocks_equal(tr_n, tr_p)
        _assert_blocks_equal(va_n, va_p)
    # sanity on the content itself (rate 0 -> all rows in train): the ok,
    # clamped-weight, CRLF, extra-column, nan/inf, and no-newline rows
    tr, _ = parse_buffer_split(buf, SCHEMA, 0.0)
    assert len(tr) == 6
    assert tr.weights.min() >= 0.0  # clamp applied


@needs_native
def test_parity_routing_hash_is_crc32_of_line_bytes():
    lines = [b"0|1|2|3|4\n", b"1|5|6|7|8\n"]
    buf = b"".join(lines)
    arr, hashes = native.parse_buffer(
        buf, wanted_columns(SCHEMA), "|", salt=9, want_hashes=True
    )
    assert arr.shape == (2, 5)
    expect = [zlib.crc32(l, 9) & 0xFFFFFFFF for l in lines]
    assert list(hashes) == expect


@needs_native
def test_parity_zscale_and_no_weight_column():
    schema = RecordSchema(
        feature_columns=(1, 2), target_column=0
    ).with_zscale([1.0, -2.0], [2.0, 0.0])  # zero std -> treated as 1.0
    buf = b"1|3|4\n0|5|6\n"
    tr_n, _ = parse_buffer_split(buf, schema, 0.0)
    tr_p = parse_block([b"1|3|4\n", b"0|5|6\n"], schema)
    _assert_blocks_equal(tr_n, tr_p)
    np.testing.assert_allclose(tr_n.features[0], [(3 - 1) / 2, 4 + 2])
    assert tr_n.weights.flatten().tolist() == [1.0, 1.0]


@needs_native
def test_multithreaded_parse_matches_serial():
    rng = np.random.default_rng(11)
    rows = []
    for i in range(20000):
        vals = rng.normal(size=5)
        row = "|".join(f"{v:.4f}" for v in vals)
        if i % 997 == 0:
            row = "bad|row"  # scattered bad rows exercise hole compaction
        rows.append(row)
    buf = ("\n".join(rows) + "\n").encode()
    cols = wanted_columns(SCHEMA)
    serial = native.parse_buffer(buf, cols, "|", salt=5, n_threads=1)
    threaded = native.parse_buffer(buf, cols, "|", salt=5, n_threads=8)
    assert serial is not None and threaded is not None
    np.testing.assert_array_equal(serial[0], threaded[0])
    np.testing.assert_array_equal(serial[1], threaded[1])


@needs_native
def test_duplicate_wanted_columns_fall_back():
    schema = RecordSchema(feature_columns=(1, 1), target_column=0)
    # native declines duplicates (returns None) and the wrapper falls back —
    # parse_buffer_split must still produce the right duplicated values
    assert native.parse_buffer(b"1|2\n", wanted_columns(schema), "|") is None
    tr, _ = parse_buffer_split(b"1|2\n", schema, 0.0)
    assert tr.features.tolist() == [[2.0, 2.0]]


def test_parse_buffer_split_python_fallback(monkeypatch):
    """With the native library masked off the same API must work."""
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_checked", True)
    buf = b"1|2|3|4|5\n0|6|7|8|-1\n"
    tr, va = parse_buffer_split(buf, SCHEMA, 0.0)
    assert len(tr) == 2 and len(va) == 0
    assert tr.weights.flatten().tolist() == [5.0, 1.0]


@needs_native
@pytest.mark.parametrize("valid_rate", [0.0, 0.5])
def test_grammar_divergence_cells_agree_across_paths(monkeypatch, valid_rate):
    """Cells where C's strtof-family and Python's float() historically
    disagree: hex floats ('0x1p3'), underscore literals ('1_0'), 'nan(tag)',
    multiple trailing CRs, unicode digits.  Both parsers must keep/drop the
    SAME rows with the SAME values — the shared grammar is the contract."""
    buf = b"".join(
        [
            b"0x1p3|2|3|4|5\n",  # hex float: rejected by both
            b"1_0|2|3|4|5\n",  # underscore literal: rejected by both
            b"nan(tag)|2|3|4|5\n",  # nan with payload: rejected by both
            b"1|2|3|4|5\r\r\n",  # multiple trailing CRs: kept by both
            "１|2|3|4|5\n".encode(),  # unicode digit: rejected by both
            b"-inf|INFINITY|nan|1|1\n",  # spellings accepted by both
            b"+.5|1.|2e3|4|5\n",  # sign/edge decimals accepted by both
        ]
    )
    tr_native, va_native = parse_buffer_split(buf, SCHEMA, valid_rate, salt=2)

    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_checked", True)
    tr_py, va_py = parse_buffer_split(buf, SCHEMA, valid_rate, salt=2)

    _assert_blocks_equal(tr_native, tr_py)
    _assert_blocks_equal(va_native, va_py)
    assert len(tr_native) + len(va_native) == 3


@needs_native
def test_out_of_range_cells_match_python_float_semantics(monkeypatch):
    """float() keeps out-of-range magnitudes (overflow → ±inf, underflow →
    0.0 after the float32 cast); the native parser must keep the same rows
    with the same values, including beyond double range."""
    buf = b"".join(
        [
            b"1|4e38|-4e38|1e-50|5\n",  # float32-range overflow/underflow
            b"1|1e400|-1e400|1e-400|5\n",  # double-range overflow/underflow
            (b"1|" + b"9" * 400 + b".0|2|3|5\n"),  # huge, no exponent
            (b"1|0." + b"0" * 330 + b"1|2|3|5\n"),  # tiny, no exponent
        ]
    )
    tr_n, _ = parse_buffer_split(buf, SCHEMA, 0.0)

    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_checked", True)
    tr_p, _ = parse_buffer_split(buf, SCHEMA, 0.0)

    _assert_blocks_equal(tr_n, tr_p)
    assert len(tr_n) == 4
    assert tr_n.features[0].tolist() == [float("inf"), float("-inf"), 0.0]
    assert tr_n.features[1].tolist() == [float("inf"), float("-inf"), 0.0]


def test_schema_rejects_negative_columns():
    """Negative indices would be an out-of-bounds write in the native parser
    and implicit from-the-end indexing in Python — both paths now reject at
    schema construction."""
    with pytest.raises(ValueError):
        RecordSchema(feature_columns=(1, -2), target_column=0)
    with pytest.raises(ValueError):
        RecordSchema(feature_columns=(1,), target_column=-1)
    with pytest.raises(ValueError):
        RecordSchema(feature_columns=(1,), target_column=0, weight_column=-3)


@needs_native
def test_multibyte_delimiter_falls_back_to_python():
    # '¦' is one str char but two UTF-8 bytes: native must decline rather
    # than split on the lead byte
    schema = RecordSchema(feature_columns=(1,), target_column=0, delimiter="¦")
    assert native.parse_buffer(b"1\xc2\xa62\n", (1, 0), "¦") is None
    tr, _ = parse_buffer_split("1¦2\n".encode(), schema, 0.0)
    assert tr.features.tolist() == [[2.0]]
    assert tr.targets.tolist() == [[1.0]]
