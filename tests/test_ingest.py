"""Staged ingest pipeline tests: deterministic parallel ordering, the
seeded shuffle stage, chaos-drill retry/resume convergence, the
close()/no-thread-leak contract, the pipelined device put, and the
autotuner policy (ISSUE 6 / ROADMAP item 2)."""

import gzip
import threading
import time

import numpy as np
import pytest

from shifu_tensorflow_tpu.data.autotune import (
    IngestAutotuner,
    resolve_ingest_knobs,
)
from shifu_tensorflow_tpu.data.dataset import (
    ShardStream,
    close_stream,
    fixed_step_batches,
    prefetch_to_device,
)
from shifu_tensorflow_tpu.data.pipeline import IngestKnobs, StageStats
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.obs import trace as obs_trace
from shifu_tensorflow_tpu.utils import faults
from shifu_tensorflow_tpu.utils import retry as retry_util

#: pipeline thread-name prefixes the leak asserts watch for
_PIPELINE_THREADS = ("stpu-ingest-read", "stpu-ingest-decode",
                     "stpu-infeed-put")


def _schema(ds):
    return RecordSchema(
        feature_columns=tuple(ds["feature_cols"]),
        target_column=ds["target_col"],
        weight_column=ds["weight_col"],
    )


def _batch_seq(stream):
    """Materialize the full (x, y, w) batch sequence — order-sensitive."""
    return [(b["x"].copy(), b["y"].copy(), b["w"].copy()) for b in stream]


def _assert_same_seq(a, b):
    assert len(a) == len(b)
    for (ax, ay, aw), (bx, by, bw) in zip(a, b):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)
        np.testing.assert_array_equal(aw, bw)


def _pipeline_threads():
    return [t.name for t in threading.enumerate()
            if any(t.name.startswith(p) for p in _PIPELINE_THREADS)]


def _assert_no_pipeline_threads(deadline_s: float = 5.0):
    """Producer threads must be joined; allow a short grace for daemon
    teardown races on slow CI hosts."""
    end = time.time() + deadline_s
    while time.time() < end:
        if not _pipeline_threads():
            return
        time.sleep(0.05)
    raise AssertionError(f"leaked pipeline threads: {_pipeline_threads()}")


# ---- deterministic ordering across stage widths ----------------------------

@pytest.mark.parametrize("n_readers,decode_workers",
                         [(2, 1), (3, 2), (4, 2)])
def test_epoch_order_bit_identical_across_widths(psv_dataset, n_readers,
                                                 decode_workers):
    """The sequencer contract: reader/decode width must not change the
    emitted batch sequence AT ALL — order included (the old ShardStream
    only preserved the multiset)."""
    schema = _schema(psv_dataset)
    base = _batch_seq(ShardStream(psv_dataset["paths"], schema, 32,
                                  valid_rate=0.2, n_readers=1))
    got = _batch_seq(ShardStream(
        psv_dataset["paths"], schema, 32, valid_rate=0.2,
        n_readers=n_readers, decode_workers=decode_workers,
        block_bytes=512, queue_depth=2,
    ))
    _assert_same_seq(base, got)
    _assert_no_pipeline_threads()


def test_seeded_shuffle_reproducible_across_widths(psv_dataset):
    """Same seed + same shard list -> bit-identical epoch order at any
    reader count; a different seed reorders."""
    schema = _schema(psv_dataset)

    def seq(n_readers, seed, decode_workers=1):
        return _batch_seq(ShardStream(
            psv_dataset["paths"], schema, 32, valid_rate=0.2,
            n_readers=n_readers, decode_workers=decode_workers,
            shuffle_rows=300, shuffle_seed=seed, block_bytes=512,
        ))

    base = seq(1, seed=11)
    for nr, dw in ((2, 1), (4, 2)):
        _assert_same_seq(base, seq(nr, seed=11, decode_workers=dw))
    other = seq(1, seed=12)
    assert len(other) == len(base)
    assert any((a[0] != b[0]).any() for a, b in zip(base, other))
    # shuffling must not change the row multiset, only the order
    def multiset(seq_):
        rows = np.concatenate([
            np.concatenate([x, y, w], axis=1)[w[:, 0] > 0]
            for x, y, w in seq_
        ])
        return rows[np.lexsort(rows.T[::-1])]

    np.testing.assert_array_equal(multiset(base), multiset(other))


# ---- chaos drill: retry/resume convergence ---------------------------------

def test_chaos_faults_on_two_readers_converge_bit_identically(psv_dataset):
    """STPU_FAULT_PLAN-style faults on two of four concurrent readers:
    the per-reader retry/resume path (PR-1 envelope + chunk-offset skip)
    must converge to the no-fault epoch bit-identically — shuffle on, so
    the whole staged path is under test."""
    schema = _schema(psv_dataset)

    def seq(**kw):
        return _batch_seq(ShardStream(
            psv_dataset["paths"], schema, 32, valid_rate=0.2,
            shuffle_rows=250, shuffle_seed=5, block_bytes=512, **kw))

    base = seq(n_readers=1)
    # shards 1 and 3 belong to readers 1 and 3 of 4 (round-robin
    # assignment); rate-based terms with a pinned seed fire
    # deterministically, and each retry re-rolls
    plan = faults.FaultPlan.parse(
        "ingest.read.s1:reset@0.6,ingest.read.s3:timeout@0.6", seed=3)
    faults.set_plan(plan)
    retry_util.reset_counters()
    try:
        got = seq(n_readers=4, decode_workers=2,
                  retry_policy=retry_util.RetryPolicy(
                      base_delay_s=0.001, max_attempts=10, seed=1))
        fired = plan.fired()
    finally:
        faults.set_plan(None)
    assert sum(fired.values()) >= 2, fired  # the drill actually injected
    c = retry_util.counters()
    assert c.get("ingest.read.recovered", 0) >= 1, c
    _assert_same_seq(base, got)
    _assert_no_pipeline_threads()


def test_chaos_control_arm_retries_off_fails(psv_dataset):
    """With retries disabled the same faults are terminal — proves the
    retry layer (not luck) absorbs them."""
    schema = _schema(psv_dataset)
    faults.set_plan(faults.FaultPlan.parse("ingest.read:reset@1.0", seed=0))
    try:
        with pytest.raises(ConnectionResetError):
            list(ShardStream(
                psv_dataset["paths"], schema, 32, n_readers=2,
                retry_policy=retry_util.NO_RETRY,
            ))
    finally:
        faults.set_plan(None)
    _assert_no_pipeline_threads()


# ---- lifecycle: the close() contract ---------------------------------------

def test_no_thread_leak_normal_completion(psv_dataset):
    schema = _schema(psv_dataset)
    list(ShardStream(psv_dataset["paths"], schema, 32, n_readers=4,
                     decode_workers=2))
    _assert_no_pipeline_threads()


def test_close_releases_abandoned_iterator(psv_dataset):
    schema = _schema(psv_dataset)
    stream = ShardStream(psv_dataset["paths"], schema, 8, n_readers=3,
                         queue_depth=1, block_bytes=256)
    it = iter(stream)
    next(it)  # producers running, queues filling
    stream.close()
    _assert_no_pipeline_threads()


def test_context_manager_closes(psv_dataset):
    schema = _schema(psv_dataset)
    with ShardStream(psv_dataset["paths"], schema, 8, n_readers=2) as s:
        next(iter(s))
    _assert_no_pipeline_threads()


def test_fixed_step_batches_closes_underlying_stream(psv_dataset):
    """The SPMD epoch adapter caps the step count and returns early —
    exactly the abandonment that used to orphan producer threads."""
    schema = _schema(psv_dataset)
    stream = ShardStream(psv_dataset["paths"], schema, 16, n_readers=4,
                         queue_depth=1, block_bytes=256)
    got = list(fixed_step_batches(stream, 16, 3,
                                  schema.num_features))
    assert len(got) == 3
    _assert_no_pipeline_threads()


def test_trainer_epoch_paths_close_stream(psv_dataset):
    """train_epoch/evaluate close their source on success AND on a
    mid-epoch exception (the health-guard rollback shape)."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.train.trainer import Trainer

    mc = ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05}}}
    )
    schema = _schema(psv_dataset)
    trainer = Trainer(mc, schema.num_features)

    stream = ShardStream(psv_dataset["paths"], schema, 64, n_readers=2)
    trainer.train_epoch(stream)
    _assert_no_pipeline_threads()

    stream = ShardStream(psv_dataset["paths"], schema, 64, n_readers=2)
    trainer.evaluate(stream)
    _assert_no_pipeline_threads()

    class _Boom(RuntimeError):
        pass

    class _Poisoned:
        """Closable batch source that fails mid-epoch."""

        def __init__(self, inner):
            self.inner = inner
            self.closed = False

        def __iter__(self):
            it = iter(self.inner)
            yield next(it)
            raise _Boom()

        def close(self):
            self.closed = True
            close_stream(self.inner)

    stream = ShardStream(psv_dataset["paths"], schema, 64, n_readers=4,
                         queue_depth=1, block_bytes=256)
    poisoned = _Poisoned(stream)
    with pytest.raises(_Boom):
        trainer.train_epoch(poisoned)
    assert poisoned.closed
    _assert_no_pipeline_threads()


# ---- pipelined device put --------------------------------------------------

def test_pipelined_prefetch_preserves_order_and_joins():
    batches = [{"x": np.full((2, 2), i)} for i in range(16)]
    pf = prefetch_to_device(iter(batches), put=lambda b: b, depth=3,
                            pipelined=True)
    out = [int(b["x"][0, 0]) for b in pf]
    assert out == list(range(16))
    pf.close()
    _assert_no_pipeline_threads()


def test_pipelined_prefetch_propagates_errors():
    def gen():
        yield {"x": np.zeros((1, 1))}
        raise ValueError("producer broke")

    pf = prefetch_to_device(gen(), put=lambda b: b, depth=2, pipelined=True)
    it = iter(pf)
    next(it)
    with pytest.raises(ValueError, match="producer broke"):
        next(it)
    pf.close()
    _assert_no_pipeline_threads()


def test_pipelined_prefetch_close_midstream_joins_and_closes_source():
    closed = []

    class _Src:
        def __iter__(self):
            for i in range(1000):
                yield {"x": np.full((1, 1), i)}

        def close(self):
            closed.append(True)

    pf = prefetch_to_device(_Src(), put=lambda b: b, depth=2,
                            pipelined=True)
    next(iter(pf))
    pf.close()
    assert closed == [True]
    _assert_no_pipeline_threads()


class _WedgedStream:
    """Contract double for ShardStream: object-level thread-safe
    close(); its iterator blocks until closed, then raises."""

    def __init__(self):
        self.closed = threading.Event()

    def close(self):
        self.closed.set()

    def __iter__(self):
        yield {"x": np.zeros((1, 1))}
        self.closed.wait(timeout=30.0)  # wedged until close()
        raise RuntimeError("stream closed underneath")


def test_pipelined_prefetch_close_unwedges_blocked_put_thread():
    """The abandonment hang case: the put thread is blocked inside
    next() on a stream whose producers stalled (only the stream's OWN
    stop signal can release it).  close() must close the root stream
    first and return promptly — not spin joining a thread that can never
    observe the prefetcher's stop event."""
    def passthrough(it):  # a generator frame LIVE on the put thread
        for b in it:
            yield b

    src = _WedgedStream()
    pf = prefetch_to_device(passthrough(iter(src)), put=lambda b: b,
                            depth=2, pipelined=True, root=src)
    it = iter(pf)
    next(it)  # put thread is now wedged producing batch 2
    t0 = time.time()
    pf.close()
    assert time.time() - t0 < 5.0, "close() hung on the wedged producer"
    assert src.closed.is_set()
    _assert_no_pipeline_threads()


def test_pipelined_prefetch_unwedges_spmd_shaped_root():
    """The SPMD worker path wraps ShardStream in fixed_step_batches, so
    the epoch ROOT handed to the prefetcher is the adapter, not the
    stream.  Its close() must reach THROUGH to the stream object
    (root-first) — closing only the adapter generator is refused while
    its frame is live on the put thread, and the wedge would hold."""
    src = _WedgedStream()
    adapter = fixed_step_batches(src, 1, 5, 1)
    pf = prefetch_to_device(adapter, put=lambda b: b, depth=2,
                            pipelined=True, root=adapter)
    next(iter(pf))  # put thread now wedged inside the adapter's next()
    t0 = time.time()
    pf.close()
    assert time.time() - t0 < 5.0, "close() hung on the wedged producer"
    assert src.closed.is_set()
    _assert_no_pipeline_threads()


def test_pipeline_close_bounded_when_reader_stuck(psv_dataset, monkeypatch):
    """A reader wedged in an uninterruptible read (dead socket, no
    timeout) can never see the stop event; close() must give up after
    close_timeout_s and abandon the daemon instead of hanging a
    health-guard rollback forever."""
    from shifu_tensorflow_tpu.data.pipeline import ShardPipeline
    from shifu_tensorflow_tpu.utils import fs

    release = threading.Event()

    class _StuckFile:
        def read(self, n=-1):
            release.wait(timeout=30.0)
            return b""

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    monkeypatch.setattr(fs, "open_maybe_gzip", lambda p: _StuckFile())
    # force the byte-chunk path (native streamer bypassed via a remote-
    # looking scheme the fs fallback owns)
    pipe = ShardPipeline(["hdfs://nn/stuck.psv"], _schema(psv_dataset),
                         n_readers=1, close_timeout_s=0.5)
    pipe.start()
    time.sleep(0.2)  # let the reader wedge inside read()
    t0 = time.time()
    pipe.close()
    assert time.time() - t0 < 5.0, "close() ignored its deadline"
    release.set()  # unstick so the daemon exits before the leak check
    _assert_no_pipeline_threads()


def test_pipelined_prefetch_records_wait_and_put_spans():
    tracer = obs_trace.Tracer()
    batches = [{"x": np.zeros((1, 1))} for _ in range(8)]
    pf = prefetch_to_device(iter(batches), put=lambda b: b, depth=2,
                            pipelined=True, tracer=tracer)
    list(pf)
    pf.close()
    s = tracer.summary()
    assert s["step.infeed.put"]["count"] == 8
    assert s["step.infeed.wait"]["count"] >= 8  # waits incl. end marker


def test_valid_stream_ingest_spans_untraced(psv_dataset):
    """The validation stream's ingest work must not pollute the train
    epoch's journaled span budget (the eval pass is untraced by
    discipline) — valid-emit streams skip the ingest.* records while
    train-emit streams report them."""
    tracer = obs_trace.install(obs_trace.Tracer())
    try:
        schema = _schema(psv_dataset)
        list(ShardStream(psv_dataset["paths"], schema, 32,
                         valid_rate=0.25, emit="valid"))
        assert not any(k.startswith("ingest.") for k in tracer.summary())
        list(ShardStream(psv_dataset["paths"], schema, 32,
                         valid_rate=0.25, emit="train"))
        spans = tracer.summary()
        assert "ingest.read" in spans and "ingest.wait" in spans
    finally:
        obs_trace.uninstall()


def test_budget_fields_split_infeed_wait_put():
    t = obs_trace.Tracer()
    t.add("step.infeed.wait", 0.2)
    t.add("step.infeed.put", 0.5)
    t.add("step.dispatch", 1.0)
    fields = obs_trace.budget_fields(t.summary())
    # wait counts toward the budget's infeed slice; put reports
    # separately (it overlaps dispatch — adding it would double-count)
    assert fields["infeed_s"] == pytest.approx(0.2)
    assert fields["infeed_wait_s"] == pytest.approx(0.2)
    assert fields["infeed_put_s"] == pytest.approx(0.5)


def test_budget_fields_host_produce_overlapped():
    """Pipelined infeed moves host production onto the put thread:
    step.host.produce reports separately (overlapped, like infeed.put)
    and never joins the disjoint host_s phase — adding it would book
    the same seconds twice against the wall clock."""
    t = obs_trace.Tracer()
    t.add("step.host.produce", 0.7)
    t.add("step.infeed.wait", 0.1)
    t.add("step.dispatch", 1.0)
    fields = obs_trace.budget_fields(t.summary())
    assert fields["host_produce_s"] == pytest.approx(0.7)
    assert fields["host_s"] == 0.0
    # sampled spans scale back to absolute estimates, same as the phases
    ts = obs_trace.Tracer(sample_every=4)
    for _ in range(2):
        ts.add("step.host.produce", 0.1)
    fields = obs_trace.budget_fields(ts.summary())
    assert fields["host_produce_s"] == pytest.approx(0.8)


# ---- autotuner policy ------------------------------------------------------

def _stats(readers, decode, *, read_s, decode_s, wait_s, wall):
    st = StageStats(readers=readers, decode_workers=decode)
    st.read_s, st.decode_s, st.wait_s, st.wall_s = (
        read_s, decode_s, wait_s, wall)
    st.rows = 1000
    return st


def test_autotuner_widens_readers_when_read_bound():
    at = IngestAutotuner(IngestKnobs(2, 1, 2), cpu_count=8)
    at.note_stats(_stats(2, 1, read_s=1.8, decode_s=0.2, wait_s=0.5,
                         wall=1.0))
    k = at.observe_epoch()
    assert (k.readers, k.decode_workers) == (3, 1)
    assert at.history[-1]["action"] == "widen-readers"


def test_autotuner_widens_decode_when_host_bound():
    at = IngestAutotuner(IngestKnobs(2, 1, 2), cpu_count=8)
    at.note_stats(_stats(2, 1, read_s=0.4, decode_s=0.9, wait_s=0.5,
                         wall=1.0))
    k = at.observe_epoch()
    assert (k.readers, k.decode_workers) == (2, 2)
    assert at.history[-1]["action"] == "widen-decode"


def test_autotuner_deepens_prefetch_when_stages_idle_but_starved():
    at = IngestAutotuner(IngestKnobs(2, 2, 2), cpu_count=8)
    at.note_stats(_stats(2, 2, read_s=0.2, decode_s=0.2, wait_s=0.4,
                         wall=1.0))
    k = at.observe_epoch()
    assert k.prefetch == 3
    assert at.history[-1]["action"] == "deepen-prefetch"


def test_autotuner_dead_band_holds_without_convergence():
    """Starvation between STARVE_LO and STARVE_HI is noise, not a
    signal: the tuner must HOLD even before ever reaching 'balanced' —
    a noise-triggered widening can't earn its regret margin and would
    burn one of the dimension's two revert strikes for nothing."""
    at = IngestAutotuner(IngestKnobs(2, 1, 2), cpu_count=8)
    at.note_stats(_stats(2, 1, read_s=1.8, decode_s=0.1, wait_s=0.07,
                         wall=1.0))
    k = at.observe_epoch()
    assert (k.readers, k.decode_workers, k.prefetch) == (2, 1, 2)
    assert at.history[-1]["action"] == "hold"


def test_autotuner_balanced_stops():
    at = IngestAutotuner(IngestKnobs(2, 1, 2), cpu_count=8)
    at.note_stats(_stats(2, 1, read_s=0.5, decode_s=0.2, wait_s=0.01,
                         wall=1.0))
    k = at.observe_epoch()
    assert (k.readers, k.decode_workers, k.prefetch) == (2, 1, 2)
    assert at.converged


def test_autotuner_respects_pins_and_caps():
    at = IngestAutotuner(IngestKnobs(2, 1, 2), pinned={"readers"},
                         cpu_count=2)
    # read-bound, but readers pinned -> must not touch them; decode not
    # the constraint -> falls through to prefetch
    at.note_stats(_stats(2, 1, read_s=1.9, decode_s=0.1, wait_s=0.5,
                         wall=1.0))
    k = at.observe_epoch()
    assert k.readers == 2
    assert k.prefetch == 3
    # decode capped at cpu count (2): widening stops at the cap
    at2 = IngestAutotuner(IngestKnobs(1, 2, 2), cpu_count=2)
    at2.note_stats(_stats(1, 2, read_s=0.1, decode_s=1.9, wait_s=0.5,
                          wall=1.0))
    k2 = at2.observe_epoch()
    assert k2.decode_workers == 2  # at cap -> fell through


def test_autotuner_reverts_widening_that_did_not_pay():
    """Regret rollback: widening must improve measured epoch throughput
    or the knob reverts and the dimension retires — on a saturated host,
    blind widening walks past the optimum into oversubscription."""
    at = IngestAutotuner(IngestKnobs(2, 1, 2), cpu_count=8)
    st = _stats(2, 1, read_s=1.8, decode_s=0.2, wait_s=0.5, wall=1.0)
    st.rows = 500_000
    at.note_stats(st)
    assert at.observe_epoch().readers == 3
    # wider but measurably NOT faster -> revert + retire the dimension
    st2 = _stats(3, 1, read_s=2.7, decode_s=0.2, wait_s=0.5, wall=1.0)
    st2.rows = 495_000
    at.note_stats(st2)
    k = at.observe_epoch()
    assert k.readers == 2
    assert at.history[-1]["action"] == "revert-readers"
    # still starved/read-bound, but readers are retired -> the tuner
    # moves to another dimension instead of re-walking the same cliff
    st3 = _stats(2, 1, read_s=1.8, decode_s=0.2, wait_s=0.5, wall=1.0)
    st3.rows = 500_000
    at.note_stats(st3)
    k = at.observe_epoch()
    assert k.readers == 2 and k.prefetch == 3


def test_autotuner_keeps_widening_that_paid():
    at = IngestAutotuner(IngestKnobs(1, 1, 2), cpu_count=8)
    st = _stats(1, 1, read_s=0.9, decode_s=0.1, wait_s=0.5, wall=1.0)
    st.rows = 300_000
    at.note_stats(st)
    assert at.observe_epoch().readers == 2
    # wider AND faster: the widen sticks, and the still-starved epoch
    # earns another one
    st2 = _stats(2, 1, read_s=1.8, decode_s=0.1, wait_s=0.5, wall=1.0)
    st2.rows = 450_000
    at.note_stats(st2)
    k = at.observe_epoch()
    assert k.readers == 3
    assert at.history[-1]["action"] == "widen-readers"


def test_autotuner_regret_skips_on_cache_transition():
    """A widen pending across a cache cold/warm boundary must not be
    judged: the source change moves rows/s severalfold on its own, so a
    warm->cold epoch would falsely revert a helpful widening (burning a
    revert strike), and cold->warm would rubber-stamp a useless one."""
    at = IngestAutotuner(IngestKnobs(2, 1, 2), cpu_count=8)
    cold = _stats(2, 1, read_s=1.8, decode_s=0.1, wait_s=0.5, wall=1.0)
    cold.rows, cold.chunks, cold.cache_chunks = 500_000, 10, 10  # warm
    at.note_stats(cold)
    assert at.observe_epoch().readers == 3  # starved -> widen, pending
    slower = _stats(3, 1, read_s=1.8, decode_s=0.1, wait_s=0.5, wall=1.0)
    slower.rows, slower.chunks, slower.cache_chunks = 300_000, 10, 0
    at.note_stats(slower)  # much slower, but COLD (cache evicted)
    k = at.observe_epoch()
    assert k.readers == 3, "confounded regret check must not revert"
    assert at.history[-1]["action"] == "regret-skip-readers"
    assert "readers" not in at._retired  # and no strike was spent


def test_autotuner_reprobe_is_bounded():
    """A retired dimension is re-probed exactly once; a second failed
    widening retires it for good (no widen/revert thrash loop)."""
    at = IngestAutotuner(IngestKnobs(2, 1, 2),
                         pinned={"decode_workers", "prefetch"}, cpu_count=8)

    def starved_epoch(readers, rows):
        st = _stats(readers, 1, read_s=0.9 * readers, decode_s=0.1,
                    wait_s=0.5, wall=1.0)
        st.rows = rows
        at.note_stats(st)
        return at.observe_epoch()

    assert starved_epoch(2, 500_000).readers == 3   # widen
    assert starved_epoch(3, 490_000).readers == 2   # revert (no gain)
    assert starved_epoch(2, 500_000).readers == 2   # all blocked -> reprobe
    assert at.history[-1]["action"] == "reprobe"
    assert starved_epoch(2, 500_000).readers == 3   # second probe
    assert starved_epoch(3, 480_000).readers == 2   # fails again -> final
    assert starved_epoch(2, 500_000).readers == 2   # permanently pinned
    assert at.history[-1]["action"] == "pinned"


def test_autotuner_uses_tracer_wait_signal():
    at = IngestAutotuner(IngestKnobs(2, 1, 2), cpu_count=8)
    # pipeline thinks it is fine, but the tracer saw the consumer starve
    at.note_stats(_stats(2, 1, read_s=1.8, decode_s=0.1, wait_s=0.0,
                         wall=1.0))
    k = at.observe_epoch({"step.infeed.wait": {"total_s": 0.4}})
    assert k.readers == 3


def test_autotuner_scales_sampled_wait_signal():
    """Under obs-trace-sample=N the wait span measured 1/N of the real
    stalls; the tuner must scale it back up (as budget_fields does) or a
    genuinely starved pipeline reads as balanced."""
    at = IngestAutotuner(IngestKnobs(2, 1, 2), cpu_count=8)
    at.note_stats(_stats(2, 1, read_s=1.8, decode_s=0.1, wait_s=0.0,
                         wall=1.0))
    # real starvation 40%; measured total 0.1 would read as 10%-borderline
    k = at.observe_epoch({"step.infeed.wait": {"total_s": 0.1,
                                               "sampled_every": 4}})
    assert k.readers == 3


def test_fit_stream_feeds_autotuner_per_epoch_summaries(psv_dataset):
    """Without an obs journal nothing else drains the tracer; fit_stream
    must hand the tuner PER-EPOCH span summaries, not cumulative ones —
    a cumulative wait total divided by one epoch's wall ratchets the
    starvation signal toward 1.0 and the tuner widens forever on a
    perfectly healthy pipeline."""
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.train.trainer import Trainer

    obs_trace.install(obs_trace.Tracer())
    try:
        mc = ModelConfig.from_json(
            {"train": {"params": {"NumHiddenLayers": 1,
                                  "NumHiddenNodes": [4],
                                  "ActivationFunc": ["relu"],
                                  "LearningRate": 0.05}}}
        )
        schema = _schema(psv_dataset)
        trainer = Trainer(mc, schema.num_features)
        seen = []

        class _Recorder:
            def settings(self):
                return IngestKnobs(1, 1, 2)

            def note_stats(self, st):
                pass

            def observe_epoch(self, summ):
                seen.append(summ)
                return IngestKnobs(1, 1, 2)

        trainer.ingest_autotuner = _Recorder()
        trainer.fit_stream(
            lambda epoch: ShardStream(psv_dataset["paths"], schema, 64),
            epochs=2,
        )
        assert len(seen) == 2 and all(s is not None for s in seen)
        # per-epoch, not cumulative: epoch 1's dispatch count must match
        # epoch 0's (same stream), not double it
        assert (seen[1]["step.dispatch"]["count"]
                == seen[0]["step.dispatch"]["count"])
    finally:
        obs_trace.uninstall()


def test_resolve_ingest_knobs_pins_explicit_dimensions():
    knobs, tuner = resolve_ingest_knobs(4, None, None, autotune=True,
                                        fallback_prefetch=3, cpu_count=2)
    assert knobs.readers == 4 and knobs.prefetch == 3
    assert tuner is not None and tuner.pinned == {"readers"}
    # autotune off -> no tuner at all
    knobs2, tuner2 = resolve_ingest_knobs(0, 0, 0, autotune=False,
                                          fallback_prefetch=2, cpu_count=2)
    assert tuner2 is None and knobs2.readers >= 1


# ---- mid-epoch resume reproducibility (cache + fault interplay) ------------

def test_resume_mid_shard_with_cache_writer(tmp_path, psv_dataset):
    """A fault mid-shard while the cache writer is open: the retried
    shard must neither duplicate nor drop cache rows, and the cold
    (faulted) and warm (cache-served) epochs must match bit-identically."""
    schema = _schema(psv_dataset)
    cache_dir = str(tmp_path / "cache")

    faults.set_plan(faults.FaultPlan.parse("ingest.read.s2:reset@0.5",
                                           seed=9))
    try:
        cold = _batch_seq(ShardStream(
            psv_dataset["paths"], schema, 32, valid_rate=0.2,
            n_readers=4, decode_workers=2, cache_dir=cache_dir,
            block_bytes=512,
            retry_policy=retry_util.RetryPolicy(base_delay_s=0.001,
                                                max_attempts=10, seed=1),
        ))
    finally:
        faults.set_plan(None)
    warm = _batch_seq(ShardStream(
        psv_dataset["paths"], schema, 32, valid_rate=0.2,
        n_readers=2, cache_dir=cache_dir,
    ))
    _assert_same_seq(cold, warm)
    _assert_no_pipeline_threads()


def test_gzip_multichunk_resume(tmp_path):
    """Byte-chunk path (small block_bytes => many chunks per shard): a
    mid-shard fault resumes at the chunk offset without reordering."""
    schema = RecordSchema(feature_columns=(1, 2), target_column=0)
    paths = []
    rng = np.random.default_rng(0)
    for i in range(2):
        p = str(tmp_path / f"s{i}.gz")
        with gzip.open(p, "wt") as f:
            for _ in range(400):
                x = rng.normal(size=2)
                f.write(f"1|{x[0]:.5f}|{x[1]:.5f}\n")
        paths.append(p)

    # small chunk sizes on BOTH sources (block_rows caps the native fused
    # stream, block_bytes the byte fallback) so each shard spans several
    # chunks and the at-step trigger "@2" fires mid-shard — the resume
    # must skip exactly the already-submitted chunks
    base = _batch_seq(ShardStream(paths, schema, 16, n_readers=1))
    faults.set_plan(faults.FaultPlan.parse("ingest.read.s0:timeout@2",
                                           seed=0))
    plan = faults.active()
    try:
        got = _batch_seq(ShardStream(
            paths, schema, 16, n_readers=2, block_bytes=1024,
            block_rows=128,
            retry_policy=retry_util.RetryPolicy(base_delay_s=0.001,
                                                max_attempts=6, seed=1),
        ))
        fired = plan.fired()
    finally:
        faults.set_plan(None)
    assert sum(fired.values()) == 1, fired  # the mid-shard fault fired
    _assert_same_seq(base, got)
