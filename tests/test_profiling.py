"""Profiling utilities (SURVEY.md §5.1 — the reference had none)."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np

from shifu_tensorflow_tpu.utils.profiling import (
    StepTimer,
    annotate,
    trace_if,
    true_sync,
)


def test_true_sync_probes_every_array_leaf():
    """true_sync is the measurement-integrity primitive (block_until_ready
    acknowledges enqueue only through the tunneled backend): it must
    fetch one element of EVERY array leaf — each leaf is an independent
    device buffer — and tolerate every pytree shape benches throw at it."""
    true_sync(jnp.ones(()))                       # scalar
    true_sync(jnp.arange(12).reshape(3, 4))       # array
    true_sync({"x": jnp.ones((8, 3)), "y": jnp.zeros((8, 1)),
               "w": jnp.ones((8, 1))})            # device_put-style batch
    true_sync([jnp.ones((2, 2)), jnp.zeros(())])  # list
    true_sync([])                                 # no leaves: no-op
    true_sync((1.0, "x", None))                   # no array leaves
    # forces REAL completion: the fetched value must be correct
    out = jax.jit(lambda a: a * 3.0)(jnp.full((4,), 2.0))
    true_sync(out)
    assert float(out[0]) == 6.0


def test_step_timer_counts_and_rates():
    timer = StepTimer(sync_every=2)
    x = jnp.ones((4,))
    for _ in range(5):
        timer.step(x * 2, rows=4)
    s = timer.summary()
    assert s["steps"] == 5
    assert s["rows_per_sec"] > 0
    assert s["elapsed_s"] > 0
    assert abs(s["steps_per_sec"] * s["step_time_s"] - 1.0) < 1e-6
    timer.reset()
    assert timer.summary()["steps"] == 0


def test_trace_if_none_is_noop():
    with trace_if(None):
        pass  # must not require jax import side effects


def test_trace_if_writes_profile(tmp_path):
    d = str(tmp_path / "trace")
    with trace_if(d):
        with annotate("unit-test-region"):
            jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))).block_until_ready()
    # jax writes <dir>/plugins/profile/<ts>/*.xplane.pb
    found = glob.glob(os.path.join(d, "**", "*.xplane.pb"), recursive=True)
    assert found, f"no trace written under {d}"


def test_trainer_step_timer_integration(model_config_json):
    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.train.trainer import Trainer

    trainer = Trainer(ModelConfig.from_json(model_config_json), 4)
    trainer.step_timer = StepTimer(sync_every=2)
    rng = np.random.default_rng(0)
    batches = [
        {
            "x": rng.normal(size=(8, 4)).astype(np.float32),
            "y": np.ones((8, 1), np.float32),
            "w": np.ones((8, 1), np.float32),
        }
        for _ in range(3)
    ]
    trainer.train_epoch(iter(batches))
    s = trainer.step_timer.summary()
    assert s["steps"] == 3
    assert trainer.step_timer.n_rows == 24
