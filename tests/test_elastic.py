"""Elastic-fleet tests — hot-standby promotion, deterministic membership
re-split, and the SLO-driven serve autoscaler policy (ISSUE 15 /
ROADMAP item 3).

Coordinator-level units drive the promotion state machine directly (no
processes); the end-to-end leg runs a thread-launcher fleet with a real
standby worker through an injected failure; the autoscaler policy is
pure (injectable clock) and unit-tested for hysteresis, cooldown,
rebalance-before-scale ordering, and empty-window neutrality.  The
process-fleet kill drill lives in ``python bench.py elastic`` /
tier1.yml (BENCH_ELASTIC.json gates).
"""

import json
import threading
import time

import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.coordinator.coordinator import (
    Coordinator,
    JobSpec,
    JobState,
)
from shifu_tensorflow_tpu.coordinator.submitter import (
    JobSubmitter,
    make_job_spec,
)
from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.data.splitter import Shard, split_size_aware
from shifu_tensorflow_tpu.serve.autoscale import (
    AutoscaleConfig,
    AutoscalePolicy,
    JournalSignals,
    TickObservation,
)


def _spec(n=2, **kw):
    shards = [Shard(i, (f"/data/part-{i}",), 1) for i in range(n)]
    kw.setdefault("registration_timeout_s", 5.0)
    if kw.get("elastic"):
        # the coordinator validates the invariant elastic_spec_kwargs
        # enforces: elastic directives ride the per-epoch barrier
        kw.setdefault("sync_epochs", True)
    return JobSpec(n_workers=n, shards=shards, epochs=2, **kw)


# ---- standby registration + promotion (coordinator units) ----

def test_standby_registers_rankless_and_outside_quorum():
    coord = Coordinator(_spec(2, standby_workers=1))
    coord.register("w0", 0)
    r = coord.register("sb0", role="standby")
    assert r["ok"] and r["role"] == "standby" and r["worker_index"] == -1
    # a standby never completes the start quorum
    assert coord.state == JobState.REGISTERING
    coord.register("w1", 1)
    assert coord.state == JobState.TRAINING
    st = coord.status()
    assert st["standbys"] == 1 and st["promotions"] == 0
    # re-registration is sticky, not a second pool slot
    coord.register("sb0", role="standby")
    assert coord.status()["standbys"] == 1


def test_promotion_takes_rank_shard_generation_without_budget():
    coord = Coordinator(_spec(2, standby_workers=1))
    coord.register("w0", 0)
    coord.register("w1", 1)
    coord.register("sb0", role="standby")
    coord.complete("w1", 1)  # worker-1 dies
    st = coord.status()
    assert st["promotions"] == 1
    assert st["restarts_used"] == 0  # promotion is budget-free
    assert st["standbys"] == 0
    assert coord.active_worker_ids() == {0: "w0", 1: "sb0"}
    rec = coord.workers["sb0"]
    assert rec.worker_index == 1
    assert rec.shard_paths == ("/data/part-1",)  # sticky shard
    assert rec.role == "worker"
    # the dead identity is gone; the submitter must not relaunch it
    assert "w1" not in coord.workers
    assert coord.restartable_workers() == []
    # promotion history rides diagnostics, roles included
    d = coord.diagnostics()
    assert d["workers"]["sb0"]["role"] == "worker"
    p = d["promotions"][0]
    assert p["worker_index"] == 1 and p["standby_id"] == "sb0"
    assert p["old_id"] == "w1" and p["claim_latency_s"] is None


def test_standby_wait_longpoll_returns_promotion_and_claims():
    coord = Coordinator(_spec(2, standby_workers=1))
    coord.register("w0", 0)
    coord.register("w1", 1)
    coord.register("sb0", role="standby")
    # unpromoted poll times out promoted=False
    r = coord.standby_wait("sb0", timeout_s=0.05)
    assert r["ok"] and not r["promoted"]
    out = {}

    def wait():
        out["r"] = coord.standby_wait("sb0", timeout_s=10.0)

    t = threading.Thread(target=wait)
    t.start()
    time.sleep(0.1)
    coord.complete("w1", 1)
    t.join(timeout=5.0)
    r = out["r"]
    assert r["promoted"] and r["worker_index"] == 1
    assert r["shard"] == ["/data/part-1"]
    assert r["generation"] == 0 and "health" in r
    # the claim stamped the takeover latency into the history
    assert coord.diagnostics()["promotions"][0]["claim_latency_s"] is not None


def test_promotion_skips_expired_standby_and_picks_freshest():
    """Satellite fix: a standby the liveness monitor has written off must
    not be promoted while expired — the choice lands on the freshest
    heartbeat and the journal records who was skipped."""
    coord = Coordinator(_spec(2, standby_workers=2))
    coord.register("w0", 0)
    coord.register("w1", 1)
    coord.register("sb0", role="standby")
    coord.register("sb1", role="standby")
    # sb0 expired; sb1 beats more recently than sb0 ever did
    with coord.liveness._lock:
        coord.liveness._expired.add("sb0")
        coord.liveness._last["sb1"] = coord.liveness._clock()
    coord.complete("w1", 1)
    assert coord.active_worker_ids()[1] == "sb1"
    assert coord.status()["standbys"] == 1  # sb0 still pooled
    # flap recovery: sb0 beats again -> eligible for the NEXT failure
    coord.liveness.beat("sb0")
    coord.complete("w0", 1)  # chief dies; sb0 promotes into rank 0
    assert coord.state == JobState.TRAINING
    assert coord.active_worker_ids()[0] == "sb0"


def test_all_standbys_expired_falls_back_to_restart_budget():
    coord = Coordinator(_spec(2, standby_workers=1, spare_restarts=2))
    coord.register("w0", 0)
    coord.register("w1", 1)
    coord.register("sb0", role="standby")
    with coord.liveness._lock:
        coord.liveness._expired.add("sb0")
    coord.complete("w1", 1)
    st = coord.status()
    # no promotion happened; the classic relaunch path charged budget
    assert st["promotions"] == 0
    assert st["restarts_used"] == 1
    assert [r.worker_id for r in coord.restartable_workers()] == ["w1"]


def test_chief_failure_without_standby_still_short_circuits():
    coord = Coordinator(_spec(2))
    coord.register("w0", 0)
    coord.register("w1", 1)
    coord.complete("w0", 1)
    assert coord.state == JobState.FAILED
    assert "chief" in coord.failure_reason


def test_spmd_promotion_substitutes_uncharged_then_exhausts():
    """SPMD: first failure consumes the standby (uncharged generation
    bump, sticky rank/shard); second failure — pool empty — falls back
    to the charged PR-2 fleet restart."""
    coord = Coordinator(_spec(2, spmd=True, standby_workers=1,
                              spare_restarts=1))
    coord.register("w0", 0, host="127.0.0.1", jax_port=1)
    coord.register("w1", 1, host="127.0.0.1", jax_port=2)
    coord.register("sb0", role="standby")
    coord.complete("w1", 1)
    st = coord.status()
    assert st["generation"] == 1
    assert st["restarts_used"] == 0  # standby paid, not the budget
    assert st["promotions"] == 1
    assert coord.active_worker_ids() == {0: "w0", 1: "sb0"}
    # fleet re-registers into generation 1 (the submitter relaunched it
    # by the identity map), then the chief dies: charged restart
    coord.register("w0", 0, host="127.0.0.1", jax_port=1)
    coord.register("sb0", 1, host="127.0.0.1", jax_port=3)
    coord.complete("w0", 1)
    st = coord.status()
    assert st["generation"] == 2 and st["restarts_used"] == 1


def test_promotion_register_reply_is_sticky_for_promoted_standby():
    """A promoted standby re-registering (relaunch, SPMD generation
    bump) must route through the sticky worker path, not the standby
    pool."""
    coord = Coordinator(_spec(2, standby_workers=1))
    coord.register("w0", 0)
    coord.register("w1", 1)
    coord.register("sb0", role="standby")
    coord.complete("w1", 1)
    r = coord.register("sb0")  # promoted: plain worker registration
    assert r["ok"] and r["worker_index"] == 1
    assert r["shard"] == ["/data/part-1"]
    # and registering it as a standby again is refused with a clear error
    r = coord.register("sb0", role="standby")
    assert not r["ok"] and "promoted" in r["error"]


def test_standby_exit_shrinks_pool_without_failing_any_rank():
    coord = Coordinator(_spec(2, standby_workers=1))
    coord.register("w0", 0)
    coord.register("w1", 1)
    coord.register("sb0", role="standby")
    coord.complete("sb0", 1)  # standby crashes
    st = coord.status()
    assert st["standbys"] == 0
    assert st["restarts_used"] == 0
    assert coord.state == JobState.TRAINING


# ---- elastic membership re-split ----

def test_budget_exhaustion_shrinks_elastic_fleet_deterministically():
    coord = Coordinator(_spec(3, elastic=True, spare_restarts=0))
    for i in range(3):
        coord.register(f"w{i}", i)
    # budget = floor(0.1*3) + 0 = 0: the first failure exhausts it
    coord.complete("w2", 1)
    st = coord.status()
    assert coord.state == JobState.TRAINING
    assert st["active_workers"] == [0, 1]
    assert st["split_generation"] == 1
    # the re-split IS split_size_aware over the union of paths — a pure
    # function of paths x n_workers, so any observer can recompute it
    paths = sorted(f"/data/part-{i}" for i in range(3))
    expect = {s.worker_index: tuple(s.paths)
              for s in split_size_aware(paths, 2)}
    got = {r.worker_index: r.shard_paths for r in coord.workers.values()}
    assert got == {i: expect[k] for k, i in zip(sorted(expect), [0, 1])}
    # the epoch barrier completes on the survivor quorum and delivers
    # the new shard to a worker still echoing the old split generation
    coord.report_epoch(_stats(0, 0).__dict__)
    coord.report_epoch(_stats(1, 0).__dict__)
    resp = coord.epoch_barrier("w0", 0, timeout_s=1.0,
                               split_generation=0)
    assert resp["ok"]
    assert resp["resplit"]["split_generation"] == 1
    assert resp["resplit"]["shard"] == list(got[0])
    # once the worker echoes the new generation, no directive rides
    resp = coord.epoch_barrier("w0", 0, timeout_s=1.0,
                               split_generation=1)
    assert resp["ok"] and "resplit" not in resp


def test_budget_exhaustion_without_elastic_still_fails():
    coord = Coordinator(_spec(3, spare_restarts=0))
    for i in range(3):
        coord.register(f"w{i}", i)
    coord.complete("w2", 1)
    assert coord.state == JobState.FAILED
    assert "exhausted" in coord.failure_reason


def test_resize_shrink_releases_ranks_and_grow_adds_pending():
    coord = Coordinator(_spec(3, elastic=True))
    for i in range(3):
        coord.register(f"w{i}", i)
    # shrink 3 -> 2: rank 2 released cooperatively at its next barrier
    r = coord.resize(2)
    assert r["ok"] and r["ranks"] == [0, 1]
    resp = coord.epoch_barrier("w2", 0, timeout_s=1.0)
    assert resp.get("released")
    # the release is NOT consumed on delivery: a lost reply redelivers
    # at the retry (this op carries no dedup token)
    resp = coord.epoch_barrier("w2", 0, timeout_s=1.0)
    assert resp.get("released")
    # growing past the data-file count is a clean refusal
    r = coord.resize(4)
    assert not r["ok"] and "data file" in r["error"]
    # grow 2 -> 3: the refilled HOLE (rank 2, shrunk above) pends until
    # the submitter launches a worker for it
    r = coord.resize(3)
    assert r["ok"] and len(r["ranks"]) == 3
    new_idx = coord.pending_indices()[0]
    assert new_idx == 2  # holes refill first
    # a worker registering into the grown rank gets the shard the
    # RE-SPLIT computed for it (never a stale spec.shards entry, which
    # for ranks past the original width does not even exist)
    reg = coord.register("grown", new_idx)
    assert reg["ok"] and reg["worker_index"] == new_idx
    paths = sorted(f"/data/part-{i}" for i in range(3))
    expect = {i: tuple(s.paths)
              for i, s in enumerate(split_size_aware(paths, 3))}
    got = {r2.worker_index: r2.shard_paths
           for r2 in coord.workers.values()}
    assert got == {idx: expect[k]
                   for k, idx in zip(sorted(expect), sorted(got))}
    assert reg["shard"] == list(got[new_idx])
    # resize needs the elastic opt-in
    plain = Coordinator(_spec(2))
    plain.register("a", 0)
    assert not plain.resize(1)["ok"]


def test_regrown_rank_reusing_released_worker_id_is_not_released():
    """A rank shrunk away and grown back relaunches under its ORIGINAL
    worker id (the submitter derives ids from rank indices): the stale
    release directive must die at re-registration, or the new process is
    told 'released' at its first barrier, exits 0, and the rank wedges
    the surviving quorum forever."""
    coord = Coordinator(_spec(3, elastic=True))
    for i in range(3):
        coord.register(f"w{i}", i)
    coord.resize(2)
    assert coord.epoch_barrier("w2", 0, timeout_s=1.0).get("released")
    coord.resize(3)
    # the submitter refills rank 2 under the same id
    reg = coord.register("w2", coord.pending_indices()[0])
    assert reg["ok"] and reg["worker_index"] == 2
    for i in range(3):
        coord.report_epoch(_stats(i, 0).__dict__)
    resp = coord.epoch_barrier("w2", 0, timeout_s=1.0)
    assert resp["ok"] and not resp.get("released")


def test_promoted_over_flapper_is_released_at_next_barrier():
    """The 'dead' rank's old process may only be FLAPPED (GC pause,
    partition), not dead: if it wakes after the standby took over, its
    next epoch barrier must hand it the cooperative-exit directive —
    otherwise two live processes train the same rank's shard."""
    coord = Coordinator(_spec(2, standby_workers=1))
    coord.register("w0", 0)
    coord.register("w1", 1)
    coord.register("sb0", role="standby")
    coord.complete("w1", 1)  # promotion consumes the standby
    assert coord.status()["promotions"] == 1
    resp = coord.epoch_barrier("w1", 0, timeout_s=1.0)
    assert resp.get("released")
    # NOT consumed on delivery: a lost reply must redeliver at retry
    resp = coord.epoch_barrier("w1", 0, timeout_s=1.0)
    assert resp.get("released")
    # the promoted standby itself keeps training under its own id
    assert "sb0" not in coord._released_ids


def test_shrunk_away_flapper_is_released_at_next_barrier():
    """Same flap hazard on the elastic-shrink path: a worker the
    re-split wrote off must exit at its next barrier instead of
    training rows the survivors now own."""
    coord = Coordinator(_spec(3, elastic=True, spare_restarts=0))
    for i in range(3):
        coord.register(f"w{i}", i)
    coord.complete("w2", 1)  # budget 0 + no standby -> shrink
    assert coord.status()["active_workers"] == [0, 1]
    resp = coord.epoch_barrier("w2", 0, timeout_s=1.0)
    assert resp.get("released")


def test_release_directive_rides_the_heartbeat_reply():
    """sync_epochs can be off outside the elastic path, so the barrier
    is not a guaranteed delivery channel: the heartbeat — which EVERY
    worker polls — must carry the release too, or a flapped-then-
    promoted-over worker trains its old shard in duplicate forever."""
    coord = Coordinator(_spec(2, standby_workers=1))
    coord.register("w0", 0)
    coord.register("w1", 1)
    coord.register("sb0", role="standby")
    coord.complete("w1", 1)  # standby promoted into rank 1
    assert coord.heartbeat("w1").get("released")
    assert not coord.heartbeat("w0").get("released")
    assert not coord.heartbeat("sb0").get("released")


def test_shrink_refused_without_data_paths_fails_instead_of_wedging():
    """Placeholder/in-memory shards have no data paths: split_size_aware
    over an empty union would raise AFTER the membership mutation inside
    the liveness callback, leaving the job half-shrunk (dead rank gone
    from workers but still in the barrier quorum).  The shrink must
    refuse up front and fall through to the normal failure policy."""
    spec = JobSpec(n_workers=2, shards=[None, None], epochs=2,
                   elastic=True, sync_epochs=True, spare_restarts=0,
                   registration_timeout_s=5.0)
    coord = Coordinator(spec)
    coord.register("w0", 0)
    coord.register("w1", 1)
    coord.complete("w1", 1)  # budget 0, no paths -> shrink refused
    assert coord.state == JobState.FAILED
    # no half-mutation: the failed job still accounts both ranks
    assert sorted(coord._active_indices) == [0, 1]


def test_resize_shrink_refused_without_data_paths_before_mutation():
    """resize() shrink must validate the path count BEFORE the drop loop
    mutates membership — split_size_aware raising mid-mutation would
    leave released workers still in the barrier quorum."""
    spec = JobSpec(n_workers=3, shards=[None, None, None], epochs=2,
                   elastic=True, sync_epochs=True,
                   registration_timeout_s=5.0)
    coord = Coordinator(spec)
    for i in range(3):
        coord.register(f"w{i}", i)
    r = coord.resize(2)
    assert not r["ok"] and "data file" in r["error"]
    # nothing was mutated by the refusal
    assert sorted(coord._active_indices) == [0, 1, 2]
    assert coord._released_ids == set()
    assert set(coord.workers) == {"w0", "w1", "w2"}


def test_policy_read_error_tick_is_fully_neutral():
    """An unreadable journal proves nothing: it must not reset the
    breach debounce, accrue recovery credit, or ever drive a decision —
    six blips in a row must not shrink a breached fleet."""
    clock = [100.0]
    p = AutoscalePolicy(AutoscaleConfig(workers_min=1, workers_max=3,
                                        ticks=2, recovery_ticks=2,
                                        cooldown_s=0.0),
                        clock=lambda: clock[0])
    breach = TickObservation(new_events=1, breached={"serve_p99_s"})
    assert p.observe(breach, 2) is None  # tick 1 of 2
    # a read-error tick holds the debounce still ...
    for _ in range(6):
        assert p.observe(TickObservation(read_error=True), 2) is None
    # ... so the next breached tick completes it
    d = p.observe(breach, 2)
    assert d is not None and d.action == "scale_up"
    # and read errors never accrue recovery credit toward scale_down
    p2 = AutoscalePolicy(AutoscaleConfig(workers_min=1, workers_max=3,
                                         ticks=2, recovery_ticks=2,
                                         cooldown_s=0.0),
                         clock=lambda: clock[0])
    p2.observe(TickObservation(new_events=1), 2)  # journal proven wired
    for _ in range(6):
        assert p2.observe(TickObservation(read_error=True), 2) is None
    assert p2._clean_ticks <= 1


def _stats(worker, epoch, loss=0.5):
    from shifu_tensorflow_tpu.train.trainer import EpochStats

    return EpochStats(
        worker_index=worker, current_epoch=epoch, training_loss=loss,
        valid_loss=loss, training_time_s=1.0, valid_time_s=0.1,
        global_step=epoch + 1,
    )


def test_coordinator_metrics_export_standby_and_budget_gauges():
    coord = Coordinator(_spec(2, standby_workers=1, spare_restarts=3))
    coord.register("w0", 0)
    coord.register("w1", 1)
    coord.register("sb0", role="standby")
    text = coord.metrics_text()
    assert "stpu_coord_standby_registered 1" in text
    assert "stpu_coord_standby_available 1" in text
    assert f"stpu_coord_restart_budget_remaining {coord.max_restarts}" \
        in text
    assert "stpu_coord_restart_budget_burn_window 0" in text
    coord.complete("w1", 1)  # promotion: still no budget burn
    text = coord.metrics_text()
    assert "stpu_coord_standby_promotions_total 1" in text
    assert f"stpu_coord_restart_budget_remaining {coord.max_restarts}" \
        in text
    coord.complete("sb0", 1)  # no standby left: budget burns
    text = coord.metrics_text()
    assert ("stpu_coord_restart_budget_remaining "
            f"{coord.max_restarts - 1}") in text
    assert "stpu_coord_restart_budget_burn_window 1" in text


# ---- worker-side resplit application ----

def test_shard_state_applies_resplit_and_release_raises():
    from shifu_tensorflow_tpu.coordinator.worker import (
        _epoch_callback,
        _Released,
        _ShardState,
    )

    shard_state = _ShardState(["/d/a"])

    class FakeHb:
        abort = threading.Event()
        restart = threading.Event()
        released = threading.Event()

    class FakeClient:
        def __init__(self):
            self.replies = []
            self.barrier_calls = []

        def report_epoch(self, stats):
            return {"ok": True}

        def epoch_barrier(self, wid, epoch, split_generation=None):
            self.barrier_calls.append(split_generation)
            return self.replies.pop(0)

    cfg = type("C", (), {"worker_id": "w1"})()
    client = FakeClient()
    cb = _epoch_callback(cfg, client, FakeHb(), sync_epochs=True,
                         fail_at_epoch=None, shard_state=shard_state)
    client.replies.append({"ok": True, "resplit": {
        "shard": ["/d/a", "/d/b"], "split_generation": 2,
        "n_workers": 2}})
    cb(_stats(1, 0))
    assert shard_state.paths == ["/d/a", "/d/b"]
    assert shard_state.split_generation == 2
    # the NEXT barrier echoes the applied generation
    client.replies.append({"ok": True})
    cb(_stats(1, 1))
    assert client.barrier_calls == [0, 2]
    # a released reply raises the cooperative-exit signal
    client.replies.append({"ok": True, "released": True})
    with pytest.raises(_Released):
        cb(_stats(1, 2))


# ---- end-to-end: thread fleet with a real standby takeover ----

def _worker_config_factory(psv_dataset, model_config, tmp_path):
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )

    def make(worker_id, addr):
        return WorkerConfig(
            worker_id=worker_id,
            coordinator_host=addr[0],
            coordinator_port=addr[1],
            model_config=model_config,
            schema=schema,
            batch_size=100,
            checkpoint_dir=str(tmp_path / "job-ckpt"),
            heartbeat_interval_s=0.1,
        )

    return make


@pytest.fixture
def job_model_config():
    return ModelConfig.from_json(
        {"train": {"numTrainEpochs": 2, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05,
                              "Optimizer": "adam"}}}
    )


def test_standby_takeover_end_to_end_thread_fleet(
    psv_dataset, tmp_path, job_model_config
):
    """A non-chief worker dies mid-job with ZERO restart budget; the
    hot standby — registered, pre-built, warm — takes the rank over and
    the job finishes without a single budgeted relaunch.  sync_epochs
    holds the chief at the barrier until the promoted rank catches up,
    so the takeover is provably on the critical path."""
    spec = make_job_spec(psv_dataset["root"], 2, epochs=2,
                         registration_timeout_s=10.0, spare_restarts=0,
                         sync_epochs=True, epoch_barrier_timeout_s=60.0,
                         standby_workers=1)
    # budget floor(0.1*2)+0 = 0: without the standby this kill is fatal
    sub = JobSubmitter(
        spec,
        _worker_config_factory(psv_dataset, job_model_config, tmp_path),
        fault_injections={"worker-1": 0},  # dies at epoch 0
    )
    result = sub.run(timeout_s=180.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    assert result.promotions_used == 1
    assert result.restarts_used == 0
    # every epoch reached full (2-worker) quorum: the promoted rank
    # re-reported the epochs the dead rank owed the barrier
    assert [s.epoch for s in result.epoch_summaries] == [0, 1]
    assert all(s.n_workers == 2 for s in result.epoch_summaries)


def test_no_standby_same_fault_fails_the_job(
    psv_dataset, tmp_path, job_model_config
):
    """Control arm for the takeover test: identical fleet and fault,
    zero budget, no standby — the job dies.  Pinned so the e2e test
    above cannot silently pass for the wrong reason."""
    spec = make_job_spec(psv_dataset["root"], 2, epochs=2,
                         registration_timeout_s=10.0, spare_restarts=0,
                         sync_epochs=True, epoch_barrier_timeout_s=60.0)
    sub = JobSubmitter(
        spec,
        _worker_config_factory(psv_dataset, job_model_config, tmp_path),
        fault_injections={"worker-1": 0},
    )
    result = sub.run(timeout_s=180.0)
    assert result.state == JobState.FAILED
    assert "exhausted" in (result.failure_reason or "")


# ---- autoscaler policy (pure units) ----

def _cfg(**kw):
    kw.setdefault("workers_min", 1)
    kw.setdefault("workers_max", 3)
    kw.setdefault("ticks", 2)
    kw.setdefault("recovery_ticks", 3)
    kw.setdefault("cooldown_s", 10.0)
    return AutoscaleConfig(**kw)


def _obs(events=1, breached=(), sheds=None, tenants=0):
    return TickObservation(
        new_events=events, breached=set(breached),
        sheds_by_model=dict(sheds or {}), tenants_seen=tenants,
    )


def test_policy_hysteresis_then_scale_up_then_cooldown():
    clock = [0.0]
    p = AutoscalePolicy(_cfg(), clock=lambda: clock[0])
    breach = _obs(breached={"serve_p99_s"})
    assert p.observe(breach, 1) is None  # tick 1 < hysteresis 2
    d = p.observe(breach, 1)
    assert d is not None and d.action == "scale_up"
    assert "serve_p99_s" in d.reason
    clock[0] += 5.0  # inside cooldown
    assert p.observe(breach, 2) is None
    clock[0] += 10.0  # cooldown over; breach held through it
    assert p.observe(breach, 2).action == "scale_up"
    clock[0] += 20.0
    # at the ceiling the policy never acts, however long the breach
    assert all(p.observe(breach, 3) is None for _ in range(5))


def test_policy_recovery_shrinks_lazily_and_respects_floor():
    clock = [100.0]
    p = AutoscalePolicy(_cfg(), clock=lambda: clock[0])
    clean = _obs()
    assert p.observe(clean, 3) is None
    assert p.observe(clean, 3) is None
    d = p.observe(clean, 3)  # 3rd clean tick = recovery_ticks
    assert d is not None and d.action == "scale_down"
    clock[0] += 20.0
    # at the floor: no shrink no matter how clean
    assert all(p.observe(clean, 1) is None for _ in range(6))


def test_policy_empty_window_discipline():
    """Empty-window rules: (1) a latched breach whose writer went
    QUIET is a dead worker, never fresh overload evidence — no
    scale_up; (2) before the journal has produced any event at all the
    policy is inert — no blind shrink; (3) a quiet UN-breached fleet
    accrues recovery credit (traffic going away entirely IS recovery,
    the slo watchdog's drained-window rule)."""
    clock = [0.0]
    # (2) pristine policy, journal never speaks: inert forever
    p0 = AutoscalePolicy(_cfg(), clock=lambda: clock[0])
    for _ in range(20):
        assert p0.observe(TickObservation(), 3) is None
    # (1) breach latches, then the writer dies (no new events): the
    # stale breach must not scale anything up
    p1 = AutoscalePolicy(_cfg(), clock=lambda: clock[0])
    assert p1.observe(_obs(breached={"serve_p99_s"}), 1) is None
    dead = TickObservation(breached={"serve_p99_s"})
    for _ in range(10):
        assert p1.observe(dead, 1) is None
    # (3) recovered then quiet: empty un-breached ticks count toward
    # the shrink
    p2 = AutoscalePolicy(_cfg(), clock=lambda: clock[0])
    assert p2.observe(_obs(), 2) is None  # one real event proves wiring
    assert p2.observe(TickObservation(), 2) is None
    d = p2.observe(TickObservation(), 2)  # 3rd clean tick
    assert d is not None and d.action == "scale_down"


def test_policy_rebalances_dominant_tenant_before_scaling():
    clock = [0.0]
    p = AutoscalePolicy(_cfg(), clock=lambda: clock[0])
    hot = _obs(breached={"serve_p99_s:beta"},
               sheds={"alpha": 100, "beta": 2}, tenants=2)
    assert p.observe(hot, 1) is None
    d = p.observe(hot, 1)
    assert d.action == "rebalance" and d.model == "alpha"
    assert d.weight == pytest.approx(0.5)
    assert p.weight_overrides == {"alpha": 0.5}
    # breach persists: weight halves again after cooldown
    clock[0] += 20.0
    hot2 = _obs(breached={"serve_p99_s:beta"},
                sheds={"alpha": 220, "beta": 3}, tenants=2)
    assert p.observe(hot2, 1) is None
    d = p.observe(hot2, 1)
    assert d.action == "rebalance" and d.weight == pytest.approx(0.25)
    # floored: capacity is the remaining lever
    clock[0] += 20.0
    hot3 = _obs(breached={"serve_p99_s:beta"},
                sheds={"alpha": 340, "beta": 4}, tenants=2)
    assert p.observe(hot3, 1) is None
    d = p.observe(hot3, 1)
    assert d.action == "scale_up"


def test_policy_no_rebalance_without_dominance_or_single_tenant():
    clock = [0.0]
    p = AutoscalePolicy(_cfg(), clock=lambda: clock[0])
    # two tenants shedding evenly: capacity problem, not fairness
    even = _obs(breached={"serve_p99_s"},
                sheds={"alpha": 50, "beta": 50}, tenants=2)
    p.observe(even, 1)
    assert p.observe(even, 1).action == "scale_up"
    clock[0] += 100.0
    p2 = AutoscalePolicy(_cfg(), clock=lambda: clock[0])
    # single tenant: nothing to rebalance against
    solo = _obs(breached={"serve_p99_s"}, sheds={"alpha": 100}, tenants=1)
    p2.observe(solo, 1)
    assert p2.observe(solo, 1).action == "scale_up"


def test_journal_signals_parse_breach_state_and_sheds(tmp_path):
    """JournalSignals reads the same files `obs summary` does: breach
    state per (writer, signal) — one worker recovering must not mask
    another's open breach — and per-tenant sheds as summed per-writer
    maxima of the monotonic counter."""
    base = tmp_path / "serve.jsonl"

    def line(**kw):
        kw.setdefault("plane", "serve")
        return json.dumps(kw) + "\n"

    base.write_text(
        line(ts=1.0, event="slo_breach", signal="serve_p99_s", worker=0)
        + line(ts=2.0, event="shed", model="alpha", worker=0,
               shed_total=5)
        + line(ts=3.0, event="shed", model="alpha", worker=1,
               shed_total=7)
        + line(ts=4.0, event="shed", model="beta", worker=0,
               shed_total=1)
        + line(ts=5.0, event="serve_batch", model="beta", worker=0,
               rows=4)
    )
    sig = JournalSignals(str(base))
    obs = sig.poll()
    assert obs.breached == {"serve_p99_s"}
    assert obs.sheds_by_model == {"alpha": 12, "beta": 1}
    assert obs.tenants_seen == 2
    assert obs.new_events == 5
    # nothing new: empty tick
    obs = sig.poll()
    assert obs.new_events == 0
    # worker 0 recovers but worker 1 opens its own breach
    with open(base, "a") as f:
        f.write(line(ts=6.0, event="slo_recover", signal="serve_p99_s",
                     worker=0))
        f.write(line(ts=7.0, event="slo_breach",
                     signal="serve_shed_rate:alpha", worker=1))
    obs = sig.poll()
    assert obs.breached == {"serve_shed_rate:alpha"}
    assert obs.new_events == 2
    # a writer that dies or restarts cannot emit its own slo_recover —
    # its latched breach clears on serve_worker_exit/scale_down (the
    # supervisor's record of the death) or on a fresh serve_start (the
    # replacement's watchdog starts un-breached).  Without this, the
    # rebalance rolling restart latches a breach forever and drives
    # scale_ups to the ceiling.
    with open(base, "a") as f:
        f.write(line(ts=8.0, event="serve_worker_exit", index=1, rc=-15))
    assert sig.poll().breached == set()
    with open(base, "a") as f:
        f.write(line(ts=9.0, event="slo_breach", signal="serve_p99_s",
                     worker=0))
        f.write(line(ts=10.0, event="serve_start", worker=0, port=1))
    assert sig.poll().breached == set()


def test_journal_signals_survive_late_flush_and_worker_restart(tmp_path):
    """Two hardenings of the incremental fold: (1) a slow writer's
    events can reach disk AFTER a faster writer's later-ts events were
    already polled — the merged-order sort puts them BEFORE the seen
    tail, so a global list-index watermark would skip them silently;
    per-writer (ts, seq) marks must still fold them.  (2) a restarted
    serve worker's shed_total restarts near 0 — its dead process's
    high-water is retired on serve_start so fresh sheds are visible
    immediately (and totals stay monotonic) instead of masked until
    they beat the old maximum."""
    base = tmp_path / "serve.jsonl"
    base.write_text("")

    def line(**kw):
        kw.setdefault("plane", "serve")
        return json.dumps(kw) + "\n"

    # writer s1 flushes first, with LATER timestamps
    (tmp_path / "serve.jsonl.s1").write_text(
        line(ts=10.0, event="serve_batch", model="alpha", worker=1)
        + line(ts=11.0, event="serve_batch", model="beta", worker=1)
    )
    sig = JournalSignals(str(base))
    assert sig.poll().new_events == 2
    # writer s0's breach reaches disk late but carries an EARLIER ts:
    # it merges before the already-seen tail and must still be folded
    (tmp_path / "serve.jsonl.s0").write_text(
        line(ts=5.0, event="slo_breach", signal="serve_p99_s", worker=0)
    )
    obs = sig.poll()
    assert obs.new_events == 1
    assert obs.breached == {"serve_p99_s"}
    # worker 0 sheds heavily, restarts, then sheds a little: the fresh
    # process's counter must show through at once
    with open(tmp_path / "serve.jsonl.s0", "a") as f:
        f.write(line(ts=6.0, event="shed", model="alpha", worker=0,
                     shed_total=500))
    assert sig.poll().sheds_by_model == {"alpha": 500}
    with open(tmp_path / "serve.jsonl.s0", "a") as f:
        f.write(line(ts=7.0, event="serve_start", worker=0, port=1))
        f.write(line(ts=8.0, event="shed", model="alpha", worker=0,
                     shed_total=5))
    assert sig.poll().sheds_by_model == {"alpha": 505}


def test_read_keyed_events_after_watermarks_return_only_new(tmp_path):
    """The autoscaler's poll path: ``after=`` per-writer watermarks make
    the reader's RETURN incremental — an unchanged-and-fully-seen file
    is skipped outright, only the new tail is keyed/sorted, and a
    late-flushing writer's earlier-ts events still come back (the marks
    are per writer, not a global index)."""
    from shifu_tensorflow_tpu.obs.journal import read_keyed_events

    base = tmp_path / "j.jsonl"

    def line(seq, ts, **kw):
        kw.update(seq=seq, ts=ts)
        return json.dumps(kw) + "\n"

    base.write_text(line(0, 1.0, event="a") + line(1, 2.0, event="b"))
    (tmp_path / "j.jsonl.s0").write_text(line(0, 1.5, event="c"))
    cache, marks = {}, {}
    keyed = read_keyed_events(str(base), cache=cache, after=marks)
    assert [t[3]["event"] for t in keyed] == ["a", "c", "b"]
    for ts, writer, seq, _ in keyed:
        marks[writer] = max(marks.get(writer, (-1.0, -1)), (ts, seq))
    # everything at or below the marks: nothing returned
    assert read_keyed_events(str(base), cache=cache, after=marks) == []
    # only the tail returns; a second writer's late flush with EARLIER
    # timestamps is new to its own mark and still folds
    with open(base, "a") as f:
        f.write(line(2, 3.0, event="d"))
    (tmp_path / "j.jsonl.s1").write_text(line(0, 0.5, event="e"))
    keyed = read_keyed_events(str(base), cache=cache, after=marks)
    assert [t[3]["event"] for t in keyed] == ["e", "d"]
    # without after= the same cache still serves full merged history
    allk = read_keyed_events(str(base), cache=cache)
    assert [t[3]["event"] for t in allk] == ["e", "a", "c", "b", "d"]


# ---- obs CLI reconstruction ----

def test_obs_fleet_and_summary_render_elastic_story(tmp_path, capsys):
    """`obs fleet` renders standby promotions (rank, epoch, takeover
    latency) beside straggler excursions, and `obs summary` renders the
    autoscaler's decisions — from journal files alone."""
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    base = tmp_path / "fleet.jsonl"

    def line(**kw):
        return json.dumps(kw) + "\n"

    base.write_text(
        line(ts=10.0, event="standby_register", plane="coordinator",
             worker_id="standby-0", standbys=1)
        + line(ts=20.0, event="standby_promote", plane="coordinator",
               worker=1, worker_id="standby-0", old_worker_id="worker-1",
               why="missed heartbeats", epoch=3, hb_age_s=0.2,
               standbys_left=0, skipped_expired=[])
        + line(ts=20.5, event="standby_claim", plane="coordinator",
               worker=1, worker_id="standby-0", latency_s=0.42)
        + line(ts=30.0, event="resplit", plane="coordinator",
               split_generation=1, ranks=[0, 1], n_files=4,
               why="shrink after worker 2 failed")
        + line(ts=40.0, event="serve_fleet_start", plane="serve",
               workers=1, workers_max=3, autoscale=True, port=1)
        + line(ts=41.0, event="scale_up", plane="serve", index=1,
               to_workers=2, reason="serve_p99_s breached")
        + line(ts=50.0, event="rebalance", plane="serve", model="alpha",
               weight=0.5, reason="tenant alpha owns the overload")
        + line(ts=60.0, event="scale_down", plane="serve", index=1,
               to_workers=1, reason="recovered")
    )
    rc = obs_main(["fleet", "--journal", str(base)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "promotion: rank 1 <- standby-0" in out
    assert "takeover 0.42s" in out
    assert "@epoch 3" in out
    assert "resplit: generation 1" in out
    rc = obs_main(["summary", "--journal", str(base)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "autoscale: scale_up -> 2 workers" in out
    assert "autoscale: rebalance tenant alpha weight -> 0.5" in out
    assert "autoscale: scale_down -> 1 workers" in out
    # --json carries the structured decisions + promotions
    rc = obs_main(["summary", "--journal", str(base), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert [d["action"] for d in doc["serve"]["autoscale"]] \
        == ["scale_up", "rebalance", "scale_down"]
    assert doc["fleet"]["promotions"][0]["latency_s"] == 0.42
