"""Bulk scoring plane drills: lease-table edge cases (expiry racing an
in-flight commit, double reclaim, renewal racing shutdown, resume from a
``_SUCCESS``-less partial state), torn-write-proof commits, thread-mode
end-to-end jobs, and the acceptance kill drill — a scorer process
SIGKILLed mid-shard under a torn-write fault plan must leave output
bit-identical to an unkilled control arm with zero duplicate or missing
rows."""

from __future__ import annotations

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.data import splitter
from shifu_tensorflow_tpu.data.pipeline import ShardPipeline
from shifu_tensorflow_tpu.export.saved_model import (
    NATIVE_MANIFEST,
    export_native_bundle,
)
from shifu_tensorflow_tpu.obs import journal as obs_journal
from shifu_tensorflow_tpu.score import committer, plan as plan_mod
from shifu_tensorflow_tpu.score.job import run_job
from shifu_tensorflow_tpu.score.lease import (
    COMMITTED,
    LeaseTable,
    PENDING,
)
from shifu_tensorflow_tpu.score.worker import format_scores, score_schema
from shifu_tensorflow_tpu.serve.tenancy.store import (
    admit_batch_tenants,
    discover_bundles,
)
from shifu_tensorflow_tpu.train.trainer import Trainer
from shifu_tensorflow_tpu.utils import faults
from shifu_tensorflow_tpu.utils import retry as retry_util

N_FEATURES = 6


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    yield
    faults.set_plan(None)


@pytest.fixture(autouse=True)
def _clear_journal():
    yield
    obs_journal.uninstall()


def _model_config(nodes: int = 4) -> ModelConfig:
    return ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1,
                              "NumHiddenNodes": [nodes],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05}}})


def _bundle(path: str, seed: int) -> str:
    t = Trainer(_model_config(), N_FEATURES, seed=seed)
    export_native_bundle(path, t.state.params, _model_config(), N_FEATURES)
    return path


@pytest.fixture(scope="module")
def models_dir(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("models"))
    _bundle(os.path.join(root, "alpha"), seed=1)
    _bundle(os.path.join(root, "beta"), seed=2)
    return root


def _write_inputs(root: str, n_files: int, rows_per_file: int) -> int:
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(7)
    for i in range(n_files):
        with open(os.path.join(root, f"in-{i:03d}.psv"), "w") as f:
            for _ in range(rows_per_file):
                x = rng.random(N_FEATURES)
                f.write("|".join(f"{v:.5f}" for v in x) + "\n")
    return n_files * rows_per_file


def _blob(out_dir: str) -> bytes:
    parts = sorted(n for n in os.listdir(out_dir)
                   if n.startswith("part-") and n.endswith(".psv"))
    return b"".join(
        open(os.path.join(out_dir, n), "rb").read() for n in parts)


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------- lease table edges


def test_lease_grant_renew_commit_walk():
    clock = FakeClock()
    events = []
    table = LeaseTable(2, ttl_s=10.0, clock=clock,
                       on_event=lambda e, **f: events.append((e, f)))
    g0 = table.acquire("w0", "tok0")
    assert g0["shard"] == 0 and g0["lease"] == "tok0"
    g1 = table.acquire("w1", "tok1")
    assert g1["shard"] == 1
    assert table.acquire("w2", "tok2") is None  # all leased, none pending
    assert not table.done()
    clock.advance(5.0)
    assert table.renew(0, "tok0")
    assert not table.renew(0, "wrong-token")
    assert table.commit(0, "tok0", {"rows": 3}, worker="w0") == "accept"
    assert table.commit(1, "tok1", {"rows": 4}, worker="w1") == "accept"
    assert table.done()
    names = [e for e, _ in events]
    assert names.count("lease_grant") == 2
    assert names.count("shard_commit") == 2


def test_expiry_while_commit_in_flight_token_wins():
    """The subtle case the protocol is built around: A's lease expires
    and the shard is re-leased to B while A's commit is in flight — A's
    commit still wins (the work is done, deterministic output makes
    re-doing it pointless) and B's later commit is the duplicate."""
    clock = FakeClock()
    events = []
    table = LeaseTable(1, ttl_s=2.0, clock=clock,
                       on_event=lambda e, **f: events.append((e, f)))
    table.acquire("A", "tokA")
    clock.advance(3.0)  # A's lease is past its deadline
    assert table.reclaim_expired() == [0]
    gB = table.acquire("B", "tokB")
    assert gB["shard"] == 0 and gB["attempt"] == 2
    # A's in-flight commit lands with its EXPIRED token: first commit wins
    assert table.commit(0, "tokA", {"rows": 5}, worker="A") == "accept"
    # B, the current leaseholder, arrives second: duplicate, discarded
    assert table.commit(0, "tokB", {"rows": 5}, worker="B") == "duplicate"
    assert table.done()
    counts = table.counts()
    assert counts["duplicates"] == 1 and counts["expiries"] == 1
    committed = table.committed()
    assert committed[0]["rows"] == 5
    names = [e for e, _ in events]
    assert names == ["lease_grant", "lease_expire", "lease_reclaim",
                     "lease_grant", "shard_commit",
                     "shard_discarded_duplicate"]


def test_double_reclaim_is_noop():
    clock = FakeClock()
    table = LeaseTable(1, ttl_s=2.0, clock=clock)
    table.acquire("A", "tokA")
    clock.advance(3.0)
    assert table.reclaim_expired() == [0]
    reclaims = table.counts()["reclaims"]
    # second tick (a racing driver, a slow thread): shard already
    # PENDING — nothing to reclaim, counters untouched
    assert table.reclaim_expired() == []
    assert table.counts()["reclaims"] == reclaims
    # reopen of a non-committed shard is equally a no-op
    table.reopen(0)
    assert table.counts()["reclaims"] == reclaims
    assert table.snapshot()[0]["state"] == PENDING


def test_renewal_racing_shutdown_sees_clean_refusal():
    clock = FakeClock()
    table = LeaseTable(2, ttl_s=10.0, clock=clock)
    table.acquire("A", "tokA")  # shard 0
    gB = table.acquire("B", "tokB")
    assert table.commit(gB["shard"], "tokB", {"rows": 1}) == "accept"
    table.close()
    # every mutation refuses — never hangs, never spuriously grants
    assert table.renew(0, "tokA") is False
    assert table.acquire("C", "tokC") is None
    assert table.reclaim_expired() == []
    # an uncommitted shard racing shutdown gets "closed": the worker
    # must NOT publish unarbitrated output
    assert table.commit(0, "tokA", {"rows": 1}) == "closed"
    # but a genuinely-committed shard still answers duplicate (truth
    # about the past survives the shutdown)
    assert table.commit(1, "tok-late", {"rows": 1}) == "duplicate"


def test_speculation_steals_longest_running_lease():
    clock = FakeClock()
    events = []
    table = LeaseTable(2, ttl_s=100.0, clock=clock, speculate_factor=2.0,
                       on_event=lambda e, **f: events.append((e, f)))
    # shard 0 commits in 1s: the median-duration baseline
    g0 = table.acquire("fast", "tok0")
    clock.advance(1.0)
    assert table.commit(g0["shard"], "tok0", {"rows": 1}) == "accept"
    # shard 1 drags: 3s > 2.0 x median(1s) — an idle worker's acquire
    # steals it even though the ttl (100s) is nowhere near expiry
    table.acquire("slow", "tok1")
    clock.advance(3.0)
    g = table.acquire("fast", "tok2")
    assert g is not None and g["shard"] == 1 and g["attempt"] == 2
    assert table.counts()["speculative_reclaims"] == 1
    assert table.counts()["expiries"] == 0  # speculation is not expiry
    # the straggler's commit arrives later: duplicate only if the fast
    # worker already committed; here it races first and wins
    assert table.commit(1, "tok2", {"rows": 1}) == "accept"
    assert table.commit(1, "tok1", {"rows": 1}) == "duplicate"


def test_preload_committed_resume_state():
    """Resume-from-partial: a fresh table preloaded from verified
    on-disk sidecars must grant only the missing shards."""
    table = LeaseTable(3, ttl_s=10.0)
    table.preload_committed(0, {"token": "old0", "rows": 7, "worker": "w"})
    table.preload_committed(2, {"token": "old2", "rows": 9, "worker": "w"})
    g = table.acquire("fresh", "tokX")
    assert g["shard"] == 1  # the only non-committed shard
    assert table.commit(1, "tokX", {"rows": 4}) == "accept"
    assert table.done()
    committed = table.committed()
    assert {s: m["rows"] for s, m in committed.items()} == {0: 7, 1: 4, 2: 9}
    # a late commit against a preloaded shard is a duplicate
    assert table.commit(0, "tok-late", {"rows": 7}) == "duplicate"


# ------------------------------------------------------------ shard plan


def test_plan_is_deterministic_and_persists(tmp_path):
    data = str(tmp_path / "in")
    _write_inputs(data, 3, 5)
    out = str(tmp_path / "out")
    os.makedirs(out)
    specs = plan_mod.build_plan(data)
    assert [s.shard for s in specs] == [0, 1, 2]
    assert specs == plan_mod.build_plan(data)  # pure function of listing
    assert [os.path.basename(s.paths[0]) for s in specs] == sorted(
        os.path.basename(p) for s in specs for p in s.paths)
    doc = plan_mod.plan_doc(specs, input_dir=data, tenants=["a", "b"])
    plan_mod.save_plan(out, doc)
    assert plan_mod.load_plan(out) == doc
    assert plan_mod.specs_from_doc(doc) == specs
    # _PLAN.json is metadata, not data: listings must not see it
    assert plan_mod.PLAN_FILE not in [
        os.path.basename(p) for p in splitter.list_data_files(out)]
    # a torn plan file reads as None (driver re-plans)
    path = os.path.join(out, plan_mod.PLAN_FILE)
    payload = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(payload[: len(payload) // 2])
    assert plan_mod.load_plan(out) is None
    assert plan_mod.load_plan(str(tmp_path / "missing")) is None


def test_plan_size_aware_grouping_under_cap(tmp_path):
    data = str(tmp_path / "in")
    _write_inputs(data, 6, 4)
    specs = plan_mod.build_plan(data, max_shards=2)
    assert len(specs) == 2
    all_paths = [p for s in specs for p in s.paths]
    assert sorted(all_paths) == sorted(splitter.list_data_files(data))


# ------------------------------------------------------- commit protocol


def test_stage_publish_verify_roundtrip(tmp_path):
    out = str(tmp_path)
    payload = b"0.1|0.2\n0.3|0.4\n"
    committer.stage(out, 3, "leaseX", payload)
    # staged attempts are dot-prefixed: invisible to data listings
    assert splitter.list_data_files(out) == []
    manifest = committer.shard_manifest(3, "leaseX", "w0", payload, 2,
                                        ["a", "b"], ["in.psv"])
    committer.publish(out, 3, "leaseX", manifest)
    got = committer.verify_shard(out, 3)
    assert got is not None and got["token"] == "leaseX" and got["rows"] == 2
    assert committer.scan_committed(out, 8) == {3: got}
    # tampered data fails its sidecar digest: not counted committed
    with open(committer.shard_file(out, 3), "ab") as f:
        f.write(b"junk\n")
    assert committer.verify_shard(out, 3) is None
    assert committer.scan_committed(out, 8) == {}


def test_torn_stage_is_invisible_and_swept(tmp_path):
    out = str(tmp_path)
    payload = b"x" * 64
    faults.set_plan(faults.FaultPlan.parse("score.commit:torn-write@2",
                                           seed=5))
    committer.stage(out, 0, "l0", payload)  # 1st check: no fire
    with pytest.raises(faults.InjectedTornWrite) as ei:
        committer.stage(out, 1, "l1", payload)  # at-step 2: tears
    assert 1 <= ei.value.cut < len(payload)
    torn = committer.tmp_file(out, 1, "l1")
    assert os.path.exists(torn)  # the prefix genuinely persisted
    assert os.path.getsize(torn) == ei.value.cut
    assert splitter.list_data_files(out) == []  # readers never see it
    assert committer.verify_shard(out, 1) is None
    assert committer.sweep_tmp(out) == 2  # both attempts removed
    assert committer.sweep_tmp(out) == 0


def test_success_seal_and_job_doc(tmp_path):
    out = str(tmp_path)
    assert committer.read_success(out) is None
    plan_doc = {"input_dir": "/in", "tenants": ["a"],
                "shards": [{"shard": 0}, {"shard": 1}]}
    committed = {
        1: {"token": "t1", "worker": "w", "rows": 4, "data": {"crc": 1}},
        0: {"token": "t0", "worker": "w", "rows": 3, "data": {"crc": 2}},
    }
    doc = committer.job_doc(plan_doc, committed)
    assert doc["total_rows"] == 7
    assert [s["shard"] for s in doc["shards"]] == [0, 1]
    committer.write_success(out, doc)
    got = committer.read_success(out)
    assert got is not None and got["total_rows"] == 7
    assert got["schema"] == committer.JOB_SCHEMA


# ------------------------------------------------- fault seams (satellite)


def test_torn_write_kind_parse_and_at_step_determinism():
    plan = faults.FaultPlan.parse("x.commit:torn-write@2", seed=9)
    faults.set_plan(plan)
    assert faults.torn_cut("x.commit", 100) is None  # 1st check
    cut = faults.torn_cut("x.commit", 100)  # at-step 2 fires
    assert cut is not None and 1 <= cut < 100
    assert faults.torn_cut("x.commit", 100) is None  # once only
    assert faults.torn_cut("other.site", 100) is None
    # same seed, same term → same cut: drills are reproducible
    faults.set_plan(faults.FaultPlan.parse("x.commit:torn-write@2", seed=9))
    faults.torn_cut("x.commit", 100)
    assert faults.torn_cut("x.commit", 100) == cut
    # torn-write never fires through the raising check() entry point
    faults.set_plan(faults.FaultPlan.parse("x.commit:torn-write@1.0",
                                           seed=9))
    faults.check("x.commit")  # must not raise
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("x:torn@1")  # unknown kind still rejected


def test_export_commit_torn_seam_leaves_no_manifest(tmp_path):
    """A torn export commit must leave an inadmissible bundle: the
    manifest is written LAST, so any earlier torn artifact means no
    manifest — verify-before-admit refuses the directory wholesale."""
    d = str(tmp_path / "bundle")
    t = Trainer(_model_config(), N_FEATURES, seed=3)
    faults.set_plan(faults.FaultPlan.parse("export.commit:torn-write@2",
                                           seed=4))
    with pytest.raises(faults.InjectedTornWrite):
        export_native_bundle(d, t.state.params, _model_config(), N_FEATURES)
    assert not os.path.exists(os.path.join(d, NATIVE_MANIFEST))


def test_checkpoint_commit_torn_seam_keeps_previous_epoch(tmp_path):
    from shifu_tensorflow_tpu.train.checkpoint import NpzCheckpointer

    t = Trainer(_model_config(), N_FEATURES, seed=3)
    with NpzCheckpointer(str(tmp_path)) as ckpt:
        ckpt.save(0, t.state)
        faults.set_plan(faults.FaultPlan.parse("ckpt.commit:torn-write@1.0",
                                               seed=6))
        with pytest.raises(faults.InjectedTornWrite):
            ckpt.save(1, t.state)
        faults.set_plan(None)
        # the torn generation never renamed into place: epoch 0 is still
        # the newest restorable one
        state, next_epoch = ckpt.restore_latest(t.state)
        assert state is not None and next_epoch == 1


def test_score_read_seam_is_named_by_global_shard(tmp_path):
    """The per-shard read seam carries the GLOBAL shard id: a plan
    targeting score.read.s3 hits the pipeline scanning shard 3 and no
    other prefix."""
    data = str(tmp_path)
    _write_inputs(data, 1, 6)
    paths = splitter.list_data_files(data)
    schema = score_schema(N_FEATURES)
    policy = retry_util.RetryPolicy(max_attempts=2, base_delay_s=0.001)
    faults.set_plan(faults.FaultPlan.parse("score.read.s3:503@1.0", seed=2))

    def drain(prefix: str, offset: int) -> int:
        pipe = ShardPipeline(paths, schema, n_readers=1, decode_workers=1,
                             block_rows=4, retry_policy=policy,
                             fault_site_prefix=prefix, shard_offset=offset)
        try:
            return sum(len(b) for b, _ in pipe.blocks())
        finally:
            pipe.close()

    with pytest.raises(Exception):
        drain("score", 3)  # site score.read.s3: the plan fires
    assert drain("score", 1) == 6  # different shard: untouched
    assert drain("ingest", 3) == 6  # training plane: untouched


# --------------------------------------------------- batch admission


def test_discover_and_admit_batch_tenants(models_dir, tmp_path):
    found = discover_bundles(models_dir)
    assert sorted(found) == ["alpha", "beta"]
    single = _bundle(str(tmp_path / "solo"), seed=5)
    assert discover_bundles(single) == {"default": single}
    with pytest.raises(ValueError, match="ghost"):
        admit_batch_tenants(models_dir, tenants=["alpha", "ghost"])
    stores = admit_batch_tenants(models_dir)
    try:
        assert sorted(stores) == ["alpha", "beta"]
        for store in stores.values():
            assert store.current().model.num_features == N_FEATURES
    finally:
        for store in stores.values():
            store.close()


# ------------------------------------------------------- end-to-end jobs


def _run_thread_job(input_dir: str, models_dir: str, out: str, stores,
                    **kw) -> dict:
    kw.setdefault("workers", 2)
    kw.setdefault("ttl_s", 5.0)
    kw.setdefault("speculate_factor", 0.0)
    kw.setdefault("batch_rows", 32)
    kw.setdefault("timeout_s", 120.0)
    return run_job(input_dir, models_dir, out, worker_mode="thread",
                   stores=stores, **kw)


def test_job_end_to_end_thread_mode_and_rerun_noop(models_dir, tmp_path):
    data = str(tmp_path / "in")
    total = _write_inputs(data, 4, 13)
    out = str(tmp_path / "out")
    journal = str(tmp_path / "journal.jsonl")
    obs_journal.install(obs_journal.Journal(journal, plane="score"))
    stores = admit_batch_tenants(models_dir)
    try:
        summary = _run_thread_job(data, models_dir, out, stores)
        assert summary["noop"] is False
        assert summary["rows"] == total and summary["shards"] == 4
        assert summary["duplicates"] == 0
        success = committer.read_success(out)
        assert success["total_rows"] == total
        tokens = [s["token"] for s in success["shards"]]
        assert len(set(tokens)) == 4  # one winning token per shard
        # every output row is |-joined per-tenant scores, sorted order
        lines = _blob(out).decode().strip().split("\n")
        assert len(lines) == total
        assert all(len(line.split("|")) == 2 for line in lines)
        # alpha and beta are different seeds: columns must differ
        a, b = zip(*(line.split("|") for line in lines))
        assert a != b
        # re-run of a sealed job: journaled no-op, output untouched
        before = _blob(out)
        again = _run_thread_job(data, models_dir, out, stores)
        assert again["noop"] is True and again["rows"] == total
        assert _blob(out) == before
    finally:
        for store in stores.values():
            store.close()
    obs_journal.uninstall()
    events = obs_journal.read_events(journal)
    names = [e["event"] for e in events]
    assert names.count("score_job_start") == 2
    assert names.count("score_job_finished") == 2
    assert names.count("shard_commit") == 4
    assert names.count("lease_grant") >= 4
    finished = [e for e in events if e["event"] == "score_job_finished"]
    assert finished[0]["rows"] == total and finished[1]["noop"] is True


def test_job_resumes_from_partial_success_less_state(models_dir, tmp_path):
    """Crash-resume: _SUCCESS missing, one shard's output gone, another's
    torn mid-byte — a fresh driver re-scores exactly those two from the
    persisted plan and leaves verified shards byte-identical."""
    data = str(tmp_path / "in")
    total = _write_inputs(data, 4, 9)
    out = str(tmp_path / "out")
    stores = admit_batch_tenants(models_dir)
    try:
        first = _run_thread_job(data, models_dir, out, stores)
        assert first["rows"] == total
        intact = {
            s: open(committer.shard_file(out, s), "rb").read()
            for s in (0, 3)
        }
        # simulate the crash window: job never sealed, shard 1 vanished,
        # shard 2 is a torn prefix of itself
        os.remove(os.path.join(out, committer.SUCCESS_FILE))
        os.remove(committer.shard_file(out, 1))
        os.remove(committer.sidecar_file(out, 1))
        p2 = committer.shard_file(out, 2)
        blob2 = open(p2, "rb").read()
        with open(p2, "wb") as f:
            f.write(blob2[: len(blob2) // 2])

        second = _run_thread_job(data, models_dir, out, stores)
        assert second["noop"] is False and second["rows"] == total
        # only the two broken shards were re-scored
        assert second["grants"] == 2
        assert committer.read_success(out)["total_rows"] == total
        for s, blob in intact.items():
            assert open(committer.shard_file(out, s), "rb").read() == blob
        assert open(p2, "rb").read() == blob2  # re-scored bit-identically
    finally:
        for store in stores.values():
            store.close()


def test_obs_score_reconstructs_job_from_journal(models_dir, tmp_path,
                                                 capsys):
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main

    data = str(tmp_path / "in")
    total = _write_inputs(data, 2, 6)
    out = str(tmp_path / "out")
    journal = str(tmp_path / "journal.jsonl")
    obs_journal.install(obs_journal.Journal(journal, plane="score"))
    stores = admit_batch_tenants(models_dir)
    try:
        _run_thread_job(data, models_dir, out, stores)
    finally:
        for store in stores.values():
            store.close()
    obs_journal.uninstall()

    assert obs_main(["score", "--journal", journal, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    jobs = doc["jobs"]
    assert len(jobs) == 1
    job = jobs[0]
    assert job["shards"] == 2
    assert len(job["committed"]) == 2
    assert job["committed_rows"] == total
    assert job["duplicate_committed_tokens"] == 0
    # the rendered (non-json) form also works on the same journal
    assert obs_main(["score", "--journal", journal]) == 0
    assert "score job" in capsys.readouterr().out


# -------------------------------------------- the acceptance kill drill


def test_kill_drill_process_mode_bit_identical_to_control(models_dir,
                                                          tmp_path):
    """ISSUE 17 acceptance: SIGKILL a scorer process mid-shard while a
    torn-write plan tears a peer's commit — the job still seals with
    output BIT-IDENTICAL to an unkilled control arm, zero duplicate
    tokens and zero missing rows by row audit, and a re-run is a
    journaled no-op."""
    data = str(tmp_path / "in")
    total = _write_inputs(data, 8, 40)
    out_control = str(tmp_path / "control")
    out_drill = str(tmp_path / "drill")
    journal = str(tmp_path / "journal.jsonl")

    # control arm: thread mode, no faults, no kill
    stores = admit_batch_tenants(models_dir)
    try:
        control = _run_thread_job(data, models_dir, out_control, stores)
    finally:
        for store in stores.values():
            store.close()
    assert control["rows"] == total

    # drill arm: REAL scorer processes; every read check drags 300ms so
    # the SIGKILL provably lands mid-shard, and the 3rd commit stage in
    # one process tears (the at-step term fires once per process)
    obs_journal.install(obs_journal.Journal(journal, plane="score"))
    procs: dict = {}
    killed = threading.Event()

    def scorer0_holds_live_lease() -> bool:
        try:
            events = obs_journal.read_events(journal)
        except OSError:
            return False
        held = None
        for e in events:
            kind = e.get("event")
            if (kind == "lease_grant"
                    and str(e.get("worker", "")).startswith("scorer-0")):
                held = e.get("shard")
            elif (kind in ("shard_commit", "lease_reclaim")
                    and e.get("shard") == held):
                held = None
        return held is not None

    def killer():
        # kill only once scorer-0 PROVABLY owns an uncommitted lease —
        # then the SIGKILL must cost an expiry + reclaim, not a no-op
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if not scorer0_holds_live_lease():
                time.sleep(0.05)
                continue
            time.sleep(0.7)  # mid-scan: every read check drags 300ms
            p = procs.get("scorer-0")
            if p is None or p.poll() is not None:
                return
            if not scorer0_holds_live_lease():
                continue  # committed in the window — wait for the next
            p.send_signal(signal.SIGKILL)
            killed.set()
            return

    t = threading.Thread(target=killer, daemon=True)
    t.start()
    drill = run_job(
        data, models_dir, out_drill,
        workers=2, ttl_s=1.5, speculate_factor=4.0, batch_rows=32,
        worker_mode="process", timeout_s=240.0,
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "STPU_FAULT_PLAN":
                "score.read:slow300@1.0,score.commit:torn-write@3",
            "STPU_FAULT_SEED": "11",
        },
        on_spawn=lambda wid, p: procs.__setitem__(wid, p),
    )
    t.join(timeout=10.0)
    obs_journal.uninstall()

    assert killed.is_set(), "the kill never landed — drill proved nothing"
    assert drill["rows"] == total, "missing or extra rows after the kill"
    assert drill["shards"] == 8
    # exactly-once by token audit: one winning token per shard, no dupes
    success = committer.read_success(out_drill)
    tokens = [s["token"] for s in success["shards"]]
    assert len(tokens) == 8 and len(set(tokens)) == 8
    # the kill was detected and the shard re-dispatched
    assert drill["reclaims"] >= 1
    # deterministic scoring: kill arm output is bit-identical to control
    assert _blob(out_drill) == _blob(out_control)
    # no staged/torn debris survives the finalize sweep
    assert not [n for n in os.listdir(out_drill) if n.endswith(".tmp")]
    # the journal tells the whole story in causal order
    events = obs_journal.read_events(journal)
    names = [e["event"] for e in events]
    assert "lease_expire" in names and "lease_reclaim" in names
    assert names.index("lease_expire") < names.index("lease_reclaim")
    assert names.count("shard_commit") == 8
    # re-run of the sealed drill output: journaled no-op
    rerun = run_job(data, models_dir, out_drill, workers=1,
                    worker_mode="thread", stores=None, timeout_s=60.0)
    assert rerun["noop"] is True and rerun["rows"] == total
