"""Environment-capability gates for tests, shared across files.

The cross-process SPMD drills (test_spmd, test_cli multi-worker,
test_convergence, test_eval_cli fleet, test_netns_spmd) need
CROSS-PROCESS collectives on the CPU backend: each worker is its own
jax process and gradients all-reduce over loopback.  jaxlib 0.4.x's CPU
PJRT client cannot form them — the fleets hang or fail inside
jax.distributed initialization, not in framework code (known-broken at
seed, CHANGES.md PR 2).  Skipping with this explicit reason makes
tier-1 output distinguish "environment can't run this" from a real
regression, and stops the broken fleets from burning the suite's
wall-clock budget on doomed subprocess timeouts.

In-process SPMD (the conftest's 8-device virtual CPU mesh) is
unaffected and runs everywhere.
"""

from __future__ import annotations

import os

import jaxlib
import pytest

JAXLIB_VERSION = tuple(
    int(p) for p in jaxlib.__version__.split(".")[:3]
)

needs_multiprocess_collectives = pytest.mark.skipif(
    JAXLIB_VERSION < (0, 5, 0),
    reason=(
        "jaxlib %s CPU backend lacks multiprocess collectives "
        "(known-broken at seed, see CHANGES.md PR 2); needs jaxlib>=0.5"
        % jaxlib.__version__
    ),
)

# The ssh-launcher drills additionally bind the jax coordination service
# to this machine's non-loopback interface — on top of the cross-process
# collective requirement, the containerized CI network cannot route
# worker<->chief traffic over it (verified failing identically on a
# pristine seed checkout, PR 4 notes).  That network limitation is
# INDEPENDENT of the jaxlib version, so a jaxlib bump alone must not
# lift the skip into a guaranteed environment failure: these tests run
# only when jaxlib has the collectives AND the operator asserts the
# network can route the non-loopback plane by setting
# STPU_NONLOOPBACK_SPMD_TESTS=1.  Tier-1 then reads
# green-or-real-regression instead of known-red.
needs_nonloopback_spmd = pytest.mark.skipif(
    JAXLIB_VERSION < (0, 5, 0)
    or not os.environ.get("STPU_NONLOOPBACK_SPMD_TESTS"),
    reason=(
        "non-loopback cross-process SPMD: needs jaxlib>=0.5 "
        "multiprocess collectives (have %s) AND a network that routes "
        "the non-loopback coordination plane — opt in with "
        "STPU_NONLOOPBACK_SPMD_TESTS=1 (container failure pre-existing "
        "at seed, see CHANGES.md PR 4)"
        % jaxlib.__version__
    ),
)
