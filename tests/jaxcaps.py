"""Environment-capability gates for tests, shared across files.

The cross-process SPMD drills (test_spmd, test_cli multi-worker,
test_convergence, test_eval_cli fleet, test_netns_spmd) need
CROSS-PROCESS collectives on the CPU backend: each worker is its own
jax process and gradients all-reduce over loopback.  jaxlib 0.4.x's CPU
PJRT client cannot form them — the fleets hang or fail inside
jax.distributed initialization, not in framework code (known-broken at
seed, CHANGES.md PR 2).  Skipping with this explicit reason makes
tier-1 output distinguish "environment can't run this" from a real
regression, and stops the broken fleets from burning the suite's
wall-clock budget on doomed subprocess timeouts.

In-process SPMD (the conftest's 8-device virtual CPU mesh) is
unaffected and runs everywhere.
"""

from __future__ import annotations

import jaxlib
import pytest

JAXLIB_VERSION = tuple(
    int(p) for p in jaxlib.__version__.split(".")[:3]
)

needs_multiprocess_collectives = pytest.mark.skipif(
    JAXLIB_VERSION < (0, 5, 0),
    reason=(
        "jaxlib %s CPU backend lacks multiprocess collectives "
        "(known-broken at seed, see CHANGES.md PR 2); needs jaxlib>=0.5"
        % jaxlib.__version__
    ),
)
