"""Coordinator/control-plane tests — the multi-worker single-host harness
SURVEY.md §4 item 2 calls for: registration barrier, sticky shard
assignment, heartbeat liveness, metrics quorum aggregation, chief
short-circuit, fault-injected recovery via checkpoint-restart."""

import threading
import time

import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.coordinator.coordinator import (
    Coordinator,
    CoordinatorClient,
    JobSpec,
    JobState,
)
from shifu_tensorflow_tpu.coordinator.heartbeat import LivenessMonitor
from shifu_tensorflow_tpu.coordinator.metrics_board import EpochAggregator
from shifu_tensorflow_tpu.coordinator.submitter import JobSubmitter, make_job_spec
from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.data.splitter import Shard
from shifu_tensorflow_tpu.train.trainer import EpochStats


def _stats(worker, epoch, loss=0.5):
    return EpochStats(
        worker_index=worker, current_epoch=epoch, training_loss=loss,
        valid_loss=loss, training_time_s=1.0 + worker, valid_time_s=0.1,
        global_step=epoch + 1,
    )


def _spec(n=2, **kw):
    shards = [Shard(i, (f"/data/part-{i}",), 1) for i in range(n)]
    kw.setdefault("registration_timeout_s", 5.0)
    return JobSpec(n_workers=n, shards=shards, epochs=2, **kw)


# ---- liveness ----

def test_liveness_expiry_and_recovery():
    now = [0.0]
    expired = []
    mon = LivenessMonitor(interval_ms=1000, max_missed=3,
                          on_expired=expired.append, clock=lambda: now[0])
    mon.register("w0")
    mon.register("w1")
    now[0] = 2.0
    mon.beat("w0")
    now[0] = 4.0  # w1 last beat at 0, deadline 3s -> expired
    assert mon.check() == ["w1"]
    assert expired == ["w1"]
    assert mon.alive() == {"w0"}
    # re-registration clears expiry (restart case)
    mon.register("w1")
    assert mon.alive() == {"w0", "w1"}


def test_liveness_unknown_beat_ignored():
    mon = LivenessMonitor()
    mon.beat("ghost")  # must not implicitly register
    assert mon.alive() == set()


# ---- metrics aggregation ----

def test_epoch_aggregator_quorum(tmp_path):
    board = tmp_path / "board.log"
    agg = EpochAggregator(2, board_path=str(board))
    assert agg.report(_stats(0, 0, 0.4)) is None
    assert agg.pending_epochs() == {0: 1}
    summary = agg.report(_stats(1, 0, 0.6))
    assert summary is not None
    assert summary.mean_training_loss == pytest.approx(0.5)
    assert summary.slowest_worker == 1  # training_time = 1 + worker_index
    assert "epoch 0" in board.read_text()
    # duplicate/stale report does not re-publish
    assert agg.report(_stats(0, 0, 0.9)) is None
    assert len(agg.summaries) == 1


def test_epoch_aggregator_out_of_order_epochs():
    agg = EpochAggregator(2)
    # worker 1 races ahead to epoch 1 before worker 0 finishes epoch 0
    agg.report(_stats(1, 1))
    agg.report(_stats(0, 0))
    agg.report(_stats(1, 0))  # completes epoch 0
    agg.report(_stats(0, 1))  # completes epoch 1
    assert [s.epoch for s in agg.summaries] == [0, 1]


# ---- coordinator state machine over TCP ----

def test_register_barrier_and_sticky_assignment():
    coord = Coordinator(_spec(2))
    host, port = coord.serve()
    try:
        c = CoordinatorClient(host, port)
        r0 = c.register("a")
        assert r0["ok"] and r0["worker_index"] == 0
        assert r0["state"] == JobState.REGISTERING.value
        assert coord.status()["registered"] == 1

        r1 = c.register("b")
        assert r1["worker_index"] == 1
        assert r1["state"] == JobState.TRAINING.value
        assert c.await_start()["ok"]

        # re-registration (restart) keeps index + shard
        r0b = c.register("a")
        assert r0b["worker_index"] == 0
        assert r0b["shard"] == r0["shard"]

        # third distinct worker rejected
        assert not c.register("c")["ok"]
    finally:
        coord.shutdown()


def test_register_pinned_index_is_deterministic():
    """Chief identity must not depend on registration order: a worker that
    pins index 0 gets it even when it registers last, and a conflicting pin
    is rejected rather than silently reassigned."""
    coord = Coordinator(_spec(3))
    host, port = coord.serve()
    try:
        c = CoordinatorClient(host, port)
        r2 = c.register("w2", worker_index=2)
        assert r2["ok"] and r2["worker_index"] == 2
        # unpinned registration takes the lowest free slot (1 is still free)
        ru = c.register("wu")
        assert ru["ok"] and ru["worker_index"] == 0
        r0 = c.register("w0", worker_index=1)
        assert r0["ok"] and r0["worker_index"] == 1
        # conflicting pin from a distinct worker is an error
        assert not c.register("dup", worker_index=2)["ok"]
        assert not c.register("oob", worker_index=3)["ok"]
    finally:
        coord.shutdown()


def test_registration_timeout_fails_job():
    coord = Coordinator(_spec(2, registration_timeout_s=0.3))
    host, port = coord.serve()
    try:
        c = CoordinatorClient(host, port)
        c.register("only-one")
        resp = c.await_start()
        assert not resp["ok"]
        assert "registration timeout" in resp["error"]
        assert coord.state == JobState.FAILED
    finally:
        coord.shutdown()


def test_chief_failure_short_circuits():
    coord = Coordinator(_spec(2))
    host, port = coord.serve()
    try:
        c = CoordinatorClient(host, port)
        c.register("a")  # index 0 = chief
        c.register("b")
        c.complete("a", exit_code=1)
        assert coord.state == JobState.FAILED
        assert "chief" in coord.failure_reason
    finally:
        coord.shutdown()


def test_chief_success_finishes_job():
    coord = Coordinator(_spec(2))
    host, port = coord.serve()
    try:
        c = CoordinatorClient(host, port)
        c.register("a")
        c.register("b")
        c.complete("a", exit_code=0)
        assert coord.state == JobState.FINISHED
    finally:
        coord.shutdown()


def test_non_chief_failure_within_budget_restartable():
    coord = Coordinator(_spec(3, spare_restarts=1))
    host, port = coord.serve()
    try:
        c = CoordinatorClient(host, port)
        for wid in ("a", "b", "c"):
            c.register(wid)
        c.complete("b", exit_code=7)
        assert coord.state == JobState.TRAINING  # tolerated
        restartable = coord.restartable_workers()
        assert [r.worker_id for r in restartable] == ["b"]
        # budget: floor(0.1*3) + 1 spare = 1 -> second failure fails the job
        c.complete("c", exit_code=7)
        assert coord.state == JobState.FAILED
        assert "budget" in coord.failure_reason
    finally:
        coord.shutdown()


def test_malformed_request_does_not_kill_server():
    coord = Coordinator(_spec(1))
    host, port = coord.serve()
    try:
        import json
        import socket

        with socket.create_connection((host, port)) as s:
            f = s.makefile("rwb")
            f.write(b"this is not json\n")
            f.flush()
            resp = json.loads(f.readline())
            assert not resp["ok"]
        # server still serves
        c = CoordinatorClient(host, port)
        assert c.status()["ok"]
    finally:
        coord.shutdown()


# ---- end-to-end job with real training + fault injection ----

def _worker_config_factory(psv_dataset, model_config, tmp_path):
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )

    def make(worker_id, addr):
        return WorkerConfig(
            worker_id=worker_id,
            coordinator_host=addr[0],
            coordinator_port=addr[1],
            model_config=model_config,
            schema=schema,
            batch_size=100,
            checkpoint_dir=str(tmp_path / "job-ckpt"),
            heartbeat_interval_s=0.1,
        )

    return make


@pytest.fixture
def job_model_config():
    return ModelConfig.from_json(
        {"train": {"numTrainEpochs": 2, "validSetRate": 0.2,
                   "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"], "LearningRate": 0.05,
                              "Optimizer": "adam"}}}
    )


def test_submitter_end_to_end_success(psv_dataset, tmp_path, job_model_config):
    spec = make_job_spec(psv_dataset["root"], 2, epochs=2,
                         registration_timeout_s=10.0)
    sub = JobSubmitter(
        spec, _worker_config_factory(psv_dataset, job_model_config, tmp_path)
    )
    result = sub.run(timeout_s=120.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    assert result.restarts_used == 0
    # both epochs aggregated across both workers
    assert [s.epoch for s in result.epoch_summaries] == [0, 1]
    assert all(s.n_workers == 2 for s in result.epoch_summaries)


def test_submitter_recovers_injected_worker_fault(
    psv_dataset, tmp_path, job_model_config
):
    """A non-chief worker dies mid-job; the submitter relaunches it and the
    job completes — checkpoint-restart recovery semantics (SURVEY.md §5.3
    replacement)."""
    # sync_epochs makes recovery deterministic: the chief holds at the
    # epoch-0 barrier until the relaunched worker-1 catches up, so the job
    # cannot finish before the failure is processed
    spec = make_job_spec(psv_dataset["root"], 2, epochs=2,
                         registration_timeout_s=10.0, spare_restarts=1,
                         sync_epochs=True, epoch_barrier_timeout_s=60.0)
    sub = JobSubmitter(
        spec,
        _worker_config_factory(psv_dataset, job_model_config, tmp_path),
        fault_injections={"worker-1": 0},  # dies at epoch 0 on first launch
    )
    result = sub.run(timeout_s=120.0)
    assert result.state == JobState.FINISHED, result.failure_reason
    assert result.restarts_used == 1
    # with the barrier, every epoch reaches full quorum
    assert [s.epoch for s in result.epoch_summaries] == [0, 1]


def test_submitter_chief_fault_fails_job(psv_dataset, tmp_path, job_model_config):
    spec = make_job_spec(psv_dataset["root"], 2, epochs=2,
                         registration_timeout_s=10.0, spare_restarts=5)
    sub = JobSubmitter(
        spec,
        _worker_config_factory(psv_dataset, job_model_config, tmp_path),
        fault_injections={"worker-0": 1},  # chief dies
    )
    result = sub.run(timeout_s=120.0)
    assert result.state == JobState.FAILED
    assert "chief" in result.failure_reason


def _es_stats(worker, epoch, ks):
    return dict(
        worker_index=worker, current_epoch=epoch, training_loss=0.4,
        valid_loss=0.4, training_time_s=1.0, valid_time_s=0.1,
        global_step=epoch + 1, ks=ks, auc=0.5,
    )


def test_coordinator_fleet_early_stop_via_barrier():
    """Fleet early stopping (non-SPMD): criteria evaluate only on FULL-
    quorum epochs, judge the CHIEF's stats (only the chief's model is
    exported — a fleet mean could clear the target while the exported
    model is below it), and the decision appears in the epoch barrier
    reply — the same value for every worker."""
    with pytest.raises(ValueError, match="sync_epochs"):
        Coordinator(_spec(n=2, early_stop_ks=0.5))  # barrier is mandatory
    spec = _spec(n=2, early_stop_ks=0.5, sync_epochs=True)
    coord = Coordinator(spec)
    coord.register("a", 0, host="127.0.0.1")
    coord.register("b", 1, host="127.0.0.1")

    # epoch 0: chief ks 0.3 < 0.5 -> no stop (peer at 0.9 is irrelevant:
    # its independently trained model is not the one exported)
    coord.report_epoch(_es_stats(0, 0, 0.3))
    coord.report_epoch(_es_stats(1, 0, 0.9))
    r = coord.epoch_barrier("a", 0, timeout_s=5.0)
    assert r["ok"] and "stop_after_epoch" not in r
    # partial quorum never triggers, even past the target
    coord.report_epoch(_es_stats(0, 1, 0.9))
    r = coord.epoch_barrier("a", 0, timeout_s=5.0)
    assert "stop_after_epoch" not in r
    # epoch 1 quorum completes with chief ks 0.9 >= 0.5 -> stop after 1,
    # visible identically to both workers
    coord.report_epoch(_es_stats(1, 1, 0.2))
    ra = coord.epoch_barrier("a", 1, timeout_s=5.0)
    rb = coord.epoch_barrier("b", 1, timeout_s=5.0)
    assert ra["stop_after_epoch"] == 1 == rb["stop_after_epoch"]
    assert "KS" in ra["stop_reason"]
    assert coord.stop_reason == ra["stop_reason"]
    coord.shutdown()


def test_coordinator_spmd_early_stop_uses_quorum_mean():
    """SPMD trains ONE model: shard-local KS differ only by shard, so the
    quorum mean is the fair estimate the criteria judge."""
    spec = _spec(n=2, early_stop_ks=0.5, sync_epochs=True, spmd=True)
    coord = Coordinator(spec)
    coord.register("a", 0, host="127.0.0.1", jax_port=9999)
    coord.register("b", 1, host="127.0.0.1")
    # chief alone below target, but mean (0.4+0.8)/2 >= 0.5 -> stop
    coord.report_epoch(_es_stats(0, 0, 0.4))
    coord.report_epoch(_es_stats(1, 0, 0.8))
    r = coord.epoch_barrier("a", 0, timeout_s=5.0)
    assert r["stop_after_epoch"] == 0
    coord.shutdown()


def test_epoch_aggregator_partial_flush_on_resume_hole():
    # worker 1 died before reporting epoch 0; after restart it resumed at
    # epoch 1 — epoch 0 must flush with partial quorum when epoch 1 closes
    agg = EpochAggregator(2)
    agg.report(_stats(0, 0))          # only worker 0 reports epoch 0
    agg.report(_stats(0, 1))
    summary = agg.report(_stats(1, 1))  # epoch 1 completes
    assert summary is not None and summary.epoch == 1
    assert [s.epoch for s in agg.summaries] == [0, 1]
    assert agg.summaries[0].n_workers == 1  # partial quorum recorded
    assert agg.pending_epochs() == {}


def test_hung_worker_is_restartable():
    spec = _spec(3, spare_restarts=1)
    coord = Coordinator(spec)
    host, port = coord.serve()
    try:
        c = CoordinatorClient(host, port)
        for wid in ("a", "b", "c"):
            c.register(wid)
        # "b" hangs: no heartbeat, no complete. Force liveness expiry.
        coord.liveness._last["b"] -= coord.liveness.deadline_s + 1
        coord.liveness.check()
        restartable = coord.restartable_workers()
        assert [r.worker_id for r in restartable] == ["b"]
        assert coord.state == JobState.TRAINING  # within budget
    finally:
        coord.shutdown()


def test_await_start_short_probe_does_not_kill_job():
    coord = Coordinator(_spec(2, registration_timeout_s=30.0))
    host, port = coord.serve()
    try:
        c = CoordinatorClient(host, port)
        c.register("a")  # 1 of 2 — still registering
        resp = c.await_start(timeout_s=0.1)
        assert not resp["ok"] and resp.get("retryable")
        assert coord.state == JobState.REGISTERING  # job unharmed
    finally:
        coord.shutdown()


def test_fail_is_noop_after_terminal_state():
    """A FINISHED job must stay FINISHED even if a late timeout path calls
    _fail (the submitter's poll loop can race the chief's completion), and
    the first failure reason is never overwritten."""
    coord = Coordinator(_spec(1))
    host, port = coord.serve()
    try:
        c = CoordinatorClient(host, port)
        c.register("a")
        c.complete("a", exit_code=0)
        assert coord.state == JobState.FINISHED
        coord._fail("job timeout after 60s")
        assert coord.state == JobState.FINISHED
        assert coord.failure_reason is None
    finally:
        coord.shutdown()


def test_abort_exit_codes_do_not_mask_failure_reason():
    coord = Coordinator(_spec(3, spare_restarts=0))
    host, port = coord.serve()
    try:
        c = CoordinatorClient(host, port)
        for wid in ("a", "b", "c"):
            c.register(wid)
        c.complete("b", exit_code=7)  # budget 0 -> job fails
        assert coord.state == JobState.FAILED
        reason = coord.failure_reason
        # chief aborts cooperatively afterwards; reason must be preserved
        c.complete("a", exit_code=42)
        assert coord.failure_reason == reason
        assert "budget" in coord.failure_reason
    finally:
        coord.shutdown()


def test_worker_config_json_roundtrip_new_fields(job_model_config, psv_dataset):
    """scan_steps / async_checkpoint survive the subprocess JSON transport,
    and configs serialized before these fields existed still load (defaults
    apply)."""
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    cfg = WorkerConfig(
        worker_id="w0", coordinator_host="127.0.0.1", coordinator_port=1,
        model_config=job_model_config, schema=schema,
        scan_steps=8, async_checkpoint=True,
    )
    back = WorkerConfig.from_json(cfg.to_json())
    assert back.scan_steps == 8 and back.async_checkpoint is True

    legacy = cfg.to_json()
    del legacy["scan_steps"], legacy["async_checkpoint"]
    old = WorkerConfig.from_json(legacy)
    assert old.scan_steps == 1 and old.async_checkpoint is False


# ---- liveness flap recovery (expiry is not terminal) ----

def test_liveness_flap_recovery_and_callback():
    """A worker marked expired that beats again must recover into
    alive(), fire on_recovered, and count the flap — a long compile/GC
    pause must not permanently shrink the fleet."""
    now = [0.0]
    expired, recovered = [], []
    mon = LivenessMonitor(
        interval_ms=1000, max_missed=3,
        on_expired=expired.append, on_recovered=recovered.append,
        clock=lambda: now[0],
    )
    mon.register("w0")
    now[0] = 4.0  # deadline 3s -> expired
    assert mon.check() == ["w0"]
    assert mon.alive() == set() and mon.expired() == {"w0"}
    mon.beat("w0")  # the pause ended
    assert mon.alive() == {"w0"}
    assert mon.expired() == set()
    assert recovered == ["w0"]
    assert mon.flaps == 1
    # expiry fires again if the silence repeats (not a one-way latch)
    now[0] = 9.0
    assert mon.check() == ["w0"]
    mon.beat("w0")
    assert mon.flaps == 2
    # ages() reports seconds since last beat (diagnostics surface)
    now[0] = 10.5
    assert mon.ages() == {"w0": pytest.approx(1.5)}


def test_liveness_unregister_clears_flap_candidates():
    mon = LivenessMonitor()
    mon.register("w")
    mon.unregister("w")
    mon.beat("w")  # must not resurrect an unregistered worker
    assert mon.alive() == set()
    assert mon.flaps == 0


# ---- health rollback arbitration ----

def test_unhealthy_spmd_rollback_directive_rides_registration():
    spec = _spec(2, spmd=True, spare_restarts=5, health_max_rollbacks=3,
                 health_lr_backoff=0.5, health_skip_window=2)
    coord = Coordinator(spec)
    coord.register("a", 0, host="h", jax_port=1)
    coord.register("b", 1, host="h")
    gen0 = coord.generation
    r = coord.report_unhealthy("a", 2, "nan loss", bad_steps=[5])
    assert r["ok"] and r["fleet"]
    assert coord.generation == gen0 + 1  # fleet restart
    # a peer reporting the same root cause is deduped by generation
    r2 = coord.report_unhealthy("b", 2, "nan loss", bad_steps=[5])
    assert r2.get("deduped")
    # re-registration delivers the directive: backed-off LR + the skip
    # window around the offending step (width 2 -> steps 4 and 5)
    reg = coord.register("a", 0, host="h", jax_port=1)
    assert reg["health"]["lr_scale"] == pytest.approx(0.5)
    assert reg["health"]["skip"] == {"epoch": 2, "steps": [4, 5]}
    assert reg["health"]["rollbacks"] == 1
    st = coord.status()
    assert st["rollbacks"] == 1 and st["restarts_used"] == 1
    coord.liveness.stop()


def test_unhealthy_non_spmd_charges_budget_once_and_relaunches():
    from shifu_tensorflow_tpu.coordinator.coordinator import (
        UNHEALTHY_EXIT_CODE,
    )

    coord = Coordinator(_spec(3, spare_restarts=2))
    for i, wid in enumerate(("a", "b", "c")):
        coord.register(wid, i, host="h")
    r = coord.report_unhealthy("b", 1, "loss spike", bad_steps=[0])
    assert r["ok"] and not r["fleet"]
    assert coord.status()["restarts_used"] == 1
    # the worker exits UNHEALTHY_EXIT_CODE: no second budget charge, but
    # it becomes restartable
    coord.complete("b", UNHEALTHY_EXIT_CODE)
    assert coord.status()["restarts_used"] == 1
    assert [w.worker_id for w in coord.restartable_workers()] == ["b"]
    assert coord.state == JobState.TRAINING
    coord.liveness.stop()


def test_unhealthy_hung_worker_queued_for_kill():
    coord = Coordinator(_spec(2, spare_restarts=2))
    coord.register("a", 0, host="h")
    coord.register("b", 1, host="h")
    r = coord.report_unhealthy("b", 0, "hung step", hung=True)
    assert r["ok"]
    # the wedged worker cannot exit on its own: the submitter must kill
    # it — and ONLY once the kill is delivered does the record become
    # restartable, so a relaunch can never race ahead of the kill and
    # become its victim
    assert coord.take_pending_kills() == ["b"]
    assert coord.take_pending_kills() == []  # drained
    assert coord.restartable_workers() == []
    coord.mark_worker_killed("b")
    assert [w.worker_id for w in coord.restartable_workers()] == ["b"]
    coord.liveness.stop()


# ---- failure diagnostics (registration/job timeout paths) ----

def test_registration_timeout_result_carries_heartbeat_diagnostics():
    """The registration-timeout failure must hand the operator per-worker
    heartbeat ages + liveness state through JobResult.diagnostics, not
    just the bare timeout message."""
    import time as _time

    spec = _spec(2, registration_timeout_s=0.4)

    def never_registers(cfg, fail_at_epoch=None):
        _time.sleep(30.0)
        return 0

    sub = JobSubmitter(
        spec,
        lambda wid, addr: WorkerConfig(
            worker_id=wid, coordinator_host=addr[0],
            coordinator_port=addr[1], model_config=None, schema=None,
        ),
        worker_runner=never_registers,
        poll_interval_s=0.05,
    )
    result = sub.run(timeout_s=10.0)
    assert result.state == JobState.FAILED
    assert "registration timeout" in result.failure_reason
    assert result.diagnostics is not None
    assert "workers" in result.diagnostics
    assert result.diagnostics["restart_budget"] == spec.spare_restarts
    # nobody ever registered: the bundle says so instead of hiding it
    assert result.diagnostics["workers"] == {}


def test_job_timeout_failure_reason_includes_heartbeat_ages():
    import time as _time

    spec = _spec(1, registration_timeout_s=10.0)

    def registers_then_hangs(cfg, fail_at_epoch=None):
        from shifu_tensorflow_tpu.coordinator.coordinator import (
            CoordinatorClient,
        )

        c = CoordinatorClient(cfg.coordinator_host, cfg.coordinator_port)
        c.register(cfg.worker_id, cfg.worker_index)
        _time.sleep(30.0)
        return 0

    sub = JobSubmitter(
        spec,
        lambda wid, addr: WorkerConfig(
            worker_id=wid, coordinator_host=addr[0],
            coordinator_port=addr[1], model_config=None, schema=None,
            worker_index=0,
        ),
        worker_runner=registers_then_hangs,
        poll_interval_s=0.05,
    )
    result = sub.run(timeout_s=1.0)
    assert result.state == JobState.FAILED
    assert "job timeout" in result.failure_reason
    assert "last-heartbeat ages" in result.failure_reason
    assert result.diagnostics["workers"]["worker-0"]["liveness"] in (
        "alive", "expired")
    assert result.diagnostics["workers"]["worker-0"][
        "last_heartbeat_age_s"] is not None


def test_worker_config_json_roundtrip_health_fields(job_model_config,
                                                    psv_dataset):
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    cfg = WorkerConfig(
        worker_id="w0", coordinator_host="127.0.0.1", coordinator_port=1,
        model_config=job_model_config, schema=schema,
        flat_checkpoint=True, health_check_finite=False,
        health_spike_factor=4.0, health_hang_timeout_s=2.5,
    )
    back = WorkerConfig.from_json(cfg.to_json())
    assert back.flat_checkpoint is True
    assert back.health_check_finite is False
    assert back.health_spike_factor == pytest.approx(4.0)
    assert back.health_hang_timeout_s == pytest.approx(2.5)
    # configs serialized before these fields existed still load
    legacy = cfg.to_json()
    for k in ("flat_checkpoint", "health_check_finite",
              "health_spike_factor", "health_spike_min_epochs",
              "health_hang_timeout_s"):
        del legacy[k]
    old = WorkerConfig.from_json(legacy)
    assert old.flat_checkpoint is False
    assert old.health_check_finite is True


def test_unhealthy_non_spmd_directive_does_not_leak_to_peers():
    """Independent models roll back independently: worker B's LR back-off
    and skip window must ride ONLY B's re-registration — a healthy worker
    relaunched after an unrelated crash keeps lr_scale 1.0."""
    coord = Coordinator(_spec(3, spare_restarts=3, health_max_rollbacks=3))
    for i, wid in enumerate(("a", "b", "c")):
        coord.register(wid, i, host="h")
    coord.report_unhealthy("b", 2, "nan", bad_steps=[4])
    # the tripper's relaunch gets the directive...
    rb = coord.register("b", 1, host="h")
    assert rb["health"]["lr_scale"] == pytest.approx(0.5)
    assert rb["health"]["skip"] == {"epoch": 2, "steps": [4]}
    # ...a healthy peer relaunched after an unrelated crash does not
    rc = coord.register("c", 2, host="h")
    assert rc["health"]["lr_scale"] == pytest.approx(1.0)
    assert rc["health"]["skip"] is None
    coord.liveness.stop()


# ---- fleet skew observability (obs/fleet.py, PR 11) ----

def _phases(host=0.0, infeed=0.0, dispatch=0.0, block=0.0, steps=4,
            barrier=None, offset=None):
    d = {"host_s": host, "infeed_s": infeed, "dispatch_s": dispatch,
         "block_s": block, "steps": steps}
    if barrier is not None:
        d["barrier_s"] = barrier
    if offset is not None:
        d["offset_s"] = offset
    return d


def test_fleet_monitor_detects_names_phase_and_clears():
    """Skew-digest aggregation unit: rank 1 runs 3x its peer with the
    excess in infeed -> straggler_detect names rank 1 + infeed after the
    hysteresis; parity restored -> straggler_clear once the slow epochs
    age out of the (epoch-denominated) window."""
    from shifu_tensorflow_tpu.obs.fleet import FleetMonitor

    mon = FleetMonitor(skew_threshold=1.5, hysteresis=2, window_epochs=4,
                       warmup_epochs=0)
    events = []
    for epoch in range(4):
        events += mon.observe_epoch(
            0, epoch, 1.0,
            phases=_phases(host=0.1, infeed=0.2, dispatch=0.5, block=0.1),
            n_workers=2)
        events += mon.observe_epoch(
            1, epoch, 3.0,
            phases=_phases(host=0.1, infeed=2.2, dispatch=0.5, block=0.1),
            n_workers=2)
    det = [e for e in events if e["event"] == "straggler_detect"]
    assert len(det) == 1  # hysteretic: one transition, not one per epoch
    assert det[0]["worker"] == 1
    assert det[0]["phase"] == "infeed"
    assert det[0]["skew"] == pytest.approx(3.0)
    # one fleet_skew record per QUORUM epoch, naming the straggler
    fs = [e for e in events if e["event"] == "fleet_skew"]
    assert len(fs) == 4
    assert fs[-1]["straggler"] == 1
    assert fs[-1]["ranks"]["1"]["straggler"] is True
    # recovery: parity for long enough that the slow samples age out
    for epoch in range(4, 12):
        events += mon.observe_epoch(0, epoch, 1.0, n_workers=2)
        events += mon.observe_epoch(1, epoch, 1.0, n_workers=2)
    clr = [e for e in events if e["event"] == "straggler_clear"]
    assert len(clr) == 1 and clr[0]["worker"] == 1
    assert clr[0]["since_epoch"] == det[0]["epoch"]
    assert mon.state()["straggler"] is None


def test_fleet_monitor_rollback_epoch_regression_resets_history():
    """Epoch numbers regress after a health rollback: the epoch-indexed
    digests must drop their history (re-adding at an old epoch would
    clobber the ring cell holding the newest samples and poison every
    window mean) and re-establish skew cleanly — no spurious detect."""
    from shifu_tensorflow_tpu.obs.fleet import FleetMonitor

    mon = FleetMonitor(skew_threshold=1.5, hysteresis=2, warmup_epochs=0)
    events = []
    for epoch in range(10):
        for w in (0, 1):
            events += mon.observe_epoch(w, epoch, 1.0, n_workers=2)
    assert not [e for e in events if e["event"] == "straggler_detect"]
    # rollback: the fleet re-reports from epoch 2 at the same parity
    for epoch in range(2, 8):
        for w in (0, 1):
            events += mon.observe_epoch(w, epoch, 1.0, n_workers=2)
    assert not [e for e in events if e["event"] == "straggler_detect"]
    st = mon.state()
    assert st["ranks"]["0"]["skew"] == pytest.approx(1.0)
    assert st["ranks"]["1"]["skew"] == pytest.approx(1.0)
    # and a rank that comes back genuinely slow after the rollback is
    # still caught by the re-established window
    for epoch in range(8, 12):
        events += mon.observe_epoch(0, epoch, 1.0, n_workers=2)
        events += mon.observe_epoch(1, epoch, 4.0, n_workers=2)
    det = [e for e in events if e["event"] == "straggler_detect"]
    assert det and det[0]["worker"] == 1


def test_fleet_monitor_uniformly_slow_fleet_never_alarms():
    """Skew is RELATIVE: the whole fleet slowing down together (bigger
    model, cold cache) is not a straggler."""
    from shifu_tensorflow_tpu.obs.fleet import FleetMonitor

    mon = FleetMonitor(skew_threshold=1.5, hysteresis=1, warmup_epochs=0)
    events = []
    for epoch in range(8):
        wall = 0.5 * 1.2 ** epoch  # every epoch slower than the last
        for w in (0, 1, 2):
            events += mon.observe_epoch(w, epoch, wall, n_workers=3)
    assert not [e for e in events if e["event"] == "straggler_detect"]


def test_fleet_monitor_absolute_floor_ignores_jitter_scale_skew():
    """On millisecond epochs OS jitter alone exceeds any ratio
    threshold: a 3x relative skew whose ABSOLUTE excess is sub-floor
    (7ms vs 21ms) must not alarm, while the same ratio at seconds
    scale must."""
    from shifu_tensorflow_tpu.obs.fleet import FleetMonitor

    mon = FleetMonitor(skew_threshold=1.5, hysteresis=1, warmup_epochs=0)
    events = []
    for epoch in range(6):
        events += mon.observe_epoch(0, epoch, 0.007, n_workers=2)
        events += mon.observe_epoch(1, epoch, 0.021, n_workers=2)
    assert not [e for e in events if e["event"] == "straggler_detect"]
    # the ratio is still reported honestly even when it does not alarm
    assert mon.state()["ranks"]["1"]["skew"] == pytest.approx(3.0)

    mon = FleetMonitor(skew_threshold=1.5, hysteresis=1, warmup_epochs=0)
    events = []
    for epoch in range(6):
        events += mon.observe_epoch(0, epoch, 0.7, n_workers=2)
        events += mon.observe_epoch(1, epoch, 2.1, n_workers=2)
    det = [e for e in events if e["event"] == "straggler_detect"]
    assert det and det[0]["worker"] == 1


def test_fleet_monitor_warmup_epochs_ignore_compile_skew():
    """Epoch 0 is compile-dominated: whoever lost the XLA race looks
    10x slow.  Warmup epochs must neither alarm nor pollute the window."""
    from shifu_tensorflow_tpu.obs.fleet import FleetMonitor

    mon = FleetMonitor(skew_threshold=1.5, hysteresis=1)  # warmup 1
    events = mon.observe_epoch(0, 0, 0.1, n_workers=2)
    events += mon.observe_epoch(1, 0, 20.0, n_workers=2)  # compiling
    assert events == []
    for epoch in (1, 2):
        events += mon.observe_epoch(0, epoch, 0.1, n_workers=2)
        events += mon.observe_epoch(1, epoch, 0.1, n_workers=2)
    assert not [e for e in events if e["event"] == "straggler_detect"]
    # the compile epoch never entered the digests
    assert mon.state()["ranks"]["1"]["skew"] == pytest.approx(1.0)


def test_fleet_monitor_barrier_attribution_points_at_straggler():
    """The rank everyone else step.blocks on is the one with the
    SMALLEST barrier wait — the inverse signal of the skew itself."""
    from shifu_tensorflow_tpu.obs.fleet import FleetMonitor

    mon = FleetMonitor(skew_threshold=1.5, hysteresis=1, warmup_epochs=0)
    events = []
    for epoch in range(3):
        # rank 0 and 2 wait 2s at the barrier FOR rank 1, which waits ~0
        events += mon.observe_epoch(
            0, epoch, 1.0, phases=_phases(dispatch=0.9, barrier=2.0),
            n_workers=3)
        events += mon.observe_epoch(
            2, epoch, 1.0, phases=_phases(dispatch=0.9, barrier=2.1),
            n_workers=3)
        events += mon.observe_epoch(
            1, epoch, 3.0, phases=_phases(dispatch=2.9, barrier=0.01),
            n_workers=3)
    det = next(e for e in events if e["event"] == "straggler_detect")
    assert det["worker"] == 1
    assert det["blocked_on"] == 1
    assert det["barrier_wait_s"] == pytest.approx(0.01, rel=0.1)


def test_report_epoch_feeds_fleet_monitor_and_metrics_op(tmp_path):
    """The coordinator wires workers' attached phase summaries into the
    installed FleetMonitor; straggler events land in the journal and the
    metrics op exposes stpu_fleet_* plus per-worker heartbeat ages."""
    from shifu_tensorflow_tpu.obs import fleet as fleet_mod
    from shifu_tensorflow_tpu.obs import journal as journal_mod
    from shifu_tensorflow_tpu.obs.journal import Journal, read_events

    base = str(tmp_path / "coord.jsonl")
    journal_mod.install(Journal(base, plane="coordinator"))
    fleet_mod.install(fleet_mod.FleetMonitor(skew_threshold=1.5,
                                             hysteresis=1,
                                             warmup_epochs=0))
    coord = Coordinator(_spec(2))
    try:
        coord.register("a", 0, host="h")
        coord.register("b", 1, host="h")
        for epoch in range(2):
            for w, wall in ((0, 0.1), (1, 0.7)):
                s = _stats(w, epoch)
                s.training_time_s = wall
                s.phases = _phases(host=wall * 0.8, dispatch=wall * 0.1,
                                   offset=0.001 * (w + 1))
                coord.report_epoch(s.__dict__)
        text = coord.metrics_text()
        assert 'stpu_fleet_skew{worker="1"}' in text
        assert 'stpu_coord_heartbeat_age_seconds{worker="0"}' in text
        assert 'stpu_coord_heartbeat_age_seconds{worker="1"}' in text
        assert "stpu_fleet_straggler 1" in text
        assert 'stpu_fleet_clock_offset_seconds{worker="1"} 0.002' in text
    finally:
        coord.liveness.stop()
        journal_mod.uninstall()
        fleet_mod.uninstall()
    events = read_events(base)
    det = [e for e in events if e["event"] == "straggler_detect"]
    assert det and det[0]["worker"] == 1 and det[0]["plane"] == "coordinator"
    assert [e for e in events if e["event"] == "fleet_skew"]


def test_slow_fault_kind_sleeps_deterministically():
    """utils/faults `slow` kind: fires by the same seeded/at-step rules
    as every other term, but SLEEPS instead of raising."""
    import time as _time

    from shifu_tensorflow_tpu.utils import faults

    plan = faults.FaultPlan.parse(
        "train.step.w1:slow50@1.0,other.site:slow@1.0")
    t0 = _time.perf_counter()
    plan.check("train.step.w1")
    lagged = _time.perf_counter() - t0
    assert lagged >= 0.045
    # rank 0's site does not match: no sleep
    t0 = _time.perf_counter()
    plan.check("train.step.w0")
    assert _time.perf_counter() - t0 < 0.02
    assert plan.fired()["train.step.w1:slow50"] == 1
    # at-step trigger: fires exactly once, at the Nth matching check
    plan2 = faults.FaultPlan.parse("train.step:slow50@2")
    t0 = _time.perf_counter()
    plan2.check("train.step.w0")  # check 1: no fire
    assert _time.perf_counter() - t0 < 0.02
    t0 = _time.perf_counter()
    plan2.check("train.step.w0")  # check 2: fires
    assert _time.perf_counter() - t0 >= 0.045
    plan2.check("train.step.w0")  # never again
    assert plan2.fired()["train.step:slow50"] == 1
    with pytest.raises(ValueError, match="slow"):
        faults.FaultPlan.parse("a:slowly@0.5")


@pytest.mark.parametrize("inject", [True, False],
                         ids=["slow-rank-1", "control"])
def test_two_worker_straggler_drill(psv_dataset, tmp_path,
                                    job_model_config, inject):
    """The acceptance drill: a 2-worker thread fleet with a `slow` fault
    plan lagging rank 1's first epochs -> straggler_detect names rank 1
    with a host/infeed dominant phase, then straggler_clear once the lag
    stops and the slow epochs age out of the window; `obs fleet`
    reconstructs the excursion from the dead fleet's files alone.  The
    control arm (no plan) journals no straggler events."""
    import json as _json
    import subprocess as _subprocess
    import sys as _sys

    from shifu_tensorflow_tpu.obs import ObsConfig, install_obs
    from shifu_tensorflow_tpu.obs import fleet as fleet_mod
    from shifu_tensorflow_tpu.obs import journal as journal_mod
    from shifu_tensorflow_tpu.obs import slo as slo_mod
    from shifu_tensorflow_tpu.obs import trace as trace_mod
    from shifu_tensorflow_tpu.obs.journal import read_events
    from shifu_tensorflow_tpu.utils import faults

    base = str(tmp_path / "drill.jsonl")
    epochs = 16
    schema = RecordSchema(
        feature_columns=tuple(psv_dataset["feature_cols"]),
        target_column=psv_dataset["target_col"],
        weight_column=psv_dataset["weight_col"],
    )
    obs_cfg = ObsConfig(enabled=True, journal_path=base)

    def make(worker_id, addr):
        return WorkerConfig(
            worker_id=worker_id,
            coordinator_host=addr[0],
            coordinator_port=addr[1],
            model_config=job_model_config,
            schema=schema,
            batch_size=100,
            heartbeat_interval_s=0.2,
            obs=obs_cfg.to_json(),
        )

    if inject:
        # deterministic lag: rank 1's host batches 2..13 (4 train
        # steps/epoch at batch 100 over its ~400-row train split) each
        # sleep 120ms via at-step triggers — epochs 1-3 run ~10x slow
        # (epoch 0 is warmup either way), everything after runs at
        # parity, so the clear leg is part of the same run
        plan = ",".join(f"train.step.w1:slow120@{n}" for n in range(2, 14))
        faults.set_plan(faults.FaultPlan.parse(plan))
    try:
        install_obs(obs_cfg, plane="coordinator", job="drill")
        spec = make_job_spec(psv_dataset["root"], 2, epochs=epochs,
                             registration_timeout_s=20.0)
        sub = JobSubmitter(spec, make)
        result = sub.run(timeout_s=180.0)
        assert result.state == JobState.FINISHED, result.failure_reason
    finally:
        faults.set_plan(None)
        journal_mod.uninstall()
        trace_mod.uninstall()
        slo_mod.uninstall()
        fleet_mod.uninstall()

    events = read_events(base)
    det = [e for e in events if e["event"] == "straggler_detect"]
    clr = [e for e in events if e["event"] == "straggler_clear"]
    if not inject:
        # control arm: parity fleet, no alarms
        assert det == [] and clr == []
        return
    assert det, "slow rank never detected"
    assert det[0]["worker"] == 1
    # the sleep lands in host-batch production: consumer-visible as the
    # host phase (unthreaded) or the infeed wait (pipelined put thread)
    assert det[0]["phase"] in ("host", "infeed")
    assert det[0]["skew"] >= 1.5
    assert clr, "straggler never cleared after the lag stopped"
    assert clr[0]["worker"] == 1
    assert clr[0]["epoch"] > det[0]["epoch"]
    # workers journaled their clock offsets (loopback: sub-second)
    offs = [e["offset"] for e in events if "offset" in e]
    assert offs and all(abs(o) < 1.0 for o in offs)
    # the dead-fleet CLI reconstructs the excursion, jax-free
    out = _subprocess.run(
        [_sys.executable, "-m", "shifu_tensorflow_tpu.obs", "fleet",
         "--journal", base, "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    doc = _json.loads(out.stdout)
    exc = doc["excursions"][0]
    assert exc["worker"] == 1 and exc["clear_epoch"] is not None
