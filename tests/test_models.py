"""Model-zoo tests: factory dispatch, activation-map parity, shapes,
embedding hashing (SURVEY.md §7.1 step 3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.models.dnn import ShifuDNN, activation_fn
from shifu_tensorflow_tpu.models.embeddings import HashedEmbedding, hash_to_buckets
from shifu_tensorflow_tpu.models.factory import build_model
from shifu_tensorflow_tpu.models.multi_task import MultiTaskDNN
from shifu_tensorflow_tpu.models.wide_deep import WideDeep


def _mc(params=None, **train_extra):
    train = {"numTrainEpochs": 1, "validSetRate": 0.1,
             "params": params or {"NumHiddenLayers": 2,
                                  "NumHiddenNodes": [8, 4],
                                  "ActivationFunc": ["relu", "tanh"],
                                  "LearningRate": 0.1}}
    train.update(train_extra)
    return ModelConfig.from_json({"train": train})


def test_activation_map_parity():
    # exact fallback semantics of ssgd_monitor.py:74-88
    import flax.linen as nn

    assert activation_fn("sigmoid") is nn.sigmoid
    assert activation_fn("TANH") is nn.tanh
    assert activation_fn("relu") is nn.relu
    assert activation_fn("LeakyReLU") is nn.leaky_relu
    assert activation_fn("bogus") is nn.leaky_relu
    assert activation_fn(None) is nn.leaky_relu


def test_dnn_output_shape_and_range():
    model = ShifuDNN(hidden_nodes=(8, 4), activations=("relu", "tanh"))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(5, 10)), jnp.float32)
    params = model.init(jax.random.key(0), x)["params"]
    y = model.apply({"params": params}, x)
    assert y.shape == (5, 1)
    assert ((y >= 0) & (y <= 1)).all()  # sigmoid head
    # configured layer structure materialized
    assert params["trunk"]["hidden_layer0"]["kernel"].shape == (10, 8)
    assert params["trunk"]["hidden_layer1"]["kernel"].shape == (8, 4)
    assert params["shifu_output_0"]["kernel"].shape == (4, 1)


def test_factory_default_dnn():
    model = build_model(_mc())
    assert isinstance(model, ShifuDNN)


def test_factory_wide_deep():
    mc = _mc(params={"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                     "ActivationFunc": ["relu"], "ModelType": "wide_deep",
                     "WideColumnNums": [2, 3], "LearningRate": 0.1})
    model = build_model(mc, feature_columns=(1, 2, 3, 4))
    assert isinstance(model, WideDeep)
    assert model.wide_indices == (1, 2)  # positions of cols 2,3 in features
    x = jnp.ones((4, 4), jnp.float32)
    params = model.init(jax.random.key(0), x)["params"]
    y = model.apply({"params": params}, x)
    assert y.shape == (4, 1)


def test_factory_multi_task():
    mc = _mc(params={"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                     "ActivationFunc": ["relu"], "ModelType": "multi_task",
                     "NumTasks": 3, "LearningRate": 0.1})
    model = build_model(mc)
    assert isinstance(model, MultiTaskDNN)
    x = jnp.ones((4, 6), jnp.float32)
    params = model.init(jax.random.key(0), x)["params"]
    y = model.apply({"params": params}, x)
    assert y.shape == (4, 3)
    assert params["task_heads"]["kernel"].shape == (8, 3)


def test_factory_embedding_augmented():
    mc = _mc(params={"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                     "ActivationFunc": ["relu"],
                     "EmbeddingColumnNums": [5, 6],
                     "EmbeddingHashSize": 64, "EmbeddingDim": 4,
                     "LearningRate": 0.1})
    model = build_model(mc, feature_columns=(1, 2, 5, 6))
    x = jnp.ones((4, 4), jnp.float32)
    variables = model.init(jax.random.key(0), x)
    y = model.apply(variables, x)
    assert y.shape == (4, 1)
    # table annotated for model-axis sharding
    import flax.linen as nn

    table = variables["params"]["hashed_columns"]["table"]
    assert isinstance(table, nn.Partitioned)
    assert table.names == ("model", None)
    assert table.value.shape == (64, 4)


def test_hash_to_buckets_range_and_spread():
    vals = jnp.asarray(np.arange(1000, dtype=np.float32))
    ids = np.asarray(hash_to_buckets(vals, 128))
    assert ids.min() >= 0 and ids.max() < 128
    assert len(np.unique(ids)) > 100  # decent spread over buckets


def test_hashed_embedding_column_salting():
    emb = HashedEmbedding(hash_size=256, features=2)
    # same value in two different columns should (generally) embed differently
    x = jnp.asarray([[7.0, 7.0]], jnp.float32)
    variables = emb.init(jax.random.key(0), x)
    out = emb.apply(variables, x).reshape(2, 2)
    assert not np.allclose(out[0], out[1])


def test_wide_deep_with_hashed_cross():
    # regression: cross table must initialize (was a crash pre-review)
    mc = _mc(params={"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                     "ActivationFunc": ["relu"], "ModelType": "wide_deep",
                     "WideColumnNums": [2, 3], "CrossHashSize": 128,
                     "LearningRate": 0.1})
    model = build_model(mc, feature_columns=(1, 2, 3, 4))
    x = jnp.ones((4, 4), jnp.float32)
    variables = model.init(jax.random.key(0), x)
    y = model.apply(variables, x)
    assert y.shape == (4, 1)
    table = variables["params"]["wide_cross"]["table"]
    assert table.value.shape == (128, 1)


def test_wide_deep_keeps_embedding_columns():
    # regression: EmbeddingColumnNums no longer silently dropped for wide_deep
    mc = _mc(params={"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                     "ActivationFunc": ["relu"], "ModelType": "wide_deep",
                     "WideColumnNums": [2], "EmbeddingColumnNums": [3],
                     "EmbeddingHashSize": 32, "EmbeddingDim": 4,
                     "LearningRate": 0.1})
    from shifu_tensorflow_tpu.models.factory import EmbeddingAugmented

    model = build_model(mc, feature_columns=(1, 2, 3))
    assert isinstance(model, EmbeddingAugmented)
    x = jnp.ones((2, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x)
    assert model.apply(variables, x).shape == (2, 1)
