"""Multi-host bootstrap helpers (parallel/distributed.py).

Real multi-process initialize needs multiple hosts; here the derivation
logic and the single-process no-op contract are unit-tested, and the
global mesh path runs on the virtual 8-device mesh.
"""

import pytest

from shifu_tensorflow_tpu.config import keys as K
from shifu_tensorflow_tpu.config.conf import Conf
from shifu_tensorflow_tpu.parallel.distributed import (
    ProcessTopology,
    global_mesh,
    initialize,
    process_batch_slice,
)


def test_topology_from_conf():
    conf = Conf({
        K.COORDINATOR_ADDRESS: "10.0.0.1:8476",
        K.NUM_PROCESSES: 4,
        K.PROCESS_ID: 2,
    })
    t = ProcessTopology.from_conf(conf)
    assert t.is_distributed
    assert t.coordinator_address == "10.0.0.1:8476"
    assert (t.num_processes, t.process_id) == (4, 2)


def test_topology_from_env(monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_COORDINATOR", "h0:1234")
    monkeypatch.setenv("SHIFU_TPU_NUM_PROCESSES", "3")
    monkeypatch.setenv("SHIFU_TPU_PROCESS_ID", "1")
    t = ProcessTopology.from_env()
    assert (t.coordinator_address, t.num_processes, t.process_id) == (
        "h0:1234", 3, 1,
    )
    monkeypatch.delenv("SHIFU_TPU_COORDINATOR")
    assert ProcessTopology.from_env().coordinator_address is None


def test_topology_from_cluster_info():
    t = ProcessTopology.from_cluster_info(
        {"chief_host": "w0.pod", "jax_port": 9999, "n_workers": 8},
        worker_index=3,
    )
    assert t.coordinator_address == "w0.pod:9999"
    assert (t.num_processes, t.process_id) == (8, 3)
    # single worker: no coordination service needed
    t1 = ProcessTopology.from_cluster_info({"n_workers": 1}, worker_index=0)
    assert not t1.is_distributed and t1.coordinator_address is None
    # multi-worker info without the chief's port is a bring-up bug
    with pytest.raises(ValueError):
        ProcessTopology.from_cluster_info({"n_workers": 4}, worker_index=1)


def test_initialize_single_process_noop():
    initialize(ProcessTopology())  # must not touch jax.distributed


def test_initialize_validates():
    with pytest.raises(ValueError):
        initialize(ProcessTopology(coordinator_address=None, num_processes=2))
    with pytest.raises(ValueError):
        initialize(ProcessTopology(
            coordinator_address="h:1", num_processes=2, process_id=5
        ))


def test_global_mesh_spans_devices():
    mesh = global_mesh("data:-1")
    assert mesh.size == 8  # the forced virtual device count


def test_process_batch_slice_partition():
    # 10 rows over 4 processes: 3,3,2,2 with contiguous offsets
    tops = [ProcessTopology("h:1", 4, i) for i in range(4)]
    slices = [process_batch_slice(10, t) for t in tops]
    assert slices == [(3, 0), (3, 3), (2, 6), (2, 8)]
    assert sum(r for r, _ in slices) == 10
