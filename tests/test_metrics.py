"""Metric tests: KS/AUC correctness incl. ties and weights."""

import numpy as np

from shifu_tensorflow_tpu.ops.metrics import accuracy, auc, ks_statistic


def test_auc_perfect_and_inverse():
    y = np.array([0, 0, 1, 1])
    assert auc([0.1, 0.2, 0.8, 0.9], y) == 1.0
    assert auc([0.9, 0.8, 0.2, 0.1], y) == 0.0


def test_auc_constant_scores_is_half():
    y = np.array([0, 1, 0, 1, 1])
    assert auc(np.full(5, 0.5), y) == 0.5


def test_auc_matches_rank_formula():
    rng = np.random.default_rng(0)
    s = rng.random(200)
    y = (rng.random(200) < 0.4).astype(float)
    # brute-force pairwise
    pos_s, neg_s = s[y > 0.5], s[y <= 0.5]
    wins = (pos_s[:, None] > neg_s[None, :]).sum()
    ties = (pos_s[:, None] == neg_s[None, :]).sum()
    expected = (wins + 0.5 * ties) / (len(pos_s) * len(neg_s))
    assert np.isclose(auc(s, y), expected)


def test_auc_weighted():
    # one heavily weighted correct pair dominates
    s = np.array([0.9, 0.1, 0.6])
    y = np.array([1.0, 0.0, 0.0])
    w = np.array([1.0, 100.0, 1.0])
    assert auc(s, y, w) == 1.0  # positive outranks all negatives regardless


def test_ks_separable():
    y = np.array([0] * 50 + [1] * 50)
    s = np.concatenate([np.linspace(0, 0.4, 50), np.linspace(0.6, 1.0, 50)])
    assert ks_statistic(s, y) == 1.0


def test_ks_constant_zero():
    y = np.array([0, 1, 0, 1])
    assert ks_statistic(np.full(4, 0.3), y) == 0.0


def test_ks_degenerate_classes():
    assert ks_statistic([0.5, 0.6], [1, 1]) == 0.0
    assert ks_statistic([], []) == 0.0


def test_zero_weight_rows_excluded():
    s = np.array([0.9, 0.1, 0.99])
    y = np.array([1.0, 0.0, 0.0])
    w = np.array([1.0, 1.0, 0.0])  # the misranked negative has weight 0
    assert auc(s, y, w) == 1.0
    assert ks_statistic(s, y, w) == 1.0


def test_accuracy_weighted():
    s = np.array([0.9, 0.2, 0.7])
    y = np.array([1.0, 0.0, 0.0])
    w = np.array([1.0, 1.0, 2.0])
    assert np.isclose(accuracy(s, y, w), 2.0 / 4.0)
