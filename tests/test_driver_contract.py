"""Driver-contract tests: the two root-level files the round driver
executes must keep their contracts — bench.py prints ONE JSON line with the
required keys, and __graft_entry__.entry() returns a jittable fn + args.
(dryrun_multichip is exercised by the driver itself and manually; running
the full multi-mesh dryrun here would double the suite's wall time.)"""

import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_prints_one_json_line_with_contract_keys():
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_BATCH": "512",
        "BENCH_SECONDS": "0.2",
        "BENCH_STREAM_ROWS": "20000",
        "BENCH_STREAM_SHARDS": "2",
        "BENCH_SCAN_STEPS": "2",
        "BENCH_DEVICE_EPOCH_ROWS": "10000",
        "BENCH_DEVICE_EPOCH_EPOCHS": "2",
        "BENCH_TPU_ATTEMPTS": "1",
        "BENCH_TPU_TIMEOUT": "200",
        "BENCH_CPU_TIMEOUT": "200",
    })
    def _reject(tok):  # json.loads accepts NaN/Infinity by default
        raise ValueError(f"non-standard JSON token {tok} in bench line")

    # one retry: on a loaded 1-CPU host the timed child can blow its
    # internal budget and bench (correctly) reports value 0 with
    # diagnostics — bench working as designed, not a contract break, so
    # give it one quiet second chance before failing the suite
    for attempt in (1, 2):
        # outer timeout must exceed bench's worst-case internal budget
        # (one 200s attempt + 5s backoff + 200s cpu fallback)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, timeout=540, env=env, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        assert len(lines) == 1, (
            f"bench must print exactly ONE line, got: {lines}"
        )
        d = json.loads(lines[0], parse_constant=_reject)
        for k in ("metric", "value", "unit", "vs_baseline"):
            assert k in d, f"contract key {k} missing"
        assert d["metric"] == "training_rows_per_sec_per_chip"
        if d["value"] > 0 or attempt == 2:
            break
    assert d["value"] > 0, f"bench measured nothing twice: {d}"
    assert np.isfinite(d["vs_baseline"])


def test_graft_entry_is_jittable_with_example_args():
    import jax

    import __graft_entry__ as g  # conftest puts the repo root on sys.path

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    out = np.asarray(jax.device_get(out))
    assert out.ndim == 2 and out.shape[1] == 1
    assert np.all(np.isfinite(out))
    # dryrun contract: callable with an int (driver passes the device count)
    assert callable(g.dryrun_multichip)
