"""Driver-contract tests: the two root-level files the round driver
executes must keep their contracts — bench.py prints only JSON lines whose
LAST line carries the required keys (earlier lines are incremental partial
results, flushed so a killed bench still leaves evidence), and
__graft_entry__.entry() returns a jittable fn + args.  (dryrun_multichip
is exercised by the driver itself and manually; running the full
multi-mesh dryrun here would double the suite's wall time.)"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_env(**extra):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_BATCH": "512",
        "BENCH_SECONDS": "0.2",
        "BENCH_STREAM_ROWS": "20000",
        "BENCH_STREAM_SHARDS": "2",
        "BENCH_SCAN_STEPS": "2",
        "BENCH_DEVICE_EPOCH_ROWS": "10000",
        "BENCH_DEVICE_EPOCH_EPOCHS": "2",
        "BENCH_TPU_ATTEMPTS": "1",
        "BENCH_TOTAL_BUDGET_S": "400",
        "BENCH_TPU_TIMEOUT": "180",
    })
    env.update(extra)
    return env


def _reject(tok):  # json.loads accepts NaN/Infinity by default
    raise ValueError(f"non-standard JSON token {tok} in bench line")


def test_bench_emits_json_lines_with_contract_keys():
    # one retry: on a loaded 1-CPU host the timed child can blow its
    # internal budget and bench (correctly) reports value 0 with
    # diagnostics — bench working as designed, not a contract break, so
    # give it one quiet second chance before failing the suite
    for attempt in (1, 2):
        # outer timeout exceeds bench's own worst-case internal budget
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, timeout=500, env=_bench_env(), cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        assert lines, "bench printed nothing"
        # EVERY line must parse — a caller that truncates the stream at
        # any point still holds a valid artifact
        parsed = [json.loads(l, parse_constant=_reject) for l in lines]
        d = parsed[-1]
        for k in ("metric", "value", "unit", "vs_baseline"):
            assert k in d, f"contract key {k} missing"
        assert d["metric"] == "training_rows_per_sec_per_chip"
        assert "partial" not in d, "final line must not be partial"
        # the primary metric must appear EARLY (incremental emission):
        # the first parsed line already carries it
        assert parsed[0].get("value", 0) > 0 or d["value"] == 0
        if d["value"] > 0 or attempt == 2:
            break
    assert d["value"] > 0, f"bench measured nothing twice: {d}"
    assert np.isfinite(d["vs_baseline"])


def test_bench_sigterm_flushes_partial_artifact():
    """The round-3 failure mode: the driver killed the bench and got an
    empty tail.  Now SIGTERM at ANY point must still end with a parseable
    JSON line on stdout (rc 0 from the parent's flush handler)."""
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=_bench_env(), cwd=REPO,
    )
    time.sleep(3.0)  # mid-startup: before any measurement finishes
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=60)
    assert proc.returncode == 0
    lines = [l for l in out.decode().splitlines() if l.strip()]
    assert lines, "killed bench left an empty tail"
    d = json.loads(lines[-1], parse_constant=_reject)
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in d, f"contract key {k} missing from flushed artifact"
    assert "diagnostics" in d


def test_graft_entry_is_jittable_with_example_args():
    import jax

    import __graft_entry__ as g  # conftest puts the repo root on sys.path

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    out = np.asarray(jax.device_get(out))
    assert out.ndim == 2 and out.shape[1] == 1
    assert np.all(np.isfinite(out))
    # dryrun contract: callable with an int (driver passes the device count)
    assert callable(g.dryrun_multichip)
