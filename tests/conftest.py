"""Test harness: force an 8-device virtual CPU mesh before JAX import.

SURVEY.md §4 item 3: JAX multi-device simulation via
``xla_force_host_platform_device_count`` lets pjit sharding and all-reduce be
tested without TPU hardware.
"""

import os
import sys

# keep XLA/CPU math deterministic-ish and quiet in tests
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# force CPU: the ambient environment points JAX_PLATFORMS at a tunneled TPU
# plugin whose initialization blocks when the platform is forced to cpu;
# tests must run on the virtual 8-device CPU mesh.  (Plugins like jaxtyping
# may import jax before this conftest runs, so the shared helper re-pins the
# platform on the already-imported module and drops the plugin factory
# before the first backend query.)
from shifu_tensorflow_tpu.utils.jaxenv import force_cpu_backend  # noqa: E402

force_cpu_backend(device_count=8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 sweep (ROADMAP.md runs -m 'not "
        "slow' under a hard wall-clock budget); run with -m slow on a "
        "host that can afford it",
    )


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(20260729)


@pytest.fixture(scope="session")
def psv_dataset(tmp_path_factory, rng):
    """A small synthetic PSV+gzip tabular dataset in the reference's shard
    layout: ``target|f0|...|f9|weight`` rows split over several .gz files."""
    import gzip

    root = tmp_path_factory.mktemp("psvdata")
    n_files, rows_per_file, n_feats = 4, 250, 10
    w_true = rng.normal(size=n_feats)
    paths = []
    for i in range(n_files):
        path = root / f"part-{i:05d}.gz"
        with gzip.open(path, "wt") as f:
            for _ in range(rows_per_file):
                x = rng.normal(size=n_feats)
                logit = float(x @ w_true)
                y = 1 if rng.random() < 1.0 / (1.0 + np.exp(-logit)) else 0
                w = round(float(rng.uniform(0.5, 2.0)), 4)
                cols = [str(y)] + [f"{v:.5f}" for v in x] + [str(w)]
                f.write("|".join(cols) + "\n")
        paths.append(str(path))
    return {
        "root": str(root),
        "paths": paths,
        "n_rows": n_files * rows_per_file,
        "n_features": n_feats,
        "target_col": 0,
        "weight_col": n_feats + 1,
        "feature_cols": list(range(1, n_feats + 1)),
    }


@pytest.fixture(scope="session")
def model_config_json():
    return {
        "basic": {"name": "unit_test_model"},
        "dataSet": {"dataDelimiter": "|"},
        "train": {
            "numTrainEpochs": 3,
            "validSetRate": 0.2,
            "params": {
                "NumHiddenLayers": 2,
                "NumHiddenNodes": [16, 8],
                "ActivationFunc": ["relu", "tanh"],
                "LearningRate": 0.05,
            },
        },
    }
