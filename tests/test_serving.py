"""Serving subsystem drills: micro-batching, bucket-ladder compile
economy, hot reload with verify-before-admit, shed-before-queue
backpressure — incl. the chaos drill the acceptance criteria pin: under
``STPU_FAULT_PLAN`` at-rest corruption of a mid-reload artifact the
server keeps serving the previous verified model and recovers when a
good artifact lands."""

from __future__ import annotations

import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.export.bucketing import bucket_size, ladder, pad_rows
from shifu_tensorflow_tpu.export.eval_model import EvalModel
from shifu_tensorflow_tpu.export.saved_model import (
    NATIVE_MANIFEST,
    NATIVE_WEIGHTS,
    export_model,
)
from shifu_tensorflow_tpu.serve.batcher import (
    BatcherClosed,
    MicroBatcher,
    ShedLoad,
)
from shifu_tensorflow_tpu.serve.config import ServeConfig
from shifu_tensorflow_tpu.serve.metrics import ServeMetrics
from shifu_tensorflow_tpu.serve.model_store import (
    ArtifactCorrupt,
    ModelStore,
    _verify_manifest,
)
from shifu_tensorflow_tpu.serve.server import ScoringServer
from shifu_tensorflow_tpu.train.trainer import Trainer
from shifu_tensorflow_tpu.utils import faults

N_FEATURES = 6


def _model_config():
    return ModelConfig.from_json(
        {"train": {"params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                              "ActivationFunc": ["relu"],
                              "LearningRate": 0.05}}}
    )


def _export(tmp_dir: str, seed: int = 0) -> str:
    export_model(tmp_dir, Trainer(_model_config(), N_FEATURES, seed=seed))
    return tmp_dir


@pytest.fixture()
def export_dir(tmp_path):
    return _export(str(tmp_path / "model"))


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    yield
    faults.set_plan(None)


def _rows(n: int, seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).random((n, N_FEATURES)).astype(
        np.float32
    )


# ------------------------------------------------------------- bucketing


def test_bucket_ladder_is_powers_of_two_then_multiples():
    assert bucket_size(1) == 8
    assert bucket_size(8) == 8
    assert bucket_size(9) == 16
    assert bucket_size(100) == 128
    assert bucket_size(4096) == 4096
    assert bucket_size(4097) == 8192
    assert bucket_size(9000) == 12288  # 3 * 4096
    with pytest.raises(ValueError):
        bucket_size(0)


def test_pad_rows_shapes_and_content():
    x = _rows(5)
    padded = pad_rows(x, 8)
    assert padded.shape == (8, N_FEATURES)
    np.testing.assert_array_equal(padded[:5], x)
    assert float(np.abs(padded[5:]).sum()) == 0.0
    assert pad_rows(x, 5) is x  # already sized: no copy
    with pytest.raises(ValueError):
        pad_rows(x, 4)


def test_ladder_enumerates_reachable_buckets():
    assert ladder(1) == (8,)
    assert ladder(8) == (8,)
    assert ladder(9) == (8, 16)
    assert ladder(256) == (8, 16, 32, 64, 128, 256)
    assert ladder(4096)[-1] == 4096
    assert ladder(5000) == (8, 16, 32, 64, 128, 256, 512, 1024, 2048,
                            4096, 8192)
    # past max_bucket, EVERY multiple up to the top is reachable (a
    # 9000-row request buckets to 12288) and must be in the warm set
    assert ladder(13000)[-4:] == (4096, 8192, 12288, 16384)
    assert all(bucket_size(n) in ladder(13000)
               for n in (1, 5000, 8300, 12289, 13000))
    with pytest.raises(ValueError):
        ladder(0)


def test_eval_model_warm_precompiles_ladder(export_dir):
    """warm() compiles every ladder bucket up front, so no later
    compute_batch — whatever its length — adds a trace."""
    with EvalModel(export_dir) as em:
        buckets = ladder(256)
        assert em.warm(buckets) == len(buckets)
        assert em.native_trace_count == len(buckets)
        assert em.warm(buckets) == 0  # idempotent: nothing re-traces
        for n in (1, 7, 9, 31, 100, 256):
            em.compute_batch(_rows(n, seed=n))
        assert em.native_trace_count == len(buckets)
    # released instance refuses to warm (typed, like compute)
    from shifu_tensorflow_tpu.export.eval_model import ModelReleasedError

    em = EvalModel(export_dir)
    em.release()
    with pytest.raises(ModelReleasedError):
        em.warm((8,))


def test_native_scorer_trace_count_flat_across_batch_lengths(export_dir):
    """The compile-once win: varying batch lengths within one bucket must
    not re-trace the jitted scorer (the old behavior traced once per
    distinct length — ~19 ms each on the flagship DNN)."""
    with EvalModel(export_dir) as em:
        for n in (1, 2, 3, 5, 7, 8):  # all pad to the 8-bucket
            em.compute_batch(_rows(n, seed=n))
        assert em.native_trace_count == 1
        for n in (9, 12, 16, 11, 4, 6):  # 16-bucket joins; 8 reused
            em.compute_batch(_rows(n, seed=n))
        assert em.native_trace_count == 2
        # and padding never leaks into results: padded batch == unpadded
        x = _rows(5, seed=42)
        np.testing.assert_allclose(
            em.compute_batch(x), np.concatenate(
                [em.compute_batch(x[:3]), em.compute_batch(x[3:])]
            ), rtol=1e-6, atol=1e-7,
        )


def test_released_model_raises_typed_error(export_dir):
    """A stale reference held across a hot-reload swap must get the
    typed released error (the server re-fetches on it), never an opaque
    AttributeError from torn-down backend state."""
    from shifu_tensorflow_tpu.export.eval_model import ModelReleasedError

    em = EvalModel(export_dir)
    em.release()
    with pytest.raises(ModelReleasedError):
        em.compute_batch(_rows(2))


def test_eval_model_concurrent_compute_is_safe(export_dir):
    """The documented thread-safety contract: concurrent compute_batch
    calls serialize on the instance lock and every caller gets its own
    correct scores (no torn state, no cross-request mixing)."""
    with EvalModel(export_dir) as em:
        x = _rows(32)
        want = em.compute_batch(x)
        errors: list[BaseException] = []

        def worker(seed: int):
            try:
                for _ in range(5):
                    got = em.compute_batch(x)
                    np.testing.assert_allclose(got, want, rtol=1e-6)
            except BaseException as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        assert not errors, errors


# ---------------------------------------------------------- micro-batcher


class _GatedScorer:
    """score_fn that can hold the batcher thread, so tests control when
    queued requests coalesce."""

    def __init__(self):
        self.gate = threading.Event()
        self.gate.set()
        self.calls: list[int] = []

    def __call__(self, rows: np.ndarray) -> np.ndarray:
        self.gate.wait(10.0)
        self.calls.append(rows.shape[0])
        return rows.sum(axis=1, keepdims=True)


def test_batcher_coalesces_concurrent_requests():
    scorer = _GatedScorer()
    metrics = ServeMetrics()
    b = MicroBatcher(scorer, max_batch=64, max_delay_s=0.05,
                     max_queue_rows=256, metrics=metrics)
    try:
        # hold the batcher on a first request, queue 6 more behind it
        scorer.gate.clear()
        results: dict[int, np.ndarray] = {}

        def submit(i, n):
            results[i] = b.submit(np.full((n, 4), float(i), np.float32))

        threads = [threading.Thread(target=submit, args=(0, 2))]
        threads[0].start()
        # let the coalescing window (50 ms) lapse so the lone request
        # enters the (gated) dispatch before the peers arrive
        time.sleep(0.2)
        for i in range(1, 7):
            threads.append(threading.Thread(target=submit, args=(i, 3)))
            threads[-1].start()
        time.sleep(0.2)
        scorer.gate.set()
        for t in threads:
            t.join(timeout=10.0)
        # first dispatch = the lone request; second coalesced the 6 queued
        assert scorer.calls[0] == bucket_size(2)
        assert len(scorer.calls) == 2
        assert scorer.calls[1] == bucket_size(18)
        # every caller got exactly its own rows' scores back
        for i in range(7):
            n = 2 if i == 0 else 3
            np.testing.assert_allclose(results[i],
                                       np.full((n, 1), i * 4.0))
        assert metrics.counters()["batches_total"] == 2
        assert metrics.counters()["rows_total"] == 20
    finally:
        scorer.gate.set()
        b.close()


def test_batcher_respects_max_batch_and_never_splits_requests():
    scorer = _GatedScorer()
    b = MicroBatcher(scorer, max_batch=12, max_delay_s=0.05,
                     max_queue_rows=256)
    try:
        scorer.gate.clear()
        threads = []

        def submit(n):
            b.submit(np.ones((n, 2), np.float32))

        t0 = threading.Thread(target=submit, args=(1,))
        t0.start()
        # past the 50 ms coalescing window: the lone request is in the
        # (gated) dispatch before the rest queue up
        time.sleep(0.2)
        for n in (5, 5, 5):  # 15 rows queued behind the gated dispatch
            threads.append(threading.Thread(target=submit, args=(n,)))
            threads[-1].start()
            time.sleep(0.02)  # deterministic queue order
        scorer.gate.set()
        t0.join(timeout=10.0)
        for t in threads:
            t.join(timeout=10.0)
        # after the gated single, dispatches are [5+5 rows] then [5]:
        # 5+5+5 > max_batch 12, and a request is never split across
        # dispatches (splitting would tear the third caller's rows apart)
        assert scorer.calls[0] == bucket_size(1)
        assert scorer.calls[1:] == [bucket_size(10), bucket_size(5)]
    finally:
        scorer.gate.set()
        b.close()


def test_batcher_sheds_before_queueing():
    scorer = _GatedScorer()
    metrics = ServeMetrics()
    b = MicroBatcher(scorer, max_batch=4, max_delay_s=0.01,
                     max_queue_rows=8, retry_after_s=3, metrics=metrics)
    try:
        scorer.gate.clear()
        threads = []
        # the pipeline absorbs three coalesced batches beyond the queue
        # (one gated in dispatch, one staged in the dispatch handoff, one
        # packed and blocked on it); the next two fill the 8-row
        # admission bound
        for _ in range(5):
            t = threading.Thread(
                target=lambda: b.submit(np.ones((4, 2), np.float32))
            )
            t.start()
            threads.append(t)
            time.sleep(0.05)
        # 8 queued + 12 in-pipeline: the gauge reports ALL outstanding
        # rows, while admission sheds on the queued 8 alone
        assert b.queued_rows() == 20
        with pytest.raises(ShedLoad) as ei:
            b.submit(np.ones((1, 2), np.float32))
        # Retry-After is jittered around the configured mean (3 s):
        # uniform over [0.5x, 1.5x], integral, floored at 1
        assert ei.value.retry_after_mean_s == 3
        assert 1 <= ei.value.retry_after_s <= 5
        assert metrics.counters()["shed_total"] == 1
        # oversized single requests are a client error, not a shed
        with pytest.raises(ValueError, match="exceeds"):
            b.submit(np.ones((9, 2), np.float32))
        scorer.gate.set()
        for t in threads:
            t.join(timeout=10.0)
        # queue drained: admission works again
        out = b.submit(np.ones((2, 2), np.float32))
        assert out.shape == (2, 1)
    finally:
        scorer.gate.set()
        b.close()


def test_batcher_survives_mixed_width_coalesce():
    """Requests with disagreeing row widths can share a coalescing
    window (a hot reload can change the model width between their
    admissions): the concatenate failure must land on THOSE callers,
    not kill the worker thread and wedge every future submit."""
    scorer = _GatedScorer()
    b = MicroBatcher(scorer, max_batch=16, max_delay_s=0.05)
    try:
        scorer.gate.clear()
        errors: list[BaseException | None] = [None, None]

        def submit(i, width):
            try:
                b.submit(np.ones((2, width), np.float32))
            except BaseException as e:
                errors[i] = e

        t0 = threading.Thread(target=submit, args=(0, 3))
        t0.start()
        time.sleep(0.2)  # lone request into the gated dispatch
        ts = [threading.Thread(target=submit, args=(i, w))
              for i, w in ((0, 3), (1, 5))]  # mixed widths queue together
        for t in ts:
            t.start()
        time.sleep(0.1)
        scorer.gate.set()
        t0.join(timeout=10.0)
        for t in ts:
            t.join(timeout=10.0)
        assert any(isinstance(e, ValueError) for e in errors), errors
        # the worker survived: a well-formed submit still completes
        out = b.submit(np.ones((2, 3), np.float32), timeout_s=10.0)
        assert out.shape == (2, 1)
    finally:
        scorer.gate.set()
        b.close()


def test_pipeline_spans_prove_pack_runs_ahead_of_dispatch():
    """The pack → dispatch → scatter pipeline: while a batch is held on
    the device, later batches are already packed (serve.pack spans land
    before the gated serve.dispatch span can), and every stage's span
    count matches the dispatch count once drained."""
    from shifu_tensorflow_tpu.obs import trace as obs_trace

    tracer = obs_trace.install(obs_trace.Tracer())
    scorer = _GatedScorer()
    b = MicroBatcher(scorer, max_batch=8, max_delay_s=0.01)
    try:
        scorer.gate.clear()
        threads = []
        for s in range(3):
            t = threading.Thread(
                target=lambda: b.submit(np.ones((2, 3), np.float32))
            )
            t.start()
            threads.append(t)
            time.sleep(0.05)  # three separate coalescing windows
        # batch 1 is gated INSIDE the dispatch stage; batches 2 and 3
        # still get packed — host work running ahead of the device
        deadline = time.time() + 5.0
        while (tracer.summary().get("serve.pack", {}).get("count", 0) < 3
               and time.time() < deadline):
            time.sleep(0.01)
        s = tracer.summary()
        assert s["serve.pack"]["count"] == 3
        assert "serve.scatter" not in s  # nothing completed yet
        scorer.gate.set()
        for t in threads:
            t.join(timeout=10.0)
        s = tracer.summary()
        assert s["serve.dispatch"]["count"] == 3
        assert s["serve.scatter"]["count"] == 3
    finally:
        scorer.gate.set()
        b.close()
        obs_trace.uninstall()


def test_batcher_propagates_scorer_errors_and_close_rejects():
    def boom(rows):
        raise RuntimeError("scorer exploded")

    b = MicroBatcher(boom, max_batch=4, max_delay_s=0.0)
    with pytest.raises(RuntimeError, match="exploded"):
        b.submit(np.ones((1, 2), np.float32))
    b.close()
    with pytest.raises(BatcherClosed):
        b.submit(np.ones((1, 2), np.float32))


# ----------------------------------------------------- manifest + store


def test_export_writes_verifiable_manifest(export_dir):
    m = _verify_manifest(export_dir)  # raises on any mismatch
    assert m is not None
    assert set(m["files"]) == {
        "shifu_tpu_model.json", NATIVE_WEIGHTS, "GenericModelConfig.json"
    }
    assert m["sha256"] == m["files"][NATIVE_WEIGHTS]["sha256"]
    # no tmp debris left behind by the atomic publishes
    assert not [n for n in os.listdir(export_dir) if ".tmp." in n]


def test_store_refuses_truncated_weights(export_dir):
    wpath = os.path.join(export_dir, NATIVE_WEIGHTS)
    data = open(wpath, "rb").read()
    open(wpath, "wb").write(data[: len(data) // 2])
    with pytest.raises(ArtifactCorrupt, match="size"):
        ModelStore(export_dir, poll_interval_s=0)


def test_store_loads_legacy_manifestless_bundle(export_dir):
    os.unlink(os.path.join(export_dir, NATIVE_MANIFEST))
    store = ModelStore(export_dir, poll_interval_s=0)
    try:
        cur = store.current()
        assert cur.verified is False and cur.digest == ""
        assert cur.model.compute_batch(_rows(3)).shape == (3, 1)
    finally:
        store.close()


def test_store_transient_read_fault_retries_under_policy(export_dir):
    """A transient injected 503 at the serve.reload seam is absorbed by
    the retry envelope (utils/retry.py), not escalated to a refusal —
    while artifact CORRUPTION never retries (a new export cures it, not a
    re-read)."""
    from shifu_tensorflow_tpu.utils.retry import RetryPolicy

    policy = RetryPolicy(max_attempts=4, base_delay_s=0.001,
                         max_delay_s=0.002, seed=0)
    # at-step trigger: fire at the 2nd serve.reload check — the initial
    # load is check 1 (clean), the reload below is check 2 (faulted) and
    # its retry is check 3 (clean again)
    faults.set_plan(faults.FaultPlan.parse("serve.reload:503@2", seed=1))
    store = ModelStore(export_dir, poll_interval_s=0, retry_policy=policy)
    try:
        loaded = store.reload_now()  # hits the 503, retries, succeeds
        plan = faults.active()
        assert plan is not None and plan.fired()["serve.reload:503"] == 1
        assert loaded.epoch == 1 and loaded.verified
    finally:
        store.close()
    # control arm: ArtifactCorrupt must NOT retry (retryable() says no)
    from shifu_tensorflow_tpu.utils.retry import retryable

    assert not retryable(ArtifactCorrupt("digest differs"))


# ------------------------------------------------------------- HTTP layer


@pytest.fixture()
def server(export_dir):
    cfg = ServeConfig(model_dir=export_dir, port=0, max_batch=64,
                      max_delay_ms=2.0, max_queue_rows=256,
                      reload_poll_ms=50)
    with ScoringServer(cfg) as srv:
        srv.start()
        yield srv


def _post(port: int, payload: dict, path="/score"):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30.0)
    try:
        c.request("POST", path, json.dumps(payload),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        return r.status, dict(r.getheaders()), json.loads(r.read())
    finally:
        c.close()


def _get(port: int, path: str):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30.0)
    try:
        c.request("GET", path)
        r = c.getresponse()
        return r.status, r.read().decode()
    finally:
        c.close()


def test_http_scores_match_direct_eval(server, export_dir):
    x = _rows(7)
    status, _, body = _post(server.port, {"rows": x.tolist()})
    assert status == 200
    with EvalModel(export_dir) as em:
        want = em.compute_batch(x)[:, 0]
    np.testing.assert_allclose(body["scores"], want, rtol=1e-4, atol=1e-6)
    assert body["model_epoch"] == 0
    # single-row form
    status, _, body = _post(server.port, {"row": x[0].tolist()})
    assert status == 200 and len(body["scores"]) == 1


def test_http_rejects_malformed_requests(server):
    for payload, match in [
        ({"rows": []}, "non-empty"),
        ({"rows": [[1.0, 2.0]]}, "features"),
        ({"nope": 1}, "rows"),
        ({"rows": [["a"] * N_FEATURES]}, "numeric"),
        ({"rows": [[float("nan")] * N_FEATURES]}, None),
    ]:
        status, _, body = _post(server.port, payload)
        assert status == 400, body
        if match:
            assert match in body["error"]
    c = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10.0)
    try:
        c.request("POST", "/score", "{not json", {})
        assert c.getresponse().status == 400
    finally:
        c.close()
    status, _, _ = _post(server.port, {"rows": [[0.0] * N_FEATURES]},
                         path="/nowhere")
    assert status == 404


def test_oversized_body_refused_before_read(server):
    """A Content-Length past the derived cap is 413'd BEFORE the body is
    read — materializing it (bytes → json → numpy) would blow memory
    long before the row-level admission checks could fire."""
    limit = server.max_body_bytes()
    c = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10.0)
    try:
        c.putrequest("POST", "/score")
        c.putheader("Content-Length", str(limit + 1))
        c.endheaders()
        r = c.getresponse()
        assert r.status == 413
        assert b"exceeds" in r.read()
    finally:
        c.close()


def test_close_without_start_does_not_hang(export_dir):
    """Construct-then-close (e.g. a with-body raising before start())
    must not deadlock in httpd.shutdown(), which blocks on an event only
    serve_forever sets."""
    cfg = ServeConfig(model_dir=export_dir, port=0, reload_poll_ms=0)
    done = threading.Event()

    def run():
        with ScoringServer(cfg):
            pass  # never started
        done.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert done.wait(30.0), "close() hung on a never-started server"


def test_healthz_and_metrics_expose_model_identity(server):
    status, body = _get(server.port, "/healthz")
    health = json.loads(body)
    assert status == 200 and health["ok"] and health["model_verified"]
    _post(server.port, {"rows": _rows(3).tolist()})
    status, text = _get(server.port, "/metrics")
    assert status == 200
    assert "stpu_serve_requests_total 1" in text
    assert "stpu_serve_rows_total 3" in text
    assert "stpu_serve_batches_total 1" in text
    assert "stpu_serve_shed_total 0" in text
    assert 'stpu_serve_model_info{digest="%s"}' % health["model_digest"] \
        in text
    assert 'stpu_serve_request_latency_seconds{quantile="0.99"}' in text
    assert "stpu_serve_queue_rows 0" in text


def test_hot_reload_swaps_to_new_artifact(server, export_dir):
    x = _rows(4)
    _, _, v1 = _post(server.port, {"rows": x.tolist()})
    _export(export_dir, seed=7)  # new params land atomically
    deadline = time.time() + 10.0
    while time.time() < deadline:
        _, _, now = _post(server.port, {"rows": x.tolist()})
        if now["model_epoch"] == 1:
            break
        time.sleep(0.05)
    assert now["model_epoch"] == 1
    assert now["model_digest"] != v1["model_digest"]
    assert now["scores"] != v1["scores"]
    assert server.metrics.counters()["reloads_total"] == 1


def test_warm_up_pins_trace_count_across_start_and_reload(server,
                                                          export_dir):
    """The pre-warm contract: after server start AND after a hot-reload
    admit, scoring across EVERY ladder bucket triggers zero new traces —
    the compile cliffs are paid off-request, before the model serves."""
    buckets = ladder(server.config.max_queue_rows)
    m0 = server.store.current().model
    assert m0.native_trace_count == len(buckets)  # warmed at start
    for n in (1, 9, 17, 33, 65, 129):  # one request per ladder bucket
        status, _, _ = _post(server.port, {"rows": _rows(n, seed=n).tolist()})
        assert status == 200
    assert m0.native_trace_count == len(buckets), \
        "a /score paid a compile the warm-up should have pre-paid"

    # hot reload: the NEW model must be warmed BEFORE the swap
    _export(export_dir, seed=5)
    deadline = time.time() + 10.0
    while server.store.current().epoch == 0 and time.time() < deadline:
        time.sleep(0.05)
    m1 = server.store.current()
    assert m1.epoch == 1
    assert m1.model.native_trace_count == len(buckets)
    for n in (1, 9, 17, 33, 65, 129):
        status, _, _ = _post(server.port, {"rows": _rows(n, seed=n).tolist()})
        assert status == 200
    assert m1.model.native_trace_count == len(buckets)


def test_corrupt_reload_keeps_warmed_model_without_recompile(server,
                                                             export_dir):
    """A refused (corrupt) reload must leave the OLD pre-warmed model
    serving bit-identically with zero re-compiles — the refusal path
    never touches the live model's compiled programs."""
    x = _rows(8, seed=2)
    _, _, v1 = _post(server.port, {"rows": x.tolist()})
    m0 = server.store.current().model
    traces_before = m0.native_trace_count
    fails_before = server.metrics.counters()["reload_failures_total"]
    faults.set_plan(
        faults.FaultPlan.parse("export.at-rest:bitflip@1", seed=7)
    )
    _export(export_dir, seed=123)
    faults.set_plan(None)
    deadline = time.time() + 10.0
    while (server.metrics.counters()["reload_failures_total"] == fails_before
           and time.time() < deadline):
        time.sleep(0.05)
    assert server.metrics.counters()["reload_failures_total"] > fails_before
    assert server.store.current().model is m0  # same warmed instance
    status, _, mid = _post(server.port, {"rows": x.tolist()})
    assert status == 200 and mid["scores"] == v1["scores"]
    assert m0.native_trace_count == traces_before


def test_chaos_drill_corrupt_reload_never_served(server, export_dir):
    """The acceptance-criteria drill: STPU_FAULT_PLAN at-rest corruption
    of a mid-reload artifact — the server keeps serving the previous
    verified model bit-for-bit, never scores through the corrupt one, and
    recovers when a good artifact lands."""
    x = _rows(16, seed=3)
    _, _, v1 = _post(server.port, {"rows": x.tolist()})

    for kind in ("bitflip", "truncate"):
        # baseline BEFORE the corrupt artifact lands: the 50 ms poller
        # may refuse it before this thread gets another word in
        fails_before = server.metrics.counters()["reload_failures_total"]
        # the corrupt export: payload mutated AFTER the manifest digest,
        # exactly how silent at-rest corruption presents
        faults.set_plan(
            faults.FaultPlan.parse(f"export.at-rest:{kind}@1", seed=11)
        )
        _export(export_dir, seed=99)
        faults.set_plan(None)
        # wait for the poller to see (and refuse) the corrupt artifact
        deadline = time.time() + 10.0
        while (server.metrics.counters()["reload_failures_total"]
               == fails_before and time.time() < deadline):
            time.sleep(0.05)
        assert server.metrics.counters()["reload_failures_total"] \
            > fails_before, f"{kind}: corrupt artifact was never refused"
        # still serving the ORIGINAL verified model, bit-for-bit
        status, _, mid = _post(server.port, {"rows": x.tolist()})
        assert status == 200
        assert mid["scores"] == v1["scores"], f"{kind}: scores drifted"
        assert mid["model_epoch"] == v1["model_epoch"]

    # recovery: a good artifact lands and is admitted
    _export(export_dir, seed=99)
    deadline = time.time() + 10.0
    while time.time() < deadline:
        _, _, now = _post(server.port, {"rows": x.tolist()})
        if now["model_epoch"] > v1["model_epoch"]:
            break
        time.sleep(0.05)
    assert now["model_epoch"] > v1["model_epoch"]
    assert now["scores"] != v1["scores"]
    # the drill proved something: faults actually fired
    assert server.metrics.counters()["reload_failures_total"] >= 2


def test_overload_sheds_with_retry_after_and_bounded_latency(export_dir):
    """Backpressure drill: a gated scorer under a flood must shed with
    429 + Retry-After while every SERVED request completes in bounded
    time (the queue can never grow past the admission bound).  The
    dispatch is BARRIER-gated, not merely slowed: nothing drains until
    the flood has arithmetically overrun the admission bound, so the
    shed assertion cannot race thread scheduling on a 2-core host."""
    cfg = ServeConfig(model_dir=export_dir, port=0, max_batch=8,
                      max_delay_ms=1.0, max_queue_rows=16,
                      retry_after_s=2, reload_poll_ms=0)
    with ScoringServer(cfg) as srv:
        inner = srv._score_once
        release = threading.Event()

        def gated(rows):
            release.wait(15.0)
            return inner(rows)

        srv.batcher._score = gated
        srv.start()
        results: list[tuple[int, float, dict]] = []
        lock = threading.Lock()

        def client(i: int):
            t0 = time.monotonic()
            status, headers, body = _post(
                srv.port, {"rows": _rows(4, seed=i).tolist()}
            )
            with lock:
                results.append((status, time.monotonic() - t0, headers))

        # 24 x 4 = 96 in-flight rows against the 16-row queue plus the
        # three-batch pipeline depth (16 + 3x8 = 40): with the gate
        # closed the overrun is guaranteed however threads schedule
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(24)]
        for t in threads:
            t.start()
        # open the gate only once the shed provably happened
        deadline = time.monotonic() + 10.0
        while (srv.metrics.counters()["shed_total"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.01)
        release.set()
        for t in threads:
            t.join(timeout=60.0)
        served = [r for r in results if r[0] == 200]
        shed = [r for r in results if r[0] == 429]
        assert served, "nothing served under overload"
        assert shed, "overload never shed — queue must be bounded"
        for _, _, headers in shed:
            # jittered around the configured mean of 2 s: [1, 3]
            assert 1 <= int(headers.get("Retry-After")) <= 3
        # bounded latency for the served fraction: the gate wait (opened
        # the moment the first shed lands) plus a <=40-row drain at full
        # speed — far under the seconds an unbounded queue accumulates
        assert max(r[1] for r in served) < 10.0
        assert srv.metrics.counters()["shed_total"] >= len(shed)


# ------------------------------------- correlation ids + SLO watchdog


def _post_rid(port: int, payload: dict, rid: str | None = None):
    headers = {"Content-Type": "application/json"}
    if rid is not None:
        headers["X-Request-Id"] = rid
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30.0)
    try:
        c.request("POST", "/score", json.dumps(payload), headers)
        r = c.getresponse()
        return r.status, dict(r.getheaders()), json.loads(r.read())
    finally:
        c.close()


def test_resolve_rid_sanitizes_and_mints():
    from shifu_tensorflow_tpu.serve.server import resolve_rid

    assert resolve_rid("ok-id_1.2") == "ok-id_1.2"
    # ':' is stripped so a numeric rid can never shadow `obs trace`'s
    # worker:epoch grammar
    assert resolve_rid("12:3") == "123"
    assert resolve_rid("x" * 100) == "x" * 64
    for hostile in (None, "", "   ", "\t{}"):
        minted = resolve_rid(hostile)
        assert len(minted) == 16 and minted.isalnum()


@pytest.fixture()
def obs_env(tmp_path):
    """Install a serve-plane obs journal (+ watchdog) and return the
    base path; uninstalls on teardown so module-global hooks never leak
    into the rest of the suite."""
    from shifu_tensorflow_tpu.obs import install_obs
    from shifu_tensorflow_tpu.obs import journal as journal_mod
    from shifu_tensorflow_tpu.obs import slo as slo_mod
    from shifu_tensorflow_tpu.obs import trace as trace_mod
    from shifu_tensorflow_tpu.obs.config import ObsConfig

    base = str(tmp_path / "serve-journal.jsonl")
    install_obs(
        ObsConfig(enabled=True, journal_path=base, slo_window_s=2.0,
                  slo_serve_shed_rate=0.25, slo_hysteresis=1),
        plane="serve", worker_index=0, job="drill001",
    )
    yield base
    trace_mod.uninstall()
    journal_mod.uninstall()
    slo_mod.uninstall()


def test_request_id_propagates_end_to_end(export_dir, obs_env):
    """Satellite e2e: the inbound X-Request-Id is echoed on the response
    AND lands in the journaled serve events that touched the request; a
    request without one gets a minted id."""
    from shifu_tensorflow_tpu.obs.journal import read_events

    cfg = ServeConfig(model_dir=export_dir, port=0, max_batch=64,
                      max_delay_ms=1.0, reload_poll_ms=0)
    with ScoringServer(cfg) as srv:
        srv.start()
        status, headers, body = _post_rid(
            srv.port, {"rows": _rows(3).tolist()}, rid="my-rid-001")
        assert status == 200
        assert headers.get("X-Request-Id") == "my-rid-001"
        assert body["request_id"] == "my-rid-001"
        # no inbound id: one is minted and still echoed
        status, headers, body = _post_rid(srv.port,
                                          {"rows": _rows(2).tolist()})
        assert status == 200
        minted = headers.get("X-Request-Id")
        assert minted and body["request_id"] == minted
        # a hostile id is sanitized before echo/journal (http.client
        # already refuses CRLF outright; everything else odd strips)
        status, headers, _ = _post_rid(
            srv.port, {"rows": _rows(1).tolist()},
            rid='sp aced "id" {x}!!')
        assert status == 200
        assert headers.get("X-Request-Id") == "spacedidx"
    events = read_events(obs_env)
    batches = [e for e in events if e["event"] == "serve_batch"]
    rids = {r for e in batches for r in e["rids"]}
    assert "my-rid-001" in rids and minted in rids
    for e in batches:
        assert e["job"] == "drill001"
        assert e["rows"] >= 1 and e["dispatch_s"] >= 0.0


def test_shed_429_echoes_rid_and_journals_it(export_dir, obs_env):
    """The 429 path: shed responses echo the id, and the (rate-limited)
    journaled shed event names a request it refused."""
    from shifu_tensorflow_tpu.obs.journal import read_events

    cfg = ServeConfig(model_dir=export_dir, port=0, max_batch=8,
                      max_delay_ms=1.0, max_queue_rows=16,
                      reload_poll_ms=0)
    with ScoringServer(cfg) as srv:
        inner = srv._score_once
        release = threading.Event()

        # barrier-gated dispatch (same deflake as the overload drill
        # above): the flood overruns the bound by arithmetic, not by
        # out-racing the drain on whatever cores CI has
        def gated(rows):
            release.wait(15.0)
            return inner(rows)

        srv.batcher._score = gated
        srv.start()
        results = []
        lock = threading.Lock()

        def client(i: int):
            status, headers, _ = _post_rid(
                srv.port, {"rows": _rows(4, seed=i).tolist()},
                rid=f"flood-{i}")
            with lock:
                results.append((status, headers))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(24)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 10.0
        while (srv.metrics.counters()["shed_total"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.01)
        release.set()
        for t in threads:
            t.join(timeout=60.0)
    shed = [(s, h) for s, h in results if s == 429]
    assert shed, "overload never shed"
    for _, headers in shed:
        assert headers.get("X-Request-Id", "").startswith("flood-")
    shed_events = [e for e in read_events(obs_env)
                   if e["event"] == "shed"]
    assert shed_events and any(
        str(e.get("rid", "")).startswith("flood-") for e in shed_events)


def test_slo_breach_recover_drill_reconstructible_from_files(
        export_dir, obs_env, capsys):
    """The acceptance chaos drill: sustained overload drives the
    windowed shed rate past its shifu.tpu.slo-serve-shed-rate target →
    the watchdog journals slo_breach (with the offending window's digest
    snapshot); the load stops, the window drains, slo_recover lands —
    and the whole sequence is reconstructible by `obs trace` and `obs
    top --once` from the dead fleet's files alone."""
    from shifu_tensorflow_tpu.obs.__main__ import main as obs_main
    from shifu_tensorflow_tpu.obs.journal import read_events

    cfg = ServeConfig(model_dir=export_dir, port=0, max_batch=8,
                      max_delay_ms=1.0, max_queue_rows=16,
                      reload_poll_ms=0)
    with ScoringServer(cfg) as srv:
        assert srv._slo is not None, "watchdog not picked up at construction"
        inner = srv._score_once

        def slow(rows):
            time.sleep(0.02)
            return inner(rows)

        srv.batcher._score = slow
        srv.start()

        def client(i: int):
            for k in range(8):
                _post_rid(srv.port, {"rows": _rows(4, seed=i).tolist()},
                          rid=f"drill-{i}-{k}")

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        # the evaluator thread (0.25s tick at window 2s) must see the
        # breach while the shed window is still hot
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if any(e["event"] == "slo_breach"
                   for e in read_events(obs_env)):
                break
            time.sleep(0.1)
        # gauges ride /metrics while the server is alive
        import urllib.request

        text = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10
        ).read().decode()
        assert "stpu_slo_serve_shed_rate" in text
        # overload over: the window drains and the watchdog recovers
        deadline = time.time() + 15.0
        while time.time() < deadline:
            if any(e["event"] == "slo_recover"
                   for e in read_events(obs_env)):
                break
            time.sleep(0.1)
    # ---- the fleet is dead; everything below reads its files alone ----
    events = read_events(obs_env)
    kinds = [e["event"] for e in events]
    assert "slo_breach" in kinds, "overload never breached the SLO"
    assert "slo_recover" in kinds, "watchdog never recovered"
    breach = next(e for e in events if e["event"] == "slo_breach")
    recover = next(e for e in events if e["event"] == "slo_recover")
    assert breach["ts"] < recover["ts"]
    assert breach["signal"] == "serve_shed_rate"
    assert breach["value"] > breach["target"] == 0.25
    # the offending window's digest snapshot rides the breach event
    assert breach["window"]["count"] > 0 and breach["window"]["shed"] > 0
    assert recover["breach_s"] > 0
    # a scored request's rid resolves through `obs trace`
    scored = next(e for e in events if e["event"] == "serve_batch")
    rid = scored["rids"][0]
    assert obs_main(["trace", rid, "--journal", obs_env]) == 0
    out = capsys.readouterr().out
    assert "serve_batch" in out and rid in out
    # and `obs top --once` renders the same story without a live fleet
    assert obs_main(["top", "--journal", obs_env, "--once"]) == 0
    out = capsys.readouterr().out
    assert "serve_shed_rate" in out and "recent events" in out


# ------------------------------------------------------------ CLI surface


def test_serve_cli_smoke(export_dir, tmp_path):
    """python -m shifu_tensorflow_tpu.serve: listening line, scoring over
    HTTP, clean SIGTERM shutdown with the final summary line."""
    import signal
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "shifu_tensorflow_tpu.serve",
         "--model-dir", export_dir, "--port", "0",
         "--reload-poll-ms", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    try:
        line = proc.stdout.readline().decode()
        ready = json.loads(line)
        assert ready["state"] == "listening" and ready["model_verified"]
        status, _, body = _post(ready["port"],
                                {"rows": _rows(2).tolist()})
        assert status == 200 and len(body["scores"]) == 2
        status, text = _get(ready["port"], "/metrics")
        assert status == 200 and "stpu_serve_requests_total 1" in text
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=30.0)
        assert proc.returncode == 0, err.decode()[-2000:]
        summary = json.loads(out.decode().strip().splitlines()[-1])
        assert summary["state"] == "stopped"
        assert summary["requests_total"] == 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


def test_multiworker_chaos_drill_serves_warmed_model_bit_identically(
    export_dir, tmp_path
):
    """The acceptance drill at scale-out: --serve-workers 2 share one
    SO_REUSEPORT port; a hot reload under STPU_FAULT_PLAN at-rest
    corruption is refused by BOTH scoring processes, which keep serving
    the previous verified, pre-warmed model bit-identically; a good
    artifact recovers both; SIGTERM drains the whole process group
    cleanly with per-worker journals."""
    import signal
    import subprocess
    import sys

    journal = str(tmp_path / "serve.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    x = _rows(16, seed=3)
    proc = subprocess.Popen(
        [sys.executable, "-m", "shifu_tensorflow_tpu.serve",
         "--model-dir", export_dir, "--port", "0", "--serve-workers", "2",
         "--reload-poll-ms", "200", "--obs-journal", journal],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    try:
        ready = json.loads(proc.stdout.readline().decode())
        assert ready["state"] == "listening" and ready["workers"] == 2
        port = ready["port"]

        def metrics_by_worker() -> dict[int, dict]:
            """Scrape until every worker index has answered (the kernel
            routes each connection to an arbitrary listener)."""
            seen: dict[int, dict] = {}
            deadline = time.time() + 30.0
            while len(seen) < 2 and time.time() < deadline:
                _, text = _get(port, "/metrics")
                fields = dict(
                    line.rsplit(" ", 1)
                    for line in text.splitlines()
                    if line and not line.startswith("#")
                    and " " in line
                )
                idx = int(float(fields.get("stpu_serve_worker_index", -1)))
                if idx >= 0:
                    seen[idx] = fields
            return seen

        assert set(metrics_by_worker()) == {0, 1}
        _, _, v1 = _post(port, {"rows": x.tolist()})
        assert v1["model_epoch"] == 0

        # corrupt artifact lands (payload mutated AFTER the manifest
        # digest, the at-rest signature) — both workers must refuse it
        faults.set_plan(
            faults.FaultPlan.parse("export.at-rest:bitflip@1", seed=11)
        )
        _export(export_dir, seed=99)
        faults.set_plan(None)
        deadline = time.time() + 30.0
        while time.time() < deadline:
            by_worker = metrics_by_worker()
            if len(by_worker) == 2 and all(
                float(m.get("stpu_serve_reload_failures_total", 0)) >= 1
                for m in by_worker.values()
            ):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(
                "both workers never refused the corrupt artifact"
            )
        # every score — whichever worker the kernel picks — is the OLD
        # verified model, bit-for-bit
        for _ in range(8):
            status, _, mid = _post(port, {"rows": x.tolist()})
            assert status == 200
            assert mid["model_epoch"] == 0
            assert mid["scores"] == v1["scores"]

        # recovery: a good artifact admits on both workers
        _export(export_dir, seed=99)
        deadline = time.time() + 30.0
        while time.time() < deadline:
            _, _, now = _post(port, {"rows": x.tolist()})
            if now["model_epoch"] == 1:
                break
            time.sleep(0.1)
        assert now["model_epoch"] == 1 and now["scores"] != v1["scores"]

        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=60.0)
        assert proc.returncode == 0, err.decode()[-2000:]
        summary = json.loads(out.decode().strip().splitlines()[-1])
        assert summary["state"] == "stopped" and summary["workers"] == 2
        assert summary["requests_total"] >= 9
        # per-worker journal siblings carry the refusal + lifecycle
        from shifu_tensorflow_tpu.obs.journal import (
            journal_files,
            read_events,
        )

        names = {os.path.basename(p) for p in journal_files(journal)}
        assert {"serve.jsonl", "serve.jsonl.s0", "serve.jsonl.s1"} <= names
        events = read_events(journal)
        refused_by = {e.get("worker") for e in events
                      if e["event"] == "reload_refused"}
        assert refused_by == {0, 1}
        assert {e.get("worker") for e in events
                if e["event"] == "serve_start"} == {0, 1}
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
