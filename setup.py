"""Build hook: compile the native libraries into the wheel.

The C++ pieces (cpp/stpu_data.cc block/stream parser, cpp/stpu_scorer.cc
batch scorer) build via the plain Makefile into
``shifu_tensorflow_tpu/_native/`` and ship as package data.  Every caller
has a pure-Python fallback, so a build host without a toolchain still
produces a working (slower) wheel — same degrade-not-break contract as the
lazy in-tree build (_native/__init__.py).
"""

import os
import shutil
import subprocess
import sys

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildNativeThenPy(build_py):
    def run(self) -> None:
        if not os.path.isdir("cpp"):
            # an sdist missing cpp/ (MANIFEST.in ships it) would silently
            # produce a pure-Python-only wheel — say so loudly
            print(
                "WARNING: cpp/ sources absent; wheel will contain no "
                "native libraries (pure-Python fallbacks only)",
                file=sys.stderr,
            )
        elif shutil.which("make") and shutil.which("g++"):
            proc = subprocess.run(["make", "-C", "cpp"], check=False)
            built = [
                os.path.join("shifu_tensorflow_tpu", "_native", so)
                for so in ("libstpu_data.so", "libstpu_scorer.so")
            ]
            if proc.returncode != 0 or not all(map(os.path.exists, built)):
                print(
                    "WARNING: native compile failed; wheel will contain "
                    "no native libraries (pure-Python fallbacks only)",
                    file=sys.stderr,
                )
        else:
            print(
                "WARNING: no make/g++ toolchain; wheel will contain no "
                "native libraries (pure-Python fallbacks only)",
                file=sys.stderr,
            )
        super().run()


setup(cmdclass={"build_py": BuildNativeThenPy})
