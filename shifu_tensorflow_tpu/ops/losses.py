"""Loss functions.

Parity surface: the reference trains with
``tf.losses.mean_squared_error(predictions, labels, weights)`` whose default
TF-1.x reduction is SUM_BY_NONZERO_WEIGHTS — sum(w·(y−p)²) divided by the
*count of nonzero weights*, not the weight sum (ssgd_monitor.py:129).
``weighted_mse`` reproduces that exactly; it also makes zero-weight padding
rows free (they join neither numerator nor denominator), which is what the
fixed-shape batching relies on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def weighted_mse(pred: jax.Array, target: jax.Array, weight: jax.Array) -> jax.Array:
    """sum(w * (t - p)^2) / count(w != 0)  (TF1 SUM_BY_NONZERO_WEIGHTS)."""
    sq = weight * jnp.square(target - pred)
    nonzero = jnp.sum((weight != 0.0).astype(sq.dtype))
    return jnp.sum(sq) / jnp.maximum(nonzero, 1.0)


def weighted_bce(pred: jax.Array, target: jax.Array, weight: jax.Array,
                 eps: float = 1e-7) -> jax.Array:
    """Weighted binary cross-entropy on probabilities (model outputs are
    post-sigmoid, matching the reference's output head), same
    nonzero-weight normalization as weighted_mse."""
    p = jnp.clip(pred, eps, 1.0 - eps)
    ll = target * jnp.log(p) + (1.0 - target) * jnp.log(1.0 - p)
    nonzero = jnp.sum((weight != 0.0).astype(ll.dtype))
    return -jnp.sum(weight * ll) / jnp.maximum(nonzero, 1.0)


def l2_penalty(params, scale: float) -> jax.Array:
    """Real L2 over all kernel/bias leaves.  The reference declared
    l2_regularizer(0.1) but never added it to the loss (dead config —
    ssgd_monitor.py:58 vs :129); enable via TrainParams.l2_reg."""
    if scale == 0.0:
        return jnp.asarray(0.0)
    leaves = jax.tree_util.tree_leaves(params)
    return scale * sum(jnp.sum(jnp.square(p)) for p in leaves)


LOSSES = {"mse": weighted_mse, "bce": weighted_bce}


def get_loss(name: str):
    try:
        return LOSSES[name.lower()]
    except KeyError:
        raise ValueError(f"unknown loss {name!r}; known: {sorted(LOSSES)}")
