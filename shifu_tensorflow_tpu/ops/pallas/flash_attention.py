"""Flash attention as a Pallas TPU kernel.

The sequence family's measured bottleneck is attention-score
materialization: BENCH_SEQUENCE_TPU.json shows a 7× tokens/s falloff
from S=256 to S=4096 at a fixed token budget (full attention builds the
(S, S) score matrix in HBM; at S=4096 that is gigabytes).  The reference
has no attention at all (fixed-width tabular vectors — SURVEY.md §5.7);
this kernel serves the beyond-parity sequence/long-context family.

Design — the standard flash decomposition, Pallas-TPU idioms:

- grid ``(B·H, S/BQ, S/BK)`` with the K/V axis innermost; VMEM scratch
  (running numerator ``acc``, running max ``m``, normalizer ``l``)
  persists across the sequential K/V steps of one (batch·head, q-block);
- each step computes a (BQ, BK) score tile on the MXU
  (``preferred_element_type=f32``), applies the online-softmax update,
  and accumulates ``p @ v`` — the (S, S) matrix never exists anywhere;
- the last K/V step normalizes and writes the output block;
- causal + padding masks come from ``broadcasted_iota`` positions, so
  arbitrary (non-multiple-of-block) S works via zero-padding.

The backward pass is the chunked XLA path (`parallel.ring.
chunked_attention`) through ``jax.vjp`` — same O(S·block) memory
property, exact attention gradients, no second kernel to maintain.
Parity vs full attention is asserted in tests/test_flash.py (interpret
mode on CPU, real kernel on TPU).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _resolve_interpret(interpret: bool | None) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  scale: float, causal: bool, s_real: int,
                  block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    # (BQ, BK) score tile on the MXU; accumulate in f32 regardless of
    # the input dtype so bf16 inputs keep full-precision statistics
    scores = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = k_pos < s_real  # zero-padded keys must not attend
    if causal:
        valid = jnp.logical_and(valid, k_pos <= q_pos)
    scores = jnp.where(valid, scores, -jnp.inf)

    m_prev = m_ref[:]
    l_prev = l_ref[:]
    m_blk = jnp.max(scores, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    # nothing seen yet where m_new is still -inf: keep correction at 0
    corr = jnp.where(jnp.isneginf(m_new), 0.0, jnp.exp(m_prev - m_new))
    p = jnp.exp(scores - m_new)
    p = jnp.where(valid, p, 0.0)
    l_ref[:] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = m_new

    @pl.when(ki == nk - 1)
    def _():
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal: bool, block_q: int, block_k: int,
                   interpret: bool | None):
    import math

    b, s, h, d = q.shape
    scale = d ** -0.5
    dp = _round_up(d, 128)
    # pad S to a common multiple of BOTH blocks: rounding to only the
    # larger one truncates the grid for the smaller (sp // block floors),
    # silently dropping trailing query rows or key blocks
    sp = _round_up(s, math.lcm(block_q, block_k))
    bq = min(block_q, sp)
    bk = min(block_k, sp)

    def prep(x):  # (B, S, H, D) -> (B*H, Sp, Dp), zero-padded
        x = jnp.pad(x, ((0, 0), (0, sp - s), (0, 0), (0, dp - d)))
        return x.transpose(0, 2, 1, 3).reshape(b * h, sp, dp)

    qp, kp, vp = prep(q), prep(k), prep(v)
    grid = (b * h, sp // bq, sp // bk)
    out = pl.pallas_call(
        partial(_flash_kernel, scale=scale, causal=causal, s_real=s,
                block_q=bq, block_k=bk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, dp), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, dp), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, dp), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, dp), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sp, dp), q.dtype),
        scratch_shapes=[
            _vmem((bq, dp)),
            _vmem((bq, 1)),
            _vmem((bq, 1)),
        ],
        interpret=_resolve_interpret(interpret),
    )(qp, kp, vp)
    out = out.reshape(b, h, sp, dp).transpose(0, 2, 1, 3)
    return out[:, :s, :, :d]


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Fused flash attention, shapes (B, S, H, D).

    Forward: the Pallas kernel above (interpret mode off-TPU).
    Backward: exact attention gradients via the chunked XLA path —
    same no-S×S-materialization property, one kernel to maintain.
    """
    return _flash_forward(q, k, v, causal=causal, block_q=block_q,
                          block_k=block_k, interpret=interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal=causal, block_q=block_q,
                         block_k=block_k, interpret=interpret)
    return out, (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    from shifu_tensorflow_tpu.parallel.ring import chunked_attention

    q, k, v = res
    # chunked_attention self-adjusts block_size to a divisor of S, so no
    # fallback here — falling back to S would mean full attention in the
    # backward, materializing the S×S matrix this kernel exists to avoid.
    # The block is never SMALLER than 512 — the sweet spot measured in
    # BENCH_SEQUENCE_TPU.json (and the default callers pass
    # block_q=block_k=128, which must not shrink the backward chunk) —
    # but a caller tuning the forward blocks LARGER raises it too.  For
    # S <= block the chunked path degenerates to one block — i.e. full
    # attention — which at that scale is the memory-optimal choice.
    block = max(512, block_q, block_k)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: chunked_attention(
            q_, k_, v_, causal=causal, block_size=block),
        q, k, v,
    )
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
