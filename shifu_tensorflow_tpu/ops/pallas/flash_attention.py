"""Flash attention as a Pallas TPU kernel.

The sequence family's measured bottleneck is attention-score
materialization: BENCH_SEQUENCE_TPU.json shows a 7× tokens/s falloff
from S=256 to S=4096 at a fixed token budget (full attention builds the
(S, S) score matrix in HBM; at S=4096 that is gigabytes).  The reference
has no attention at all (fixed-width tabular vectors — SURVEY.md §5.7);
this kernel serves the beyond-parity sequence/long-context family.

Design — the standard flash decomposition, Pallas-TPU idioms:

- grid ``(B·H, S/BQ, S/BK)`` with the K/V axis innermost; VMEM scratch
  (running numerator ``acc``, running max ``m``, normalizer ``l``)
  persists across the sequential K/V steps of one (batch·head, q-block);
- each step computes a (BQ, BK) score tile on the MXU
  (``preferred_element_type=f32``), applies the online-softmax update,
  and accumulates ``p @ v`` — the (S, S) matrix never exists anywhere;
- the last K/V step normalizes and writes the output block;
- causal + padding masks come from ``broadcasted_iota`` positions, so
  arbitrary (non-multiple-of-block) S works via zero-padding.

The backward pass is a true Pallas FlashAttention-2 backward (new in
r05; the forward now also emits per-row logsumexp): one kernel
accumulates dQ over key blocks, a second accumulates dK/dV over query
blocks, P reconstructed per tile from the saved logsumexp — no S×S
matrix in either pass.  ``STPU_FLASH_BWD=chunked`` selects the previous
chunked-XLA-scan gradient for A/B measurement
(scripts/bench_flash_sweep.py).  Parity vs full attention is asserted
in tests/test_flash.py (interpret mode on CPU, real kernel on TPU).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _resolve_interpret(interpret: bool | None) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                  l_ref, *, scale: float, causal: bool, s_real: int,
                  block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    # (BQ, BK) score tile on the MXU; accumulate in f32 regardless of
    # the input dtype so bf16 inputs keep full-precision statistics
    scores = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = k_pos < s_real  # zero-padded keys must not attend
    if causal:
        valid = jnp.logical_and(valid, k_pos <= q_pos)
    scores = jnp.where(valid, scores, -jnp.inf)

    m_prev = m_ref[:]
    l_prev = l_ref[:]
    m_blk = jnp.max(scores, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    # nothing seen yet where m_new is still -inf: keep correction at 0
    corr = jnp.where(jnp.isneginf(m_new), 0.0, jnp.exp(m_prev - m_new))
    p = jnp.exp(scores - m_new)
    p = jnp.where(valid, p, 0.0)
    l_ref[:] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = m_new

    @pl.when(ki == nk - 1)
    def _():
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # logsumexp per query row, for the backward kernels: rows with no
        # valid key (l == 0, e.g. zero-padding) get +inf so that
        # exp(S - L) reconstructs P = 0 there instead of NaN
        lse = jnp.where(l_ref[:] > 0.0,
                        m_ref[:] + jnp.log(l_ref[:]), jnp.inf)
        lse_ref[0, :] = lse[:, 0]


def _pad_geom(q, block_q: int, block_k: int):
    import math

    b, s, h, d = q.shape
    dp = _round_up(d, 128)
    # pad S to a common multiple of BOTH blocks: rounding to only the
    # larger one truncates the grid for the smaller (sp // block floors),
    # silently dropping trailing query rows or key blocks
    sp = _round_up(s, math.lcm(block_q, block_k))
    bq = min(block_q, sp)
    bk = min(block_k, sp)
    return b, s, h, d, dp, sp, bq, bk


def _prep(x, b, s, h, d, dp, sp):
    """(B, S, H, D) -> (B*H, Sp, Dp), zero-padded."""
    x = jnp.pad(x, ((0, 0), (0, sp - s), (0, 0), (0, dp - d)))
    return x.transpose(0, 2, 1, 3).reshape(b * h, sp, dp)


def _unprep(xp, b, s, h, d, dp, sp):
    return xp.reshape(b, h, sp, dp).transpose(0, 2, 1, 3)[:, :s, :, :d]


def _flash_forward_with_stats(q, k, v, *, causal: bool, block_q: int,
                              block_k: int, interpret: bool | None):
    """Returns (out (B,S,H,D), lse (B*H, Sp) padded-layout logsumexp)."""
    from shifu_tensorflow_tpu.obs import compile as obs_compile

    b, s, h, d, dp, sp, bq, bk = _pad_geom(q, block_q, block_k)
    scale = d ** -0.5
    qp = _prep(q, b, s, h, d, dp, sp)
    kp = _prep(k, b, s, h, d, dp, sp)
    vp = _prep(v, b, s, h, d, dp, sp)
    grid = (b * h, sp // bq, sp // bk)
    # compile-attribution region (obs/compile.py): an EAGER call compiles
    # the kernel inside this frame and journals under the pallas name; a
    # call traced into an outer jitted step compiles later, inside that
    # step's own observed call — attributed there, which is the truth
    with obs_compile.attribute("pallas.flash_attention"):
        out, lse = pl.pallas_call(
            partial(_flash_kernel, scale=scale, causal=causal, s_real=s,
                    block_q=bq, block_k=bk),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, dp), lambda bh, qi, ki: (bh, qi, 0)),
                pl.BlockSpec((1, bk, dp), lambda bh, qi, ki: (bh, ki, 0)),
                pl.BlockSpec((1, bk, dp), lambda bh, qi, ki: (bh, ki, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, dp), lambda bh, qi, ki: (bh, qi, 0)),
                pl.BlockSpec((1, bq), lambda bh, qi, ki: (bh, qi)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, sp, dp), q.dtype),
                jax.ShapeDtypeStruct((b * h, sp), jnp.float32),
            ],
            scratch_shapes=[
                _vmem((bq, dp)),
                _vmem((bq, 1)),
                _vmem((bq, 1)),
            ],
            interpret=_resolve_interpret(interpret),
        )(qp, kp, vp)
    return _unprep(out, b, s, h, d, dp, sp), lse


def _flash_forward(q, k, v, *, causal: bool, block_q: int, block_k: int,
                   interpret: bool | None):
    out, _ = _flash_forward_with_stats(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret)
    return out


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _bwd_masks(qi, ki, block_q, block_k, s_real, causal):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = k_pos < s_real
    if causal:
        valid = jnp.logical_and(valid, k_pos <= q_pos)
    return valid


def _bwd_p_ds(qf, kf, vf, dof, lse, dvec, valid, scale):
    """Shared tile math: reconstruct P from the forward's logsumexp, then
    dS = P * (dP - D).  All f32; (bq, bk) tiles on the MXU."""
    s = jax.lax.dot_general(
        qf, kf, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    # rows with no valid key carry lse=+inf -> exp(-inf)=0, NaN-free
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(
        dof, vf, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - dvec)
    return p, ds


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref,
                         dq_ref, acc_ref, *, scale: float, causal: bool,
                         s_real: int, block_q: int, block_k: int):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    qf = q_ref[0].astype(jnp.float32)
    kf = k_ref[0].astype(jnp.float32)
    vf = v_ref[0].astype(jnp.float32)
    dof = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :][:, None]   # (bq, 1)
    dvec = d_ref[0, :][:, None]    # (bq, 1)
    valid = _bwd_masks(pl.program_id(1), ki, block_q, block_k, s_real,
                       causal)
    _, ds = _bwd_p_ds(qf, kf, vf, dof, lse, dvec, valid, scale)
    acc_ref[:] += jax.lax.dot_general(
        ds, kf, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, d_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                          causal: bool, s_real: int, block_q: int,
                          block_k: int):
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    qf = q_ref[0].astype(jnp.float32)
    kf = k_ref[0].astype(jnp.float32)
    vf = v_ref[0].astype(jnp.float32)
    dof = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :][:, None]
    dvec = d_ref[0, :][:, None]
    valid = _bwd_masks(qi, pl.program_id(1), block_q, block_k, s_real,
                       causal)
    p, ds = _bwd_p_ds(qf, kf, vf, dof, lse, dvec, valid, scale)
    # dV += P^T @ dO ; dK += dS^T @ Q * scale  (both (bk, dp))
    dv_acc[:] += jax.lax.dot_general(
        p, dof, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dk_acc[:] += jax.lax.dot_general(
        ds, qf, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, *, causal: bool, block_q: int,
                    block_k: int, interpret: bool | None):
    """True Pallas flash backward: P is reconstructed per tile from the
    forward's logsumexp (no S×S matrix anywhere), dQ accumulates over key
    blocks, dK/dV over query blocks — the FlashAttention-2 decomposition.
    """
    b, s, h, d, dp, sp, bq, bk = _pad_geom(q, block_q, block_k)
    scale = d ** -0.5
    qp = _prep(q, b, s, h, d, dp, sp)
    kp = _prep(k, b, s, h, d, dp, sp)
    vp = _prep(v, b, s, h, d, dp, sp)
    dop = _prep(g, b, s, h, d, dp, sp)
    outp = _prep(out, b, s, h, d, dp, sp)
    # D_i = sum_d dO_i * O_i — cheap elementwise+reduce, XLA does it well
    dvec = jnp.sum(dop.astype(jnp.float32) * outp.astype(jnp.float32),
                   axis=-1)  # (BH, Sp)
    interp = _resolve_interpret(interpret)

    dq = pl.pallas_call(
        partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                s_real=s, block_q=bq, block_k=bk),
        grid=(b * h, sp // bq, sp // bk),
        in_specs=[
            pl.BlockSpec((1, bq, dp), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, dp), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, dp), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bq, dp), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bq), lambda bh, qi, ki: (bh, qi)),
            pl.BlockSpec((1, bq), lambda bh, qi, ki: (bh, qi)),
        ],
        out_specs=pl.BlockSpec((1, bq, dp), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sp, dp), q.dtype),
        scratch_shapes=[_vmem((bq, dp))],
        interpret=interp,
    )(qp, kp, vp, dop, lse, dvec)

    dk, dv = pl.pallas_call(
        partial(_flash_bwd_dkv_kernel, scale=scale, causal=causal,
                s_real=s, block_q=bq, block_k=bk),
        grid=(b * h, sp // bk, sp // bq),
        in_specs=[
            pl.BlockSpec((1, bk, dp), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, bk, dp), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, bq, dp), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, dp), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq), lambda bh, ki, qi: (bh, qi)),
            pl.BlockSpec((1, bq), lambda bh, ki, qi: (bh, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, dp), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, bk, dp), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sp, dp), k.dtype),
            jax.ShapeDtypeStruct((b * h, sp, dp), v.dtype),
        ],
        scratch_shapes=[_vmem((bk, dp)), _vmem((bk, dp))],
        interpret=interp,
    )(kp, vp, qp, dop, lse, dvec)

    un = lambda xp: _unprep(xp, b, s, h, d, dp, sp)  # noqa: E731
    return un(dq), un(dk), un(dv)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Fused flash attention, shapes (B, S, H, D).

    Forward: the Pallas kernel above (interpret mode off-TPU).
    Backward: the Pallas FlashAttention-2 backward (_flash_backward) —
    P reconstructed per tile from the forward's saved logsumexp, dQ/dK/dV
    accumulated blockwise, no S×S matrix in either pass.  Set
    ``STPU_FLASH_BWD=chunked`` to fall back to the chunked-XLA-scan
    gradient (the pre-r05 behavior) for A/B measurement
    (scripts/bench_flash_sweep.py).

    ``STPU_FLASH_BWD`` is read at TRACE time: when the gradient is taken
    inside a jitted train step, the chosen branch is baked into the cached
    jaxpr, so flipping the env var mid-process silently keeps whichever
    backward was traced first.  To actually switch, start a new process
    (how bench_flash_sweep.py runs its subprocess-per-case A/B) or clear
    the jit caches (``jax.clear_caches()``) before the next call.
    """
    return _flash_forward(q, k, v, causal=causal, block_q=block_q,
                          block_k=block_k, interpret=interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward_with_stats(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    import os

    q, k, v, out, lse = res
    # trace-time read: under jit this branch is frozen into the cached
    # jaxpr — see the flash_attention docstring for the switching contract
    if os.environ.get("STPU_FLASH_BWD", "pallas") == "chunked":
        from shifu_tensorflow_tpu.parallel.ring import chunked_attention

        # chunked fallback: never SMALLER than 512 — the sweet spot
        # measured in BENCH_SEQUENCE_TPU.json (default callers pass
        # block_q=block_k=128, which must not shrink the backward chunk)
        block = max(512, block_q, block_k)
        _, vjp = jax.vjp(
            lambda q_, k_, v_: chunked_attention(
                q_, k_, v_, causal=causal, block_size=block),
            q, k, v,
        )
        return vjp(g)
    return _flash_backward(q, k, v, out, lse, g, causal=causal,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
