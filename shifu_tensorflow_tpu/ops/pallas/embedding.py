"""Hashed-embedding lookup with a Pallas TPU gather-as-matmul kernel.

SURVEY.md §7.1 item 8 names the embedding gather as the likely XLA gap to
close with Pallas.  The XLA path (models/embeddings.py) lowers
``jnp.take(table, ids)`` to a dynamic gather that runs on the VPU/scalar
units and leaves the MXU idle.  Here the gather is expressed as a one-hot ×
table matmul accumulated over table tiles — the MXU-native formulation —
with the table streamed through VMEM tile by tile:

    out[r, :] = Σ_tiles  onehot(ids[r] - tile_base) @ table_tile

The bucket ids are computed by the caller with ``ops.hashing`` (elementwise
uint32 ops XLA fuses into the surrounding program; Mosaic cannot relayout
the (B, C) → (B·C, 1) id reshape in-kernel, so hashing stays outside).  The
backward pass is the transpose — one-hotᵀ × g, a scatter-add as the same
MXU matmul — via custom_vjp.

Bucket assignment uses ``hashing.salted_bucket_ids`` for both this and the
XLA path, so the two implementations are bit-identical; tests assert exact
equality of outputs and gradients.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from shifu_tensorflow_tpu.ops import hashing


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _gather_kernel(ids_ref, table_ref, out_ref, *, h_tile: int):
    j = pl.program_id(1)  # table-tile position (innermost: accumulation)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    rb = ids_ref.shape[0]
    base = j * h_tile
    iota = jax.lax.broadcasted_iota(jnp.int32, (rb, h_tile), 1)
    onehot = (iota + base == ids_ref[:]).astype(table_ref.dtype)
    # HIGHEST: f32 operands must not be truncated to one bf16 MXU pass —
    # gathered rows (and the bwd scatter sums) must match the XLA path
    out_ref[:] += jnp.dot(
        onehot, table_ref[:], preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ).astype(out_ref.dtype)


def _scatter_kernel(ids_ref, g_ref, dtable_ref, *, h_tile: int):
    i = pl.program_id(1)  # row-block position (innermost: accumulation)

    @pl.when(i == 0)
    def _():
        dtable_ref[:] = jnp.zeros_like(dtable_ref)

    rb = ids_ref.shape[0]
    base = pl.program_id(0) * h_tile
    iota = jax.lax.broadcasted_iota(jnp.int32, (rb, h_tile), 1)
    onehot = (iota + base == ids_ref[:]).astype(dtable_ref.dtype)
    # onehotᵀ @ g : contract the row axis of both — the scatter-add
    dtable_ref[:] += jax.lax.dot_general(
        onehot, g_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ).astype(dtable_ref.dtype)


def _resolve_interpret(interpret: bool | None) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _block_shapes(n_rows: int, hash_size: int, block_rows: int, h_tile: int):
    rb = min(block_rows, _round_up(max(n_rows, 1), 8))
    ht = min(h_tile, _round_up(hash_size, 128))
    return rb, ht


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def embedding_gather(
    ids: jax.Array,
    table: jax.Array,
    block_rows: int = 1024,
    h_tile: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """(N,) int32 bucket ids, (H, D) table -> (N, D) rows, on the MXU.

    ``interpret=None`` auto-selects interpreter mode off-TPU so the same
    call runs (slowly, for tests) on the CPU mesh.
    """
    return _gather_impl(ids, table, block_rows, h_tile, interpret)


def _gather_impl(ids, table, block_rows, h_tile, interpret):
    from shifu_tensorflow_tpu.obs import compile as obs_compile

    (n,) = ids.shape
    hash_size, dim = table.shape
    rb, ht = _block_shapes(n, hash_size, block_rows, h_tile)
    n_pad = _round_up(n, rb)
    h_pad = _round_up(hash_size, ht)
    # pad ids with -1: matches no table row, so padded rows read zeros
    idp = jnp.pad(ids.reshape(n, 1), ((0, n_pad - n), (0, 0)),
                  constant_values=-1)
    tp = jnp.pad(table, ((0, h_pad - hash_size), (0, 0)))

    # compile-attribution region (obs/compile.py): an eager call's
    # kernel compile journals under the pallas name; traced into a
    # jitted step, the compile lands on that step's observed call
    with obs_compile.attribute("pallas.embedding_gather"):
        out = pl.pallas_call(
            partial(_gather_kernel, h_tile=ht),
            grid=(n_pad // rb, h_pad // ht),
            in_specs=[
                pl.BlockSpec((rb, 1), lambda i, j: (i, 0)),
                pl.BlockSpec((ht, dim), lambda i, j: (j, 0)),
            ],
            out_specs=pl.BlockSpec((rb, dim), lambda i, j: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n_pad, dim), table.dtype),
            interpret=_resolve_interpret(interpret),
        )(idp, tp)
    return out[:n]


def _gather_fwd(ids, table, block_rows, h_tile, interpret):
    return _gather_impl(ids, table, block_rows, h_tile, interpret), (ids, table)


def _gather_bwd(block_rows, h_tile, interpret, res, g):
    ids, table = res
    (n,) = ids.shape
    (hash_size, dim), tdtype = table.shape, table.dtype
    rb, ht = _block_shapes(n, hash_size, block_rows, h_tile)
    n_pad = _round_up(n, rb)
    h_pad = _round_up(hash_size, ht)
    idp = jnp.pad(ids.reshape(n, 1), ((0, n_pad - n), (0, 0)),
                  constant_values=-1)
    # zero-padded gradient rows contribute nothing to the scatter-add
    gp = jnp.pad(g.astype(tdtype), ((0, n_pad - n), (0, 0)))

    dtable = pl.pallas_call(
        partial(_scatter_kernel, h_tile=ht),
        grid=(h_pad // ht, n_pad // rb),
        in_specs=[
            pl.BlockSpec((rb, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((rb, dim), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((ht, dim), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((h_pad, dim), tdtype),
        interpret=_resolve_interpret(interpret),
    )(idp, gp)
    # integer ids carry a float0 tangent
    return (np.zeros(ids.shape, jax.dtypes.float0), dtable[:hash_size])


embedding_gather.defvjp(_gather_fwd, _gather_bwd)


def hashed_embedding_lookup(
    x: jax.Array,
    table: jax.Array,
    block_rows: int = 1024,
    h_tile: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """(B, C) float categories, (H, D) table -> (B, C*D) embeddings.

    Hash (XLA-fused elementwise) + Pallas MXU gather; drop-in for the XLA
    path in models/embeddings.HashedEmbedding.
    """
    n, c = x.shape
    dim = table.shape[1]
    ids = hashing.salted_bucket_ids(x, table.shape[0]).reshape(n * c)
    rows = embedding_gather(ids, table, block_rows, h_tile, interpret)
    return rows.reshape(n, c * dim)
