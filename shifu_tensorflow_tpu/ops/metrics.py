"""Evaluation metrics for binary tabular models.

The reference reports only train/valid loss through its metrics plane
(SocketServer.java:71-89); the framework's north-star quality metric is the
KS statistic (BASELINE.json: "wall-clock to KS>=0.45"), so KS and AUC are
first-class here.  Implementations are vectorized numpy over host-gathered
scores — eval sets are the small side of the workload.
"""

from __future__ import annotations

import numpy as np


def _prep(scores, labels, weights=None):
    s = np.asarray(scores, np.float64).ravel()
    y = np.asarray(labels, np.float64).ravel()
    w = (
        np.ones_like(s)
        if weights is None
        else np.asarray(weights, np.float64).ravel()
    )
    keep = w > 0
    return s[keep], y[keep], w[keep]


def _grouped(s, y, w):
    """Sort descending and collapse tied scores: returns per-unique-score
    positive/negative weight sums (ties must share one ROC point)."""
    order = np.argsort(-s, kind="stable")
    s, y, w = s[order], y[order], w[order]
    # boundaries of tie groups in the descending-sorted scores
    is_last = np.empty(s.size, bool)
    is_last[-1] = True
    is_last[:-1] = s[1:] != s[:-1]
    group_id = np.cumsum(np.concatenate([[0], is_last[:-1].astype(np.int64)]))
    n_groups = group_id[-1] + 1
    pos = np.bincount(group_id, w * (y > 0.5), minlength=n_groups)
    neg = np.bincount(group_id, w * (y <= 0.5), minlength=n_groups)
    return pos, neg


def ks_statistic(scores, labels, weights=None) -> float:
    """Kolmogorov–Smirnov: max |cum-pos-rate − cum-neg-rate| over score
    thresholds (the standard scorecard KS).  Tie-correct: the curve is
    evaluated only at unique-score boundaries."""
    s, y, w = _prep(scores, labels, weights)
    if s.size == 0:
        return 0.0
    pos, neg = _grouped(s, y, w)
    tot_pos, tot_neg = pos.sum(), neg.sum()
    if tot_pos == 0 or tot_neg == 0:
        return 0.0
    tpr = np.cumsum(pos) / tot_pos
    fpr = np.cumsum(neg) / tot_neg
    return float(np.max(np.abs(tpr - fpr)))


def auc(scores, labels, weights=None) -> float:
    """Weighted ROC AUC = P(score_pos > score_neg) + 0.5·P(tie), computed
    over tie groups so constant scores give exactly 0.5."""
    s, y, w = _prep(scores, labels, weights)
    if s.size == 0:
        return 0.5
    pos, neg = _grouped(s, y, w)
    tot_pos, tot_neg = pos.sum(), neg.sum()
    if tot_pos == 0 or tot_neg == 0:
        return 0.5
    # scanning descending: negatives strictly below group g plus half the
    # tied negatives
    neg_above_incl = np.cumsum(neg)
    neg_below = tot_neg - neg_above_incl
    num = np.sum(pos * (neg_below + 0.5 * neg))
    return float(num / (tot_pos * tot_neg))


def accuracy(scores, labels, weights=None, threshold: float = 0.5) -> float:
    s, y, w = _prep(scores, labels, weights)
    if s.size == 0:
        return 0.0
    correct = ((s >= threshold) == (y > 0.5)).astype(np.float64)
    return float((correct * w).sum() / w.sum())
