"""On-device feature hashing shared by the XLA and Pallas embedding paths.

One source of truth: both `models.embeddings` (XLA gather) and
`ops.pallas.embedding` (fused TPU kernel) call these functions, so bucket
assignment is bit-identical whichever implementation runs — the same
parity discipline the data layer applies to its native/Python parsers.

The hash is multiplicative (Fibonacci) hashing over the raw float bits:
elementwise uint32 ops only, so it fuses into surrounding XLA and is legal
inside a Pallas kernel body.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# large odd multipliers for the multiplicative hash
HASH_MULT = 2654435761
HASH_MULT2 = 40503
# per-column salt so the same value in different columns hashes apart
COLUMN_SALT = 0x9E3779B9


def mix(bits: jax.Array) -> jax.Array:
    """Finalizer of the multiplicative hash: uint32 bits -> uint32."""
    h = bits * jnp.uint32(HASH_MULT)
    h = h ^ (h >> 16)
    return h * jnp.uint32(HASH_MULT2)


def float_bits(values: jax.Array) -> jax.Array:
    """Bit-cast floats so distinct raw category codes (e.g. 3.0 vs 4.0)
    hash apart; elementwise and fusable."""
    return jax.lax.bitcast_convert_type(values.astype(jnp.float32), jnp.uint32)


def hash_to_buckets(values: jax.Array, hash_size: int) -> jax.Array:
    """Hash float feature values into [0, hash_size) on device."""
    return (mix(float_bits(values)) % jnp.uint32(hash_size)).astype(jnp.int32)


def salted_bucket_ids(x: jax.Array, hash_size: int) -> jax.Array:
    """(B, C) float categories -> (B, C) int32 bucket ids, column-salted.

    Uses ``broadcasted_iota`` (not ``arange``) for the column index so the
    identical function body is legal inside a Pallas TPU kernel, where 1-D
    iota does not lower.
    """
    cols = jax.lax.broadcasted_iota(jnp.uint32, x.shape, dimension=x.ndim - 1)
    salted = float_bits(x) ^ (cols * jnp.uint32(COLUMN_SALT))
    return (mix(salted) % jnp.uint32(hash_size)).astype(jnp.int32)


def crossed_bucket_ids(x: jax.Array, hash_size: int) -> jax.Array:
    """(B, C) float categories -> (B,) int32: one joint id per row (the
    'crossed column' hash of classic wide&deep)."""
    bits = float_bits(x)
    h = jnp.zeros(x.shape[:1], jnp.uint32)
    for c in range(x.shape[-1]):
        h = (h ^ bits[:, c]) * jnp.uint32(HASH_MULT)
        h = h ^ (h >> 13)
    return (h % jnp.uint32(hash_size)).astype(jnp.int32)
