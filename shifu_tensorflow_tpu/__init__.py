"""shifu_tensorflow_tpu — a TPU-native distributed training framework for tabular ML.

A ground-up JAX/XLA/pjit/Pallas re-design of the capabilities of
ShifuML/shifu-tensorflow (distributed TensorFlow-on-YARN for the Shifu
tabular pipeline).  Where the reference runs TF-1.x parameter-server
training inside YARN containers coordinated by an embedded ZooKeeper
(reference: shifu-tensorflow-on-yarn/.../TensorflowSession.java), this
framework runs SPMD data-parallel training over a `jax.sharding.Mesh`
with gradient all-reduce over ICI, streams normalized column shards
into device infeed, and exports the same serving artifact contract
(`shifu_input_0` -> `shifu_output_0` SavedModel + GenericModelConfig.json,
reference: ssgd_monitor.py:457-490) so downstream Java batch scoring
is unchanged.

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):

  L6  export/   - serving-artifact export + scoring parity (Python + C++)
  L5  train/    - jitted train step, epoch loop, checkpointing
  L4  models/   - config-driven model zoo (DNN, Wide&Deep, multi-task, embeddings)
  L3  parallel/ - mesh, shardings, collectives, multi-host init
  L2  coordinator/ - job submitter / coordinator / worker lifecycle
  L1  data/     - sharded streaming input pipeline (PSV+gzip, ZSCALE)
  L0  config/ + utils/ - layered configuration, typed keys, fs helpers
"""

__version__ = "0.1.0"

from shifu_tensorflow_tpu.config.conf import Conf  # noqa: F401
from shifu_tensorflow_tpu.config.model_config import ModelConfig  # noqa: F401
