"""Compile flight recorder: every XLA compilation, journaled and priced.

The host-side obs plane (PRs 4+7) can say *where the step's wall clock
went*; it cannot say *what the compiler did* — how many programs this
process built, how long each took, what they cost in flops and device
bytes, and (the classic production incident) whether an unpadded input
shape is quietly recompiling the same callable hundreds of times.  The
reference had nothing here at all; TensorFlow ships per-op cost/memory
accounting as a first-class runtime subsystem (PAPERS.md), and both
ROADMAP item 1 (sharded SPMD) and item 5 (pipeline parallelism) need
per-stage compile/memory visibility before they can be placed or
benchmarked.  This module is that leg.

How a compilation is *detected*: jax publishes per-compile durations
through ``jax.monitoring`` (``.../backend_compile_duration`` events fire
once per XLA backend compile, and never on a dispatch-cache hit — probed
on jax 0.4.37).  The recorder registers ONE process-wide listener; the
instrumented seams (:func:`observe`-wrapped jitted callables,
:func:`attribute` regions around Pallas entry points) push a
thread-local attribution frame around each call, so whatever the
listener hears lands on the *named callable that caused it*.  A call
during which no compile event fired costs two ``perf_counter`` reads
and a list push/pop; a call that DID compile additionally journals one
``compile`` event:

- ``name`` / ``signature`` — the callable and the abstract
  shape/dtype signature of its arguments (what XLA keys its cache on);
- ``bucket`` / ``model`` / ``kind`` — serving context (ladder bucket,
  tenant, ``warm`` vs request-path);
- ``compile_s`` (the listener's backend-compile seconds) and ``wall_s``
  (the whole call, i.e. compile + first execution);
- cost/memory analysis where the backend provides it: ``flops`` and
  ``bytes_accessed`` from ``Lowered.cost_analysis()`` (cheap — the
  jaxpr is already cached, nothing recompiles), and argument/output/
  temp/generated-code bytes from ``Compiled.memory_analysis()`` —
  which requires a second backend compile, so it is gated by
  ``shifu.tpu.obs-compile-analysis`` (``full`` | ``cost`` | ``off``)
  and suppressed from its own accounting.  Backends that implement
  neither degrade to the timing fields alone.

The recorder also maintains an in-process executable registry —
``stpu_compile_*`` gauges (live executables, cumulative compile
seconds, per-plane executable bytes) appended to that plane's
``/metrics`` surface — and runs the recompile-storm detector: a
:class:`~shifu_tensorflow_tpu.obs.slo.WindowedCounter` over the
compile-rate signal with an :class:`~shifu_tensorflow_tpu.obs.slo.EwmaZ`
corroborating z-score, journaling ``recompile_storm`` (naming the
churning callable and its last signature) when the windowed rate
crosses the storm threshold and ``recompile_storm_clear`` when it
drains.  Warm-ladder compiles (``kind="warm"``) are *expected* churn
and never count toward a storm — a serve fleet pre-warming ten buckets
at startup is the cure, not the disease.

stdlib-only at import (the obs CLI renders journals on jax-free
hosts); jax is touched lazily from inside the seams, which only run in
jax processes.  Off-by-default-cheap like its siblings: with no
recorder installed every seam is one module-global ``is None`` check.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable

from shifu_tensorflow_tpu.obs.registry import MetricsRegistry
from shifu_tensorflow_tpu.utils import logs

log = logs.get("obs")

__all__ = [
    "CompileRecorder",
    "observe",
    "attribute",
    "warm_section",
    "kind_section",
    "apply_persistent_cache",
    "install",
    "uninstall",
    "active",
]

#: compile kinds that are deliberate admission/export work, never
#: request-path churn: the warm ladder, an AOT executable deserialized
#: instead of compiled (kind=aot_load, ~0 compile_s), the per-bucket
#: live-compile fallback when AOT couldn't deliver (kind=aot_fallback),
#: and export-time AOT pre-compilation.  None of these count toward a
#: recompile storm — a 10-tenant fleet restart deserializing (or even
#: re-compiling) its ladders is the cure, not the disease.
ADMISSION_KINDS = frozenset({"warm", "aot_load", "aot_fallback",
                             "export"})

_perf = time.perf_counter
_mono = time.monotonic

#: jax.monitoring event-name suffix that marks one XLA backend compile
#: (jax 0.4.x: "/jax/core/compile/backend_compile_duration"; matched by
#: suffix so a renamed prefix in a later jax keeps reporting)
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"


class _Tls(threading.local):
    def __init__(self):
        self.stack: list[list] = []  # frames: [compile_s, n_compiles]
        self.kinds: list[tuple] = []  # kind_section() stack: (kind, fields)
        self.suppress = 0            # self-inflicted compiles (analysis)


_tls = _Tls()
_listener_registered = False
_listener_lock = threading.Lock()


def _on_duration_event(name: str, duration: float, **_kw) -> None:
    """The process-wide jax.monitoring listener.  Listeners cannot be
    individually unregistered, so this one is installed once and stays;
    with no recorder installed (or no frame on this thread) it is a
    suffix check and a global read."""
    if not name.endswith(_COMPILE_EVENT_SUFFIX):
        return
    if _tls.suppress:
        return
    if _tls.stack:
        frame = _tls.stack[-1]
        frame[0] += duration
        frame[1] += 1
        return
    rec = _active
    if rec is not None:
        rec._note_unattributed(duration)


def _ensure_listener() -> bool:
    """Register the monitoring listener (idempotent).  Called from the
    seams, which by definition run inside jax code paths — never at
    import or install time, which must stay jax-free."""
    global _listener_registered
    if _listener_registered:
        return True
    with _listener_lock:
        if _listener_registered:
            return True
        try:
            import jax.monitoring as monitoring

            monitoring.register_event_duration_secs_listener(
                _on_duration_event)
        except Exception as e:  # jax absent / API moved: degrade silently
            log.warning("compile recorder cannot listen for compile "
                        "events (%s: %s); compile journaling disabled",
                        type(e).__name__, e)
            _listener_registered = True  # don't retry per call
            return False
        _listener_registered = True
        return True


def _abstract(x: Any) -> str:
    """One argument leaf -> its abstract signature atom (what the XLA
    dispatch cache keys on: shape + dtype; values never matter)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        name = getattr(dtype, "name", None) or str(dtype)
        return f"{name}[{','.join(str(d) for d in shape)}]"
    return type(x).__name__


def signature_of(args: tuple, kw: dict) -> str:
    """Abstract shape/dtype signature of a call's arguments.  Long
    pytrees (a TrainState's every leaf) collapse to the first few atoms
    plus a count — the storm diagnosis needs the *varying* part (batch
    shapes), not a thousand identical param leaves."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kw))
    atoms = [_abstract(l) for l in leaves]
    if len(atoms) > 6:
        head = ";".join(atoms[:3])
        tail = ";".join(atoms[-2:])
        return f"{head};..{len(atoms) - 5}more..;{tail}"
    return ";".join(atoms)


class _StormState:
    """Recompile-storm detector state (one per recorder).

    The compile-rate signal is a windowed count of non-warm compiles;
    the storm opens when the window holds >= ``threshold`` compiles and
    closes when it drains back below half of it (hysteresis by level,
    matching the windowed-signal discipline of obs/slo.py).  EwmaZ rides
    along as the "how abnormal is this" annotation — fed one rate sample
    per tick, its z-score is journaled with the storm event when the
    warm-up has passed."""

    def __init__(self, window_s: float, threshold: int):
        from shifu_tensorflow_tpu.obs.slo import EwmaZ, WindowedCounter

        self.window_s = float(window_s)
        self.threshold = max(2, int(threshold))
        self.counter = WindowedCounter(self.window_s)
        self.by_name: dict[str, Any] = {}   # name -> WindowedCounter
        self.last_sig: dict[str, str] = {}  # name -> last signature
        self.ewma = EwmaZ()
        self.last_z: float | None = None
        self.active = False
        self.since: float | None = None
        self.culprit: str = "?"        # remembered at storm open: the
        self.culprit_sig: str = "?"    # clear event names the STORM's
        self.storms_total = 0          # churner, not the drained window's
        self._counter_cls = WindowedCounter


class CompileRecorder:
    """The per-process flight recorder (one per plane, installed by
    ``obs.install_obs`` next to the tracer/journal/watchdog)."""

    def __init__(self, *, plane: str = "train", worker: int | None = None,
                 analysis: str = "full", storm_window_s: float = 60.0,
                 storm_threshold: int = 8):
        if analysis not in ("full", "cost", "off"):
            raise ValueError(
                f"compile analysis must be full|cost|off, got {analysis!r}")
        self.plane = plane
        self.worker = worker
        self.analysis = analysis
        self._lock = threading.Lock()
        # (name, signature) -> [compiles, compile_s, code_bytes]: the
        # in-process executable registry.  An entry is an executable XLA
        # holds live in its dispatch cache; re-compiles of the SAME
        # signature (cache eviction, donation-variant retrace) bump the
        # count without growing the registry.
        self._executables: dict[tuple[str, str], list] = {}
        self.compiles_total = 0
        self.compile_seconds_total = 0.0
        self.aot_loads_total = 0
        self.unattributed_compiles = 0
        self.unattributed_seconds = 0.0
        self.registry = MetricsRegistry()
        self._storm = _StormState(storm_window_s, storm_threshold)

    # ---- attribution frames (hot path) ----
    def _push(self) -> list:
        frame = [0.0, 0]
        _tls.stack.append(frame)
        return frame

    def _pop(self, frame: list) -> None:
        # pop by identity so a seam that leaks an exception mid-nest
        # cannot leave a stale frame absorbing someone else's compiles
        stack = _tls.stack
        if stack and stack[-1] is frame:
            stack.pop()
        elif frame in stack:
            stack.remove(frame)

    def _note_unattributed(self, duration: float) -> None:
        with self._lock:
            self.unattributed_compiles += 1
            self.unattributed_seconds += duration
            self.compiles_total += 1
            self.compile_seconds_total += duration

    # ---- the observed-call seam ----
    def observed_call(self, fn: Callable, name: str, args: tuple,
                      kw: dict, *, kind: str | None = None,
                      model: str | None = None,
                      bucket_from: Callable | None = None):
        _ensure_listener()
        frame = self._push()
        t0 = _perf()
        try:
            out = fn(*args, **kw)
        finally:
            wall = _perf() - t0
            self._pop(frame)
        if frame[1]:
            try:
                self._record_compiled(fn, name, args, kw, frame, wall,
                                      kind=kind, model=model,
                                      bucket_from=bucket_from)
            except Exception as e:  # recording must never fail the call
                log.warning("compile event for %s dropped (%s: %s)",
                            name, type(e).__name__, e)
        return out

    def _record_compiled(self, fn, name, args, kw, frame, wall_s, *,
                         kind, model, bucket_from) -> None:
        try:
            sig = signature_of(args, kw)
        except Exception:
            sig = "?"
        bucket = None
        if bucket_from is not None:
            try:
                bucket = bucket_from(*args, **kw)
            except Exception:
                bucket = None
        fields = self._analyze(fn, args, kw)
        section_kind, extra = _section(kind)
        self.record(name=name, signature=sig, compile_s=frame[0],
                    parts=frame[1], wall_s=wall_s, bucket=bucket,
                    model=model, kind=section_kind, **extra, **fields)

    def _analyze(self, fn, args, kw) -> dict:
        """Cost/memory analysis fields, degrading to {} wherever the
        backend (or the callable) doesn't provide them.  ``full`` pays a
        SECOND backend compile for ``memory_analysis`` — suppressed from
        the listener so the recorder cannot count its own probe."""
        out: dict[str, Any] = {}
        if self.analysis == "off":
            return out
        lower = getattr(fn, "lower", None)
        if lower is None:
            return out
        _tls.suppress += 1
        try:
            lowered = lower(*args, **kw)
            cost = lowered.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else None
            if isinstance(cost, dict):
                if "flops" in cost:
                    out["flops"] = float(cost["flops"])
                if "bytes accessed" in cost:
                    out["bytes_accessed"] = float(cost["bytes accessed"])
            if self.analysis == "full":
                mem = lowered.compile().memory_analysis()
                if mem is not None:
                    out["arg_bytes"] = int(mem.argument_size_in_bytes)
                    out["out_bytes"] = int(mem.output_size_in_bytes)
                    out["temp_bytes"] = int(mem.temp_size_in_bytes)
                    out["code_bytes"] = int(
                        mem.generated_code_size_in_bytes)
        except Exception:
            pass  # cost/memory introspection is best-effort by contract
        finally:
            _tls.suppress -= 1
        return out

    # ---- recording (also the direct API for attribute()) ----
    def record(self, *, name: str, signature: str = "?",
               compile_s: float = 0.0, parts: int = 1,
               wall_s: float | None = None, bucket: int | None = None,
               model: str | None = None, kind: str | None = None,
               now: float | None = None, **fields: Any) -> None:
        from shifu_tensorflow_tpu.obs import journal as obs_journal
        from shifu_tensorflow_tpu.obs import slo as obs_slo

        now = _mono() if now is None else now
        with self._lock:
            entry = self._executables.get((name, signature))
            if entry is None:
                entry = self._executables[(name, signature)] = [0, 0.0, 0]
            entry[0] += 1
            entry[1] += compile_s
            if "code_bytes" in fields:
                entry[2] = int(fields["code_bytes"])
            if kind == "aot_load":
                # a deserialized shipped executable: live in the
                # registry (it occupies the device like any program)
                # but NOT a compilation — compiles_total must keep
                # meaning "times XLA ran"
                self.aot_loads_total += 1
            else:
                # counts BACKEND compiles (one jit call can compile
                # several sub-programs — `parts`), matching what
                # _note_unattributed counts for compiles nobody claimed
                self.compiles_total += max(1, parts)
                self.compile_seconds_total += compile_s
        ev: dict[str, Any] = {
            "name": name, "signature": signature,
            "compile_s": round(compile_s, 6), "parts": parts,
        }
        if wall_s is not None:
            ev["wall_s"] = round(wall_s, 6)
        if bucket is not None:
            ev["bucket"] = int(bucket)
        if model is not None:
            ev["model"] = model
        if kind is not None:
            ev["kind"] = kind
        backend = _backend_name()
        if backend is not None:
            ev["backend"] = backend
        for k, v in fields.items():
            ev[k] = round(v, 6) if isinstance(v, float) else v
        obs_journal.emit("compile", plane=self.plane, worker=self.worker,
                         **ev)
        wd = obs_slo.active()
        if wd is not None and kind != "aot_load":
            # the shifu.tpu.slo-compile-s target judges the window MAX
            # of this signal (from_config); one slow compile is the
            # breach, not the average of many fast ones.  A deserialized
            # AOT executable never ran XLA — its ~0 is not a compile
            # sample.
            wd.observe("compile_s", compile_s)
        if kind not in ADMISSION_KINDS:
            self._storm_note(name, signature, now)
        else:
            # even expected churn must let an open storm close
            self._storm_check(now)

    # ---- recompile-storm detection ----
    def _storm_note(self, name: str, signature: str, now: float) -> None:
        st = self._storm
        with self._lock:
            st.counter.add(1, now=now)
            c = st.by_name.get(name)
            if c is None:
                c = st.by_name[name] = st._counter_cls(st.window_s)
            c.add(1, now=now)
            st.last_sig[name] = signature
        self._storm_check(now)

    def _storm_check(self, now: float | None = None) -> list[dict]:
        """Evaluate the storm state machine; returns the events it
        journaled.  Called on every non-warm compile and from
        :meth:`tick` — the clear transition needs a tick, because a
        storm that simply *stops compiling* fires no more events."""
        from shifu_tensorflow_tpu.obs import journal as obs_journal

        now = _mono() if now is None else now
        events: list[dict] = []
        st = self._storm
        with self._lock:
            total = st.counter.total(now=now)
            if not st.active and total >= st.threshold:
                st.active = True
                st.since = now
                st.storms_total += 1
                name, n, sig = self._churn_culprit(now)
                st.culprit, st.culprit_sig = name, sig
                events.append({
                    "event": "recompile_storm",
                    "compiles_in_window": total,
                    "window_s": st.window_s,
                    "threshold": st.threshold,
                    "culprit": name,
                    "culprit_compiles": n,
                    "signature": sig,
                    **({"z": round(st.last_z, 2)}
                       if st.last_z is not None else {}),
                })
            elif st.active and total <= st.threshold // 2:
                st.active = False
                events.append({
                    "event": "recompile_storm_clear",
                    "compiles_in_window": total,
                    "storm_s": round(now - (st.since or now), 3),
                    "culprit": st.culprit,
                    "signature": st.culprit_sig,
                })
                st.since = None
        for ev in events:
            kind = ev.pop("event")
            obs_journal.emit(kind, plane=self.plane, worker=self.worker,
                             **ev)
        return events

    def _churn_culprit(self, now: float) -> tuple[str, int, str]:
        """The callable with the most window compiles + its last
        signature — "which signature churned".  Caller holds the lock."""
        st = self._storm
        best, best_n = "?", 0
        for name, c in st.by_name.items():
            n = c.total(now=now)
            if n > best_n:
                best, best_n = name, n
        return best, best_n, st.last_sig.get(best, "?")

    def tick(self, now: float | None = None) -> list[dict]:
        """Slow-path evaluation (per train epoch / per serve SLO tick):
        feed the EwmaZ rate sample and run the storm state machine so a
        storm whose compiles stopped can clear."""
        now = _mono() if now is None else now
        st = self._storm
        with self._lock:
            z = st.ewma.update(float(st.counter.total(now=now)))
            if z is not None:
                st.last_z = z
        return self._storm_check(now)

    # ---- reading ----
    def executables(self) -> dict[tuple[str, str], dict]:
        with self._lock:
            return {
                key: {"compiles": e[0], "compile_s": e[1],
                      "code_bytes": e[2]}
                for key, e in self._executables.items()
            }

    def state(self) -> dict:
        with self._lock:
            st = self._storm
            return {
                "live_executables": len(self._executables),
                "compiles_total": self.compiles_total,
                "aot_loads_total": self.aot_loads_total,
                "compile_seconds_total": round(
                    self.compile_seconds_total, 6),
                "executable_bytes": sum(
                    e[2] for e in self._executables.values()),
                "unattributed_compiles": self.unattributed_compiles,
                "storm_active": st.active,
                "storms_total": st.storms_total,
            }

    def render_prometheus(self) -> str:
        """``stpu_compile_*`` gauge text, appended by the plane's scrape
        surface (serve ``/metrics``, the coordinator ``metrics`` op) —
        the per-plane executable registry as Prometheus sees it."""
        s = self.state()
        r = self.registry
        r.set_gauge("live_executables", s["live_executables"])
        r.set_gauge("seconds_total", round(s["compile_seconds_total"], 6))
        r.set_gauge("total", s["compiles_total"])
        r.set_gauge("aot_loads_total", s["aot_loads_total"])
        if self.analysis == "full":
            # code bytes come only from memory_analysis: under
            # cost/off the signal is ABSENT, not a measured zero (the
            # accountant's absent-never-zero discipline)
            r.set_gauge("executable_bytes", s["executable_bytes"])
        r.set_gauge("storm_active", int(s["storm_active"]))
        r.set_gauge("storms_total", s["storms_total"])
        return r.render_prometheus("stpu_compile_")


def _backend_name() -> str | None:
    """The initialized jax backend's platform name — WITHOUT initializing
    one (the coordinator plane renders metrics in processes that may
    never touch a device; default_backend() there would pay full backend
    startup inside a scrape)."""
    import sys

    if "jax" not in sys.modules:
        return None
    try:
        xb = sys.modules.get("jax._src.xla_bridge")
        if xb is not None and getattr(xb, "_default_backend", None) is None:
            return None
        import jax

        return jax.default_backend()
    except Exception:
        return None


# ---- module-level seams ----

_active: CompileRecorder | None = None


def install(recorder: CompileRecorder) -> CompileRecorder:
    global _active
    _active = recorder
    return recorder


def uninstall() -> None:
    global _active
    _active = None


def active() -> CompileRecorder | None:
    return _active


class _Observed:
    """The :func:`observe` wrapper: calls route through the recorder
    when one is installed; every OTHER attribute (``lower``,
    ``_cache_size``, ...) proxies to the wrapped jitted callable, so
    callers that introspect the jit object keep working."""

    __slots__ = ("__wrapped__", "_name", "_kind", "_model", "_bucket_from")

    def __init__(self, fn, name, kind, model, bucket_from):
        self.__wrapped__ = fn
        self._name = name
        self._kind = kind
        self._model = model
        self._bucket_from = bucket_from

    def __call__(self, *args, **kw):
        rec = _active
        if rec is None:
            return self.__wrapped__(*args, **kw)
        return rec.observed_call(self.__wrapped__, self._name, args, kw,
                                 kind=self._kind, model=self._model,
                                 bucket_from=self._bucket_from)

    def __getattr__(self, item):
        return getattr(self.__wrapped__, item)


def observe(fn: Callable, name: str, *, kind: str | None = None,
            model: str | None = None,
            bucket_from: Callable | None = None) -> Callable:
    """Wrap a jitted callable so every call that COMPILES journals a
    ``compile`` event attributed to ``name``.  With no recorder
    installed the wrapper is one module-global ``is None`` check; the
    wrapped callable stays reachable as ``.__wrapped__`` and through
    transparent attribute proxying."""
    return _Observed(fn, name, kind, model, bucket_from)


@contextlib.contextmanager
def attribute(name: str, *, kind: str | None = None,
              model: str | None = None):
    """Attribution region for code that compiles WITHOUT an observable
    jitted callable (Pallas entry points, eager-mode first calls):
    compile events fired inside the region journal under ``name`` with
    whatever timing the listener heard (no signature/analysis — there is
    no ``.lower`` to ask)."""
    rec = _active
    if rec is None:
        yield
        return
    _ensure_listener()
    frame = rec._push()
    t0 = _perf()
    try:
        yield
    finally:
        wall = _perf() - t0
        rec._pop(frame)
        if frame[1]:
            try:
                section_kind, extra = _section(kind)
                rec.record(name=name, compile_s=frame[0], parts=frame[1],
                           wall_s=wall, model=model, kind=section_kind,
                           **extra)
            except Exception as e:
                log.warning("compile event for %s dropped (%s: %s)",
                            name, type(e).__name__, e)


def _section(default: str | None) -> tuple[str | None, dict]:
    """The innermost :func:`kind_section`'s (kind, extra fields), or
    ``(default, {})`` when no section is open on this thread."""
    if _tls.kinds:
        return _tls.kinds[-1]
    return default, {}


@contextlib.contextmanager
def kind_section(kind: str, **fields):
    """Mark the dynamic extent where compile events journal with
    ``kind=`` (plus any extra fields — e.g. the AOT fallback's
    ``aot_error`` reason) instead of the seam's default.  Innermost
    section wins; kinds in :data:`ADMISSION_KINDS` are excluded from
    recompile-storm detection."""
    _tls.kinds.append((kind, fields))
    try:
        yield
    finally:
        _tls.kinds.pop()


def warm_section():
    """Mark the dynamic extent of deliberate pre-warming (the serve
    bucket ladder): compiles inside journal with ``kind="warm"`` and are
    EXCLUDED from recompile-storm detection — expected churn, and the
    cure for the storm the detector exists to catch."""
    return kind_section("warm")


def apply_persistent_cache(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``
    (``shifu.tpu.compile-cache-dir``) — the middle tier of the AOT
    fallback ladder: a bucket that live-compiles (AOT mismatch, or no
    AOT shipped) writes its program here, so the NEXT worker/restart on
    this host deserializes from the cache instead of re-running XLA.
    The min-compile-time floor drops to 0 because serve-plane scorer
    programs compile in well under jax's 1s default — exactly the
    programs whose re-compilation scales as tenants x buckets.

    Best-effort by contract: returns False (logged) on a host without
    jax or a jax without the config knobs — the caller's plane must
    come up regardless.

    In a process that has NOT imported jax yet (the serve supervisor,
    the coordinator — planes that deliberately stay jax-free), the
    settings land as environment variables instead: jax reads them at
    import, and child processes (SO_REUSEPORT workers, subprocess
    fleets) inherit them for free — install time stays jax-free, per
    this module's contract."""
    import os
    import sys

    if "jax" not in sys.modules:
        os.environ["JAX_COMPILATION_CACHE_DIR"] = str(cache_dir)
        os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
        os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
        return True
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              0)
        except Exception:
            pass  # knob absent on older jax: the default (0) matches
        try:
            # the cache object initializes lazily at the FIRST compile
            # and then sticks: a process that compiled anything before
            # this call (an earlier model load, a probe) would silently
            # keep the old (usually disabled) cache — reset so the new
            # dir takes effect regardless of call order
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        except Exception:
            pass
        return True
    except Exception as e:
        log.warning("persistent compile cache at %s not applied (%s: %s)",
                    cache_dir, type(e).__name__, e)
        return False
