"""Append-only JSONL event journal: the fleet's flight recorder.

One structured line per lifecycle event (register, epoch, health trip,
rollback, reload, shed, ...), from every plane (train / coordinator /
serve / checkpoint), into a size-capped rotating file set.  The CLI
(``python -m shifu_tensorflow_tpu.obs``) reconstructs a per-step time
budget and a fleet timeline from it — for a finished job or a running
one (readers never lock writers).

Crash-safety contract: every event is ONE ``write()`` of one complete
``\\n``-terminated line, flushed immediately.  A process killed
mid-write can tear at most the final line of one file; readers
(:func:`iter_events`) skip unparseable lines instead of failing, so a
journal with a torn tail (or a corrupted middle) still yields every
intact event.  One writer per file: fleet workers write
``<path>.w<index>`` siblings (obs.install_obs) rather than interleaving
into one file — POSIX O_APPEND atomicity is not portable past pipe-buf
sizes, and rotation across processes is unresolvable races.

Rotation: when a write would push the file past ``max_bytes``, the file
shifts ``path → path.1 → path.2 → ...`` keeping ``max_files`` files
total — the journal's disk footprint is bounded at
``max_bytes * max_files`` per writer no matter how long the job runs.

Journal failures (disk full, permission lost mid-job) degrade to a
logged warning, never an exception: observability must not take down
the job it observes.
"""

from __future__ import annotations

import glob
import itertools
import json
import os
import re
import threading
import time
from typing import Any, Iterator

from shifu_tensorflow_tpu.utils import logs

log = logs.get("obs")

__all__ = [
    "Journal",
    "install",
    "uninstall",
    "active",
    "emit",
    "iter_events",
    "journal_files",
    "read_events",
]


class Journal:
    def __init__(
        self,
        path: str,
        *,
        max_bytes: int = 8 << 20,
        max_files: int = 4,
        plane: str | None = None,
        worker: int | None = None,
        job: str | None = None,
    ):
        self.path = os.fspath(path)
        self.max_bytes = max(4096, int(max_bytes))
        self.max_files = max(1, int(max_files))
        self.plane = plane
        self.worker = worker
        self.job = job
        # per-writer monotonic sequence: same-microsecond events from one
        # writer (and across its rotations) keep their emission order in
        # the merged read — `obs trace`'s causal ordering depends on it.
        # itertools.count: atomic under the GIL, never resets (a process
        # restart writing the same path starts a new Journal, but its
        # first event's ts is always past the old tail's).
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._file = None
        self._size = 0
        self._warned = False
        #: events dropped because the filesystem failed (diagnostics)
        self.dropped = 0
        # clock-offset estimate (coordinator clock minus this writer's,
        # obs/fleet.ClockSync): stamped as offset= on every event once
        # known, so read_events/`obs trace` can render a fleet-aligned
        # timeline (ts + offset ≈ coordinator time) while --json keeps
        # the raw wall clock.  Plain attribute write/read: a float slot
        # is atomic under the GIL and a torn update is impossible.
        self._offset: float | None = None
        # in-process event tap (obs/rollup.RollupCompactor): sees every
        # record dict at emit time, BEFORE rotation can drop it — the
        # rollup sidecar's feed.  Exceptions are swallowed; reference
        # assignment, so readers see a whole callable or None.
        self._tap = None
        # callables fired once when this writer closes (the compactor's
        # final flush rides here so a drained fleet's sidecar is
        # complete)
        self._close_hooks: list = []

    def set_offset(self, offset: float | None) -> None:
        """Update the writer's clock-offset estimate (None clears it)."""
        self._offset = None if offset is None else float(offset)

    def set_tap(self, fn) -> None:
        """Install (or clear, with None) the in-process event tap."""
        self._tap = fn

    def on_close(self, fn) -> None:
        """Run ``fn`` when this writer closes (at most once; errors are
        swallowed — the journal contract)."""
        self._close_hooks.append(fn)

    # ---- writing ----
    def emit(self, event: str, **fields: Any) -> None:
        rec: dict[str, Any] = {"ts": round(time.time(), 6),
                               "seq": next(self._seq), "event": event}
        if self.plane is not None:
            rec["plane"] = self.plane
        if self.worker is not None:
            rec["worker"] = self.worker
        if self.job is not None:
            rec["job"] = self.job
        offset = self._offset
        if offset is not None:
            rec["offset"] = round(offset, 6)
        rec.update(fields)
        tap = self._tap
        if tap is not None:
            try:
                tap(rec)
            except Exception:
                pass
        try:
            line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
        except (TypeError, ValueError) as e:
            # an unserializable field must not kill the event, let alone
            # the job — record what we can plus the failure
            fallback = {"ts": rec["ts"], "seq": rec["seq"], "event": event,
                        "journal_error": f"{type(e).__name__}: {e}"}
            if self.plane is not None:
                fallback["plane"] = self.plane
            if self.worker is not None:
                fallback["worker"] = self.worker
            line = json.dumps(fallback) + "\n"
        data = line.encode("utf-8")
        with self._lock:
            try:
                self._ensure_open(len(data))
                os.write(self._file, data)
                self._size += len(data)
            except OSError as e:
                self.dropped += 1
                if not self._warned:
                    self._warned = True
                    log.warning("journal write to %s failed (%s); further "
                                "events will be dropped silently",
                                self.path, e)

    def _open(self) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._file = os.open(
            self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        self._size = os.fstat(self._file).st_size

    def _ensure_open(self, incoming: int) -> None:
        """Open (or rotate-and-reopen) the journal file.  Caller holds
        the lock.  Uses a raw fd: one ``os.write`` per line is the
        crash-safety unit — buffered layers can tear lines anywhere.

        Rotation failure (e.g. the directory lost write permission while
        the already-open file stays writable) is tolerated ONCE per
        attempt, not retried in a loop: the file keeps growing past the
        cap — the footprint bound degrades, the job does not."""
        if self._file is None:
            self._open()
        if self._size and self._size + incoming > self.max_bytes:
            os.close(self._file)
            self._file = None
            self._rotate()
            self._open()
            if self._size and self._size + incoming > self.max_bytes:
                if not self._warned:
                    self._warned = True
                    log.warning(
                        "journal rotation of %s failed (file still %d "
                        "bytes past the %d cap); continuing to append — "
                        "the size bound is degraded, not the job",
                        self.path, self._size, self.max_bytes,
                    )

    def _rotate(self) -> None:
        # shift path.{N-1} -> path.N, ..., path -> path.1; the oldest
        # file falls off the end (bounded footprint)
        for i in range(self.max_files - 1, 0, -1):
            src = self.path if i == 1 else f"{self.path}.{i - 1}"
            dst = f"{self.path}.{i}"
            try:
                if os.path.exists(src):
                    os.replace(src, dst)
            except OSError:
                pass
        if self.max_files == 1:
            # no room for history: truncate in place
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def close(self) -> None:
        hooks, self._close_hooks = self._close_hooks, []
        for fn in hooks:
            try:
                fn()
            except Exception:
                pass
        with self._lock:
            if self._file is not None:
                try:
                    os.close(self._file)
                except OSError:
                    pass
                self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---- process-global hook ----

_active: Journal | None = None

#: callables to fire when a journal next installs — the backlog channel
#: for conditions detected BEFORE the CLI installs obs (config parsing
#: runs first): the detector registers a deferred emit instead of
#: silently losing the record.  Fired once each, best-effort.
_install_hooks: list = []


def notify_on_install(fn) -> None:
    """Run ``fn`` now if a journal is active, else when one installs.
    ``fn`` fires at most once; exceptions are swallowed (the journal
    contract: observability never takes down what it observes)."""
    if _active is not None:
        try:
            fn()
        except Exception:
            pass
    else:
        _install_hooks.append(fn)


def install(journal: Journal) -> Journal:
    global _active
    _active = journal
    hooks, _install_hooks[:] = list(_install_hooks), []
    for fn in hooks:
        try:
            fn()
        except Exception:
            pass
    return journal


def uninstall() -> None:
    global _active
    if _active is not None:
        _active.close()
    _active = None


def active() -> Journal | None:
    return _active


def emit(event: str, **fields: Any) -> None:
    """Emit into the installed journal; free no-op when none is."""
    j = _active
    if j is not None:
        j.emit(event, **fields)


# ---- reading ----

def journal_files(base: str) -> list[str]:
    """Every file belonging to the journal at ``base``: the file itself,
    its rotations (``base.N``), fleet-worker siblings (``base.wK`` for
    train workers, ``base.sK`` for --serve-workers scoring processes,
    ``base.lK`` for the lifecycle controller), and their rotations —
    oldest-first within each writer so a re-sorted merge is stable for
    equal timestamps."""
    base = os.fspath(base)
    pat = re.compile(
        re.escape(os.path.basename(base)) + r"(\.[wsl]\d+)?(\.\d+)?$"
    )
    found = [
        p for p in glob.glob(glob.escape(base) + "*")
        if pat.fullmatch(os.path.basename(p))
    ]

    def order(p: str):
        m = pat.fullmatch(os.path.basename(p))
        # siblings sort base-first, then .w<k>, then .s<k>, then .l<k>
        # (train fleet before serve fleet before the lifecycle
        # controller; within equal timestamps the merge is stable in
        # this order)
        kind = {"": -1, "w": 0, "s": 1,
                "l": 2}[m.group(1)[1] if m.group(1) else ""]
        worker = int(m.group(1)[2:]) if m.group(1) else -1
        rot = int(m.group(2)[1:]) if m.group(2) else 0
        return (kind, worker, -rot)  # higher rotation number = older

    return sorted(found, key=order)


def iter_events(path: str) -> Iterator[dict]:
    """Parse one journal file, skipping torn/corrupt lines (at minimum
    the final line of a file whose writer was killed mid-write)."""
    try:
        f = open(path, "rb")
    except OSError:
        return
    with f:
        for raw in f:
            try:
                ev = json.loads(raw)
            except ValueError:
                continue  # torn tail / corrupted line: skip, keep reading
            if isinstance(ev, dict) and "event" in ev:
                yield ev


def read_keyed_events(
    base: str, cache: dict | None = None,
    after: dict | None = None,
) -> list[tuple[float, tuple, int, dict]]:
    """``read_events`` plus each event's merge key: ``(ts, writer, seq,
    event)`` tuples in merged order.  ``writer`` is the file-derived
    identity (``(-1, -1)`` for the base file, ``(0, k)`` for ``.w<k>``,
    ``(1, k)`` for ``.s<k>``, ``(2, k)`` for ``.l<k>``) and
    ``(ts, seq)`` is monotonic WITHIN a
    writer — the contract an incremental poller needs to keep a
    per-writer high-water mark that survives late file flushes and
    rotation dropping old files (a global list index does neither: a
    slow writer's events can merge BEFORE an already-seen tail, and
    rotation can shrink the list below the index).

    ``after`` (writer -> (ts, seq) watermark) makes the RETURN
    incremental too: only events past each writer's mark are keyed,
    sorted, and returned, and an unchanged file whose whole key span
    sits at or below the mark is skipped without iterating its parsed
    events — so a steady-state poller (the serve autoscaler) pays per
    tick for the new tail, not an O(total-events) rebuild of history."""
    base = os.fspath(base)
    pat = re.compile(
        re.escape(os.path.basename(base)) + r"(\.([wsl])(\d+))?(\.\d+)?$"
    )
    keyed: list[tuple[float, tuple, int, dict]] = []
    positions: dict[tuple, int] = {}
    for path in journal_files(base):
        m = pat.fullmatch(os.path.basename(path))
        writer = ((-1, -1) if not m or not m.group(2)
                  else ({"w": 0, "s": 1, "l": 2}[m.group(2)],
                        int(m.group(3))))
        mark = after.get(writer) if after is not None else None
        if cache is not None:
            try:
                st = os.stat(path)
                # st_ino travels WITH the content across a rotation
                # rename (path -> path.1 keeps the inode): on a
                # coarse-mtime filesystem two successive rotations can
                # leave path.1 with the same (size, mtime) as its
                # previous occupant, and without the inode the cache
                # would serve the older file's parsed events as the new
                # one's
                sig = (st.st_size, st.st_mtime_ns, st.st_ino)
            except OSError:
                continue
            if mark is not None:
                # key-span sidecar entry (tuple key — invisible to the
                # plain-path lookups above): an unchanged file fully at
                # or below the watermark contributes nothing; only its
                # event count matters (the pos fallback for any later
                # file of the same writer)
                span = cache.get(("span", path))
                if (span is not None and span[0] == sig
                        and span[2] <= mark):
                    positions[writer] = (
                        positions.get(writer, 0) + span[1])
                    continue
            hit = cache.get(path)
            if hit is not None and hit[0] == sig:
                parsed = hit[1]
            else:
                parsed = list(iter_events(path))
                cache[path] = (sig, parsed)
        else:
            parsed = iter_events(path)
        pos = positions.get(writer, 0)
        all_seq = True
        max_key = (-1.0, -1)
        for ev in parsed:
            seq = ev.get("seq")
            if not isinstance(seq, int):
                # pos-keyed legacy event: its key depends on preceding
                # files' counts, so this file never earns a span entry
                all_seq = False
                seq = pos
            key = (ev.get("ts", 0.0), seq)
            if key > max_key:
                max_key = key
            if mark is None or key > mark:
                keyed.append((key[0], writer, seq, ev))
            pos += 1
        positions[writer] = pos
        if cache is not None and all_seq:
            cache[("span", path)] = (sig, len(parsed), max_key)
    keyed.sort(key=lambda t: t[:3])
    return keyed


def read_events(base: str, cache: dict | None = None) -> list[dict]:
    """All intact events of the journal (every writer, every rotation),
    merged oldest-first by ``(ts, writer, seq)``.

    The ``seq`` tiebreak matters for causal reads: two events emitted in
    the same microsecond by one writer (or straddling a rotation) would
    otherwise merge in whatever order the sort left them, and ``obs
    trace`` renders the merged order as causality.  Events predating the
    ``seq`` field fall back to their position within the writer's file
    set (journal_files returns each writer's rotations oldest-first, so
    position IS emission order).

    ``cache`` (an initially-empty dict the caller keeps between calls)
    makes repeated reads incremental: a file whose ``(size, mtime)``
    is unchanged reuses its parsed events instead of re-reading JSONL —
    rotated files are immutable, so a poller like ``obs top`` pays only
    for the growing active file per refresh, not the whole rotation
    set."""
    return [t[3] for t in read_keyed_events(base, cache=cache)]
