"""Device-memory accountant: what actually lives in device memory, by
owner, over time.

``jax.live_arrays()`` enumerates every device buffer the process holds;
backend ``memory_stats()`` (where the PJRT backend implements it — TPU
and GPU do, CPU returns None) adds the allocator's own view
(bytes_in_use / peak / limit).  Neither tells you *whose* bytes those
are — so the accountant takes attribution pytrees from its callers and
buckets the total:

- ``params`` / ``opt_state`` — the trainer passes its TrainState's
  trees per epoch;
- ``infeed`` — in-flight input batches (the pipelined-prefetch buffers);
- ``executable`` — generated-code bytes from the compile flight
  recorder's registry (executables are not jax arrays, so this rides
  BESIDE the live-array total, not inside it; present only under
  ``obs-compile-analysis=full`` — absent otherwise, never zero);
- ``models`` — the serve tenancy plane passes each admitted
  ``EvalModel``'s device-resident weights, so the LRU budget's
  dashboard shows *device* bytes per tenant, not just bundle bytes
  (gauge name ``stpu_devmem_model_bytes_<escaped-name>`` carrying a
  ``model="<name>"`` label — registry gauges are name-keyed, so the
  tenant rides in both);
- ``other`` — live-array bytes nothing above claimed (leaked buffers,
  retained eval outputs, donation ghosts — exactly the bucket an
  operator stares at when a job OOMs "for no reason").

Each snapshot journals one ``device_mem`` event, updates the
``stpu_devmem_*`` gauges (appended to the plane's ``/metrics``), tracks
the high-water mark, and — when the backend reports a bytes limit —
feeds the ``devmem_frac`` SLO signal the ``shifu.tpu.slo-devmem-frac``
watchdog target judges.

Cadence is caller-owned and cheap-by-construction: per epoch on the
train plane, per admission/eviction on the serve plane — never per step
or per request.  A snapshot walks the live-array list once (tens of
arrays on the workloads this repo trains; microseconds).  stdlib-only
at import; jax is imported inside :meth:`snapshot`, which only runs in
jax processes.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from shifu_tensorflow_tpu.obs.registry import (
    MetricsRegistry,
    escape_label_suffix as _escape,  # one escape across every obs leg
)
from shifu_tensorflow_tpu.utils import logs

log = logs.get("obs")

__all__ = [
    "MemoryAccountant",
    "install",
    "uninstall",
    "active",
]


def _array_bytes(a: Any) -> int:
    """This process's bytes for one jax array: addressable-shard bytes
    under sharding (``nbytes`` is the GLOBAL logical size — counting it
    would charge every host for the whole fleet's tables), plain nbytes
    otherwise.  Deleted arrays (donation consumed them) count zero."""
    try:
        if getattr(a, "is_deleted", None) is not None and a.is_deleted():
            return 0
        sharding = getattr(a, "sharding", None)
        if sharding is not None and len(sharding.device_set) > 1:
            return sum(int(s.data.nbytes) for s in a.addressable_shards)
        return int(a.nbytes)
    except Exception:
        return 0


def tree_device_bytes(tree: Any) -> int:
    """Total device bytes of a pytree's jax-array leaves (numpy/host
    leaves count zero — they are not device memory)."""
    if tree is None:
        return 0
    try:
        import jax

        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "addressable_shards") or (
                    hasattr(leaf, "device") and hasattr(leaf, "nbytes")):
                total += _array_bytes(leaf)
        return total
    except Exception:
        return 0


def tree_per_device_bytes(tree: Any) -> dict[int, int]:
    """Device id -> bytes this pytree holds ON that device.  The
    sharding-aware view of :func:`tree_device_bytes`: a table sharded
    over the ``model`` axis charges each device its slice, a replicated
    leaf charges every device the full array — so ``max`` over the
    returned dict is the per-device parameter footprint the mesh-shape
    capacity planning (bench sharding) reasons about."""
    per_dev: dict[int, int] = {}
    if tree is None:
        return per_dev
    try:
        import jax

        for leaf in jax.tree_util.tree_leaves(tree):
            if getattr(leaf, "is_deleted", None) is not None \
                    and leaf.is_deleted():
                continue
            shards = getattr(leaf, "addressable_shards", None)
            if shards is not None:
                try:
                    for s in shards:
                        did = int(getattr(s.device, "id", 0))
                        per_dev[did] = per_dev.get(did, 0) \
                            + int(s.data.nbytes)
                    continue
                except Exception:
                    pass
            dev = getattr(leaf, "device", None)
            nbytes = getattr(leaf, "nbytes", None)
            if dev is not None and nbytes is not None:
                d = dev() if callable(dev) else dev
                did = int(getattr(d, "id", 0) or 0)
                per_dev[did] = per_dev.get(did, 0) + int(nbytes)
    except Exception:
        return per_dev
    return per_dev


class MemoryAccountant:
    """Per-plane device-memory snapshots with attribution and
    high-water tracking (installed by ``obs.install_obs`` beside the
    tracer/journal/watchdog/compile recorder)."""

    def __init__(self, *, plane: str = "train", worker: int | None = None):
        self.plane = plane
        self.worker = worker
        self._lock = threading.Lock()
        self.high_water = 0
        self.high_water_ts: float | None = None
        self._model_bytes: dict[str, int] = {}
        self._last: dict[str, Any] = {}
        self.snapshots = 0
        self.registry = MetricsRegistry()

    def snapshot(self, *, params: Any = None, opt_state: Any = None,
                 infeed: Any = None, models: dict[str, Any] | None = None,
                 event: str = "device_mem", **ctx: Any) -> dict | None:
        """One accounting pass; returns (and journals) the bucketed
        record, or None when jax is unavailable in this process."""
        from shifu_tensorflow_tpu.obs import compile as obs_compile
        from shifu_tensorflow_tpu.obs import journal as obs_journal
        from shifu_tensorflow_tpu.obs import slo as obs_slo

        try:
            import jax
        except Exception:
            return None
        try:
            live = jax.live_arrays()
        except Exception as e:
            log.warning("device-memory snapshot failed (%s: %s)",
                        type(e).__name__, e)
            return None
        total = sum(_array_bytes(a) for a in live)
        params_b = tree_device_bytes(params)
        # per-device params footprint (max over local devices): THE
        # capacity signal model-axis sharding moves — a table sharded
        # model:M charges each device 1/M of what replication would
        params_dev_b = max(tree_per_device_bytes(params).values(),
                           default=0) if params is not None else None
        opt_b = tree_device_bytes(opt_state)
        infeed_b = tree_device_bytes(infeed)
        model_b: dict[str, int] = {}
        for name, tree in (models or {}).items():
            # the tenancy store precomputes bytes (EvalModel.device_bytes)
            # so it never hands private param trees across the seam
            model_b[name] = (int(tree) if isinstance(tree, (int, float))
                             else tree_device_bytes(tree))
        # executable bytes come from the compile registry's
        # memory_analysis fields — available only under analysis="full";
        # under cost/off the field is ABSENT, never a measured zero
        exec_b = None
        rec = obs_compile.active()
        if rec is not None and rec.analysis == "full":
            exec_b = rec.state()["executable_bytes"]
        attributed = params_b + opt_b + infeed_b + sum(model_b.values())
        other = max(0, total - attributed)
        out: dict[str, Any] = {
            "total_bytes": total,
            "arrays": len(live),
            "params_bytes": params_b,
            **({"params_dev_bytes": params_dev_b}
               if params_dev_b is not None else {}),
            "opt_bytes": opt_b,
            "infeed_bytes": infeed_b,
            **({"exec_bytes": exec_b} if exec_b is not None else {}),
            "other_bytes": other,
        }
        if model_b:
            out["models"] = dict(sorted(model_b.items()))
        stats = self._backend_stats(jax)
        if stats:
            out.update(stats)
        with self._lock:
            self.snapshots += 1
            if total > self.high_water:
                self.high_water = total
                self.high_water_ts = time.time()
            # MERGE, don't replace: a single-model reload snapshot must
            # not wipe sibling tenants' last-known bytes (eviction
            # removes its entry explicitly via drop_model)
            self._model_bytes.update(model_b)
            out["hwm_bytes"] = self.high_water
        frac = None
        limit = out.get("bytes_limit")
        if limit:
            frac = min(1.0, out.get("bytes_in_use", total) / limit)
            out["devmem_frac"] = round(frac, 6)
        obs_journal.emit(event, plane=self.plane, worker=self.worker,
                         **out, **ctx)
        wd = obs_slo.active()
        if wd is not None and frac is not None:
            wd.observe("devmem_frac", frac)
        self._last = out
        self._set_gauges(out)
        return out

    @staticmethod
    def _backend_stats(jax) -> dict:
        """Allocator-view totals summed over local devices; {} when the
        backend doesn't implement memory_stats (CPU) — the signal is
        then absent, never zero."""
        in_use = peak = limit = 0
        seen = False
        try:
            for d in jax.local_devices():
                ms = d.memory_stats()
                if not ms:
                    continue
                seen = True
                in_use += int(ms.get("bytes_in_use", 0))
                peak += int(ms.get("peak_bytes_in_use", 0))
                limit += int(ms.get("bytes_limit", 0))
        except Exception:
            return {}
        if not seen:
            return {}
        out = {"bytes_in_use": in_use, "peak_bytes_in_use": peak}
        if limit:
            out["bytes_limit"] = limit
        return out

    def _set_gauges(self, out: dict) -> None:
        r = self.registry
        for key in ("total_bytes", "params_bytes", "opt_bytes",
                    "infeed_bytes", "other_bytes", "hwm_bytes"):
            r.set_gauge(key, out.get(key, 0))
        if "exec_bytes" in out:
            r.set_gauge("exec_bytes", out["exec_bytes"])
        else:
            r.remove_gauge("exec_bytes")  # absent signal, not zero
        if "params_dev_bytes" in out:
            r.set_gauge("params_dev_bytes", out["params_dev_bytes"])
        else:
            r.remove_gauge("params_dev_bytes")  # absent signal, not zero
        if "bytes_in_use" in out:
            r.set_gauge("backend_bytes_in_use", out["bytes_in_use"])
        if "bytes_limit" in out:
            r.set_gauge("backend_bytes_limit", out["bytes_limit"])
        with self._lock:
            models = dict(self._model_bytes)
        for name, b in models.items():
            r.set_gauge(f"model_bytes_{_escape(name)}", b,
                        labels='{model="%s"}' % name)

    def drop_model(self, name: str) -> None:
        """Eviction: the tenant's device bytes leave the scrape instead
        of freezing at their last value (same contract as the SLO
        watchdog's untrack_serve_tenant)."""
        with self._lock:
            self._model_bytes.pop(name, None)
        self.registry.remove_gauge(f"model_bytes_{_escape(name)}")

    def model_bytes(self) -> dict[str, int]:
        """Last-known device bytes per admitted model (the tenancy
        store's budget dashboard reads this)."""
        with self._lock:
            return dict(self._model_bytes)

    def state(self) -> dict:
        with self._lock:
            return {
                "high_water": self.high_water,
                "snapshots": self.snapshots,
                "model_bytes": dict(self._model_bytes),
            }

    def render_prometheus(self) -> str:
        """``stpu_devmem_*`` gauges for the plane's scrape surface.
        Renders the full gauge set from the first scrape (zeros before
        the first snapshot) — a series that appears only after its
        first event breaks dashboards, the registry's own rule."""
        self._set_gauges(self._last)
        return self.registry.render_prometheus("stpu_devmem_")


# ---- process-global hook (mirrors obs.trace / obs.journal) ----

_active: MemoryAccountant | None = None


def install(accountant: MemoryAccountant) -> MemoryAccountant:
    global _active
    _active = accountant
    return accountant


def uninstall() -> None:
    global _active
    _active = None


def active() -> MemoryAccountant | None:
    return _active
