"""Fleet skew observability: straggler detection, clock alignment, and
collective/transfer accounting — the fleet leg of the obs plane.

A synchronous fleet is only as fast as its slowest rank (the reason the
reference ran backup workers behind ``SyncReplicasOptimizer``, PAPER.md
L2/L3), yet the host (PR 4), causal/SLO (PR 7), and device (PR 10) legs
can only describe ONE process at a time.  This module answers the three
fleet questions they cannot:

- **which rank is slow, and why** — :class:`FleetMonitor` keeps one
  :class:`~shifu_tensorflow_tpu.obs.slo.WindowedDigest` of per-epoch
  step time per rank (fed by the coordinator from the phase summaries
  workers attach to their epoch reports), computes each rank's
  *relative skew* (its window mean over the median of its peers'), and
  runs a hysteretic state machine per rank: ``skew >= threshold`` for
  ``hysteresis`` consecutive epochs journals ``straggler_detect``
  naming the rank AND its dominant phase (the step phase whose excess
  over the fleet median is largest — "rank 1 is 1.8x the fleet and the
  time went to infeed"); recovery journals ``straggler_clear`` with
  the excursion length.  Barrier waits attribute the inverse view: the
  rank everyone else ``step.block``s on is the one with the SMALLEST
  barrier wait.  ``stpu_fleet_*`` gauges render on the coordinator
  ``metrics`` op, and the window-max skew feeds the
  ``shifu.tpu.slo-straggler-skew`` watchdog target — the exact signal
  the ROADMAP item-3 standby-takeover/autoscaler policy consumes.
- **what time it was** — :class:`ClockSync` estimates each worker's
  clock offset against the coordinator NTP-style, from the four
  timestamps of RPCs the worker already makes (client send / server
  receive / server send / client receive; no new traffic).  Server
  processing time — minutes inside an epoch barrier — cancels out of
  ``offset = ((t1-t0) + (t2-t3)) / 2``; the residual error is bounded
  by half the network round trip, and the estimator keeps the
  minimum-delay sample of a sliding window (the NTP discipline) so one
  congested exchange cannot skew it.  Each worker's
  :class:`~shifu_tensorflow_tpu.obs.journal.Journal` stamps the
  current estimate as an ``offset=`` field, so ``obs trace`` can
  render a fleet-aligned timeline instead of interleaving
  unsynchronized wall clocks.
- **what the collectives cost** — :func:`comm_region` wraps the
  host-callable collective entry points (``parallel/ring.py``
  rotations and all-to-alls, ``parallel/shmap.py`` shard_map calls,
  ``parallel/distributed.py`` bring-up and global device_put) in a
  tracer span (``comm.<kind>``, drained into ``step_breakdown`` per
  epoch like any auxiliary span), a PR-10 ``attribute()`` region (a
  compile inside lands on the collective's name), and a bytes-moved
  counter rendered as ``stpu_fleet_comm_*`` gauges and journaled per
  epoch as a ``comm`` event — per-step comm cost for the day sharded
  SPMD (ROADMAP item 1) and pipeline stages (item 5) land.

stdlib-only at import and off-by-default-cheap like its siblings: with
no monitor installed every seam is one module-global ``is None`` check,
and ``comm_region`` with nothing installed is a nullcontext.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Any

from shifu_tensorflow_tpu.obs.slo import WindowedDigest
from shifu_tensorflow_tpu.utils import logs

log = logs.get("obs")

__all__ = [
    "ClockSync",
    "FleetMonitor",
    "comm_region",
    "add_comm_bytes",
    "take_comm",
    "comm_text",
    "note_offset",
    "clock_offset",
    "install",
    "uninstall",
    "active",
]

_mono = time.monotonic

#: the disjoint step phases a straggler's excess is attributed to (the
#: step_breakdown schema's wall-clock split; "other" is wall minus the
#: named four)
PHASES = ("host", "infeed", "dispatch", "block", "other")


class ClockSync:
    """NTP-style clock-offset estimator over an existing RPC channel.

    Feed :meth:`update` the four timestamps of each request/reply
    exchange: ``t0`` client send, ``t1`` server receive, ``t2`` server
    send, ``t3`` client receive — all raw ``time.time()`` readings from
    their respective clocks.  The estimate::

        offset = ((t1 - t0) + (t2 - t3)) / 2     # server − client
        delay  = (t3 - t0) - (t2 - t1)           # network round trip

    cancels server processing time exactly (an epoch barrier can hold a
    reply for minutes without corrupting the estimate) and is wrong by
    at most ``delay / 2`` under asymmetric network legs — the classic
    NTP error bound, which :meth:`offset` minimizes by returning the
    minimum-delay sample of the last ``keep`` exchanges.  A worker
    restart constructs a fresh client and therefore a fresh estimator:
    offsets never survive the process whose clock they describe."""

    def __init__(self, keep: int = 8):
        self._samples: deque = deque(maxlen=max(1, int(keep)))
        self._lock = threading.Lock()

    def update(self, t0: float, t1: float, t2: float,
               t3: float) -> float | None:
        """Fold in one exchange; returns this sample's offset estimate
        (or None for an unusable sample — missing/absurd stamps)."""
        try:
            t0, t1, t2, t3 = (float(t0), float(t1), float(t2), float(t3))
        except (TypeError, ValueError):
            return None
        if t3 < t0 or t2 < t1:
            return None  # a clock ran backwards mid-exchange
        delay = max(0.0, (t3 - t0) - (t2 - t1))
        offset = ((t1 - t0) + (t2 - t3)) / 2.0
        with self._lock:
            self._samples.append((delay, offset))
        return offset

    def offset(self) -> float | None:
        """Best current estimate (the minimum-delay sample's offset),
        None before the first usable exchange."""
        with self._lock:
            if not self._samples:
                return None
            return min(self._samples)[1]

    def delay(self) -> float | None:
        """The best sample's round-trip delay — the error bound on
        :meth:`offset` is half of this."""
        with self._lock:
            if not self._samples:
                return None
            return min(self._samples)[0]

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()


class _RankState:
    """One rank's windowed statistics + straggler state machine.

    The digests are EPOCH-denominated: samples are added at
    ``now=epoch``, so "window" means the last ``window_epochs`` epochs
    this rank reported — the natural unit for a one-sample-per-epoch
    signal (a wall-clock window would hold a fast fleet's entire
    history in one bucket and never let a recovered straggler clear
    until real minutes passed).  Deterministic under test for free."""

    __slots__ = ("step", "phases", "barrier", "offset_s", "bad", "good",
                 "straggler", "since_ts", "since_epoch", "last_skew",
                 "last_epoch")

    def __init__(self, window_epochs: int):
        buckets = max(2, int(window_epochs))
        self.step = WindowedDigest(window_epochs, buckets, quantiles=())
        self.phases = {
            p: WindowedDigest(window_epochs, buckets, quantiles=())
            for p in PHASES
        }
        self.barrier = WindowedDigest(window_epochs, buckets,
                                      quantiles=())
        self.offset_s: float | None = None
        self.bad = 0
        self.good = 0
        self.straggler = False
        self.since_ts: float | None = None
        self.since_epoch: int | None = None
        self.last_skew = 1.0
        self.last_epoch = -1


class FleetMonitor:
    """Per-rank skew aggregation at the coordinator.

    ``observe_epoch`` is called by ``Coordinator.report_epoch`` with
    each worker's epoch wall time and the phase summary it attached
    (``EpochStats.phases`` — the same ``budget_fields`` drain
    ``Trainer._obs_epoch`` journals).  Detection is *relative*: a
    rank's skew is its window-mean step time over the median of its
    PEERS' window means, so a uniformly slow fleet (bigger model, cold
    cache) never alarms — only divergence between ranks does.
    Hysteresis mirrors the SLO watchdog: ``hysteresis`` consecutive
    breaching epochs to detect, the same count of clean ones to clear.
    ``min_excess_s`` is an absolute floor under the relative test: on
    millisecond-scale epochs (tiny drills, unit fleets) OS scheduling
    jitter alone exceeds any ratio threshold, and sub-jitter absolute
    skew is never operationally actionable anyway.
    """

    def __init__(self, *, window_epochs: int = 8,
                 skew_threshold: float = 1.5, hysteresis: int = 2,
                 warmup_epochs: int = 1, min_excess_s: float = 0.05,
                 plane: str = "coordinator"):
        if skew_threshold <= 1.0:
            raise ValueError(
                f"fleet skew threshold must be > 1 (a rank is a straggler "
                f"when it is THAT many times its peers), got {skew_threshold}")
        self.window_epochs = max(2, int(window_epochs))
        self.skew_threshold = float(skew_threshold)
        self.hysteresis = max(1, int(hysteresis))
        # epoch 0 is compile-dominated and its wall time is whoever won
        # the XLA race, not a data-path skew: warmup epochs neither feed
        # the digests nor advance the streaks (feeding them would
        # pollute the window for window_epochs MORE epochs)
        self.warmup_epochs = max(0, int(warmup_epochs))
        self.min_excess_s = max(0.0, float(min_excess_s))
        self.plane = plane
        self._lock = threading.Lock()
        self._ranks: dict[int, _RankState] = {}
        self._epoch_seen: dict[int, set[int]] = {}
        self.stragglers_total = 0

    # ---- feeding (coordinator side) ----
    def observe_epoch(self, worker: int, epoch: int, wall_s: float,
                      phases: dict | None = None,
                      n_workers: int | None = None) -> list[dict]:
        """Fold one rank's epoch report in; returns the events emitted
        (also journaled).  ``phases`` is the worker-attached
        ``step_breakdown`` field dict (host_s/infeed_s/... totals plus
        optional ``barrier_s``/``offset_s``)."""
        from shifu_tensorflow_tpu.obs import journal as obs_journal
        from shifu_tensorflow_tpu.obs import slo as obs_slo

        worker = int(worker)
        if int(epoch) < self.warmup_epochs:
            return []
        # the digests run on the EPOCH clock (see _RankState): a sample
        # ages out after window_epochs epochs, not wall seconds
        now = float(int(epoch)) + 0.5
        events: list[dict] = []
        with self._lock:
            rank = self._ranks.get(worker)
            if rank is not None and int(epoch) < rank.last_epoch:
                # epoch numbers regressed: a health rollback restarted
                # training from a checkpoint.  The digests are indexed
                # by epoch, so re-adding at an old epoch would RESET the
                # ring cell holding the newest samples and poison every
                # window mean for the next window_epochs — drop the
                # rank's history instead (skew re-establishes within a
                # couple of epochs) while carrying the straggler state
                # machine across, so an open excursion still closes with
                # a straggler_clear rather than dangling forever
                fresh = _RankState(self.window_epochs)
                fresh.straggler = rank.straggler
                fresh.since_ts = rank.since_ts
                fresh.since_epoch = rank.since_epoch
                fresh.bad, fresh.good = rank.bad, rank.good
                fresh.offset_s = rank.offset_s
                rank = self._ranks[worker] = fresh
                for e in [e for e in self._epoch_seen if e >= int(epoch)]:
                    del self._epoch_seen[e]
            if rank is None:
                rank = self._ranks[worker] = _RankState(
                    self.window_epochs)
            wall = max(0.0, float(wall_s))
            rank.step.add(wall, now=now)
            rank.last_epoch = int(epoch)
            if phases:
                named = 0.0
                for p in PHASES[:-1]:
                    v = float(phases.get(f"{p}_s", 0.0) or 0.0)
                    named += v
                    rank.phases[p].add(v, now=now)
                rank.phases["other"].add(max(0.0, wall - named), now=now)
                if phases.get("barrier_s") is not None:
                    rank.barrier.add(float(phases["barrier_s"]), now=now)
                if phases.get("offset_s") is not None:
                    rank.offset_s = float(phases["offset_s"])
            # hysteretic straggler state machine for THE REPORTING rank
            # only — each rank's streak advances once per ITS epochs,
            # so a fleet where one rank reports twice as often cannot
            # double-count breaches for its peers
            skew = self._skew_locked(worker, now)
            rank.last_skew = skew
            mine = self._mean_locked(worker, now)
            peers = self._peer_median_locked(worker, now)
            excess_s = ((mine - peers)
                        if mine is not None and peers is not None else 0.0)
            if skew >= self.skew_threshold and excess_s >= self.min_excess_s:
                rank.bad += 1
                rank.good = 0
                if not rank.straggler and rank.bad >= self.hysteresis:
                    rank.straggler = True
                    rank.since_ts = _mono()  # wall clock: excursion length
                    rank.since_epoch = int(epoch)
                    self.stragglers_total += 1
                    phase, excess = self._dominant_phase_locked(worker, now)
                    events.append({
                        "event": "straggler_detect",
                        "worker": worker,
                        "epoch": int(epoch),
                        "skew": round(skew, 4),
                        "threshold": self.skew_threshold,
                        "phase": phase,
                        "phase_excess_s": round(excess, 6),
                        "step_s": round(self._mean_locked(worker, now)
                                        or 0.0, 6),
                        "fleet_step_s": round(
                            self._peer_median_locked(worker, now) or 0.0,
                            6),
                        **self._barrier_attr_locked(now),
                    })
            else:
                rank.good += 1
                rank.bad = 0
                if rank.straggler and rank.good >= self.hysteresis:
                    rank.straggler = False
                    events.append({
                        "event": "straggler_clear",
                        "worker": worker,
                        "epoch": int(epoch),
                        "skew": round(skew, 4),
                        "straggler_s": round(
                            _mono() - (rank.since_ts or _mono()), 3),
                        "since_epoch": rank.since_epoch,
                    })
                    rank.since_ts = None
                    rank.since_epoch = None
            # quorum bookkeeping: one fleet_skew record per epoch, from
            # whichever report completes it (or from the first report
            # past a fleet whose size we were never told)
            seen = self._epoch_seen.setdefault(int(epoch), set())
            seen.add(worker)
            quorum = (n_workers is not None
                      and len(seen) >= int(n_workers))
            if quorum:
                del self._epoch_seen[int(epoch)]
                # drop stale partial epochs a restart leapfrogged
                for e in [e for e in self._epoch_seen if e <= int(epoch)]:
                    del self._epoch_seen[e]
                ranks, max_skew = self._table_locked(now)
                events.append({
                    "event": "fleet_skew",
                    "epoch": int(epoch),
                    "n_workers": int(n_workers),
                    "max_skew": round(max_skew, 4),
                    "straggler": self._current_straggler_locked(),
                    "ranks": ranks,
                })
        for ev in events:
            fields = {k: v for k, v in ev.items() if k != "event"}
            if ev["event"] in ("straggler_detect", "straggler_clear"):
                log.warning("%s: worker %s skew %.2f (epoch %s)",
                            ev["event"], ev.get("worker"),
                            ev.get("skew", 0.0), ev.get("epoch"))
            obs_journal.emit(ev["event"], plane=self.plane, **fields)
        if any(e["event"] == "fleet_skew" for e in events):
            wd = obs_slo.active()
            if wd is not None:
                # the slo-straggler-skew watchdog target judges the
                # window MAX of this signal; evaluated HERE because the
                # coordinator is the only process that can see fleet
                # skew (on the process launcher nothing else ticks its
                # plane's watchdog)
                max_skew = next(e["max_skew"] for e in events
                                if e["event"] == "fleet_skew")
                wd.observe("fleet_skew", max_skew)
                wd.evaluate(epoch=int(epoch))
        return events

    # ---- math (callers hold the lock) ----
    def _mean_locked(self, worker: int, now: float) -> float | None:
        snap = self._ranks[worker].step.snapshot(now)
        return None if snap is None else snap["mean"]

    def _peer_median_locked(self, worker: int,
                            now: float) -> float | None:
        """Median of the OTHER ranks' window means — self-exclusion so
        a 2-worker fleet's straggler cannot halve its own yardstick."""
        means = sorted(
            m for w, r in self._ranks.items()
            if w != worker
            for m in [self._mean_locked(w, now)]
            if m is not None and m > 0
        )
        if not means:
            return None
        mid = len(means) // 2
        if len(means) % 2:
            return means[mid]
        return (means[mid - 1] + means[mid]) / 2.0

    def _skew_locked(self, worker: int, now: float) -> float:
        mine = self._mean_locked(worker, now)
        peers = self._peer_median_locked(worker, now)
        if mine is None or peers is None or peers <= 0:
            return 1.0
        return mine / peers

    def _dominant_phase_locked(self, worker: int,
                               now: float) -> tuple[str, float]:
        """The phase whose excess over the fleet's per-phase median is
        largest — "WHERE the extra time went", not merely the biggest
        phase (a dispatch-dominated fleet where one rank's infeed grew
        3x must name infeed).  Falls back to the rank's own largest
        phase when no peer has phase data."""
        best, best_excess = "?", float("-inf")
        own_best, own_best_v = "?", float("-inf")
        for p in PHASES:
            snap = self._ranks[worker].phases[p].snapshot(now)
            if snap is None:
                continue
            mine = snap["mean"]
            if mine > own_best_v:
                own_best, own_best_v = p, mine
            peers = sorted(
                s["mean"]
                for w, r in self._ranks.items()
                if w != worker
                for s in [r.phases[p].snapshot(now)]
                if s is not None
            )
            if not peers:
                continue
            med = peers[len(peers) // 2]
            excess = mine - med
            if excess > best_excess:
                best, best_excess = p, excess
        if best_excess == float("-inf"):
            return own_best, max(0.0, own_best_v)
        return best, max(0.0, best_excess)

    def _barrier_attr_locked(self, now: float) -> dict:
        """Barrier-wait attribution: everyone waits at the epoch
        barrier FOR the straggler, so the rank with the smallest mean
        barrier wait is the one being waited on.  Only meaningful when
        at least two ranks report barrier spans and they diverge."""
        waits = {
            w: s["mean"]
            for w, r in self._ranks.items()
            for s in [r.barrier.snapshot(now)]
            if s is not None
        }
        if len(waits) < 2:
            return {}
        lo = min(waits, key=waits.get)
        hi = max(waits.values())
        if hi <= 0:
            return {}
        return {"blocked_on": lo,
                "barrier_wait_s": round(waits[lo], 6),
                "peer_barrier_wait_s": round(hi, 6)}

    def _table_locked(self, now: float) -> tuple[dict, float]:
        ranks: dict[str, dict] = {}
        max_skew = 1.0
        for w in sorted(self._ranks):
            r = self._ranks[w]
            mean = self._mean_locked(w, now)
            skew = self._skew_locked(w, now)
            max_skew = max(max_skew, skew)
            phase, _ = self._dominant_phase_locked(w, now)
            barrier = r.barrier.snapshot(now)
            entry: dict[str, Any] = {
                "step_s": round(mean or 0.0, 6),
                "skew": round(skew, 4),
                "phase": phase,
                "straggler": r.straggler,
                "epoch": r.last_epoch,
            }
            if barrier is not None:
                entry["barrier_s"] = round(barrier["mean"], 6)
            if r.offset_s is not None:
                entry["offset_s"] = round(r.offset_s, 6)
            ranks[str(w)] = entry
        return ranks, max_skew

    def _current_straggler_locked(self) -> int | None:
        for w, r in self._ranks.items():
            if r.straggler:
                return w
        return None

    # ---- reading ----
    def state(self) -> dict:
        with self._lock:
            # evaluate at the fleet's newest epoch (the digests run on
            # the epoch clock)
            now = max(
                (r.last_epoch for r in self._ranks.values()),
                default=0,
            ) + 0.5
            ranks, max_skew = self._table_locked(now)
            return {
                "ranks": ranks,
                "max_skew": max_skew,
                "straggler": self._current_straggler_locked(),
                "stragglers_total": self.stragglers_total,
            }

    def render_prometheus(self, prefix: str = "stpu_") -> str:
        """``stpu_fleet_*`` gauge text for the coordinator's scrape
        surface.  Hand-rendered: per-rank series share one metric name
        across ``worker=`` label values, which the one-label-set-per-
        gauge registry cannot express."""
        s = self.state()
        lines = [
            f"# TYPE {prefix}fleet_skew gauge",
        ]
        for w, r in s["ranks"].items():
            lines.append(
                f'{prefix}fleet_skew{{worker="{w}"}} {r["skew"]}')
        lines.append(f"# TYPE {prefix}fleet_step_seconds gauge")
        for w, r in s["ranks"].items():
            lines.append(
                f'{prefix}fleet_step_seconds{{worker="{w}"}} '
                f'{r["step_s"]}')
        offsets = {w: r["offset_s"] for w, r in s["ranks"].items()
                   if "offset_s" in r}
        if offsets:
            lines.append(f"# TYPE {prefix}fleet_clock_offset_seconds "
                         f"gauge")
            for w, off in offsets.items():
                lines.append(
                    f'{prefix}fleet_clock_offset_seconds{{worker="{w}"}} '
                    f'{off}')
        lines.append(f"# TYPE {prefix}fleet_straggler gauge")
        lines.append(f"{prefix}fleet_straggler "
                     f"{-1 if s['straggler'] is None else s['straggler']}")
        lines.append(f"# TYPE {prefix}fleet_max_skew gauge")
        lines.append(f"{prefix}fleet_max_skew {round(s['max_skew'], 4)}")
        lines.append(f"# TYPE {prefix}fleet_stragglers_total counter")
        lines.append(f"{prefix}fleet_stragglers_total "
                     f"{s['stragglers_total']}")
        return "\n".join(lines) + "\n" + comm_text(prefix)


# ---- collective/transfer accounting (worker side) ----

class _CommStats:
    """Process-wide bytes-moved counters per collective kind.  One dict
    update per collective call — noise against an actual transfer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_kind: dict[str, list] = {}  # kind -> [calls, bytes]

    def add(self, kind: str, nbytes: int) -> None:
        with self._lock:
            e = self._by_kind.get(kind)
            if e is None:
                self._by_kind[kind] = [1, int(nbytes)]
            else:
                e[0] += 1
                e[1] += int(nbytes)

    def snapshot(self, reset: bool = False) -> dict[str, dict]:
        with self._lock:
            out = {k: {"calls": v[0], "bytes": v[1]}
                   for k, v in self._by_kind.items()}
            if reset:
                self._by_kind = {}
            return out


_comm = _CommStats()
#: lifetime totals for the scrape surface (snapshot(reset) drains the
#: per-epoch view into the journal; gauges must keep counting)
_comm_total = _CommStats()


def add_comm_bytes(kind: str, nbytes: int) -> None:
    """Count one collective/transfer call's bytes moved (a static
    estimate from the argument shapes is fine — the point is relative
    attribution, not a NIC counter)."""
    _comm.add(kind, nbytes)
    _comm_total.add(kind, nbytes)


def take_comm() -> dict[str, dict]:
    """Drain the per-epoch comm snapshot (``Trainer._obs_epoch``
    journals it as a ``comm`` event); lifetime gauges keep counting."""
    return _comm.snapshot(reset=True)


def comm_text(prefix: str = "stpu_") -> str:
    """``stpu_fleet_comm_*`` series (lifetime totals per kind)."""
    snap = _comm_total.snapshot()
    if not snap:
        return ""
    lines = [f"# TYPE {prefix}fleet_comm_calls_total counter"]
    for kind in sorted(snap):
        lines.append(
            f'{prefix}fleet_comm_calls_total{{kind="{kind}"}} '
            f'{snap[kind]["calls"]}')
    lines.append(f"# TYPE {prefix}fleet_comm_bytes_total counter")
    for kind in sorted(snap):
        lines.append(
            f'{prefix}fleet_comm_bytes_total{{kind="{kind}"}} '
            f'{snap[kind]["bytes"]}')
    return "\n".join(lines) + "\n"


@contextlib.contextmanager
def comm_region(kind: str, nbytes: int = 0):
    """Instrument one collective/transfer entry point: a tracer span
    (``comm.<kind>`` — drains into the epoch's ``step_breakdown`` spans
    like any auxiliary span), a PR-10 compile-attribution region (a
    compile fired inside lands on the collective's name), and the
    bytes-moved counters.  Each leg is one ``is None`` check when its
    plane is off; with nothing installed only the byte counters run.

    Counting unit: one HOST-LEVEL call.  An eager entry point (the
    pipelined device_put, a direct ring call) counts once per step; a
    collective invoked from inside an enclosing ``jit`` runs this
    wrapper only while XLA TRACES, so it counts once per compile — the
    device-side repetitions execute inside the compiled program, where
    host instrumentation cannot see them (the same rule the PR-10
    Pallas seams follow).  The counters are call/shape attribution, not
    a NIC counter; per-step device comm cost under jit is the enclosing
    observed step's wall time."""
    from shifu_tensorflow_tpu.obs import compile as obs_compile
    from shifu_tensorflow_tpu.obs import trace as obs_trace

    if nbytes:
        add_comm_bytes(kind, nbytes)
    with obs_trace.span(f"comm.{kind}"):
        with obs_compile.attribute(f"comm.{kind}"):
            yield


# ---- worker-side clock plumbing ----

_last_offset: float | None = None


def note_offset(offset: float | None) -> None:
    """Record this process's current clock-offset estimate (coordinator
    clock minus local clock).  Called by ``CoordinatorClient`` after
    each timestamped exchange; the active Journal stamps it onto every
    subsequent event as ``offset=`` so readers can align the fleet's
    timelines onto the coordinator's clock."""
    global _last_offset
    if offset is None:
        return
    _last_offset = float(offset)
    from shifu_tensorflow_tpu.obs import journal as obs_journal

    j = obs_journal.active()
    if j is not None:
        j.set_offset(_last_offset)


def clock_offset() -> float | None:
    """This process's last clock-offset estimate (None before the first
    timestamped coordinator exchange)."""
    return _last_offset


# ---- process-global hook (mirrors the sibling legs) ----

_active: FleetMonitor | None = None


def install(monitor: FleetMonitor) -> FleetMonitor:
    global _active
    _active = monitor
    return monitor


def uninstall() -> None:
    global _active
    _active = None


def active() -> FleetMonitor | None:
    return _active
