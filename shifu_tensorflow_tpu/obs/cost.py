"""Device-time & cost attribution: who actually consumed the device.

The tenancy ``DeviceScheduler`` shares one device by deficit round-robin
and the serve batcher times every dispatch (``dispatch_s``) — but until
now nobody ACCOUNTED that time: the weight-rebalancing policy ROADMAP
item 3 wants ("victim tenant's p99 recovers without operator input")
needs to know which tenant consumed how many device-seconds, not just
who was queued.  This module is that ledger, in the obs plane's usual
shape: one process-global accountant (``install``/``active``), seams
that are a single is-None check when the plane is off, and a Prometheus
text block every scrape surface appends (``obs.device_obs_text``).

Per tenant, monotonic counters:

- **device-seconds** — wall time inside ``score_fn`` (the batcher's
  ``dispatch_s``), the raw device occupancy;
- **padded-row-seconds** — ``dispatch_s × bucket`` rows, the DRR
  currency: what the scheduler actually charges (padding cannot launder
  cost), so tenant shares here compare directly against their
  configured weights;
- **rows** and **bytes** — payload volume (pre-padding), the
  denominator for per-row cost.

Plus the device lane itself: cumulative **busy seconds** (the whole
dispatch envelope, scoring included) against the lane's wall clock
since first dispatch — the busy/idle split is the headroom gauge an
autoscaler reads before adding load, and the conservation bound the
rollup drill checks per-tenant device-seconds against.

The train plane attributes device-seconds per (job, worker): the
``Trainer._obs_epoch`` step-phase drain already measures
``dispatch_s`` per epoch, and the journal's ``job`` stamp scopes it —
one merged scrape answers "what did job X's worker 3 cost".

Everything exports as ``stpu_cost_*`` on every ``/metrics`` surface and
flows into the rollup sidecar via the compactor's counter-source poll
(:mod:`obs.rollup`), so a dead fleet's cost table reconstructs from
files alone.
"""

from __future__ import annotations

import threading
import time
from typing import Any

__all__ = [
    "CostAccountant",
    "install",
    "uninstall",
    "active",
]

_mono = time.monotonic


class _TenantCost:
    __slots__ = ("device_s", "padded_row_s", "rows", "batches", "bytes")

    def __init__(self):
        self.device_s = 0.0
        self.padded_row_s = 0.0
        self.rows = 0
        self.batches = 0
        self.bytes = 0


class CostAccountant:
    """Monotonic device-time ledger.  All note_* calls are hot-path
    cheap (one lock + float adds); rendering and counter export are
    scrape-time work."""

    def __init__(self, *, plane: str = "serve",
                 worker: int | None = None):
        self.plane = plane
        self.worker = worker
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantCost] = {}
        # worker -> {"device_s": x, "steps": n} (train attribution; on
        # the train plane each process accounts its own rank, on the
        # thread launcher all ranks share this accountant)
        self._train: dict[int, dict[str, float]] = {}
        # the device lane: busy wall inside the dispatch envelope vs
        # wall clock since the lane first dispatched.  Starting the
        # clock at first use (not construction) keeps a server that
        # sat idle before its first request from reading as headroom
        # it never actually had.
        self._busy_s = 0.0
        self._lane_started: float | None = None

    # ---- serve side ----
    def note_dispatch(self, model: str | None, *, dispatch_s: float,
                      rows: int, bucket_rows: int,
                      nbytes: int = 0) -> None:
        """Attribute one scored batch to its tenant (the batcher's
        dispatch thread / the scheduler's device thread)."""
        key = model or "default"
        with self._lock:
            t = self._tenants.get(key)
            if t is None:
                t = self._tenants[key] = _TenantCost()
            t.device_s += dispatch_s
            t.padded_row_s += dispatch_s * bucket_rows
            t.rows += rows
            t.batches += 1
            t.bytes += nbytes

    def note_busy(self, seconds: float) -> None:
        """One dispatch ENVELOPE's wall time (scoring + handoffs) on
        the device lane — the denominator-side measurement the
        per-tenant device-seconds must conserve against."""
        now = _mono()
        with self._lock:
            if self._lane_started is None:
                self._lane_started = now - seconds
            self._busy_s += seconds

    # ---- train side ----
    def note_train_epoch(self, worker: int | None, *, dispatch_s: float,
                         steps: int) -> None:
        """Attribute one epoch's device dispatch time to its rank (fed
        from the same ``step_breakdown`` drain the journal records, so
        the numbers agree by construction)."""
        w = int(worker or 0)
        with self._lock:
            rec = self._train.get(w)
            if rec is None:
                rec = self._train[w] = {"device_s": 0.0, "steps": 0.0}
            rec["device_s"] += dispatch_s
            rec["steps"] += steps

    # ---- reading ----
    def utilization(self) -> dict[str, float] | None:
        """Busy/idle split of the device lane since its first dispatch,
        or None before any (the signal is absent, not 100% idle)."""
        with self._lock:
            if self._lane_started is None:
                return None
            wall = max(_mono() - self._lane_started, 1e-9)
            busy = min(self._busy_s, wall)
            return {
                "busy_s": round(busy, 6),
                "wall_s": round(wall, 6),
                "busy_frac": round(busy / wall, 6),
                "idle_frac": round(1.0 - busy / wall, 6),
            }

    def counters(self) -> dict[str, float]:
        """Flat monotonic counters for the rollup compactor's source
        poll: per-tenant series keyed ``<counter>:<model>``, train
        series ``train_device_seconds:w<rank>``, plus the lane's busy
        seconds.  Values are cumulative; the compactor writes per-window
        deltas."""
        out: dict[str, float] = {}
        with self._lock:
            for name, t in self._tenants.items():
                out[f"device_seconds:{name}"] = round(t.device_s, 6)
                out[f"padded_row_seconds:{name}"] = round(t.padded_row_s, 6)
                out[f"rows:{name}"] = t.rows
                out[f"batches:{name}"] = t.batches
                out[f"bytes:{name}"] = t.bytes
            for w, rec in self._train.items():
                out[f"train_device_seconds:w{w}"] = round(rec["device_s"], 6)
                out[f"train_steps:w{w}"] = int(rec["steps"])
            if self._lane_started is not None:
                out["device_busy_seconds"] = round(self._busy_s, 6)
        return out

    def state(self) -> dict[str, Any]:
        """Structured snapshot (tests, /healthz embedding)."""
        with self._lock:
            tenants = {
                name: {"device_s": round(t.device_s, 6),
                       "padded_row_s": round(t.padded_row_s, 6),
                       "rows": t.rows, "batches": t.batches,
                       "bytes": t.bytes}
                for name, t in self._tenants.items()
            }
            train = {w: {"device_s": round(r["device_s"], 6),
                         "steps": int(r["steps"])}
                     for w, r in self._train.items()}
        return {"tenants": tenants, "train": train,
                "utilization": self.utilization()}

    def render_prometheus(self, prefix: str = "stpu_") -> str:
        """The ``stpu_cost_*`` scrape block: per-tenant counters share
        one metric name across ``model=`` label values (hand-rendered,
        like the coordinator's per-worker heartbeat gauges), so a
        dashboard sums or ratios tenants without name surgery."""
        with self._lock:
            tenants = sorted(self._tenants.items())
            train = sorted(self._train.items())
        lines: list[str] = []
        per_tenant = (
            ("cost_device_seconds_total", "device_s", 6),
            ("cost_padded_row_seconds_total", "padded_row_s", 6),
            ("cost_rows_total", "rows", 0),
            ("cost_bytes_total", "bytes", 0),
        )
        for metric, attr, nd in per_tenant:
            if not tenants:
                continue
            lines.append(f"# TYPE {prefix}{metric} counter")
            for name, t in tenants:
                v = getattr(t, attr)
                v = round(v, nd) if nd else int(v)
                lines.append(f'{prefix}{metric}{{model="{name}"}} {v}')
        if train:
            lines.append(f"# TYPE {prefix}cost_train_device_seconds_total"
                         " counter")
            for w, rec in train:
                lines.append(
                    f'{prefix}cost_train_device_seconds_total'
                    f'{{worker="{w}"}} {round(rec["device_s"], 6)}')
        util = self.utilization()
        if util is not None:
            lines.append(f"# TYPE {prefix}cost_device_busy_frac gauge")
            lines.append(f"{prefix}cost_device_busy_frac"
                         f" {util['busy_frac']}")
            lines.append(f"# TYPE {prefix}cost_device_idle_frac gauge")
            lines.append(f"{prefix}cost_device_idle_frac"
                         f" {util['idle_frac']}")
            lines.append(f"# TYPE {prefix}cost_device_busy_seconds_total"
                         " counter")
            lines.append(f"{prefix}cost_device_busy_seconds_total"
                         f" {util['busy_s']}")
        return "\n".join(lines) + "\n" if lines else ""


# ---- process-global hook (mirrors obs.trace / obs.journal) ----

_active: CostAccountant | None = None


def install(accountant: CostAccountant) -> CostAccountant:
    global _active
    _active = accountant
    return accountant


def uninstall() -> None:
    global _active
    _active = None


def active() -> CostAccountant | None:
    return _active
