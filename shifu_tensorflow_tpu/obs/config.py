"""Observability configuration — the ``shifu.tpu.obs-*`` surface as a
typed dataclass, resolved with the framework's usual precedence
(built-in defaults → ``--globalconfig`` XML/JSON layers → CLI flags),
the same bridge the serve and health keys ride.

Import-light on purpose (stdlib + config.keys only): every CLI resolves
this on startup, including ``--help`` paths that must not pay for jax.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field

from shifu_tensorflow_tpu.config import keys as K


@dataclass(frozen=True)
class ObsConfig:
    """Everything the observability plane needs — JSON-bridgeable so a
    submitter ships it to subprocess workers inside WorkerConfig, the
    same way the retry envelope travels."""

    enabled: bool = K.DEFAULT_OBS_ENABLED
    journal_path: str = K.DEFAULT_OBS_JOURNAL
    journal_max_bytes: int = K.DEFAULT_OBS_JOURNAL_MAX_BYTES
    journal_max_files: int = K.DEFAULT_OBS_JOURNAL_MAX_FILES
    trace_sample: int = K.DEFAULT_OBS_TRACE_SAMPLE
    hist_buckets: tuple[float, ...] = field(default_factory=tuple)
    # SLO watchdog (shifu.tpu.slo-* — obs/slo.py): window + hysteresis +
    # per-plane targets.  Flat fields (not a nested dataclass) so the
    # existing WorkerConfig JSON bridge carries them unchanged.
    slo_window_s: float = K.DEFAULT_SLO_WINDOW_S
    slo_serve_p99_ms: float = K.DEFAULT_SLO_SERVE_P99_MS
    slo_serve_shed_rate: float = K.DEFAULT_SLO_SERVE_SHED_RATE
    slo_step_time_ms: float = K.DEFAULT_SLO_STEP_TIME_MS
    slo_infeed_frac: float = K.DEFAULT_SLO_INFEED_FRAC
    slo_hysteresis: int = K.DEFAULT_SLO_HYSTERESIS
    slo_anomaly_sigma: float = K.DEFAULT_SLO_ANOMALY_SIGMA
    # device/compiler leg (obs/compile.py + obs/memory.py) — flat fields
    # for the same JSON-bridge reason as the slo block above
    compile_analysis: str = K.DEFAULT_OBS_COMPILE_ANALYSIS
    compile_storm: int = K.DEFAULT_OBS_COMPILE_STORM
    # jax persistent compilation cache (shifu.tpu.compile-cache-dir) —
    # the middle tier of the AOT fallback ladder (export/aot.py):
    # applied by install_obs on every jax plane, bridged to subprocess
    # workers like every other field.  Empty = off.
    compile_cache_dir: str = K.DEFAULT_COMPILE_CACHE_DIR
    slo_compile_s: float = K.DEFAULT_SLO_COMPILE_S
    slo_devmem_frac: float = K.DEFAULT_SLO_DEVMEM_FRAC
    # fleet leg (obs/fleet.py) — straggler skew watchdog target (0 =
    # untargeted) and the detect/clear threshold; flat for the same
    # JSON-bridge reason as the blocks above
    slo_straggler_skew: float = K.DEFAULT_SLO_STRAGGLER_SKEW
    fleet_skew_threshold: float = K.DEFAULT_FLEET_SKEW_THRESHOLD
    # data leg (obs/datastats.py) — drift-score watchdog target (0 =
    # untargeted) and the per-feature detect/clear threshold
    slo_data_drift: float = K.DEFAULT_SLO_DATA_DRIFT
    data_drift_threshold: float = K.DEFAULT_DATA_DRIFT_THRESHOLD
    # long-horizon leg (obs/rollup.py) — the rotation-exempt rollup
    # sidecar compactor (active only with a journal path), the pinned
    # baseline for cross-run comparison, and the regression watchdog
    # target; flat fields for the same JSON-bridge reason as above
    rollup: bool = K.DEFAULT_OBS_ROLLUP
    rollup_window_s: float = K.DEFAULT_OBS_ROLLUP_WINDOW_S
    baseline_path: str = K.DEFAULT_OBS_BASELINE
    slo_regression: float = K.DEFAULT_SLO_REGRESSION

    def __post_init__(self):
        if self.journal_max_bytes < 4096:
            raise ValueError(
                f"{K.OBS_JOURNAL_MAX_BYTES} must be >= 4096 bytes "
                f"(got {self.journal_max_bytes}): a cap below one event "
                "batch would rotate on every line"
            )
        if self.journal_max_files < 1:
            raise ValueError(f"{K.OBS_JOURNAL_MAX_FILES} must be >= 1")
        if self.trace_sample < 1:
            raise ValueError(f"{K.OBS_TRACE_SAMPLE} must be >= 1")
        if list(self.hist_buckets) != sorted(self.hist_buckets) or any(
            b <= 0 for b in self.hist_buckets
        ):
            raise ValueError(
                f"{K.OBS_HIST_BUCKETS} must be positive and ascending, "
                f"got {self.hist_buckets}"
            )
        if self.slo_window_s <= 0:
            raise ValueError(f"{K.SLO_WINDOW_S} must be > 0")
        if self.slo_hysteresis < 1:
            raise ValueError(f"{K.SLO_HYSTERESIS} must be >= 1")
        for key, val in ((K.SLO_SERVE_P99_MS, self.slo_serve_p99_ms),
                         (K.SLO_SERVE_SHED_RATE, self.slo_serve_shed_rate),
                         (K.SLO_STEP_TIME_MS, self.slo_step_time_ms),
                         (K.SLO_INFEED_FRAC, self.slo_infeed_frac),
                         (K.SLO_ANOMALY_SIGMA, self.slo_anomaly_sigma),
                         (K.SLO_COMPILE_S, self.slo_compile_s),
                         (K.SLO_DEVMEM_FRAC, self.slo_devmem_frac)):
            if val < 0:
                raise ValueError(f"{key} must be >= 0 (0 = disabled), "
                                 f"got {val}")
        for key, val in ((K.SLO_SERVE_SHED_RATE, self.slo_serve_shed_rate),
                         (K.SLO_INFEED_FRAC, self.slo_infeed_frac),
                         (K.SLO_DEVMEM_FRAC, self.slo_devmem_frac)):
            if val > 1:
                raise ValueError(f"{key} is a fraction in [0, 1], got {val}")
        if self.slo_straggler_skew < 0:
            raise ValueError(f"{K.SLO_STRAGGLER_SKEW} must be >= 0 "
                             f"(0 = disabled), got {self.slo_straggler_skew}")
        if 0 < self.slo_straggler_skew <= 1:
            raise ValueError(
                f"{K.SLO_STRAGGLER_SKEW} must be > 1 when set (skew is a "
                f"ratio; the fleet sits at 1 when balanced), got "
                f"{self.slo_straggler_skew}")
        if self.fleet_skew_threshold <= 1:
            raise ValueError(
                f"{K.FLEET_SKEW_THRESHOLD} must be > 1 (a rank is a "
                f"straggler when it is that many times its peers), got "
                f"{self.fleet_skew_threshold}")
        if self.slo_data_drift < 0:
            raise ValueError(f"{K.SLO_DATA_DRIFT} must be >= 0 "
                             f"(0 = disabled), got {self.slo_data_drift}")
        if self.data_drift_threshold <= 0:
            raise ValueError(
                f"{K.DATA_DRIFT_THRESHOLD} must be > 0 (a 0 threshold "
                f"would flag every feature on every tick), got "
                f"{self.data_drift_threshold}")
        if self.rollup_window_s <= 0:
            raise ValueError(f"{K.OBS_ROLLUP_WINDOW_S} must be > 0, got "
                             f"{self.rollup_window_s}")
        if self.slo_regression < 0:
            raise ValueError(f"{K.SLO_REGRESSION} must be >= 0 "
                             f"(0 = disabled), got {self.slo_regression}")
        if 0 < self.slo_regression <= 1:
            raise ValueError(
                f"{K.SLO_REGRESSION} must be > 1 when set (it is a "
                f"live/baseline ratio; a run sits at ~1 against its own "
                f"baseline), got {self.slo_regression}")
        if self.compile_analysis not in ("auto", "full", "cost", "off"):
            raise ValueError(
                f"{K.OBS_COMPILE_ANALYSIS} must be auto|full|cost|off, "
                f"got {self.compile_analysis!r}")
        if self.compile_storm < 2:
            raise ValueError(f"{K.OBS_COMPILE_STORM} must be >= 2, got "
                             f"{self.compile_storm} (a 1-compile 'storm' "
                             "would fire on every cold start)")

    def to_json(self) -> dict:
        d = asdict(self)
        d["hist_buckets"] = list(self.hist_buckets)
        return d

    @classmethod
    def from_json(cls, d: dict) -> "ObsConfig":
        d = dict(d)
        d["hist_buckets"] = tuple(d.get("hist_buckets") or ())
        return cls(**d)


def parse_buckets(value: str) -> tuple[float, ...]:
    """Comma-separated seconds -> bounds tuple ("" = built-in ladder)."""
    if not value or not value.strip():
        return ()
    return tuple(float(s) for s in value.split(",") if s.strip())


def resolve_obs_config(args, conf) -> ObsConfig:
    """CLI flag wins, then the conf key, then the built-in default.

    ``--obs-journal`` (or a conf journal path) implies ``enabled``: a
    requested journal that silently recorded nothing because a second
    flag was missing would be the worst kind of observability bug.
    ``args`` may be any namespace — absent attributes read as unset, so
    the serve CLI and the train CLI share this resolver.
    """

    def flag(name):
        return getattr(args, name, None)

    journal = flag("obs_journal")
    if journal is None:
        journal = conf.get(K.OBS_JOURNAL, K.DEFAULT_OBS_JOURNAL) or ""
    enabled = flag("obs")
    if enabled is None:
        enabled = conf.get_bool(K.OBS_ENABLED, K.DEFAULT_OBS_ENABLED)
    enabled = bool(enabled) or bool(journal)
    max_bytes = conf.get_memory(
        K.OBS_JOURNAL_MAX_BYTES, str(K.DEFAULT_OBS_JOURNAL_MAX_BYTES)
    )
    return ObsConfig(
        enabled=enabled,
        journal_path=journal,
        journal_max_bytes=int(max_bytes),
        journal_max_files=conf.get_int(K.OBS_JOURNAL_MAX_FILES,
                                       K.DEFAULT_OBS_JOURNAL_MAX_FILES),
        trace_sample=conf.get_int(K.OBS_TRACE_SAMPLE,
                                  K.DEFAULT_OBS_TRACE_SAMPLE),
        hist_buckets=parse_buckets(
            conf.get(K.OBS_HIST_BUCKETS, K.DEFAULT_OBS_HIST_BUCKETS) or ""
        ),
        slo_window_s=conf.get_float(K.SLO_WINDOW_S, K.DEFAULT_SLO_WINDOW_S),
        slo_serve_p99_ms=conf.get_float(K.SLO_SERVE_P99_MS,
                                        K.DEFAULT_SLO_SERVE_P99_MS),
        slo_serve_shed_rate=conf.get_float(K.SLO_SERVE_SHED_RATE,
                                           K.DEFAULT_SLO_SERVE_SHED_RATE),
        slo_step_time_ms=conf.get_float(K.SLO_STEP_TIME_MS,
                                        K.DEFAULT_SLO_STEP_TIME_MS),
        slo_infeed_frac=conf.get_float(K.SLO_INFEED_FRAC,
                                       K.DEFAULT_SLO_INFEED_FRAC),
        slo_hysteresis=conf.get_int(K.SLO_HYSTERESIS,
                                    K.DEFAULT_SLO_HYSTERESIS),
        slo_anomaly_sigma=conf.get_float(K.SLO_ANOMALY_SIGMA,
                                         K.DEFAULT_SLO_ANOMALY_SIGMA),
        compile_analysis=(conf.get(K.OBS_COMPILE_ANALYSIS,
                                   K.DEFAULT_OBS_COMPILE_ANALYSIS)
                          or K.DEFAULT_OBS_COMPILE_ANALYSIS).strip(),
        compile_storm=conf.get_int(K.OBS_COMPILE_STORM,
                                   K.DEFAULT_OBS_COMPILE_STORM),
        compile_cache_dir=(flag("compile_cache_dir")
                           or conf.get(K.COMPILE_CACHE_DIR,
                                       K.DEFAULT_COMPILE_CACHE_DIR)
                           or ""),
        slo_compile_s=conf.get_float(K.SLO_COMPILE_S,
                                     K.DEFAULT_SLO_COMPILE_S),
        slo_devmem_frac=conf.get_float(K.SLO_DEVMEM_FRAC,
                                       K.DEFAULT_SLO_DEVMEM_FRAC),
        slo_straggler_skew=conf.get_float(K.SLO_STRAGGLER_SKEW,
                                          K.DEFAULT_SLO_STRAGGLER_SKEW),
        fleet_skew_threshold=conf.get_float(
            K.FLEET_SKEW_THRESHOLD, K.DEFAULT_FLEET_SKEW_THRESHOLD),
        slo_data_drift=conf.get_float(K.SLO_DATA_DRIFT,
                                      K.DEFAULT_SLO_DATA_DRIFT),
        data_drift_threshold=conf.get_float(
            K.DATA_DRIFT_THRESHOLD, K.DEFAULT_DATA_DRIFT_THRESHOLD),
        rollup=conf.get_bool(K.OBS_ROLLUP, K.DEFAULT_OBS_ROLLUP),
        rollup_window_s=conf.get_float(K.OBS_ROLLUP_WINDOW_S,
                                       K.DEFAULT_OBS_ROLLUP_WINDOW_S),
        baseline_path=(flag("obs_baseline")
                       or conf.get(K.OBS_BASELINE, K.DEFAULT_OBS_BASELINE)
                       or ""),
        slo_regression=conf.get_float(K.SLO_REGRESSION,
                                      K.DEFAULT_SLO_REGRESSION),
    )
