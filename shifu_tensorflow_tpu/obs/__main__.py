"""Observability CLI — render a journal as a time budget + fleet timeline.

    python -m shifu_tensorflow_tpu.obs summary --journal /tmp/job.jsonl
    python -m shifu_tensorflow_tpu.obs tail    --journal /tmp/job.jsonl -n 40

Works on a finished or a RUNNING job: readers never lock writers, and a
torn final line (writer killed mid-event) is skipped, not fatal.  The
``--journal`` path is the base the job was configured with
(``shifu.tpu.obs-journal``); fleet-worker siblings (``.w<k>``) and
rotations (``.N``) are discovered and merged by timestamp.

stdlib-only and jax-free: this must run on an operator's laptop against
a journal scp'd out of a dead fleet.
"""

from __future__ import annotations

import argparse
import sys
from collections import defaultdict

from shifu_tensorflow_tpu.obs.journal import journal_files, read_events

#: events that are high-signal fleet lifecycle (the timeline keeps every
#: event, but these get rendered even under --compact aggregation)
_STEP_PHASES = ("infeed", "host", "dispatch", "block")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m shifu_tensorflow_tpu.obs",
        description="Inspect a shifu.tpu.obs-journal event journal.",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    tail = sub.add_parser("tail", help="print the last N events")
    tail.add_argument("--journal", required=True,
                      help="journal base path (shifu.tpu.obs-journal)")
    tail.add_argument("-n", type=int, default=20, dest="count",
                      help="events to show (default 20)")
    summ = sub.add_parser(
        "summary",
        help="per-step time budget + fleet event timeline",
    )
    summ.add_argument("--journal", required=True,
                      help="journal base path (shifu.tpu.obs-journal)")
    summ.add_argument("--timeline-limit", type=int, default=200,
                      help="max timeline rows (default 200; 0 = all)")
    return p


def _fmt_event(ev: dict, t0: float) -> str:
    ts = ev.get("ts", t0)
    plane = ev.get("plane", "?")
    worker = ev.get("worker")
    who = f"{plane} w{worker}" if worker is not None else plane
    skip = {"ts", "event", "plane", "worker"}
    detail = " ".join(
        f"{k}={_short(v)}" for k, v in ev.items() if k not in skip
    )
    return f"+{ts - t0:10.3f}s  {who:<14} {ev.get('event', '?'):<22} {detail}"


def _short(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    s = str(v)
    return s if len(s) <= 60 else s[:57] + "..."


def cmd_tail(args) -> int:
    events = read_events(args.journal)
    if not events:
        print(f"no journal events under {args.journal!r} "
              f"(files: {journal_files(args.journal) or 'none'})",
              file=sys.stderr)
        return 1
    t0 = events[0].get("ts", 0.0)
    for ev in events[-args.count:]:
        print(_fmt_event(ev, t0))
    return 0


def _step_budget(events: list[dict]) -> list[str]:
    """Aggregate step_breakdown (+ matching epoch) events into one
    budget row per worker: where each step's wall clock went."""
    # (worker) -> accumulated phase seconds / steps / epochs
    acc: dict = defaultdict(lambda: {
        "epochs": 0, "steps": 0,
        "infeed_wait": 0.0, "infeed_put": 0.0, "host_produce": 0.0,
        **{p: 0.0 for p in _STEP_PHASES}, "spans": defaultdict(
            lambda: {"count": 0, "total_s": 0.0}),
    })
    epoch_wall: dict = defaultdict(float)  # worker -> train wall seconds
    for ev in events:
        w = ev.get("worker", 0) or 0
        if ev.get("event") == "step_breakdown":
            a = acc[w]
            a["epochs"] += 1
            a["steps"] += int(ev.get("steps", 0))
            for p in _STEP_PHASES:
                a[p] += float(ev.get(f"{p}_s", 0.0))
            a["infeed_wait"] += float(ev.get("infeed_wait_s", 0.0))
            a["infeed_put"] += float(ev.get("infeed_put_s", 0.0))
            a["host_produce"] += float(ev.get("host_produce_s", 0.0))
            for name, s in (ev.get("spans") or {}).items():
                a["spans"][name]["count"] += int(s.get("count", 0))
                a["spans"][name]["total_s"] += float(s.get("total_s", 0.0))
        elif ev.get("event") == "epoch":
            epoch_wall[w] += float(ev.get("train_time_s", 0.0))
    if not acc:
        return ["  (no step_breakdown events — was the run traced? "
                "set shifu.tpu.obs-enabled=true / --obs)"]
    lines = [
        "  worker  epochs  steps  step_ms   infeed%   host%  dispatch%"
        "  block%  other%"
    ]
    for w in sorted(acc):
        a = acc[w]
        phase_total = sum(a[p] for p in _STEP_PHASES)
        wall = epoch_wall.get(w, 0.0) or phase_total
        denom = max(wall, phase_total) or 1.0
        other = max(0.0, denom - phase_total)
        step_ms = (denom / a["steps"] * 1000.0) if a["steps"] else 0.0
        pct = {p: 100.0 * a[p] / denom for p in _STEP_PHASES}
        lines.append(
            f"  {w:<7} {a['epochs']:<7} {a['steps']:<6} {step_ms:<9.3f}"
            f" {pct['infeed']:<9.1f} {pct['host']:<6.1f}"
            f" {pct['dispatch']:<10.1f} {pct['block']:<7.1f}"
            f" {100.0 * other / denom:.1f}"
        )
        if a["infeed_wait"] or a["infeed_put"] or a["host_produce"]:
            # pipelined infeed: wait is the consumer's stall (part of the
            # infeed%% above); put and host-produce are work on the put
            # thread, overlapped with dispatch — wait-heavy means STARVED
            # (widen the ingest pipeline), put-heavy means PLACEMENT-SLOW
            # (transfer/pad cost; see docs/ingest.md)
            line = (
                f"          infeed split: wait "
                f"{100.0 * a['infeed_wait'] / denom:.1f}% of wall, put "
                f"{100.0 * a['infeed_put'] / denom:.1f}% (overlapped)"
            )
            if a["host_produce"]:
                line += (f", host produce "
                         f"{100.0 * a['host_produce'] / denom:.1f}%"
                         f" (overlapped)")
            lines.append(line)
        span_bits = [
            f"{name} {s['count']}x {s['total_s']:.3f}s"
            for name, s in sorted(a["spans"].items())
        ]
        if span_bits:
            lines.append(f"          spans: {', '.join(span_bits)}")
    return lines


def _serve_plane(events: list[dict]) -> list[str]:
    """Aggregate the serve plane's lifecycle events into one row per
    scoring process: request volume and rate (from serve_start/stop),
    shed pressure, and reload outcomes — the per-worker split the
    SO_REUSEPORT fleet's per-process /metrics cannot show in one
    place."""
    serve = [e for e in events if e.get("plane") == "serve"]
    if not serve:
        return []
    per: dict = defaultdict(lambda: {
        "start_ts": None, "stop_ts": None, "requests": None,
        "reloads": 0, "refused": 0, "shed_events": 0, "shed_total": 0,
        "restarts": 0,
    })
    fleet = {"workers": None, "restarts": 0}
    for ev in serve:
        kind = ev.get("event")
        w = ev.get("worker")
        a = per[w]
        if kind == "serve_start":
            a["start_ts"] = ev.get("ts")
        elif kind == "serve_stop":
            a["stop_ts"] = ev.get("ts")
            a["requests"] = ev.get("requests_total")
            a["shed_total"] = max(a["shed_total"],
                                  int(ev.get("shed_total", 0) or 0))
        elif kind == "reload":
            a["reloads"] += 1
        elif kind == "reload_refused":
            a["refused"] += 1
        elif kind == "shed":
            a["shed_events"] += 1
            a["shed_total"] = max(a["shed_total"],
                                  int(ev.get("shed_total", 0) or 0))
        elif kind == "serve_fleet_start":
            fleet["workers"] = ev.get("workers")
        elif kind in ("serve_worker_restart",):
            fleet["restarts"] += 1
    rows = {w: a for w, a in per.items()
            if a["start_ts"] is not None or a["requests"] is not None
            or a["reloads"] or a["refused"] or a["shed_events"]}
    lines = []
    if fleet["workers"]:
        lines.append(f"  fleet: {fleet['workers']} workers"
                     + (f", {fleet['restarts']} restart(s)"
                        if fleet["restarts"] else ""))
    if not rows:
        # a fleet whose workers all died before serve_start (crash
        # loop: bad artifact, stolen port) has no per-worker rows, but
        # the fleet line above — workers + restart count — is exactly
        # what the operator diagnosing it needs; never hide it
        if fleet["workers"]:
            lines.append("  (no worker reached serve_start)")
        return lines
    lines.append(
        "  worker  requests  req/s    shed   reloads  refused")
    for w in sorted(rows, key=lambda k: (k is None, k)):
        a = rows[w]
        who = "-" if w is None else str(w)
        reqs = a["requests"]
        rate = ""
        if (reqs is not None and a["start_ts"] is not None
                and a["stop_ts"] is not None
                and a["stop_ts"] > a["start_ts"]):
            rate = f"{reqs / (a['stop_ts'] - a['start_ts']):.1f}"
        lines.append(
            f"  {who:<7} {('?' if reqs is None else reqs):<9} "
            f"{rate or '?':<8} {a['shed_total']:<6} {a['reloads']:<8} "
            f"{a['refused']}"
        )
    return lines


def cmd_summary(args) -> int:
    files = journal_files(args.journal)
    events = read_events(args.journal)
    if not events:
        print(f"no journal events under {args.journal!r} "
              f"(files: {files or 'none'})", file=sys.stderr)
        return 1
    t0 = events[0].get("ts", 0.0)
    t1 = events[-1].get("ts", t0)
    counts = defaultdict(int)
    for ev in events:
        counts[ev.get("event", "?")] += 1
    print(f"journal {args.journal}: {len(events)} events in "
          f"{len(files)} file(s), spanning {t1 - t0:.1f}s")
    print("  " + ", ".join(
        f"{name} x{n}" for name, n in sorted(counts.items())))
    print()
    print("per-step time budget")
    for line in _step_budget(events):
        print(line)
    print()
    serve_lines = _serve_plane(events)
    if serve_lines:
        print("serve plane")
        for line in serve_lines:
            print(line)
        print()
    print("fleet timeline")
    timeline = [e for e in events if e.get("event") != "step_breakdown"]
    limit = args.timeline_limit
    shown = timeline if not limit else timeline[-limit:]
    if len(shown) < len(timeline):
        print(f"  ... {len(timeline) - len(shown)} earlier events elided "
              f"(--timeline-limit {limit})")
    for ev in shown:
        print(" " + _fmt_event(ev, t0))
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "tail":
            return cmd_tail(args)
        return cmd_summary(args)
    except BrokenPipeError:
        # `... | head` closes our stdout mid-timeline; that is the
        # reader's prerogative, not an error
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())
